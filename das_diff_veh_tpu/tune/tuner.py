"""Knob sweep (AutoTVM/Ansor-style, degenerate search space) + application.

The tuner treats a small whitelist of *execution* knobs — Pallas block
sizes, fused-kernel support caps, the chunk-pipeline dispatch mode — as a
search space and greedily coordinate-descends it: knobs are swept in order,
each candidate timed with the caller's ``time_fn`` (typically a thin
wrapper over the bench harness's best-of-reps pattern), and a candidate
only displaces the incumbent when it is measurably faster.  The search is
deliberately primitive next to Ansor's learned cost model: the space here
is tens of points, not billions, so exhaustive-per-knob timing IS the
cheap, robust answer.

Two invariants the whitelist enforces:

- **physics never tunes**: every path in ``TUNABLE_KNOBS`` is an execution
  knob whose value cannot change an output bit on the kernel path (block
  sizes, caps, dispatch mode).  Physics knobs are not sweepable and an
  attempt to apply one is warn-and-skipped, never obeyed.
- **precision never tunes**: the bf16 tier trades accuracy for throughput
  under a committed error budget — an *operator* decision, not a timing
  winner.  ``*.precision`` is excluded on purpose.
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from das_diff_veh_tpu.config import PipelineConfig, RingConfig
from das_diff_veh_tpu.runtime.manifest import config_hash
from das_diff_veh_tpu.tune.store import TunedEntry, TunerStore

log = logging.getLogger("das_diff_veh_tpu.tune")

TUNABLE_KNOBS = frozenset({
    # gather: fused-kernel support caps + dispatch knobs
    "gather.traj_gather",
    "gather.traj_gather_finish",
    "gather.fused_max_nwin",
    "gather.dot_max_wlen",
    "gather.dot_max_matrix_elems",
    # ring all-pairs: block sizes / tile bounds (RingConfig root)
    "ring.win_block",
    "ring.lagmax_block",
    "ring.lag_tile_max",
    # per-chunk pipeline dispatch mode
    "chunk_pipeline",
    # fleet inversion: batch-size knobs (FleetInversionConfig)
    "fleet.target_chunk",
    "fleet.eval_chunk",
    "fleet.refine_chunk",
})
"""Dotted knob paths the tuner may sweep/apply.  ``ring.*`` roots at a
:class:`~das_diff_veh_tpu.config.RingConfig` (not part of PipelineConfig);
everything else roots at :class:`~das_diff_veh_tpu.config.PipelineConfig`
(``fleet.*`` at its :class:`~das_diff_veh_tpu.config.FleetInversionConfig`
— inversion batch sizes, chunking-invariant by test pin).
``*.precision`` and all physics knobs are excluded by construction."""


@dataclass(frozen=True)
class KnobSpec:
    """One swept knob: a whitelisted dotted path + candidate values.

    The *current* config value is always implicitly a candidate (the
    incumbent a challenger must beat), so ``candidates`` need only list the
    alternatives."""

    path: str
    candidates: Tuple[Any, ...]

    def __post_init__(self):
        if self.path not in TUNABLE_KNOBS:
            raise ValueError(
                f"{self.path!r} is not a tunable knob; sweepable paths: "
                f"{sorted(TUNABLE_KNOBS)}")


def _get_path(root, path: str):
    for part in path.split("."):
        root = getattr(root, part)
    return root


def _replace_path(root, path: str, value):
    head, _, rest = path.partition(".")
    if not rest:
        return dataclasses.replace(root, **{head: value})
    return dataclasses.replace(
        root, **{head: _replace_path(getattr(root, head), rest, value)})


def apply_winners(cfg: PipelineConfig, winners: Dict[str, Any],
                  ring: Optional[RingConfig] = None,
                  ) -> Tuple[PipelineConfig, Optional[RingConfig]]:
    """Apply a winners dict onto the config tree; returns (cfg, ring).

    Only whitelisted paths are obeyed; anything else — a physics knob, a
    precision field, a path from a future version's store — is warned
    about and skipped, so a hand-edited or forward-versioned store can
    degrade a run's speed but never its correctness or its ability to
    start.  ``ring.*`` entries are skipped (with a warning) when no
    ``ring`` is passed: the caller has no ring engine to apply them to.
    """
    for path, value in winners.items():
        if path not in TUNABLE_KNOBS:
            log.warning("tuned knob %r is not in the tunable whitelist; "
                        "skipping", path)
            continue
        if path.startswith("ring."):
            if ring is None:
                log.warning("tuned knob %r needs a RingConfig; skipping",
                            path)
                continue
            ring = _replace_path(ring, path[len("ring."):], value)
        else:
            cfg = _replace_path(cfg, path, value)
    return cfg, ring


def base_hash(cfg: PipelineConfig) -> str:
    """Store key hash: the config with every sweepable PipelineConfig knob
    reset to its default.  Hashing the *base* (not the tuned) config keeps
    the key stable across apply→lookup cycles: applying winners would
    otherwise change the hash and every lookup after the first would
    miss its own entry."""
    ref = PipelineConfig()
    for path in sorted(TUNABLE_KNOBS):
        if not path.startswith("ring."):
            cfg = _replace_path(cfg, path, _get_path(ref, path))
    return config_hash(cfg)


def _best_time(time_fn, cfg, ring, reps: int) -> float:
    return min(time_fn(cfg, ring) for _ in range(max(1, int(reps))))


def sweep_knobs(base_cfg: PipelineConfig, knobs: Sequence[KnobSpec],
                time_fn: Callable[[PipelineConfig, Optional[RingConfig]], float],
                reps: int = 2,
                ring: Optional[RingConfig] = None) -> TunedEntry:
    """Greedy coordinate descent over ``knobs``; returns the winners.

    ``time_fn(cfg, ring) -> seconds`` is the measurement source — the
    caller owns warmup/dispatch semantics (the bench harness's
    K-in-dispatch amortized timing is the intended implementation; tests
    use a stub).  Each knob is swept holding earlier winners fixed; a
    candidate must beat the incumbent's best-of-``reps`` time to win, so
    the returned winners never include a knob whose default already won.
    """
    cur_cfg, cur_ring = base_cfg, ring
    t_base = _best_time(time_fn, cur_cfg, cur_ring, reps)
    t_cur = t_base
    winners: Dict[str, Any] = {}
    trace = []
    for spec in knobs:
        best_val, best_t = None, t_cur
        for cand in spec.candidates:
            cfg_c, ring_c = apply_winners(cur_cfg, {spec.path: cand},
                                          cur_ring)
            t = _best_time(time_fn, cfg_c, ring_c, reps)
            trace.append({"path": spec.path, "value": repr(cand),
                          "best_s": t})
            if t < best_t:
                best_val, best_t = cand, t
        if best_val is not None:
            winners[spec.path] = best_val
            cur_cfg, cur_ring = apply_winners(cur_cfg,
                                              {spec.path: best_val},
                                              cur_ring)
            t_cur = best_t
    return TunedEntry(winners=winners,
                      meta={"baseline_s": t_base, "tuned_s": t_cur,
                            "speedup": (t_base / t_cur) if t_cur > 0 else 1.0,
                            "reps": int(reps), "trace": trace})


def tune(store: TunerStore, backend: str, geometry: str,
         cfg: PipelineConfig, knobs: Sequence[KnobSpec],
         time_fn, reps: int = 2, ring: Optional[RingConfig] = None,
         force: bool = False,
         ) -> Tuple[PipelineConfig, Optional[RingConfig], TunedEntry]:
    """Lookup-or-sweep: the tuned config for this (backend, geometry, cfg).

    A store hit (same backend, geometry, and base config hash) applies the
    persisted winners without re-measuring; a miss — including a config-
    hash mismatch from any upstream config change — runs the sweep and
    records the outcome.  ``force=True`` re-sweeps unconditionally
    (refreshing a stale winner after a software update)."""
    chash = base_hash(cfg)
    entry = None if force else store.lookup(backend, geometry, chash)
    if entry is None:
        entry = sweep_knobs(cfg, knobs, time_fn, reps=reps, ring=ring)
        store.record(backend, geometry, chash, entry)
        log.info("tuner swept %s|%s|%s: winners=%s speedup=%.2fx",
                 backend, geometry, chash, entry.winners,
                 entry.meta.get("speedup", 1.0))
    else:
        log.info("tuner store hit %s|%s|%s: winners=%s", backend, geometry,
                 chash, entry.winners)
    tuned_cfg, tuned_ring = apply_winners(cfg, entry.winners, ring)
    return tuned_cfg, tuned_ring, entry


def load_tuned(cfg: PipelineConfig, store_path: str, geometry: str,
               backend: Optional[str] = None,
               ring: Optional[RingConfig] = None,
               ) -> Tuple[PipelineConfig, Optional[RingConfig],
                          Optional[TunedEntry]]:
    """Lookup-only store consultation (the warmup/executor entry point).

    Never sweeps, never raises: any store problem or a plain miss returns
    the config unchanged with ``entry=None`` — defaults are always a safe
    answer at warmup time."""
    try:
        if backend is None:
            import jax
            backend = jax.default_backend()
        entry = TunerStore(store_path).lookup(backend, geometry,
                                              base_hash(cfg))
    except Exception as e:       # never let tuning break a warmup
        log.warning("tuner store consultation failed (%s: %s); running "
                    "default knobs", type(e).__name__, e)
        return cfg, ring, None
    if entry is None:
        return cfg, ring, None
    cfg, ring = apply_winners(cfg, entry.winners, ring)
    return cfg, ring, entry
