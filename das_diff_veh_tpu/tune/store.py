"""Config-hash-keyed persistence for tuned kernel/serve knobs.

Same durability pattern as the resume manifest (``runtime/manifest.py``):
one JSON file, atomically replaced on every write, versioned, and *soft* on
every failure mode — a corrupt, truncated, or foreign-version store file
means "no tuned values", never a crashed warmup.  Entries are keyed
``"<backend>|<geometry>|<config_hash>"``:

- ``backend``: ``jax.default_backend()`` at sweep time — a winner measured
  on a v5e says nothing about CPU block sizes;
- ``geometry``: an operator-chosen fiber/deployment label (channel count,
  spacing and record length all change the optimum);
- ``config_hash``: ``runtime.manifest.config_hash`` of the PipelineConfig
  the sweep timed, with the swept knobs themselves *reset to defaults*
  before hashing (``base_hash`` below) — otherwise applying the winners
  would change the hash and every lookup after the first would miss.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from das_diff_veh_tpu.runtime.manifest import _atomic_write_json

log = logging.getLogger("das_diff_veh_tpu.tune")

STORE_VERSION = 1


def store_key(backend: str, geometry: str, chash: str) -> str:
    return f"{backend}|{geometry}|{chash}"


@dataclass
class TunedEntry:
    """One sweep's outcome: the winning knob values plus provenance."""

    winners: Dict[str, Any]
    """Dotted knob path -> winning value (see ``tune.tuner.TUNABLE_KNOBS``)."""

    meta: Dict[str, Any] = field(default_factory=dict)
    """Sweep provenance: baseline/tuned seconds, reps, sweep order — kept
    for docs/bench reporting, never consulted at load time."""

    def to_json(self) -> dict:
        return {"winners": self.winners, "meta": self.meta}

    @classmethod
    def from_json(cls, d: dict) -> "TunedEntry":
        return cls(winners=dict(d.get("winners", {})),
                   meta=dict(d.get("meta", {})))


class TunerStore:
    """Load/lookup/record tuned winners in one JSON file.

    ``load`` (implicit on first access) never raises for a bad file: a
    missing path is an empty store, and an unreadable/corrupt/foreign-
    version file is *warned about* and treated as empty — the contract
    warmup depends on (tests/test_tune.py pins every failure mode).
    """

    def __init__(self, path: str):
        self.path = path
        self._entries: Optional[Dict[str, TunedEntry]] = None

    # -- persistence ---------------------------------------------------------
    def load(self) -> Dict[str, TunedEntry]:
        if self._entries is not None:
            return self._entries
        self._entries = {}
        if not os.path.exists(self.path):
            return self._entries
        try:
            with open(self.path) as f:
                d = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            log.warning("tuner store %s unreadable (%s: %s); falling back "
                        "to default knobs", self.path, type(e).__name__, e)
            return self._entries
        if not isinstance(d, dict) or d.get("version") != STORE_VERSION:
            log.warning("tuner store %s has version %r (want %d); falling "
                        "back to default knobs", self.path,
                        d.get("version") if isinstance(d, dict) else None,
                        STORE_VERSION)
            return self._entries
        try:
            for k, v in d.get("entries", {}).items():
                self._entries[k] = TunedEntry.from_json(v)
        except (AttributeError, TypeError) as e:
            log.warning("tuner store %s malformed (%s: %s); falling back "
                        "to default knobs", self.path, type(e).__name__, e)
            self._entries = {}
        return self._entries

    def save(self) -> None:
        entries = self.load()
        _atomic_write_json(self.path, {
            "version": STORE_VERSION,
            "entries": {k: e.to_json() for k, e in sorted(entries.items())}})

    # -- access --------------------------------------------------------------
    def lookup(self, backend: str, geometry: str,
               chash: str) -> Optional[TunedEntry]:
        """The tuned entry for this exact (backend, geometry, config), or
        None — a config-hash mismatch is just a miss (the caller re-tunes
        or runs defaults; stale winners are never applied)."""
        return self.load().get(store_key(backend, geometry, chash))

    def record(self, backend: str, geometry: str, chash: str,
               entry: TunedEntry) -> None:
        self.load()[store_key(backend, geometry, chash)] = entry
        self.save()
