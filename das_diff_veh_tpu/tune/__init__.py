"""Obs-driven kernel/serve knob autotuner (docs/TUNING.md).

Sweeps the whitelisted execution knobs per ``(backend, geometry)`` with the
bench timing harness as the measurement source, persists winners in a
config-hash-keyed JSON store (``tune.store``), and applies them at batch
start (``runtime.executor.consult_tuner``) and serve warmup
(``serve.imaging.ImagingComputeFactory``).  Defaults always remain a safe
answer: every store failure mode degrades to "no tuned values".
"""

from das_diff_veh_tpu.tune.store import (STORE_VERSION, TunedEntry,
                                         TunerStore, store_key)
from das_diff_veh_tpu.tune.tuner import (TUNABLE_KNOBS, KnobSpec,
                                         apply_winners, base_hash,
                                         load_tuned, sweep_knobs, tune)

__all__ = [
    "STORE_VERSION", "TunedEntry", "TunerStore", "store_key",
    "TUNABLE_KNOBS", "KnobSpec", "apply_winners", "base_hash",
    "load_tuned", "sweep_knobs", "tune",
]
