"""Savitzky-Golay smoothing as convolution + edge-projection matmuls.

The reference calls ``scipy.signal.savgol_filter`` in four places (fv-map
smooth (25,4) modules/utils.py:473, ridge smooth (25,2) modules/utils.py:676,
file pre-smooth (21,15) modules/imaging_IO.py:45, quasi-static smooth (101,3)
imaging_diff_speed.ipynb cell 5).  scipy's default ``mode='interp'`` fits a
polynomial to the first/last window for the edge samples; both the interior
convolution and the edge fits are linear maps, so the whole filter is one
correlation plus two small matmuls — precomputed on the host, applied in jnp.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=32)
def _savgol_matrices(window: int, order: int):
    """(conv_coeffs (window,), left_edge (half, window), right_edge (half, window))."""
    from scipy.signal import savgol_coeffs
    coeffs = savgol_coeffs(window, order)              # interior correlation kernel
    half = window // 2
    # polynomial LS projection for edges: fit first/last `window` samples,
    # evaluate the fitted polynomial at positions 0..half-1 (left) and
    # window-half..window-1 (right) — exactly scipy's mode='interp'.
    # centered positions: mathematically the same projection as scipy's
    # uncentered polyfit, but vastly better conditioned at high order
    pos = np.arange(window, dtype=np.float64) - half
    V = np.vander(pos, order + 1, increasing=True)     # (window, order+1)
    proj = V @ np.linalg.pinv(V)                       # (window, window) LS smoother
    left = proj[:half]                                 # first half outputs
    right = proj[window - half:]                       # last half outputs
    return (np.asarray(coeffs, dtype=np.float64), left, right)


def savgol_filter(data: jnp.ndarray, window: int, order: int, axis: int = -1) -> jnp.ndarray:
    """Savitzky-Golay filter matching ``scipy.signal.savgol_filter(mode='interp')``."""
    coeffs, left, right = _savgol_matrices(window, order)
    half = window // 2

    moved = jnp.moveaxis(data, axis, -1)
    shape = moved.shape
    flat = moved.reshape(-1, shape[-1])                # (batch, n)
    n = flat.shape[-1]
    if window % 2 == 0:
        raise ValueError(f"savgol window must be odd, got {window}")
    if n < window:
        raise ValueError(f"savgol window {window} longer than axis length {n}")

    k = jnp.asarray(coeffs[::-1], dtype=flat.dtype)    # correlate == conv w/ reversed
    # vectorized 'same' correlation via conv_general_dilated
    import jax.lax as lax
    lhs = flat[:, None, :]                             # (batch, 1, n)
    rhs = k[None, None, :]                             # (1, 1, window)
    out = lax.conv_general_dilated(lhs, rhs, window_strides=(1,),
                                   padding=[(half, half)])[:, 0, :]

    lmat = jnp.asarray(left, dtype=flat.dtype)
    rmat = jnp.asarray(right, dtype=flat.dtype)
    head = flat[:, :window] @ lmat.T                   # (batch, half)
    tail = flat[:, n - window:] @ rmat.T               # (batch, half)
    out = out.at[:, :half].set(head)
    out = out.at[:, n - half:].set(tail)

    return jnp.moveaxis(out.reshape(shape), -1, axis)
