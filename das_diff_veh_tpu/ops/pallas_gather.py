"""Pallas scalar-prefetch gather kernel: the trajectory-following window cut
fused into the correlate pass.

The paper's centerpiece gather cuts a *per-channel, data-dependent* time
window for every channel between pivot and vehicle
(``ops.xcorr.xcorr_traj_follow``): channel ``ch_indices[k]`` and the pivot
trace are both sliced at ``dt_idx[k]`` (an ``argmax`` over the vehicle's
arrival times), windowed, and circularly correlated.  The legacy
formulation vmaps a ``lax.dynamic_slice`` with traced starts over channels;
XLA lowers that to a *serialized* chain of contiguous block copies on TPU —
docs/PERF.md measured it as the pipeline's hottest op (2.4 ms for 288 rows
at the reference shape, already the fastest XLA formulation; the win left
is doing the cut inside one kernel sweep).

Here the per-channel starts become a **scalar-prefetched operand** of a
Pallas kernel (``pltpu.PrefetchScalarGridSpec``): the grid runs one step
per output channel, and each step's ``index_map`` uses the prefetched
``(row, block)`` indices to DMA that channel's (and the pivot's) spectra
tile straight from HBM at its own offset — the DMAs double-buffer across
grid steps instead of serializing, and the element-granular residue of the
start is applied *inside* the kernel with a dynamic slice of the
VMEM-resident tile.  Because Pallas block indexing is block-granular, the
record is reshaped to ``(nch, nblk, G)`` blocks of grain
``G = roundup(nsamp, 128)`` and each step loads the TWO adjacent blocks
that cover ``[start, start + nsamp)`` for any in-range start
(``rem < G`` and ``(nwin-1)*offset + wlen <= nsamp <= G``).

Two finishes, selected by ``GatherConfig.traj_gather_finish``:

- ``"rfft"`` (default): the kernel emits the packed ``(nk, nwin, wlen)``
  window tensors for channel and pivot (invalid windows zeroed — exactly
  the windows ``_masked_window_specs``'s validity mask would discard) and
  the existing batched-rfft circular correlate finishes outside.  Valid
  windows are bitwise-identical copies of the record, so this path is
  numerically the legacy path with the serialized cut swapped out.
- ``"dot"``: for small ``wlen`` (<= ``DOT_MAX_WLEN``) the circular
  correlation itself finishes in-kernel as an MXU dot against the doubled
  source-window matrix (``c[k] = sum_n s2[n+k] r[n]`` with
  ``s2 = [s, s]``), so nothing window-shaped ever leaves the kernel —
  the output is the final ``(nk, wlen)`` correlation rows.  Time-domain
  vs FFT float error applies (see tests for the pinned tolerance).

Off-TPU the kernel drops to interpret mode (same convention as
``ops.pallas_xcorr``), so CPU CI exercises the identical program.

Reference-parity semantics (numpy truncation / backward empty slice) are
carried by the same ``avail`` arithmetic as ``_masked_window_specs``; the
validity masks are applied in-kernel.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Compile-time bound on the static per-step window unroll: the imaging
# gathers cut ~3-15 windows (nwin = (nsamp - wlen)//offset + 1); past this
# the unrolled in-kernel cut would bloat the kernel body, so ``mode="auto"``
# falls back to the serialized path (continuous-record window counts belong
# to the all-pairs engine, not the per-vehicle gather).  These module values
# are the DEFAULTS of the corresponding ``GatherConfig`` fields
# (``fused_max_nwin`` / ``dot_max_wlen`` / ``dot_max_matrix_elems``), which
# the tuner sweeps per backend/geometry (docs/TUNING.md); every entry point
# below takes the caps as optional arguments defaulting to these.
FUSED_MAX_NWIN = 64

# The "dot" finish materializes the (nwin, wlen, wlen) doubled-window
# matrix in VMEM, so the budget is JOINT in nwin and wlen: wlen is capped
# per-axis (the unrolled slice count) and nwin*wlen^2 against a ~4 MB f32
# element budget (2^20 elements = 15 windows at wlen 256; a larger nwin
# passes only with a proportionally smaller wlen).  The reference wlen of
# 500 samples stays on the rfft finish either way.
DOT_MAX_WLEN = 256
DOT_MAX_MATRIX_ELEMS = 1 << 20

_LANE = 128


def _round_up(n: int, mult: int) -> int:
    return -(-n // mult) * mult


def _resolve_interpret(interpret: bool | None) -> bool:
    if interpret is None:
        return jax.default_backend() not in ("tpu", "axon")
    return bool(interpret)


def _cut_windows(row, rem, avail, nwin: int, wlen: int, offset: int):
    """Cut ``nwin`` overlapping windows from the (1, 2G) VMEM-resident row
    at element offsets ``rem + w*offset`` and zero the invalid ones.

    The per-window dynamic slice runs on the VMEM tile (the HBM read
    already happened at block granularity through the index_map), so the
    data-dependent part of the cut never touches HBM.  Zeroing invalid
    windows reproduces the masked-sum semantics of
    ``ops.xcorr._masked_window_specs``: every downstream contribution of an
    invalid window is exactly zero.
    """
    zero = jnp.int32(0)
    segs = [lax.dynamic_slice(row, (zero, (rem + w * offset).astype(jnp.int32)),
                              (1, wlen))[0]
            for w in range(nwin)]
    wins = jnp.stack(segs)                               # (nwin, wlen)
    ok = (jnp.arange(nwin, dtype=jnp.int32) * offset + wlen) <= avail
    return jnp.where(ok[:, None], wins, 0.0), ok


def _rows(ch_lo, ch_hi, pv_lo, pv_hi):
    """Concatenate each pair of adjacent grain blocks into a (1, 2G) row."""
    row_ch = jnp.concatenate([ch_lo[0, 0, :], ch_hi[0, 0, :]])[None, :]
    row_pv = jnp.concatenate([pv_lo[0, 0, :], pv_hi[0, 0, :]])[None, :]
    return row_ch, row_pv


def _pack_kernel(nwin: int, wlen: int, offset: int,
                 sref, ch_lo, ch_hi, pv_lo, pv_hi, out_ch, out_pv):
    """One grid step = one output channel: cut the channel's and the
    pivot's ``nwin`` windows at this channel's start and emit them packed
    (invalid windows zeroed).  Block shapes: inputs (1, 1, G) x4, outputs
    (1, nwin, wlen_pad)."""
    k = pl.program_id(0)
    rem, avail = sref[0, k], sref[1, k]
    row_ch, row_pv = _rows(ch_lo, ch_hi, pv_lo, pv_hi)
    wins_ch, _ = _cut_windows(row_ch, rem, avail, nwin, wlen, offset)
    wins_pv, _ = _cut_windows(row_pv, rem, avail, nwin, wlen, offset)
    out_ch[:] = jnp.zeros(out_ch.shape, out_ch.dtype)
    out_pv[:] = jnp.zeros(out_pv.shape, out_pv.dtype)
    out_ch[0, :, 0:wlen] = wins_ch
    out_pv[0, :, 0:wlen] = wins_pv


def _dot_kernel(nwin: int, wlen: int, offset: int, swap: bool,
                precision: str,
                sref, ch_lo, ch_hi, pv_lo, pv_hi, out):
    """Fully fused step: cut both traces' windows AND finish the circular
    correlation in-kernel as an MXU dot against the doubled source-window
    matrix.  ``c[w, k] = sum_n s2[w, n+k] * r[w, n]`` with ``s2 = [s, s]``
    is exactly the reference's doubled-source "valid" correlate; the masked
    window mean and the zero-lag centering roll happen here too, so the
    output block is the final (1, wlen_pad) correlation row.

    ``precision="bf16"`` feeds the MXU bfloat16 operands with float32
    accumulation (``preferred_element_type``) — the Micikevicius-style
    mixed-precision tier; ``"f32"`` keeps the HIGHEST-precision full-width
    contraction bit-identical to the pre-tier kernel."""
    k = pl.program_id(0)
    rem, avail = sref[0, k], sref[1, k]
    row_ch, row_pv = _rows(ch_lo, ch_hi, pv_lo, pv_hi)
    wins_ch, ok = _cut_windows(row_ch, rem, avail, nwin, wlen, offset)
    wins_pv, _ = _cut_windows(row_pv, rem, avail, nwin, wlen, offset)
    src, rcv = (wins_pv, wins_ch) if swap else (wins_ch, wins_pv)
    s2 = jnp.concatenate([src, src], axis=1)             # (nwin, 2*wlen)
    # doubled-window matrix D[w, k, :] = s2[w, k:k+wlen]: wlen STATIC
    # slices (bounded by dot_max_wlen), then one batched MXU contraction
    dmat = jnp.stack([s2[:, j:j + wlen] for j in range(wlen)], axis=1)
    if precision == "bf16":
        c = lax.dot_general(dmat.astype(jnp.bfloat16),
                            rcv.astype(jnp.bfloat16),
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32,
                            ).astype(rcv.dtype)          # (nwin, wlen)
    else:
        c = lax.dot_general(dmat, rcv, (((2,), (1,)), ((0,), (0,))),
                            precision=lax.Precision.HIGHEST,
                            preferred_element_type=rcv.dtype)  # (nwin, wlen)
    n_eff = jnp.sum(ok.astype(c.dtype))
    row = jnp.sum(c, axis=0) / jnp.maximum(n_eff, 1)
    row = jnp.roll(row, wlen // 2)                       # zero lag -> wlen//2
    out[:] = jnp.zeros(out.shape, out.dtype)
    out[0, 0:wlen] = row


def _traj_scalars(dt_idx, ch_indices, pivot_idx, nt: int, nsamp: int,
                  grain: int, backward: bool):
    """Per-channel prefetch scalars: (5, nk) int32
    [rem, avail, row, blk, pivot_row].

    The truncation/empty-slice arithmetic is ``ops.xcorr``'s shared
    :func:`~das_diff_veh_tpu.ops.xcorr.window_slice_avail` — one source of
    truth for the numpy-parity semantics on both paths.  The pivot row
    index rides the scalar operand too (broadcast), so a traced pivot is
    as legal here as on the serialized path.
    """
    from das_diff_veh_tpu.ops.xcorr import window_slice_avail

    start = dt_idx.astype(jnp.int32)
    s0, avail = window_slice_avail(start, nt, nsamp, backward)
    base = jnp.clip(s0, 0, nt)
    blk = base // grain
    rem = base - blk * grain
    pv = jnp.full(ch_indices.shape, pivot_idx)
    return jnp.stack([rem, avail.astype(jnp.int32),
                      ch_indices.astype(jnp.int32), blk,
                      pv.astype(jnp.int32)]).astype(jnp.int32)


def _blocked_record(data: jnp.ndarray, grain: int):
    """Zero-pad the (nch, nt) record and reshape to (nch, nblk, G) grain
    blocks so any clipped start's two covering blocks are in range.  Valid
    windows never reach the pad (their samples lie in ``[0, nt)`` by the
    ``avail`` bounds); pad samples only feed windows that are zeroed."""
    nt = data.shape[-1]
    nblk = nt // grain + 2
    dpad = jnp.pad(data, ((0, 0), (0, nblk * grain - nt)))
    return dpad.reshape(data.shape[0], nblk, grain)


def _gather_specs(grain: int):
    """The four block index maps: channel row at the channel's block, the
    pivot row at the SAME block (shared per-channel window), each with its
    ``+1`` neighbor so the in-kernel element shift stays in range.  Every
    index — channel row, pivot row, block — comes from the prefetched
    scalar operand."""
    return [
        pl.BlockSpec((1, 1, grain), lambda k, s: (s[2, k], s[3, k], 0)),
        pl.BlockSpec((1, 1, grain), lambda k, s: (s[2, k], s[3, k] + 1, 0)),
        pl.BlockSpec((1, 1, grain), lambda k, s: (s[4, k], s[3, k], 0)),
        pl.BlockSpec((1, 1, grain), lambda k, s: (s[4, k], s[3, k] + 1, 0)),
    ]


def _fused_call(data, pivot_idx, ch_indices, dt_idx, nsamp: int, wlen: int,
                backward: bool, interpret: bool | None, kernel, out_specs,
                out_shape_fn):
    """Shared harness of both fused entry points: resolve interpret mode,
    compute the grain, block the record, build the prefetch scalars, and
    run ``kernel`` over the ``(nk,)`` grid with the four gather specs.
    ``out_shape_fn(nk, wlen_pad, dtype)`` supplies the finish-specific
    output aval(s); returns ``(outs, scal, wlen_pad)``."""
    nt = data.shape[-1]
    nk = ch_indices.shape[0]
    interpret = _resolve_interpret(interpret)
    grain = _round_up(nsamp, _LANE)     # nwin >= 1 guarantees nsamp >= wlen
    wlen_pad = _round_up(wlen, _LANE)
    data3 = _blocked_record(data, grain)
    scal = _traj_scalars(dt_idx, ch_indices, pivot_idx, nt, nsamp, grain,
                         backward)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nk,),
        in_specs=_gather_specs(grain),
        out_specs=out_specs,
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape_fn(nk, wlen_pad, data.dtype),
        interpret=interpret,
    )(scal, data3, data3, data3, data3)
    return outs, scal, wlen_pad


def traj_follow_windows(data: jnp.ndarray, pivot_idx,
                        ch_indices: jnp.ndarray, dt_idx: jnp.ndarray,
                        nsamp: int, wlen: int, offset: int,
                        backward: bool = False,
                        interpret: bool | None = None,
                        max_nwin: int | None = None):
    """Fused window cut: packed ``(nk, nwin, wlen)`` channel and pivot
    window tensors, one kernel sweep over the ``nk`` output channels
    (invalid windows zeroed, ``n_eff`` per channel returned).

    This is the "(a)" finish: the caller runs the existing batched-rfft
    circular correlate on the packed windows.  Valid windows are
    bit-identical to the serialized cut's.
    """
    nwin = (nsamp - wlen) // offset + 1
    _check_fused(nwin, wlen, None, max_nwin=max_nwin)
    if ch_indices.shape[0] == 0:
        z = jnp.zeros((0, nwin, wlen), data.dtype)
        return z, z, jnp.zeros((0,), jnp.int32)
    wp = _round_up(wlen, _LANE)
    (wins_ch, wins_pv), scal, _ = _fused_call(
        data, pivot_idx, ch_indices, dt_idx, nsamp, wlen, backward,
        interpret,
        kernel=partial(_pack_kernel, nwin, wlen, offset),
        out_specs=[pl.BlockSpec((1, nwin, wp), lambda k, s: (k, 0, 0))] * 2,
        out_shape_fn=lambda nk, wlen_pad, dt: [
            jax.ShapeDtypeStruct((nk, nwin, wlen_pad), dt)] * 2)
    n_eff = jnp.sum((jnp.arange(nwin, dtype=jnp.int32)[None, :] * offset
                     + wlen) <= scal[1][:, None], axis=1)
    return wins_ch[..., :wlen], wins_pv[..., :wlen], n_eff


def traj_follow_correlate_dot(data: jnp.ndarray, pivot_idx,
                              ch_indices: jnp.ndarray, dt_idx: jnp.ndarray,
                              nsamp: int, wlen: int, offset: int,
                              backward: bool = False, swap: bool = False,
                              interpret: bool | None = None,
                              max_nwin: int | None = None,
                              dot_max_wlen: int | None = None,
                              dot_max_elems: int | None = None,
                              precision: str = "f32") -> jnp.ndarray:
    """Fully fused gather+correlate ("(b)" finish): the kernel cuts both
    traces' windows AND finishes the circular correlation as an in-kernel
    MXU dot — returns the final rolled ``(nk, wlen)`` correlation rows.
    ``swap=True`` correlates (src=pivot, rcv=channel), the reverse-side
    operand order of ``xcorr_traj_follow``.  ``precision="bf16"`` runs the
    in-kernel contraction on bfloat16 operands with f32 accumulation
    (``GatherConfig.precision``; tests/test_precision.py pins the error
    budget)."""
    nwin = (nsamp - wlen) // offset + 1
    _check_fused(nwin, wlen, "dot", max_nwin=max_nwin,
                 dot_max_wlen=dot_max_wlen, dot_max_elems=dot_max_elems)
    if ch_indices.shape[0] == 0:
        return jnp.zeros((0, wlen), data.dtype)
    wp = _round_up(wlen, _LANE)
    out, _, _ = _fused_call(
        data, pivot_idx, ch_indices, dt_idx, nsamp, wlen, backward,
        interpret,
        kernel=partial(_dot_kernel, nwin, wlen, offset, swap, precision),
        out_specs=pl.BlockSpec((1, wp), lambda k, s: (k, 0)),
        out_shape_fn=lambda nk, wlen_pad, dt: jax.ShapeDtypeStruct(
            (nk, wlen_pad), dt))
    return out[:, :wlen]


def _resolve_caps(max_nwin: int | None, dot_max_wlen: int | None,
                  dot_max_elems: int | None) -> tuple[int, int, int]:
    """Fill unset caps with the module defaults (= the ``GatherConfig``
    field defaults, the tuner's sweep baseline)."""
    return (FUSED_MAX_NWIN if max_nwin is None else int(max_nwin),
            DOT_MAX_WLEN if dot_max_wlen is None else int(dot_max_wlen),
            DOT_MAX_MATRIX_ELEMS if dot_max_elems is None
            else int(dot_max_elems))


def _check_fused(nwin: int, wlen: int, finish: str | None,
                 max_nwin: int | None = None,
                 dot_max_wlen: int | None = None,
                 dot_max_elems: int | None = None) -> None:
    cap_nwin, cap_wlen, cap_elems = _resolve_caps(max_nwin, dot_max_wlen,
                                                  dot_max_elems)
    if nwin < 1:
        raise ValueError(
            f"fused gather needs at least one window (nwin={nwin}: "
            f"nsamp < wlen?)")
    if nwin > cap_nwin:
        raise ValueError(
            f"fused gather unrolls nwin={nwin} window cuts per grid step; "
            f"past fused_max_nwin={cap_nwin} use the serialized path "
            f"(traj_gather='serialized')")
    if finish == "dot" and (wlen > cap_wlen
                            or nwin * wlen * wlen > cap_elems):
        raise ValueError(
            f"dot finish materializes a ({nwin}, {wlen}, {wlen}) doubled-"
            f"window matrix in VMEM; past wlen > dot_max_wlen={cap_wlen} "
            f"or nwin*wlen^2 > dot_max_matrix_elems={cap_elems} "
            f"use the rfft finish (traj_gather_finish='rfft')")


def fused_supported(nwin: int, wlen: int, finish: str,
                    max_nwin: int | None = None,
                    dot_max_wlen: int | None = None,
                    dot_max_elems: int | None = None) -> bool:
    """Shape gate used by ``mode="auto"`` resolution in ``ops.xcorr``.
    Caps default to the module constants; pass the ``GatherConfig`` fields
    to honor tuned values."""
    cap_nwin, cap_wlen, cap_elems = _resolve_caps(max_nwin, dot_max_wlen,
                                                  dot_max_elems)
    if nwin < 1 or nwin > cap_nwin:
        return False
    if finish == "dot" and (wlen > cap_wlen
                            or nwin * wlen * wlen > cap_elems):
        return False
    return True
