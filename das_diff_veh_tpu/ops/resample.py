"""Polyphase rational resampling (scipy.signal.resample_poly equivalent).

The tracking preprocessor upsamples the channel axis 8.16 m -> 1 m with
``signal.resample_poly(data, 204, 25)`` (reference:
apis/timeLapseImaging.py:91).  The TPU path builds the identical default
Kaiser anti-alias FIR on the host and expresses up-firdn as zero-stuffing +
one ``conv_general_dilated`` — a single fused XLA convolution.
"""

from __future__ import annotations

import functools
import math

import jax.lax as lax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=16)
def _default_filter(up: int, down: int) -> np.ndarray:
    """scipy.signal.resample_poly's default anti-alias FIR (kaiser beta=5)."""
    from scipy.signal import firwin
    max_rate = max(up, down)
    f_c = 1.0 / max_rate
    half_len = 10 * max_rate
    h = firwin(2 * half_len + 1, f_c, window=("kaiser", 5.0))
    return np.asarray(h, dtype=np.float64) * up


def resample_poly(data: jnp.ndarray, up: int, down: int, axis: int = 0) -> jnp.ndarray:
    """Rational-rate polyphase resample along ``axis``; matches
    ``scipy.signal.resample_poly`` (default window, zero padding)."""
    g = math.gcd(up, down)
    up, down = up // g, down // g
    if up == 1 and down == 1:
        return data
    h = _default_filter(up, down)
    n_out = -(-data.shape[axis] * up // down)          # ceil

    moved = jnp.moveaxis(data, axis, -1)
    shape = moved.shape
    flat = moved.reshape(-1, shape[-1])                # (batch, n)
    n = flat.shape[-1]

    # zero-stuff: x_up[i*up] = x[i]
    up_len = n * up
    upped = jnp.zeros((flat.shape[0], up_len), dtype=flat.dtype)
    upped = upped.at[:, ::up].set(flat)

    # scipy centers the filter: output sample j taps x_up[j*down - k + half]
    half = (len(h) - 1) // 2
    k = jnp.asarray(h[::-1].copy(), dtype=flat.dtype)
    lhs = upped[:, None, :]
    rhs = k[None, None, :]
    full = lax.conv_general_dilated(lhs, rhs, window_strides=(down,),
                                    padding=[(half, half)])[:, 0, :]
    out = full[:, :n_out]
    return jnp.moveaxis(out.reshape(shape[:-1] + (n_out,)), -1, axis)
