"""Continuous wavelet transform (Morlet) and the CWT travel-time picker.

Covers the reference's ``pick_travel_time`` (modules/utils.py:19-32), which
runs an external ``xwt.cwt`` per gather trace in a Python loop and argmaxes
the scalogram magnitude at one frequency over the positive-lag half of the
cross-correlation.  Here the transform is one batched frequency-domain
product — rfft of all traces once, multiply by the analytic Morlet response
for every scale at once, one irfft — so the whole gather transforms in a
single fused XLA computation instead of ``ntraces x nscales`` host FFTs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

OMEGA0 = 6.0   # standard Morlet admissibility-safe center frequency


def log_freqs(freq_min: float, freq_max: float, n: int = 200) -> np.ndarray:
    """Log-spaced analysis frequencies [Hz], low to high (the reference's
    ``nptsfreq`` scale axis)."""
    return np.logspace(np.log10(freq_min), np.log10(freq_max), int(n))


def cwt_morlet(data: jnp.ndarray, fs: float, freqs, omega0: float = OMEGA0):
    """Morlet CWT along the last axis.

    ``data``: (..., nt) real.  Returns complex (..., nfreq, nt) coefficients.

    The analytic Morlet response at scale ``s`` is
    ``psi_hat(s*w) = pi**-0.25 * exp(-(s*w - omega0)**2 / 2)`` for ``w > 0``,
    with the scale chosen so the response peaks at the requested frequency
    (``s = omega0 / (2*pi*f)``).  L2 (energy) normalization ``sqrt(2*pi*s*fs)``
    keeps equal-amplitude tones comparable across scales.  The signal is
    zero-padded to the next power of two >= 2*nt so the circular product
    cannot wrap energy between the two ends.
    """
    data = jnp.asarray(data)
    nt = data.shape[-1]
    nfft = 1 << int(np.ceil(np.log2(max(2 * nt, 2))))
    freqs = np.asarray(freqs, dtype=np.float64)
    scales = omega0 / (2.0 * np.pi * freqs)                      # seconds

    w = 2.0 * np.pi * np.fft.rfftfreq(nfft, d=1.0 / fs)          # (nw,)
    sw = scales[:, None] * w[None, :]                            # (nfreq, nw)
    psi_hat = (np.pi ** -0.25) * np.exp(-0.5 * (sw - omega0) ** 2) * (w[None, :] > 0)
    psi_hat = psi_hat * np.sqrt(2.0 * np.pi * scales[:, None] * fs)
    psi_hat = jnp.asarray(psi_hat, dtype=jnp.complex64 if data.dtype != jnp.float64
                          else jnp.complex128)

    # jitted core: the tunneled axon TPU platform lacks eager kernels for
    # some fft/layout ops, so eager library calls route through XLA too
    return _cwt_apply(data, psi_hat, nfft, nt)


@partial(jax.jit, static_argnames=("nfft", "nt"))
def _cwt_apply(data, psi_hat, nfft: int, nt: int):
    spec = jnp.fft.rfft(data, n=nfft, axis=-1)                   # (..., nw)
    prod = spec[..., None, :] * psi_hat                          # (..., nfreq, nw)
    # analytic wavelet: build the full spectrum with zero negative freqs
    return jnp.fft.ifft(_rfft_to_full(prod, nfft), axis=-1)[..., :nt]


def _rfft_to_full(half: jnp.ndarray, nfft: int) -> jnp.ndarray:
    """Embed an rfft-layout spectrum into the full fft layout with zeros in
    the negative-frequency bins (the wavelet is analytic, not Hermitian)."""
    pad = nfft - half.shape[-1]
    return jnp.concatenate([half, jnp.zeros(half.shape[:-1] + (pad,), half.dtype)],
                           axis=-1)


def pick_travel_times(gather: jnp.ndarray, dt: float, pick_freq: float = 12.0,
                      freq_min: float = 2.0, freq_max: float = 12.0,
                      nfreq: int = 200, omega0: float = OMEGA0):
    """Group-arrival travel time per gather trace from the CWT scalogram.

    Mirrors the reference picker (modules/utils.py:19-32): per trace, take the
    scalogram magnitude on the positive-lag half (``[:, nt//2:]``), find the
    frequency row nearest ``pick_freq``, argmax over lag, convert the index to
    seconds.  Vectorized over every trace at once.

    ``gather``: (ntr, nt) with zero lag at ``nt//2`` (the gather layout
    produced by the xcorr engine).  Returns ``(times_s (ntr,), f_used)``.
    """
    freqs = log_freqs(freq_min, freq_max, nfreq)
    fi = int(np.argmin(np.abs(freqs - pick_freq)))
    nt = gather.shape[-1]
    times = _pick_apply(jnp.asarray(gather), 1.0 / dt, float(freqs[fi]),
                        float(omega0), nt)
    return times, float(freqs[fi])


@partial(jax.jit, static_argnames=("fs", "f_pick", "omega0", "nt"))
def _pick_apply(gather, fs: float, f_pick: float, omega0: float, nt: int):
    """Whole picker under one jit (scalogram row + positive-lag argmax): the
    axon platform cannot run the eager post-ops, and one fused XLA program is
    what a production caller compiles anyway."""
    mag = jnp.abs(cwt_morlet(gather, fs, np.array([f_pick]), omega0=omega0))
    half = mag[..., 0, nt // 2:]                                  # (ntr, nlag)
    idx = jnp.argmax(half, axis=-1)
    dtype = jnp.float64 if half.dtype == jnp.float64 else jnp.float32
    return idx.astype(dtype) / fs
