"""Static-shape peak detection (local maxima + distance pruning + prominence).

TPU re-design of ``scipy.signal.find_peaks(prominence=, wlen=, distance=)`` as
used by the reference tracker (apis/tracking.py:36-39,122): dense local-maxima
mask -> ``lax.top_k`` candidate extraction -> sequential-by-priority distance
pruning (scipy's algorithm, ranked loop instead of a Python while) -> windowed
prominence from suffix/prefix minima.  Everything is fixed capacity
(``cap`` candidates, ``max_peaks`` outputs) so the whole detector jit/vmaps
over channels.

Deliberate deltas vs scipy (documented, tolerance-tested on continuous data):
plateaus (exact float ties between neighbors) are not peak candidates, and
only the ``cap`` highest local maxima enter distance pruning — exact whenever
a trace has <= cap local maxima.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_BIG = jnp.asarray(2 ** 30, dtype=jnp.int32)


def local_maxima(trace: jnp.ndarray) -> jnp.ndarray:
    """Strict interior local maxima mask (x[i-1] < x[i] > x[i+1])."""
    mid = (trace[1:-1] > trace[:-2]) & (trace[1:-1] > trace[2:])
    return jnp.pad(mid, (1, 1), constant_values=False)


def _distance_prune(pos: jnp.ndarray, keep: jnp.ndarray, distance: int) -> jnp.ndarray:
    """scipy _select_by_peak_distance on candidates already sorted by priority
    (highest first): walk down the ranking; a surviving peak removes every
    other candidate within ``distance`` samples."""
    cap = pos.shape[0]
    ranks = jnp.arange(cap)

    def body(r, kp):
        alive = kp[r]
        close = (jnp.abs(pos - pos[r]) < distance) & (ranks != r)
        return jnp.where(alive, kp & ~close, kp)

    return jax.lax.fori_loop(0, cap, body, keep)


def _window_minima(wins: jnp.ndarray, half: int):
    """Per-candidate left/right prominence bases.

    ``wins``: (cap, 2*half+1) values centered on each candidate, +inf outside
    the record (scipy clamps its window at the record edge; +inf padding both
    terminates the search stretch there and stays out of the minima).
    """
    c = half
    center = wins[:, c:c + 1]
    idx = jnp.arange(half)
    # left stretch: from the nearest higher sample (or edge) up to the peak
    left = wins[:, :c]
    higher = left > center
    j_hi = jnp.max(jnp.where(higher, idx, -1), axis=1)          # -1 if none
    # suffix minima toward the center: lmin[:, j] = min(left[:, j:])
    lmin = jnp.flip(jax.lax.cummin(jnp.flip(left, axis=1), axis=1), axis=1)
    sel = jnp.clip(j_hi + 1, 0, c - 1)
    left_base = jnp.take_along_axis(lmin, sel[:, None], axis=1)[:, 0]
    # right stretch, mirrored so "toward the peak" is again rightward
    right = jnp.flip(wins[:, c + 1:], axis=1)
    higher_r = right > center
    j_hi_r = jnp.max(jnp.where(higher_r, idx, -1), axis=1)
    rmin = jnp.flip(jax.lax.cummin(jnp.flip(right, axis=1), axis=1), axis=1)
    sel_r = jnp.clip(j_hi_r + 1, 0, c - 1)
    right_base = jnp.take_along_axis(rmin, sel_r[:, None], axis=1)[:, 0]
    return left_base, right_base


@functools.partial(jax.jit, static_argnames=("min_distance", "wlen", "max_peaks",
                                             "cap", "use_prominence"))
def find_peaks(trace: jnp.ndarray, min_prominence: float = 0.2,
               min_distance: int = 50, wlen: int = 600, max_peaks: int = 64,
               cap: int = 512, use_prominence: bool = True):
    """scipy-compatible peak pick; returns (positions (max_peaks,) int32
    ascending, valid mask).  Condition order matches scipy: distance first,
    prominence second."""
    nt = trace.shape[-1]
    heights = jnp.where(local_maxima(trace), trace, -jnp.inf)
    cap = min(cap, nt)
    vals, pos = jax.lax.top_k(heights, cap)                     # priority order
    keep = vals > -jnp.inf
    keep = _distance_prune(pos, keep, int(math.ceil(min_distance)))

    if use_prominence:
        half = (wlen if wlen % 2 else wlen + 1) // 2            # scipy rounds wlen up to odd
        offs = jnp.arange(-half, half + 1)
        gidx = pos[:, None] + offs[None, :]
        inside = (gidx >= 0) & (gidx < nt)
        wins = jnp.where(inside, trace[jnp.clip(gidx, 0, nt - 1)], jnp.inf)
        left_base, right_base = _window_minima(wins, half)
        prominence = vals - jnp.maximum(left_base, right_base)
        keep = keep & (prominence >= min_prominence)

    # compact ascending-by-position into max_peaks slots
    key = jnp.where(keep, pos, _BIG)
    order = jnp.argsort(key)
    out_pos = key[order][:max_peaks]
    valid = out_pos < _BIG
    return jnp.where(valid, out_pos, 0).astype(jnp.int32), valid


def gaussian_likelihood(peak_idx: jnp.ndarray, peak_valid: jnp.ndarray,
                        t_axis: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Sum of normal pdfs centered on peak times (reference ``likelihood_1d``,
    modules/car_tracking_utils.py:21-26)."""
    t0 = t_axis[peak_idx]                                        # (npk,)
    z = (t_axis[None, :] - t0[:, None]) / sigma
    pdf = jnp.exp(-0.5 * z * z) / (sigma * jnp.sqrt(2.0 * jnp.pi))
    return jnp.sum(jnp.where(peak_valid[:, None], pdf, 0.0), axis=0)
