from das_diff_veh_tpu.ops.cwt import cwt_morlet, pick_travel_times
from das_diff_veh_tpu.ops.filters import (bandpass_space, bandpass_time,
                                          das_preprocess, detrend_linear,
                                          remove_common_mode, taper_time,
                                          tukey_window)
from das_diff_veh_tpu.ops.psd import welch_psd
from das_diff_veh_tpu.ops.qc import (empty_trace_mask, impute_traces,
                                     noisy_trace_mask)
from das_diff_veh_tpu.ops.resample import resample_poly
from das_diff_veh_tpu.ops.savgol import savgol_filter
