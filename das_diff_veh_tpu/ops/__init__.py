from das_diff_veh_tpu.ops.filters import (  # noqa: F401
    bandpass_time,
    bandpass_space,
    tukey_window,
    taper_time,
    detrend_linear,
    remove_common_mode,
    das_preprocess,
)
from das_diff_veh_tpu.ops.savgol import savgol_filter  # noqa: F401
from das_diff_veh_tpu.ops.resample import resample_poly  # noqa: F401
from das_diff_veh_tpu.ops.psd import welch_psd  # noqa: F401
from das_diff_veh_tpu.ops.cwt import cwt_morlet, pick_travel_times  # noqa: F401
from das_diff_veh_tpu.ops.qc import (  # noqa: F401
    noisy_trace_mask,
    empty_trace_mask,
    impute_traces,
)
