"""Windowed cross-correlation engine — the hot kernel of the framework.

The reference computes, per 50%-overlap window, ``signal.correlate(doubled
source, receiver, mode='valid', method='fft')`` where the source window is
circularly doubled via ``repeat1d`` (reference modules/utils.py:250-270
XCORR_two_traces; :289-314 XCORR_vshot — a Python double loop of
nwin x nch FFT calls).  That "doubled + valid" scheme is exactly *circular*
cross-correlation of the two windows:

    c[k] = sum_n src[(n+k) mod W] * rcv[n] = irfft( rfft(src) * conj(rfft(rcv)) )

so one virtual-shot gather collapses to a single batched rfft over
(channel, window) tiles, one elementwise complex product, and one irfft —
fully MXU/VPU-friendly, no Python loops, vmappable over windows and shards
over channels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sliding_windows(trace_or_data: jnp.ndarray, wlen: int, offset: int) -> jnp.ndarray:
    """Cut 1-D (or (nch, nt)) data into ``nwin`` windows of ``wlen`` samples
    every ``offset`` samples: returns (..., nwin, wlen)."""
    nt = trace_or_data.shape[-1]
    nwin = (nt - wlen) // offset + 1
    idx = jnp.arange(nwin)[:, None] * offset + jnp.arange(wlen)[None, :]
    return trace_or_data[..., idx]


def _circ_corr_freq(src_f: jnp.ndarray, rcv_f: jnp.ndarray, wlen: int) -> jnp.ndarray:
    """irfft(src_f * conj(rcv_f)): circular correlation, zero lag at index 0."""
    return jnp.fft.irfft(src_f * jnp.conj(rcv_f), n=wlen, axis=-1)


def xcorr_pair(tr_src: jnp.ndarray, tr_rcv: jnp.ndarray, wlen: int,
               overlap_ratio: float = 0.5) -> jnp.ndarray:
    """Windowed circular xcorr of two traces; matches reference
    XCORR_two_traces(tr1=tr_src, tr2=tr_rcv) (modules/utils.py:253-270):
    average over windows then roll zero lag to index wlen//2."""
    offset = int(wlen * (1.0 - overlap_ratio))
    src_w = sliding_windows(tr_src, wlen, offset)       # (nwin, wlen)
    rcv_w = sliding_windows(tr_rcv, wlen, offset)
    sf = jnp.fft.rfft(src_w, axis=-1)
    rf = jnp.fft.rfft(rcv_w, axis=-1)
    c = _circ_corr_freq(sf, rf, wlen)
    out = jnp.mean(c, axis=0)
    return jnp.roll(out, wlen // 2, axis=-1)


def xcorr_vshot(data: jnp.ndarray, ivs, wlen: int, overlap_ratio: float = 0.5,
                reverse: bool = False) -> jnp.ndarray:
    """One virtual source vs every channel; matches reference XCORR_vshot
    (modules/utils.py:289-314).

    ``data``: (nch, nt).  ``ivs``: source channel (may be traced).
    ``reverse=True`` reproduces the reference's swapped-operand call
    ``correlate(receiver, doubled source, 'valid')`` — numerically the
    *index-reversed* circular correlation c[wlen-1-k].
    Returns (nch, wlen) with zero lag at wlen//2.
    """
    offset = int(wlen * (1.0 - overlap_ratio))
    wins = sliding_windows(data, wlen, offset)          # (nch, nwin, wlen)
    wf = jnp.fft.rfft(wins, axis=-1)
    src_f = jnp.take(wf, ivs, axis=0)                   # (nwin, nf) — traced ok
    spec = src_f[None] * jnp.conj(wf)
    c = jnp.fft.irfft(spec, n=wlen, axis=-1)            # (nch, nwin, wlen)
    if reverse:
        c = c[..., ::-1]
    out = jnp.mean(c, axis=1)
    return jnp.roll(out, wlen // 2, axis=-1)


def xcorr_vshot_batch(data: jnp.ndarray, wlen: int, overlap_ratio: float = 0.5,
                      reverse: bool = False) -> jnp.ndarray:
    """All-pairs generalization: every channel as virtual source.

    Returns (nch_src, nch_rcv, wlen).  One einsum in the frequency domain —
    the building block of the 10k-channel ambient-noise config
    (BASELINE.json config 4); for channel counts that exceed HBM the Pallas
    tiled variant in ops/pallas_xcorr.py streams the (src, rcv) tile space.
    """
    offset = int(wlen * (1.0 - overlap_ratio))
    wins = sliding_windows(data, wlen, offset)          # (nch, nwin, wlen)
    wf = jnp.fft.rfft(wins, axis=-1)                    # (nch, nwin, nf)
    spec = jnp.einsum("swf,rwf->srwf", wf, jnp.conj(wf))
    c = jnp.fft.irfft(spec, n=wlen, axis=-1)
    if reverse:
        c = c[..., ::-1]
    out = jnp.mean(c, axis=2)                           # (nsrc, nrcv, wlen)
    return jnp.roll(out, wlen // 2, axis=-1)


def xcorr_traj_follow(data: jnp.ndarray, t_axis: jnp.ndarray, pivot_idx: int,
                      ch_indices: jnp.ndarray, t_at_ch: jnp.ndarray,
                      nsamp: int, wlen: int, overlap_ratio: float = 0.5,
                      reverse: bool = False) -> jnp.ndarray:
    """Trajectory-following pair correlations (reference
    apis/virtual_shot_gather.py:14-43 xcorr_two_traces_based_on_traj).

    For each channel ``ch_indices[k]`` a per-channel time window of ``nsamp``
    samples starts (forward) or ends (reverse) at the first t_axis sample
    >= ``t_at_ch[k]``; the pivot trace is cut with the *same* per-channel
    window, then the pair runs through the windowed circular xcorr.  The
    data-dependent window starts become ``dynamic_slice`` + vmap — static
    shapes, no retracing.

    Returns (len(ch_indices), wlen).
    """
    dt_idx = jnp.searchsorted(t_axis, t_at_ch)          # first index with t >= target
    nt = data.shape[-1]

    def one(ch, ti):
        start = jnp.where(reverse, ti - nsamp, ti)
        start = jnp.clip(start, 0, nt - nsamp)
        tr_ch = jax.lax.dynamic_slice(data[ch], (start,), (nsamp,))
        tr_pv = jax.lax.dynamic_slice(data[pivot_idx], (start,), (nsamp,))
        if reverse:
            # reference: vs, vr = pivot, channel (virtual_shot_gather.py:37-38)
            return xcorr_pair(tr_pv, tr_ch, wlen, overlap_ratio)
        # reference: vs, vr = channel, pivot (virtual_shot_gather.py:39-40)
        return xcorr_pair(tr_ch, tr_pv, wlen, overlap_ratio)

    return jax.vmap(one)(ch_indices, dt_idx)
