"""Windowed cross-correlation engine — the hot kernel of the framework.

The reference computes, per 50%-overlap window, ``signal.correlate(doubled
source, receiver, mode='valid', method='fft')`` where the source window is
circularly doubled via ``repeat1d`` (reference modules/utils.py:250-270
XCORR_two_traces; :289-314 XCORR_vshot — a Python double loop of
nwin x nch FFT calls).  That "doubled + valid" scheme is exactly *circular*
cross-correlation of the two windows:

    c[k] = sum_n src[(n+k) mod W] * rcv[n] = irfft( rfft(src) * conj(rfft(rcv)) )

so one virtual-shot gather collapses to a single batched rfft over
(channel, window) tiles, one elementwise complex product, and one irfft —
fully MXU/VPU-friendly, no Python loops, vmappable over windows and shards
over channels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def sliding_windows(trace_or_data: jnp.ndarray, wlen: int, offset: int) -> jnp.ndarray:
    """Cut 1-D (or (nch, nt)) data into ``nwin`` windows of ``wlen`` samples
    every ``offset`` samples: returns (..., nwin, wlen).

    Static starts -> a stack of static slices (contiguous block copies), not
    an elementwise gather: TPU gathers move ~0.4 GB/s while slice copies run
    at memory speed.  The stack unrolls ``nwin`` slice ops into the traced
    graph, so beyond a few hundred windows (continuous-record use, not the
    ~15-window vehicle gathers this repo cuts) it falls back to the single
    dynamic-slice formulation to keep trace/compile time bounded.
    """
    nt = trace_or_data.shape[-1]
    nwin = (nt - wlen) // offset + 1
    if nwin <= 0:  # trace shorter than one window: empty batch, like the
        # old gather formulation (the reference guards nwin > 0 at
        # modules/utils.py:267)
        return jnp.zeros((*trace_or_data.shape[:-1], 0, wlen),
                         trace_or_data.dtype)
    if nwin > 256:
        starts = jnp.arange(0, nwin * offset, offset)
        return cut_windows_at(trace_or_data, starts, wlen)
    return jnp.stack([trace_or_data[..., s:s + wlen]
                      for s in range(0, nwin * offset, offset)], axis=-2)


def cut_windows_at(data: jnp.ndarray, starts: jnp.ndarray, wlen: int) -> jnp.ndarray:
    """Cut (..., nt) data into windows of ``wlen`` at traced ``starts``
    (nwin,): returns (..., nwin, wlen).

    Batched ``lax.dynamic_slice`` — ~3x faster than the equivalent
    ``take_along_axis`` gather on TPU (contiguous block copies instead of
    elementwise random access; measured on the v5e this repo benches on).
    """
    wins = jax.vmap(lambda st: lax.dynamic_slice_in_dim(data, st, wlen,
                                                        axis=-1))(starts)
    return jnp.moveaxis(wins, 0, -2)


def _circ_corr_freq(src_f: jnp.ndarray, rcv_f: jnp.ndarray, wlen: int) -> jnp.ndarray:
    """irfft(src_f * conj(rcv_f)): circular correlation, zero lag at index 0."""
    return jnp.fft.irfft(src_f * jnp.conj(rcv_f), n=wlen, axis=-1)


def xcorr_pair(tr_src: jnp.ndarray, tr_rcv: jnp.ndarray, wlen: int,
               overlap_ratio: float = 0.5) -> jnp.ndarray:
    """Windowed circular xcorr of two traces; matches reference
    XCORR_two_traces(tr1=tr_src, tr2=tr_rcv) (modules/utils.py:253-270):
    average over windows then roll zero lag to index wlen//2."""
    offset = int(wlen * (1.0 - overlap_ratio))
    src_w = sliding_windows(tr_src, wlen, offset)       # (nwin, wlen)
    rcv_w = sliding_windows(tr_rcv, wlen, offset)
    sf = jnp.fft.rfft(src_w, axis=-1)
    rf = jnp.fft.rfft(rcv_w, axis=-1)
    c = _circ_corr_freq(sf, rf, wlen)
    out = jnp.mean(c, axis=0)
    return jnp.roll(out, wlen // 2, axis=-1)


def xcorr_vshot(data: jnp.ndarray, ivs, wlen: int, overlap_ratio: float = 0.5,
                reverse: bool = False) -> jnp.ndarray:
    """One virtual source vs every channel; matches reference XCORR_vshot
    (modules/utils.py:289-314).

    ``data``: (nch, nt).  ``ivs``: source channel (may be traced).
    ``reverse=True`` reproduces the reference's swapped-operand call
    ``correlate(receiver, doubled source, 'valid')`` — numerically the
    *index-reversed* circular correlation c[wlen-1-k].
    Returns (nch, wlen) with zero lag at wlen//2.
    """
    offset = int(wlen * (1.0 - overlap_ratio))
    wins = sliding_windows(data, wlen, offset)          # (nch, nwin, wlen)
    wf = jnp.fft.rfft(wins, axis=-1)
    src_f = jnp.take(wf, ivs, axis=0)                   # (nwin, nf) — traced ok
    spec = src_f[None] * jnp.conj(wf)
    c = jnp.fft.irfft(spec, n=wlen, axis=-1)            # (nch, nwin, wlen)
    if reverse:
        c = c[..., ::-1]
    out = jnp.mean(c, axis=1)
    return jnp.roll(out, wlen // 2, axis=-1)


def xcorr_vshot_batch(data: jnp.ndarray, wlen: int, overlap_ratio: float = 0.5,
                      reverse: bool = False) -> jnp.ndarray:
    """All-pairs generalization: every channel as virtual source.

    Returns (nch_src, nch_rcv, wlen).  One einsum in the frequency domain;
    note it materializes the (nsrc, nrcv, nwin, nf) product, so it is for
    imaging-sized gathers (~40 channels) over short records.  For the
    10k-channel ambient-noise config (BASELINE.json config 4) — or ANY
    channel count over minutes-long records — use
    ``ops.pallas_xcorr.xcorr_all_pairs`` / ``xcorr_all_pairs_peak``: a
    source-chunked Pallas tiled kernel that never materializes the
    pair-window product and streams the window axis through its grid
    (``win_block``), so memory is bounded in both channel count and record
    length (parity-tested against this function in
    tests/test_pallas_xcorr.py).
    """
    offset = int(wlen * (1.0 - overlap_ratio))
    wins = sliding_windows(data, wlen, offset)          # (nch, nwin, wlen)
    wf = jnp.fft.rfft(wins, axis=-1)                    # (nch, nwin, nf)
    spec = jnp.einsum("swf,rwf->srwf", wf, jnp.conj(wf))
    c = jnp.fft.irfft(spec, n=wlen, axis=-1)
    if reverse:
        c = c[..., ::-1]
    out = jnp.mean(c, axis=2)                           # (nsrc, nrcv, wlen)
    return jnp.roll(out, wlen // 2, axis=-1)


def window_slice_avail(start, nt: int, nsamp: int, backward: bool):
    """Shared numpy-slice-parity arithmetic of the data-dependent window
    cut: ``(s0, avail)`` where ``s0`` is the logical slice start and
    ``avail`` how many of its ``nsamp`` samples actually exist.

    ``backward=False``: the slice is ``[start, start+nsamp)``, truncated at
    the record end like a numpy slice.  ``backward=True``: the slice is
    ``[start-nsamp, start)``, *empty* whenever ``start < nsamp`` (numpy's
    negative-start slice), truncated at the record end for ``start > nt``.
    Both the serialized cut (:func:`_masked_window_specs`) and the fused
    Pallas gather (``ops.pallas_gather._traj_scalars``) derive their
    validity masks from this one function, so the two paths cannot
    silently diverge on edge semantics."""
    if backward:
        s0 = start - nsamp
        avail = jnp.where(s0 >= 0, jnp.clip(nt - s0, 0, nsamp), 0)
    else:
        s0 = start
        avail = jnp.clip(nt - start, 0, nsamp)
    return s0, avail


def _masked_window_specs(data: jnp.ndarray, start, nsamp: int, wlen: int,
                         offset: int, backward: bool):
    """rfft of windows cut at *absolute* sample positions, with reference-parity
    validity masks.

    ``backward=False``: the logical slice is ``[start, start+nsamp)`` and is
    *truncated at the record end* like a numpy slice — window w (at
    start + w*offset) is valid iff it fits inside the truncated span.
    ``backward=True``: the logical slice is ``[start-nsamp, start)`` and is
    *empty whenever start < nsamp* — numpy's negative-start slice yields an
    empty array there (reference apis/virtual_shot_gather.py:31,152), so every
    window is invalid.  Assumes nsamp <= nt.

    Returns ``(win_f (..., nwin, nf), valid (nwin,), n_eff scalar)``.
    """
    nt = data.shape[-1]
    nwin = (nsamp - wlen) // offset + 1
    w = jnp.arange(nwin)
    s0, avail = window_slice_avail(start, nt, nsamp, backward)
    valid = (w * offset + wlen) <= avail                # (nwin,)
    # the nwin overlapping windows tile ONE contiguous nsamp block: cut that
    # block with a single dynamic slice (the serialized-slice loop is the
    # pipeline's hottest op — one trip instead of nwin) and take static
    # sub-windows.  Zero-padding the tail lets the block read past the
    # record end; every window reaching the pad (or the clamped backward
    # empty-slice case) has ``valid`` False by the ``avail`` bounds above,
    # so every VALID window's samples are bit-identical to a direct cut.
    dpad = jnp.pad(data, [(0, 0)] * (data.ndim - 1) + [(0, nsamp)])
    block = lax.dynamic_slice_in_dim(dpad, jnp.clip(s0, 0, nt), nsamp,
                                     axis=-1)
    if nwin > 256:       # bounded graph for continuous-record window counts
        wins = cut_windows_at(block, w * offset, wlen)
    else:
        wins = jnp.stack([block[..., k * offset:k * offset + wlen]
                          for k in range(nwin)], axis=-2)
    return jnp.fft.rfft(wins, axis=-1), valid, jnp.sum(valid)


def xcorr_pair_at(tr_src: jnp.ndarray, tr_rcv: jnp.ndarray, start, nsamp: int,
                  wlen: int, overlap_ratio: float = 0.5,
                  backward: bool = False) -> jnp.ndarray:
    """Windowed circular xcorr of the data-dependent slice
    ``[start, start+nsamp)`` (or ``[start-nsamp, start)`` with
    ``backward=True``) of two traces — the building block of the
    trajectory-following gather (reference apis/virtual_shot_gather.py:31-41).

    Static shapes: the reference's numpy truncation/empty-slice behavior is
    reproduced with per-window validity masks (zero output when no window
    fits, matching XCORR_two_traces' ``nwin > 0`` guard, modules/utils.py:267).
    """
    offset = int(wlen * (1.0 - overlap_ratio))
    # both traces share the same per-window starts: stack them so the
    # data-dependent window cut (a serialized dynamic-slice loop on TPU —
    # the pipeline's single hottest op) runs ONCE over (2, nt) instead of
    # twice over (nt,), and the rffts batch together
    both = jnp.stack([tr_src, tr_rcv])                  # (2, nt)
    bf, valid, n_eff = _masked_window_specs(both, start, nsamp, wlen, offset, backward)
    c = _circ_corr_freq(bf[0], bf[1], wlen)             # (nwin, wlen)
    out = jnp.sum(jnp.where(valid[:, None], c, 0.0), axis=0) / jnp.maximum(n_eff, 1)
    return jnp.roll(out, wlen // 2, axis=-1)


def xcorr_vshot_at(data: jnp.ndarray, ivs, start, nsamp: int, wlen: int,
                   overlap_ratio: float = 0.5, reverse: bool = False,
                   backward: bool = False) -> jnp.ndarray:
    """``xcorr_vshot`` on the data-dependent time slice ``[start, start+nsamp)``
    (``backward=True``: ``[start-nsamp, start)``) of (nch, nt) data — the
    one-sided gather kernels of the reference
    (apis/virtual_shot_gather.py:152-153,172).  Same masked-window parity
    semantics as :func:`xcorr_pair_at`.  Returns (nch, wlen)."""
    offset = int(wlen * (1.0 - overlap_ratio))
    wf, valid, n_eff = _masked_window_specs(data, start, nsamp, wlen, offset, backward)
    src_f = jnp.take(wf, ivs, axis=0)                   # (nwin, nf)
    c = _circ_corr_freq(src_f[None], wf, wlen)          # (nch, nwin, wlen)
    if reverse:
        c = c[..., ::-1]
    out = jnp.sum(jnp.where(valid[None, :, None], c, 0.0), axis=1) / jnp.maximum(n_eff, 1)
    return jnp.roll(out, wlen // 2, axis=-1)


def _decide_traj_gather(mode: str | None, nwin: int, wlen: int,
                        finish: str, *, max_nwin: int | None = None,
                        dot_max_wlen: int | None = None,
                        dot_max_elems: int | None = None) -> bool:
    """Resolve the gather-path knob to fused (True) / serialized (False).

    ``"auto"`` (the :class:`~das_diff_veh_tpu.config.GatherConfig` default)
    mirrors ``pallas_xcorr._decide_pallas``: the Pallas kernel runs on TPU
    backends (where the serialized slice chain is the measured hot path);
    CPU keeps the XLA formulation — fused is still fully exercised there by
    forcing ``mode="fused"`` (interpret-mode kernel, tests do).
    """
    if finish not in ("rfft", "dot"):
        raise ValueError(f"traj_gather_finish must be 'rfft' or 'dot', "
                         f"got {finish!r}")
    if mode in (None, "auto"):
        from das_diff_veh_tpu.ops.pallas_gather import fused_supported
        from das_diff_veh_tpu.resilience import degrade
        # degradation-ladder rung 2: once the fused kernel has been demoted
        # (repeated compute-dispatch failures, see resilience/degrade.py),
        # "auto" resolves to the battle-tested serialized cut.  Explicit
        # mode="fused" still forces the kernel — the operator's override.
        if degrade.demoted(degrade.GATHER_FUSED):
            return False
        return (jax.default_backend() in ("tpu", "axon")
                and fused_supported(nwin, wlen, finish, max_nwin=max_nwin,
                                    dot_max_wlen=dot_max_wlen,
                                    dot_max_elems=dot_max_elems))
    if mode == "serialized":
        return False
    if mode == "fused":
        return True
    raise ValueError(f"traj_gather must be 'auto', 'fused' or 'serialized', "
                     f"got {mode!r}")


def xcorr_traj_follow(data: jnp.ndarray, t_axis: jnp.ndarray, pivot_idx: int,
                      ch_indices: jnp.ndarray, t_at_ch: jnp.ndarray,
                      nsamp: int, wlen: int, overlap_ratio: float = 0.5,
                      reverse: bool = False, *, mode: str | None = "auto",
                      finish: str = "rfft",
                      interpret: bool | None = None,
                      max_nwin: int | None = None,
                      dot_max_wlen: int | None = None,
                      dot_max_elems: int | None = None,
                      precision: str = "f32") -> jnp.ndarray:
    """Trajectory-following pair correlations (reference
    apis/virtual_shot_gather.py:14-43 xcorr_two_traces_based_on_traj).

    For each channel ``ch_indices[k]`` a per-channel time window of ``nsamp``
    samples starts (forward) or ends (reverse) at
    ``argmax(t_axis >= t_at_ch[k])``; the pivot trace is cut with the *same*
    per-channel window, then the pair runs through the masked windowed
    circular xcorr (numpy truncation/empty-slice parity, see
    :func:`xcorr_pair_at`).  Returns (len(ch_indices), wlen).

    ``mode`` selects the window-cut engine: ``"serialized"`` is the legacy
    vmapped ``dynamic_slice`` (an O(nch) serialized slice chain on TPU —
    the pipeline's measured hottest op), ``"fused"`` the Pallas
    scalar-prefetch gather kernel (``ops.pallas_gather``) that cuts every
    channel's window in one grid sweep, ``"auto"`` picks fused on TPU
    backends.  ``finish``: ``"rfft"`` runs the packed kernel windows
    through this module's batched circular correlate (bit-parity with the
    serialized path); ``"dot"`` finishes the correlation in-kernel as an
    MXU dot (small ``wlen`` only).  ``interpret`` follows
    ``ops.pallas_xcorr`` convention (None = interpret off-TPU).

    ``max_nwin`` / ``dot_max_wlen`` / ``dot_max_elems`` override the fused
    kernel's support caps (``GatherConfig.fused_max_nwin`` /
    ``dot_max_wlen`` / ``dot_max_matrix_elems``; None = the module
    defaults).  ``precision`` selects the "dot" finish's MXU tier
    (``"bf16"`` = bf16 operands, f32 accumulation); the rfft and
    serialized paths ignore it — they never touch the MXU.
    """
    dt_idx = jnp.argmax(t_axis[None, :] >= t_at_ch[:, None], axis=-1)
    offset = int(wlen * (1.0 - overlap_ratio))
    nwin = (nsamp - wlen) // offset + 1
    if _decide_traj_gather(mode, nwin, wlen, finish, max_nwin=max_nwin,
                           dot_max_wlen=dot_max_wlen,
                           dot_max_elems=dot_max_elems):
        return _traj_follow_fused(data, pivot_idx, ch_indices, dt_idx,
                                  nsamp, wlen, offset, reverse, finish,
                                  interpret, max_nwin=max_nwin,
                                  dot_max_wlen=dot_max_wlen,
                                  dot_max_elems=dot_max_elems,
                                  precision=precision)

    def one(ch, ti):
        tr_ch = data[ch]
        tr_pv = data[pivot_idx]
        if reverse:
            # reference: vs, vr = pivot, channel (virtual_shot_gather.py:37-38)
            return xcorr_pair_at(tr_pv, tr_ch, ti, nsamp, wlen, overlap_ratio,
                                 backward=True)
        # reference: vs, vr = channel, pivot (virtual_shot_gather.py:39-40)
        return xcorr_pair_at(tr_ch, tr_pv, ti, nsamp, wlen, overlap_ratio,
                             backward=False)

    return jax.vmap(one)(ch_indices, dt_idx)


def _traj_follow_fused(data, pivot_idx, ch_indices, dt_idx, nsamp: int,
                       wlen: int, offset: int, reverse: bool, finish: str,
                       interpret: bool | None, *,
                       max_nwin: int | None = None,
                       dot_max_wlen: int | None = None,
                       dot_max_elems: int | None = None,
                       precision: str = "f32") -> jnp.ndarray:
    """Fused gather path: one Pallas scalar-prefetch sweep cuts every
    channel's (and the pivot's) windows at that channel's data-dependent
    start; the circular correlate runs on the packed windows (``"rfft"``)
    or inside the kernel (``"dot"``).  Operand order and backward-window
    semantics match the serialized path exactly."""
    from das_diff_veh_tpu.ops import pallas_gather as pg

    if finish == "dot":
        return pg.traj_follow_correlate_dot(
            data, pivot_idx, ch_indices, dt_idx, nsamp, wlen, offset,
            backward=reverse, swap=reverse, interpret=interpret,
            max_nwin=max_nwin, dot_max_wlen=dot_max_wlen,
            dot_max_elems=dot_max_elems, precision=precision)
    wins_ch, wins_pv, n_eff = pg.traj_follow_windows(
        data, pivot_idx, ch_indices, dt_idx, nsamp, wlen, offset,
        backward=reverse, interpret=interpret, max_nwin=max_nwin)
    cf = jnp.fft.rfft(wins_ch, axis=-1)                 # (nk, nwin, nf)
    pf = jnp.fft.rfft(wins_pv, axis=-1)
    src_f, rcv_f = (pf, cf) if reverse else (cf, pf)
    c = _circ_corr_freq(src_f, rcv_f, wlen)             # (nk, nwin, wlen)
    # invalid windows are zeroed in BOTH operands by the kernel, so their
    # cross-spectra are exactly zero: the plain window sum equals the
    # serialized path's masked sum bit-for-bit
    out = jnp.sum(c, axis=1) / jnp.maximum(n_eff, 1)[:, None]
    return jnp.roll(out, wlen // 2, axis=-1)
