"""Welch power spectral density (jnp), matching scipy.signal.welch defaults.

The reference averages Welch PSDs per channel per window
(modules/utils.py:715-728, virtual_shot_gather.py:55) with scipy defaults:
hann window, 50% overlap, constant detrend, density scaling.
"""

from __future__ import annotations

import jax.numpy as jnp


def _hann_periodic(n: int, dtype) -> jnp.ndarray:
    """Periodic hann — what ``scipy.signal.get_window('hann')`` returns."""
    k = jnp.arange(n, dtype=dtype)
    return 0.5 - 0.5 * jnp.cos(2.0 * jnp.pi * k / n)


def welch_psd(data: jnp.ndarray, fs: float, nperseg: int = 2048,
              noverlap: int | None = None, nfft: int | None = None):
    """Welch PSD along the last axis.  Returns (freqs, Pxx).

    Matches ``scipy.signal.welch(..., window='hann', detrend='constant',
    scaling='density')``; if the signal is shorter than ``nperseg`` scipy
    shrinks the segment — we require nperseg <= n instead (static shapes).
    """
    n = data.shape[-1]
    nperseg = min(nperseg, n)
    if noverlap is None:
        noverlap = nperseg // 2
    if nfft is None:
        nfft = nperseg
    if nfft < nperseg:
        raise ValueError(f"nfft ({nfft}) must be >= nperseg ({nperseg})")
    if noverlap >= nperseg:
        raise ValueError(f"noverlap ({noverlap}) must be < nperseg ({nperseg}; "
                         f"note nperseg shrinks to the signal length {n})")
    step = nperseg - noverlap
    nseg = (n - noverlap) // step

    idx = (jnp.arange(nseg)[:, None] * step + jnp.arange(nperseg)[None, :])
    segs = data[..., idx]                               # (..., nseg, nperseg)
    segs = segs - jnp.mean(segs, axis=-1, keepdims=True)
    win = _hann_periodic(nperseg, data.dtype)
    spec = jnp.fft.rfft(segs * win, n=nfft, axis=-1)
    scale = 1.0 / (fs * jnp.sum(win * win))
    p = (jnp.abs(spec) ** 2) * scale
    # one-sided: double everything but DC (and Nyquist when nfft even)
    if nfft % 2 == 0:
        mult = jnp.concatenate([jnp.ones(1), 2 * jnp.ones(nfft // 2 - 1), jnp.ones(1)])
    else:
        mult = jnp.concatenate([jnp.ones(1), 2 * jnp.ones((nfft - 1) // 2)])
    p = p * mult.astype(data.dtype)
    freqs = jnp.fft.rfftfreq(nfft, d=1.0 / fs)
    return freqs, jnp.mean(p, axis=-2)


def stack_avg_psd(window_data: jnp.ndarray, fs: float, nperseg: int = 2048):
    """Average PSD over channels then windows (reference win_avg_psd,
    modules/utils.py:715-728).  ``window_data``: (nwin, nch, nt)."""
    freqs, p = welch_psd(window_data, fs, nperseg=nperseg)   # (nwin, nch, nf)
    per_window = jnp.mean(p, axis=1)
    return freqs, jnp.mean(per_window, axis=0), per_window
