"""f-v map enhancement: CLAHE + box blur (reference ``fv_map_enhance``,
modules/utils.py:613-619: normalize -> uint8 -> cv2 CLAHE(clipLimit=100,
tileGridSize=(100, 10)) -> 10x10 blur).

Re-implemented as pure jnp following OpenCV's CLAHE algorithm
(modules/imgproc clahe.cpp semantics, written from the published algorithm,
parity-tested against cv2 in tests/test_enhance.py):

1. pad right/bottom with BORDER_REFLECT_101 so tiles divide evenly;
2. per-tile 256-bin histogram (one scatter-add over the flattened image);
3. clip at ``max(clipLimit * tileArea / 256, 1)`` and redistribute the
   clipped excess (uniform part + OpenCV's stride-pattern residual);
4. per-tile LUT = round(cdf * 255 / tileArea);
5. per-pixel bilinear interpolation between the four neighboring tile LUTs.

The histograms/LUTs are one batched scatter + cumsum, the interpolation is
four gathers — no Python loops, jit/vmap-friendly, TPU-compatible.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _pad_reflect101(img: jnp.ndarray, pad_h: int, pad_w: int) -> jnp.ndarray:
    """cv2 BORDER_REFLECT_101 padding on the bottom/right edges.

    numpy's "reflect" mode is exactly REFLECT_101 (no edge repeat) and also
    handles pads wider than the image (repeated reflection) — reached when
    the tile grid exceeds the image, e.g. 100 frequency tiles on a coarse
    test map."""
    if pad_h or pad_w:
        img = jnp.pad(img, ((0, pad_h), (0, pad_w)), mode="reflect")
    return img


@partial(jax.jit, static_argnames=("clip_limit", "tiles"))
def clahe_u8(img: jnp.ndarray, clip_limit: float = 100.0,
             tiles: tuple[int, int] = (100, 10)) -> jnp.ndarray:
    """Contrast-limited adaptive histogram equalization of a uint8-valued
    image (values 0..255, any integer/float dtype accepted).

    ``tiles`` follows cv2's tileGridSize convention ``(tilesX, tilesY)`` =
    (columns of tiles, rows of tiles).  Returns int32 values 0..255.
    """
    tx, ty = tiles
    img = jnp.asarray(img).astype(jnp.int32)
    H, W = img.shape
    th = -(-H // ty)          # tile height (ceil)
    tw = -(-W // tx)
    imgp = _pad_reflect101(img, ty * th - H, tx * tw - W)

    # --- per-tile histograms: one scatter-add ------------------------------
    Hp, Wp = ty * th, tx * tw
    row_tile = jnp.arange(Hp) // th                      # (Hp,)
    col_tile = jnp.arange(Wp) // tw                      # (Wp,)
    tile_id = row_tile[:, None] * tx + col_tile[None, :]  # (Hp, Wp)
    flat_id = tile_id.reshape(-1) * 256 + imgp.reshape(-1)
    hist = jnp.zeros((ty * tx * 256,), jnp.int32).at[flat_id].add(1)
    hist = hist.reshape(ty * tx, 256)

    # --- clip + redistribute (OpenCV semantics) ----------------------------
    area = th * tw
    clip = max(int(clip_limit * area / 256.0), 1)
    clipped = jnp.minimum(hist, clip)
    excess = jnp.sum(hist - clipped, axis=1, keepdims=True)   # (ntiles, 1)
    bin_incr = excess // 256
    residual = excess - bin_incr * 256                        # (ntiles, 1)
    hist2 = clipped + bin_incr
    # OpenCV walks i = 0, step, 2*step, ... adding 1 while residual lasts,
    # with step = max(256 // residual, 1)
    step = jnp.maximum(256 // jnp.maximum(residual, 1), 1)
    i = jnp.arange(256)[None, :]
    gets_one = (i % step == 0) & (i // step < residual)
    hist2 = hist2 + gets_one.astype(jnp.int32)

    # --- LUTs --------------------------------------------------------------
    scale = 255.0 / area
    luts = jnp.clip(jnp.round(jnp.cumsum(hist2, axis=1) * scale),
                    0, 255).astype(jnp.int32)                 # (ntiles, 256)

    # --- bilinear interpolation between tile LUTs --------------------------
    yf = (jnp.arange(H) + 0.5) / th - 0.5
    xf = (jnp.arange(W) + 0.5) / tw - 0.5
    y1 = jnp.floor(yf).astype(jnp.int32)
    x1 = jnp.floor(xf).astype(jnp.int32)
    wy = (yf - y1)[:, None]
    wx = (xf - x1)[None, :]
    y1c = jnp.clip(y1, 0, ty - 1)[:, None]
    y2c = jnp.clip(y1 + 1, 0, ty - 1)[:, None]
    x1c = jnp.clip(x1, 0, tx - 1)[None, :]
    x2c = jnp.clip(x1 + 1, 0, tx - 1)[None, :]

    v = img
    lut_at = lambda tyi, txi: luts[tyi * tx + txi, v]
    top = lut_at(y1c, x1c) * (1 - wx) + lut_at(y1c, x2c) * wx
    bot = lut_at(y2c, x1c) * (1 - wx) + lut_at(y2c, x2c) * wx
    out = top * (1 - wy) + bot * wy
    return jnp.clip(jnp.round(out), 0, 255).astype(jnp.int32)


@partial(jax.jit, static_argnames=("ksize",))
def box_blur_u8(img: jnp.ndarray, ksize: int = 10) -> jnp.ndarray:
    """cv2.blur semantics: normalized ``ksize x ksize`` box filter with
    BORDER_REFLECT_101 edges and the anchor at ``ksize // 2`` (so an even
    kernel reaches ``ksize//2`` up/left and ``ksize//2 - 1`` down/right)."""
    img = jnp.asarray(img).astype(jnp.float32)
    a = ksize // 2
    b = ksize - 1 - a
    # reflect-101 pad: top/left a, bottom/right b
    top = img[1:1 + a][::-1]
    botr = img[-1 - b:-1][::-1]
    img = jnp.concatenate([top, img, botr], axis=0)
    left = img[:, 1:1 + a][:, ::-1]
    right = img[:, -1 - b:-1][:, ::-1]
    img = jnp.concatenate([left, img, right], axis=1)
    k = jnp.full((ksize, ksize), 1.0 / (ksize * ksize), jnp.float32)
    blurred = jax.lax.conv_general_dilated(
        img[None, None], k[None, None],
        window_strides=(1, 1), padding="VALID")[0, 0]
    return jnp.clip(jnp.round(blurred), 0, 255).astype(jnp.int32)


def fv_map_enhance(fv_map: jnp.ndarray, clip_limit: float = 100.0,
                   tiles: tuple[int, int] = (100, 10),
                   blur_ksize: int = 10) -> jnp.ndarray:
    """Reference fv_map_enhance (modules/utils.py:613-619): normalize by
    ``(fv - min) / max`` (the reference divides by the raw max, not the
    range), quantize to uint8 by truncation, CLAHE, 10x10 blur.  Returns
    int32 values 0..255."""
    fv = jnp.asarray(fv_map)
    mx = jnp.max(fv)
    fv = (fv - jnp.min(fv)) / jnp.where(mx != 0, mx, 1.0)  # all-constant map -> 0
    u8 = jnp.clip((fv * 255.0), 0, 255).astype(jnp.int32)  # C-cast truncation
    eq = clahe_u8(u8, clip_limit=clip_limit, tiles=tiles)
    return box_blur_u8(eq, ksize=blur_ksize)
