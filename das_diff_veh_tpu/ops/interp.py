"""Masked 1-D linear interpolation with end-segment extrapolation.

The reference interpolates vehicle trajectories with
``scipy.interpolate.interp1d(..., fill_value='extrapolate')`` (reference
apis/virtual_shot_gather.py:112, apis/data_classes.py:55) and its ``extrap1d``
wrapper (modules/utils.py:54-69) — both are piecewise-linear with linear
extrapolation from the end segments.  Trajectories here are NaN-padded to
static shapes, so the interpolant must ignore invalid knots under jit:
invalid abscissae are pushed to +inf, a sort compacts the valid knots to the
front, and queries interpolate/extrapolate on the valid run only.
"""

from __future__ import annotations

import jax.numpy as jnp

_BIG = 1e30


def masked_interp_clamped(xq: jnp.ndarray, xs: jnp.ndarray, ys: jnp.ndarray,
                          valid: jnp.ndarray) -> jnp.ndarray:
    """Like :func:`masked_interp` but with ``np.interp`` edge semantics:
    queries outside the valid span return the first/last valid ``y`` instead
    of extrapolating (the reference's track NaN-fill uses np.interp,
    modules/car_tracking_utils.py:28-35)."""
    xs_f = jnp.where(valid, xs, _BIG)
    order = jnp.argsort(xs_f)
    xs_s = xs_f[order]
    ys_s = jnp.where(valid, ys, 0.0)[order]
    n_valid = jnp.sum(valid)
    lo = xs_s[0]
    hi = xs_s[jnp.maximum(n_valid - 1, 0)]
    y_lo = ys_s[0]
    y_hi = ys_s[jnp.maximum(n_valid - 1, 0)]
    mid = masked_interp(xq, xs, ys, valid)
    return jnp.where(xq <= lo, y_lo, jnp.where(xq >= hi, y_hi, mid))


def masked_interp(xq: jnp.ndarray, xs: jnp.ndarray, ys: jnp.ndarray,
                  valid: jnp.ndarray) -> jnp.ndarray:
    """Piecewise-linear interpolation of ``(xs, ys)`` knots at ``xq``.

    ``valid`` masks live knots; valid ``xs`` must be strictly increasing.
    Queries outside the valid span extrapolate linearly from the first/last
    valid segment (scipy ``fill_value='extrapolate'`` behavior).  With a
    single valid knot the query returns its ``y``; with none, zeros
    (callers are expected to mask such trajectories out entirely).
    """
    xs = jnp.where(valid, xs, _BIG)
    order = jnp.argsort(xs)
    xs_s = xs[order]
    ys_s = jnp.where(valid, ys, 0.0)[order]
    n_valid = jnp.sum(valid)
    last_seg = jnp.maximum(n_valid - 2, 0)         # index of the last valid segment start
    i = jnp.searchsorted(xs_s, xq, side="right") - 1
    i = jnp.clip(i, 0, last_seg)
    x0 = xs_s[i]
    x1 = xs_s[i + 1]
    dx = x1 - x0
    w = (xq - x0) / jnp.where((dx > 0) & (dx < _BIG / 2), dx, 1.0)
    w = jnp.where((n_valid >= 2) & (x1 < _BIG / 2), w, 0.0)
    return ys_s[i] + w * (ys_s[i + 1] - ys_s[i])
