"""Masked 1-D linear interpolation with end-segment extrapolation.

The reference interpolates vehicle trajectories with
``scipy.interpolate.interp1d(..., fill_value='extrapolate')`` (reference
apis/virtual_shot_gather.py:112, apis/data_classes.py:55) and its ``extrap1d``
wrapper (modules/utils.py:54-69) — both are piecewise-linear with linear
extrapolation from the end segments.  Trajectories here are NaN-padded to
static shapes, so the interpolant must ignore invalid knots under jit:
invalid abscissae are pushed to +inf, a sort compacts the valid knots to the
front, and queries interpolate/extrapolate on the valid run only.
"""

from __future__ import annotations

import jax.numpy as jnp

_BIG = 1e30


def masked_interp(xq: jnp.ndarray, xs: jnp.ndarray, ys: jnp.ndarray,
                  valid: jnp.ndarray) -> jnp.ndarray:
    """Piecewise-linear interpolation of ``(xs, ys)`` knots at ``xq``.

    ``valid`` masks live knots; valid ``xs`` must be strictly increasing.
    Queries outside the valid span extrapolate linearly from the first/last
    valid segment (scipy ``fill_value='extrapolate'`` behavior).  With a
    single valid knot the query returns its ``y``; with none, zeros
    (callers are expected to mask such trajectories out entirely).
    """
    xs = jnp.where(valid, xs, _BIG)
    order = jnp.argsort(xs)
    xs_s = xs[order]
    ys_s = jnp.where(valid, ys, 0.0)[order]
    n_valid = jnp.sum(valid)
    last_seg = jnp.maximum(n_valid - 2, 0)         # index of the last valid segment start
    i = jnp.searchsorted(xs_s, xq, side="right") - 1
    i = jnp.clip(i, 0, last_seg)
    x0 = xs_s[i]
    x1 = xs_s[i + 1]
    dx = x1 - x0
    w = (xq - x0) / jnp.where((dx > 0) & (dx < _BIG / 2), dx, 1.0)
    w = jnp.where((n_valid >= 2) & (x1 < _BIG / 2), w, 0.0)
    return ys_s[i] + w * (ys_s[i + 1] - ys_s[i])
