"""Pallas-tiled all-pairs windowed cross-correlation (BASELINE config 4).

The all-pairs generalization of the reference's XCORR_vshot loop
(modules/utils.py:289-314) is, in the frequency domain,

    C[s, r, f] = (1/nwin) * sum_w  S[s, w, f] * conj(S[r, w, f])

followed by an irfft over f.  ``ops.xcorr.xcorr_vshot_batch`` evaluates this
with one einsum that materializes the (nsrc, nrcv, nwin, nf) product — fine
for the ~40-channel imaging gathers, hopeless for the synthetic 10k-channel
ambient-noise config (that intermediate would be ~10 TB, and even the full
(nch, nch, nf) spectra cube is ~800 GB).

This module therefore streams at three levels, and every level is
padding-free along the axes that grow with the problem:

1. *Source-chunk loop* (``lax.map``): only ``src_chunk`` source rows'
   spectra/lag products exist at a time, so channel count never bounds
   memory.  The receiver-side spectra are prepared (planar float32 split +
   channel/freq tile padding) ONCE, outside the chunk loop — under
   ``parallel.allpairs.sharded_all_pairs_peak`` that preparation happens
   once per device, not once per chunk step.
2. *Window-block grid dimension inside the Pallas kernel*: the window axis
   is streamed ``win_block`` windows at a time as the kernel's innermost
   grid dimension.  The (src-tile x rcv-tile x f-block) output tile stays
   resident in VMEM across the window blocks while Pallas's grid pipeline
   double-buffers the next block's spectra tiles — HBM spectra loads overlap
   the compute of the current block, and the VMEM working set is bounded by
   ``win_block`` regardless of record length.  A record-length ragged tail
   (nwin not divisible by win_block) is masked *inside* the kernel; neither
   ``wf_src`` nor ``wf_all`` is ever padded (or copied) along the window
   axis.  Window-mean cross-spectra accumulate linearly, so per-
   (pair, window) throughput is record-length-invariant by construction —
   and measured so by bench.py's nt≈60k entry.
3. *Pallas spectra-tile kernel* inside each (chunk, window-block): the grid
   loads two (tile, win_block, fblock) spectra tiles into VMEM, forms the
   complex product and accumulates the window mean in one pass — HBM
   traffic is one read of each spectra tile per (s, r) tile pair plus one
   output-tile write; no (s, r, w, f) intermediate ever exists.

Each chunk is finished in the lag domain (irfft + zero-lag roll + lag trim,
or a per-pair peak reduction) before the next chunk starts, so arbitrarily
large channel counts AND arbitrarily long records run in bounded memory on
both the lag-domain (``xcorr_all_pairs``) and peak (``xcorr_all_pairs_peak``)
paths.

On the kernel path the peak finish is *fused*: the irfft runs blockwise over
``lagmax_block`` receiver rows and each block's lag tiles feed a Pallas
abs-max reduction (``_lag_absmax_kernel``) whose (pairs,) running-max
accumulator stays resident in VMEM while the grid streams the lag axis — the
(src_chunk, nall, wlen) lag cube of the old finish never materializes in
HBM, only one (src_chunk, lagmax_block, wlen) slab at a time, and each lag
tile is read exactly once.

Below ``PALLAS_MIN_CH`` channels (or on non-TPU backends) an XLA batched
contraction ``einsum("swf,rwf->srf")`` replaces the kernel — same math,
also 4-D-free, with the same win_block-streamed accumulation (an unpadded
``fori_loop`` over full blocks plus a static ragged-tail contraction).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from das_diff_veh_tpu.ops.xcorr import sliding_windows

PALLAS_MIN_CH = 512     # below this the XLA einsum path wins (compile + pad overhead)
# Mosaic requires the last block dim divisible by 128 (lanes); VMEM is kept
# under the 16 MB limit by shrinking the channel tiles instead: out tiles are
# (32, 32, 128) f32 x2 outputs x2 pipeline buffers ~= 2 MB.
_TILE_CH = 32           # (src, rcv) tile edge
_TILE_F = 128           # frequency block (lane-aligned)

# Past this window count the kernel's window axis streams in win_block-sized
# slabs (an extra innermost grid dimension): 4 (tile, win_block, 128) f32
# inputs x 2 pipeline buffers stay ~4 MB at the default block, independent of
# record length.  Below it a single slab holds the whole record — the typical
# ~7-window imaging gathers never see the streamed path.
WIN_BLOCK_AUTO = 48
_WIN_BLOCK_DEFAULT = 32

# Fused peak finish: receiver rows per blockwise irfft + Pallas abs-max pass
# (the only lag-domain transient is (src_chunk, LAGMAX_BLOCK, wlen)).  The
# reduction kernel's tiles: _PEAK_TILE_P flattened (src x rcv) pair rows by
# up to _PEAK_TILE_L lag samples (shrunk to fit short records — see
# _pallas_lag_absmax), 256x512 f32 = 512 KB x2 pipeline buffers at the cap.
# _PEAK_TILE_L is the DEFAULT of ``RingConfig.lag_tile_max`` (the tuner's
# sweepable upper bound); the 128 floor below is the hardware lane width
# and stays a module constant.
LAGMAX_BLOCK_DEFAULT = 512
_PEAK_TILE_P = 256
_PEAK_TILE_L = 512


def _bf16_round_complex(wf: jnp.ndarray) -> jnp.ndarray:
    """Round a complex spectra array's real/imag planes through bfloat16
    (bf16-valued float32 planes): the input side of the ``"bf16"``
    precision tier on paths that contract complex operands directly.  On
    TPU the subsequent DEFAULT-precision contraction runs the MXU's bf16
    passes; off-TPU the contraction is exact on the bf16-rounded inputs,
    so the committed error bounds (tests/test_precision.py) measure the
    same input-rounding semantics everywhere."""
    wf = jnp.asarray(wf)
    r = wf.real.astype(jnp.bfloat16).astype(jnp.float32)
    i = wf.imag.astype(jnp.bfloat16).astype(jnp.float32)
    return (r + 1j * i).astype(jnp.complex64)


def _resolve_win_block(nwin: int, win_block: int | None) -> int:
    """Validate and normalize ``win_block`` to a slab size in [1, nwin]."""
    if win_block is not None and win_block < 0:
        raise ValueError(f"win_block must be None or >= 0, got {win_block}")
    if not win_block:                   # None/0: stream only past the auto cap
        return _WIN_BLOCK_DEFAULT if nwin > WIN_BLOCK_AUTO else max(nwin, 1)
    return max(min(win_block, nwin), 1)


def _resolve_lagmax_block(nall: int, use_pallas: bool,
                          lagmax_block: int | None) -> int:
    """Normalize ``lagmax_block``: 0 disables the fused finish, None fuses
    on the kernel path only (the einsum fallback keeps the exact-XLA
    finish), a positive value forces that receiver-block size."""
    if lagmax_block is not None and lagmax_block < 0:
        raise ValueError(
            f"lagmax_block must be None or >= 0, got {lagmax_block}")
    if lagmax_block is None:
        return min(LAGMAX_BLOCK_DEFAULT, nall) if use_pallas else 0
    return min(lagmax_block, nall)


def _lag_absmax_kernel(x, out):
    """One (pair-tile, lag-tile) step of the running peak-|xcorr| reduction.

    Block shapes: x (Tp, Tl) float32 lag samples, out (Tp, 128) running max.
    The innermost grid dimension streams the lag axis: the max accumulator
    tile stays resident in VMEM across lag tiles while the grid pipeline
    double-buffers the next tile's HBM load against this tile's compute —
    each lag sample is read from HBM exactly once and nothing lag-shaped is
    written back.  The per-tile reduction folds the Tl lanes onto a 128-lane
    running max (static loop, VPU maximums); the final 128 -> 1 fold happens
    outside on the (pairs, 128) output.  Lag/pair padding is zero-filled by
    the caller — |.| >= 0, so zeros never win a max over real samples."""
    lag_step = pl.program_id(1)

    @pl.when(lag_step == 0)
    def _init():
        out[:] = jnp.zeros(out.shape, out.dtype)

    a = jnp.abs(x[:])
    m = a[:, 0:128]
    for j in range(1, a.shape[1] // 128):
        m = jnp.maximum(m, a[:, j * 128:(j + 1) * 128])
    out[:] = jnp.maximum(out[:], m)


@partial(jax.jit, static_argnames=("interpret", "lag_tile_max"))
def _pallas_lag_absmax(lag: jnp.ndarray, interpret: bool = False,
                       lag_tile_max: int = _PEAK_TILE_L):
    """(npairs, nlag) float32 lag block -> (npairs,) peak |xcorr|, the lag
    axis streamed through the kernel grid with a VMEM-resident accumulator.
    Pads both axes with zeros (safe: |.| >= 0) — the lag axis only to the
    128-lane grain, with the lag tile sized as the largest power-of-two
    multiple of 128 that divides the padded length (capped at
    ``lag_tile_max``, default ``_PEAK_TILE_L`` = the
    ``RingConfig.lag_tile_max`` default), so a short ``wlen`` is not
    inflated to a full 512 tile (8x the real bytes at wlen=64)."""
    npairs, _ = lag.shape
    lp = _pad_to(_pad_to(lag, 0, _PEAK_TILE_P), 1, 128)
    cap = max(int(lag_tile_max), 128)    # 128 = the lane-width floor
    tile_l = 128
    while tile_l < cap and lp.shape[1] % (tile_l * 2) == 0:
        tile_l *= 2
    grid = (lp.shape[0] // _PEAK_TILE_P, lp.shape[1] // tile_l)
    out = pl.pallas_call(
        _lag_absmax_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((_PEAK_TILE_P, tile_l),
                               lambda i, l: (i, l),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_PEAK_TILE_P, 128), lambda i, l: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((lp.shape[0], 128), jnp.float32),
        interpret=interpret,
    )(lp)
    return jnp.max(out[:npairs], axis=-1)


def _fused_peak_finish(cross, wlen: int, rcv_block: int, interpret: bool,
                       lag_tile_max: int = _PEAK_TILE_L):
    """(m, nall, nf) cross-spectra -> (m, nall) peak |xcorr| without ever
    materializing the (m, nall, wlen) lag cube: the irfft runs ``rcv_block``
    receiver rows at a time and each slab reduces through the Pallas abs-max
    grid before the next slab's transform starts (``lax.map`` keeps exactly
    one slab live; XLA overlaps slab k+1's irfft with slab k's reduction).

    Callers may opt in from the einsum fallback (``lagmax_block > 0`` with
    ``use_pallas=False``) — the reduction kernel only lowers on TPU, so on
    other backends it drops to interpret mode here instead of failing in
    ``pallas_call``."""
    interpret = interpret or jax.default_backend() not in ("tpu", "axon")
    m, nall, nf = cross.shape
    if rcv_block >= nall:
        lag = jnp.fft.irfft(cross, n=wlen, axis=-1)
        return _pallas_lag_absmax(lag.reshape(m * nall, wlen),
                                  interpret=interpret,
                                  lag_tile_max=lag_tile_max,
                                  ).reshape(m, nall)
    pad = (-nall) % rcv_block
    cp = jnp.pad(cross, ((0, 0), (0, pad), (0, 0)))   # receiver rows, not
    n_blocks = cp.shape[1] // rcv_block               # the window axis
    blocks = jnp.moveaxis(cp.reshape(m, n_blocks, rcv_block, nf), 1, 0)

    def one(blk):
        lag = jnp.fft.irfft(blk, n=wlen, axis=-1)     # (m, rcv_block, wlen)
        return _pallas_lag_absmax(lag.reshape(m * rcv_block, wlen),
                                  interpret=interpret,
                                  lag_tile_max=lag_tile_max,
                                  ).reshape(m, rcv_block)

    peaks = lax.map(one, blocks)                      # (n_blocks, m, rcv_block)
    return jnp.moveaxis(peaks, 0, 1).reshape(m, -1)[:, :nall]


def _spectra_tile_kernel(nwin: int, win_block: int, sr, si, rr, ri, cr, ci):
    """One (src-tile, rcv-tile, f-block, win-block) step of the window-mean
    complex product.

    Block shapes: sr/si (Ts, win_block, fb), rr/ri (Tr, win_block, fb),
    cr/ci (Ts, Tr, fb).  The innermost grid dimension streams the window
    axis: the output tile is initialized at the first window block and
    accumulated into across the rest (it stays resident in VMEM while the
    pipeline prefetches the next block's spectra tiles — the spectra loads
    double-buffer against this block's compute).  The per-slab w loop is
    static; each term is a VPU broadcast multiply-accumulate, all operands
    resident in VMEM.

    When win_block does not divide nwin the last window block reads past the
    record (Pallas pads the ragged block with unspecified values): every
    operand of the out-of-range windows is zeroed by the ``ok`` select below,
    so the garbage (possibly non-finite) fill never reaches the accumulator.
    The select compiles away entirely when win_block divides nwin.
    """
    w = pl.program_id(3)

    @pl.when(w == 0)
    def _init():
        cr[:] = jnp.zeros(cr.shape, cr.dtype)
        ci[:] = jnp.zeros(ci.shape, ci.dtype)

    ragged = (nwin % win_block) != 0
    acc_r = jnp.zeros(cr.shape, jnp.float32)
    acc_i = jnp.zeros(ci.shape, jnp.float32)
    for wl in range(win_block):
        # upcast per-window slices to f32 for the accumulate: a no-op on
        # the default f32 planes, the f32-accumulation half of the bf16
        # tier when _planar_padded emitted bfloat16 planes
        a, b = (sr[:, wl, :].astype(jnp.float32),
                si[:, wl, :].astype(jnp.float32))  # (Ts, fb)
        c, d = (rr[:, wl, :].astype(jnp.float32),
                ri[:, wl, :].astype(jnp.float32))  # (Tr, fb)
        if ragged:
            ok = (w * win_block + wl) < nwin
            a = jnp.where(ok, a, 0.0)
            b = jnp.where(ok, b, 0.0)
            c = jnp.where(ok, c, 0.0)
            d = jnp.where(ok, d, 0.0)
        # (a + ib)(c - id) = (ac + bd) + i(bc - ad), outer over (Ts, Tr)
        acc_r += a[:, None, :] * c[None, :, :] + b[:, None, :] * d[None, :, :]
        acc_i += b[:, None, :] * c[None, :, :] - a[:, None, :] * d[None, :, :]
    inv = jnp.float32(1.0 / nwin)
    cr[:] += acc_r * inv
    ci[:] += acc_i * inv


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _planar_padded(wf: jnp.ndarray,
                   precision: str = "f32") -> tuple[jnp.ndarray, jnp.ndarray]:
    """Complex (n, nwin, nf) spectra -> (real, imag) planes padded to the
    channel/freq tile grid.  The window axis is NEVER padded here — the
    kernel's ragged-tail mask handles non-divisible window counts.

    ``precision="bf16"`` emits bfloat16 planes (half the HBM/VMEM footprint
    of the receiver planes the ring pipeline rotates); the spectra-tile
    kernel upcasts each window slice to f32 before the accumulate —
    bf16 inputs, f32 accumulation.  Default emits float32 planes,
    bit-identical to the pre-tier behavior."""
    dt = jnp.bfloat16 if precision == "bf16" else jnp.float32
    r = _pad_to(_pad_to(wf.real.astype(dt), 0, _TILE_CH), 2, _TILE_F)
    i = _pad_to(_pad_to(wf.imag.astype(dt), 0, _TILE_CH), 2, _TILE_F)
    return r, i


@partial(jax.jit, static_argnames=("win_block", "interpret"))
def _pallas_cross_spectra(src_r, src_i, all_r, all_i, win_block: int,
                          interpret: bool = False):
    """Tile-padded planar (mp, nwin, nfp) x (ncp, nwin, nfp) spectra ->
    (mp, ncp, nfp) float32 (real, imag) window-mean cross-spectra.

    Inputs must already be channel/freq padded (``_planar_padded``); the
    window axis is streamed through the innermost grid dimension in
    ``win_block`` slabs with in-kernel ragged-tail masking.
    """
    mp, nwin, nfp = src_r.shape
    ncp = all_r.shape[0]
    grid = (mp // _TILE_CH, ncp // _TILE_CH, nfp // _TILE_F,
            pl.cdiv(nwin, win_block))
    src_spec = pl.BlockSpec((_TILE_CH, win_block, _TILE_F),
                            lambda i, j, k, w: (i, w, k),
                            memory_space=pltpu.VMEM)
    rcv_spec = pl.BlockSpec((_TILE_CH, win_block, _TILE_F),
                            lambda i, j, k, w: (j, w, k),
                            memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((_TILE_CH, _TILE_CH, _TILE_F),
                            lambda i, j, k, w: (i, j, k),
                            memory_space=pltpu.VMEM)
    out_shape = [jax.ShapeDtypeStruct((mp, ncp, nfp), jnp.float32)] * 2
    return pl.pallas_call(
        partial(_spectra_tile_kernel, nwin, win_block),
        grid=grid,
        in_specs=[src_spec, src_spec, rcv_spec, rcv_spec],
        out_specs=[out_spec, out_spec],
        out_shape=out_shape,
        interpret=interpret,
    )(src_r, src_i, all_r, all_i)


def _einsum_cross_spectra(src_wf, all_wf, win_block: int,
                          precision: str = "f32"):
    """Exact-precision fallback with the same streamed window math: full
    win_block slabs accumulate through an unpadded ``fori_loop`` and a
    record-length ragged tail contracts as one static slice — neither
    operand is copied or padded along the window axis.

    ``precision="bf16"`` rounds both spectra through bfloat16 and drops the
    contraction to DEFAULT precision (the MXU's bf16 passes on TPU) — the
    fallback-side twin of the kernel's bf16-planes tier."""
    if precision == "bf16":
        src_wf = _bf16_round_complex(src_wf)
        all_wf = _bf16_round_complex(all_wf)
        xla_prec = lax.Precision.DEFAULT
    else:
        # HIGHEST: TPUs otherwise contract this complex matmul on the MXU in
        # bfloat16, which visibly degrades the spectra (the Pallas kernel is
        # exact f32 VPU arithmetic; keep the fallback numerically equivalent)
        xla_prec = lax.Precision.HIGHEST
    nwin = src_wf.shape[1]
    ein = partial(jnp.einsum, "swf,rwf->srf", precision=xla_prec)
    if win_block >= nwin:
        return ein(src_wf, jnp.conj(all_wf)) / nwin
    n_full = nwin // win_block

    def body(i, acc):
        s = lax.dynamic_slice_in_dim(src_wf, i * win_block, win_block, 1)
        a = lax.dynamic_slice_in_dim(all_wf, i * win_block, win_block, 1)
        return acc + ein(s, jnp.conj(a))

    # accumulator dtype follows the inputs (complex128 under x64), not a
    # hardcoded complex64 — a mismatched fori_loop carry would throw
    acc0 = jnp.zeros((src_wf.shape[0], all_wf.shape[0], src_wf.shape[2]),
                     jnp.result_type(src_wf, all_wf))
    acc = lax.fori_loop(0, n_full, body, acc0)
    if nwin % win_block:
        acc = acc + ein(src_wf[:, n_full * win_block:, :],
                        jnp.conj(all_wf[:, n_full * win_block:, :]))
    return acc / nwin


def _make_cross_fn(wf_all, use_pallas: bool, interpret: bool, win_block: int,
                   precision: str = "f32"):
    """Build ``cross(src_rows) -> (m, nall, nf)`` window-mean cross-spectra
    against the fixed receiver set ``wf_all``.

    The receiver-side kernel preparation (planar split + channel/freq tile
    padding) runs HERE, once — not inside the per-chunk ``lax.map`` body —
    so the largest array in the program is touched once per call (and once
    per device under ``parallel.allpairs``), not once per source chunk."""
    nall, _, nf = wf_all.shape
    if not use_pallas:
        return lambda src_rows: _einsum_cross_spectra(src_rows, wf_all,
                                                      win_block,
                                                      precision=precision)
    all_r, all_i = _planar_padded(wf_all, precision)

    def cross(src_rows):
        m = src_rows.shape[0]
        src_r, src_i = _planar_padded(src_rows, precision)
        cr, ci = _pallas_cross_spectra(src_r, src_i, all_r, all_i,
                                       win_block=win_block,
                                       interpret=interpret)
        # slice the float32 planes BEFORE forming the complex array: the
        # padded complex intermediate was the largest per-chunk transient
        # at 10k channels
        return cr[:m, :nall, :nf] + 1j * ci[:m, :nall, :nf]

    return cross


def _window_spectra(data: jnp.ndarray, wlen: int,
                    overlap_ratio: float) -> jnp.ndarray:
    offset = int(wlen * (1.0 - overlap_ratio))
    wins = sliding_windows(data, wlen, offset)           # (nch, nwin, wlen)
    return jnp.fft.rfft(wins.astype(jnp.float32), axis=-1)


def _check_precision(precision: str) -> str:
    if precision not in ("f32", "bf16"):
        raise ValueError(
            f"precision must be 'f32' or 'bf16', got {precision!r}")
    return precision


def _decide_pallas(nch: int, use_pallas: bool | None) -> bool:
    if use_pallas is None:
        return (nch >= PALLAS_MIN_CH
                and jax.default_backend() not in ("cpu",))
    return use_pallas


def _chunked(wf: jnp.ndarray, src_chunk: int, finish):
    """Map ``finish(cross-spectra of chunk rows)`` over source-row chunks."""
    nch = wf.shape[0]
    if nch <= src_chunk:
        return finish(wf)[0:nch]
    pad = (-nch) % src_chunk
    wfp = jnp.pad(wf, ((0, pad), (0, 0), (0, 0)))
    out = jax.lax.map(finish, wfp.reshape(-1, src_chunk, *wf.shape[1:]))
    return out.reshape(-1, *out.shape[2:])[:nch]


def xcorr_all_pairs(data: jnp.ndarray, wlen: int, overlap_ratio: float = 0.5,
                    lag_keep: int | None = None, src_chunk: int = 128,
                    use_pallas: bool | None = None,
                    interpret: bool = False,
                    win_block: int | None = None,
                    precision: str = "f32") -> jnp.ndarray:
    """All-pairs lag-domain xcorr, zero lag centered — the (nch, nch, ...)
    generalization of ``xcorr_vshot_batch`` (parity-tested against it in
    tests/test_pallas_xcorr.py).

    ``lag_keep`` trims to the +-lag_keep samples around zero lag (standard
    ambient-noise practice; the full 10k x 10k x wlen cube would be ~800 GB).
    Source rows are processed ``src_chunk`` at a time; each chunk's spectra
    are finished (irfft, roll, trim) before the next chunk starts.

    ``win_block`` streams the window axis through the kernel grid for
    minutes-long records (auto-enabled past ``WIN_BLOCK_AUTO`` windows), the
    same record-length-invariant accumulation as ``xcorr_all_pairs_peak`` —
    the lag-domain path no longer loads whole-record spectra tiles into VMEM.
    """
    wf = _window_spectra(data, wlen, overlap_ratio)
    use_p = _decide_pallas(wf.shape[0], use_pallas)
    wb = _resolve_win_block(wf.shape[1], win_block)
    cross = _make_cross_fn(wf, use_p, interpret, wb,
                           precision=_check_precision(precision))
    mid = wlen // 2
    sl = slice(0, wlen) if lag_keep is None else slice(mid - lag_keep,
                                                       mid + lag_keep + 1)

    def finish(src_rows):
        c = jnp.fft.irfft(cross(src_rows), n=wlen, axis=-1)
        return jnp.roll(c, mid, axis=-1)[..., sl]

    return _chunked(wf, src_chunk, finish)


def xcorr_all_pairs_peak(data: jnp.ndarray, wlen: int,
                         overlap_ratio: float = 0.5, src_chunk: int = 64,
                         use_pallas: bool | None = None,
                         interpret: bool = False,
                         win_block: int | None = None,
                         lagmax_block: int | None = None,
                         lag_tile_max: int = _PEAK_TILE_L,
                         precision: str = "f32") -> jnp.ndarray:
    """Per-pair peak |xcorr| over all lags: (nch, nch) float32.

    The fully streamed form for channel counts where even a trimmed lag
    cube exceeds HBM (the 10k-channel config): per chunk, spectra tiles ->
    irfft -> lag-axis max reduction; nothing larger than
    (src_chunk, nch, wlen) ever materializes.

    ``win_block`` streams the window axis too, for minutes-long records
    (window-mean cross-spectra accumulate linearly, so the record length
    only adds accumulation steps — per-(pair, window) throughput is
    record-length-invariant; measured by bench.py's nt≈60k entry).
    Auto-enabled past ``WIN_BLOCK_AUTO`` windows to keep the kernel's VMEM
    tiles bounded.

    ``lagmax_block`` controls the fused peak finish (see
    :func:`peak_from_spectra`): None fuses on the kernel path, 0 forces the
    unfused XLA finish, a positive value sets the receiver-block size.
    """
    wf = _window_spectra(data, wlen, overlap_ratio)
    use_p = _decide_pallas(wf.shape[0], use_pallas)
    return peak_from_spectra(wf, wf, wlen, src_chunk, use_p, interpret,
                             win_block=win_block, lagmax_block=lagmax_block,
                             lag_tile_max=lag_tile_max, precision=precision)


def peak_from_spectra(wf_src, wf_all, wlen: int, src_chunk: int,
                      use_pallas: bool, interpret: bool = False,
                      win_block: int | None = None,
                      lagmax_block: int | None = None,
                      lag_tile_max: int = _PEAK_TILE_L,
                      precision: str = "f32"):
    """Peak |xcorr| of every ``wf_src`` row against every ``wf_all`` row:
    (nsrc, nall) float32.  Split out so a sharded caller
    (``parallel.allpairs``) can hand each device its own source-row block
    while the receiver side stays the full spectra set.

    With ``win_block`` (or automatically past ``WIN_BLOCK_AUTO`` windows)
    the window mean accumulates ``win_block`` windows at a time inside the
    kernel grid; a ragged tail is masked in-kernel, so ``wf_all`` — under
    ``parallel.allpairs``'s ring pipeline the per-device O(nch/D) receiver
    shard — is never padded or copied along the window axis.  Negative
    ``win_block`` raises ``ValueError``.

    ``lagmax_block`` (None = fuse on the kernel path, 0 = unfused XLA
    finish, >0 = that receiver-block size) routes the irfft + |.|-max
    finish through :func:`_fused_peak_finish`: blockwise irfft + a Pallas
    lag-streaming max whose accumulator stays VMEM-resident, so the
    (src_chunk, nall, wlen) lag cube of the unfused finish never exists in
    HBM.  The einsum fallback keeps the unfused finish by default (exact
    parity reference).  Negative values raise ``ValueError``.

    ``lag_tile_max`` caps the lag-axis tile auto-sizing of the fused
    finish (``RingConfig.lag_tile_max``); ``precision`` selects the
    f32/bf16 tier of the cross-spectra stage (``RingConfig.precision``,
    see ``_planar_padded`` / ``_einsum_cross_spectra``)."""
    wb = _resolve_win_block(wf_src.shape[1], win_block)
    lb = _resolve_lagmax_block(wf_all.shape[0], use_pallas, lagmax_block)
    cross = _make_cross_fn(wf_all, use_pallas, interpret, wb,
                           precision=_check_precision(precision))

    def finish(src_rows):
        c = cross(src_rows)
        if lb:
            return _fused_peak_finish(c, wlen, lb, interpret,
                                      lag_tile_max=lag_tile_max)
        lag = jnp.fft.irfft(c, n=wlen, axis=-1)
        return jnp.max(jnp.abs(lag), axis=-1)

    return _chunked(wf_src, src_chunk, finish)
