"""Pallas-tiled all-pairs windowed cross-correlation (BASELINE config 4).

The all-pairs generalization of the reference's XCORR_vshot loop
(modules/utils.py:289-314) is, in the frequency domain,

    C[s, r, f] = (1/nwin) * sum_w  S[s, w, f] * conj(S[r, w, f])

followed by an irfft over f.  ``ops.xcorr.xcorr_vshot_batch`` evaluates this
with one einsum that materializes the (nsrc, nrcv, nwin, nf) product — fine
for the ~40-channel imaging gathers, hopeless for the synthetic 10k-channel
ambient-noise config (that intermediate would be ~10 TB, and even the full
(nch, nch, nf) spectra cube is ~800 GB).

This module therefore streams at two levels:

1. *Source-chunk loop* (``lax.map``): only ``src_chunk`` source rows'
   spectra/lag products exist at a time.
2. *Pallas kernel* inside each chunk: the (src-tile x rcv-tile x f-block)
   grid loads two (tile, nwin, fblock) spectra tiles into VMEM, forms the
   complex product and accumulates the window mean in one pass — HBM
   traffic is one read of each spectra tile per (s, r) tile pair plus one
   output-tile write; no (s, r, w, f) intermediate ever exists.

Each chunk is finished in the lag domain (irfft + zero-lag roll + lag trim,
or a per-pair peak reduction) before the next chunk starts, so arbitrarily
large channel counts run in bounded memory.

Below ``PALLAS_MIN_CH`` channels (or on non-TPU backends) an XLA batched
contraction ``einsum("swf,rwf->srf")`` replaces the kernel — same math,
also 4-D-free, without explicit tiling control.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from das_diff_veh_tpu.ops.xcorr import sliding_windows

PALLAS_MIN_CH = 512     # below this the XLA einsum path wins (compile + pad overhead)
# Mosaic requires the last block dim divisible by 128 (lanes); VMEM is kept
# under the 16 MB limit by shrinking the channel tiles instead: out tiles are
# (32, 32, 128) f32 x2 outputs x2 pipeline buffers ~= 2 MB.
_TILE_CH = 32           # (src, rcv) tile edge
_TILE_F = 128           # frequency block (lane-aligned)


def _spectra_tile_kernel(nwin: int, sr, si, rr, ri, cr, ci):
    """One (src-tile, rcv-tile, f-block) step: window-mean complex product.

    Block shapes: sr/si (Ts, nwin, fb), rr/ri (Tr, nwin, fb),
    cr/ci (Ts, Tr, fb).  The w loop is static (nwin is small — ~7 for the
    reference's 50%-overlap 2 s windows in 8 s records); each term is a VPU
    broadcast multiply-accumulate, all operands resident in VMEM.
    """
    acc_r = jnp.zeros(cr.shape, jnp.float32)
    acc_i = jnp.zeros(ci.shape, jnp.float32)
    for w in range(nwin):
        a, b = sr[:, w, :], si[:, w, :]          # (Ts, fb)
        c, d = rr[:, w, :], ri[:, w, :]          # (Tr, fb)
        # (a + ib)(c - id) = (ac + bd) + i(bc - ad), outer over (Ts, Tr)
        acc_r += a[:, None, :] * c[None, :, :] + b[:, None, :] * d[None, :, :]
        acc_i += b[:, None, :] * c[None, :, :] - a[:, None, :] * d[None, :, :]
    inv = jnp.float32(1.0 / nwin)
    cr[:] = acc_r * inv
    ci[:] = acc_i * inv


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@partial(jax.jit, static_argnames=("interpret",))
def _pallas_cross_spectra(src_r, src_i, all_r, all_i,
                          interpret: bool = False) -> jnp.ndarray:
    """(m, nwin, nf) source-row spectra x (nch, nwin, nf) full spectra ->
    (m, nch, nf) complex window-mean cross-spectra via the tiled kernel.
    Pads m/nch to _TILE_CH and nf to _TILE_F; slices the padding back off."""
    m, nwin, nf = src_r.shape
    nch = all_r.shape[0]
    src_r = _pad_to(_pad_to(src_r, 0, _TILE_CH), 2, _TILE_F)
    src_i = _pad_to(_pad_to(src_i, 0, _TILE_CH), 2, _TILE_F)
    all_r = _pad_to(_pad_to(all_r, 0, _TILE_CH), 2, _TILE_F)
    all_i = _pad_to(_pad_to(all_i, 0, _TILE_CH), 2, _TILE_F)
    mp, ncp, nfp = src_r.shape[0], all_r.shape[0], src_r.shape[2]
    grid = (mp // _TILE_CH, ncp // _TILE_CH, nfp // _TILE_F)
    src_spec = pl.BlockSpec((_TILE_CH, nwin, _TILE_F),
                            lambda i, j, k: (i, 0, k),
                            memory_space=pltpu.VMEM)
    rcv_spec = pl.BlockSpec((_TILE_CH, nwin, _TILE_F),
                            lambda i, j, k: (j, 0, k),
                            memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((_TILE_CH, _TILE_CH, _TILE_F),
                            lambda i, j, k: (i, j, k),
                            memory_space=pltpu.VMEM)
    out_shape = [jax.ShapeDtypeStruct((mp, ncp, nfp), jnp.float32)] * 2
    cr, ci = pl.pallas_call(
        partial(_spectra_tile_kernel, nwin),
        grid=grid,
        in_specs=[src_spec, src_spec, rcv_spec, rcv_spec],
        out_specs=[out_spec, out_spec],
        out_shape=out_shape,
        interpret=interpret,
    )(src_r, src_i, all_r, all_i)
    return (cr + 1j * ci)[:m, :nch, :nf]


def _window_spectra(data: jnp.ndarray, wlen: int,
                    overlap_ratio: float) -> jnp.ndarray:
    offset = int(wlen * (1.0 - overlap_ratio))
    wins = sliding_windows(data, wlen, offset)           # (nch, nwin, wlen)
    return jnp.fft.rfft(wins.astype(jnp.float32), axis=-1)


def _decide_pallas(nch: int, use_pallas: bool | None) -> bool:
    if use_pallas is None:
        return (nch >= PALLAS_MIN_CH
                and jax.default_backend() not in ("cpu",))
    return use_pallas


def _cross_spectra(src_wf, all_wf, use_pallas: bool, interpret: bool):
    """(m, nwin, nf) x (nch, nwin, nf) -> (m, nch, nf) window-mean products."""
    if use_pallas:
        return _pallas_cross_spectra(
            src_wf.real.astype(jnp.float32), src_wf.imag.astype(jnp.float32),
            all_wf.real.astype(jnp.float32), all_wf.imag.astype(jnp.float32),
            interpret=interpret)
    # HIGHEST: TPUs otherwise contract this complex matmul on the MXU in
    # bfloat16, which visibly degrades the spectra (the Pallas kernel is
    # exact f32 VPU arithmetic; keep the fallback numerically equivalent)
    return jnp.einsum("swf,rwf->srf", src_wf, jnp.conj(all_wf),
                      precision=jax.lax.Precision.HIGHEST) / src_wf.shape[1]


def _chunked(wf: jnp.ndarray, src_chunk: int, finish):
    """Map ``finish(cross-spectra of chunk rows)`` over source-row chunks."""
    nch = wf.shape[0]
    if nch <= src_chunk:
        return finish(wf)[0:nch]
    pad = (-nch) % src_chunk
    wfp = jnp.pad(wf, ((0, pad), (0, 0), (0, 0)))
    out = jax.lax.map(finish, wfp.reshape(-1, src_chunk, *wf.shape[1:]))
    return out.reshape(-1, *out.shape[2:])[:nch]


def xcorr_all_pairs(data: jnp.ndarray, wlen: int, overlap_ratio: float = 0.5,
                    lag_keep: int | None = None, src_chunk: int = 128,
                    use_pallas: bool | None = None,
                    interpret: bool = False) -> jnp.ndarray:
    """All-pairs lag-domain xcorr, zero lag centered — the (nch, nch, ...)
    generalization of ``xcorr_vshot_batch`` (parity-tested against it in
    tests/test_pallas_xcorr.py).

    ``lag_keep`` trims to the +-lag_keep samples around zero lag (standard
    ambient-noise practice; the full 10k x 10k x wlen cube would be ~800 GB).
    Source rows are processed ``src_chunk`` at a time; each chunk's spectra
    are finished (irfft, roll, trim) before the next chunk starts.
    """
    wf = _window_spectra(data, wlen, overlap_ratio)
    use_p = _decide_pallas(wf.shape[0], use_pallas)
    mid = wlen // 2
    sl = slice(0, wlen) if lag_keep is None else slice(mid - lag_keep,
                                                       mid + lag_keep + 1)

    def finish(src_rows):
        spec = _cross_spectra(src_rows, wf, use_p, interpret)
        c = jnp.fft.irfft(spec, n=wlen, axis=-1)
        return jnp.roll(c, mid, axis=-1)[..., sl]

    return _chunked(wf, src_chunk, finish)


# Above this window count the kernel's (tile, nwin, fblock) VMEM operands
# (4 inputs x 2 pipeline buffers) approach the 16 MB budget; block the
# window-mean accumulation instead.  32 windows -> ~2 MB/operand.
WIN_BLOCK_AUTO = 48


def xcorr_all_pairs_peak(data: jnp.ndarray, wlen: int,
                         overlap_ratio: float = 0.5, src_chunk: int = 64,
                         use_pallas: bool | None = None,
                         interpret: bool = False,
                         win_block: int | None = None) -> jnp.ndarray:
    """Per-pair peak |xcorr| over all lags: (nch, nch) float32.

    The fully streamed form for channel counts where even a trimmed lag
    cube exceeds HBM (the 10k-channel config): per chunk, spectra tiles ->
    irfft -> lag-axis max reduction; nothing larger than
    (src_chunk, nch, wlen) ever materializes.

    ``win_block`` streams the window axis too, for minutes-long records
    (window-mean cross-spectra accumulate linearly, so the record length
    only adds accumulation steps — per-(pair, window) throughput is
    record-length-invariant).  Auto-enabled past ``WIN_BLOCK_AUTO`` windows
    to keep the kernel's VMEM tiles bounded.
    """
    wf = _window_spectra(data, wlen, overlap_ratio)
    use_p = _decide_pallas(wf.shape[0], use_pallas)
    return peak_from_spectra(wf, wf, wlen, src_chunk, use_p, interpret,
                             win_block=win_block)


def peak_from_spectra(wf_src, wf_all, wlen: int, src_chunk: int,
                      use_pallas: bool, interpret: bool = False,
                      win_block: int | None = None):
    """Peak |xcorr| of every ``wf_src`` row against every ``wf_all`` row:
    (nsrc, nall) float32.  Split out so a sharded caller
    (``parallel.allpairs``) can hand each device its own source-row block
    while the receiver side stays the full spectra set.

    With ``win_block`` (or automatically past ``WIN_BLOCK_AUTO`` windows)
    the window mean is accumulated ``win_block`` windows at a time:
    mean_w = (wb/nwin) * sum_blocks mean_block, with zero-padded windows
    contributing nothing — so arbitrarily long records keep both the VMEM
    tiles and the per-step working set bounded."""
    nwin = wf_src.shape[1]
    if win_block is None and nwin > WIN_BLOCK_AUTO:
        win_block = 32

    if not win_block or win_block >= nwin:
        def finish(src_rows):
            spec = _cross_spectra(src_rows, wf_all, use_pallas, interpret)
            c = jnp.fft.irfft(spec, n=wlen, axis=-1)
            return jnp.max(jnp.abs(c), axis=-1)

        return _chunked(wf_src, src_chunk, finish)

    from jax import lax

    pad = (-nwin) % win_block
    wpad = ((0, 0), (0, pad), (0, 0))
    wf_src_p = jnp.pad(wf_src, wpad)
    wf_all_p = jnp.pad(wf_all, wpad)
    n_blocks = (nwin + pad) // win_block
    nall, nf = wf_all.shape[0], wf_all.shape[2]

    def finish(src_rows):
        def body(i, acc):
            s = lax.dynamic_slice_in_dim(src_rows, i * win_block, win_block, 1)
            a = lax.dynamic_slice_in_dim(wf_all_p, i * win_block, win_block, 1)
            return acc + _cross_spectra(s, a, use_pallas, interpret)

        acc0 = jnp.zeros((src_rows.shape[0], nall, nf), jnp.complex64)
        spec = lax.fori_loop(0, n_blocks, body, acc0) * (win_block / nwin)
        c = jnp.fft.irfft(spec, n=wlen, axis=-1)
        return jnp.max(jnp.abs(c), axis=-1)

    return _chunked(wf_src_p, src_chunk, finish)
