"""Trace quality control: bad-channel detection and imputation.

The reference finds ONE noisy/empty channel per call (argmax) and imputes it
by neighbor *summing* (no /2; modules/utils.py:327) — a latent bug when
several channels are bad.  The TPU-native version is fully vectorized: boolean masks
over all channels, one-shot neighbor imputation, no data-dependent shapes.
A strict single-index variant is kept for oracle-parity tests.
"""

from __future__ import annotations

import jax.numpy as jnp


def noisy_trace_mask(data: jnp.ndarray, threshold: float = 5.0) -> jnp.ndarray:
    """Channels whose max amplitude exceeds ``threshold``
    (reference find_noise_idx(empty_tr=False), modules/utils.py:316-318)."""
    return jnp.max(data, axis=-1) > threshold


def empty_trace_mask(data: jnp.ndarray, threshold: float = 5.0) -> jnp.ndarray:
    """Channels whose L2 norm is below ``threshold``
    (reference find_noise_idx(empty_tr=True), modules/utils.py:319-320)."""
    return jnp.linalg.norm(data, axis=-1) < threshold


def impute_traces(data: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Replace masked channels by the sum of their immediate neighbors
    (edge channels copy the single neighbor) — the reference's per-channel
    rule (modules/utils.py:323-329), applied to every masked channel at once.
    """
    up = jnp.roll(data, -1, axis=0)
    down = jnp.roll(data, 1, axis=0)
    nch = data.shape[0]
    repl = up + down
    repl = repl.at[0].set(up[0])
    repl = repl.at[nch - 1].set(down[nch - 1])
    return jnp.where(mask[:, None], repl, data)


def impute_first_noisy(data: jnp.ndarray, threshold: float = 5.0,
                       empty: bool = False) -> jnp.ndarray:
    """Strict reference semantics: impute only argmax of the predicate
    (modules/utils.py:316-329).  Used for oracle equivalence tests."""
    if empty:
        idx = jnp.argmax(jnp.linalg.norm(data, axis=-1) < threshold)
    else:
        idx = jnp.argmax(jnp.max(data, axis=-1) > threshold)
    nch = data.shape[0]
    prev = data[jnp.clip(idx - 1, 0, nch - 1)]
    nxt = data[jnp.clip(idx + 1, 0, nch - 1)]
    repl = jnp.where(idx == 0, nxt, jnp.where(idx == nch - 1, prev, prev + nxt))
    return data.at[idx].set(repl)


def kill_loud_channels(data: jnp.ndarray, noise_level: float = 10.0) -> jnp.ndarray:
    """Zero out channels whose median |amplitude| exceeds ``noise_level``
    (reference: apis/timeLapseImaging.py:76-77)."""
    loud = jnp.median(jnp.abs(data), axis=-1) > noise_level
    return jnp.where(loud[:, None], 0.0, data)
