"""Zero-phase filtering and tapering kernels (jnp, jit-friendly).

The reference filters with order-10 Butterworth ``sosfiltfilt`` along time
(modules/utils.py:179-195) and space (modules/utils.py:584-603).  Sequential
IIR recursions map poorly to the MXU, so the TPU-native equivalent applies the
*squared magnitude response* |H(f)|² of the same SOS cascade in the frequency
domain — mathematically identical to filtfilt's zero-phase response away from
edge transients (documented delta; tolerance-tested in
tests/test_filters.py).  Filter design happens once on the host (static
config); the jitted path is rfft · gain · irfft, which XLA fuses.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=64)
def _butter_sos(order: int, wlo: float, whi: float) -> np.ndarray:
    """Host-side Butterworth band-pass design (normalized freqs in (0, 1))."""
    from scipy import signal
    return signal.butter(order, [wlo, whi], btype="band", output="sos")


def _sos_gain(sos: np.ndarray, freqs: np.ndarray, fs: float) -> np.ndarray:
    """|H(f)|² of an SOS cascade evaluated at ``freqs`` [Hz].

    Pure host-side numpy: the gain is a static constant of the filter design,
    and complex128 scalar math must never reach the TPU (unsupported there —
    an eager complex op also wedges the axon tunnel's transfer stream)."""
    z = np.exp(-2j * np.pi * np.asarray(freqs) / fs)
    h = np.ones_like(z)
    for b0, b1, b2, a0, a1, a2 in sos:
        h = h * (b0 + b1 * z + b2 * z * z) / (a0 + a1 * z + a2 * z * z)
    return np.abs(h) ** 2


def _fft_zero_phase(data: jnp.ndarray, fs: float, flo: float, fhi: float,
                    order: int, axis: int) -> jnp.ndarray:
    data = jnp.moveaxis(data, axis, -1)
    n = data.shape[-1]
    # odd-extension padding (the same trick filtfilt uses) suppresses the
    # circular-wraparound transient of frequency-domain filtering
    pad = min(n - 1, max(int(3.0 * fs / max(flo, 1e-6)), 64))
    head = 2.0 * data[..., :1] - data[..., 1:pad + 1][..., ::-1]
    tail = 2.0 * data[..., -1:] - data[..., -pad - 1:-1][..., ::-1]
    ext = jnp.concatenate([head, data, tail], axis=-1)
    nfft = ext.shape[-1]
    sos = _butter_sos(order, 2.0 * flo / fs, 2.0 * fhi / fs)
    freqs = np.fft.rfftfreq(nfft, d=1.0 / fs)
    gain = jnp.asarray(_sos_gain(sos, freqs, fs), dtype=data.dtype)
    spec = jnp.fft.rfft(ext, axis=-1) * gain
    out = jnp.fft.irfft(spec, n=nfft, axis=-1)[..., pad:pad + n].astype(data.dtype)
    return jnp.moveaxis(out, -1, axis)


def bandpass_time(data: jnp.ndarray, dt: float, flo: float, fhi: float,
                  order: int = 10) -> jnp.ndarray:
    """Zero-phase temporal band-pass (reference: modules/utils.py:179-195)."""
    return _fft_zero_phase(data, 1.0 / dt, flo, fhi, order, axis=-1)


def bandpass_space(data: jnp.ndarray, dx: float, flo: float, fhi: float,
                   order: int = 10) -> jnp.ndarray:
    """Zero-phase spatial (wavenumber) band-pass along the channel axis
    (reference: modules/utils.py:584-603).  flo == fhi == -1 is a no-op,
    mirroring the reference's sentinel."""
    if flo == -1 and fhi == -1:
        return data
    return _fft_zero_phase(data, 1.0 / dx, flo, fhi, order, axis=0)


def tukey_window(n: int, alpha: float, dtype=jnp.float64) -> jnp.ndarray:
    """Tukey (tapered-cosine) window, analytic closed form.

    Matches ``scipy.signal.windows.tukey(n, alpha)`` (sym=True).
    """
    if n == 1:
        return jnp.ones((1,), dtype=dtype)
    k = jnp.arange(n, dtype=dtype) / (n - 1)          # position in [0, 1]
    if alpha <= 0:
        return jnp.ones((n,), dtype=dtype)
    edge = alpha / 2.0
    left = 0.5 * (1 + jnp.cos(jnp.pi * (2.0 * k / alpha - 1.0)))
    right = 0.5 * (1 + jnp.cos(jnp.pi * (2.0 * (1.0 - k) / alpha - 1.0)))
    w = jnp.where(k < edge, left, jnp.where(k > 1.0 - edge, right, 1.0))
    return w.astype(dtype)


def taper_time(data: jnp.ndarray, alpha: float = 0.05) -> jnp.ndarray:
    """Tukey taper along time (reference: modules/utils.py:126-129)."""
    return data * tukey_window(data.shape[-1], alpha, dtype=data.dtype)


def detrend_linear(data: jnp.ndarray) -> jnp.ndarray:
    """Per-trace linear detrend via closed-form least squares
    (matches ``scipy.signal.detrend(type='linear')``)."""
    n = data.shape[-1]
    t = jnp.arange(n, dtype=data.dtype)
    t_mean = (n - 1) / 2.0
    tc = t - t_mean
    denom = jnp.sum(tc * tc)
    slope = (data @ tc) / denom                        # (..., )
    mean = jnp.mean(data, axis=-1)
    return data - mean[..., None] - slope[..., None] * tc


def remove_common_mode(data: jnp.ndarray) -> jnp.ndarray:
    """Subtract the per-time-sample median across channels
    (reference: modules/utils.py:121-124)."""
    return data - jnp.median(data, axis=0, keepdims=True)


def das_preprocess(data: jnp.ndarray) -> jnp.ndarray:
    """detrend + common-mode removal (reference: modules/utils.py:121-124)."""
    return remove_common_mode(detrend_linear(data))


def l2_normalize_traces(data: jnp.ndarray, eps: float = 0.0) -> jnp.ndarray:
    """Per-trace L2 normalization (reference: apis/timeLapseImaging.py:71)."""
    return data / (jnp.linalg.norm(data, axis=-1, keepdims=True) + eps)
