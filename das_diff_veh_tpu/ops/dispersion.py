"""f-k and frequency-velocity (dispersion) transforms.

Two paths:

- ``fv_map_fk``: parity with the reference's ``map_fv``
  (modules/utils.py:457-475): 2-D FFT magnitude (``fk``, modules/
  utils.py:236-248), bilinear sampling along k = f/v, Savitzky-Golay (25,4)
  smoothing over frequency.  The reference samples with the long-removed
  ``scipy.interpolate.interp2d`` (linear spline); our bilinear gather
  *clamps* out-of-domain queries to the boundary value, which is what
  FITPACK's degree-1 spline does for the k = f/v samples beyond spatial
  Nyquist (verified empirically against RectBivariateSpline(kx=ky=1)).

- ``fv_map_phase_shift``: the frequency-domain slant stack
  P(v, f) = |Σ_x U(x, f) e^{i 2π f x / v}| (Park et al. phase-shift method)
  — the physics the reference's dead ``map_fv_FD_slant_stack``
  (modules/utils.py:429-454) loops over, here as one batched complex
  contraction with optional spectral whitening.  Preferred on TPU: no
  oversized zero-padded FFT, no gather, all MXU-friendly.

Both return (nvel, nfreq) maps; stacking over windows is a mean over a
leading batch axis (replacing the reference's __add__/__truediv__ algebra,
modules/utils.py:412-426).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from das_diff_veh_tpu.ops.savgol import savgol_filter


def _next_pow2_plus(n: int) -> int:
    """Reference's padded FFT size: 2 ** (1 + ceil(log2 n)) (modules/utils.py:239-240)."""
    return 2 ** (1 + math.ceil(math.log2(n)))


def fk_transform(data: jnp.ndarray, dx: float, dt: float):
    """2-D f-k magnitude spectrum with fftshifted axes
    (reference ``fk``, modules/utils.py:236-248).

    Returns (fk_mag (nk, nf), f_axis (nf,), k_axis (nk,)).
    """
    nch, nt = data.shape[-2], data.shape[-1]
    nf = _next_pow2_plus(nt)
    nk = _next_pow2_plus(nch)
    spec = jnp.fft.fftshift(jnp.fft.fft2(data, s=(nk, nf)), axes=(-2, -1))
    f_axis = jnp.arange(-nf / 2, nf / 2) / nf / dt
    k_axis = jnp.arange(-nk / 2, nk / 2) / nk / dx
    return jnp.abs(spec), f_axis, k_axis


def _bilinear_clamped(grid: jnp.ndarray, u: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Tensor-product linear interpolation on a regular grid; out-of-domain
    queries are clamped to the boundary — FITPACK's bisplev behavior, i.e.
    what both the removed ``interp2d`` and ``RectBivariateSpline(kx=ky=1)``
    do for the k = f/v samples beyond spatial Nyquist."""
    n0, n1 = grid.shape
    u = jnp.clip(u, 0.0, n0 - 1.0)
    v = jnp.clip(v, 0.0, n1 - 1.0)
    i0 = jnp.clip(jnp.floor(u).astype(jnp.int32), 0, n0 - 2)
    i1 = jnp.clip(jnp.floor(v).astype(jnp.int32), 0, n1 - 2)
    w0 = u - i0
    w1 = v - i1
    g00 = grid[i0, i1]
    g01 = grid[i0, i1 + 1]
    g10 = grid[i0 + 1, i1]
    g11 = grid[i0 + 1, i1 + 1]
    return ((1 - w0) * (1 - w1) * g00 + (1 - w0) * w1 * g01 +
            w0 * (1 - w1) * g10 + w0 * w1 * g11)


def _hat(centers: jnp.ndarray, u: jnp.ndarray) -> jnp.ndarray:
    """Linear-interpolation hat weights max(0, 1 - |center - u|): for a u
    clamped inside the grid these reproduce clamped bilinear weights
    exactly (interior: (1-frac, frac) on the two neighbors; edge: weight 1
    on the edge node)."""
    return jnp.maximum(0.0, 1.0 - jnp.abs(centers - u))


def _bf16_round(x: jnp.ndarray) -> jnp.ndarray:
    """Round a real array through bfloat16 (bf16-valued float32): the input
    side of the ``"bf16"`` precision tier.  Paired with DEFAULT-precision
    contractions it yields bf16 operands + f32 accumulation on the TPU MXU;
    off-TPU the contraction is exact on the bf16-rounded inputs, so the
    committed error bounds measure the same input-rounding semantics."""
    return x.astype(jnp.bfloat16).astype(jnp.float32)


def _contraction_precision(precision: str):
    if precision not in ("f32", "bf16"):
        raise ValueError(
            f"precision must be 'f32' or 'bf16', got {precision!r}")
    return (jax.lax.Precision.DEFAULT if precision == "bf16"
            else jax.lax.Precision.HIGHEST)


def fv_map_fk(data: jnp.ndarray, dx: float, dt: float, freqs: jnp.ndarray,
              vels: jnp.ndarray, norm: bool = False,
              sg_window: int = 25, sg_order: int = 4,
              precision: str = "f32") -> jnp.ndarray:
    """Reference-parity dispersion map (``map_fv``, modules/utils.py:457-475).

    Returns (nvel, nfreq).  ``norm`` applies the per-trace L1 normalization
    the reference applies before the transform (modules/utils.py:464).

    The bilinear sampling along k = f/v is evaluated as two hat-weight
    contractions (einsum) instead of four gathers: the query frequencies
    are constant per output column, so f-interpolation is one
    (nk, nf_pad) @ (nf_pad, nfreq) matmul, and the per-(v, f) k-positions
    contract against on-the-fly hat weights.  Identical math to clamped
    bilinear (tested), but it runs on the MXU — the gather formulation was
    ~10 ms of the benchmark pipeline on the v5e, the contraction is ~none.

    ``precision="bf16"`` (``DispersionConfig.precision``) rounds the
    f-k magnitude and hat weights through bfloat16 and contracts at
    DEFAULT precision — bf16 MXU passes with f32 accumulation; the
    default ``"f32"`` keeps the HIGHEST-precision path bit-identical to
    the pre-tier behavior (tests/test_precision.py pins the bf16 budget).
    """
    xla_prec = _contraction_precision(precision)
    if norm:
        data = data / jnp.linalg.norm(data, axis=-1, keepdims=True, ord=1)
    fk_mag, f_axis, k_axis = fk_transform(data, dx, dt)
    # uniform axes -> index arithmetic instead of searchsorted
    f0, df = f_axis[0], f_axis[1] - f_axis[0]
    k0, dk = k_axis[0], k_axis[1] - k_axis[0]
    fr = jnp.asarray(freqs)
    vl = jnp.asarray(vels)
    nk, nf = fk_mag.shape
    if precision == "bf16":
        fk_mag = _bf16_round(fk_mag.astype(jnp.float32))
    # f-direction: one clamped position per output column
    uf = jnp.clip((fr - f0) / df, 0.0, nf - 1.0)          # (nfreq,)
    Wf = _hat(jnp.arange(nf)[:, None], uf[None, :])       # (nf_pad, nfreq)
    if precision == "bf16":
        Wf = _bf16_round(Wf.astype(jnp.float32))
    colmix = jnp.matmul(fk_mag, Wf, precision=xla_prec)
    # k-direction: per-(v, f) clamped position k = f/v
    uk = jnp.clip((fr[None, :] / vl[:, None] - k0) / dk, 0.0, nk - 1.0)
    Wk = _hat(jnp.arange(nk)[None, None, :], uk[..., None])  # (nvel, nfreq, nk)
    if precision == "bf16":
        Wk = _bf16_round(Wk.astype(jnp.float32))
    vals = jnp.einsum("vfk,kf->vf", Wk, colmix,
                      precision=xla_prec)                    # (nvel, nfreq)
    smoothed = savgol_filter(vals, sg_window, sg_order, axis=-1)  # over frequency
    return smoothed


def fv_map_phase_shift(data: jnp.ndarray, dx: float, dt: float, freqs: jnp.ndarray,
                       vels: jnp.ndarray, whiten: bool = True,
                       x0: float = 0.0, direction: float = 1.0,
                       vel_chunk: int = 128,
                       precision: str = "f32") -> jnp.ndarray:
    """Phase-shift (frequency-domain slant stack) dispersion map.

    P(v, f) = | Σ_x U(x, f) e^{i·direction·2π f (x - x0) / v} |, with optional
    spectral whitening U → U/|U| (standard MASW practice).  ``direction=+1``
    stacks waves propagating toward *increasing* x (delay grows with x);
    ``-1`` the opposite — match it to the gather's propagation sense (the
    reference's one-sided gathers run offsets -150..0 m with the virtual
    source at 0, i.e. direction=-1 in slice coordinates).  Velocity axis is
    processed in chunks to bound the steering-tensor footprint.
    Returns (nvel, nfreq).

    ``precision="bf16"`` rounds the sampled spectrum and steering tensor's
    real/imag planes through bfloat16 (the contraction precision is left at
    the platform default either way — this path was never forced to
    HIGHEST, and forcing it for f32 would silently change the compiled
    program); ``"f32"`` (default) is bit-identical to the pre-tier
    behavior.
    """
    _contraction_precision(precision)      # validate the tier name

    def _round_c(z):
        if precision != "bf16":
            return z
        z = z.astype(jnp.complex64)
        return (_bf16_round(z.real) + 1j * _bf16_round(z.imag)
                ).astype(jnp.complex64)

    nch, nt = data.shape[-2], data.shape[-1]
    spec = jnp.fft.rfft(data, axis=-1)                  # (nch, nfr)
    fft_freqs = jnp.fft.rfftfreq(nt, d=dt)
    if whiten:
        spec = spec / (jnp.abs(spec) + 1e-20)
    # sample the data spectrum at the scan frequencies (nearest bin — the scan
    # step 0.1 Hz is finer than typical bin spacing, matching reference's
    # nearest-bin pick in map_fv_FD_slant_stack modules/utils.py:451)
    fbin = jnp.clip(jnp.round(jnp.asarray(freqs) * nt * dt).astype(jnp.int32),
                    0, fft_freqs.shape[0] - 1)
    u = _round_c(spec[:, fbin])                         # (nch, nfreq)
    x = (jnp.arange(nch) * dx - x0)
    fr = jnp.asarray(freqs)

    def chunk(vc):
        # steering: (nvc, nfreq, nch)
        phase = 2.0 * jnp.pi * fr[None, :, None] * x[None, None, :] / vc[:, None, None]
        steer = _round_c(jnp.exp(1j * direction * phase))
        return jnp.abs(jnp.einsum("xf,vfx->vf", u, steer))

    vl = jnp.asarray(vels)
    nv = vl.shape[0]
    pad = (-nv) % vel_chunk
    vl_pad = jnp.concatenate([vl, jnp.full((pad,), vl[-1])]) if pad else vl
    out = jax.lax.map(chunk, vl_pad.reshape(-1, vel_chunk))
    return out.reshape(-1, fr.shape[0])[:nv]


def stack_fv_maps(maps: jnp.ndarray) -> jnp.ndarray:
    """Average a (nwin, nvel, nfreq) batch — replaces the reference's
    Dispersion __add__/__truediv__ stacking (modules/utils.py:412-426)."""
    return jnp.mean(maps, axis=0)
