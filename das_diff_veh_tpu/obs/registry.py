"""One metrics registry for the whole process: counters, gauges, histograms.

Before this package, observability was three disconnected fragments — the
serve engine's private counter dict (``serve/metrics.py``), the runtime's
Chrome-trace spans (``runtime/tracing.py``), and ad-hoc
``device.memory_stats()`` calls inside ``bench.py``.  The registry is the
shared spine: every subsystem registers labeled metric families here, and
the same registry renders as Prometheus text exposition on the serve HTTP
front (``GET /metrics``), as a JSON snapshot (``/v1/metrics`` keeps its
legacy shape via ``serve.metrics.ServeMetrics``), and as a periodic JSONL
sink for batch runs (``obs.sink``).

Three family types, Prometheus semantics:

- :class:`Counter` — monotonic float, ``inc(by)``;
- :class:`Gauge` — settable value or a zero-arg callable evaluated at
  collection time (``set_fn`` — how queue depths and device memory stats
  stay live without a writer thread);
- :class:`Histogram` — a bounded ring of recent observations rendered as a
  Prometheus *summary* (quantile samples from the ring + monotonic
  ``_sum``/``_count``), the same reservoir the serve layer always used for
  p50/p95/p99 so recent traffic dominates without unbounded memory.

Families are labeled: ``registry.counter("das_x_total", labels=("stage",))``
returns the family, ``family.labels(stage="load")`` the child.  An
unlabeled family is its own single child.  Re-registering an existing name
returns the same family (subsystems can re-wire across engine/executor
lifetimes inside one process), but a type or label-set mismatch raises.

Everything is thread-safe; write-side operations are a dict lookup plus a
float add under a lock — cheap enough for per-chunk and per-request paths
(bench.py's ``obs_overhead`` entry holds the end-to-end cost under 2%).
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: quantiles rendered for every histogram, as (label value, q)
QUANTILES = (("0.5", 0.50), ("0.95", 0.95), ("0.99", 0.99))


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile of an already-sorted sequence (the serve
    layer's historical definition, now shared by every histogram)."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return float(sorted_vals[idx])


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _fmt_labels(labels: Tuple[str, ...], values: Tuple[str, ...],
                extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = [f'{k}="{_escape_label(str(v))}"'
             for k, v in list(zip(labels, values)) + list(extra)]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Child:
    """State shared by all child kinds: one (family, label-values) cell."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock


class Counter(_Child):
    def __init__(self, lock):
        super().__init__(lock)
        self._value = 0.0

    def inc(self, by: float = 1.0) -> None:
        if by < 0:
            raise ValueError(f"counter increment must be >= 0, got {by}")
        with self._lock:
            self._value += by

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Child):
    def __init__(self, lock):
        super().__init__(lock)
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        """Collect-time callback (live queue depths, device memory stats).
        A callback that raises or returns None reads as the last set value —
        a dead provider must not kill the scrape."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            v = fn()
        except Exception:
            v = None
        with self._lock:
            if v is not None:
                self._value = float(v)
            return self._value


class Histogram(_Child):
    """Bounded ring of recent observations + monotonic sum/count."""

    def __init__(self, lock, window: int):
        super().__init__(lock)
        self._ring = deque(maxlen=window)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self._ring.append(float(value))
            self._sum += float(value)
            self._count += 1

    def values(self) -> List[float]:
        """The ring contents, sorted (feed to :func:`percentile`)."""
        with self._lock:
            return sorted(self._ring)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentiles(self, qs=(0.50, 0.95, 0.99)) -> Dict[str, float]:
        vals = self.values()
        out = {f"p{int(q * 100)}": percentile(vals, q) for q in qs}
        out["n"] = len(vals)
        out["max"] = vals[-1] if vals else 0.0
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One named metric family; children keyed by label-value tuples."""

    def __init__(self, name: str, kind: str, help: str,
                 labels: Tuple[str, ...], window: int = 1024):
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = labels
        self._window = window
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        if not labels:                      # unlabeled family is its own child
            self._default = self._make()
            self._children[()] = self._default

    def _make(self) -> _Child:
        if self.kind == "histogram":
            return Histogram(self._lock, self._window)
        return _KINDS[self.kind](self._lock)

    def labels(self, **kv):
        if set(kv) != set(self.label_names):
            raise ValueError(f"{self.name}: expected labels "
                             f"{self.label_names}, got {tuple(kv)}")
        key = tuple(str(kv[k]) for k in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make()
                self._children[key] = child
        return child

    def children(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())

    # unlabeled families proxy the child API directly
    def inc(self, by: float = 1.0) -> None:
        self._default.inc(by)

    def set(self, value: float) -> None:
        self._default.set(value)

    def set_fn(self, fn) -> None:
        self._default.set_fn(fn)

    def observe(self, value: float) -> None:
        self._default.observe(value)

    def values(self) -> List[float]:
        return self._default.values()

    def percentiles(self, qs=(0.50, 0.95, 0.99)) -> Dict[str, float]:
        return self._default.percentiles(qs)

    @property
    def value(self) -> float:
        return self._default.value

    @property
    def count(self) -> int:
        return self._default.count

    @property
    def sum(self) -> float:
        return self._default.sum


class MetricsRegistry:
    """Thread-safe name -> :class:`Family` map with two renderers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}

    def _register(self, name: str, kind: str, help: str,
                  labels: Iterable[str], window: int = 1024) -> Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        labels = tuple(labels)
        for lbl in labels:
            if not _LABEL_RE.match(lbl):
                raise ValueError(f"invalid label name {lbl!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != labels:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names}, not {kind}{labels}")
                return fam
            fam = Family(name, kind, help, labels, window)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Family:
        return self._register(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Family:
        return self._register(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "", labels: Iterable[str] = (),
                  window: int = 1024) -> Family:
        return self._register(name, "histogram", help, labels, window=window)

    def get(self, name: str) -> Optional[Family]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # -- renderers -----------------------------------------------------------
    def prometheus_text(self) -> str:
        """Prometheus text exposition (format version 0.0.4).  Histograms
        render as summaries: quantile samples from the bounded ring plus
        monotonic ``_sum``/``_count``."""
        lines: List[str] = []
        for fam in self.families():
            ptype = "summary" if fam.kind == "histogram" else fam.kind
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {ptype}")
            for key, child in fam.children():
                if fam.kind == "histogram":
                    vals = child.values()
                    for qlabel, q in QUANTILES:
                        lbl = _fmt_labels(fam.label_names, key,
                                          (("quantile", qlabel),))
                        lines.append(
                            f"{fam.name}{lbl} {percentile(vals, q):g}")
                    base = _fmt_labels(fam.label_names, key)
                    lines.append(f"{fam.name}_sum{base} {child.sum:g}")
                    lines.append(f"{fam.name}_count{base} {child.count}")
                else:
                    lbl = _fmt_labels(fam.label_names, key)
                    lines.append(f"{fam.name}{lbl} {child.value:g}")
        return "\n".join(lines) + "\n"

    def to_json(self) -> dict:
        """One JSON-ready dict: ``{name: {kind, [help], values}}`` where
        ``values`` maps rendered label strings to the child's value (or
        percentile dict for histograms)."""
        out: Dict[str, dict] = {}
        for fam in self.families():
            vals = {}
            for key, child in fam.children():
                lbl = _fmt_labels(fam.label_names, key) or "()"
                if fam.kind == "histogram":
                    p = child.percentiles()
                    p["sum"] = child.sum
                    p["count"] = child.count
                    vals[lbl] = p
                else:
                    vals[lbl] = child.value
            out[fam.name] = {"kind": fam.kind, "values": vals}
            if fam.help:
                out[fam.name]["help"] = fam.help
        return out

    def snapshot_line(self) -> dict:
        """One JSONL sink line: wall-clock timestamp + the full JSON dump."""
        return {"ts": time.time(), "metrics": self.to_json()}


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-global registry.  Batch runs, the parallel engines, and
    the serve CLI all register here so one scrape / one JSONL sink carries
    every subsystem; tests and embedded engines build their own
    :class:`MetricsRegistry` for isolation."""
    return _DEFAULT
