"""Periodic JSONL metrics sink: the batch-run counterpart of ``/metrics``.

An online engine is scraped; a batch run has no listener to scrape it, so
the sink inverts the direction — a daemon thread appends one JSON line
(``{"ts": ..., "metrics": registry.to_json()}``) every ``interval_s``
seconds, plus one final line at :meth:`close` so even a sub-interval run
leaves a complete last snapshot.  Line-delimited JSON for the same reason
as the Chrome-trace writer: a killed run keeps every completed line.

``scripts/obs_report.py`` renders the last line of this file next to the
trace spans and any flight-recorder dump.
"""

from __future__ import annotations

import json
import os
import threading
from typing import List

from das_diff_veh_tpu.obs.registry import MetricsRegistry


class MetricsSink:
    """Append registry snapshots to ``path`` every ``interval_s`` seconds."""

    def __init__(self, registry: MetricsRegistry, path: str,
                 interval_s: float = 10.0):
        self.registry = registry
        self.path = path
        self.interval_s = max(float(interval_s), 0.05)
        # append, not truncate: run_date_range builds one sink per date
        # against the same path, and a resumed run must keep the earlier
        # run's snapshots (same contract as the flight recorder's makedirs)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="obs-metrics-sink", daemon=True)
        self._thread.start()

    def _write_line(self) -> None:
        line = json.dumps(self.registry.snapshot_line())
        with self._lock:
            if not self._f.closed:
                self._f.write(line + "\n")
                self._f.flush()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._write_line()

    def flush(self) -> None:
        """Write one snapshot line now (tests, checkpoints)."""
        self._write_line()

    def close(self) -> None:
        """Stop the thread, write the final snapshot, close the file."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._write_line()
        with self._lock:
            if not self._f.closed:
                self._f.close()


def load_metrics_jsonl(path: str) -> List[dict]:
    """Parse a sink file; raises ValueError on a malformed line."""
    out = []
    with open(path) as f:
        for n, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                snap = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{n}: not valid JSON: {e}") from e
            if not isinstance(snap, dict) or "ts" not in snap \
                    or "metrics" not in snap:
                raise ValueError(f"{path}:{n}: missing ts/metrics keys")
            out.append(snap)
    return out
