"""Unified observability layer: one registry, device truth, a flight recorder.

Five concerns, one module each:

- :mod:`registry` — thread-safe counters/gauges/bounded-ring histograms
  with labeled families; renders as Prometheus text exposition (the serve
  HTTP front's ``GET /metrics``) and as a JSON snapshot.  Every subsystem
  (``serve``, ``runtime``, ``parallel``) registers into the same registry,
  so one scrape / one sink line carries the whole process;
- :mod:`sink` — periodic JSONL snapshots for batch runs (no scraper);
- :mod:`xla_events` — ``jax.monitoring`` listener counting jaxpr traces and
  backend compiles: the "zero steady-state compiles" SLO measured at the
  JAX layer, not inferred from the compiled-function cache's own counters;
- :mod:`profiling` — knob-gated programmatic ``jax.profiler`` window around
  N steady-state chunks, plus per-device ``memory_stats()`` gauges/sampler
  (device-side truth where host ``stage_*`` spans mislead — docs/PERF.md);
- :mod:`flight` — bounded ring of recent per-chunk/per-request records
  dumped to a JSON artifact on quarantine, shed, unhandled error, or
  SIGTERM; rendered by ``scripts/obs_report.py``.

Knobs live in ``config.ObsConfig`` (referenced by both ``RuntimeConfig``
and ``ServeConfig``); the full model is documented in
docs/OBSERVABILITY.md.
"""

from das_diff_veh_tpu.obs.flight import FlightRecorder, load_flight_dump
from das_diff_veh_tpu.obs.profiling import (HBMSampler, ProfilerWindow,
                                            register_memory_gauges)
from das_diff_veh_tpu.obs.registry import (MetricsRegistry, default_registry,
                                           percentile)
from das_diff_veh_tpu.obs.sink import MetricsSink, load_metrics_jsonl
from das_diff_veh_tpu.obs.xla_events import CompileWatch, install, uninstall

__all__ = [
    "MetricsRegistry", "default_registry", "percentile",
    "MetricsSink", "load_metrics_jsonl",
    "CompileWatch", "install", "uninstall",
    "ProfilerWindow", "HBMSampler", "register_memory_gauges",
    "FlightRecorder", "load_flight_dump",
]
