"""Crash flight recorder: a bounded ring of recent work records, dumped on
failure.

When a chunk is quarantined or a request is shed, the quarantine/shed
counter says *that* it happened; the flight recorder preserves *what was in
flight when it happened* — the last N per-chunk / per-request records
(shapes, bucket, config hash, stage timings, error or shed cause) — as a
JSON artifact a human can read after the process is gone.  Recording is a
dict append into a deque (cheap enough for every request); dumping happens
only on the failure paths:

- ``runtime/executor.py`` — every chunk is recorded; a quarantine dumps;
- ``serve/engine.py`` — every request is recorded; sheds, compute errors,
  and unhandled dispatcher errors dump;
- SIGTERM/SIGINT — :meth:`install_signal_handlers` dumps on the way out
  (chaining to the previous handler, so shutdown semantics are unchanged).

Auto-dumps are rate-limited per reason (``min_dump_interval_s``) so a shed
storm produces one artifact per window, not one per request; an explicit
``dump(..., force=True)`` always writes.  ``scripts/obs_report.py`` joins a
dump with the trace and metrics JSONL into one report.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# process-wide dump sequence: two recorders with the same name in one
# process (bench A/B reps, a re-run date after resume) must not overwrite
# each other's artifacts, so the filename counter cannot be per-instance
_DUMP_SEQ = itertools.count()


class FlightRecorder:
    """Thread-safe bounded ring of recent records + JSON dump on demand.

    With ``out_dir=None`` the ring still records (``records()`` for tests
    and embedders) but auto-dump calls are no-ops — the recorder is always
    safe to wire in.
    """

    def __init__(self, capacity: int = 256, out_dir: Optional[str] = None,
                 name: str = "flight", min_dump_interval_s: float = 1.0):
        self.capacity = int(capacity)
        self.out_dir = out_dir
        self.name = name
        self.min_dump_interval_s = float(min_dump_interval_s)
        # reentrant: the SIGTERM handler runs dump(force=True) on the main
        # thread, which may already be inside record()/dump() holding this
        # lock — a plain Lock would deadlock the exact shutdown path the
        # recorder exists to cover
        self._lock = threading.RLock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._n_recorded = 0
        self._n_dumps = 0
        self._last_dump: Dict[str, float] = {}      # reason -> monotonic s
        self._prev_handlers: dict = {}

    # -- write side ----------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """Append one record; ``kind`` tags the record type ("chunk",
        "request", "shed", "error", ...)."""
        rec = {"ts": time.time(), "kind": kind}
        rec.update(fields)
        with self._lock:
            self._ring.append(rec)
            self._n_recorded += 1

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    @property
    def n_dumps(self) -> int:
        with self._lock:
            return self._n_dumps

    # -- dump ----------------------------------------------------------------
    def dump(self, reason: str, path: Optional[str] = None,
             force: bool = False, **context) -> Optional[str]:
        """Write the ring to a JSON artifact; returns the path, or None when
        suppressed (no ``out_dir`` and no explicit ``path``, or the same
        reason dumped within ``min_dump_interval_s`` and not ``force``)."""
        now = time.monotonic()
        with self._lock:
            if path is None:
                if self.out_dir is None:
                    return None
                last = self._last_dump.get(reason, -1e18)
                if not force and now - last < self.min_dump_interval_s:
                    return None
                path = os.path.join(
                    self.out_dir,
                    f"{self.name}_{reason}_{os.getpid()}_"
                    f"{next(_DUMP_SEQ)}.json")
            self._last_dump[reason] = now
            self._n_dumps += 1
            payload = {"reason": reason, "dumped_at": time.time(),
                       "pid": os.getpid(), "n_recorded": self._n_recorded,
                       "capacity": self.capacity, "context": context,
                       "records": list(self._ring)}
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        os.replace(tmp, path)
        return path

    # -- signals -------------------------------------------------------------
    def install_signal_handlers(
            self, signals=(signal.SIGTERM, signal.SIGINT)) -> bool:
        """Dump (reason ``sig<N>``) before chaining to the previous handler
        (for SIGINT that chain ends in the default KeyboardInterrupt, so
        Ctrl-C semantics are unchanged).  Only possible on the main thread
        — returns False (and installs nothing) elsewhere, so callers can
        wire this unconditionally."""
        def _handler(signum, frame):
            self.dump(f"sig{signum}", force=True)
            prev = self._prev_handlers.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                signal.raise_signal(signum)

        try:
            for s in signals:
                self._prev_handlers[s] = signal.signal(s, _handler)
        except ValueError:          # not the main thread
            return False
        return True

    def uninstall_signal_handlers(self) -> None:
        for s, prev in list(self._prev_handlers.items()):
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
            del self._prev_handlers[s]


def load_flight_dump(path: str) -> dict:
    """Parse + validate a dump artifact (raises ValueError on bad schema)."""
    with open(path) as f:
        payload = json.load(f)
    missing = {"reason", "dumped_at", "records"} - set(payload)
    if missing:
        raise ValueError(f"{path}: flight dump missing keys {missing}")
    if not isinstance(payload["records"], list):
        raise ValueError(f"{path}: records is not a list")
    return payload
