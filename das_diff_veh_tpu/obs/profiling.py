"""Device-truth profiling hooks: programmatic profiler windows + HBM gauges.

docs/PERF.md concedes that standalone ``stage_*`` host timings "add up to
more than the combined pipeline" — XLA fuses across stage boundaries, so
wall-clock spans cannot attribute device time inside a fused program.  The
two tools here produce device-side truth instead:

- :class:`ProfilerWindow` — a knob-gated programmatic ``jax.profiler``
  capture around N *steady-state* chunks of a batch run (skip the first
  ``start_after`` chunks so compile/warmup noise stays out of the window).
  Call :meth:`step` once per chunk; the window opens and closes itself and
  the capture lands in ``profile_dir`` for TensorBoard/XProf.  This is the
  measurement the ROADMAP's fused-``process_chunk`` item needs — per-op
  device time inside the one dispatch, not host spans around it.

- :class:`HBMSampler` / :func:`register_memory_gauges` — per-device memory
  truth from ``device.memory_stats()`` (the bench.py peak-bytes pattern,
  now continuous): ``das_device_bytes_in_use`` / ``das_device_peak_bytes``
  labeled gauges per device.  The gauge form evaluates lazily at scrape
  time (zero cost between scrapes); the sampler form adds a background
  thread for platforms where ``bytes_in_use`` must be polled to catch
  transients.  Platforms without allocator stats (CPU returns None) simply
  leave the gauges at their last value.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

from das_diff_veh_tpu.obs.registry import MetricsRegistry

log = logging.getLogger("das_diff_veh_tpu.obs")


class ProfilerWindow:
    """Programmatic ``jax.profiler`` capture around N steady-state steps."""

    def __init__(self, profile_dir: str, start_after: int = 3,
                 n_steps: int = 2, registry: Optional[MetricsRegistry] = None):
        self.profile_dir = profile_dir
        self.start_after = int(start_after)
        self.n_steps = max(int(n_steps), 1)
        self._seen = 0
        self._active = False
        self._done = False
        self._lock = threading.Lock()
        self._gauge = None
        if registry is not None:
            self._gauge = registry.gauge(
                "das_obs_profiled_steps",
                "steps captured by the active profiler window")

    def step(self) -> None:
        """Advance one step (one chunk); opens/closes the capture window."""
        with self._lock:
            self._seen += 1
            if self._done:
                return
            if not self._active and self._seen > self.start_after:
                try:
                    import jax
                    jax.profiler.start_trace(self.profile_dir)
                    self._active = True
                    self._window_start = self._seen
                except Exception as e:      # profiling must never kill a run
                    log.warning("profiler window failed to start: %s", e)
                    self._done = True
                    return
            if self._active:
                captured = self._seen - self._window_start + 1
                if self._gauge is not None:
                    self._gauge.set(captured)
                if captured >= self.n_steps:
                    self._stop()

    def _stop(self) -> None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            log.warning("profiler window failed to stop: %s", e)
        self._active = False
        self._done = True

    def close(self) -> None:
        """Stop a still-open window (run ended inside it)."""
        with self._lock:
            if self._active:
                self._stop()

    @property
    def captured(self) -> bool:
        with self._lock:
            return self._done and not self._active


def _device_label(dev) -> str:
    return f"{getattr(dev, 'platform', 'dev')}:{getattr(dev, 'id', 0)}"


def register_memory_gauges(registry: MetricsRegistry,
                           devices: Optional[Sequence] = None) -> int:
    """Lazy per-device memory gauges: ``das_device_bytes_in_use`` and
    ``das_device_peak_bytes`` labeled by device, each reading
    ``device.memory_stats()`` at scrape time.  Returns the number of
    devices wired (0 when the platform has no allocator stats — the gauges
    are still registered so the scrape shape is stable)."""
    if devices is None:
        import jax
        devices = jax.devices()
    in_use = registry.gauge("das_device_bytes_in_use",
                            "allocator bytes in use", labels=("device",))
    peak = registry.gauge("das_device_peak_bytes",
                          "allocator peak bytes in use", labels=("device",))
    wired = 0
    for dev in devices:
        lbl = _device_label(dev)
        in_use.labels(device=lbl).set_fn(lambda d=dev: _stat(d, "bytes_in_use"))
        peak.labels(device=lbl).set_fn(
            lambda d=dev: _stat(d, "peak_bytes_in_use"))
        try:
            if dev.memory_stats() is not None:
                wired += 1
        except Exception:
            pass
    return wired


def _stat(dev, key: str):
    stats = dev.memory_stats()
    return None if stats is None else stats.get(key)


class HBMSampler:
    """Background thread refreshing the per-device memory gauges every
    ``interval_s`` — for catching transient peaks between scrapes (the
    ring-pipeline working set lives and dies inside one dispatch)."""

    def __init__(self, registry: MetricsRegistry, interval_s: float = 1.0,
                 devices: Optional[Sequence] = None):
        if devices is None:
            import jax
            devices = jax.devices()
        self._devices = list(devices)
        register_memory_gauges(registry, self._devices)
        self._in_use = registry.gauge("das_device_bytes_in_use",
                                      labels=("device",))
        self._peak = registry.gauge("das_device_peak_bytes",
                                    labels=("device",))
        self._interval = max(float(interval_s), 0.01)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name="obs-hbm",
                                        daemon=True)
        self._thread.start()

    def _sample(self) -> None:
        for dev in self._devices:
            # reading .value evaluates the set_fn and caches the result, so
            # the sampler and the scraper share one code path
            lbl = _device_label(dev)
            self._in_use.labels(device=lbl).value
            self._peak.labels(device=lbl).value

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._sample()
            except Exception:       # a dead device must not kill the thread
                pass

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
