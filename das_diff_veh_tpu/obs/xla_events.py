"""Device-truth compile/transfer accounting via ``jax.monitoring``.

The serve layer's "zero steady-state compiles" SLO was previously asserted
only through the compiled-function cache's OWN counters — which can't see a
compile that happens outside the cache (a stray un-warmed jit in a compute
function, a shape leaking through padding).  ``jax.monitoring`` is the
ground truth: JAX emits a duration event for every jaxpr trace
(``/jax/core/compile/jaxpr_trace_duration``) and every backend compile
(``/jax/core/compile/backend_compile_duration``) regardless of who
triggered it, so counting those events turns the SLO into a registry gauge
assertable in tests and scrapable in production.

``jax.monitoring`` has no public per-listener unregister, so this module
registers ONE module-level forwarding listener (lazily, on first
:func:`install`) and fans events out to the currently-subscribed
registries; :func:`uninstall` drops a registry from the fan-out without
touching JAX state.  Counted into each subscribed registry:

- ``das_jax_traces_total`` — jaxpr traces (fires on every fresh jit
  lowering, persistent compilation cache hit or not — the steady-state
  gauge keys off this one);
- ``das_jax_compiles_total`` / ``das_jax_compile_seconds_total`` — actual
  backend compiles and their summed duration (a persistent-cache hit skips
  these);
- ``das_jax_events_total{event=...}`` — every other monitoring event by
  name (compilation-cache hits/misses, and on real TPU platforms the
  transfer/fusion events the backend emits), so device-side activity this
  module doesn't special-case still lands in the scrape.

Wired in by ``serve.engine.ServingEngine`` (plus a
``das_serve_steady_state_compiles`` gauge anchored at warmup end) and by
``pipeline.workflow.run_directory``; knob-gated by ``ObsConfig.xla_events``.
"""

from __future__ import annotations

import threading
from typing import Dict

from das_diff_veh_tpu.obs.registry import MetricsRegistry

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Emitted by ``pipeline.fused.fused_process_chunk`` at its single program
# launch site — one event per fused chunk dispatch, by construction the
# only device dispatch the fused path performs.  Flows through the same
# listener as the trace/compile events, so "one dispatch per chunk AND
# zero steady-state retraces" is assertable from one registry scrape
# (``CompileWatch.fused_dispatches`` vs ``CompileWatch.traces``).
FUSED_DISPATCH_EVENT = "/das/pipeline/fused_chunk_dispatch"

_lock = threading.Lock()
# registry -> subscription count.  Ref-counted because independent
# components legitimately share one registry (the serve CLI's engine and
# an in-process batch run both install the process default): the first
# uninstall must not silently freeze the other component's counters.
_subscribers: Dict[MetricsRegistry, int] = {}
_installed = False


def _fanout_event(event: str, **kw) -> None:
    with _lock:
        regs = list(_subscribers)
    for reg in regs:
        reg.counter("das_jax_events_total",
                    "jax.monitoring events by name",
                    labels=("event",)).labels(event=event).inc()


def _fanout_duration(event: str, duration_secs: float, **kw) -> None:
    with _lock:
        regs = list(_subscribers)
    for reg in regs:
        if event == _TRACE_EVENT:
            reg.counter("das_jax_traces_total",
                        "jaxpr traces (fresh jit lowerings)").inc()
        elif event == _COMPILE_EVENT:
            reg.counter("das_jax_compiles_total",
                        "XLA backend compiles").inc()
            reg.counter("das_jax_compile_seconds_total",
                        "summed backend compile time").inc(duration_secs)
        else:
            reg.counter("das_jax_events_total",
                        "jax.monitoring events by name",
                        labels=("event",)).labels(event=event).inc()


def _ensure_listener() -> None:
    global _installed
    if _installed:
        return
    from jax import monitoring
    monitoring.register_event_listener(_fanout_event)
    monitoring.register_event_duration_secs_listener(_fanout_duration)
    _installed = True


class CompileWatch:
    """Read-side view of one registry's compile counters."""

    def __init__(self, registry: MetricsRegistry):
        self._registry = registry

    def _value(self, name: str) -> float:
        fam = self._registry.get(name)
        return fam.value if fam is not None and not fam.label_names else 0.0

    @property
    def traces(self) -> int:
        return int(self._value("das_jax_traces_total"))

    @property
    def compiles(self) -> int:
        return int(self._value("das_jax_compiles_total"))

    @property
    def compile_seconds(self) -> float:
        return self._value("das_jax_compile_seconds_total")

    @property
    def fused_dispatches(self) -> int:
        """Fused per-chunk program launches (:data:`FUSED_DISPATCH_EVENT`
        events) counted into this registry."""
        fam = self._registry.get("das_jax_events_total")
        if fam is None:
            return 0
        for values, child in fam.children():
            if values == (FUSED_DISPATCH_EVENT,):
                return int(child.value)
        return 0


def install(registry: MetricsRegistry) -> CompileWatch:
    """Subscribe ``registry`` to monitoring events; the counters exist (at
    zero) from this call on.  Subscriptions are ref-counted: events fan
    out once per registry however many times it is installed, and the
    registry stays subscribed until every install is matched by an
    :func:`uninstall`."""
    _ensure_listener()
    # pre-register so a scrape before the first event still shows the family
    registry.counter("das_jax_traces_total",
                     "jaxpr traces (fresh jit lowerings)")
    registry.counter("das_jax_compiles_total", "XLA backend compiles")
    registry.counter("das_jax_compile_seconds_total",
                     "summed backend compile time")
    with _lock:
        _subscribers[registry] = _subscribers.get(registry, 0) + 1
    return CompileWatch(registry)


def uninstall(registry: MetricsRegistry) -> None:
    """Release one :func:`install` of ``registry``; the fan-out drops it
    when the last subscription is released (its counters keep their
    values).  A no-op for a registry that was never installed."""
    with _lock:
        n = _subscribers.get(registry, 0)
        if n <= 1:
            _subscribers.pop(registry, None)
        else:
            _subscribers[registry] = n - 1
