from das_diff_veh_tpu.core.section import DasSection, VehicleTracks, WindowBatch  # noqa: F401
