"""Core array containers, registered as JAX pytrees.

The reference couples arrays to eagerly-computing classes (e.g. Dispersion
computes in its constructor, modules/utils.py:383-405; SurfaceWaveSelector
slices in __init__, apis/data_classes.py:168). Here containers are inert
pytrees; all compute lives in pure functions that jit/vmap/shard cleanly.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import numpy as np


def _register(cls):
    """Register a dataclass as a pytree (all fields are leaves unless named in meta_fields)."""
    meta = getattr(cls, "_meta_fields", ())
    data = [f.name for f in dataclasses.fields(cls) if f.name not in meta]
    return jax.tree_util.register_dataclass(cls, data_fields=data, meta_fields=list(meta))


@_register
@dataclass
class DasSection:
    """One (nch, nt) DAS waterfall with its axes.

    Mirrors the (data, x_axis, t_axis) triple threaded through the reference
    (modules/utils.py:169-176 read_data returns).  ``x`` is distance along the
    fiber [m] (already interrogator-corrected), ``t`` is time [s].
    """

    data: jax.Array        # (nch, nt)
    x: jax.Array           # (nch,)
    t: jax.Array           # (nt,)

    @property
    def nch(self) -> int:
        return self.data.shape[0]

    @property
    def nt(self) -> int:
        return self.data.shape[-1]

    @property
    def dx(self) -> float:
        return float(self.x[1] - self.x[0])

    @property
    def dt(self) -> float:
        return float(self.t[1] - self.t[0])

    def numpy(self) -> "DasSection":
        return DasSection(np.asarray(self.data), np.asarray(self.x), np.asarray(self.t))

    def cut_time(self, t1: float, t2: float) -> "DasSection":
        """Slice to the [t1, t2) time range by nearest sample (reference
        ``cut_data_along_time``, modules/utils.py:131-134)."""
        t = np.asarray(self.t)
        i1 = int(np.abs(t1 - t).argmin())
        i2 = int(np.abs(t2 - t).argmin())
        return DasSection(self.data[:, i1:i2], self.x, self.t[i1:i2])


@_register
@dataclass
class VehicleTracks:
    """Tracked vehicle states on the tracking grid.

    ``t_idx``: (max_vehicles, n_track_ch) float arrival-time *sample index* per
    channel (NaN = no detection) — same convention as the reference's
    ``veh_states`` (apis/tracking.py:79).  ``valid``: (max_vehicles,) bool mask
    of live tracks after QC.  ``x``/``t``: tracking-grid axes (1 m / 50 Hz).
    """

    t_idx: jax.Array       # (max_vehicles, n_track_ch)
    valid: jax.Array       # (max_vehicles,)
    x: jax.Array           # (n_track_ch,)
    t: jax.Array           # (n_track_t,)


@_register
@dataclass
class WindowBatch:
    """Static-shape batch of per-vehicle surface-wave windows.

    The reference keeps a Python list of SurfaceWaveWindow objects with
    deep-copied slices (apis/data_classes.py:211-223).  For jit we instead hold
    one (max_windows, nx, nt_win) tensor plus a validity mask; trajectory
    samples are stored per-window on the tracking grid (NaN-padded).
    """

    data: jax.Array        # (max_windows, nx, nt_win)
    x: jax.Array           # (nx,) common spatial axis (offsets are window-relative)
    t: jax.Array           # (max_windows, nt_win) absolute time axis per window
    traj_x: jax.Array      # (max_windows, n_traj) vehicle position samples [m]
    traj_t: jax.Array      # (max_windows, n_traj) vehicle time samples [s] (NaN-padded)
    valid: jax.Array       # (max_windows,)

    @property
    def max_windows(self) -> int:
        return self.data.shape[0]
