"""Command-line entry: batch imaging, plus the ``serve`` subcommand.

    python -m das_diff_veh_tpu.pipeline.cli --data_root /data \
        --start_date 20230301 --end_date 20230307 --x0 700 --method xcorr \
        --prefetch_depth 3 --trace results/run_trace.jsonl

    python -m das_diff_veh_tpu.pipeline.cli serve \
        --buckets 140x30000,140x15000 --x0 700 --port 8080

The batch flags stay top-level (stable since PR 2); ``serve`` routes to
:mod:`das_diff_veh_tpu.serve.cli`.
"""

from __future__ import annotations

import argparse
import json
import logging

from das_diff_veh_tpu.config import ImagingConfig, ObsConfig, PipelineConfig
from das_diff_veh_tpu.pipeline.workflow import run_date_range
from das_diff_veh_tpu.runtime import RuntimeConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Vehicle-DAS time-lapse imaging")
    p.add_argument("--data_root", help="root with per-date npz folders")
    p.add_argument("--start_date", help="YYYYMMDD")
    p.add_argument("--end_date", help="YYYYMMDD")
    p.add_argument("--out_dir", default="results")
    p.add_argument("--method", default="xcorr", choices=["xcorr", "surface_wave"])
    p.add_argument("--x0", type=float, default=700.0, help="pivot along fiber [m]")
    p.add_argument("--n_min_save", type=float, default=60.0,
                   help="checkpoint the running average every N data-minutes")
    p.add_argument("--max_chunks", type=int, default=None,
                   help="process at most N remaining chunks per date "
                        "(smoke runs; the manifest resumes the rest later)")
    p.add_argument("--verbal", action="store_true", help="per-chunk progress logs")
    p.add_argument("--figures", action="store_true",
                   help="write the reference QC figure set from a synthetic "
                        "run into out_dir and exit (no data_root needed)")
    rt = p.add_argument_group("runtime", "pipelined batch-execution knobs")
    rt.add_argument("--prefetch_depth", type=int, default=2,
                    help="chunks staged ahead by the loader thread; 0 = serial")
    rt.add_argument("--retries", type=int, default=1,
                    help="retry attempts per chunk stage before quarantine")
    rt.add_argument("--retry_backoff", type=float, default=0.05,
                    help="linear backoff unit between retries [s]")
    rt.add_argument("--trace", default=None, metavar="PATH",
                    help="write Chrome-trace JSONL spans to PATH "
                         "(open in chrome://tracing or Perfetto)")
    rt.add_argument("--compilation_cache_dir", default=None, metavar="DIR",
                    help="persistent XLA compilation cache "
                         "(jax_compilation_cache_dir): reruns and serve "
                         "warmups skip recompiles across process restarts")
    obs = p.add_argument_group("observability",
                               "metrics/flight/profiler knobs "
                               "(docs/OBSERVABILITY.md)")
    obs.add_argument("--metrics_jsonl", default=None, metavar="PATH",
                     help="append periodic metrics-registry snapshots "
                          "(JSON lines) here — the batch counterpart of the "
                          "serve front's GET /metrics")
    obs.add_argument("--metrics_interval", type=float, default=10.0,
                     metavar="S", help="seconds between metrics snapshots")
    obs.add_argument("--flight_dir", default=None, metavar="DIR",
                     help="crash-flight-recorder dumps (recent per-chunk "
                          "records as JSON on quarantine/SIGTERM); render "
                          "with scripts/obs_report.py")
    obs.add_argument("--profile_dir", default=None, metavar="DIR",
                     help="capture a programmatic jax.profiler window of "
                          "--profile_chunks steady-state chunks here")
    obs.add_argument("--profile_chunks", type=int, default=2,
                     help="chunks inside the profiler window")
    obs.add_argument("--trace_flush_interval", type=float, default=0.0,
                     metavar="S", help="batch trace writes, flushing every "
                                       "S seconds (0 = flush per span)")
    return p


def main(argv=None) -> int:
    import sys

    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        from das_diff_veh_tpu.serve.cli import serve_main
        return serve_main(argv[1:])
    parser = build_parser()
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO if args.verbal else logging.WARNING,
                        format="%(asctime)s %(name)s %(message)s")
    if args.compilation_cache_dir:
        from das_diff_veh_tpu.cache import enable_compilation_cache
        enable_compilation_cache(cache_dir=args.compilation_cache_dir)
    if args.figures:
        from das_diff_veh_tpu.viz import figure_set_from_synthetic
        for f in figure_set_from_synthetic(args.out_dir):
            print(f)
        return 0
    if not (args.data_root and args.start_date and args.end_date):
        parser.error("--data_root/--start_date/--end_date are "
                     "required unless --figures is given")
    cfg = PipelineConfig().replace(imaging=ImagingConfig(x0=args.x0))
    obs = ObsConfig(metrics_jsonl=args.metrics_jsonl,
                    metrics_interval_s=args.metrics_interval,
                    flight_dir=args.flight_dir,
                    profile_dir=args.profile_dir,
                    profile_n_chunks=args.profile_chunks,
                    trace_flush_interval_s=args.trace_flush_interval)
    runtime = RuntimeConfig(prefetch_depth=args.prefetch_depth,
                            max_retries=args.retries,
                            retry_backoff_s=args.retry_backoff,
                            trace_path=args.trace, obs=obs)
    summary = run_date_range(args.data_root, args.start_date, args.end_date,
                             cfg=cfg, method=args.method, out_dir=args.out_dir,
                             n_min_save=args.n_min_save,
                             max_chunks=args.max_chunks, runtime=runtime)
    print(json.dumps(summary, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
