"""Command-line batch imaging (reference apis/imaging_workflow.py:206-223).

    python -m das_diff_veh_tpu.pipeline.cli --data_root /data \
        --start_date 20230301 --end_date 20230307 --x0 700 --method xcorr
"""

from __future__ import annotations

import argparse
import logging

from das_diff_veh_tpu.config import ImagingConfig, PipelineConfig
from das_diff_veh_tpu.pipeline.workflow import run_date_range


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Vehicle-DAS time-lapse imaging")
    p.add_argument("--data_root", help="root with per-date npz folders")
    p.add_argument("--start_date", help="YYYYMMDD")
    p.add_argument("--end_date", help="YYYYMMDD")
    p.add_argument("--out_dir", default="results")
    p.add_argument("--method", default="xcorr", choices=["xcorr", "surface_wave"])
    p.add_argument("--x0", type=float, default=700.0, help="pivot along fiber [m]")
    p.add_argument("--n_min_save", type=float, default=60.0,
                   help="checkpoint the running average every N data-minutes")
    p.add_argument("--verbal", action="store_true", help="per-chunk progress logs")
    p.add_argument("--figures", action="store_true",
                   help="write the reference QC figure set from a synthetic "
                        "run into out_dir and exit (no data_root needed)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO if args.verbal else logging.WARNING,
                        format="%(asctime)s %(name)s %(message)s")
    if args.figures:
        from das_diff_veh_tpu.viz import figure_set_from_synthetic
        for f in figure_set_from_synthetic(args.out_dir):
            print(f)
        return 0
    if not (args.data_root and args.start_date and args.end_date):
        build_parser().error("--data_root/--start_date/--end_date are "
                             "required unless --figures is given")
    cfg = PipelineConfig().replace(imaging=ImagingConfig(x0=args.x0))
    summary = run_date_range(args.data_root, args.start_date, args.end_date,
                             cfg=cfg, method=args.method, out_dir=args.out_dir,
                             n_min_save=args.n_min_save)
    print(summary)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
