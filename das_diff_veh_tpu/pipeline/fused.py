"""Single-dispatch fused per-chunk pipeline: ONE donated XLA program from
tracking to dispersion image.

The staged path (``pipeline.timelapse.process_chunk``) interleaves host
geometry with eager device stages — on the tunneled single-chip test rig
every stage boundary is a ~100-200 ms round trip (docs/PERF.md), and
``BENCH_cpu_smoke_r11.json`` measured the SAME work at 0.256 s amortized
in-dispatch vs 0.828 s when dispatch-bound: the latency lever is dispatch
*count*, not kernel time.  This module runs the whole post-screen pipeline
as one jitted program per chunk:

- **all geometry at trace time**: every slice bound (tracking grid, window
  aperture, VSG geometry, dispersion offsets) resolves from the host
  ``(x, t, cfg)`` metadata while tracing, so the compiled program contains
  only device ops — ``chunk_body`` is shared with the staged path, which
  stays the parity oracle (bit-exact, tests/test_fused_pipeline.py);
- **on-device masking end to end**: ``batch.valid`` never becomes a Python
  int mid-pipeline; ``n_windows`` returns as a device scalar inside the
  result pytree, pulled by the consumer in one ``jax.device_get``;
- **buffer donation**: the chunk input is donated to the program
  (``donate_argnums``), so the dominant buffer is reused instead of held
  across the dispatch.  The fused entry therefore CONSUMES a device-array
  input — callers that need ``section.data`` afterwards should pass host
  numpy (the entry stages a fresh device buffer) or copy first.  The
  runtime's loader device_puts a fresh buffer per chunk, so the batch and
  serve paths donate safely by construction;
- **per-geometry program cache**: programs are keyed on the data
  shape/dtype, fingerprints of the ``x``/``t`` axes, the config, method,
  and ``with_qs`` — the serve layer's per-bucket warmup therefore compiles
  each bucket's fused program once, and steady state is zero compiles
  (asserted via ``obs/xla_events.py`` trace counters).

Device-truth accounting mirrors PR 7's zero-extra-dispatch pattern
(``resilience.health.SCREENS_BY_TAG``): the single launch site below
counts per-tag module counters AND emits a ``jax.monitoring`` event
(``obs.xla_events.FUSED_DISPATCH_EVENT``) that lands in any installed
metrics registry next to the trace/compile counters, so "one dispatch per
chunk, zero steady-state retraces" is a counter assertion, not a claim.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.config import PipelineConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.pipeline.timelapse import (ChunkResult, chunk_body,
                                                 resolve_chunk_metadata,
                                                 screen_chunk)

_lock = threading.Lock()
_PROGRAMS: Dict[tuple, object] = {}

# per-call-site dispatch accounting (the PR 7 SCREENS_BY_TAG pattern):
# tests assert "exactly one device dispatch per fused chunk" against these
# instead of trusting the docstring
DISPATCHES_BY_TAG: Dict[str, int] = {}


def n_dispatches(tag: Optional[str] = None) -> int:
    with _lock:
        if tag is not None:
            return DISPATCHES_BY_TAG.get(tag, 0)
        return sum(DISPATCHES_BY_TAG.values())


def n_programs() -> int:
    """Distinct fused programs built in this process (cache size)."""
    with _lock:
        return len(_PROGRAMS)


def clear_programs() -> None:
    """Drop the program cache (tests; a donated-buffer program pins its
    input layout, so geometry churn in a long session can release here)."""
    with _lock:
        _PROGRAMS.clear()


def _fingerprint(a: np.ndarray) -> tuple:
    a = np.ascontiguousarray(a)
    return (a.shape, str(a.dtype), hashlib.sha1(a.tobytes()).hexdigest())


def _donate() -> tuple:
    # XLA CPU cannot alias the input record into this program's outputs and
    # warns per-compile about the unusable donation; donation buys its
    # memory back on the accelerator backends only
    return (0,) if jax.default_backend() != "cpu" else ()


def _program(shape: tuple, dtype, x_dist: np.ndarray, t: np.ndarray,
             cfg: PipelineConfig, method: str, with_qs: bool):
    """Get-or-build the fused program for this chunk geometry.  The key
    hashes the axis VALUES (not just shapes): every slice bound inside is a
    trace-time constant derived from them, so two sections that differ only
    in (say) the time origin are different programs — exactly the serve
    layer's bucket+canonicalization contract (serve/imaging.py rebases t,
    so real deployments hit one key per bucket)."""
    key = (tuple(shape), str(dtype), _fingerprint(x_dist), _fingerprint(t),
           cfg, method, with_qs)
    with _lock:
        prog = _PROGRAMS.get(key)
    if prog is not None:
        return prog

    dt = float(t[1] - t[0])

    def body(data):
        img, vsg_stack, n_windows, tracks, batch, qs_batch = chunk_body(
            data, x_dist, t, dt, cfg, method=method, with_qs=with_qs)
        return dict(disp_image=img, vsg_stack=vsg_stack,
                    n_windows=n_windows, tracks=tracks, batch=batch,
                    qs_batch=qs_batch)

    prog = jax.jit(body, donate_argnums=_donate())
    with _lock:
        # setdefault: a racing builder's program is identical — keep one
        return _PROGRAMS.setdefault(key, prog)


def fused_process_chunk(section: DasSection,
                        cfg: Optional[PipelineConfig] = None,
                        method: str = "xcorr", x_is_channels: bool = False,
                        with_qs: bool = False,
                        tag: str = "process_chunk") -> ChunkResult:
    """``process_chunk`` semantics in one device dispatch.

    After the input-health screen (the one unavoidable host decision — its
    verdict gates a Python ``raise``), the remaining pipeline executes as a
    single jitted, input-donated XLA program; the returned
    :class:`ChunkResult` is an inert on-device pytree whose ``n_windows``
    is a device scalar.  Pull what you need in ONE ``jax.device_get`` —
    ``run_directory`` and the serve compute do exactly that.

    Bit-exact vs the staged oracle on the default config (both methods,
    tests/test_fused_pipeline.py); reach it via
    ``cfg.replace(chunk_pipeline="fused")`` on any ``process_chunk``
    call site, or call this entry directly.
    """
    assert method in {"xcorr", "surface_wave"}
    cfg = cfg if cfg is not None else PipelineConfig()

    section, health = screen_chunk(section, cfg, tag=tag)
    x_dist, t, _dt = resolve_chunk_metadata(section, cfg, x_is_channels)

    data = section.data
    shape, dtype = data.shape, data.dtype
    prog = _program(shape, dtype, x_dist, t, cfg, method, with_qs)
    if not isinstance(data, jax.Array):
        # host input: stage a fresh device buffer the program may consume
        data = jnp.asarray(data)

    with _lock:
        DISPATCHES_BY_TAG[tag] = DISPATCHES_BY_TAG.get(tag, 0) + 1
    from das_diff_veh_tpu.obs.xla_events import FUSED_DISPATCH_EVENT
    jax.monitoring.record_event(FUSED_DISPATCH_EVENT)
    out = prog(data)

    return ChunkResult(disp_image=out["disp_image"],
                       vsg_stack=out["vsg_stack"],
                       n_windows=out["n_windows"], tracks=out["tracks"],
                       batch=out["batch"], qs_batch=out["qs_batch"],
                       health=health)
