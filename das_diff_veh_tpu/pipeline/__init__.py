"""Pipeline orchestration: preprocessing stages, per-chunk imaging, batch
workflows with checkpoint/resume, and the CLI.

Replaces the reference's eager compute-in-constructor orchestration
(apis/timeLapseImaging.py, apis/imaging_workflow.py) with explicit staged
pure functions around jit boundaries.  The batch workflows execute on the
pipelined runtime (``das_diff_veh_tpu.runtime``): prefetch, per-chunk fault
isolation, manifest-driven exact resume, and Chrome-trace span output.
"""

from das_diff_veh_tpu.pipeline.preprocess import (channels_to_distance,
                                                  preprocess_for_surface_waves,
                                                  preprocess_for_tracking)
from das_diff_veh_tpu.pipeline.timelapse import ChunkResult, process_chunk
