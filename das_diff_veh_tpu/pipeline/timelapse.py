"""Per-chunk processing: one DAS time window -> tracked vehicles -> selected
surface-wave windows -> stacked dispersion image (and/or VSG stack).

The reference's TimeLapseImaging object (apis/timeLapseImaging.py:22-197)
re-cast as a pure staged function: every stage is an explicit call, all
heavy compute sits behind jit, and the result is an inert pytree.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.config import PipelineConfig
from das_diff_veh_tpu.core.section import (DasSection, VehicleTracks,
                                           WindowBatch)
from das_diff_veh_tpu.models import vsg as V
from das_diff_veh_tpu.models.tracking import track_grid, track_section
from das_diff_veh_tpu.models.windows import (select_windows, traj_mute_mask,
                                             window_x_slice)
from das_diff_veh_tpu.pipeline.preprocess import (channels_to_distance,
                                                  preprocess_for_surface_waves,
                                                  preprocess_for_tracking)


@dataclass
class ChunkResult:
    """One processed chunk: stacked image + provenance."""

    disp_image: jnp.ndarray          # (nvel, nfreq)
    vsg_stack: Optional[jnp.ndarray]  # (nch_out, wlen) for method='xcorr'
    n_windows: int                   # accepted (isolated) vehicle windows —
                                     # a Python int on the staged path, a
                                     # device scalar on the fused path (pull
                                     # it in the SAME jax.device_get as the
                                     # image; that is the point)
    tracks: VehicleTracks
    batch: WindowBatch               # surface-wave-band windows
    qs_batch: Optional[WindowBatch]  # raw-band windows (with_qs=True only)
    health: Optional[object] = None  # resilience.health.ChannelHealth when
                                     # cfg.health.enabled, else None


def disp_image_batch(batch: WindowBatch, cfg: PipelineConfig,
                     x: Optional[np.ndarray] = None,
                     dt: Optional[float] = None) -> jnp.ndarray:
    """Direct per-window dispersion images with muting (reference
    DispersionImagesFromWindows + SurfaceWaveDispersion 'naive' over
    [disp_start_x+x0, x0], apis/imaging_classes.py:96-107 +
    apis/dispersion_classes.py:24-32): mute along the trajectory, slant the
    muted window over the imaging offset range.  Returns (max_windows, nvel,
    nfreq).

    ``x``/``dt``: host copies of the batch's window x axis and sample
    interval.  When omitted they are pulled from ``batch`` (a device->host
    sync); the fused chunk program passes them so the slice geometry below
    resolves at trace time without touching the device."""
    dcfg = cfg.dispersion
    dx = cfg.interrogator.dx
    x = np.asarray(batch.x if x is None else x)
    start_x = cfg.imaging.x0 + cfg.imaging.disp_start_x
    sxi = int(np.argmax(x >= start_x))
    nx = int((cfg.imaging.disp_end_x - cfg.imaging.disp_start_x) / dx)
    freqs = jnp.arange(dcfg.freq_min, dcfg.freq_max, dcfg.freq_step)
    vels = jnp.arange(dcfg.vel_min, dcfg.vel_max, dcfg.vel_step)
    dt = float(batch.t[0, 1] - batch.t[0, 0]) if dt is None else float(dt)

    from das_diff_veh_tpu.ops.dispersion import fv_map_fk, fv_map_phase_shift

    def one(args):
        data, t, tx, tt = args
        mask = traj_mute_mask(batch.x, t, tx, tt, jnp.isfinite(tt), dx,
                              offset=cfg.mute.offset, alpha=cfg.mute.alpha,
                              delta_x=cfg.mute.delta_x)
        muted = data * mask
        sliced = muted[sxi:sxi + nx]
        if dcfg.method == "phase_shift":
            return fv_map_phase_shift(sliced, dx, dt, freqs, vels,
                                      direction=-1.0, whiten=False)
        return fv_map_fk(sliced, dx, dt, freqs, vels,
                         norm=dcfg.norm, sg_window=dcfg.sg_window,
                         sg_order=dcfg.sg_order)

    # accelerators: one batched program (vmap) — windows image in parallel.
    # CPU: a 64-way batched version of this gather-heavy transform segfaults
    # the XLA CPU compiler, so the mapped body compiles once and loops.
    args = (batch.data, batch.t, batch.traj_x, batch.traj_t)
    if jax.default_backend() not in ("cpu",):
        return jax.vmap(one)(args)
    return jax.lax.map(one, args)


def resolve_chunk_metadata(section: DasSection, cfg: PipelineConfig,
                           x_is_channels: bool = False):
    """The one host decision of the per-chunk path: ``(x_dist, t, dt)`` as
    host numpy from the section's axis metadata.  Loaders keep ``x``/``t``
    host-resident (only ``data`` rides the device, runtime/executor.py), so
    this is normally a no-op view; a device-resident axis is pulled ONCE
    here and never again downstream."""
    x_dist = (channels_to_distance(np.asarray(section.x), cfg.interrogator)
              if x_is_channels else np.asarray(section.x))
    t = np.asarray(section.t)
    return x_dist, t, float(t[1] - t[0])


def chunk_body(data: jnp.ndarray, x_dist: np.ndarray, t: np.ndarray,
               dt: float, cfg: PipelineConfig, method: str = "xcorr",
               with_qs: bool = False):
    """The traceable per-chunk pipeline core shared by the staged and fused
    paths: preprocess both bands -> track -> select windows -> build the
    method's stacked image.  ``x_dist``/``t`` MUST be host numpy — every
    slice bound below resolves from them at trace time, so ``data`` may be
    a tracer and the whole body compiles into one XLA program with zero
    host round trips (pinned by tests/test_fused_pipeline.py).

    Returns ``(img, vsg_stack, n_windows, tracks, batch, qs_batch)`` with
    ``n_windows`` a device scalar (the staged wrapper converts it; the
    fused program keeps it on-device until the caller's single
    ``device_get``)."""
    # --- both preprocessing bands --------------------------------------------
    d_sw = preprocess_for_surface_waves(data, dt, cfg.sw_preprocess,
                                        normalize=(method == "surface_wave"))
    d_track, x_track, t_stride = preprocess_for_tracking(
        data, x_dist, dt, cfg.tracking_preprocess, dx=cfg.interrogator.dx)
    t_track = t[::t_stride]

    # --- track (amplitude negated: deflection pulses become positive peaks,
    #     reference apis/timeLapseImaging.py:108-109) --------------------------
    tracks = track_section(-d_track, x_track, t_track,
                           cfg.imaging.start_x, cfg.imaging.end_x,
                           cfg.tracking, cfg.track_qc)
    # host copies of the tracking grid (== tracks.x / tracks.t values):
    # select_windows resolves its geometry from these instead of pulling
    # the device-resident pytree leaves back
    tgrid = track_grid(x_track, cfg.imaging.start_x, cfg.imaging.end_x)

    # --- select windows: filtered band + raw band (quasi-static weights),
    #     reference select_surface_wave_windows (:166-192) ---------------------
    batch = select_windows(d_sw, x_dist, t, tracks, cfg.imaging.x0,
                           cfg.window, track_x=tgrid, track_t=t_track)
    qs_batch = (select_windows(data, x_dist, t, tracks, cfg.imaging.x0,
                               cfg.window, track_x=tgrid, track_t=t_track)
                if with_qs else None)

    n_windows = jnp.sum(batch.valid)
    x_win = window_x_slice(x_dist, cfg.imaging.x0, cfg.window)  # host batch.x
    if method == "xcorr":
        g = V.VsgGeometry.build(x_win, dt, cfg.imaging.x0,
                                cfg.imaging.x0 + cfg.imaging.disp_start_x,
                                cfg.imaging.x0 + cfg.gather.far_offset,
                                cfg.gather)
        gathers = V.build_gather_batch(batch, g, cfg.gather)
        stack = V.stack_gathers(gathers, batch.valid)
        img = V.gather_disp_image(stack, g.offsets(x_win), dt,
                                  cfg.interrogator.dx, cfg.dispersion,
                                  cfg.imaging.disp_start_x, cfg.imaging.disp_end_x)
        vsg_stack = stack
    else:
        imgs = disp_image_batch(batch, cfg, x=x_win, dt=dt)
        img = V.stack_gathers(imgs, batch.valid)
        vsg_stack = None
    return img, vsg_stack, n_windows, tracks, batch, qs_batch


def screen_chunk(section: DasSection, cfg: PipelineConfig, tag: str):
    """Input-health sentinel shared by the staged and fused entries
    (resilience/health.py).  Off by default: costs one attribute check and
    ZERO extra device dispatches (counter-asserted in
    tests/test_resilience.py).  On, one fused jitted program screens
    NaN/Inf, flatline, and clipped channels and masks them before anything
    downstream can average them.  Returns ``(section, health-or-None)``;
    raises ``PoisonedChunkError`` on a failing verdict."""
    if not cfg.health.enabled:
        return section, None
    from das_diff_veh_tpu.resilience.health import (PoisonedChunkError,
                                                    screen_section)
    section, health = screen_section(section, cfg.health, tag=tag)
    if not health.ok(cfg.health):
        raise PoisonedChunkError(health)
    return section, health


def process_chunk(section: DasSection, cfg: Optional[PipelineConfig] = None,
                  method: str = "xcorr", x_is_channels: bool = False,
                  with_qs: bool = False) -> ChunkResult:
    """Full per-chunk pipeline (reference TimeLapseImaging usage in
    apis/imaging_workflow.py:50-67): preprocess both bands, track, select
    windows around cfg.imaging.x0, and build the method's stacked image.

    ``method``: 'xcorr' (virtual shot gathers -> dispersion of the stack) or
    'surface_wave' (muted direct dispersion per window, averaged).
    ``with_qs``: also cut raw-band windows for quasi-static weight analysis
    (reference qs_selector, apis/timeLapseImaging.py:183-191); off by default
    because the imaging workflow never consumes them.

    ``cfg.chunk_pipeline`` selects the execution mode: ``"staged"`` (this
    body — eager stages, host geometry between them, ``n_windows`` pulled
    to a Python int) or ``"fused"`` (``pipeline.fused.fused_process_chunk``
    — one jitted donated program per chunk, ``n_windows`` left on-device).
    """
    assert method in {"xcorr", "surface_wave"}
    cfg = cfg if cfg is not None else PipelineConfig()
    assert cfg.chunk_pipeline in {"staged", "fused"}, cfg.chunk_pipeline
    if cfg.chunk_pipeline == "fused":
        from das_diff_veh_tpu.pipeline.fused import fused_process_chunk
        return fused_process_chunk(section, cfg, method=method,
                                   x_is_channels=x_is_channels,
                                   with_qs=with_qs)

    section, health = screen_chunk(section, cfg, tag="process_chunk")
    x_dist, t, dt = resolve_chunk_metadata(section, cfg, x_is_channels)
    data = jnp.asarray(section.data)

    img, vsg_stack, n_windows, tracks, batch, qs_batch = chunk_body(
        data, x_dist, t, dt, cfg, method=method, with_qs=with_qs)

    return ChunkResult(disp_image=img, vsg_stack=vsg_stack,
                       n_windows=int(n_windows), tracks=tracks,
                       batch=batch, qs_batch=qs_batch, health=health)


class FleetVsMonitor:
    """Continuous Vs change detection over time-lapse fleet inversions.

    Closes the loop ROADMAP item 4 asks for: each monitoring epoch's
    :class:`~das_diff_veh_tpu.inversion.fleet.FleetResult` is compared
    against a baseline epoch's bootstrap credible intervals
    (:func:`~das_diff_veh_tpu.inversion.fleet.detect_vs_shifts`), and any
    layer whose point estimate leaves the baseline interval raises the
    obs-registry alarm surface:

    - ``das_fleet_vs_shift_total{target=...}`` — counter, one inc per
      shifted (target, layer) observation;
    - ``das_fleet_vs_alarm_active{target=...}`` — gauge, 1 while the
      latest epoch has any out-of-interval layer for that target, 0 once
      it returns inside;
    - ``das_fleet_vs_epochs_total`` — epochs observed;
    - a ``"vs_shift"`` flight-recorder record per event (target, layer,
      Vs, interval) when a :class:`~das_diff_veh_tpu.obs.flight.FlightRecorder`
      is attached, so a post-mortem dump shows *which* layer moved.

    The monitor never mutates inversion results and its alarm threshold is
    exactly the baseline's credible interval — uncertainty machinery
    gating alerts, not loosening misfits.
    """

    def __init__(self, baseline, registry=None, flight=None,
                 target_names=None):
        from das_diff_veh_tpu.obs.registry import default_registry
        self.baseline = baseline
        self.registry = (registry if registry is not None
                         else default_registry())
        self.flight = flight
        n_t = baseline.vs.shape[0]
        self.target_names = (tuple(str(t) for t in target_names)
                             if target_names is not None
                             else tuple(str(i) for i in range(n_t)))
        if len(self.target_names) != n_t:
            raise ValueError(f"{len(self.target_names)} target names for "
                             f"{n_t} baseline targets")
        self._shifts = self.registry.counter(
            "das_fleet_vs_shift_total",
            "fleet Vs layer shifts beyond the baseline credible interval",
            labels=("target",))
        self._alarm = self.registry.gauge(
            "das_fleet_vs_alarm_active",
            "1 while the latest epoch has an out-of-interval Vs layer",
            labels=("target",))
        self._epochs = self.registry.counter(
            "das_fleet_vs_epochs_total", "fleet monitoring epochs observed")
        for name in self.target_names:
            self._alarm.labels(target=name).set(0.0)

    def observe(self, current):
        """Compare one epoch against the baseline; returns the events.

        Increments the shift counter per event, sets/clears the per-target
        alarm gauge, and appends ``"vs_shift"`` flight records."""
        from das_diff_veh_tpu.inversion.fleet import detect_vs_shifts
        events = detect_vs_shifts(self.baseline, current)
        self._epochs.inc()
        shifted = set()
        for ev in events:
            name = self.target_names[ev.target]
            shifted.add(ev.target)
            self._shifts.labels(target=name).inc()
            if self.flight is not None:
                self.flight.record("vs_shift", target=name, layer=ev.layer,
                                   vs=ev.vs, lo=ev.lo, hi=ev.hi)
        for t, name in enumerate(self.target_names):
            self._alarm.labels(target=name).set(1.0 if t in shifted else 0.0)
        return events

    def rebase(self, baseline):
        """Adopt a new baseline epoch (e.g. after a confirmed site change);
        clears every alarm."""
        if baseline.vs.shape != self.baseline.vs.shape:
            raise ValueError("rebase needs the same fleet geometry")
        self.baseline = baseline
        for name in self.target_names:
            self._alarm.labels(target=name).set(0.0)
