"""Preprocessing stages feeding the tracker and the imaging kernels.

Mirrors the reference's two preprocessing paths
(apis/timeLapseImaging.py:51-102) as pure jit-able functions:

- *surface-wave band*: 1.2-30 Hz bandpass, empty/noisy trace imputation,
  optional per-trace L2 norm;
- *quasi-static band (tracking)*: loud-channel kill, imputation, 0.08-1 Hz
  bandpass, 250->50 Hz temporal subsample, 8.16 m -> 1 m polyphase spatial
  resample, spatial wavenumber bandpass.

Deliberate delta: the reference imputes exactly ONE trace per call via
``argmax`` of the QC mask (modules/utils.py:316-329 — and imputes channel 0
when nothing matches); here every flagged trace is imputed by its neighbor
average and nothing is touched when the mask is empty (SURVEY.md §7 step 2).
"""

from __future__ import annotations

from fractions import Fraction

import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.config import (InterrogatorConfig,
                                     SurfaceWavePreprocessConfig,
                                     TrackingPreprocessConfig)
from das_diff_veh_tpu.ops.filters import (bandpass_space, bandpass_time,
                                          l2_normalize_traces)
from das_diff_veh_tpu.ops.qc import (empty_trace_mask, impute_traces,
                                     noisy_trace_mask)
from das_diff_veh_tpu.ops.resample import resample_poly


def channels_to_distance(x: np.ndarray,
                         interrogator: InterrogatorConfig = InterrogatorConfig()) -> np.ndarray:
    """Channel numbers -> meters along fiber (reference
    apis/timeLapseImaging.py:42: ``(x - start_ch) * dx``)."""
    return (np.asarray(x) - interrogator.start_ch) * interrogator.dx


def preprocess_for_surface_waves(data: jnp.ndarray, dt: float,
                                 cfg: SurfaceWavePreprocessConfig = SurfaceWavePreprocessConfig(),
                                 normalize: bool | None = None) -> jnp.ndarray:
    """Surface-wave band conditioning (reference
    apis/timeLapseImaging.py:51-71).  ``normalize`` overrides
    ``cfg.normalize_traces`` (the reference normalizes for the direct
    dispersion method but not the xcorr method)."""
    out = bandpass_time(data, dt, cfg.flo, cfg.fhi)
    if cfg.impute_empty:
        out = impute_traces(out, empty_trace_mask(out, cfg.noise_threshold))
    if cfg.impute_noisy:
        out = impute_traces(out, noisy_trace_mask(out, cfg.noise_threshold))
    norm = cfg.normalize_traces if normalize is None else normalize
    if norm:
        out = l2_normalize_traces(out)
    return out


def preprocess_for_tracking(data: jnp.ndarray, x_dist: np.ndarray, dt: float,
                            cfg: TrackingPreprocessConfig = TrackingPreprocessConfig(),
                            dx: float = 8.16):
    """Quasi-static band conditioning for the tracker (reference
    apis/timeLapseImaging.py:74-102).

    Returns ``(track_data (n_track_ch, n_track_t), x_track (meters, ~1 m
    grid), t_stride)``; the caller slices its time axis with ``t_stride``.
    """
    # zero out loud channels, impute dead ones
    loud = jnp.median(jnp.abs(data), axis=-1) > cfg.noise_level
    out = jnp.where(loud[:, None], 0.0, data)
    out = impute_traces(out, empty_trace_mask(out, cfg.empty_threshold))
    out = bandpass_time(out, dt, cfg.flo, cfg.fhi)
    out = out[:, ::cfg.subsample]
    # spatial resample dx -> target_dx (8.16 m -> 1 m is 204/25)
    frac = Fraction(dx / cfg.target_dx).limit_denominator(1000)
    out = resample_poly(out, frac.numerator, frac.denominator, axis=0)
    # index BEFORE converting: np.asarray(x_dist)[0] would pull the whole
    # axis device->host when x_dist is device-resident, for one scalar
    x_track = np.arange(out.shape[0]) * cfg.target_dx + float(np.asarray(x_dist[0]))
    out = bandpass_space(out, cfg.target_dx, cfg.flo_space, cfg.fhi_space)
    return out, x_track, cfg.subsample
