"""Batch workflows as thin callers of the pipelined execution runtime.

Reference counterparts: ImagingWorkflowOneDirectory.imaging
(apis/imaging_workflow.py:23-111 — running average, per-window wall-time
print, periodic intermediate snapshots) and Imaging_for_multiple_date_range
(:132-203 — date folder loop, resume by output existence).

The serial reference loop (read -> preprocess -> compute -> accumulate, one
chunk at a time, skip-date-if-output-exists resume) is replaced by
:mod:`das_diff_veh_tpu.runtime`: a background loader prefetches and stages
the next chunks while the device computes the current one, per-chunk
failures are retried then quarantined instead of aborting the date, resume
is exact (config-hash-keyed manifest + partial-accumulator state, restart
mid-date), and every stage emits Chrome-trace spans.  Accumulation stays on
the main thread in sorted-file order, so results are bit-identical to the
serial loop at any prefetch depth.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from datetime import datetime, timedelta
from typing import List, Optional
from zipfile import BadZipFile as zipfile_BadZipFile

import jax
import numpy as np

from das_diff_veh_tpu.config import PipelineConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.io.readers import DirectoryDataset
from das_diff_veh_tpu.obs import (FlightRecorder, HBMSampler, MetricsSink,
                                  ProfilerWindow, default_registry,
                                  register_memory_gauges, xla_events)
from das_diff_veh_tpu.pipeline.timelapse import process_chunk
from das_diff_veh_tpu.runtime import (ChunkTask, RunManifest, RuntimeConfig,
                                      config_hash, consult_tuner, make_tracer,
                                      run_pipelined)

log = logging.getLogger("das_diff_veh_tpu.workflow")


def date_range(start_date: str, end_date: str, fmt: str = "%Y%m%d") -> List[str]:
    """Inclusive date-string list (reference get_date_string_list,
    modules/utils.py:272-287)."""
    a = datetime.strptime(start_date, fmt)
    b = datetime.strptime(end_date, fmt)
    out = []
    while a <= b:
        out.append(a.strftime(fmt))
        a += timedelta(days=1)
    return out


@dataclass
class DirectoryResult:
    avg_image: Optional[np.ndarray] = None   # sum of per-chunk averages (nvel, nfreq)
    n_vehicles: int = 0                      # isolated vehicles accumulated
    n_chunks: int = 0                        # chunks that contributed windows
    wall_s: float = 0.0
    checkpoints: list = field(default_factory=list)
    quarantined: list = field(default_factory=list)  # QuarantineRecord per bad chunk
    n_retries: int = 0
    n_resumed: int = 0                       # chunks restored from the manifest
    chunks_per_s: float = 0.0                # processed this run (excl. resumed)
    vehicles_per_s: float = 0.0
    complete: bool = True                    # every file settled (not truncated)
    n_degraded: int = 0                      # chunks that ran with health-masked channels
    resumed_quarantined: list = field(default_factory=list)
    """Keys the manifest already held as quarantined at start — known-bad
    chunks this run skipped without re-failing them (the restart contract;
    RuntimeConfig.retry_quarantined=True requeues them instead)."""
    n_requeued: int = 0                      # quarantine records cleared for retry


def _manifest_path(out_dir: str, date: str) -> str:
    return os.path.join(out_dir, f"{date}_manifest.json")


def _state_path(out_dir: str, date: str) -> str:
    return os.path.join(out_dir, f"{date}_state.npz")


def _dataset_fingerprint(dataset) -> dict:
    """Dataset knobs that change output values (hashed into the manifest)."""
    return {k: getattr(dataset, k, None)
            for k in ("ch1", "ch2", "smoothing", "sg_window", "sg_order",
                      "rescale_after", "rescale_value")}


def _run_config_hash(cfg: PipelineConfig, method: str, x_is_channels: bool,
                     dataset) -> str:
    return config_hash(cfg, method, x_is_channels, _dataset_fingerprint(dataset))


def _save_state(out_dir: str, date: str, chash: str,
                acc: Optional[np.ndarray], done: dict) -> None:
    """Atomically checkpoint the partial accumulator + done-chunk set.

    This file is the single source of truth for which chunks the
    accumulator already contains (the JSON manifest is reconciled from it
    on resume), so a crash between the two writes can never double-count or
    drop a chunk: the worst case is re-running work the manifest alone
    would have remembered.
    """
    path = _state_path(out_dir, date)
    os.makedirs(out_dir, exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez(tmp, config_hash=np.str_(chash),
             avg_image=(acc if acc is not None else np.zeros(0)),
             keys=np.array(list(done), dtype=np.str_),
             n_windows=np.array(list(done.values()), dtype=np.int64))
    os.replace(tmp, path)


def _load_state(out_dir: str, date: str, chash: str):
    """Returns (acc, done_dict) or None when absent/stale/other-config."""
    path = _state_path(out_dir, date)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as f:
            if str(f["config_hash"]) != chash:
                return None
            acc = np.asarray(f["avg_image"])
            done = {str(k): int(n) for k, n in zip(f["keys"], f["n_windows"])}
    except (KeyError, OSError, ValueError, zipfile_BadZipFile):
        return None
    return (acc if acc.size else None), done


def run_directory(dataset: DirectoryDataset, cfg: Optional[PipelineConfig] = None,
                  method: str = "xcorr", x_is_channels: bool = True,
                  out_dir: Optional[str] = None, n_min_save: float = 30.0,
                  max_chunks: Optional[int] = None,
                  runtime: Optional[RuntimeConfig] = None,
                  tracer=None, compute_fn=None) -> DirectoryResult:
    """Process every time-window file of one date folder through the
    pipelined runtime.  Chunks with zero isolated vehicles are skipped,
    otherwise the chunk's average image is *summed* into the accumulator
    (the reference's ``avg_image += images.avg_image``,
    imaging_workflow.py:67 — a sum of chunk averages, not a vehicle-weighted
    mean).  The running sum is snapshotted to ``out_dir`` every
    ``n_min_save`` data-minutes worth of chunks (:68-74); with ``out_dir``
    set, a resume manifest + per-chunk state checkpoint is maintained so an
    interrupted run restarts at the first unprocessed chunk.

    ``compute_fn`` swaps the per-chunk computation (default: the full
    ``process_chunk`` imaging pipeline) for any callable
    ``section -> (n_windows, image | None)`` — the extension point for
    other chunk-level workloads riding the same prefetch / quarantine /
    resume machinery.  With ``cfg.health.enabled`` the input-health
    sentinel screens every chunk first (custom compute fns receive the
    sanitized section; a third ``ChannelHealth`` return element, as the
    default path produces, is surfaced the same way) and chunks that
    complete with masked channels are counted/flight-recorded as degraded.
    """
    cfg = cfg if cfg is not None else PipelineConfig()
    runtime = runtime if runtime is not None else RuntimeConfig()
    own_tracer = tracer is None
    obs_cfg = runtime.obs
    tracer = tracer if tracer is not None else make_tracer(
        runtime.trace_path,
        flush_interval_s=obs_cfg.trace_flush_interval_s)
    res = DirectoryResult()
    date = dataset.directory
    t_start = time.perf_counter()

    # --- observability: one registry, a flight ring, optional sink/profiler --
    # Batch runs register into the process-default registry so a serve front
    # (or anything else) in the same process scrapes runtime metrics too;
    # the JSONL sink is the scrapeless equivalent for offline runs.
    # ObsConfig.enabled=False (the bench A/B's bare side) turns the whole
    # stack off: every handle below stays None and run_pipelined sees the
    # same knob, so the instrumented path is genuinely absent, not no-op'd.
    obs_on = obs_cfg.enabled
    registry = flight = sink = profiler = hbm = c_degraded = None
    xla_installed = signals_installed = False

    # everything below may raise (a sink open against a bad path, disk-full
    # checkpoints, compute errors escaping the retry budget); the obs stack
    # and the owned tracer must not leak past this run either way, so even
    # the obs constructors live inside the try
    try:
        if obs_on:
            registry = default_registry()
            flight = FlightRecorder(capacity=obs_cfg.flight_capacity,
                                    out_dir=obs_cfg.flight_dir,
                                    name=f"flight_{date}")
            if obs_cfg.metrics_jsonl:
                sink = MetricsSink(registry, obs_cfg.metrics_jsonl,
                                   obs_cfg.metrics_interval_s)
            if obs_cfg.profile_dir:
                profiler = ProfilerWindow(
                    obs_cfg.profile_dir,
                    start_after=obs_cfg.profile_start_chunk,
                    n_steps=obs_cfg.profile_n_chunks, registry=registry)
            if obs_cfg.xla_events:
                xla_events.install(registry)
                xla_installed = True
            register_memory_gauges(registry)
            c_degraded = registry.counter(
                "das_health_degraded_chunks_total",
                "chunks completed with health-masked channels")
            if obs_cfg.hbm_sample_interval_s > 0:
                hbm = HBMSampler(registry,
                                 interval_s=obs_cfg.hbm_sample_interval_s)
            if obs_cfg.flight_dir is not None:
                signals_installed = flight.install_signal_handlers()
        # --- tuner: apply persisted knob winners BEFORE hashing -----------------
        # (the manifest hash must fingerprint the config that actually runs,
        # so a tuned resume never absorbs default-knob chunks or vice versa)
        cfg, _tuned = consult_tuner(cfg, runtime, registry=registry)
        # --- manifest: load-or-invalidate, restore partial state ----------------
        chash = _run_config_hash(cfg, method, x_is_channels, dataset)
        if flight is not None:
            flight.record("run", date=date, config_hash=chash, method=method,
                          n_files=len(dataset.files))
        manifest: Optional[RunManifest] = None
        acc: Optional[np.ndarray] = None
        done: dict = {}                      # key -> n_windows, in processed order
        if out_dir:
            manifest = RunManifest.load(_manifest_path(out_dir, date))
            if manifest is not None and manifest.config_hash != chash:
                log.warning("%s: config hash changed (%s -> %s); stale outputs "
                            "invalidated, reprocessing", date,
                            manifest.config_hash, chash)
                manifest = None
            st = _load_state(out_dir, date, chash)
            if manifest is not None and st is not None:
                acc, done = st
            if manifest is None:
                manifest = RunManifest(path=_manifest_path(out_dir, date),
                                       config_hash=chash, date=date)
            # reconcile: the state checkpoint is authoritative for done chunks
            # (quarantine records stay manifest-side; a done entry the state
            # never absorbed is dropped and recomputed).  Health provenance
            # rides along: a resumed degraded chunk keeps its record.
            for k in list(manifest.files):
                if manifest.files[k]["status"] == "done" and k not in done:
                    del manifest.files[k]
            for k, n in done.items():
                prior = manifest.files.get(k) or {}
                manifest.mark_done(k, n, health=prior.get("health"))
            # known-bad chunks: skipped on restart (settled), unless the
            # operator asked for a fresh attempt through the retry ladder
            if runtime.retry_quarantined:
                res.n_requeued = manifest.clear_quarantined()
                if res.n_requeued:
                    log.info("%s: retry_quarantined — %d known-bad chunks "
                             "requeued", date, res.n_requeued)
            res.resumed_quarantined = sorted(manifest.quarantined)
            manifest.complete = False
            manifest.save()
            res.n_resumed = sum(1 for p in dataset.files
                                if manifest.is_settled(os.path.basename(p)))
            if res.n_resumed:
                log.info("%s: resuming — %d/%d chunks already settled "
                         "(%d known-bad skipped)", date, res.n_resumed,
                         len(dataset.files), len(res.resumed_quarantined))
        state = {"n_vehicles": sum(done.values()),
                 "n_chunks": sum(1 for n in done.values() if n > 0)}

        # --- build the remaining work list --------------------------------------
        settled = (manifest.is_settled if manifest is not None
                   else (lambda key: False))
        remaining = [(i, p) for i, p in enumerate(dataset.files)
                     if not settled(os.path.basename(p))]
        truncated = max_chunks is not None and len(remaining) > max_chunks
        if truncated:
            remaining = remaining[:max_chunks]

        split_load = hasattr(dataset, "read") and hasattr(dataset, "preprocess")

        def make_task(i: int, path: str) -> ChunkTask:
            # index = absolute position in dataset.files, so snapshot tags and
            # progress logs stay truthful across resumed runs
            key = os.path.basename(path)

            def load() -> DasSection:
                if split_load:
                    with tracer.span("read", file=key):
                        sec = dataset.read(i)
                    with tracer.span("preprocess", file=key):
                        sec = dataset.preprocess(sec, i)
                else:
                    with tracer.span("read", file=key):
                        sec = dataset[i]
                if runtime.device_put:
                    with tracer.span("device_put", file=key):
                        sec = DasSection(jax.device_put(np.asarray(sec.data)),
                                         sec.x, sec.t)
                return sec

            return ChunkTask(index=i, key=key, load=load)

        tasks = [make_task(i, p) for i, p in remaining]

        # --- snapshot cadence (reference n_min_save, imaging_workflow.py:68-74) --
        try:
            interval_s = dataset.time_interval()
        except ValueError:
            interval_s = n_min_save * 60.0
        n_win_save = max(int(n_min_save * 60.0 / interval_s), 1)

        # --- the three runtime callbacks ----------------------------------------
        def _default_compute(section: DasSection):
            chunk = process_chunk(section, cfg, method=method,
                                  x_is_channels=x_is_channels)
            # ONE device_get for everything this consumer needs: the count
            # and the image come back in a single coalesced transfer (which
            # also blocks), instead of the old block_until_ready +
            # per-field int()/np.asarray() pull-per-field epilogue — on the
            # fused path n_windows is a device scalar, so a separate int()
            # here would be a second round trip per chunk
            n, img = jax.device_get((chunk.n_windows, chunk.disp_image))
            n = int(n)
            return (n, (np.asarray(img) if n > 0 else None), chunk.health)

        chunk_fn = compute_fn if compute_fn is not None else _default_compute

        # input-health sentinel for CUSTOM compute fns: the default path
        # screens inside process_chunk (so ChunkResult carries the verdict);
        # a caller-supplied compute_fn gets the same screen applied here —
        # either way exactly one screen per chunk, none when disabled.
        screen_custom = compute_fn is not None and cfg.health.enabled

        def compute(section: DasSection):
            tic = time.perf_counter()
            health = None
            if screen_custom:
                from das_diff_veh_tpu.resilience.health import (
                    PoisonedChunkError, screen_section)
                section, health = screen_section(section, cfg.health,
                                                 tag="runtime")
                if not health.ok(cfg.health):
                    raise PoisonedChunkError(health)
            out = chunk_fn(section)
            n, img = out[0], out[1]
            if len(out) > 2 and out[2] is not None:
                health = out[2]
            return int(n), img, time.perf_counter() - tic, health

        def checkpoint() -> None:
            if out_dir:
                _save_state(out_dir, date, chash, acc, done)  # state first: truth
                manifest.save()

        seq_done = {"n": 0}              # chunks accumulated THIS run

        def accumulate(task: ChunkTask, result) -> None:
            nonlocal acc
            n, img, dt_chunk, health = result
            if n > 0:
                acc = img if acc is None else acc + img
                state["n_vehicles"] += n
                state["n_chunks"] += 1
            degraded = health is not None and health.degraded
            if degraded:
                # degradation-ladder rung 0: the chunk completed with
                # unhealthy channels masked — count it, flight-record it,
                # persist the provenance in the manifest
                res.n_degraded += 1
                if c_degraded is not None:
                    c_degraded.inc()
                if flight is not None:
                    flight.record("health", key=task.key, **health.summary())
                log.warning("chunk %s: degraded — %s", task.key,
                            health.summary())
            done[task.key] = n
            if manifest is not None:
                manifest.mark_done(task.key, n,
                                   health=health.summary() if degraded
                                   else None)
            seq_done["n"] += 1
            log.info("chunk %s (%d/%d): %d windows, %.2fs", task.key,
                     task.index + 1, len(dataset.files), n, dt_chunk)
            tracer.counter("vehicles", total=state["n_vehicles"])
            if profiler is not None:
                profiler.step()         # opens/closes the steady-state window
            if seq_done["n"] % runtime.state_every == 0 or \
                    seq_done["n"] == len(tasks):
                checkpoint()
            if out_dir and acc is not None and \
                    (task.index == 0 or (task.index + 1) % n_win_save == 0):
                _save_snapshot(out_dir, date, acc, state["n_vehicles"],
                               tag=f"win{task.index + 1}")
                res.checkpoints.append(task.index + 1)

        def on_quarantine(rec) -> None:
            if manifest is not None:
                manifest.mark_quarantined(rec.key, rec.stage, rec.error,
                                          rec.retries)
            checkpoint()

        # degradation-ladder rung 2: a compute-dispatch failure (when the
        # fused Pallas gather could actually be in play — the DEFAULT
        # process_chunk path in "auto" mode on a TPU backend; a custom
        # compute_fn's failure says nothing about the gather) demotes it
        # process-wide BEFORE the retry, so the retry and every later
        # chunk trace the serialized fallback.  Poison verdicts are input
        # problems, not code-path problems, and never demote anything.
        from das_diff_veh_tpu.resilience import degrade as _degrade
        from das_diff_veh_tpu.resilience.health import PoisonedChunkError

        def on_stage_failure(stage, key, error, attempt):
            if stage != "compute" or compute_fn is not None \
                    or isinstance(error, PoisonedChunkError):
                return
            if cfg.gather.traj_gather in (None, "auto") and \
                    jax.default_backend() in ("tpu", "axon"):
                lad = _degrade.ladder()
                if flight is not None and lad.flight is None:
                    lad.flight = flight
                lad.note_failure(_degrade.GATHER_FUSED, error)

        n_veh0 = state["n_vehicles"]
        stats = run_pipelined(tasks, compute, accumulate, cfg=runtime,
                              tracer=tracer, on_quarantine=on_quarantine,
                              registry=registry, flight=flight,
                              on_stage_failure=on_stage_failure)

        # --- completion + result ---------------------------------------------
        res.avg_image = acc
        res.n_vehicles = state["n_vehicles"]
        res.n_chunks = state["n_chunks"]
        res.quarantined = list(stats.quarantined)
        res.n_retries = stats.n_retries
        res.complete = not truncated
        if manifest is not None:
            res.complete = res.complete and all(
                manifest.is_settled(os.path.basename(p)) for p in dataset.files)
            manifest.complete = res.complete
            checkpoint()
        res.wall_s = time.perf_counter() - t_start
        n_processed = stats.n_done + len(stats.quarantined)
        if stats.wall_s > 0 and n_processed:
            res.chunks_per_s = n_processed / stats.wall_s
            res.vehicles_per_s = (state["n_vehicles"] - n_veh0) / stats.wall_s
        return res
    finally:
        if profiler is not None:
            profiler.close()        # stop a window the run ended inside
        if hbm is not None:
            hbm.close()
        if sink is not None:
            sink.close()            # final snapshot line
        if xla_installed:
            xla_events.uninstall(registry)
        if signals_installed:
            flight.uninstall_signal_handlers()
        if own_tracer:
            tracer.close()


def _save_snapshot(out_dir: str, date: str, avg_image: np.ndarray,
                   n_vehicles: int, tag: str = "final") -> str:
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"{date}_{tag}.npz")
    tmp = path + ".tmp.npz"          # atomic: resume reads this file unguarded
    np.savez(tmp, avg_image=avg_image, n_vehicles=n_vehicles)
    os.replace(tmp, path)
    return path


def run_date_range(root: str, start_date: str, end_date: str,
                   cfg: Optional[PipelineConfig] = None, method: str = "xcorr",
                   out_dir: str = "results", n_min_save: float = 30.0,
                   max_chunks: Optional[int] = None, x_is_channels: bool = True,
                   runtime: Optional[RuntimeConfig] = None,
                   **dataset_kwargs) -> dict:
    """Run every date folder in [start_date, end_date] through the runtime.

    Resume is manifest-driven: a date is skipped only when its manifest says
    the run completed under the *same* config hash (or, for pre-manifest
    outputs, when the final .npz exists) — and skipped dates still report
    their ``n_vehicles`` from the existing final .npz so resumed and fresh
    runs are comparable.  A config change invalidates stale outputs and
    reprocesses; an interrupted date resumes mid-directory.
    """
    cfg = cfg if cfg is not None else PipelineConfig()
    runtime = runtime if runtime is not None else RuntimeConfig()
    tracer = make_tracer(runtime.trace_path,
                         flush_interval_s=runtime.obs.trace_flush_interval_s)
    summary = {}
    try:
        for date in date_range(start_date, end_date):
            folder = os.path.join(root, date)
            final_path = os.path.join(out_dir, f"{date}_final.npz")
            if not os.path.isdir(folder):
                log.info("%s: no data folder, skipping", date)
                continue
            dataset = DirectoryDataset(directory=date, root=root,
                                       **dataset_kwargs)
            chash = _run_config_hash(cfg, method, x_is_channels, dataset)
            man = RunManifest.load(_manifest_path(out_dir, date))
            man_done = man is not None and man.config_hash == chash and man.complete
            if os.path.exists(final_path) and (man is None or man_done):
                # completed under this config (or a legacy pre-manifest run)
                try:
                    with np.load(final_path) as f:
                        n_veh = int(f["n_vehicles"])
                except (KeyError, OSError, ValueError, zipfile_BadZipFile) as e:
                    log.warning("%s: final output unreadable (%s); "
                                "reprocessing the date", date, e)
                else:
                    log.info("%s: complete output exists, skipping (resume)",
                             date)
                    summary[date] = {"skipped": True, "n_vehicles": n_veh}
                    continue
            res = run_directory(dataset, cfg, method=method, out_dir=out_dir,
                                n_min_save=n_min_save, max_chunks=max_chunks,
                                x_is_channels=x_is_channels, runtime=runtime,
                                tracer=tracer)
            if res.complete and res.avg_image is not None:
                _save_snapshot(out_dir, date, res.avg_image, res.n_vehicles)
            summary[date] = {"n_vehicles": res.n_vehicles,
                             "n_chunks": res.n_chunks,
                             "wall_s": round(res.wall_s, 2),
                             "chunks_per_s": round(res.chunks_per_s, 3),
                             "n_quarantined": len(res.quarantined),
                             "n_degraded": res.n_degraded,
                             "n_resumed": res.n_resumed,
                             "complete": res.complete}
            log.info("%s: %s", date, json.dumps(summary[date]))
    finally:
        tracer.close()
    return summary
