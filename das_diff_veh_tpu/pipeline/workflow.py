"""Batch workflows: per-directory chunk loops and date-range batches, with
artifact checkpointing and skip-if-exists resume.

Reference counterparts: ImagingWorkflowOneDirectory.imaging
(apis/imaging_workflow.py:23-111 — running average, per-window wall-time
print, periodic intermediate snapshots) and Imaging_for_multiple_date_range
(:132-203 — date folder loop, resume by output existence).
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from datetime import datetime, timedelta
from typing import List, Optional

import numpy as np
import jax

from das_diff_veh_tpu.config import PipelineConfig
from das_diff_veh_tpu.io.readers import DirectoryDataset
from das_diff_veh_tpu.pipeline.timelapse import process_chunk

log = logging.getLogger("das_diff_veh_tpu.workflow")


def date_range(start_date: str, end_date: str, fmt: str = "%Y%m%d") -> List[str]:
    """Inclusive date-string list (reference get_date_string_list,
    modules/utils.py:272-287)."""
    a = datetime.strptime(start_date, fmt)
    b = datetime.strptime(end_date, fmt)
    out = []
    while a <= b:
        out.append(a.strftime(fmt))
        a += timedelta(days=1)
    return out


@dataclass
class DirectoryResult:
    avg_image: Optional[np.ndarray] = None   # sum of per-chunk averages (nvel, nfreq)
    n_vehicles: int = 0                      # isolated vehicles accumulated
    n_chunks: int = 0
    wall_s: float = 0.0
    checkpoints: list = field(default_factory=list)


def run_directory(dataset: DirectoryDataset, cfg: PipelineConfig = PipelineConfig(),
                  method: str = "xcorr", x_is_channels: bool = True,
                  out_dir: Optional[str] = None, n_min_save: float = 30.0,
                  max_chunks: Optional[int] = None) -> DirectoryResult:
    """Process every time-window file of one date folder; chunks with zero
    isolated vehicles are skipped, otherwise the chunk's average image is
    *summed* into the accumulator (the reference's ``avg_image +=
    images.avg_image``, imaging_workflow.py:67 — a sum of chunk averages, not
    a vehicle-weighted mean).  The running sum is snapshotted to ``out_dir``
    every ``n_min_save`` data-minutes worth of chunks (:68-74)."""
    res = DirectoryResult()
    acc = None
    try:
        interval_s = dataset.time_interval()
    except ValueError:
        interval_s = n_min_save * 60.0
    n_win_save = max(int(n_min_save * 60.0 / interval_s), 1)
    t_start = time.perf_counter()
    for k, section in enumerate(dataset):
        if max_chunks is not None and k >= max_chunks:
            break
        tic = time.perf_counter()
        chunk = process_chunk(section, cfg, method=method,
                              x_is_channels=x_is_channels)
        jax.block_until_ready(chunk.disp_image)
        if chunk.n_windows == 0:
            continue
        img = np.asarray(chunk.disp_image)
        acc = img if acc is None else acc + img
        res.n_vehicles += chunk.n_windows
        res.n_chunks += 1
        log.info("chunk %d/%d: %d windows, %.2fs", k + 1, len(dataset),
                 chunk.n_windows, time.perf_counter() - tic)
        if out_dir and (k == 0 or (k + 1) % n_win_save == 0):
            _save_snapshot(out_dir, dataset.directory, acc, res.n_vehicles,
                           tag=f"win{k + 1}")
            res.checkpoints.append(k + 1)
    res.wall_s = time.perf_counter() - t_start
    res.avg_image = acc
    return res


def _save_snapshot(out_dir: str, date: str, avg_image: np.ndarray,
                   n_vehicles: int, tag: str = "final") -> str:
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"{date}_{tag}.npz")
    np.savez(path, avg_image=avg_image, n_vehicles=n_vehicles)
    return path


def run_date_range(root: str, start_date: str, end_date: str,
                   cfg: PipelineConfig = PipelineConfig(), method: str = "xcorr",
                   out_dir: str = "results", n_min_save: float = 30.0,
                   max_chunks: Optional[int] = None, x_is_channels: bool = True,
                   **dataset_kwargs) -> dict:
    """Run every date folder in [start_date, end_date]; resume by skipping
    dates whose final output exists (reference imaging_workflow.py:189-191)."""
    summary = {}
    for date in date_range(start_date, end_date):
        folder = os.path.join(root, date)
        final_path = os.path.join(out_dir, f"{date}_final.npz")
        if not os.path.isdir(folder):
            log.info("%s: no data folder, skipping", date)
            continue
        if os.path.exists(final_path):
            log.info("%s: output exists, skipping (resume)", date)
            summary[date] = {"skipped": True}
            continue
        dataset = DirectoryDataset(directory=date, root=root, **dataset_kwargs)
        res = run_directory(dataset, cfg, method=method, out_dir=out_dir,
                            n_min_save=n_min_save, max_chunks=max_chunks,
                            x_is_channels=x_is_channels)
        if res.avg_image is not None:
            _save_snapshot(out_dir, date, res.avg_image, res.n_vehicles)
        summary[date] = {"n_vehicles": res.n_vehicles, "n_chunks": res.n_chunks,
                         "wall_s": round(res.wall_s, 2)}
        log.info("%s: %s", date, json.dumps(summary[date]))
    return summary
