"""Multi-device execution: meshes, sharded window stacking, collectives.

The reference is single-process NumPy (SURVEY.md §5: no distributed backend);
its scaling unit is the per-vehicle window.  Here the window axis shards over
a ``jax.sharding.Mesh`` — each device builds its local gathers and the masked
mean stack turns into an XLA all-reduce inserted by pjit.
"""

from das_diff_veh_tpu.parallel.allpairs import sharded_all_pairs_peak  # noqa: F401
from das_diff_veh_tpu.parallel.distributed import (  # noqa: F401
    cluster_spec_from_env, initialize_cluster, ring_perm)
from das_diff_veh_tpu.parallel.mesh import make_mesh, pad_batch  # noqa: F401
from das_diff_veh_tpu.parallel.stack import sharded_stack_pipeline  # noqa: F401
