"""Device-mesh helpers.

One logical axis for now — ``"win"`` (data parallelism over per-vehicle
windows, the framework's natural scaling unit; BASELINE.md config 3).  The
helpers accept any device count: the driver dry-runs with N virtual CPU
devices (``xla_force_host_platform_device_count``), CI uses 8, hardware uses
whatever the slice provides.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

from das_diff_veh_tpu.core.section import WindowBatch


def make_mesh(n_devices: int | None = None, axis: str = "win") -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n]), (axis,))


def pad_batch(batch: WindowBatch, multiple: int) -> WindowBatch:
    """Pad the window axis to a device-count multiple with invalid slots.

    Masked stacking ignores padding, so results are unchanged; shapes become
    shardable without ragged remainders.
    """
    b = batch.max_windows
    pad = (-b) % multiple
    if pad == 0:
        return batch
    def pad0(a):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jax.numpy.pad(a, widths)
    return dataclasses.replace(
        batch,
        data=pad0(batch.data), t=pad0(batch.t),
        traj_x=pad0(batch.traj_x), traj_t=pad0(batch.traj_t),
        valid=pad0(batch.valid),
    )
