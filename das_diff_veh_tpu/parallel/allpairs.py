"""Multi-device all-pairs cross-correlation: source rows sharded over the mesh.

Scales the BASELINE config-4 workload (synthetic 10k-channel ambient-noise
all-pairs, the generalization of the reference's XCORR_vshot loop,
modules/utils.py:289-314) across a device mesh.  The decomposition follows
the scaling-book recipe: the (nch x nch) pair space splits along the
*source-row* axis — each device owns ``nch / n_devices`` source rows and
correlates them against the full receiver set, so the work is embarrassingly
parallel and the only cross-device traffic is the initial replicated input
broadcast; no collectives run in the loop (output stays source-sharded for
any downstream reduction to contract over ICI).

Inside each shard the single-device streaming machinery is reused unchanged
(``ops.pallas_xcorr``: source-chunk ``lax.map`` + Pallas spectra-tile kernel
with window-block grid streaming on TPU, exact-f32 einsum elsewhere), so
per-device memory stays bounded regardless of channel count AND record
length.  The receiver-side kernel preparation (planar split + tile padding
of the replicated full spectra set — the largest array of the 10k-channel
config) happens once per device, outside the source-chunk loop, and the
window axis is never zero-padded or copied at all (ragged window tails are
masked inside the kernel).

``bench.py`` executes this path with ``use_pallas=True`` on the real chip
(BENCH ``pallas_sharded_*`` entries, with parity against the unsharded
kernel); the CI tests exercise the same code in interpret mode on the
8-device CPU mesh.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
try:                                    # jax >= 0.8
    from jax import shard_map
    _NO_VMA_CHECK = {"check_vma": False}
except ImportError:                     # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
    _NO_VMA_CHECK = {"check_rep": False}    # same knob, pre-0.8 spelling
from jax.sharding import Mesh, PartitionSpec as P

from das_diff_veh_tpu.ops.pallas_xcorr import (_decide_pallas,
                                               _resolve_win_block,
                                               _window_spectra,
                                               peak_from_spectra)


def sharded_all_pairs_peak(data: jnp.ndarray, wlen: int, mesh: Mesh, *,
                           axis: str = "win", overlap_ratio: float = 0.5,
                           src_chunk: int = 64,
                           use_pallas: bool | None = None,
                           interpret: bool = False,
                           win_block: int | None = None) -> jnp.ndarray:
    """Per-pair peak |xcorr| (nch, nch) computed with source rows sharded
    over ``mesh``'s ``axis``.  Matches ``xcorr_all_pairs_peak`` exactly
    (parity-tested on the CI 8-device CPU mesh).

    ``data``: (nch, nt) replicated; rows are zero-padded to a device-count
    multiple and the padding is trimmed from the output.
    """
    _resolve_win_block(1, win_block)    # validate before any device work
    nch = data.shape[0]
    n_dev = mesh.shape[axis]
    pad = (-nch) % n_dev
    dpad = jnp.pad(data, ((0, pad), (0, 0)))
    # decide on the PER-DEVICE workload: each shard correlates nch/n_dev
    # source rows (not nch) against the full set, and the kernel-vs-einsum
    # crossover tracks the smaller source-tile axis
    use_p = _decide_pallas((nch + pad) // n_dev, use_pallas)
    # windowed spectra once, outside the shard: each device then receives its
    # source-row slice plus the replicated full set (recomputing inside the
    # shard would run the full-set rfft n_dev times)
    wf = _window_spectra(dpad, wlen, overlap_ratio)

    # vma/rep checking off: the body is collective-free (each device works on
    # its own source rows), and jax's varying-mesh-axes validation cannot see
    # through pallas_call's out_shape (it would demand explicit vma tags)
    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis, None, None), P(None, None, None)),
             out_specs=P(axis, None), **_NO_VMA_CHECK)
    def run(wf_src, wf_all):
        return peak_from_spectra(wf_src, wf_all, wlen, src_chunk, use_p,
                                 interpret, win_block=win_block)

    out = run(wf, wf)
    return out[:nch, :nch]
