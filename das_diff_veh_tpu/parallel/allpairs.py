"""Multi-device all-pairs cross-correlation: a ring pipeline over the mesh.

Scales the BASELINE config-4 workload (synthetic 10k-channel ambient-noise
all-pairs, the generalization of the reference's XCORR_vshot loop,
modules/utils.py:289-314) across a device mesh with O(nch/D) per-device
memory on BOTH sides of the pair space.

The pre-ring decomposition sharded only source rows and replicated the full
windowed-spectra set on every device — the largest array of the 10k-channel
config, so per-device memory stayed O(nch) and the engine could not scale
past one chip's HBM.  The ring decomposition (the ring-attention recipe
applied to seismic interferometry) removes that ceiling:

- each device keeps its own ``nch/D`` *source* rows AND only ``nch/D``
  *receiver* spectra — nothing receiver-sided is ever materialized at full
  width on any device (asserted structurally on the traced jaxpr by
  tests/test_parallel.py, not just benchmarked);
- inside a ``shard_map``, D steps correlate the resident source rows against
  the currently-held receiver shard while ``lax.ppermute`` rotates the
  shards one neighbor hop around the mesh (``distributed.ring_perm``);
- the rotation is double-buffered: step k+1's ppermute is issued *before*
  step k's correlation, so XLA's latency-hiding scheduler overlaps the ICI
  transfer with the Pallas compute.  The overlap ceiling is
  ``t_comm/t_compute`` (docs/PERF.md §ring); at all-pairs arithmetic
  intensity the compute side dominates for any realistic shard size.

Inside each (device, step) the single-device streaming machinery is reused
unchanged (``ops.pallas_xcorr``: source-chunk ``lax.map`` + Pallas
spectra-tile kernel with window-block grid streaming on TPU, exact-f32
einsum elsewhere, fused irfft+lag-max finish on the kernel path), so
per-device memory stays bounded regardless of channel count AND record
length.  Each step's receiver-side kernel preparation (planar split + tile
padding) touches only the O(nch/D) resident shard.

A channel count that does not divide the mesh is zero-padded to the next
device multiple before windowing; padded rows ride the ring like real ones
(their peaks land in rows/cols that are trimmed from the output), so every
shard stays the same static shape — no ragged collective.

``bench.py`` executes this path with ``use_pallas=True`` on the real chip
(BENCH ``ring_*`` entries, with parity against the unsharded kernel and a
replicated-vs-ring per-device peak-memory comparison); the CI tests exercise
the same code in interpret mode on the 8-device CPU mesh, including the
1-device degenerate ring and ragged channel counts.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

try:                                    # jax >= 0.8
    from jax import shard_map
    _NO_VMA_CHECK = {"check_vma": False}
except ImportError:                     # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
    _NO_VMA_CHECK = {"check_rep": False}    # same knob, pre-0.8 spelling
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from das_diff_veh_tpu.config import RingConfig
from das_diff_veh_tpu.obs.profiling import register_memory_gauges
from das_diff_veh_tpu.obs.registry import MetricsRegistry, default_registry
from das_diff_veh_tpu.ops.pallas_xcorr import (_decide_pallas,
                                               _resolve_lagmax_block,
                                               _resolve_win_block,
                                               _window_spectra,
                                               peak_from_spectra)
from das_diff_veh_tpu.parallel.distributed import ring_perm
from das_diff_veh_tpu.resilience import faults


@partial(jax.jit, static_argnames=("wlen", "overlap_ratio", "spec"))
def _sharded_window_spectra(data, wlen: int, overlap_ratio: float, spec):
    """Windowed spectra with their channel rows pinned to ``spec`` (the
    mesh's source-row sharding).  Jitted so an *eager* caller never
    materializes the full (nch, nwin, nf) set on one device — GSPMD places
    the row-parallel window/rfft work shard-by-shard under the constraint;
    under an outer jit the constraint simply propagates.  Without this,
    the O(nch/D) per-device claim would only hold for jitted callers."""
    return lax.with_sharding_constraint(
        _window_spectra(data, wlen, overlap_ratio), spec)


def _observe_ring_build(mesh: Mesh, ring: RingConfig,
                        registry: MetricsRegistry | None) -> None:
    """Register this engine's host-side observability: a build counter
    labeled by decomposition mode (this code runs at trace time under jit,
    so it counts ring *programs built*, not loop steps — the in-loop truth
    is the profiler's job), the mesh size, and lazy per-device
    ``memory_stats()`` gauges (the bench.py peak-bytes pattern, scrapable
    while a ring program runs)."""
    reg = registry if registry is not None else default_registry()
    reg.counter("das_ring_builds_total",
                "all-pairs ring programs traced, by decomposition",
                labels=("mode",)).labels(mode=ring.mode).inc()
    reg.gauge("das_ring_devices", "mesh size of the last ring build").set(
        int(mesh.devices.size))
    register_memory_gauges(reg, list(mesh.devices.flat))


def sharded_all_pairs_peak(data: jnp.ndarray, wlen: int, mesh: Mesh, *,
                           axis: str = "win", overlap_ratio: float = 0.5,
                           src_chunk: int = 64,
                           use_pallas: bool | None = None,
                           interpret: bool = False,
                           win_block: int | None = None,
                           ring: RingConfig | None = None,
                           registry: MetricsRegistry | None = None) -> jnp.ndarray:
    """Per-pair peak |xcorr| (nch, nch) computed as a ring pipeline over
    ``mesh``'s ``axis``.  On the kernel path this matches
    ``xcorr_all_pairs_peak`` bit-for-bit — the in-kernel window
    accumulation order is fixed regardless of shard shape (parity-tested
    on the CI 8-device CPU mesh, ragged nch included); the einsum fallback
    agrees to dot_general reduction-order tolerance (~1e-7 relative).

    ``data``: (nch, nt) replicated; rows are zero-padded to a device-count
    multiple and the padding is trimmed from the output.  ``ring`` selects
    the decomposition (``RingConfig.mode``): the default ``"ring"`` keeps
    O(nch/D) receiver spectra per device; ``"replicated"`` restores the
    pre-ring full-set broadcast for A/B memory benchmarking.
    """
    ring = RingConfig() if ring is None else ring
    if ring.mode not in ("ring", "replicated"):
        raise ValueError(f"RingConfig.mode must be 'ring' or 'replicated', "
                         f"got {ring.mode!r}")
    # chaos site: a simulated ICI/collective failure on the ring path (the
    # degradation ladder's resilient_all_pairs_peak catches it and falls
    # back to the replicated layout; see resilience/degrade.py)
    if ring.mode == "ring":
        faults.fire("parallel.ring")
    _observe_ring_build(mesh, ring, registry)
    # validate before any device work (per-call override or the config knob)
    _resolve_win_block(1, win_block if win_block is not None
                       else ring.win_block)
    _resolve_lagmax_block(1, False, ring.lagmax_block)
    nch = data.shape[0]
    n_dev = mesh.shape[axis]
    pad = (-nch) % n_dev
    dpad = jnp.pad(data, ((0, pad), (0, 0)))
    shard_rows = (nch + pad) // n_dev
    # decide on the PER-DEVICE workload: each shard correlates nch/n_dev
    # source rows against nch/n_dev-row receiver shards, and the
    # kernel-vs-einsum crossover tracks the smaller tile axis
    use_p = _decide_pallas(shard_rows, use_pallas)
    # windowed spectra once, outside the shard (recomputing inside would run
    # the rfft n_dev times), with the row sharding constrained to the mesh —
    # the full set never lands on any single device, eager callers included
    wf = _sharded_window_spectra(dpad, wlen, overlap_ratio,
                                 NamedSharding(mesh, P(axis, None, None)))

    # per-call win_block wins over the RingConfig knob (the tuner writes the
    # config field; explicit callers keep their override)
    kernel_kw = dict(win_block=win_block if win_block is not None
                     else ring.win_block,
                     lagmax_block=ring.lagmax_block,
                     lag_tile_max=ring.lag_tile_max,
                     precision=ring.precision)

    if ring.mode == "replicated":
        # pre-ring layout: full receiver set broadcast to every device, no
        # collectives in the loop.  O(nch) per-device memory — kept for the
        # bench's replicated-vs-ring peak-bytes comparison and for
        # single-chip meshes where the "broadcast" is the resident copy.
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(axis, None, None), P(None, None, None)),
                 out_specs=P(axis, None), **_NO_VMA_CHECK)
        def run_replicated(wf_src, wf_all):
            return peak_from_spectra(wf_src, wf_all, wlen, src_chunk, use_p,
                                     interpret, **kernel_kw)

        return run_replicated(wf, wf)[:nch, :nch]

    perm = ring_perm(n_dev)

    # vma/rep checking off: the body's only collective is the neighbor
    # ppermute (uniform across devices), and jax's varying-mesh-axes
    # validation cannot see through pallas_call's out_shape (it would
    # demand explicit vma tags)
    @partial(shard_map, mesh=mesh, in_specs=(P(axis, None, None),),
             out_specs=P(axis, None), **_NO_VMA_CHECK)
    def run_ring(wf_local):
        me = lax.axis_index(axis)
        m = wf_local.shape[0]

        # one traced step body (fori_loop, not a Python unroll): program
        # size stays O(1) in the device count — a pod-scale mesh would
        # otherwise inline D copies of the whole kernel pipeline.  The
        # trade: every step rotates, so the final step sends one shard
        # nobody reads (overlapped with its compute; negligible vs a
        # D-times-larger HLO).
        def step(k, carry):
            rcv, out = carry
            if ring.double_buffer:
                # issue the rotation BEFORE this step's correlation: the
                # two depend only on rcv, so XLA overlaps the collective-
                # permute-start/done pair with the compute between them
                nxt = lax.ppermute(rcv, axis, perm)
                blk = peak_from_spectra(wf_local, rcv, wlen, src_chunk,
                                        use_p, interpret, **kernel_kw)
            else:
                # profiling mode: gate the rotation on the finished
                # correlation so transfer and compute truly serialize —
                # without the barrier both orderings trace to the same
                # dependency graph and the scheduler overlaps them anyway
                blk = peak_from_spectra(wf_local, rcv, wlen, src_chunk,
                                        use_p, interpret, **kernel_kw)
                gated, _ = lax.optimization_barrier((rcv, blk))
                nxt = lax.ppermute(gated, axis, perm)
            # the shard held at step k started on device (me + k) % D, so
            # its peaks are the output columns of that device's global rows
            col = ((me + k) % n_dev) * shard_rows
            out = lax.dynamic_update_slice(out, blk,
                                           (jnp.zeros_like(col), col))
            return nxt, out

        out0 = jnp.zeros((m, n_dev * shard_rows), jnp.float32)
        _, out = lax.fori_loop(0, n_dev, step, (wf_local, out0))
        return out

    return run_ring(wf)[:nch, :nch]
