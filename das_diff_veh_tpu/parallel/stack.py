"""Sharded window-stack pipeline: windows -> gathers -> stacked dispersion.

The scaling recipe (jax-ml scaling-book style): pick a mesh, annotate the
window axis of the batch with ``NamedSharding(mesh, P("win"))``, jit the pure
pipeline, and let XLA insert the all-reduce for the masked-mean stack.  No
hand-written collectives — the per-window gather builds are embarrassingly
parallel and the only cross-device traffic is the (nch_out, wlen) /
(nvel, nfreq) reductions, which ride ICI.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from das_diff_veh_tpu.config import DispersionConfig, GatherConfig
from das_diff_veh_tpu.core.section import WindowBatch
from das_diff_veh_tpu.models import vsg as V
from das_diff_veh_tpu.parallel.mesh import pad_batch


def batch_shardings(mesh: Mesh, axis: str = "win") -> WindowBatch:
    """Sharding tree for a WindowBatch: window axis sharded, shared x axis
    replicated."""
    win = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    return WindowBatch(data=win, x=rep, t=win, traj_x=win, traj_t=win, valid=win)


def shard_windows(batch: WindowBatch, mesh: Mesh, axis: str = "win") -> WindowBatch:
    """Pad to the device count and place the batch window-sharded on the mesh."""
    batch = pad_batch(batch, mesh.devices.size)
    return jax.tree.map(jax.device_put, batch, batch_shardings(mesh, axis))


@functools.lru_cache(maxsize=64)
def _compiled_pipeline(mesh: Mesh, axis: str, g: V.VsgGeometry,
                       gather_cfg: GatherConfig, disp_cfg: DispersionConfig,
                       offsets_key: tuple, dx: float,
                       disp_start_x: float, disp_end_x: float):
    """Jit cache keyed on the static configuration: repeated calls with the
    same geometry reuse one compiled program instead of retracing a fresh
    closure every time."""
    offsets = np.asarray(offsets_key)
    rep = NamedSharding(mesh, P())

    def _pipeline(b: WindowBatch):
        gathers = V.build_gather_batch(b, g, gather_cfg)
        stack = V.stack_gathers(gathers, b.valid)      # masked mean -> all-reduce
        img = V.gather_disp_image(stack, offsets, g.dt, dx, disp_cfg,
                                  disp_start_x, disp_end_x)
        return stack, img

    return jax.jit(_pipeline, in_shardings=(batch_shardings(mesh, axis),),
                   out_shardings=(rep, rep))


def sharded_stack_pipeline(batch: WindowBatch, g: V.VsgGeometry, offsets,
                           mesh: Mesh, gather_cfg: GatherConfig = GatherConfig(),
                           disp_cfg: DispersionConfig = DispersionConfig(),
                           disp_start_x: float = -150.0, disp_end_x: float = 0.0,
                           dx: float = 8.16, axis: str = "win"):
    """Build all gathers (window-sharded), stack, and image — one jit program.

    Returns ``(stacked_gather (nch_out, wlen), disp_image (nvel, nfreq))``,
    both replicated.  ``batch`` should come from :func:`shard_windows`.
    """
    run = _compiled_pipeline(mesh, axis, g, gather_cfg, disp_cfg,
                             tuple(float(o) for o in np.asarray(offsets)),
                             dx, disp_start_x, disp_end_x)
    return run(batch)
