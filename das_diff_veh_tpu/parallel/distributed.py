"""Multi-host (multi-process) cluster bootstrap over DCN.

The reference's only cross-process machinery is evodcinv's ``workers=-1``
multiprocessing pool (SURVEY.md §5); it has no distributed backend.  The
TPU-native equivalent: each host runs one process, ``jax.distributed``
connects them over DCN, and ``jax.devices()`` then spans every chip in the
slice — all the mesh-sharded paths in this package (``sharded_stack_pipeline``,
``sharded_all_pairs_peak``, ``invert_multirun(mesh=...)``) work unchanged
because they are written against ``jax.sharding.Mesh``, not a device count.
Collectives ride ICI within a host's chips and DCN across hosts; shardings in
this package keep the heavy traffic (window/source-row axes) intra-host.

On Cloud TPU slices ``jax.distributed.initialize()`` autodetects everything
from the metadata server; on other clusters the coordinator triplet comes
from the environment (the same convention torch.distributed/NCCL deployments
use, so existing launchers port directly).
"""

from __future__ import annotations

import os
from typing import Optional


def ring_perm(n_dev: int, shift: int = 1) -> list:
    """``lax.ppermute`` permutation for one ring rotation over ``n_dev``
    mesh slots: device j sends to device ``(j - shift) % n_dev``, so after
    one application device i holds the shard that started on device
    ``(i + shift) % n_dev``.

    Centralized here because the rotation direction is a *placement*
    concern: ``make_mesh`` lays devices out in ``jax.devices()`` order, so
    on a TPU slice consecutive mesh slots are ICI neighbors within a host
    and the single cross-host hop rides DCN — the same nearest-neighbor
    traffic pattern whether the mesh spans one host or many
    (``initialize_cluster`` above).  Every ring step moves each shard
    exactly one hop; no step ever needs all-to-all bandwidth.
    """
    return [(j, (j - shift) % n_dev) for j in range(n_dev)]


def cluster_spec_from_env(env: Optional[dict] = None):
    """(coordinator_address, num_processes, process_id) from the environment.

    Recognized variables, in precedence order:

    - ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``
      (jax's own convention);
    - ``MASTER_ADDR``+``MASTER_PORT`` / ``WORLD_SIZE`` / ``RANK`` (the
      torch.distributed convention most cluster launchers already export).

    Returns ``(None, None, None)`` when nothing is set — callers then fall
    through to jax's TPU-metadata autodetection.
    """
    e = os.environ if env is None else env
    addr = e.get("JAX_COORDINATOR_ADDRESS")
    if addr is None and e.get("MASTER_ADDR"):
        addr = e["MASTER_ADDR"] + ":" + e.get("MASTER_PORT", "8476")
    nproc = e.get("JAX_NUM_PROCESSES", e.get("WORLD_SIZE"))
    pid = e.get("JAX_PROCESS_ID", e.get("RANK"))
    return (addr,
            int(nproc) if nproc is not None else None,
            int(pid) if pid is not None else None)


def initialize_cluster(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> bool:
    """Connect this process to the jax cluster; no-op for single-process runs.

    Explicit arguments win; otherwise the environment (``cluster_spec_from_env``)
    is consulted; with neither, on TPU pods ``jax.distributed.initialize()``
    autodetects from platform metadata, and on a plain single host this
    function returns ``False`` without touching jax state (so library code
    may call it unconditionally).

    Returns True when a multi-process runtime was initialized.
    """
    import jax

    env_addr, env_n, env_pid = cluster_spec_from_env()
    addr = coordinator_address or env_addr
    n = num_processes if num_processes is not None else env_n
    pid = process_id if process_id is not None else env_pid
    if addr is None and n is None and pid is None:
        # bare single host unless the TPU metadata server says otherwise
        in_pod = bool(os.environ.get("TPU_WORKER_HOSTNAMES"))
        if not in_pod:
            return False
        jax.distributed.initialize()
        return True
    if addr is None or n is None or pid is None:
        # partial spec (e.g. a stale MASTER_ADDR from a launcher wrapper
        # with no WORLD_SIZE/RANK): initializing would block on a
        # nonexistent coordinator — honor the safe-to-call-unconditionally
        # contract by warning and staying single-process
        import logging

        logging.getLogger(__name__).warning(
            "incomplete cluster spec (address=%s num_processes=%s "
            "process_id=%s); staying single-process", addr, n, pid)
        return False
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=n, process_id=pid)
    return True
