"""das_diff_veh_tpu — TPU-native framework for vehicle-induced DAS seismic imaging.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
``NohPei/das_diff_veh`` codebase (near-surface characterization from
vehicle-induced surface waves on DAS fiber):

- DAS data I/O (npz + native SEG-Y parser) and preprocessing
- Kalman-filter vehicle tracking (``lax.scan`` over channels)
- Surface-wave window selection + trajectory-aware muting (static-shape batches)
- Virtual-shot-gather interferometry (batched circular FFT cross-correlation)
- Phase-velocity (f-v) dispersion imaging (fk bilinear sampling + phase-shift
  slant stack, selectable via ``DispersionConfig.method``)
- Vehicle speed/weight classification and bootstrap dispersion uncertainty
- Differentiable Rayleigh-wave forward model + optax/PSO Vs inversion
- Multi-device sharding of the window axis over ``jax.sharding.Mesh``
  (``parallel/``) for the time-lapse stacking path

All compute kernels are pure functions over pytrees; a NumPy/SciPy oracle
(``das_diff_veh_tpu.oracle``) mirrors the reference semantics for equivalence
testing and speedup measurement.
"""

__version__ = "0.1.0"

from das_diff_veh_tpu import config  # noqa: F401
from das_diff_veh_tpu.core.section import DasSection  # noqa: F401
