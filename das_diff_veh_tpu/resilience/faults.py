"""Deterministic, seeded fault injection: named sites, zero overhead off.

PRs 2/3/6 built the machinery that is supposed to survive bad inputs —
per-chunk retry/quarantine, admission shedding, the flight recorder — but
nothing ever *exercised* it against realistic interrogator faults.  This
module is the chaos half of that contract: a :class:`FaultPlan` names which
fault fires at which **site** (a string like ``"io.read"``) for which
**key** (a chunk filename, a request index), and the production code paths
carry one-line ``faults.fire(site, key)`` / ``faults.corrupt(site, key,
data)`` hooks at those sites.

Sites wired through the codebase (grep for the literal string):

- ``io.read``      — loader failure (:func:`io.readers.read_npz_section`);
- ``io.slow``      — slow read latency (same place);
- ``io.corrupt``   — NaN/Inf bursts, dead or clipped channels injected into
  the loaded waterfall (same place, after decode AND after the ch1/ch2 /
  taper cuts, so channel indices match what the pipeline sees);
- ``runtime.compute`` — per-chunk compute dispatch failure
  (``runtime/executor.run_pipelined``);
- ``runtime.slow`` — slow-chunk latency in the compute stage (same place);
- ``serve.dispatch`` — per-request dispatch failure on the serve
  dispatcher thread (``serve/engine._execute``);
- ``parallel.ring`` — multi-chip ring dispatch failure
  (``parallel/allpairs.sharded_all_pairs_peak``), the trigger for the
  ring -> replicated degradation rung.

Everything is **off by default and free when off**: the module-level hooks
read one global and return (``_ACTIVE is None`` — no allocation, no lock).
Injection is explicit (:func:`install` / the :func:`injected` context
manager), deterministic (corruption draws from a per-``(seed, site, key)``
``np.random.default_rng``, so a retry of the same chunk refires the same
fault — exactly what sends a persistently-bad chunk through the retry
ladder into quarantine), and observable (every injection increments
``das_faults_injected_total{site,kind}`` and lands a flight record when a
recorder is attached).
"""

from __future__ import annotations

import hashlib
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: fault kinds understood by the injector
ERROR_KINDS = ("error",)
LATENCY_KINDS = ("slow",)
DATA_KINDS = ("nan", "inf", "dead", "clip")
KINDS = ERROR_KINDS + LATENCY_KINDS + DATA_KINDS


class InjectedFault(RuntimeError):
    """Raised by an ``error``-kind spec; carries its site for assertions."""

    def __init__(self, site: str, key):
        super().__init__(f"injected fault at {site} (key={key})")
        self.site = site
        self.key = key


@dataclass(frozen=True)
class FaultSpec:
    """One fault rule: fire ``kind`` at ``site`` for the listed ``keys``.

    ``keys`` empty means the spec fires on *every* call at the site.
    ``param`` is kind-specific: seconds for ``slow``, the fraction of
    channels to corrupt for the data kinds (``channels`` overrides the
    seeded choice with explicit indices), the saturation amplitude for
    ``clip`` (falls back to 1.0 when 0).
    """

    site: str
    kind: str
    keys: Tuple[str, ...] = ()
    param: float = 0.0
    channels: Tuple[int, ...] = ()

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {KINDS})")

    def matches(self, key) -> bool:
        return not self.keys or str(key) in self.keys


@dataclass(frozen=True)
class FaultPlan:
    """A seeded, explicit set of fault specs — the chaos campaign input.

    The plan is data, not behavior: tests assert quarantine/degradation
    counts *against the plan* (``n_keys(site)``), so the expected outcome
    is derived from the same object that drives the injection.
    """

    specs: Tuple[FaultSpec, ...] = ()
    seed: int = 0

    def n_keys(self, site: str) -> int:
        """Distinct keys targeted at ``site`` (0-key specs count as 0 —
        they are rate faults, not countable plan entries)."""
        keys = set()
        for s in self.specs:
            if s.site == site:
                keys.update(s.keys)
        return len(keys)

    @classmethod
    def sample(cls, seed: int, keys: Sequence[str], *,
               n_loader_faults: int = 0, n_corrupt: int = 0,
               corrupt_kind: str = "nan",
               corrupt_fraction: float = 0.1) -> "FaultPlan":
        """Deterministically pick disjoint loader-fault and corrupt-chunk
        key sets from ``keys`` — the canonical chaos-campaign shape."""
        if n_loader_faults + n_corrupt > len(keys):
            raise ValueError(f"plan wants {n_loader_faults}+{n_corrupt} "
                             f"faulted keys but only {len(keys)} exist")
        rng = np.random.default_rng(seed)
        picked = rng.choice(len(keys), size=n_loader_faults + n_corrupt,
                            replace=False)
        loader = tuple(sorted(str(keys[i]) for i in picked[:n_loader_faults]))
        corrupt = tuple(sorted(str(keys[i]) for i in picked[n_loader_faults:]))
        specs: List[FaultSpec] = []
        if loader:
            specs.append(FaultSpec("io.read", "error", keys=loader))
        if corrupt:
            specs.append(FaultSpec("io.corrupt", corrupt_kind, keys=corrupt,
                                   param=corrupt_fraction))
        return cls(specs=tuple(specs), seed=seed)


def _spec_rng(seed: int, site: str, key) -> np.random.Generator:
    """Deterministic per-(seed, site, key) generator: the same chunk gets
    the same corruption on every attempt (retries included)."""
    h = hashlib.sha256(f"{seed}|{site}|{key}".encode()).digest()
    return np.random.default_rng(int.from_bytes(h[:8], "little"))


class FaultInjector:
    """Evaluates a :class:`FaultPlan` at the wired sites, with counters.

    ``registry`` defaults to the process obs registry; ``flight`` is
    optional — when given, every injection lands a ``"fault"`` record so a
    post-mortem dump shows what chaos was active.
    """

    def __init__(self, plan: FaultPlan, registry=None, flight=None):
        self.plan = plan
        self.flight = flight
        if registry is None:
            from das_diff_veh_tpu.obs.registry import default_registry
            registry = default_registry()
        self._counter = registry.counter(
            "das_faults_injected_total",
            "chaos faults injected, by site and kind",
            labels=("site", "kind"))
        self.n_injected = 0

    # -- bookkeeping ---------------------------------------------------------
    def _note(self, spec: FaultSpec, key) -> None:
        self.n_injected += 1
        self._counter.labels(site=spec.site, kind=spec.kind).inc()
        if self.flight is not None:
            self.flight.record("fault", site=spec.site, fault_kind=spec.kind,
                               key=str(key), param=spec.param)

    # -- site hooks ----------------------------------------------------------
    def fire(self, site: str, key=None) -> None:
        """Apply control-flow faults at ``site``: sleep for ``slow`` specs,
        raise :class:`InjectedFault` for ``error`` specs (latency first, so
        a slow+error site pays the latency before failing, like a hung
        read that finally times out)."""
        for spec in self.plan.specs:
            if spec.site != site or not spec.matches(key):
                continue
            if spec.kind == "slow":
                self._note(spec, key)
                time.sleep(spec.param)
        for spec in self.plan.specs:
            if (spec.site == site and spec.kind == "error"
                    and spec.matches(key)):
                self._note(spec, key)
                raise InjectedFault(site, key)

    def corrupt(self, site: str, key, data: np.ndarray) -> np.ndarray:
        """Apply data faults at ``site``; returns a corrupted *copy* when a
        spec fires, the original array untouched otherwise."""
        out = None
        for spec in self.plan.specs:
            if (spec.site != site or spec.kind not in DATA_KINDS
                    or not spec.matches(key)):
                continue
            if out is None:
                out = np.array(data, copy=True)
            self._apply_data_fault(spec, key, out)
            self._note(spec, key)
        return data if out is None else out

    def _apply_data_fault(self, spec: FaultSpec, key,
                          out: np.ndarray) -> None:
        nch, nt = out.shape[0], out.shape[-1]
        rng = _spec_rng(self.plan.seed, spec.site, key)
        if spec.channels:
            chans = [c for c in spec.channels if 0 <= c < nch]
        else:
            n_bad = max(1, int(round(spec.param * nch)))
            chans = sorted(rng.choice(nch, size=min(n_bad, nch),
                                      replace=False).tolist())
        for c in chans:
            if spec.kind == "dead":
                out[c] = 0.0
            elif spec.kind == "clip":
                lim = spec.param if spec.param > 0 else 1.0
                out[c] = np.sign(out[c] + 0.5) * lim   # hard-saturated rail
            else:                                      # nan / inf burst
                burst = max(1, int(0.25 * nt))
                start = int(rng.integers(0, max(nt - burst, 1)))
                out[c, start:start + burst] = (
                    np.nan if spec.kind == "nan" else np.inf)


# --------------------------------------------------------------------------
# module-level hooks — the only thing production code touches
# --------------------------------------------------------------------------

_ACTIVE: Optional[FaultInjector] = None


def install(plan_or_injector, registry=None, flight=None) -> FaultInjector:
    """Activate injection process-wide; returns the injector.  Accepts a
    ready :class:`FaultInjector` or builds one from a :class:`FaultPlan`."""
    global _ACTIVE
    if isinstance(plan_or_injector, FaultInjector):
        _ACTIVE = plan_or_injector
    else:
        _ACTIVE = FaultInjector(plan_or_injector, registry=registry,
                                flight=flight)
    return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FaultInjector]:
    return _ACTIVE


@contextmanager
def injected(plan_or_injector, registry=None, flight=None):
    """``with faults.injected(plan): ...`` — scoped chaos, always cleaned."""
    inj = install(plan_or_injector, registry=registry, flight=flight)
    try:
        yield inj
    finally:
        uninstall()


def fire(site: str, key=None) -> None:
    """Production-side hook: no-op (one global read) unless an injector is
    installed AND has a spec for this site/key."""
    inj = _ACTIVE
    if inj is not None:
        inj.fire(site, key)


def corrupt(site: str, key, data):
    """Production-side data hook: returns ``data`` untouched (no copy, no
    inspection) unless an injector is installed."""
    inj = _ACTIVE
    if inj is None:
        return data
    return inj.corrupt(site, key, data)
