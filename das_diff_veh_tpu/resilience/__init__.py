"""Resilience: chaos injection, input-health screening, degradation ladder.

The robustness counterpart of the ``obs`` package — where ``obs`` makes the
system *observable* under failure, ``resilience`` makes failure *survivable
and rehearsable*.  Three concerns, one module each:

- :mod:`faults` — deterministic, seeded fault injection behind named sites
  threaded through the loaders, the batch executor, the serve dispatcher,
  and the multi-chip ring (off by default, one global read when off);
- :mod:`health` — a single fused jitted input-health sentinel (NaN/Inf,
  flatline, clipping per channel) producing the ``ChannelHealth`` mask the
  gather/VSG/stack path consumes via mask-aware normalization, plus the
  zero-dispatch numpy screen the serve front sheds poison requests with;
- :mod:`degrade` — the explicit degradation ladder (mask channels ->
  serialized gather -> replicated/einsum all-pairs), sticky process-wide
  demotions with counters and flight events.

Knobs live in ``config.HealthConfig`` (``PipelineConfig.health`` for the
batch/compute path, ``ServeConfig.health`` for admission); the full model
— sites, thresholds, ladder rungs, event names — is documented in
docs/ROBUSTNESS.md.
"""

from das_diff_veh_tpu.config import HealthConfig
from das_diff_veh_tpu.resilience.degrade import (DegradationLadder,
                                                 demoted, ladder,
                                                 note_failure,
                                                 resilient_all_pairs_peak,
                                                 set_ladder)
from das_diff_veh_tpu.resilience.faults import (FaultInjector, FaultPlan,
                                                FaultSpec, InjectedFault,
                                                injected, install, uninstall)
from das_diff_veh_tpu.resilience.health import (ChannelHealth,
                                                PoisonedChunkError,
                                                admission_verdict,
                                                quick_screen, screen_arrays,
                                                screen_section)

__all__ = [
    "HealthConfig",
    "FaultPlan", "FaultSpec", "FaultInjector", "InjectedFault",
    "injected", "install", "uninstall",
    "ChannelHealth", "PoisonedChunkError", "screen_arrays", "screen_section",
    "quick_screen", "admission_verdict",
    "DegradationLadder", "ladder", "set_ladder", "demoted", "note_failure",
    "resilient_all_pairs_peak",
]
