"""Input-health sentinel: one fused jitted screen, a per-channel mask.

Real interrogators emit NaN/Inf bursts, flatlined channels, and saturated
rails; the imaging pipeline's FFT chains turn ONE non-finite sample into a
fully-poisoned dispersion image (NaN propagates through every rfft, norm,
and mean it touches).  The sentinel screens a waterfall *before* the
pipeline sees it:

- **one fused program** — NaN/Inf counts, sample variance (flatline
  detection), and clipping fraction per channel, plus the sanitized data,
  all computed in a single jitted dispatch (``_screen``); the masking rule
  itself reuses the :mod:`das_diff_veh_tpu.ops.qc` primitives
  (``impute_traces`` for the neighbor fill);
- **mask-aware sanitization** — non-finite samples become 0, unhealthy
  channels are zeroed (and neighbor-imputed when ``HealthConfig.impute``),
  so the existing mask-aware normalizations downstream (``vsg._postprocess``
  divides where > 0, ``stack_gathers`` is ``where``-masked, the preprocess
  imputes empty traces) degrade gracefully instead of averaging garbage;
- **zero cost when off** — ``HealthConfig.enabled`` is False by default and
  every call site checks it before calling in here; the per-tag dispatch
  counters below let tests *assert* the zero-extra-dispatch claim instead
  of trusting it.

The host-side :func:`quick_screen` is the serve-admission variant: plain
numpy, no device dispatch, cheap enough for ``submit`` — a poison request
(NaN fraction / dead channels over the configured bounds) is shed with a
structured report before it can join a microbatch cohort.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.config import HealthConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.ops.qc import impute_traces

# per-call-site dispatch accounting: tests assert e.g. that the default
# (disabled) config never screens inside process_chunk — the acceptance
# bar "the sentinel adds zero extra dispatches" as a counter, not a claim
_SCREENS_LOCK = threading.Lock()
SCREENS_BY_TAG: Dict[str, int] = {}


def n_screens(tag: Optional[str] = None) -> int:
    with _SCREENS_LOCK:
        if tag is not None:
            return SCREENS_BY_TAG.get(tag, 0)
        return sum(SCREENS_BY_TAG.values())


def _count_screen(tag: str) -> None:
    with _SCREENS_LOCK:
        SCREENS_BY_TAG[tag] = SCREENS_BY_TAG.get(tag, 0) + 1


class PoisonedChunkError(RuntimeError):
    """A chunk whose masked-channel fraction exceeds
    ``HealthConfig.max_masked_fraction`` — beyond degrading, the batch path
    quarantines it instead of imaging noise."""

    def __init__(self, health: "ChannelHealth"):
        super().__init__(
            f"chunk poisoned beyond the degradation ladder: "
            f"{health.n_masked}/{health.n_channels} channels masked "
            f"(nan_fraction={health.nan_fraction:.4f}, "
            f"dead={health.n_dead}, clipped={health.n_clipped})")
        self.health = health


@dataclass(frozen=True)
class ChannelHealth:
    """Host-side screen verdict: the per-channel mask plus summary stats.

    ``healthy`` is the :class:`ChannelHealthMask` the gather/VSG/stack path
    consumes (True = keep); ``degraded`` says whether anything was masked
    at all (the transition the obs counters and flight events record).
    """

    healthy: np.ndarray                 # (nch,) bool — the ChannelHealthMask
    nan_fraction: float                 # global non-finite sample fraction
    n_nonfinite_channels: int
    n_dead: int                         # flatline / zero-variance channels
    n_clipped: int

    @property
    def n_channels(self) -> int:
        return int(self.healthy.size)

    @property
    def n_masked(self) -> int:
        return int(self.n_channels - np.count_nonzero(self.healthy))

    @property
    def degraded(self) -> bool:
        return self.n_masked > 0

    def ok(self, cfg: HealthConfig) -> bool:
        """Chunk-level verdict: masked fraction within the degrading bound."""
        if self.n_channels == 0:
            return True
        return self.n_masked <= cfg.max_masked_fraction * self.n_channels

    def summary(self) -> dict:
        """Flight-record / manifest-friendly dict."""
        return {"n_masked": self.n_masked,
                "nan_fraction": round(self.nan_fraction, 6),
                "n_nonfinite_channels": self.n_nonfinite_channels,
                "n_dead": self.n_dead, "n_clipped": self.n_clipped}


@partial(jax.jit, static_argnames=("flatline_var", "clip_limit",
                                   "clip_fraction_max", "impute"))
def _screen(data: jnp.ndarray, flatline_var: float, clip_limit: float,
            clip_fraction_max: float, impute: bool):
    """The fused sentinel: stats + mask + sanitized data, one program.

    Returns ``(sanitized (nch, nt), healthy (nch,), n_nonfinite (nch,),
    n_clipped_ch scalar, n_dead scalar)``.  Variance/clip stats are
    computed on the zero-filled data so a NaN channel cannot poison its
    own verdict.
    """
    finite = jnp.isfinite(data)
    n_nonfinite = jnp.sum(~finite, axis=-1)             # (nch,)
    clean = jnp.where(finite, data, 0.0)
    # flatline = peak-to-peak span, not variance: an exactly-constant
    # channel has ptp == 0.0 bit-for-bit, whereas float variance of a
    # constant picks up mean-subtraction roundoff (~1e-34) and would slip
    # past a zero threshold
    ptp = jnp.max(clean, axis=-1) - jnp.min(clean, axis=-1)
    dead = ptp <= flatline_var
    if clip_limit > 0:
        clip_frac = jnp.mean((jnp.abs(clean) >= clip_limit) & finite, axis=-1)
        clipped = clip_frac >= clip_fraction_max
    else:
        clipped = jnp.zeros(data.shape[0], bool)
    healthy = (n_nonfinite == 0) & ~dead & ~clipped
    bad = ~healthy
    masked = jnp.where(bad[:, None], 0.0, clean)
    if impute:
        # qc.impute_traces: neighbor SUM (edge channels copy the single
        # neighbor) — the reference's per-channel rule, vectorized.  A bad
        # channel whose neighbors are also bad imputes zeros, which the
        # mask-aware normalizations downstream treat as absent.
        masked = impute_traces(masked, bad)
    return masked, healthy, n_nonfinite, jnp.sum(clipped), jnp.sum(dead)


def screen_arrays(data, cfg: HealthConfig, tag: str = "direct"
                  ) -> Tuple[jnp.ndarray, ChannelHealth]:
    """Screen one (nch, nt) waterfall; returns (sanitized, verdict).

    ONE device dispatch (the fused ``_screen`` program), counted under
    ``tag`` in :data:`SCREENS_BY_TAG` so call sites stay auditable."""
    data = jnp.asarray(data)
    _count_screen(tag)
    out, healthy, n_nonfinite, n_clipped, n_dead = _screen(
        data, float(cfg.flatline_var), float(cfg.clip_limit),
        float(cfg.clip_fraction_max), bool(cfg.impute))
    n_nonfinite = np.asarray(n_nonfinite)
    nt = max(int(data.shape[-1]), 1)
    health = ChannelHealth(
        healthy=np.asarray(healthy),
        nan_fraction=float(n_nonfinite.sum()) / (n_nonfinite.size * nt),
        n_nonfinite_channels=int(np.count_nonzero(n_nonfinite)),
        n_dead=int(n_dead), n_clipped=int(n_clipped))
    return out, health


def screen_section(section: DasSection, cfg: HealthConfig,
                   tag: str = "direct") -> Tuple[DasSection, ChannelHealth]:
    """:func:`screen_arrays` on a :class:`DasSection` (axes pass through)."""
    data, health = screen_arrays(section.data, cfg, tag=tag)
    return DasSection(data, section.x, section.t), health


def quick_screen(data: np.ndarray, cfg: HealthConfig) -> ChannelHealth:
    """Host-side (numpy, zero-dispatch) screen for serve admission.

    Same per-channel rules as the fused sentinel, evaluated on the request
    thread: admission must not touch the device (a dispatch there would
    serialize against the dispatcher's compute and break the zero-compile
    accounting).  Returns the verdict only — sanitization happens on the
    batch path; a served request is either admitted whole or shed."""
    data = np.asarray(data)
    finite = np.isfinite(data)
    n_nonfinite = np.sum(~finite, axis=-1)
    clean = np.where(finite, data, 0.0)
    dead = np.ptp(clean, axis=-1) <= cfg.flatline_var   # same rule as _screen
    if cfg.clip_limit > 0:
        clip_frac = np.mean((np.abs(clean) >= cfg.clip_limit) & finite,
                            axis=-1)
        clipped = clip_frac >= cfg.clip_fraction_max
    else:
        clipped = np.zeros(data.shape[0], bool)
    healthy = (n_nonfinite == 0) & ~dead & ~clipped
    nt = max(int(data.shape[-1]), 1)
    return ChannelHealth(
        healthy=healthy,
        nan_fraction=float(n_nonfinite.sum()) / (n_nonfinite.size * nt),
        n_nonfinite_channels=int(np.count_nonzero(n_nonfinite)),
        n_dead=int(np.count_nonzero(dead)),
        n_clipped=int(np.count_nonzero(clipped)))


def admission_verdict(health: ChannelHealth,
                      cfg: HealthConfig) -> Optional[str]:
    """Serve-admission poison rule: a rejection reason, or None to admit.

    Stricter than the batch path's :meth:`ChannelHealth.ok` on purpose —
    batch chunks degrade (mask + continue) because the data is already on
    disk; a served request can be fixed and resubmitted by its caller, so
    ANY non-finite content beyond ``nan_fraction_max`` is shed."""
    if health.nan_fraction > cfg.nan_fraction_max:
        return (f"non-finite sample fraction {health.nan_fraction:.4f} "
                f"exceeds the admission bound {cfg.nan_fraction_max}")
    if not health.ok(cfg):
        return (f"{health.n_masked}/{health.n_channels} channels unhealthy "
                f"(dead={health.n_dead}, clipped={health.n_clipped}) — over "
                f"the max_masked_fraction={cfg.max_masked_fraction} bound")
    return None
