"""Explicit degradation ladder: lose capability, not the run.

The pipeline has three tiers of "fancy" with safe fallbacks underneath,
but until now the fallbacks were only reachable by editing config.  The
ladder makes the transitions automatic, observable, and sticky:

1. **unhealthy channels masked** (rung 0, per-chunk) — the health sentinel
   (:mod:`resilience.health`) zeroes/imputes bad channels and the
   mask-aware normalization downstream carries on; counted per chunk by
   the batch workflow (``das_health_degraded_chunks_total``);
2. **fused gather -> serialized** (component ``"gather.fused"``) — when a
   chunk's compute dispatch fails repeatedly, the Pallas scalar-prefetch
   gather is the newest/most-hardware-dependent code on the path;
   demoting it makes ``GatherConfig.traj_gather="auto"`` resolve to the
   legacy serialized cut (``ops.xcorr._decide_traj_gather`` consults
   :func:`demoted`) so the retry — and every later chunk — runs the
   battle-tested formulation;
3. **ring -> replicated -> einsum** (component ``"parallel.ring"``) — a
   failed multi-chip ring dispatch (ICI flake, collective timeout) falls
   back to the replicated layout, and a replicated Pallas failure falls
   back once more to the pure-XLA einsum path
   (:func:`resilient_all_pairs_peak`).

Demotions are **process-wide and sticky** (a flaking kernel should not be
retried per chunk), recorded as ``das_degrade_transitions_total{component}``
counters, a ``das_degrade_active{component}`` gauge, and a ``"degrade"``
flight-recorder event; :func:`reset` restores full capability (tests, or an
operator after a driver fix).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

log = logging.getLogger("das_diff_veh_tpu.resilience")

#: ladder components with automatic fallbacks
GATHER_FUSED = "gather.fused"
PARALLEL_RING = "parallel.ring"


class DegradationLadder:
    """Failure-count -> sticky demotion registry with obs wiring.

    ``threshold`` failures of a component demote it (default 1: the first
    failure already cost a retry — flaky hardware earns no benefit of the
    doubt on the hot path).  ``flight`` is optional; when given every
    transition lands a ``"degrade"`` record.
    """

    def __init__(self, registry=None, flight=None, threshold: int = 1):
        if registry is None:
            from das_diff_veh_tpu.obs.registry import default_registry
            registry = default_registry()
        self._lock = threading.Lock()
        self._fails: Dict[str, int] = {}
        self._demoted: Dict[str, str] = {}      # component -> last error
        self.flight = flight
        self.threshold = max(int(threshold), 1)
        self._transitions = registry.counter(
            "das_degrade_transitions_total",
            "degradation-ladder demotions, by component",
            labels=("component",))
        self._active = registry.gauge(
            "das_degrade_active",
            "1 while the component runs demoted, else 0",
            labels=("component",))

    def demoted(self, component: str) -> bool:
        with self._lock:
            return component in self._demoted

    def failures(self, component: str) -> int:
        with self._lock:
            return self._fails.get(component, 0)

    def note_failure(self, component: str, error=None) -> bool:
        """Record one failure; returns True when the component is (now)
        demoted.  Idempotent past the threshold — counters fire once."""
        err = f"{type(error).__name__}: {error}" if error is not None else ""
        with self._lock:
            self._fails[component] = self._fails.get(component, 0) + 1
            if component in self._demoted:
                return True
            if self._fails[component] < self.threshold:
                return False
            self._demoted[component] = err
        log.warning("degradation ladder: %s demoted after %d failure(s): %s",
                    component, self.failures(component), err or "(no error)")
        self._transitions.labels(component=component).inc()
        self._active.labels(component=component).set(1.0)
        if self.flight is not None:
            self.flight.record("degrade", component=component, error=err,
                               failures=self.failures(component))
        return True

    def reset(self, component: Optional[str] = None) -> None:
        with self._lock:
            comps = [component] if component else list(self._demoted)
            for c in comps:
                self._demoted.pop(c, None)
                self._fails.pop(c, None)
        for c in comps:
            self._active.labels(component=c).set(0.0)


# --------------------------------------------------------------------------
# process-wide ladder — consulted by ops.xcorr / parallel.allpairs
# --------------------------------------------------------------------------

_LADDER: Optional[DegradationLadder] = None
_LADDER_LOCK = threading.Lock()


def ladder() -> DegradationLadder:
    """The process ladder (lazily built against the default registry)."""
    global _LADDER
    with _LADDER_LOCK:
        if _LADDER is None:
            _LADDER = DegradationLadder()
        return _LADDER


def set_ladder(lad: Optional[DegradationLadder]) -> None:
    global _LADDER
    with _LADDER_LOCK:
        _LADDER = lad


def demoted(component: str) -> bool:
    """Cheap process-wide consult: False when no ladder was ever built (the
    common case — one global read, no allocation)."""
    lad = _LADDER
    return lad is not None and lad.demoted(component)


def note_failure(component: str, error=None) -> bool:
    return ladder().note_failure(component, error)


def reset(component: Optional[str] = None) -> None:
    lad = _LADDER
    if lad is not None:
        lad.reset(component)


# --------------------------------------------------------------------------
# rung 3: the multi-chip all-pairs engine with automatic layout fallback
# --------------------------------------------------------------------------

def resilient_all_pairs_peak(data, wlen: int, mesh, *,
                             ring=None, lad: Optional[DegradationLadder] = None,
                             **kw):
    """``parallel.allpairs.sharded_all_pairs_peak`` behind the ladder.

    Tries the configured decomposition (ring unless already demoted), falls
    back to the replicated layout on failure, and to the pure-XLA einsum
    path (``use_pallas=False``) on a second failure — recording each
    transition.  Pre-dispatch input-validation errors (``ValueError`` /
    ``TypeError``, e.g. a bad ``win_block``) re-raise untouched: they are
    caller bugs every rung would fail identically, not hardware flakes,
    and must never demote the ring.  Raises only when the last rung fails
    too (or when there is no lower rung left to stand on).
    """
    import dataclasses

    from das_diff_veh_tpu.config import RingConfig
    from das_diff_veh_tpu.parallel.allpairs import sharded_all_pairs_peak

    lad = lad if lad is not None else ladder()
    cfg = ring if ring is not None else RingConfig()
    if cfg.mode == "ring" and lad.demoted(PARALLEL_RING):
        cfg = dataclasses.replace(cfg, mode="replicated")
    try:
        return sharded_all_pairs_peak(data, wlen, mesh, ring=cfg, **kw)
    except (ValueError, TypeError):   # validation, not dispatch — no rung
        raise
    except Exception as e:  # noqa: BLE001 — any dispatch failure degrades
        if cfg.mode != "ring":
            # already on the replicated rung: drop the Pallas kernel too —
            # unless the caller already had it off, in which case the retry
            # would be the byte-identical call that just failed
            if kw.get("use_pallas") is False:
                raise
            lad.note_failure(PARALLEL_RING, e)
            kw = dict(kw, use_pallas=False)
            return sharded_all_pairs_peak(data, wlen, mesh, ring=cfg, **kw)
        lad.note_failure(PARALLEL_RING, e)
        cfg = dataclasses.replace(cfg, mode="replicated")
        try:
            return sharded_all_pairs_peak(data, wlen, mesh, ring=cfg, **kw)
        except Exception as e2:  # noqa: BLE001
            lad.note_failure(PARALLEL_RING, e2)
            if kw.get("use_pallas") is False:
                raise
            kw = dict(kw, use_pallas=False)
            return sharded_all_pairs_peak(data, wlen, mesh, ring=cfg, **kw)
