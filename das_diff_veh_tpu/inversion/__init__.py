"""Differentiable Rayleigh-wave Vs inversion (SURVEY §7 step 10).

Replaces the reference's external evodcinv (CPSO) + disba (numba surf96)
stack (inversion_diff_*.ipynb) with a JAX transfer-matrix forward model,
batched particle-swarm + optax gradient inversion, and jacfwd sensitivity
kernels.
"""

from das_diff_veh_tpu.inversion.curves import (Curve, curves_from_ridges,
                                               load_reference_ridge_npz,
                                               ridge_stats)
from das_diff_veh_tpu.inversion.fleet import (CurveBatch, FleetResult,
                                              VsShiftEvent, detect_vs_shifts,
                                              invert_fleet,
                                              make_packed_misfit_fn,
                                              pack_curve_sets)
from das_diff_veh_tpu.inversion.forward import (LayeredModel,
                                                density_gardner_linear,
                                                phase_velocity,
                                                rayleigh_halfspace_velocity,
                                                scan_mode_diagnostics, secular,
                                                vp_from_poisson)
from das_diff_veh_tpu.inversion.invert import (InversionResult, LayerBounds,
                                               ModelSpec, invert,
                                               invert_multirun, make_misfit_fn,
                                               speed_model_spec,
                                               weight_model_spec)
from das_diff_veh_tpu.inversion.sensitivity import (SensitivityKernel,
                                                    phase_sensitivity,
                                                    resample_fine)

__all__ = [
    "Curve", "curves_from_ridges", "load_reference_ridge_npz", "ridge_stats",
    "LayeredModel", "density_gardner_linear", "phase_velocity",
    "rayleigh_halfspace_velocity", "scan_mode_diagnostics",
    "secular", "vp_from_poisson",
    "InversionResult", "LayerBounds", "ModelSpec", "invert",
    "invert_multirun", "make_misfit_fn",
    "CurveBatch", "FleetResult", "VsShiftEvent", "detect_vs_shifts",
    "invert_fleet", "make_packed_misfit_fn", "pack_curve_sets",
    "speed_model_spec", "weight_model_spec",
    "SensitivityKernel", "phase_sensitivity", "resample_fine",
]
