"""Vs-profile inversion: misfit, particle swarm, and optax refinement.

TPU-first replacement for the reference's ``evodcinv.EarthModel`` CPSO
inversion (inversion_diff_speed.ipynb cells 7-9: popsize 50, maxiter 1000,
``workers=-1`` multiprocessing, maxrun 5, misfit "rmse").  Re-design:

* the whole population's misfits evaluate as ONE ``vmap`` over the
  differentiable forward model - the multiprocessing pool becomes a single
  batched XLA computation;
* because the forward model is differentiable, a short swarm search is
  followed by vectorised multi-start Adam refinement (optax) - the
  evolutionary search only needs to land in a basin, not polish it;
* sensitivity kernels run as one batched vmap of root re-solves
  (sensitivity.py) instead of disba's serial numba loop.

Misfit follows evodcinv's "rmse" semantics: per curve
``sqrt(mean(((obs - pred)/unc)^2))``, combined as a weight-normalised sum
over curves; overtones that do not exist at a period contribute a fixed
penalty residual instead of NaN so the objective stays finite.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from das_diff_veh_tpu.inversion.curves import Curve
from das_diff_veh_tpu.inversion.forward import (LayeredModel,
                                                density_gardner_linear,
                                                phase_velocity,
                                                vp_from_poisson)

INVALID_RESIDUAL = 5.0  # penalty residual for below-cutoff overtone samples


class LayerBounds(NamedTuple):
    """Search bounds for one layer: thickness (km), vs (km/s), Poisson.

    Same triple as ``evodcinv.Layer`` (inversion_diff_speed.ipynb cell 7);
    a degenerate Poisson interval pins nu (the speed notebooks fix 0.4375,
    the weight notebooks search [0.33, 0.49])."""

    thickness: tuple[float, float]
    vs: tuple[float, float]
    poisson: tuple[float, float] = (0.4375, 0.4375)


class ModelSpec(NamedTuple):
    layers: tuple[LayerBounds, ...]
    density: Callable[[jnp.ndarray], jnp.ndarray] = density_gardner_linear

    @property
    def n_layers(self) -> int:
        return len(self.layers)

    @property
    def free_poisson(self) -> bool:
        return any(b.poisson[0] != b.poisson[1] for b in self.layers)

    @property
    def n_params(self) -> int:
        return self.n_layers * (3 if self.free_poisson else 2)

    def bounds_arrays(self):
        lo = [b.thickness[0] for b in self.layers] + [b.vs[0] for b in self.layers]
        hi = [b.thickness[1] for b in self.layers] + [b.vs[1] for b in self.layers]
        if self.free_poisson:
            lo += [b.poisson[0] for b in self.layers]
            hi += [b.poisson[1] for b in self.layers]
        return jnp.asarray(lo), jnp.asarray(hi)

    def to_model(self, x01: jnp.ndarray) -> LayeredModel:
        """Unit-cube parameter vector -> LayeredModel."""
        lo, hi = self.bounds_arrays()
        x = lo + (hi - lo) * jnp.clip(x01, 0.0, 1.0)
        n = self.n_layers
        d, vs = x[:n], x[n:2 * n]
        if self.free_poisson:
            nu = x[2 * n:3 * n]
        else:
            nu = jnp.asarray([b.poisson[0] for b in self.layers])
        vp = vp_from_poisson(vs, nu)
        return LayeredModel(thickness=d, vp=vp, vs=vs, rho=self.density(vp))


def speed_model_spec() -> ModelSpec:
    """The 6-layer search space of inversion_diff_speed.ipynb cell 7
    (thickness/vs bounds in km and km/s, Poisson fixed at 0.4375)."""
    return ModelSpec(layers=(
        LayerBounds((0.001, 0.015), (0.1, 0.5)),
        LayerBounds((0.001, 0.015), (0.1, 0.5)),
        LayerBounds((0.005, 0.025), (0.2, 0.6)),
        LayerBounds((0.005, 0.025), (0.2, 0.6)),
        LayerBounds((0.02, 0.08), (0.4, 1.0)),
        LayerBounds((0.02, 0.08), (0.4, 1.0)),
    ))


def weight_model_spec() -> ModelSpec:
    """inversion_diff_weight.ipynb cell 7: same skeleton, thinner upper
    layers and free Poisson in [0.33, 0.49]."""
    nu = (0.33, 0.49)
    return ModelSpec(layers=(
        LayerBounds((0.001, 0.01), (0.1, 0.5), nu),
        LayerBounds((0.001, 0.01), (0.1, 0.5), nu),
        LayerBounds((0.001, 0.01), (0.2, 0.6), nu),
        LayerBounds((0.005, 0.025), (0.2, 0.6), nu),
        LayerBounds((0.02, 0.08), (0.4, 1.0), nu),
        LayerBounds((0.02, 0.08), (0.4, 1.0), nu),
    ))


def curve_misfit(model: LayeredModel, curve_period, curve_velocity,
                 curve_unc, mode: int, n_grid: int):
    """Uncertainty-normalised RMSE of one modal curve (evodcinv 'rmse')."""
    pred = phase_velocity(curve_period, model, mode=mode, n_grid=n_grid)
    r = (curve_velocity - pred) / curve_unc
    r = jnp.where(jnp.isfinite(pred), r, INVALID_RESIDUAL)
    return jnp.sqrt(jnp.mean(r * r))


def make_misfit_fn(spec: ModelSpec, curves: Sequence[Curve],
                   n_grid: int = 400):
    """misfit(x01) -> scalar, jit/vmap/grad-compatible.

    Curves are baked in as static arrays (their lengths differ, so each
    curve is its own closed-over computation; the small curve count makes
    this cheap)."""
    baked = [(jnp.asarray(c.period), jnp.asarray(c.velocity),
              jnp.asarray(c.uncertainty if c.uncertainty is not None
                          else np.ones_like(c.velocity)),
              int(c.mode), float(c.weight)) for c in curves]
    wsum = sum(w for *_, w in baked)

    def misfit(x01):
        model = spec.to_model(x01)
        total = 0.0
        for period, vel, unc, mode, w in baked:
            total = total + w * curve_misfit(model, period, vel, unc, mode,
                                             n_grid)
        return total / wsum

    return misfit


class InversionResult(NamedTuple):
    """Best model + the final population ensemble (cf. evodcinv's
    ``res.model`` / ``res.models`` / ``res.misfits`` used by the
    reference's plot_model/plot_predicted_curve, cell 1)."""

    model: LayeredModel
    misfit: jnp.ndarray
    x_best: jnp.ndarray
    models_x: jnp.ndarray      # (pop, n_params) final population, unit cube
    misfits: jnp.ndarray       # (pop,)
    history: jnp.ndarray       # (iters,) best-so-far misfit trace


@partial(jax.jit, static_argnames=("misfit_fn", "n_params", "popsize",
                                   "maxiter"))
def _pso(misfit_fn, key, n_params: int, popsize: int, maxiter: int):
    """Inertial global-best PSO on the unit cube (w=0.73, c1=c2=1.496 -
    the constriction coefficients the reference's stochopy CPSO also
    defaults to), velocities clamped, positions clipped."""
    w, c1, c2 = 0.7298, 1.49618, 1.49618
    k1, k2 = jax.random.split(key)
    x = jax.random.uniform(k1, (popsize, n_params))
    v = 0.1 * (jax.random.uniform(k2, (popsize, n_params)) - 0.5)
    f = jax.vmap(misfit_fn)(x)
    pbest_x, pbest_f = x, f
    g = jnp.argmin(f)
    gbest_x, gbest_f = x[g], f[g]

    def step(state, key):
        x, v, pbest_x, pbest_f, gbest_x, gbest_f = state
        r1 = jax.random.uniform(key, (2, popsize, n_params))
        v = (w * v + c1 * r1[0] * (pbest_x - x)
             + c2 * r1[1] * (gbest_x[None] - x))
        v = jnp.clip(v, -0.25, 0.25)
        x = jnp.clip(x + v, 0.0, 1.0)
        f = jax.vmap(misfit_fn)(x)
        better = f < pbest_f
        pbest_x = jnp.where(better[:, None], x, pbest_x)
        pbest_f = jnp.where(better, f, pbest_f)
        g = jnp.argmin(pbest_f)
        improved = pbest_f[g] < gbest_f
        gbest_x = jnp.where(improved, pbest_x[g], gbest_x)
        gbest_f = jnp.where(improved, pbest_f[g], gbest_f)
        return (x, v, pbest_x, pbest_f, gbest_x, gbest_f), gbest_f

    keys = jax.random.split(jax.random.fold_in(key, 7), maxiter)
    state, trace = jax.lax.scan(step, (x, v, pbest_x, pbest_f, gbest_x,
                                       gbest_f), keys)
    x, v, pbest_x, pbest_f, gbest_x, gbest_f = state
    return gbest_x, gbest_f, pbest_x, pbest_f, trace


@partial(jax.jit, static_argnames=("misfit_fn", "n_steps"))
def _refine(misfit_fn, x0_batch, n_steps: int, lr: float = 0.02):
    """Vectorised multi-start Adam in logit space (keeps iterates strictly
    inside the box while gradients stay unconstrained)."""
    eps = 1e-4
    z0 = jax.scipy.special.logit(jnp.clip(x0_batch, eps, 1.0 - eps))
    opt = optax.adam(lr)

    def run_one(z):
        state = opt.init(z)
        def body(carry, _):
            z, state = carry
            loss, grad = jax.value_and_grad(
                lambda zz: misfit_fn(jax.nn.sigmoid(zz)))(z)
            grad = jnp.where(jnp.isfinite(grad), grad, 0.0)
            updates, state = opt.update(grad, state)
            return (optax.apply_updates(z, updates), state), loss
        (z, _), losses = jax.lax.scan(body, (z, state), None, length=n_steps)
        return jax.nn.sigmoid(z), misfit_fn(jax.nn.sigmoid(z))

    return jax.vmap(run_one)(z0)


def invert(spec: ModelSpec, curves: Sequence[Curve], *, popsize: int = 50,
           maxiter: int = 200, n_refine_starts: int = 8,
           n_refine_steps: int = 80, n_grid: int = 400,
           seed: int = 0) -> InversionResult:
    """Swarm search + gradient refinement for a 1-D Vs profile.

    Matches the role of ``EarthModel.invert(curves, maxrun=5)`` with CPSO
    popsize 50 x maxiter 1000 (inversion_diff_speed.ipynb cell 9); the
    gradient stage makes far fewer forward evaluations necessary for the
    same (or better) final misfit.
    """
    misfit_fn = make_misfit_fn(spec, curves, n_grid=n_grid)
    key = jax.random.PRNGKey(seed)
    gbest_x, gbest_f, pop_x, pop_f, trace = _pso(
        misfit_fn, key, spec.n_params, popsize, maxiter)

    k = min(n_refine_starts, popsize)
    top = jnp.argsort(pop_f)[:k]
    starts = jnp.concatenate([gbest_x[None], pop_x[top]], axis=0)
    ref_x, ref_f = _refine(misfit_fn, starts, n_refine_steps)

    all_x = jnp.concatenate([pop_x, ref_x], axis=0)
    all_f = jnp.concatenate([pop_f, ref_f], axis=0)
    best = jnp.argmin(all_f)
    x_best = all_x[best]
    return InversionResult(
        model=spec.to_model(x_best), misfit=all_f[best], x_best=x_best,
        models_x=all_x, misfits=all_f, history=trace)
