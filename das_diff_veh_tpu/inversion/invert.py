"""Vs-profile inversion: misfit, particle swarm, and optax refinement.

TPU-first replacement for the reference's ``evodcinv.EarthModel`` CPSO
inversion (inversion_diff_speed.ipynb cells 7-9: popsize 50, maxiter 1000,
``workers=-1`` multiprocessing, maxrun 5, misfit "rmse").  Re-design:

* the whole population's misfits evaluate as ONE ``vmap`` over the
  differentiable forward model - the multiprocessing pool becomes a single
  batched XLA computation;
* because the forward model is differentiable, a short swarm search is
  followed by vectorised multi-start Adam refinement (optax) - the
  evolutionary search only needs to land in a basin, not polish it;
* sensitivity kernels run as one batched vmap of root re-solves
  (sensitivity.py) instead of disba's serial numba loop.

Misfit follows evodcinv's "rmse" semantics: per curve
``sqrt(mean(((obs - pred)/unc)^2))``, combined as a weight-normalised sum
over curves; overtones that do not exist at a period contribute a fixed
penalty residual instead of NaN so the objective stays finite.
"""

from __future__ import annotations

import weakref
from functools import partial
from typing import Callable, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from das_diff_veh_tpu.inversion.curves import Curve
from das_diff_veh_tpu.inversion.forward import (LayeredModel,
                                                density_gardner_linear,
                                                phase_velocity,
                                                vp_from_poisson)

INVALID_RESIDUAL = 5.0  # penalty residual for below-cutoff overtone samples


class LayerBounds(NamedTuple):
    """Search bounds for one layer: thickness (km), vs (km/s), Poisson.

    Same triple as ``evodcinv.Layer`` (inversion_diff_speed.ipynb cell 7);
    a degenerate Poisson interval pins nu (the speed notebooks fix 0.4375,
    the weight notebooks search [0.33, 0.49])."""

    thickness: tuple[float, float]
    vs: tuple[float, float]
    poisson: tuple[float, float] = (0.4375, 0.4375)


class ModelSpec(NamedTuple):
    layers: tuple[LayerBounds, ...]
    density: Callable[[jnp.ndarray], jnp.ndarray] = density_gardner_linear

    @property
    def n_layers(self) -> int:
        return len(self.layers)

    @property
    def free_poisson(self) -> bool:
        return any(b.poisson[0] != b.poisson[1] for b in self.layers)

    @property
    def n_params(self) -> int:
        return self.n_layers * (3 if self.free_poisson else 2)

    def bounds_arrays(self):
        lo = [b.thickness[0] for b in self.layers] + [b.vs[0] for b in self.layers]
        hi = [b.thickness[1] for b in self.layers] + [b.vs[1] for b in self.layers]
        if self.free_poisson:
            lo += [b.poisson[0] for b in self.layers]
            hi += [b.poisson[1] for b in self.layers]
        return jnp.asarray(lo), jnp.asarray(hi)

    def to_model(self, x01: jnp.ndarray) -> LayeredModel:
        """Unit-cube parameter vector -> LayeredModel (in x01's dtype)."""
        x01 = jnp.asarray(x01)
        lo, hi = self.bounds_arrays()
        lo, hi = lo.astype(x01.dtype), hi.astype(x01.dtype)
        x = lo + (hi - lo) * jnp.clip(x01, 0.0, 1.0)
        n = self.n_layers
        d, vs = x[:n], x[n:2 * n]
        if self.free_poisson:
            nu = x[2 * n:3 * n]
        else:
            nu = jnp.asarray([b.poisson[0] for b in self.layers], x01.dtype)
        vp = vp_from_poisson(vs, nu)
        return LayeredModel(thickness=d, vp=vp, vs=vs, rho=self.density(vp))


def speed_model_spec() -> ModelSpec:
    """The 6-layer search space of inversion_diff_speed.ipynb cell 7
    (thickness/vs bounds in km and km/s, Poisson fixed at 0.4375)."""
    return ModelSpec(layers=(
        LayerBounds((0.001, 0.015), (0.1, 0.5)),
        LayerBounds((0.001, 0.015), (0.1, 0.5)),
        LayerBounds((0.005, 0.025), (0.2, 0.6)),
        LayerBounds((0.005, 0.025), (0.2, 0.6)),
        LayerBounds((0.02, 0.08), (0.4, 1.0)),
        LayerBounds((0.02, 0.08), (0.4, 1.0)),
    ))


def weight_model_spec() -> ModelSpec:
    """inversion_diff_weight.ipynb cell 7: same skeleton, thinner upper
    layers and free Poisson in [0.33, 0.49]."""
    nu = (0.33, 0.49)
    return ModelSpec(layers=(
        LayerBounds((0.001, 0.01), (0.1, 0.5), nu),
        LayerBounds((0.001, 0.01), (0.1, 0.5), nu),
        LayerBounds((0.001, 0.01), (0.2, 0.6), nu),
        LayerBounds((0.005, 0.025), (0.2, 0.6), nu),
        LayerBounds((0.02, 0.08), (0.4, 1.0), nu),
        LayerBounds((0.02, 0.08), (0.4, 1.0), nu),
    ))


def make_misfit_fn(spec: ModelSpec, curves: Sequence[Curve],
                   n_grid: int = 400, n_subdiv: int = 1, dtype=None,
                   invalid: str = "penalty"):
    """misfit(x01) -> scalar, jit/vmap/grad-compatible.

    All curves' (period, mode) samples are concatenated so the forward
    model runs as ONE batched root solve per misfit evaluation - modes 0,
    3 and 4 share the same secular-function grid scan (one ``lax.scan``
    over layers), which is what keeps both the XLA graph and the runtime
    small.  Per-curve RMSE semantics (evodcinv 'rmse': per curve
    ``sqrt(mean(((obs-pred)/unc)^2))``, weight-normalised sum) are then
    recovered by static slicing of the concatenated prediction.

    ``n_subdiv=1`` (default) keeps the root solve at ~0.1 m/s resolution —
    two orders below the bootstrap-curve uncertainties — with a markedly
    smaller XLA graph than the full-precision ``n_subdiv=3`` path.
    ``dtype`` pins the working precision (e.g. float32 for a TPU search
    under an x64-enabled process); None follows the default float type.
    ``invalid`` selects below-cutoff overtone handling: ``"penalty"``
    (ours: fixed INVALID_RESIDUAL per missing point — keeps the objective
    sensitive to losing overtones) or ``"truncate"`` (evodcinv semantics:
    missing points are dropped from the per-curve mean, reference
    EarthModel misfit="rmse"; use this for apples-to-apples parity runs)."""
    baked = [(np.asarray(c.period, dtype=np.float64),
              np.asarray(c.velocity, dtype=np.float64),
              np.asarray(c.uncertainty if c.uncertainty is not None
                         else np.ones_like(c.velocity), dtype=np.float64),
              int(c.mode), float(c.weight)) for c in curves]
    wsum = sum(w for *_, w in baked)
    period_all = jnp.asarray(np.concatenate([p for p, *_ in baked]), dtype)
    mode_all = jnp.asarray(np.concatenate(
        [np.full(len(p), m) for p, _, _, m, _ in baked]))
    vel_all = jnp.asarray(np.concatenate([v for _, v, *_ in baked]), dtype)
    unc_all = jnp.asarray(np.concatenate([u for _, _, u, *_ in baked]), dtype)
    slices = np.cumsum([0] + [len(p) for p, *_ in baked])

    assert invalid in ("penalty", "truncate")

    def misfit(x01):
        model = spec.to_model(x01)
        pred = phase_velocity(period_all, model, mode=mode_all,
                              n_grid=n_grid, n_subdiv=n_subdiv)
        fin = jnp.isfinite(pred)
        r = (vel_all - pred) / unc_all
        r = jnp.where(fin, r, INVALID_RESIDUAL)
        total = 0.0
        for i, (*_, w) in enumerate(baked):
            sl = slice(slices[i], slices[i + 1])
            ri, fi = r[sl], fin[sl]
            if invalid == "truncate":
                n_fin = jnp.sum(fi)
                rmse = jnp.sqrt(jnp.sum(jnp.where(fi, ri * ri, 0.0))
                                / jnp.maximum(n_fin, 1))
                rmse = jnp.where(n_fin > 0, rmse, INVALID_RESIDUAL)
            else:
                rmse = jnp.sqrt(jnp.mean(ri * ri))
            total = total + w * rmse
        return total / wsum

    return misfit


class InversionResult(NamedTuple):
    """Best model + the final population ensemble (cf. evodcinv's
    ``res.model`` / ``res.models`` / ``res.misfits`` used by the
    reference's plot_model/plot_predicted_curve, cell 1)."""

    model: LayeredModel
    misfit: jnp.ndarray
    x_best: jnp.ndarray
    models_x: jnp.ndarray      # (pop, n_params) final population, unit cube
    misfits: jnp.ndarray       # (pop,)
    history: jnp.ndarray       # (iters,) best-so-far misfit trace


# legacy misfit(x01) closure -> misfit(x01, data) adapter, cached by the
# closure's identity: the jitted swarm/refine executables are keyed on the
# (static) misfit function object, so handing the SAME closure back must
# produce the SAME adapter or every call would re-trace.
_data_adapters: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _as_data_misfit(fn):
    """Adapt a single-argument misfit closure (from :func:`make_misfit_fn`)
    to the internal data-parameterized signature ``misfit(x01, data)``.

    The closure path bakes its observations into the function, so ``data``
    is simply ignored (``None`` flows through the jitted helpers as an
    empty pytree).  The fleet engine (``inversion.fleet``) instead passes a
    packed :class:`~das_diff_veh_tpu.inversion.fleet.CurveBatch` as
    ``data`` — one traced function for every curve set."""
    try:
        adapter = _data_adapters.get(fn)
    except TypeError:                      # unhashable/unweakrefable callable
        adapter = None
    if adapter is None:
        def adapter(x01, data, _fn=fn):
            del data                       # baked into the closure
            return _fn(x01)
        try:
            _data_adapters[fn] = adapter
        except TypeError:
            pass
    return adapter


def _eval_pop(misfit_fn, x, data, eval_chunk: int):
    """Population misfits; ``eval_chunk > 0`` bounds how many evaluate
    concurrently (lax.map over chunks) so batched-restart populations can't
    exceed device memory — an outer run-axis vmap turns the chunk loop into
    a (runs x eval_chunk) working set instead of (runs x popsize).

    ``misfit_fn(x01, data)``: ``data`` broadcasts across the population
    (closure path: None; fleet path: this target's packed curve set)."""
    pop = x.shape[0]
    one = jax.vmap(lambda xx: misfit_fn(xx, data))
    if eval_chunk <= 0 or eval_chunk >= pop:
        return one(x)
    pad = (-pop) % eval_chunk
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    f = jax.lax.map(one, xp.reshape(-1, eval_chunk, x.shape[-1]))
    return f.reshape(-1)[:pop]


@partial(jax.jit, static_argnames=("misfit_fn", "n_params", "popsize",
                                   "dtype", "eval_chunk"))
def _pso_init(misfit_fn, key, data=None, *, n_params: int, popsize: int,
              dtype=None, eval_chunk: int = 0, x0=None):
    dtype = dtype or jnp.zeros(()).dtype
    k1, k2 = jax.random.split(key)
    x = jax.random.uniform(k1, (popsize, n_params), dtype=dtype)
    if x0 is not None:
        # warm starts: known-good points seed the population (first rows);
        # the swarm keeps them only through pbest/gbest if they score well
        m = min(x0.shape[0], popsize)
        x = x.at[:m].set(jnp.clip(jnp.asarray(x0[:m], dtype), 0.0, 1.0))
    v = 0.1 * (jax.random.uniform(k2, (popsize, n_params), dtype=dtype) - 0.5)
    f = _eval_pop(misfit_fn, x, data, eval_chunk)
    g = jnp.argmin(f)
    return (x, v, x, f, x[g], f[g])


@partial(jax.jit, static_argnames=("misfit_fn", "n_iters", "eval_chunk"))
def _pso_run(misfit_fn, state, key, n_iters: int, eval_chunk: int = 0,
             data=None):
    """``n_iters`` inertial global-best PSO steps on the unit cube (w=0.73,
    c1=c2=1.496 - the constriction coefficients the reference's stochopy
    CPSO also defaults to), velocities clamped, positions clipped."""
    w, c1, c2 = 0.7298, 1.49618, 1.49618
    popsize, n_params = state[0].shape
    dtype = state[0].dtype

    def step(state, key):
        x, v, pbest_x, pbest_f, gbest_x, gbest_f = state
        r1 = jax.random.uniform(key, (2, popsize, n_params), dtype=dtype)
        v = (w * v + c1 * r1[0] * (pbest_x - x)
             + c2 * r1[1] * (gbest_x[None] - x))
        v = jnp.clip(v, -0.25, 0.25)
        x = jnp.clip(x + v, 0.0, 1.0)
        f = _eval_pop(misfit_fn, x, data, eval_chunk)
        better = f < pbest_f
        pbest_x = jnp.where(better[:, None], x, pbest_x)
        pbest_f = jnp.where(better, f, pbest_f)
        g = jnp.argmin(pbest_f)
        improved = pbest_f[g] < gbest_f
        gbest_x = jnp.where(improved, pbest_x[g], gbest_x)
        gbest_f = jnp.where(improved, pbest_f[g], gbest_f)
        return (x, v, pbest_x, pbest_f, gbest_x, gbest_f), gbest_f

    keys = jax.random.split(key, n_iters)
    return jax.lax.scan(step, state, keys)


@partial(jax.jit, static_argnames=("misfit_fn", "n_steps", "lr"))
def _refine_run(misfit_fn, z, opt_state, n_steps: int, lr: float, data=None):
    opt = optax.adam(lr)

    def one(z, opt_state):
        def body(carry, _):
            z, state = carry
            loss, grad = jax.value_and_grad(
                lambda zz: misfit_fn(jax.nn.sigmoid(zz), data))(z)
            grad = jnp.where(jnp.isfinite(grad), grad, 0.0)
            updates, state = opt.update(grad, state)
            return (optax.apply_updates(z, updates), state), loss
        (z, state), _ = jax.lax.scan(body, (z, opt_state), None,
                                     length=n_steps)
        return z, state

    # ``data`` is closed over, so it broadcasts across the start axis
    return jax.vmap(one)(z, opt_state)


def _refine(misfit_fn, x0_batch, n_steps: int, lr: float = 0.02,
            chunk: int = 50, data=None):
    """Vectorised multi-start Adam in logit space (keeps iterates strictly
    inside the box while gradients stay unconstrained).  Host-chunked like
    the PSO loop in :func:`invert_multirun` to bound single device-call
    time (long monolithic scans have crashed the tunneled-TPU worker)."""
    eps = 1e-4
    z = jax.scipy.special.logit(jnp.clip(x0_batch, eps, 1.0 - eps))
    opt_state = jax.vmap(optax.adam(lr).init)(z)
    done = 0
    while done < n_steps:
        n = min(chunk, n_steps - done)
        z, opt_state = _refine_run(misfit_fn, z, opt_state, n, lr, data)
        done += n
    x = jax.nn.sigmoid(z)
    return x, _misfit_batch(misfit_fn, x, data)


@partial(jax.jit, static_argnames=("misfit_fn",))
def _misfit_batch(misfit_fn, x, data=None):
    return jax.vmap(lambda xx: misfit_fn(xx, data))(x)


def invert(spec: ModelSpec, curves: Sequence[Curve], *, popsize: int = 50,
           maxiter: int = 200, n_refine_starts: int = 8,
           n_refine_steps: int = 80, n_grid: int = 400,
           n_subdiv: int = 1, dtype=None, invalid: str = "penalty",
           seed: int = 0, misfit_fn=None, x0=None) -> InversionResult:
    """Swarm search + gradient refinement for a 1-D Vs profile.

    Matches the role of ``EarthModel.invert(curves, maxrun=5)`` with CPSO
    popsize 50 x maxiter 1000 (inversion_diff_speed.ipynb cell 9); the
    whole population evaluates as one batched forward solve per iteration
    and a gradient stage polishes the best basins (far fewer forward
    evaluations for the same or better final misfit).

    One machine, two entry points: this is :func:`invert_multirun` with a
    single restart (same RNG stream as seed ``seed``, same pooling), kept as
    the stable per-run unit the parity script's serial mode loops over.
    """
    return invert_multirun(spec, curves, n_runs=1, popsize=popsize,
                           maxiter=maxiter, n_refine_starts=n_refine_starts,
                           n_refine_steps=n_refine_steps, n_grid=n_grid,
                           n_subdiv=n_subdiv, dtype=dtype, invalid=invalid,
                           seed=seed, misfit_fn=misfit_fn, x0=x0)


def invert_multirun(spec: ModelSpec, curves: Sequence[Curve], *,
                    n_runs: int = 3, popsize: int = 50, maxiter: int = 200,
                    n_refine_starts: int = 8, n_refine_steps: int = 80,
                    n_grid: int = 400, n_subdiv: int = 1, dtype=None,
                    invalid: str = "penalty", seed: int = 0,
                    chunk: int = 50, eval_chunk: int = 0,
                    refine_chunk: int = 0, misfit_fn=None, x0=None,
                    mesh=None, mesh_axis: str = "win") -> InversionResult:
    """Best-of-``n_runs`` inversion with every run's swarm advanced in ONE
    batched computation (``vmap`` over the run axis).

    The reference's ``maxrun`` restarts execute serially (evodcinv
    EarthModel.invert(maxrun=5), inversion_diff_speed.ipynb cell 9); here a
    population of ``n_runs x popsize`` misfits evaluates per iteration in
    one device program, so N restarts cost roughly ONE run's wall-clock on
    an accelerator with headroom.  Refinement then pools the top basins of
    *all* runs into a single vectorised multi-start Adam batch.

    ``eval_chunk``/``refine_chunk`` bound the concurrent misfit / gradient
    evaluations per device call (0 = unbounded): with ``n_runs`` swarms the
    working set is runs x eval_chunk, which keeps big restart counts inside
    HBM on a single chip.

    ``misfit_fn``: optional prebuilt objective (from :func:`make_misfit_fn`)
    — pass the SAME function object across repeated calls so the jitted
    swarm/refine executables (keyed on its identity) are traced once; the
    parity script's serial mode uses this to avoid re-tracing per restart.

    ``x0``: optional ``(m, n_params)`` unit-cube warm-start points injected
    into every run's initial population (budget-escalation reruns restart
    from a previous best instead of from scratch).

    ``mesh``: optional ``jax.sharding.Mesh`` — the run axis of the swarm
    state shards over ``mesh_axis`` and each device advances its own
    restarts with no cross-device traffic until the final pooling (restarts
    are independent; ``n_runs`` should be a device-count multiple for even
    placement).  Results are independent of the sharding.
    """
    if misfit_fn is None:
        misfit_fn = make_misfit_fn(spec, curves, n_grid=n_grid,
                                   n_subdiv=n_subdiv, dtype=dtype,
                                   invalid=invalid)
    misfit_fn = _as_data_misfit(misfit_fn)
    keys = jax.vmap(jax.random.PRNGKey)(seed + jnp.arange(n_runs))

    def _shard_runs(tree):
        if mesh is None:
            return tree
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(a):
            spec_ = P(*((mesh_axis,) + (None,) * (a.ndim - 1)))
            return jax.device_put(a, NamedSharding(mesh, spec_))

        return jax.tree.map(place, tree)

    keys = _shard_runs(keys)
    if x0 is not None:
        x0 = jnp.asarray(np.asarray(x0, dtype=np.float64), dtype)
    init = partial(_pso_init, misfit_fn, n_params=spec.n_params,
                   popsize=popsize, dtype=dtype, eval_chunk=eval_chunk,
                   x0=x0)
    states = _shard_runs(jax.vmap(lambda k: init(k))(keys))
    traces, done = [], 0
    while done < maxiter:
        n = min(chunk, maxiter - done)
        step_keys = jax.vmap(lambda k: jax.random.fold_in(k, 7 + done))(keys)
        states, tr = jax.vmap(
            lambda st, k: _pso_run(misfit_fn, st, k, n,
                                   eval_chunk=eval_chunk))(states, step_keys)
        traces.append(tr)
        done += n
    _, _, pop_x, pop_f, gbest_x, gbest_f = states   # leading axis: run

    k = min(n_refine_starts, popsize)
    top = jnp.argsort(pop_f, axis=1)[:, :k]                      # (runs, k)
    starts = jnp.concatenate(
        [gbest_x[:, None], jnp.take_along_axis(pop_x, top[..., None], axis=1)],
        axis=1).reshape(-1, spec.n_params)                       # pooled
    if refine_chunk and refine_chunk < starts.shape[0]:
        parts = [_refine(misfit_fn, starts[i:i + refine_chunk], n_refine_steps)
                 for i in range(0, starts.shape[0], refine_chunk)]
        ref_x = jnp.concatenate([p[0] for p in parts], axis=0)
        ref_f = jnp.concatenate([p[1] for p in parts], axis=0)
    else:
        ref_x, ref_f = _refine(misfit_fn, starts, n_refine_steps)

    all_x = jnp.concatenate([pop_x.reshape(-1, spec.n_params), ref_x], axis=0)
    all_f = jnp.concatenate([pop_f.reshape(-1), ref_f], axis=0)
    best = jnp.argmin(all_f)
    x_best = all_x[best]
    return InversionResult(
        model=spec.to_model(x_best), misfit=all_f[best], x_best=x_best,
        models_x=all_x, misfits=all_f,
        history=jnp.min(jnp.concatenate(traces, axis=-1), axis=0))
