"""Dispersion-curve containers and preparation from bootstrap ridges.

Mirrors the reference's curve-building path: ``plot_disp_curves``
(/root/reference/modules/utils.py:680-713) computes per-band mean / range /
std across bootstrap ridge repetitions, and inversion_diff_speed.ipynb
cell 5 turns those into period-domain ``evodcinv.Curve`` objects (km/s,
reversed so periods ascend, uncertainties = bootstrap range).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np


class Curve(NamedTuple):
    """One observed modal dispersion curve (period-domain, km/s).

    Same fields as ``evodcinv.Curve`` (reference inversion notebooks,
    cell 5): ``mode`` 0 is fundamental; ``weight`` scales this curve's
    contribution to the joint misfit; ``uncertainty`` (km/s) normalises
    residuals (None => 1).
    """

    period: np.ndarray
    velocity: np.ndarray
    mode: int
    weight: float = 1.0
    uncertainty: np.ndarray | None = None


def ridge_stats(ridge_vels: np.ndarray):
    """(mean, range, std) over bootstrap repetitions, shape (nf,) each.

    The non-plotting core of the reference's ``plot_disp_curves``
    (modules/utils.py:690-698): mean / (max-min) / std across the
    ``(n_bootstrap, nf)`` ridge matrix of one frequency band.
    """
    v = np.asarray(ridge_vels, dtype=np.float64)
    return v.mean(axis=0), v.max(axis=0) - v.min(axis=0), v.std(axis=0)


def curves_from_ridges(
    freqs: np.ndarray,
    freq_lb: Sequence[float],
    freq_ub: Sequence[float],
    ridge_vels: Sequence[np.ndarray],
    band_modes: Sequence[int],
    weights: Sequence[float] | None = None,
    skip_bands: Sequence[int] = (),
) -> list[Curve]:
    """Build period-domain curves from per-band bootstrap ridges.

    Reference: inversion_diff_speed.ipynb cell 5 - band ``i`` covers
    ``freq_lb[i] <= f < freq_ub[i]``; velocities m/s -> km/s; arrays are
    reversed so period ascends; uncertainty = bootstrap range.
    ``band_modes`` maps each band to its modal order (the reference uses
    bands 0,2,3 as modes 0,3,4 and skips band 1).
    """
    freqs = np.asarray(freqs, dtype=np.float64)
    weights = list(weights) if weights is not None else [1.0] * len(ridge_vels)
    curves = []
    for i, vels in enumerate(ridge_vels):
        if i in skip_bands:
            continue
        mask = (freqs >= freq_lb[i]) & (freqs < freq_ub[i])
        mean, rng, _ = ridge_stats(vels)
        periods = (1.0 / freqs[mask])[::-1]
        curves.append(
            Curve(
                period=periods,
                velocity=mean[::-1] / 1000.0,
                mode=int(band_modes[i]),
                weight=float(weights[i]),
                uncertainty=np.maximum(rng[::-1] / 1000.0, 1e-4),
            )
        )
    return curves


def load_reference_ridge_npz(path: str):
    """Load a ``{x0}_speeds.npz`` / ``{x0}_weights.npz``-layout archive
    (reference data/700_speeds.npz: ``freqs``, ``freq_lb``, ``freq_ub``,
    plus per-class ``vels_*`` object arrays of bootstrap ridges)."""
    d = np.load(path, allow_pickle=True)
    out = {k: d[k] for k in d.files}
    return out
