"""Fleet inversion: ONE compiled program inverts T curve sets at once.

The closure path (:func:`~das_diff_veh_tpu.inversion.invert.make_misfit_fn`)
bakes each curve set into a Python closure — concatenated arrays captured
by value, per-curve RMSE recovered by Python-level static slices — so every
new target re-traces and re-compiles the jitted swarm/refine executables
(keyed on the closure's identity) and a bootstrap/time-lapse fleet runs
serially.  This module makes the misfit *data-parameterized* instead:

* :func:`pack_curve_sets` pads T ragged curve sets into ``(T, max_pts)``
  period/velocity/uncertainty/mode tensors with a validity mask and
  per-point curve-segment ids (:class:`CurveBatch`);
* :func:`make_packed_misfit_fn` builds ``misfit(x01, curve_batch)`` where
  per-curve RMSE is a masked segment reduction (``jax.ops.segment_sum``
  with a static segment count) — numerically the same objective as the
  closure, but the observations are *traced operands*, so one traced
  function serves every curve set with the same (geometry, budget);
* :func:`invert_fleet` stacks a target-axis ``vmap`` on top of
  :func:`~das_diff_veh_tpu.inversion.invert.invert_multirun`'s run-axis
  ``vmap``, shards the target axis over an optional device mesh (same
  NamedSharding pattern as the multirun run axis), and host-chunks the
  (targets x runs x pop) working set through ``target_chunk`` /
  ``eval_chunk`` / ``refine_chunk`` so big fleets stay inside HBM.

On top of the batched ensemble, :class:`FleetResult` carries per-target
credible intervals from the pooled multi-start population (deep-ensembles
style — Lakshminarayanan et al., NeurIPS 2017: independently-initialised
restarts as an ensemble posterior; PAPERS.md), and
:func:`detect_vs_shifts` turns a (baseline, current) result pair into
change-detection events for the obs registry
(``pipeline.timelapse.FleetVsMonitor``).

Parity contract: the packed misfit must agree with the closure oracle on
the same curves (pinned in tests/test_fleet_inversion.py, including at the
committed ``INVERSION_PARITY.json`` best models), and the credible-interval
machinery never touches best-model selection — uncertainty can only
annotate, never loosen, a misfit.

Seeding contract: fleet target ``t`` run ``r`` uses
``PRNGKey(seed + t * n_runs + r)``, i.e. target ``t`` reproduces
``invert_multirun(..., seed=seed + t * n_runs)`` exactly (same init, same
``fold_in`` chunk stream) — the per-target equivalence tests rely on it.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from das_diff_veh_tpu.inversion.curves import Curve
from das_diff_veh_tpu.inversion.forward import phase_velocity
from das_diff_veh_tpu.inversion.invert import (INVALID_RESIDUAL, ModelSpec,
                                               _misfit_batch, _pso_init,
                                               _pso_run, _refine_run)


class CurveBatch(NamedTuple):
    """T ragged curve sets packed into padded, maskable tensors.

    Leading axes are arbitrary (the fleet engine carries ``(T, ...)`` and
    vmaps the target axis away); trailing axes are ``P`` points
    (``period``..``segment``) and ``S`` curve slots (``weight``).  Padding
    points carry ``valid=False``, a benign period (1.0 s) and segment 0 —
    they are masked out of every reduction; padding curve slots carry
    weight 0.  ``wsum`` is the per-target sum of real curve weights (the
    closure's weight normaliser)."""

    period: jnp.ndarray        # (..., P) seconds, padded with 1.0
    velocity: jnp.ndarray      # (..., P) km/s
    uncertainty: jnp.ndarray   # (..., P) km/s, padded with 1.0
    mode: jnp.ndarray          # (..., P) int32 modal order, padded with 0
    valid: jnp.ndarray         # (..., P) bool point-validity mask
    segment: jnp.ndarray       # (..., P) int32 curve id in [0, S)
    weight: jnp.ndarray        # (..., S) per-curve weights, padded with 0
    wsum: jnp.ndarray          # (...,)   sum of real weights

    @property
    def n_targets(self) -> int:
        return self.period.shape[0]

    @property
    def n_curves(self) -> int:
        return self.weight.shape[-1]


def pack_curve_sets(curve_sets: Sequence[Sequence[Curve]], dtype=None,
                    max_points: Optional[int] = None,
                    max_curves: Optional[int] = None) -> CurveBatch:
    """Pack T ragged curve sets into one padded :class:`CurveBatch`.

    ``max_points``/``max_curves`` pin the padded capacity (they must cover
    the largest set); fixing them across fleets keeps the packed shapes —
    and therefore the compiled fleet programs — identical between calls.
    ``dtype`` pins the float dtype of the packed observations (None =
    default float, matching :func:`make_misfit_fn`'s ``dtype=None``)."""
    if not curve_sets:
        raise ValueError("pack_curve_sets needs at least one curve set")
    counts = [[int(np.asarray(c.period).shape[0]) for c in cs]
              for cs in curve_sets]
    if any(len(c) == 0 for c in counts):
        raise ValueError("every curve set needs at least one curve")
    p_need = max(sum(cnt) for cnt in counts)
    s_need = max(len(cnt) for cnt in counts)
    P = p_need if max_points is None else int(max_points)
    S = s_need if max_curves is None else int(max_curves)
    if P < p_need or S < s_need:
        raise ValueError(f"capacity ({P} pts, {S} curves) below largest "
                         f"set ({p_need} pts, {s_need} curves)")
    T = len(curve_sets)
    per = np.ones((T, P))
    vel = np.zeros((T, P))
    unc = np.ones((T, P))
    mode = np.zeros((T, P), dtype=np.int32)
    valid = np.zeros((T, P), dtype=bool)
    seg = np.zeros((T, P), dtype=np.int32)
    w = np.zeros((T, S))
    for t, cs in enumerate(curve_sets):
        o = 0
        for i, c in enumerate(cs):
            p = np.asarray(c.period, dtype=np.float64)
            n = p.shape[0]
            per[t, o:o + n] = p
            vel[t, o:o + n] = np.asarray(c.velocity, dtype=np.float64)
            unc[t, o:o + n] = (np.asarray(c.uncertainty, dtype=np.float64)
                               if c.uncertainty is not None else 1.0)
            mode[t, o:o + n] = int(c.mode)
            valid[t, o:o + n] = True
            seg[t, o:o + n] = i
            w[t, i] = float(c.weight)
            o += n
    return CurveBatch(period=jnp.asarray(per, dtype),
                      velocity=jnp.asarray(vel, dtype),
                      uncertainty=jnp.asarray(unc, dtype),
                      mode=jnp.asarray(mode),
                      valid=jnp.asarray(valid),
                      segment=jnp.asarray(seg),
                      weight=jnp.asarray(w, dtype),
                      wsum=jnp.asarray(w.sum(axis=1), dtype))


@functools.lru_cache(maxsize=64)
def make_packed_misfit_fn(spec: ModelSpec, n_grid: int = 400,
                          n_subdiv: int = 1, invalid: str = "penalty"):
    """``misfit(x01, curve_batch) -> scalar`` for ONE target's packed set.

    Numerically the closure objective of :func:`make_misfit_fn` — evodcinv
    'rmse': per curve ``sqrt(mean(((obs-pred)/unc)^2))``, weight-normalised
    sum; below-cutoff handling per ``invalid`` ("penalty": fixed
    INVALID_RESIDUAL per missing point; "truncate": missing points drop
    from the per-curve mean) — but with the observations as traced operands
    and the per-curve reduction as a masked ``segment_sum`` over static
    segment count, so one traced function (and one jitted swarm/refine
    executable keyed on it) serves every curve set of a given padded shape.

    lru-cached on ``(spec, n_grid, n_subdiv, invalid)``: repeated fleets
    with the same geometry/budget get the SAME function object, which is
    what keeps the jit caches warm across calls (the one-program
    amortization the bench entry measures)."""
    assert invalid in ("penalty", "truncate")

    def misfit(x01, cb: CurveBatch):
        model = spec.to_model(x01)
        pred = phase_velocity(cb.period, model, mode=cb.mode,
                              n_grid=n_grid, n_subdiv=n_subdiv)
        fin = jnp.isfinite(pred) & cb.valid
        r = (cb.velocity - pred) / cb.uncertainty
        r = jnp.where(fin, r, INVALID_RESIDUAL)   # below-cutoff -> penalty
        r = jnp.where(cb.valid, r, 0.0)           # padding contributes 0
        n_seg = cb.weight.shape[-1]
        one = jnp.ones_like(r)
        zero = jnp.zeros_like(r)
        npts = jax.ops.segment_sum(jnp.where(cb.valid, one, zero),
                                   cb.segment, n_seg)
        if invalid == "truncate":
            n_fin = jax.ops.segment_sum(jnp.where(fin, one, zero),
                                        cb.segment, n_seg)
            ss = jax.ops.segment_sum(jnp.where(fin, r * r, zero),
                                     cb.segment, n_seg)
            rmse = jnp.sqrt(ss / jnp.maximum(n_fin, 1.0))
            rmse = jnp.where(n_fin > 0, rmse, INVALID_RESIDUAL)
            # padding curve slots (npts == 0) carry weight 0 anyway; zero
            # them so 0 * INVALID_RESIDUAL can never leak through a NaN
            rmse = jnp.where(npts > 0, rmse, 0.0)
        else:
            ss = jax.ops.segment_sum(r * r, cb.segment, n_seg)
            rmse = jnp.sqrt(ss / jnp.maximum(npts, 1.0))
        return jnp.sum(cb.weight * rmse) / cb.wsum

    return misfit


class FleetResult(NamedTuple):
    """Per-target best models + pooled-ensemble credible intervals.

    All fields are HOST numpy arrays with a leading target axis ``T`` (the
    fleet engine pulls each target chunk in one ``device_get``).  The
    interval fields come from the pooled multi-start ensemble (population +
    refined members with misfit within ``credible_factor`` of the target's
    best) — deep-ensembles style; they are widened to always contain the
    best model's profile, and computing them never alters which member is
    selected as best ("uncertainty never loosens misfits")."""

    x_best: np.ndarray       # (T, n_params) unit-cube best model
    misfit: np.ndarray       # (T,)
    thickness: np.ndarray    # (T, n_layers) km
    vs: np.ndarray           # (T, n_layers) km/s best-model profile
    vs_lo: np.ndarray        # (T, n_layers) lower credible bound
    vs_med: np.ndarray       # (T, n_layers) ensemble median
    vs_hi: np.ndarray        # (T, n_layers) upper credible bound
    n_ensemble: np.ndarray   # (T,) members inside the credible cut
    models_x: np.ndarray     # (T, M, n_params) pooled pop + refined
    misfits: np.ndarray      # (T, M)
    history: np.ndarray      # (T, maxiter) best-so-far misfit trace


class VsShiftEvent(NamedTuple):
    """One layer of one target drifted outside the baseline interval."""

    target: int
    layer: int
    vs: float        # current best-model Vs (km/s)
    lo: float        # baseline interval bounds it escaped
    hi: float


def detect_vs_shifts(baseline: FleetResult,
                     current: FleetResult) -> list[VsShiftEvent]:
    """Change detection: layers whose current best Vs falls outside the
    BASELINE's credible interval.  Pure function of two results; the obs
    wiring (counter/alarm/flight record) lives in
    ``pipeline.timelapse.FleetVsMonitor``."""
    if baseline.vs.shape != current.vs.shape:
        raise ValueError(f"baseline/current fleet shapes differ: "
                         f"{baseline.vs.shape} vs {current.vs.shape}")
    out = (current.vs < baseline.vs_lo) | (current.vs > baseline.vs_hi)
    events = []
    for t, layer in zip(*np.nonzero(out)):
        events.append(VsShiftEvent(
            target=int(t), layer=int(layer),
            vs=float(current.vs[t, layer]),
            lo=float(baseline.vs_lo[t, layer]),
            hi=float(baseline.vs_hi[t, layer])))
    return events


def _ensemble_intervals(spec: ModelSpec, models_x: np.ndarray,
                        misfits: np.ndarray, factor: float,
                        q: tuple[float, float]):
    """Per-layer Vs quantiles over the credible members of each target's
    pooled ensemble.  Members qualify when their misfit is finite and
    within ``factor`` x the target's best (the best member always
    qualifies, so ``n_ensemble >= 1``)."""
    lo_b, hi_b = (np.asarray(a, dtype=np.float64)
                  for a in spec.bounds_arrays())
    n = spec.n_layers
    x = lo_b + (hi_b - lo_b) * np.clip(models_x, 0.0, 1.0)
    vs_all = x[..., n:2 * n]                              # (T, M, L)
    best = np.nanmin(np.where(np.isfinite(misfits), misfits, np.inf),
                     axis=1, keepdims=True)
    sel = np.isfinite(misfits) & (misfits <= factor * best)
    v = np.where(sel[..., None], vs_all, np.nan)
    lo_q = np.nanquantile(v, q[0], axis=1)
    med = np.nanquantile(v, 0.5, axis=1)
    hi_q = np.nanquantile(v, q[1], axis=1)
    return lo_q, med, hi_q, sel.sum(axis=1)


def invert_fleet(spec: ModelSpec,
                 curve_sets: Optional[Sequence[Sequence[Curve]]] = None, *,
                 batch: Optional[CurveBatch] = None, n_runs: int = 2,
                 popsize: int = 50, maxiter: int = 200,
                 n_refine_starts: int = 8, n_refine_steps: int = 80,
                 n_grid: int = 400, n_subdiv: int = 1, dtype=None,
                 invalid: str = "penalty", seed: int = 0, chunk: int = 50,
                 eval_chunk: int = 0, refine_chunk: int = 0,
                 target_chunk: int = 0, credible_factor: float = 2.0,
                 credible_q: tuple[float, float] = (0.05, 0.95),
                 x0=None, mesh=None, mesh_axis: str = "win") -> FleetResult:
    """Invert T curve sets as one target-axis-vmapped, mesh-shardable
    computation: ONE XLA program per (geometry, budget) regardless of T.

    Parameters mirror :func:`invert_multirun` per target, plus:

    ``batch``: a prebuilt :class:`CurveBatch` (e.g. from
    :func:`pack_curve_sets` with pinned capacities) instead of
    ``curve_sets``; passing the same padded shapes across calls reuses the
    compiled programs.

    ``target_chunk``: host-chunks the target axis (0 = all targets in one
    device program).  Chunks are padded to a fixed size (by repeating a
    real target, later dropped), so every chunk runs the SAME compiled
    program — the program count is invariant in T.

    ``credible_factor``/``credible_q``: pooled-ensemble credible cut and
    quantiles for the per-target Vs intervals (see :class:`FleetResult`).

    ``mesh``: shards the *target* axis over ``mesh_axis`` (each device
    inverts its own targets; targets are independent so results match the
    unsharded run to cross-restart-fusion tolerance).  The padded chunk
    size is rounded up to a device-count multiple for even placement.
    """
    if batch is None:
        if curve_sets is None:
            raise ValueError("pass curve_sets or a packed batch")
        batch = pack_curve_sets(curve_sets, dtype=dtype)
    misfit_fn = make_packed_misfit_fn(spec, n_grid=n_grid,
                                      n_subdiv=n_subdiv, invalid=invalid)
    T = batch.n_targets
    tc = target_chunk if (target_chunk and target_chunk < T) else T
    if mesh is not None:
        ndev = int(mesh.shape[mesh_axis])
        tc = -(-tc // ndev) * ndev          # round up to a device multiple

    def _shard_targets(tree):
        if mesh is None:
            return tree
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(a):
            spec_ = P(*((mesh_axis,) + (None,) * (a.ndim - 1)))
            return jax.device_put(a, NamedSharding(mesh, spec_))

        return jax.tree.map(place, tree)

    if x0 is not None:
        x0 = jnp.asarray(np.asarray(x0, dtype=np.float64), dtype)
    init = functools.partial(
        _pso_init, misfit_fn, n_params=spec.n_params, popsize=popsize,
        dtype=dtype, eval_chunk=eval_chunk, x0=x0)

    chunks = []
    for start in range(0, T, tc):
        # fixed-size chunk: pad by repeating the chunk's first target
        # (dropped after device_get), so every chunk hits the same program
        sel = np.arange(start, start + tc)
        sel = np.where(sel < T, sel, start)
        keep = tc if start + tc <= T else T - start
        # numpy gather: eager jax indexing would trace tc-dependent
        # index-normalization programs, breaking the T-invariant trace count
        data = jax.tree.map(lambda a: jnp.asarray(np.asarray(a)[sel]), batch)
        seeds = seed + sel[:, None] * n_runs + np.arange(n_runs)[None, :]
        keys = jax.vmap(jax.vmap(jax.random.PRNGKey))(jnp.asarray(seeds))
        keys = _shard_targets(keys)
        data = _shard_targets(data)
        states = _shard_targets(jax.vmap(
            lambda ks, d: jax.vmap(lambda k: init(k, d))(ks))(keys, data))
        traces, done = [], 0
        while done < maxiter:
            n = min(chunk, maxiter - done)
            step_keys = jax.vmap(jax.vmap(
                lambda k: jax.random.fold_in(k, 7 + done)))(keys)
            states, tr = jax.vmap(
                lambda st, ks, d: jax.vmap(
                    lambda s, k: _pso_run(misfit_fn, s, k, n,
                                          eval_chunk=eval_chunk,
                                          data=d))(st, ks))(
                states, step_keys, data)
            traces.append(tr)                         # (tc, n_runs, n)
            done += n
        _, _, pop_x, pop_f, gbest_x, gbest_f = states  # (tc, runs, pop, ..)

        k = min(n_refine_starts, popsize)
        top = jnp.argsort(pop_f, axis=2)[..., :k]      # (tc, runs, k)
        starts = jnp.concatenate(
            [gbest_x[:, :, None],
             jnp.take_along_axis(pop_x, top[..., None], axis=2)],
            axis=2).reshape(tc, -1, spec.n_params)     # per-target pooled
        ref_x, ref_f = _refine_fleet(misfit_fn, starts, data,
                                     n_refine_steps,
                                     refine_chunk=refine_chunk)

        all_x = jnp.concatenate(
            [pop_x.reshape(tc, -1, spec.n_params), ref_x], axis=1)
        all_f = jnp.concatenate([pop_f.reshape(tc, -1), ref_f], axis=1)
        hist = jnp.min(jnp.concatenate(traces, axis=-1), axis=1)
        ax, af, ah = jax.device_get((all_x, all_f, hist))
        chunks.append((ax[:keep], af[:keep], ah[:keep]))

    models_x = np.concatenate([c[0] for c in chunks], axis=0)
    misfits = np.concatenate([c[1] for c in chunks], axis=0)
    history = np.concatenate([c[2] for c in chunks], axis=0)

    best = np.argmin(misfits, axis=1)
    x_best = np.take_along_axis(
        models_x, best[:, None, None], axis=1)[:, 0]
    misfit_best = np.take_along_axis(misfits, best[:, None], axis=1)[:, 0]
    lo_b, hi_b = (np.asarray(a, dtype=np.float64)
                  for a in spec.bounds_arrays())
    xb = lo_b + (hi_b - lo_b) * np.clip(x_best, 0.0, 1.0)
    nl = spec.n_layers
    thickness, vs = xb[:, :nl], xb[:, nl:2 * nl]
    vs_lo, vs_med, vs_hi, n_ens = _ensemble_intervals(
        spec, models_x, misfits, credible_factor, credible_q)
    # the interval always contains the shipped best profile (a best model
    # at an extreme quantile would otherwise sit outside its own interval
    # and every epoch would false-alarm against itself)
    vs_lo = np.minimum(vs_lo, vs)
    vs_hi = np.maximum(vs_hi, vs)
    return FleetResult(x_best=x_best, misfit=misfit_best,
                       thickness=thickness, vs=vs, vs_lo=vs_lo,
                       vs_med=vs_med, vs_hi=vs_hi, n_ensemble=n_ens,
                       models_x=models_x, misfits=misfits, history=history)


def _refine_fleet(misfit_fn, starts, data, n_steps: int, lr: float = 0.02,
                  step_chunk: int = 50, refine_chunk: int = 0):
    """Per-target pooled multi-start Adam refinement with a target axis:
    the fleet-shaped twin of :func:`invert._refine` (same logit-space
    iteration, same host chunking over steps and starts)."""
    eps = 1e-4
    z = jax.scipy.special.logit(jnp.clip(starts, eps, 1.0 - eps))
    S = z.shape[1]
    rc = refine_chunk if (refine_chunk and refine_chunk < S) else S
    xs, fs = [], []
    for i in range(0, S, rc):
        zi = z[:, i:i + rc]
        opt_state = jax.vmap(jax.vmap(optax.adam(lr).init))(zi)
        done = 0
        while done < n_steps:
            n = min(step_chunk, n_steps - done)
            zi, opt_state = jax.vmap(
                lambda zz, oo, dd: _refine_run(misfit_fn, zz, oo, n, lr,
                                               data=dd))(zi, opt_state, data)
            done += n
        xi = jax.nn.sigmoid(zi)
        xs.append(xi)
        fs.append(jax.vmap(
            lambda xx, dd: _misfit_batch(misfit_fn, xx, dd))(xi, data))
    return jnp.concatenate(xs, axis=1), jnp.concatenate(fs, axis=1)
