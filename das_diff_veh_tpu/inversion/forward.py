"""Differentiable Rayleigh-wave phase-velocity forward model.

TPU-first replacement for the reference's external ``disba`` dependency
(numba surf96 Dunkin-matrix code, imported at
/root/reference/inversion_diff_speed.ipynb cell 0 and driven through
``evodcinv.EarthModel.invert``).  Rather than translating surf96's
hand-derived delta-matrix formulas, we re-derive the computation in a form
that is (a) verifiable piece by piece and (b) smooth/differentiable end to
end so ``jax.grad`` gives exact sensitivities:

* The P-SV displacement-stress field ``y = (V, W, S, T)`` (with ``V = i*u``
  and ``T = i*tau_zx`` so everything is real) obeys ``y' = A y`` with a real
  4x4 coefficient matrix per layer (Aki & Richards ch. 7 form).
* The layer propagator ``M = expm(A d)`` is evaluated in closed form as a
  cubic polynomial in ``A`` whose coefficients are *entire* functions of the
  squared vertical wavenumbers (``cosh``/``sinh`` below the velocity,
  ``cos``/``sin`` above, one smooth formula for both) - no complex numbers,
  no branch cuts, exact derivatives.
* Instead of propagating single solution vectors (numerically unstable: the
  two fundamental solutions collapse onto the fastest-growing one), we
  propagate the *bivector* of the two free-surface solutions as an
  antisymmetric matrix ``Wg <- M Wg M^T``.  This tracks exactly the 2x2
  minors that Dunkin's (1965) delta-matrix method tracks - same numerical
  stability - without hand-coded 6x6 compound matrices.  Each step is
  renormalised (positive scale), which leaves the secular function's roots
  and signs unchanged.
* The secular function is the 4x4 determinant ``det[vp, vs, y1, y2]``
  pairing the halfspace's two downward-decaying eigenvectors with the
  propagated surface solutions; modes are its roots in ``c``.
* Root finding: sign-change scan on a static ``c`` grid, batched
  subdivision refinement, then one Newton polish step written so the
  implicit-function-theorem gradient ``dc/dtheta = -D_theta / D_c`` flows
  through ``jax.grad``/``jax.jacfwd``.

Everything is written *natively batched*: ``secular`` accepts arbitrary
broadcastable ``(c, omega)`` arrays and runs ONE ``lax.scan`` over layers of
``(..., 4, 4)`` tensors.  This matters enormously for XLA compile time -
the round-2 formulation (scalar secular + nested ``vmap`` per period per
grid point per particle) produced graphs that took minutes to compile; the
batched form compiles in seconds and evaluates a whole (population x
period x grid) workload in a single fused scan.

Units follow disba's convention: km, km/s, g/cm^3, periods in seconds.
Layer hyperbolics are evaluated in exponentially-scaled form, so both
float64 (CPU; ~1e-12 root accuracy) and float32 (TPU; ~1e-5 relative)
work without overflow.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class LayeredModel(NamedTuple):
    """1-D layered elastic model; the last entry is the halfspace.

    Attributes are ``(n_layers,)`` arrays: ``thickness`` (km; the last
    value is ignored - halfspace), ``vp``/``vs`` (km/s), ``rho`` (g/cm^3).
    """

    thickness: jnp.ndarray
    vp: jnp.ndarray
    vs: jnp.ndarray
    rho: jnp.ndarray


def vp_from_poisson(vs: jnp.ndarray, nu: jnp.ndarray) -> jnp.ndarray:
    """P velocity from S velocity and Poisson's ratio.

    ``vp/vs = sqrt((2-2nu)/(1-2nu))``; the reference fixes ``nu = 0.4375``
    (inversion_diff_speed.ipynb cell 7) giving exactly ``vp = 3 vs``.
    """
    return vs * jnp.sqrt((2.0 - 2.0 * nu) / (1.0 - 2.0 * nu))


def density_gardner_linear(vp: jnp.ndarray) -> jnp.ndarray:
    """The reference's density model ``rho = 1.56 + 0.186 vp`` (g/cm^3,
    vp km/s) - ``f_rho`` in inversion_diff_speed.ipynb cell 7 (evodcinv
    applies its ``density`` callable to P velocity)."""
    return 1.56 + 0.186 * vp


# -- entire-function building blocks ----------------------------------------


def _sqrt_relu(x: jnp.ndarray) -> jnp.ndarray:
    """sqrt(max(x, 0)) with a zero (not NaN) gradient on x <= 0.

    A bare ``sqrt(where(x > 0, x, 0))`` back-propagates ``0 * inf = NaN``
    through the inactive branch; the dummy-operand pattern avoids it.
    """
    pos = x > 0
    return jnp.where(pos, jnp.sqrt(jnp.where(pos, x, 1.0)), 0.0)


def _scaled_trig(x: jnp.ndarray, s: jnp.ndarray):
    """(cosh(sqrt(x)) e^-s, sinh(sqrt(x))/sqrt(x) e^-s), continued to x<0
    as cos/sinc - entire functions of x, pre-scaled by e^-s so that no
    intermediate ever exceeds O(1) even when sqrt(x) is in the hundreds
    (k d reaches ~100 at 20 Hz x 80 m layers; unscaled cosh overflows
    float32 at ~89 and float64 at ~710)."""
    pos = x >= 0
    big = x >= 1e-8
    neg = x <= -1e-8
    xr = _sqrt_relu(x)                         # |Re sqrt(x)|, grad-safe
    xn = jnp.sqrt(jnp.where(neg, -x, 1.0))
    ep = jnp.exp(xr - s)                       # <= 1 by construction of s
    en = jnp.exp(-xr - s)
    es = jnp.exp(-s)
    c_pos = 0.5 * (ep + en)
    s_pos = jnp.where(big, 0.5 * (ep - en) / jnp.where(big, xr, 1.0),
                      (1.0 + x / 6.0) * es)   # series covers |x| < 1e-8
    c_neg = jnp.cos(xn) * es
    s_neg = jnp.where(neg, jnp.sin(xn) / xn * es, (1.0 + x / 6.0) * es)
    cv = jnp.where(pos, c_pos, c_neg)
    sv = jnp.where(pos, s_pos, jnp.where(neg, s_neg, (1.0 + x / 6.0) * es))
    return cv, sv


def _mm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched 4x4 matmul at full input precision: TPUs default to bfloat16
    MXU multiplication, which destroys the secular function's delicate minor
    structure; these tiny products belong on the VPU at float32 anyway."""
    return jnp.matmul(a, b, precision=lax.Precision.HIGHEST)


def _mT(a: jnp.ndarray) -> jnp.ndarray:
    """Transpose of the trailing 4x4 block (batch dims untouched)."""
    return jnp.swapaxes(a, -1, -2)


# projection basis for the symplectic invariant Wg[0,3] + Wg[1,2] = 0:
# adding delta * _SYMPL subtracts delta from the [0,3]/[1,2] slots (and
# adds it to their antisymmetric mirrors).
_SYMPL = jnp.zeros((4, 4)).at[0, 3].set(-1.0).at[3, 0].set(1.0) \
                          .at[1, 2].set(-1.0).at[2, 1].set(1.0)


def _project_symplectic(W: jnp.ndarray) -> jnp.ndarray:
    """Project the antisymmetric ``(..., 4, 4)`` bivector back onto the
    Plucker/symplectic constraint surface (see ``secular``)."""
    delta = 0.5 * (W[..., 0, 3] + W[..., 1, 2])
    return W + delta[..., None, None] * _SYMPL.astype(W.dtype)


def _fro_normalise(W: jnp.ndarray) -> jnp.ndarray:
    """Smooth (Frobenius) renormalisation of trailing 4x4 blocks: keeps
    magnitudes O(1) without introducing max()-kinks into the secular
    function's c-derivative."""
    n = jnp.sqrt(jnp.sum(W * W, axis=(-2, -1), keepdims=True))
    return W / (n + jnp.finfo(W.dtype).tiny)


# -- layer system ------------------------------------------------------------


def _layer_A(k, omega, vp, vs, rho, stress_scale=1.0):
    """Real ``(..., 4, 4)`` coefficient matrix of y' = A y for
    y = (V, W, S, T); ``k``/``omega`` (and optionally ``stress_scale``) may
    carry arbitrary broadcastable batch dims, layer properties are scalars.

    Derived from plane-strain elastodynamics with u = -iV, tau_zx = -iT
    (harmonic e^{i(kx - omega t)}); eigenvalues are +-k*nu_p, +-k*nu_s with
    nu^2 = 1 - c^2/v^2 (verified in tests against the analytic halfspace
    eigenvectors).

    ``stress_scale`` nondimensionalises the stress components (S,T)/scale -
    a similarity transform diag(1,1,1/s,1/s) A diag(1,1,s,s) that leaves
    eigenvalues (and secular roots) unchanged but keeps all matrix entries
    comparable in magnitude, which matters for the final 6-term determinant
    cancellation (mixed units cost ~6 digits of the root-side noise floor).
    """
    k = jnp.asarray(k)
    mu = rho * vs * vs
    lam = rho * (vp * vp - 2.0 * vs * vs)
    lam2mu = lam + 2.0 * mu
    zeta = 4.0 * mu * (lam + mu) / lam2mu
    rw2 = rho * omega * omega * jnp.ones_like(k)
    s0 = stress_scale * jnp.ones_like(k)
    z = jnp.zeros_like(k)
    rows = [
        jnp.stack([z, k, z, s0 / mu], axis=-1),
        jnp.stack([-lam * k / lam2mu, z, s0 / lam2mu, z], axis=-1),
        jnp.stack([z, -rw2 / s0, z, -k], axis=-1),
        jnp.stack([(k * k * zeta - rw2) / s0, z, lam * k / lam2mu, z],
                  axis=-1),
    ]
    return jnp.stack(rows, axis=-2)


def _layer_propagator(k, omega, d, vp, vs, rho, stress_scale=1.0):
    """expm(A d) in closed form, batched over ``k``/``omega``: A's
    eigenvalues are +-a, +-b with a^2 = k^2 - omega^2/vp^2,
    b^2 = k^2 - omega^2/vs^2, so expm(A d) = c0 I + c1 A + c2 A^2 + c3 A^3
    with coefficients matching cosh/sinh on the two eigenvalue pairs
    (Lagrange interpolation on the minimal polynomial).  Entire in a^2, b^2
    => smooth across c = vp, vs.
    """
    k = jnp.asarray(k)
    a2 = (k * k - (omega / vp) ** 2) * d * d
    b2 = (k * k - (omega / vs) ** 2) * d * d
    # common scale e^-s with s = max evanescent exponent: the returned
    # matrix is e^-s expm(A d) - a positive multiple, which leaves the
    # secular function's roots/signs unchanged and keeps everything finite
    # in float32 on TPU.
    # smooth upper bound on max(|a|,|b|): Newton root-polish differentiates
    # the secular function, so every rescaling factor must be smooth in c -
    # a hard max would put kinks exactly where Newton needs a slope.
    s = jnp.logaddexp(_sqrt_relu(a2), _sqrt_relu(b2))
    ca, sa = _scaled_trig(a2, s)
    cb, sb = _scaled_trig(b2, s)  # s* = sinh(sqrt)/sqrt, scaled
    denom = a2 - b2  # = omega^2 d^2 (1/vs^2 - 1/vp^2) > 0 always (vp > vs)
    c2 = (ca - cb) / denom
    c0 = ca - c2 * a2
    c3 = (sa - sb) / denom
    c1 = sa - c3 * a2
    Ad = _layer_A(k, omega, vp, vs, rho, stress_scale) * d
    Ad2 = _mm(Ad, Ad)
    eye = jnp.eye(4, dtype=Ad.dtype)
    e = lambda c: c[..., None, None]
    return e(c0) * eye + e(c1) * Ad + e(c2) * Ad2 + e(c3) * _mm(Ad, Ad2)


def _halfspace_bivector(k, omega, vp, vs, rho, stress_scale=1.0):
    """Antisymmetric ``(..., 4, 4)`` matrix v_p ^ v_s of the halfspace's two
    downward-decaying eigenvectors (eigenvalues -k nu_p, -k nu_s; require
    c < vs)."""
    k = jnp.asarray(k)
    c = omega / k
    mu = rho * vs * vs
    nup2 = 1.0 - (c / vp) ** 2
    nus2 = 1.0 - (c / vs) ** 2
    # guard: modes only exist for c < vs_halfspace; callers mask c >= vs.
    nup = jnp.sqrt(jnp.maximum(nup2, 1e-12))
    nus = jnp.sqrt(jnp.maximum(nus2, 1e-12))
    s0 = stress_scale * jnp.ones_like(k)
    one = jnp.ones_like(k)
    v1 = jnp.stack([one, nup,
                    -rho * k * (2.0 * vs * vs - c * c) / s0,
                    -2.0 * mu * k * nup / s0], axis=-1)
    v2 = jnp.stack([nus, one, -2.0 * mu * k * nus / s0,
                    -mu * k * (2.0 - (c / vs) ** 2) / s0], axis=-1)
    V = v1[..., :, None] * v2[..., None, :] - v2[..., :, None] * v1[..., None, :]
    # V[0,3] + V[1,2] = 0 analytically (symplectic product of eigenvectors
    # with lambda1 + lambda2 != 0); enforce it exactly - see secular().
    return _project_symplectic(V)


def secular(c, omega, model: LayeredModel):
    """Rayleigh secular function D(c, omega); zero exactly at modal phase
    velocities.  Sign-normalised per layer so values stay O(1).

    ``c`` and ``omega`` may be scalars or broadcastable arrays; the whole
    batch runs through ONE ``lax.scan`` over layers of ``(..., 4, 4)``
    tensors (the compile-time-friendly form - see module docstring).

    Mirrors the role of disba's dunkin/fast-delta secular function
    (reference uses it via evodcinv, inversion_diff_speed.ipynb cell 9),
    computed as det[v_p, v_s, y1, y2] with the bivector recursion described
    in the module docstring.
    """
    dt = jnp.result_type(jnp.asarray(c).dtype, jnp.asarray(omega).dtype,
                         model.vs.dtype)
    c, omega = jnp.broadcast_arrays(jnp.asarray(c, dt), jnp.asarray(omega, dt))
    k = omega / c
    # global stress nondimensionalisation (see _layer_A): mu_1 * k
    s0 = model.rho[0] * model.vs[0] * model.vs[0] * k
    Wg = jnp.zeros((*k.shape, 4, 4), dtype=dt)
    Wg = Wg.at[..., 0, 1].set(1.0).at[..., 1, 0].set(-1.0)

    layer_params = (model.thickness[:-1], model.vp[:-1], model.vs[:-1],
                    model.rho[:-1])

    def step(Wg, p):
        d, a, b, r = p
        M = _layer_propagator(k, omega, d, a, b, r, s0)
        Wg = _mm(_mm(M, Wg), _mT(M))
        # The elastic ODE conserves the symplectic product
        # Q(y1,y2) = V1 T2 - T1 V2 + W1 S2 - S1 W2 = Wg[0,3] + Wg[1,2],
        # which is exactly 0 for the free-surface pair.  Round-off drift in
        # this invariant is what floors |D| near roots (the cancellation
        # surf96's reduced 5-component delta vector eliminates); project it
        # back out after every layer.
        Wg = _fro_normalise(_project_symplectic(Wg))
        return Wg, None

    Wg, _ = lax.scan(step, Wg, layer_params)

    V = _halfspace_bivector(k, omega, model.vp[-1], model.vs[-1],
                            model.rho[-1], s0)
    V = _fro_normalise(V)
    # det[v_p, v_s, y1, y2] = sum_{i<j} sign(ij,comp) V_ij W_comp(ij)
    D = (V[..., 0, 1] * Wg[..., 2, 3] - V[..., 0, 2] * Wg[..., 1, 3]
         + V[..., 0, 3] * Wg[..., 1, 2] + V[..., 1, 2] * Wg[..., 0, 3]
         - V[..., 1, 3] * Wg[..., 0, 2] + V[..., 2, 3] * Wg[..., 0, 1])
    return D


# -- root finding ------------------------------------------------------------


def _first_flip(Df: jnp.ndarray):
    """Index of the first sign change along the last axis of ``Df``."""
    s = jnp.sign(Df)
    flips = (s[..., :-1] * s[..., 1:]) < 0
    return jnp.argmax(flips, axis=-1)


@partial(jax.jit, static_argnames=("n_grid", "n_subdiv", "subdiv_pts"))
def phase_velocity(periods, model: LayeredModel, mode: int | jnp.ndarray = 0,
                   cmin=None, cmax=None, n_grid: int = 1200,
                   n_subdiv: int = 3, subdiv_pts: int = 16):
    """Modal Rayleigh phase velocities c(T) for a layered model.

    Replaces ``disba.PhaseDispersion``/``surf96`` (reference
    inversion_diff_speed.ipynb cells 1,9).  ``mode`` 0 is fundamental (a
    scalar or a per-period array; the reference's curves use modes 0, 3 and
    4 - cell 5, evodcinv ``Curve`` third argument).  Returns NaN where the
    requested overtone does not exist at that period (below cutoff), like
    disba returns 0.

    Bracket refinement is ``n_subdiv`` rounds of ``subdiv_pts``-ary
    subdivision - each round is one *batched* secular evaluation (TPU/CPU
    vector units like wide batches far better than a deep bisection chain)
    and shrinks the bracket ``(subdiv_pts-1)x``, so defaults reach ~3e3/
    (15^3) ~ 1e-6 relative.  The secular function near steep roots is
    plateau-then-cliff, so subdivision (sign-based, derivative-free) is
    used instead of Newton.  Gradients of the root in the model parameters
    come from a final implicit-function-theorem polish whose step is
    clamped to the refined bracket width for safety.
    """
    periods = jnp.atleast_1d(periods)
    # pin the working dtype from the inputs: under an x64-enabled process,
    # bare jnp.linspace would be float64 and promote the whole secular scan
    # to f64 — on TPU that is slow at best and has crashed the worker.
    wdt = jnp.result_type(periods.dtype, model.vs.dtype)
    omega = (2.0 * jnp.pi / periods).astype(wdt)         # (nT,)
    mode_arr = jnp.broadcast_to(jnp.asarray(mode), periods.shape)
    vs_min = jnp.min(model.vs)
    vs_half = model.vs[-1]
    lo = 0.7 * vs_min if cmin is None else cmin
    hi = 0.999 * vs_half if cmax is None else cmax
    # scan bounds must NOT carry model gradient: the root's model gradient
    # comes from the final secant step's D values alone (IFT), so every c
    # the secular function is evaluated at is a constant w.r.t. the model.
    lo = lax.stop_gradient(jnp.asarray(lo, wdt))
    hi = lax.stop_gradient(jnp.asarray(hi, wdt))
    grid = jnp.linspace(0.0, 1.0, n_grid, dtype=wdt)
    subgrid = jnp.linspace(0.0, 1.0, subdiv_pts, dtype=wdt)

    cs = lo + (hi - lo) * grid                            # (n_grid,)
    Ds = secular(cs[None, :], omega[:, None], model)      # (nT, n_grid)
    sign = jnp.sign(Ds)
    flips = (sign[:, :-1] * sign[:, 1:]) < 0
    order = jnp.cumsum(flips, axis=-1)
    hit = flips & (order == (mode_arr[:, None] + 1))
    valid = jnp.any(hit, axis=-1)
    idx = jnp.argmax(hit, axis=-1)                        # (nT,)
    take = lambda a, j: jnp.take_along_axis(a, j, axis=1)[:, 0]
    c_lo, c_hi = cs[idx], cs[idx + 1]

    def narrow(state, _):
        c_lo, c_hi = state
        cf = c_lo[:, None] + (c_hi - c_lo)[:, None] * subgrid[None, :]
        Df = secular(cf, omega[:, None], model)
        j = _first_flip(Df)[:, None]
        return (take(cf, j), take(cf, j + 1)), None

    if n_subdiv > 0:  # one compiled body, n_subdiv iterations; carries only
        # bracket endpoints (integer-gather paths), so reverse-mode AD skips
        # the whole scan - no grad-of-scan machinery in the misfit gradient.
        (c_lo, c_hi), _ = lax.scan(narrow, (c_lo, c_hi), None,
                                   length=n_subdiv)

    # final regula-falsi step inside the bracket, from ONE differentiable
    # secular evaluation at the two endpoints; the denominator is under
    # stop_gradient, so dc/dtheta = -D_theta / D_c_secant flows through the
    # D values (implicit function theorem).  The step is clamped to the
    # bracket so a degenerate bracket (e.g. sign(D) exactly 0 at a
    # subdivision point) can never fling the root outside it.
    D2 = secular(jnp.stack([c_lo, c_hi], axis=0), omega[None, :], model)
    D_lo, D_hi = D2[0], D2[1]
    w = lax.stop_gradient(c_hi - c_lo)
    denom = lax.stop_gradient(D_hi - D_lo)
    denom = jnp.where(jnp.abs(denom) > 0, denom, 1.0)
    c_root = c_lo + jnp.clip(-D_lo * w / denom, 0.0, w)
    return jnp.where(valid, c_root, jnp.nan)


@partial(jax.jit, static_argnames=("n_grid", "refine_factor"))
def scan_mode_diagnostics(periods, model: LayeredModel, cmin=None, cmax=None,
                          n_grid: int = 1200, refine_factor: int = 4,
                          rel_floor: float = 0.05):
    """Mode-miss guard for the sign-change scan in :func:`phase_velocity`.

    The root finder counts sign changes of D(c) on an ``n_grid`` scan
    (phase_velocity above; cf. the role of disba's root bracketing).  Two
    osculating roots inside one grid cell produce NO sign change, so every
    overtone above them silently resolves one branch too low (round-2
    advisory).  This diagnostic returns, per period:

    - ``count``          — sign changes found at ``n_grid``;
    - ``count_refined``  — sign changes at ``refine_factor * n_grid``
      (calibration-free: ``missed = count_refined > count`` proves roots
      were skipped at the working resolution);
    - ``missed``         — the bool flag above;
    - ``dip``            — heuristic osculation signature at the working
      resolution alone: an interior local minimum of |D| below
      ``rel_floor x median |D|`` with no sign change in the two adjacent
      cells (a kissing pair whose zeros never separate, or a near-miss the
      refined scan could still skip).

    Use: run on a final model at the search's ``n_grid``; any ``missed`` or
    ``dip`` True means that period's overtone indexing needs a finer scan
    (the parity script records the counts next to each reported misfit).
    """
    periods = jnp.atleast_1d(periods)
    wdt = jnp.result_type(periods.dtype, model.vs.dtype)
    omega = (2.0 * jnp.pi / periods).astype(wdt)
    vs_min = jnp.min(model.vs)
    vs_half = model.vs[-1]
    lo = 0.7 * vs_min if cmin is None else cmin
    hi = 0.999 * vs_half if cmax is None else cmax
    lo = lax.stop_gradient(jnp.asarray(lo, wdt))
    hi = lax.stop_gradient(jnp.asarray(hi, wdt))

    def scan_counts(n):
        cs = lo + (hi - lo) * jnp.linspace(0.0, 1.0, n, dtype=wdt)
        Ds = secular(cs[None, :], omega[:, None], model)
        s = jnp.sign(Ds)
        flips = (s[:, :-1] * s[:, 1:]) < 0
        return Ds, flips, jnp.sum(flips, axis=-1)

    Ds, flips, count = scan_counts(n_grid)
    _, _, count_refined = scan_counts(refine_factor * n_grid)

    absD = jnp.abs(Ds)
    interior_min = (absD[:, 1:-1] <= absD[:, :-2]) \
        & (absD[:, 1:-1] <= absD[:, 2:])
    no_flip = ~(flips[:, :-1] | flips[:, 1:])             # cells around i
    floor = rel_floor * jnp.median(absD, axis=-1, keepdims=True)
    dip = jnp.any(interior_min & no_flip & (absD[:, 1:-1] < floor), axis=-1)
    return {"count": count, "count_refined": count_refined,
            "missed": count_refined > count, "dip": dip}


def rayleigh_halfspace_velocity(vp, vs):
    """Analytic homogeneous-halfspace Rayleigh speed (oracle for tests):
    root of the classic Rayleigh polynomial in x = (c/vs)^2."""
    import numpy as np

    g = (vs / vp) ** 2
    # x^3 - 8x^2 + (24 - 16 g) x - 16 (1 - g) = 0
    roots = np.roots([1.0, -8.0, 24.0 - 16.0 * g, -16.0 * (1.0 - g)])
    real = roots[np.abs(roots.imag) < 1e-9].real
    x = real[(real > 0) & (real < 1)]
    return float(vs * np.sqrt(x.min()))
