"""Phase-velocity sensitivity kernels.

Replaces ``disba.PhaseSensitivity`` as used by the reference
(inversion_diff_weight.ipynb cells 19-20): resample the best model to
uniform fine layers, then evaluate dc/dVs per layer.  All perturbed root
solves run as one batched vmap (disba loops them serially in numba); see
``phase_sensitivity`` for why central differences are preferred over
implicit-function AD on fine relayerings.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.inversion.forward import LayeredModel, phase_velocity


class SensitivityKernel(NamedTuple):
    depth: np.ndarray    # top depth of each fine layer (km)
    kernel: np.ndarray   # dc/dVs per fine layer (dimensionless)
    period: float
    mode: int


def resample_fine(model: LayeredModel, dz: float = 0.01,
                  zmax: float = 0.3) -> LayeredModel:
    """Uniform ``dz``-thick relayering of a coarse model down to ``zmax``.

    Mirrors inversion_diff_weight.ipynb cell 19: each fine layer takes the
    properties of the coarse layer containing its top; the halfspace
    properties extend below the coarse stack and form the final entry.
    """
    n_fine = int(round(zmax / dz))
    tops = np.arange(n_fine) * dz
    coarse_tops = np.concatenate([[0.0], np.cumsum(np.asarray(
        model.thickness)[:-1])])
    idx = np.searchsorted(coarse_tops, tops + 1e-12, side="right") - 1
    idx = np.clip(idx, 0, len(coarse_tops) - 1)
    take = lambda a: jnp.concatenate([jnp.asarray(a)[idx],
                                      jnp.asarray(a)[-1:]])
    return LayeredModel(
        thickness=jnp.concatenate([jnp.full((n_fine,), dz), jnp.zeros(1)]),
        vp=take(model.vp), vs=take(model.vs), rho=take(model.rho))


def phase_sensitivity(model: LayeredModel, period: float, mode: int = 0,
                      dz: float = 0.01, zmax: float = 0.3,
                      n_grid: int = 1200, h: float = 1e-3) -> SensitivityKernel:
    """dc/dVs depth kernel at one period (disba ``parameter="velocity_s"``
    semantics: Vs perturbed alone, Vp and rho held fixed).

    Computed as one *batched* central difference over the fine layers (all
    2n perturbed root solves run as a single vmap).  Central differences of
    the sign-based root locator are used instead of implicit-function AD
    because fine relayerings produce osculating (super-steep) roots where
    the secular function's c-derivative off the exact root is a plateau
    value - verified against 50-digit arithmetic - making -D_theta/D_c
    ill-conditioned; disba's PhaseSensitivity re-solves perturbed models
    for the same reason.  AD through ``phase_velocity`` remains available
    and accurate for coarse (inversion-grade) models.
    """
    fine = resample_fine(model, dz=dz, zmax=zmax)
    n = len(np.asarray(fine.vs))

    eye = jnp.eye(n, dtype=fine.vs.dtype)
    vs_pert = jnp.concatenate([fine.vs[None] + h * eye,
                               fine.vs[None] - h * eye], axis=0)

    def c_of_vs(vs):
        m = LayeredModel(fine.thickness, fine.vp, vs, fine.rho)
        return phase_velocity(jnp.asarray([period]), m, mode=mode,
                              n_grid=n_grid)[0]

    cs = jax.vmap(c_of_vs)(vs_pert)
    kern = (cs[:n] - cs[n:]) / (2.0 * h)
    depth = np.arange(n) * dz
    return SensitivityKernel(depth=depth, kernel=np.asarray(kern),
                             period=float(period), mode=mode)
