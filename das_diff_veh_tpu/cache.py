"""Persistent XLA compilation cache setup (single definition).

This host has one slow CPU core; XLA backend compiles of the larger graphs
take minutes, dominating cold test/benchmark runs.  Every entry point
(tests/conftest.py, bench.py, scripts/*) enables the same repo-local cache
through this helper so reruns skip compilation entirely.  The batch CLI and
the serving engine expose ``cache_dir`` as a user knob
(``--compilation_cache_dir`` / ``ServeConfig.compilation_cache_dir``) so
deployments point it at a durable path and warmups stay cheap across
process restarts.
"""

from __future__ import annotations

import os


def enable_compilation_cache(repo_root: str | None = None,
                             cache_dir: str | None = None) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir`` (explicit
    deployment path) or ``<repo_root>/.jax_cache`` (the repo-local default
    used by tests and benches)."""
    import jax

    if cache_dir is None:
        if repo_root is None:
            repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cache_dir = os.path.join(repo_root, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
