"""Visualization — the reference's QC-by-plotting capability as a library.

Mirrors (semantics, not code) the reference's figure set:

- waterfall ``plot_waterfall``        <- plot_data, modules/utils.py:198-217
  (minus its NameError bug at :210 — the colorbar referenced undefined
  ``cax``/``fig``)
- track overlay ``plot_tracks``       <- tracking_visulization_one_section,
  apis/tracking.py:170-191
- window rectangles ``plot_windows``  <- SurfaceWaveWindow.plot_on_data /
  overlay_windows_on_data, apis/data_classes.py:41-47,238-244
- gather ``plot_gather``              <- plot_xcorr, modules/utils.py:331-377
  (pivot-trace amplitude norm, seismic colormap, offset x lag extent)
- f-v map ``plot_fv_map``             <- plot_fv_map incl. the norm_part
  high-frequency/high-velocity re-normalization block,
  modules/utils.py:522-581
- dispersion curves ``plot_disp_curves`` <- modules/utils.py:680-713
  (bootstrap spaghetti + every-5th-point std error bars; returns
  means/ranges/stds like the reference)
- per-class figures ``save_class_figures`` <- save_disp_imgs,
  apis/imaging_classes.py:50-85 (gather + norm/no-norm f-v figures per
  vehicle class)
- detection example ``plot_detection`` <- show_detection_example,
  apis/tracking.py:197-237
- gather spectra ``plot_psd_vs_offset`` / ``plot_spectrum_vs_offset``
  <- apis/virtual_shot_gather.py:45-109
- per-class profiles ``plot_class_timeseries`` / ``plot_class_psd``
  <- imaging_diff_speed.ipynb cells 11, 18
- inversion ensemble ``plot_model_ensemble`` <- inversion_diff_speed.ipynb
  cell 12 role (profiles colored by misfit, best model highlighted)

All functions draw on a supplied/created matplotlib Axes and return it;
``fig_path=`` saves to disk.  Arrays may be jax or numpy.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import matplotlib
import numpy as np

if not os.environ.get("DISPLAY") and not os.environ.get("MPLBACKEND"):
    # headless fallback only — never clobber an interactive session's backend
    matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402


def _np(a):
    return np.asarray(a)


def _save(fig, fig_path: Optional[str]):
    if fig_path:
        os.makedirs(os.path.dirname(fig_path) or ".", exist_ok=True)
        fig.savefig(fig_path, bbox_inches="tight")
        plt.close(fig)


def plot_waterfall(data, x, t, pclip: float = 98, ax=None, cmap="seismic",
                   fig_path: Optional[str] = None):
    """DAS waterfall, time down, amplitude clipped at the ``pclip``-th
    percentile (reference plot_data semantics, modules/utils.py:198-217)."""
    data, x, t = _np(data), _np(x), _np(t)
    if ax is None:
        fig, ax = plt.subplots(figsize=(8, 8))
    else:
        fig = ax.figure
    vmax = np.percentile(np.abs(data), pclip)
    im = ax.imshow(data.T, aspect="auto",
                   extent=[x[0], x[-1], t[-1], t[0]],
                   cmap=cmap, vmax=vmax, vmin=-vmax)
    fig.colorbar(im, ax=ax, label="DAS response")
    ax.set_xlabel("Distance (m)")
    ax.set_ylabel("Time (s)")
    _save(fig, fig_path)
    return ax


def plot_tracks(tracks, ax=None, color="red", markersize: float = 1.0,
                fig_path: Optional[str] = None):
    """Overlay tracked vehicle arrival times (red dots per channel) on an
    existing waterfall axes (reference apis/tracking.py:177-181)."""
    if ax is None:
        _, ax = plt.subplots(figsize=(8, 8))
    t_idx = _np(tracks.t_idx)
    x = _np(tracks.x)
    t = _np(tracks.t)
    valid = _np(tracks.valid)
    for v in range(t_idx.shape[0]):
        if not valid[v]:
            continue
        ok = np.isfinite(t_idx[v])
        idx = np.clip(t_idx[v][ok].astype(int), 0, len(t) - 1)
        ax.plot(x[ok], t[idx], ".", color=color, markersize=markersize)
    _save(ax.figure, fig_path)
    return ax


def plot_windows(batch, ax=None, color="y", fig_path: Optional[str] = None):
    """Draw each valid window's space-time rectangle on a waterfall axes
    (reference SurfaceWaveWindow.plot_on_data, apis/data_classes.py:41-47)."""
    if ax is None:
        _, ax = plt.subplots(figsize=(8, 8))
    x = _np(batch.x)
    t = _np(batch.t)
    valid = _np(batch.valid)
    for w in range(t.shape[0]):
        if not valid[w]:
            continue
        t0, t1 = t[w, 0], t[w, -1]
        ax.plot([x[0], x[-1], x[-1], x[0], x[0]],
                [t0, t0, t1, t1, t0], "-", color=color, linewidth=1)
    _save(ax.figure, fig_path)
    return ax


def plot_gather(xcf, lags, offsets, ax=None, cmap="seismic",
                x_lim=(-120.0, 120.0), fig_path: Optional[str] = None):
    """Virtual-shot-gather image: offset x lag time, amplitudes normalized by
    the zero-offset (pivot) trace's max (reference plot_xcorr,
    modules/utils.py:331-377)."""
    xcf, lags, offsets = _np(xcf), _np(lags), _np(offsets)
    if ax is None:
        fig, ax = plt.subplots(figsize=(6, 8))
    else:
        fig = ax.figure
    pivot = np.abs(offsets).argmin()
    peak = np.abs(xcf[pivot]).max()
    xn = xcf / (peak if peak > 0 else 1.0)
    ax.imshow(xn.T, aspect="auto", vmax=1.0, vmin=-1.0, cmap=cmap,
              extent=[offsets[0], offsets[-1], lags[-1], lags[0]],
              interpolation="bicubic")
    ax.set_xlabel("Offset (m)")
    ax.set_ylabel("Time lag (s)")
    ax.set_xlim(list(x_lim))
    ax.grid(True)
    _save(fig, fig_path)
    return ax


def plot_fk(fk_mag, freqs, ks, f_max: float = 20.0, k_max: float = 0.04,
            ax=None, fig_path: Optional[str] = None):
    """f-k magnitude image, positive-quadrant view (reference plot_fk /
    compute_and_plot_fk, modules/utils.py:225-234; the view limits are the
    reference's hardcoded defaults, exposed as arguments)."""
    fk_mag, freqs, ks = _np(fk_mag), _np(freqs), _np(ks)
    if ax is None:
        _, ax = plt.subplots(figsize=(8, 8))
    ax.imshow(fk_mag.T, extent=[ks[0], ks[-1], freqs[-1], freqs[0]],
              aspect="auto")
    ax.set_ylim(0, f_max)
    ax.set_xlim(0, k_max)
    ax.set_xlabel("Wavenumber (1/m)")
    ax.set_ylabel("Frequency (Hz)")
    _save(ax.figure, fig_path)
    return ax


def plot_psd_vs_offset(xcf, offsets, dt, fhi: float = 20.0, pclip: float = 98,
                       log_scale: bool = False, nperseg: int = 256,
                       nfft: int = 1024, ax=None,
                       fig_path: Optional[str] = None):
    """Welch PSD of each gather trace vs offset, imaged to ``fhi`` Hz with
    pclip color limits (reference plot_psd_vs_offset,
    apis/virtual_shot_gather.py:45-90; optional 10*log10 dB scale)."""
    import jax.numpy as jnp

    from das_diff_veh_tpu.ops.psd import welch_psd

    xcf, offsets = _np(xcf), _np(offsets)
    freqs, p = welch_psd(jnp.asarray(xcf), 1.0 / dt, nperseg=nperseg,
                         nfft=nfft)
    freqs, p = _np(freqs), _np(p)
    sel = freqs < fhi
    spec = p[:, sel]
    if log_scale:
        spec = 10.0 * np.log10(np.maximum(spec, 1e-30))
    vmax = np.percentile(spec, pclip)
    vmin = np.percentile(spec, 100 - pclip)
    if ax is None:
        _, ax = plt.subplots(figsize=(8, 8))
    ax.imshow(spec.T, extent=[offsets[0], offsets[-1],
                              freqs[sel][-1], freqs[0]],
              cmap="jet", aspect="auto", vmax=vmax, vmin=vmin)
    ax.set_xlabel("Distance along the fiber [m]")
    ax.set_ylabel("Frequency [Hz]")
    _save(ax.figure, fig_path)
    return ax


def plot_spectrum_vs_offset(xcf, offsets, dt, fhi: float = 20.0, ax=None,
                            fig_path: Optional[str] = None):
    """FFT amplitude of each gather trace vs offset to ``fhi`` Hz
    (reference plot_spectrum_vs_offset, apis/virtual_shot_gather.py:93-109)."""
    xcf, offsets = _np(xcf), _np(offsets)
    freqs = np.fft.rfftfreq(xcf.shape[-1], d=dt)
    sel = freqs < fhi
    spec = np.abs(np.fft.rfft(xcf, axis=-1))[:, sel]
    if ax is None:
        _, ax = plt.subplots(figsize=(8, 8))
    ax.imshow(spec.T, extent=[offsets[0], offsets[-1],
                              freqs[sel][-1], freqs[0]],
              cmap="jet", aspect="auto")
    ax.set_xlabel("Distance along the fiber [m]")
    ax.set_ylabel("Frequency [Hz]")
    _save(ax.figure, fig_path)
    return ax


def _norm_columns(fv: np.ndarray) -> np.ndarray:
    m = fv.max(axis=0)
    return fv / np.where(m != 0, m, 1.0)


def apply_norm_part(fv: np.ndarray, freqs, vels, f_split: float = 10.0,
                    v_split: float = 600.0) -> np.ndarray:
    """The reference's norm_part block (modules/utils.py:528-543): after the
    global per-frequency max-normalization, the (f > f_split, v > v_split)
    quadrant is re-normalized *within itself* so weak high-mode energy
    becomes visible.  Returns a new array (map layout: (nvel, nfreq),
    velocity ascending)."""
    fv, freqs, vels = _np(fv).copy(), _np(freqs), _np(vels)
    hf = np.where(freqs > f_split)[0]
    hv = np.where(vels > v_split)[0]
    win = fv[np.ix_(hv, hf)]
    win = _norm_columns(win)
    fv = _norm_columns(fv)
    fv[np.ix_(hv, hf)] = win
    return fv


def plot_fv_map(fv, freqs, vels, norm: bool = True, norm_part: bool = False,
                ridge_data=None, ax=None, pclip: float = 98,
                f_lim=(2.0, 25.0), v_lim=(250.0, 900.0),
                fig_path: Optional[str] = None):
    """Frequency-velocity dispersion image (reference plot_fv_map,
    modules/utils.py:522-581): optional per-frequency max norm, optional
    norm_part quadrant re-norm, jet colormap, percentile color clip, and
    optional ridge-curve overlay ``ridge_data=(freq_lists, vel_lists)``."""
    fv, freqs, vels = _np(fv), _np(freqs), _np(vels)
    if norm_part:
        fv = apply_norm_part(fv, freqs, vels)
    elif norm:
        fv = _norm_columns(fv)
    if ax is None:
        fig, ax = plt.subplots(figsize=(4.5, 3.5))
    else:
        fig = ax.figure
    vmax = np.percentile(np.abs(fv), pclip)
    vmin = np.percentile(np.abs(fv), 100 - pclip)
    # imshow with origin-at-top extent [v0, v_end] reversed: put velocity
    # ascending upward like the reference (extent bottom = vels[0])
    ax.imshow(fv[::-1], aspect="auto",
              extent=[freqs[0], freqs[-1], vels[0], vels[-1]],
              cmap="jet", vmax=vmax, vmin=vmin)
    if ridge_data is not None:
        freq_r, vel_r = ridge_data
        for fr, vr in zip(freq_r, vel_r):
            ax.plot(_np(fr), _np(vr), "w.", alpha=0.5, markersize=5)
    ax.grid(True)
    ax.set_xlabel("Frequency (Hz)")
    ax.set_ylabel("Phase velocity (m/s)")
    ax.set_xlim(list(f_lim))
    ax.set_ylim(list(v_lim))
    _save(fig, fig_path)
    return ax


def plot_disp_curves(freqs, freq_lb, freq_ub, ridge_vels,
                     errorbar_stride: int = 5, ax=None,
                     f_lim=(2.0, 25.0), v_lim=(250.0, 900.0),
                     fig_path: Optional[str] = None):
    """Bootstrap dispersion curves with error bars (reference
    plot_disp_curves, modules/utils.py:680-713): per band, every bootstrap
    rep as a faint line plus mean +- std error bars every
    ``errorbar_stride``-th frequency.  Returns (means, ranges, stds) lists
    exactly like the reference."""
    from das_diff_veh_tpu.inversion.curves import ridge_stats

    freqs = _np(freqs)
    if ax is None:
        fig, ax = plt.subplots(figsize=(4.5, 3.5))
    else:
        fig = ax.figure
    means, ranges, stds = [], [], []
    for i, band in enumerate(ridge_vels):
        fmask = (freqs >= freq_lb[i]) & (freqs < freq_ub[i])
        f = freqs[fmask]
        band = np.stack([_np(b).astype(np.float64) for b in band])
        for rep in band:
            ax.plot(f, rep, "-b", alpha=0.2, linewidth=1)
        mean, rng, std = ridge_stats(band)
        means.append(mean)
        ranges.append(rng)
        stds.append(std)
        s = slice(None, None, errorbar_stride)
        ax.errorbar(f[s], mean[s], yerr=std[s], fmt="ro", zorder=3,
                    markersize=3, linewidth=2)
    ax.grid(True)
    ax.set_xlabel("Frequency (Hz)")
    ax.set_ylabel("Phase velocity (m/s)")
    ax.set_xlim(list(f_lim))
    ax.set_ylim(list(v_lim))
    _save(fig, fig_path)
    return means, ranges, stds


def save_class_figures(stack, lags, offsets, disp_image, freqs, vels,
                       class_name: str, fig_dir: str, x0: float):
    """Per-vehicle-class figure set (reference save_disp_imgs,
    apis/imaging_classes.py:50-85): the class's averaged gather plus its
    dispersion map with and without per-frequency normalization.  Writes
    ``{fig_dir}/{x0}/sg_{class}_cars.pdf`` / ``disp_{class}_cars*.pdf``
    (the reference's filenames)."""
    base = os.path.join(fig_dir, str(int(x0)))
    plot_gather(stack, lags, offsets,
                fig_path=os.path.join(base, f"sg_{class_name}_cars.pdf"))
    plot_fv_map(disp_image, freqs, vels, norm=False,
                fig_path=os.path.join(base, f"disp_{class_name}_cars_no_norm.pdf"))
    plot_fv_map(disp_image, freqs, vels, norm=True,
                fig_path=os.path.join(base, f"disp_{class_name}_cars_no_enhance.pdf"))
    return base


def plot_detection(data, t, start_x_idx: int, cfg=None, ax=None,
                   fig_path: Optional[str] = None):
    """Detection example: the ``n_detect_channels`` traces (vertically
    offset) with their picked peaks, the stacked Gaussian likelihood below,
    and the detected vehicle bases (reference show_detection_example /
    detect_in_one_section(show_plot=True), apis/tracking.py:47-60,197-237).
    """
    import jax.numpy as jnp

    from das_diff_veh_tpu.config import TrackingConfig
    from das_diff_veh_tpu.models.tracking import detect_vehicle_base

    cfg = cfg or TrackingConfig()
    base, valid, (rows, pk_pos, pk_valid, stacked) = detect_vehicle_base(
        jnp.asarray(data), jnp.asarray(t), start_x_idx, cfg,
        return_details=True)
    rows, pk_pos, pk_valid = _np(rows), _np(pk_pos), _np(pk_valid)
    stacked, base, valid, t = _np(stacked), _np(base), _np(valid), _np(t)
    if ax is None:
        _, ax = plt.subplots(figsize=(6, 5))
    span = max(np.nanmax(np.abs(rows)), 1e-12)
    for i, row in enumerate(rows):
        off = (i + 1) * 2 * span
        ax.plot(t, row + off, "k", lw=0.5)
        pk = pk_pos[i][pk_valid[i]]
        ax.plot(t[pk], row[pk] + off, "rx", markersize=4)
    lk = stacked / max(stacked.max(), 1e-12) * span
    ax.plot(t, lk, "b", label="stacked likelihood")
    bv = base[valid]
    ax.plot(t[bv], lk[bv], "g^", markersize=8, label="vehicle base")
    ax.set_xlabel("Time (s)")
    ax.set_yticks([])
    ax.legend(loc="upper right")
    _save(ax.figure, fig_path)
    return ax


_CLASS_COLORS = {"slow": "b", "mid": "r", "fast": "k",
                 "light": "b", "heavy": "k"}


def plot_class_timeseries(t, stats, ax=None, band: str = "std",
                          fig_path: Optional[str] = None):
    """Per-class mean quasi-static trace with a spread band
    (imaging_diff_speed.ipynb cell 11: mean line per class, ±std fill).

    ``stats``: mapping class name -> (mean, std, ci) as produced by
    ``analysis.class_profiles.class_timeseries_stats``; ``band`` picks the
    fill half-width ("std" or "ci").
    """
    if band not in ("std", "ci"):
        raise ValueError(f"band must be 'std' or 'ci', got {band!r}")
    if ax is None:
        _, ax = plt.subplots(figsize=(3, 3))
    t = _np(t)
    for i, (name, (mean, std, ci)) in enumerate(stats.items()):
        color = _CLASS_COLORS.get(name, f"C{i}")
        half = _np(ci if band == "ci" else std)
        ax.plot(t, _np(mean), color, label=name)
        ax.fill_between(t, _np(mean) - half, _np(mean) + half,
                        color=color, alpha=0.1)
    ax.set_xlabel("Time (s)")
    ax.set_ylabel("DAS amplitude")
    ax.legend()
    _save(ax.figure, fig_path)
    return ax


def plot_class_psd(freqs, psds, ax=None, f_lo: float = 2.0, f_hi: float = 25.0,
                   fig_path: Optional[str] = None):
    """Per-class averaged Welch PSD (semilogy) with the min/max per-window
    envelope, limited to [f_lo, f_hi] Hz (imaging_diff_speed.ipynb cell 18).

    ``psds``: mapping class name -> (avg, per_window) as produced by
    ``analysis.class_profiles.class_psd``.
    """
    if ax is None:
        _, ax = plt.subplots(figsize=(5, 3.5))
    freqs = _np(freqs)
    sel = (freqs >= f_lo) & (freqs <= f_hi)
    for i, (name, (avg, per_window)) in enumerate(psds.items()):
        color = _CLASS_COLORS.get(name, f"C{i}")
        ax.semilogy(freqs[sel], _np(avg)[sel], color, label=name)
        per_window = _np(per_window)
        if per_window.shape[0]:
            ax.fill_between(freqs[sel], per_window.min(axis=0)[sel],
                            per_window.max(axis=0)[sel], color=color, alpha=0.2)
    ax.set_xlabel("Frequency (Hz)")
    ax.set_ylabel("PSD ($A^2$/Hz)")
    ax.set_xlim(f_lo, f_hi)
    ax.legend()
    _save(ax.figure, fig_path)
    return ax


def plot_model_ensemble(models_x, misfits, spec, max_depth_m: float = 150.0,
                        top_frac: float = 0.3, ax=None,
                        fig_path: Optional[str] = None):
    """Vs-profile ensemble colored by misfit, with the best model and the
    mean of the best ``top_frac`` highlighted (role of
    inversion_diff_speed.ipynb cell 12's plot_model)."""
    import jax.numpy as jnp

    models_x, misfits = _np(models_x), _np(misfits)
    if ax is None:
        fig, ax = plt.subplots(figsize=(4, 6))
    else:
        fig = ax.figure
    order = np.argsort(misfits)[::-1]          # worst first so best draws on top
    fin = order[np.isfinite(misfits[order])]
    lo, hi = misfits[fin[-1]], np.percentile(misfits[fin], 90)
    cmap = plt.get_cmap("viridis_r")

    def steps(x01):
        m = spec.to_model(jnp.asarray(x01))
        d = np.asarray(m.thickness)[:-1] * 1000.0
        vs = np.asarray(m.vs) * 1000.0
        tops = np.concatenate([[0.0], np.cumsum(d)])
        z = np.repeat(tops, 2)[1:]
        z = np.append(z, max_depth_m)
        v = np.repeat(vs, 2)
        return v, z

    for i in fin:
        v, z = steps(models_x[i])
        c = cmap(float(np.clip((misfits[i] - lo) / max(hi - lo, 1e-12), 0, 1)))
        ax.plot(v, z, color=c, alpha=0.25, linewidth=0.8)
    # mean of best top_frac
    k = max(1, int(len(fin) * top_frac))
    best_set = fin[-k:]
    vbar = np.mean([steps(models_x[i])[0] for i in best_set], axis=0)
    _, zbar = steps(models_x[best_set[-1]])
    ax.plot(vbar, zbar, "b-", linewidth=2, label=f"mean best {int(top_frac*100)}%")
    vb, zb = steps(models_x[fin[-1]])
    ax.plot(vb, zb, "r-", linewidth=2, label=f"best (misfit {misfits[fin[-1]]:.3f})")
    ax.invert_yaxis()
    ax.set_xlabel("Vs (m/s)")
    ax.set_ylabel("Depth (m)")
    ax.legend(fontsize=8)
    ax.grid(True)
    _save(fig, fig_path)
    return ax


def plot_convergence(spreads, ax=None, fig_path: Optional[str] = None):
    """Bootstrap ridge spread vs sample count per mode
    (imaging_diff_speed.ipynb cell 31's convergence figure).  ``spreads``:
    (n_modes, max_sample_num) from ``analysis.bootstrap.convergence_test``.
    """
    spreads = _np(spreads)
    if ax is None:
        _, ax = plt.subplots(figsize=(5, 3.5))
    n = np.arange(1, spreads.shape[1] + 1)
    for m, row in enumerate(spreads):
        ax.plot(n, row, label=f"mode band {m}")
    ax.set_xlabel("Bootstrap sample count")
    ax.set_ylabel("Summed ridge std (m/s)")
    ax.legend(fontsize=8)
    ax.grid(True)
    _save(ax.figure, fig_path)
    return ax


def plot_predicted_curves(model, curves, n_pred: int = 60, ax=None,
                          fig_path: Optional[str] = None):
    """Observed modal dispersion samples vs the inverted model's predicted
    curves (role of the inversion notebooks' predicted-curve overlay,
    inversion_diff_speed.ipynb cells 14-15: observed ridges + forward-model
    curves of the best profile).

    ``model``: a ``LayeredModel`` (e.g. ``InversionResult.model``);
    ``curves``: the ``Curve`` list the inversion consumed (period-domain,
    km/s).  Each curve's mode is forward-modelled on a dense period grid.
    """
    import jax.numpy as jnp

    from das_diff_veh_tpu.inversion import phase_velocity

    if ax is None:
        _, ax = plt.subplots(figsize=(5, 4))
    for i, c in enumerate(curves):
        color = f"C{i}"
        T = _np(c.period)
        ax.errorbar(1.0 / T, _np(c.velocity),
                    yerr=None if c.uncertainty is None else _np(c.uncertainty),
                    fmt=".", ms=4, color=color, alpha=0.6,
                    label=f"mode {c.mode} observed")
        Tg = np.linspace(T.min(), T.max(), n_pred)
        pred = np.asarray(phase_velocity(jnp.asarray(Tg), model, mode=c.mode))
        ax.plot(1.0 / Tg, pred, "-", color=color,
                label=f"mode {c.mode} predicted")
    ax.set_xlabel("Frequency (Hz)")
    ax.set_ylabel("Phase velocity (km/s)")
    ax.legend(fontsize=7)
    ax.grid(True)
    _save(ax.figure, fig_path)
    return ax


def plot_sensitivity_kernels(kernels: Sequence, ax=None,
                             fig_path: Optional[str] = None):
    """Depth sensitivity kernels dc/dVs per period (role of
    inversion_diff_weight.ipynb cells 19-20 PhaseSensitivity figures)."""
    if ax is None:
        fig, ax = plt.subplots(figsize=(4, 6))
    else:
        fig = ax.figure
    for k in kernels:
        ax.plot(_np(k.kernel), _np(k.depth) * 1000.0,
                label=f"{1.0 / k.period:.1f} Hz")
    ax.invert_yaxis()
    ax.set_xlabel("dc/dVs")
    ax.set_ylabel("Depth (m)")
    ax.legend(fontsize=8)
    ax.grid(True)
    _save(fig, fig_path)
    return ax


def figure_set_from_synthetic(out_dir: str, n_windows: int = 16,
                              seed: int = 0) -> list[str]:
    """Produce the reference figure set from a synthetic run — the CLI's
    ``--figures`` entry point.  Returns the list of files written."""
    import jax.numpy as jnp

    from das_diff_veh_tpu.config import DispersionConfig, GatherConfig
    from das_diff_veh_tpu.models import vsg as V
    from das_diff_veh_tpu.workloads import (make_gather_geometry,
                                            make_window_batch)

    gcfg, dcfg = GatherConfig(), DispersionConfig()
    batch, x = make_window_batch(n_windows=n_windows, seed=seed)
    g = make_gather_geometry(x)
    gathers = V.build_gather_batch(batch, g, gcfg)
    stack = V.stack_gathers(gathers, batch.valid)
    offs = g.offsets(x)
    dx_ch = float(x[1] - x[0])      # channel spacing from the axis itself —
    # the one place the reference's dx=8.16 hardcode had crept back in
    img = V.gather_disp_image(stack, offs, g.dt, dx_ch, dcfg, -150.0, 0.0)
    freqs = np.arange(dcfg.freq_min, dcfg.freq_max, dcfg.freq_step)
    vels = np.arange(dcfg.vel_min, dcfg.vel_max, dcfg.vel_step)

    files = []

    def out(name):
        p = os.path.join(out_dir, name)
        files.append(p)
        return p

    w0 = np.asarray(batch.data[0])
    plot_waterfall(w0, x, np.asarray(batch.t[0]),
                   fig_path=out("waterfall.png"))
    ax = plot_waterfall(w0, x, np.asarray(batch.t[0]))
    plot_windows(batch, ax=ax, fig_path=out("waterfall_windows.png"))
    plot_gather(np.asarray(stack), g.lags(),
                offs[: stack.shape[0]], fig_path=out("gather.png"))
    plot_fv_map(np.asarray(img), freqs, vels, norm=True,
                fig_path=out("fv_map.png"))
    plot_fv_map(np.asarray(img), freqs, vels, norm_part=True,
                fig_path=out("fv_map_norm_part.png"))
    dt = float(g.dt)
    nch_plot = min(stack.shape[0], len(offs))
    plot_psd_vs_offset(np.asarray(stack)[:nch_plot], offs[:nch_plot], dt,
                       log_scale=True, fig_path=out("gather_psd_offset.png"))
    plot_spectrum_vs_offset(np.asarray(stack)[:nch_plot], offs[:nch_plot],
                            dt, fig_path=out("gather_spectrum_offset.png"))
    return files
