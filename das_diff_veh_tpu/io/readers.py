"""DAS data readers and dataset iteration (host-side, numpy).

Covers the reference's L1 tier: npz reader with channel-range and taper cut
(modules/utils.py:94-113), format dispatch + multi-file time concatenation
(modules/utils.py:116-166), and the per-date directory iterator
(modules/imaging_IO.py:23-54).  Everything returns plain numpy; arrays cross
onto the device at the jit boundary of the compute pipeline.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from datetime import datetime
from typing import Iterator, Optional, Sequence

import numpy as np

from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.io import segy as _segy
from das_diff_veh_tpu.resilience import faults


def _cut_symmetric_taper(data: np.ndarray, t: np.ndarray):
    """Drop the pre-zero taper pad on both ends (reference: modules/utils.py:87-92).

    Files store a symmetric taper region; its length is where |t| is minimal.
    """
    nt = data.shape[-1]
    pad = int(np.argmin(np.abs(t)))
    return data[:, pad:nt - pad], t[pad:nt - pad]


def read_npz_section(path: str, ch1: Optional[float] = None, ch2: Optional[float] = None,
                     cut_taper: bool = True) -> DasSection:
    """Load one npz file with ``data``/``x_axis``/``t_axis`` keys
    (reference key layout: modules/utils.py:94-113)."""
    # chaos sites (no-ops unless an injector is installed): a read failure,
    # a slow read, and post-decode data corruption — keyed by basename so a
    # retried chunk deterministically refires its planned fault
    key = os.path.basename(path)
    faults.fire("io.slow", key)
    faults.fire("io.read", key)
    with np.load(path) as f:
        data, x, t = f["data"], f["x_axis"], f["t_axis"]
    if ch1 is not None and not np.any(x >= ch1):
        raise ValueError(f"ch1={ch1} beyond channel axis [{x[0]}, {x[-1]}] in {path}")
    lo = 0 if ch1 is None else int(np.argmax(x >= ch1))
    hi = len(x) if (ch2 is None or not np.any(x >= ch2)) else int(np.argmax(x >= ch2))
    data, x = data[lo:hi], x[lo:hi]
    if cut_taper:
        data, t = _cut_symmetric_taper(data, t)
    # corruption fires on the post-cut waterfall: planned channel indices
    # (and fraction draws) refer to the channels the pipeline actually sees,
    # so a counted injection can never be sliced away by ch1/ch2
    data = faults.corrupt("io.corrupt", key, data)
    return DasSection(np.asarray(data, dtype=np.float64), np.asarray(x, dtype=np.float64),
                      np.asarray(t, dtype=np.float64))


def read_segy_section(path: str, ch1: int = 0, ch2: Optional[int] = None,
                      **_ignored) -> DasSection:
    """Load a SEG-Y file via the built-in parser (segyio-free;
    reference behavior: modules/utils.py:72-85).  ``ch1``/``ch2`` are trace
    indices; npz-only kwargs (e.g. cut_taper) are accepted and ignored so
    mixed-format lists work through ``read_sections``."""
    data, dt, ns = _segy.read_segy(path, ch1=int(ch1), ch2=None if ch2 is None else int(ch2))
    nch = data.shape[0]
    return DasSection(data.astype(np.float64), np.arange(ch1, ch1 + nch, dtype=np.float64),
                      np.arange(ns) * dt)


_READERS = {".npz": read_npz_section, ".segy": read_segy_section, ".sgy": read_segy_section}


def read_sections(paths: Sequence[str], **kwargs) -> DasSection:
    """Read several files and concatenate along time with accumulated shift
    (reference: modules/utils.py:136-166)."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    datas, ts, t_shift, x = [], [], 0.0, None
    for p in paths:
        reader = _READERS[os.path.splitext(p)[-1].lower()]
        sec = reader(p, **kwargs)
        dt = sec.t[1] - sec.t[0]
        datas.append(np.asarray(sec.data))
        ts.append(np.asarray(sec.t) + t_shift)
        t_shift += sec.t.shape[0] * dt
        x = np.asarray(sec.x)
    return DasSection(np.concatenate(datas, axis=-1), x, np.concatenate(ts))


def read_csv_section(data_dir: str, name: str) -> DasSection:
    """Load the ``<name>.csv`` / ``<name>_x_axis.csv`` / ``<name>_t_axis.csv``
    triplet used by the older tracking path (reference:
    modules/car_tracking_utils.py:13-18 — space-delimited data matrix plus
    one-column axis files; whitespace splitting so aligned/padded columns
    read identically)."""
    base = os.path.join(data_dir, name)
    x = np.atleast_1d(np.genfromtxt(base + "_x_axis.csv", dtype=np.float64))
    t = np.atleast_1d(np.genfromtxt(base + "_t_axis.csv", dtype=np.float64))
    data = np.genfromtxt(base + ".csv", dtype=np.float64)
    if data.ndim < 2 and data.size == x.size * t.size:
        data = data.reshape(x.size, t.size)
    data = np.atleast_2d(data)
    if data.shape != (x.size, t.size):
        raise ValueError(f"csv triplet {base}: data {data.shape} does not match "
                         f"axes ({x.size} channels, {t.size} samples)")
    return DasSection(data, np.atleast_1d(x), np.atleast_1d(t))


def parse_time_from_filename(path: str, fmt: str = "%Y%m%d_%H%M%S") -> datetime:
    """Parse the acquisition timestamp from a file name
    (reference: modules/imaging_IO.py:17-20)."""
    return datetime.strptime(os.path.basename(path).split(".")[0], fmt)


@dataclass
class DirectoryDataset:
    """Sorted iterator over the npz time-window files of one date folder
    (reference: modules/imaging_IO.py:23-54).

    The reference hardcodes a Savitzky-Golay pre-smooth (21,15) and a magic
    amplitude rescale ``6463.81735715902`` for dates > '20230219'
    (modules/imaging_IO.py:41-46); both are explicit knobs here.
    """

    directory: str
    root: str = "."
    ch1: float = 400
    ch2: float = 540
    smoothing: bool = True
    sg_window: int = 21
    sg_order: int = 15
    rescale_after: Optional[str] = "20230219"
    rescale_value: float = 6463.81735715902

    def __post_init__(self):
        folder = os.path.join(self.root, self.directory)
        files = [os.path.join(folder, f) for f in os.listdir(folder) if f.endswith(".npz")]
        files.sort(key=os.path.basename)
        self.files = files

    def time_interval(self) -> float:
        """Seconds between consecutive files (reference: modules/imaging_IO.py:31-35)."""
        if len(self.files) < 2:
            raise ValueError(
                f"need >= 2 npz files in {os.path.join(self.root, self.directory)} "
                f"to infer the window interval (found {len(self.files)})")
        a = parse_time_from_filename(self.files[0])
        b = parse_time_from_filename(self.files[1])
        return (b - a).total_seconds()

    def __len__(self) -> int:
        return len(self.files)

    def read(self, idx: int) -> DasSection:
        """Raw host I/O stage: npz load + channel cut + taper cut.

        Split from :meth:`preprocess` so the batch runtime can trace (and
        overlap) the two host stages separately.
        """
        return read_npz_section(self.files[idx], ch1=self.ch1, ch2=self.ch2)

    def preprocess(self, sec: DasSection, idx: int) -> DasSection:
        """Host preprocessing stage: savgol pre-smooth + date rescale."""
        path = self.files[idx]
        data = np.asarray(sec.data)
        if self.smoothing:
            from scipy.signal import savgol_filter
            data = savgol_filter(data, self.sg_window, self.sg_order)
        if self.rescale_after is not None:
            date = os.path.basename(os.path.dirname(path))
            if date > self.rescale_after:
                data = data / self.rescale_value
        return DasSection(data, sec.x, sec.t)

    def __getitem__(self, idx: int) -> DasSection:
        return self.preprocess(self.read(idx), idx)

    def __iter__(self) -> Iterator[DasSection]:
        for i in range(len(self)):
            yield self[i]


def save_section_npz(path: str, section: DasSection) -> None:
    """Write the reference npz layout so files round-trip between frameworks."""
    np.savez(path, data=np.asarray(section.data), x_axis=np.asarray(section.x),
             t_axis=np.asarray(section.t))
