"""Minimal, dependency-free SEG-Y trace reader (numpy only).

The reference reads SEG-Y via the external ``segyio`` package
(modules/utils.py:72-85).  That package is not a dependency here; DAS SEG-Y
files are simple enough (uniform traces, no geometry) that a direct parser is
~100 lines: 3200-byte EBCDIC text header, 400-byte binary header, then
fixed-length traces of 240-byte header + ns samples.

Supports data format codes 1 (4-byte IBM float), 2 (int32), 3 (int16),
5 (IEEE float32), 8 (int8) — format 1 and 5 cover every DAS interrogator we
know of.  Assumptions (loud failure otherwise): uniform ns/dt from the
binary header (per-trace header overrides are ignored — DAS interrogators
write uniform traces), non-zero ns and dt; a trailing partial trace is
dropped with only the complete traces returned.
"""

from __future__ import annotations

import numpy as np

_TEXT_HEADER_LEN = 3200
_BIN_HEADER_LEN = 400
_TRACE_HEADER_LEN = 240

# byte offsets (0-based) within the 400-byte binary header
_BIN_DT_OFFSET = 16        # sample interval, microseconds (int16)
_BIN_NS_OFFSET = 20        # samples per trace (int16)
_BIN_FORMAT_OFFSET = 24    # data sample format code (int16)

_SAMPLE_BYTES = {1: 4, 2: 4, 3: 2, 5: 4, 8: 1}


def _ibm_to_float(raw: np.ndarray) -> np.ndarray:
    """Vectorized IBM System/360 hexadecimal float -> IEEE float64."""
    raw = raw.astype(np.uint32)
    sign = np.where(raw >> 31, -1.0, 1.0)
    exponent = ((raw >> 24) & 0x7F).astype(np.int64) - 64
    mantissa = (raw & 0x00FFFFFF).astype(np.float64) / float(1 << 24)
    return sign * mantissa * np.power(16.0, exponent)


def read_segy(path: str, ch1: int = 0, ch2: int | None = None):
    """Read traces [ch1:ch2] from a SEG-Y file.

    Returns ``(data (nch, ns) float32, dt seconds, ns)``.  Mirrors what the
    reference extracts through segyio (modules/utils.py:75-85): raw traces plus
    the sample interval from the binary header in microseconds.
    """
    with open(path, "rb") as f:
        header = f.read(_TEXT_HEADER_LEN + _BIN_HEADER_LEN)
        if len(header) < _TEXT_HEADER_LEN + _BIN_HEADER_LEN:
            raise ValueError(f"truncated SEG-Y file (no binary header): {path}")
        binh = header[_TEXT_HEADER_LEN:]
        dt_us = int.from_bytes(binh[_BIN_DT_OFFSET:_BIN_DT_OFFSET + 2], "big", signed=False)
        ns = int.from_bytes(binh[_BIN_NS_OFFSET:_BIN_NS_OFFSET + 2], "big", signed=False)
        fmt = int.from_bytes(binh[_BIN_FORMAT_OFFSET:_BIN_FORMAT_OFFSET + 2], "big", signed=False)
        if fmt not in _SAMPLE_BYTES:
            raise ValueError(f"unsupported SEG-Y format code {fmt} in {path}")
        if ns == 0:
            raise ValueError(f"SEG-Y binary header declares 0 samples/trace: {path}")
        if dt_us == 0:
            raise ValueError(f"SEG-Y binary header declares 0 us sample interval"
                             f" (dt unrecoverable): {path}")
        sample_bytes = _SAMPLE_BYTES[fmt]
        trace_len = _TRACE_HEADER_LEN + ns * sample_bytes

        f.seek(0, 2)
        file_len = f.tell()
        ntraces = (file_len - _TEXT_HEADER_LEN - _BIN_HEADER_LEN) // trace_len
        if ch2 is None:
            ch2 = ntraces
        ch2 = min(ch2, ntraces)
        nch = max(ch2 - ch1, 0)

        f.seek(_TEXT_HEADER_LEN + _BIN_HEADER_LEN + ch1 * trace_len)
        buf = f.read(nch * trace_len)

    rec = np.frombuffer(buf, dtype=np.uint8).reshape(nch, trace_len)
    payload = np.ascontiguousarray(rec[:, _TRACE_HEADER_LEN:])

    if fmt == 1:
        words = payload.view(">u4").reshape(nch, ns)
        data = _ibm_to_float(words).astype(np.float32)
    elif fmt == 2:
        data = payload.view(">i4").reshape(nch, ns).astype(np.float32)
    elif fmt == 3:
        data = payload.view(">i2").reshape(nch, ns).astype(np.float32)
    elif fmt == 5:
        data = payload.view(">f4").reshape(nch, ns).astype(np.float32)
    else:  # fmt == 8
        data = payload.view(np.int8).reshape(nch, ns).astype(np.float32)

    return data, dt_us / 1e6, ns


def write_segy(path: str, data: np.ndarray, dt: float) -> None:
    """Write a minimal IEEE-float SEG-Y file (for tests / interchange)."""
    data = np.asarray(data, dtype=np.float32)
    nch, ns = data.shape
    binh = bytearray(_BIN_HEADER_LEN)
    binh[_BIN_DT_OFFSET:_BIN_DT_OFFSET + 2] = int(round(dt * 1e6)).to_bytes(2, "big")
    binh[_BIN_NS_OFFSET:_BIN_NS_OFFSET + 2] = int(ns).to_bytes(2, "big")
    binh[_BIN_FORMAT_OFFSET:_BIN_FORMAT_OFFSET + 2] = (5).to_bytes(2, "big")
    with open(path, "wb") as f:
        f.write(b" " * _TEXT_HEADER_LEN)
        f.write(bytes(binh))
        empty_th = bytes(_TRACE_HEADER_LEN)
        for tr in data:
            f.write(empty_th)
            f.write(tr.astype(">f4").tobytes())
