"""Gather / dispersion artifact persistence (npz round-trip).

Schema-compatible with the reference so archives interchange both ways:

- virtual shot gathers: ``XCF_out`` (nch, wlen), ``x_axis`` (offsets, m),
  ``t_axis`` (lags, s) — VirtualShotGather.save_to_npz /
  get_VirtualShotGather_obj, apis/virtual_shot_gather.py:212-217,231-232;
- dispersion maps: ``freqs``, ``vels``, ``fv_map`` — Dispersion.save_to_npz
  / get_dispersion_obj, modules/utils.py:394-402.

Plus one capability the reference lacks: ``save_window_gathers`` persists a
whole *per-window* gather batch, so bootstrap resampling and per-class
stacking (which are linear in the per-window gathers) can run across
sessions on precomputed gathers instead of recomputing every correlation
(the reference recomputes every gather every bootstrap rep,
apis/imaging_classes.py:31-36).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class GatherArtifact(NamedTuple):
    xcf: np.ndarray        # (nch, wlen)
    offsets: np.ndarray    # (nch,) offsets re-zeroed at the pivot [m]
    lags: np.ndarray       # (wlen,) zero-lag-centered lag axis [s]


class DispersionArtifact(NamedTuple):
    fv_map: np.ndarray     # (nvel, nfreq)
    freqs: np.ndarray
    vels: np.ndarray


class WindowGathersArtifact(NamedTuple):
    gathers: np.ndarray    # (n_windows, nch, wlen) per-window VSGs
    valid: np.ndarray      # (n_windows,) bool
    offsets: np.ndarray    # (nch,)
    lags: np.ndarray       # (wlen,)


def save_gather_npz(path: str, xcf, offsets, lags, **extra) -> None:
    """Reference VirtualShotGather schema (XCF_out / x_axis / t_axis)."""
    np.savez(path, XCF_out=np.asarray(xcf), x_axis=np.asarray(offsets),
             t_axis=np.asarray(lags), **extra)


def load_gather_npz(path: str) -> GatherArtifact:
    f = np.load(path, allow_pickle=True)
    return GatherArtifact(xcf=f["XCF_out"], offsets=f["x_axis"],
                          lags=f["t_axis"])


def save_dispersion_npz(path: str, fv_map, freqs, vels) -> None:
    """Reference Dispersion schema (freqs / vels / fv_map)."""
    np.savez(path, freqs=np.asarray(freqs), vels=np.asarray(vels),
             fv_map=np.asarray(fv_map))


def load_dispersion_npz(path: str) -> DispersionArtifact:
    f = np.load(path)
    return DispersionArtifact(fv_map=f["fv_map"], freqs=f["freqs"],
                              vels=f["vels"])


def save_window_gathers(path: str, gathers, valid, offsets, lags,
                        **extra) -> None:
    """Per-window gather batch for cross-session bootstrap/classing."""
    np.savez_compressed(path, gathers=np.asarray(gathers),
                        valid=np.asarray(valid), x_axis=np.asarray(offsets),
                        t_axis=np.asarray(lags), **extra)


def load_window_gathers(path: str) -> WindowGathersArtifact:
    f = np.load(path, allow_pickle=True)
    return WindowGathersArtifact(gathers=f["gathers"], valid=f["valid"],
                                 offsets=f["x_axis"], lags=f["t_axis"])
