"""Synthetic vehicle-DAS scene generator.

The reference's analysis inputs (``data/sw_data/700.pkl`` etc., loaded at
imaging_diff_speed.ipynb cell 2) are not shipped with the repo, so this module
generates physically-plausible scenes end-to-end testable against known truth:

- **quasi-static deformation**: a slow negative deflection pulse as each
  vehicle passes each channel (the 0.08-1 Hz band the tracker uses,
  reference apis/timeLapseImaging.py:83-85), amplitude ∝ vehicle weight;
- **dispersive surface waves**: each vehicle radiates a band-limited wavelet
  from every channel crossing, propagated with a prescribed phase-velocity
  curve c(f) — the ground truth the dispersion transform must recover.

The surface-wave synthesis is a per-frequency convolution along the channel
axis (sources live on the same uniform grid as receivers), so the whole scene
is O(nf · nx log nx) instead of O(nf · nx²).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from das_diff_veh_tpu.core.section import DasSection


def default_phase_velocity(freqs: np.ndarray) -> np.ndarray:
    """Smooth fundamental-mode-like Rayleigh curve: fast at low f, slow at high f.

    Shaped to sit inside the reference scan grid (200-1200 m/s, 0.8-25 Hz;
    apis/dispersion_classes.py:11).
    """
    freqs = np.asarray(freqs, dtype=np.float64)
    return 300.0 + 600.0 * np.exp(-np.maximum(freqs, 0.0) / 6.0)


@dataclass
class SceneConfig:
    nch: int = 140
    dx: float = 8.16
    fs: float = 250.0
    duration: float = 120.0
    start_ch: int = 400                 # interrogator channel offset (x = (ch-400)*dx)
    # vehicles
    n_vehicles: int = 6
    speed_range: tuple = (8.0, 22.0)    # m/s
    weight_range: tuple = (0.8, 2.5)    # arbitrary load units
    # quasi-static pulse
    qs_tau: float = 0.9                 # pulse width [s]
    qs_amp: float = 2.0
    # surface waves
    sw_amp: float = 0.35
    sw_fmin: float = 1.0
    sw_fmax: float = 24.0
    attenuation_length: float = 400.0   # exponential decay [m]
    phase_velocity: Callable[[np.ndarray], np.ndarray] = field(default=default_phase_velocity)
    noise_std: float = 0.01
    seed: int = 0


@dataclass
class SceneTruth:
    t_enter: np.ndarray        # (nveh,) entry time at x=0 of the section [s]
    speed: np.ndarray          # (nveh,) m/s
    weight: np.ndarray         # (nveh,)
    phase_velocity: Callable[[np.ndarray], np.ndarray]

    def arrival_times(self, x: np.ndarray) -> np.ndarray:
        """(nveh, nx) arrival time of each vehicle at each position."""
        return self.t_enter[:, None] + np.asarray(x)[None, :] / self.speed[:, None]


def _band_wavelet_spectrum(freqs: np.ndarray, fmin: float, fmax: float) -> np.ndarray:
    """Smooth band-limited amplitude spectrum (cosine-tapered band edges)."""
    f = np.asarray(freqs)
    bw = fmax - fmin
    lo_edge = 0.25 * bw
    amp = np.zeros_like(f)
    inside = (f >= fmin) & (f <= fmax)
    u = np.clip((f - fmin) / lo_edge, 0.0, 1.0) * np.clip((fmax - f) / lo_edge, 0.0, 1.0)
    amp[inside] = np.sin(0.5 * np.pi * np.clip(u[inside], 0, 1)) ** 2
    return amp


def surface_wave_field(nch: int, nt: int, dx: float, dt: float,
                       crossing_times: np.ndarray, amps: np.ndarray,
                       phase_velocity: Callable[[np.ndarray], np.ndarray],
                       fmin: float = 1.0, fmax: float = 24.0,
                       attenuation_length: float = 400.0) -> np.ndarray:
    """(nch, nt) dispersive wavefield radiated by moving sources.

    Source ``v`` fires a band-limited wavelet from every channel it crosses,
    at ``crossing_times[v, ch]`` with amplitude ``amps[v]``; propagation
    along the channel axis uses the prescribed c(f) (per-frequency channel
    convolution, O(nf · nx log nx)).  Shared by the scene synthesizer and
    the benchmark workload builder (each benchmark window radiates from its
    own trajectory instead of re-using one cached shot)."""
    crossing_times = np.atleast_2d(np.asarray(crossing_times, np.float64))
    amps = np.atleast_1d(np.asarray(amps, np.float64))
    nf = 2 * nt                                           # zero-pad to avoid wrap
    freqs = np.fft.rfftfreq(nf, d=dt)                     # (nfr,)
    amp = _band_wavelet_spectrum(freqs, fmin, fmax)
    c = np.maximum(phase_velocity(freqs), 1e-3)           # (nfr,)

    # propagation kernel over channel-offset d >= 0: exp(-i 2π f d / c(f)) decay
    nxp = 2 * nch                                         # zero-pad channel conv
    offs = np.arange(nch) * dx                            # one-sided offsets
    geo = np.exp(-offs / attenuation_length) / np.sqrt(offs + 2.0 * dx)
    kern = geo[None, :] * np.exp(-2j * np.pi * freqs[:, None] * offs[None, :] / c[:, None])
    kern_pos = np.zeros((freqs.size, nxp), dtype=np.complex128)
    kern_pos[:, :nch] = kern                              # causal (rightward) part
    kern_neg = np.zeros_like(kern_pos)
    kern_neg[:, 0] = kern[:, 0]
    kern_neg[:, nxp - nch + 1:] = kern[:, 1:][:, ::-1]    # leftward part
    # two-sided kernel; avoid double-count at zero offset
    kern2 = kern_pos + kern_neg
    kern2[:, 0] = kern[:, 0]
    K = np.fft.fft(kern2, axis=-1)                        # (nfr, nxp)

    sw = np.zeros((nch, nt), dtype=np.float64)
    for v in range(crossing_times.shape[0]):
        # source spectrum per channel crossing: delta at crossing_times[v]
        src = np.zeros((freqs.size, nxp), dtype=np.complex128)
        src[:, :nch] = np.exp(-2j * np.pi * freqs[:, None]
                              * crossing_times[v][None, :])
        U = np.fft.ifft(np.fft.fft(src, axis=-1) * K, axis=-1)[:, :nch]
        U *= (amps[v] * amp)[:, None]
        sw += np.fft.irfft(U.T, n=nf, axis=-1)[:, :nt]
    return sw


def synthesize_section(cfg: SceneConfig):
    """Build one DAS section with cfg.n_vehicles vehicles.

    Returns ``(DasSection, SceneTruth)``.  Data layout matches the reference
    waterfalls: shape (nch, nt), x in meters along fiber, t in seconds.
    """
    rng = np.random.default_rng(cfg.seed)
    nt = int(round(cfg.duration * cfg.fs))
    dt = 1.0 / cfg.fs
    x = np.arange(cfg.nch) * cfg.dx
    t = np.arange(nt) * dt

    span = x[-1] - x[0]
    speed = rng.uniform(*cfg.speed_range, size=cfg.n_vehicles)
    weight = rng.uniform(*cfg.weight_range, size=cfg.n_vehicles)
    # spread entries so each vehicle's full transit fits in the record
    max_transit = span / speed.min()
    t_enter = np.sort(rng.uniform(2.0, max(cfg.duration - max_transit - 2.0, 3.0),
                                  size=cfg.n_vehicles))
    truth = SceneTruth(t_enter=t_enter, speed=speed, weight=weight,
                       phase_velocity=cfg.phase_velocity)

    t_arr = truth.arrival_times(x)                       # (nveh, nx)

    # --- quasi-static deflection: -w * gaussian(t - t_arr(x)) ------------------
    # (nveh, nx, nt) would be large; accumulate per vehicle
    data = np.zeros((cfg.nch, nt), dtype=np.float64)
    for v in range(cfg.n_vehicles):
        pulse = np.exp(-0.5 * ((t[None, :] - t_arr[v][:, None]) / cfg.qs_tau) ** 2)
        data -= cfg.qs_amp * weight[v] * pulse

    # --- dispersive surface waves ---------------------------------------------
    data += surface_wave_field(cfg.nch, nt, cfg.dx, dt, t_arr,
                               cfg.sw_amp * weight, cfg.phase_velocity,
                               cfg.sw_fmin, cfg.sw_fmax,
                               cfg.attenuation_length)
    if cfg.noise_std > 0:
        data += cfg.noise_std * rng.standard_normal(data.shape)

    return DasSection(data, x, t), truth


def dispersive_shot(nx: int, nt: int, dx: float, dt: float,
                    phase_velocity: Callable[[np.ndarray], np.ndarray] = default_phase_velocity,
                    src_idx: int = 0, fmin: float = 1.0, fmax: float = 24.0,
                    attenuation_length: float = 1e9) -> np.ndarray:
    """Single point-source dispersive wavefield on a line — the closed-form
    oracle for dispersion-transform tests (slant stack of this field must
    recover ``phase_velocity``)."""
    nf = 2 * nt
    freqs = np.fft.rfftfreq(nf, d=dt)
    amp = _band_wavelet_spectrum(freqs, fmin, fmax)
    c = np.maximum(phase_velocity(freqs), 1e-3)
    offs = np.abs(np.arange(nx) - src_idx) * dx
    U = amp[None, :] * np.exp(-2j * np.pi * freqs[None, :] * offs[:, None] / c[None, :])
    U *= np.exp(-offs / attenuation_length)[:, None]
    return np.fft.irfft(U, n=nf, axis=-1)[:, :nt]
