from das_diff_veh_tpu.io.readers import (DirectoryDataset, read_npz_section,
                                         read_sections, read_segy_section)
from das_diff_veh_tpu.io.synthetic import SceneConfig, synthesize_section
