from das_diff_veh_tpu.io.readers import (  # noqa: F401
    read_npz_section,
    read_segy_section,
    read_sections,
    DirectoryDataset,
)
from das_diff_veh_tpu.io.synthetic import SceneConfig, synthesize_section  # noqa: F401
