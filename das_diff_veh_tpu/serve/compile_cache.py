"""Compiled-function cache keyed on ``(bucket_shape, config_hash)``.

The engine never calls a compute function directly: it asks this cache for
the program bound to a request's bucket.  A program is built once per
``(bucket, config_key)`` by the engine's :class:`ComputeFactory` and then
reused for every request padded to that bucket — with AOT warmup at engine
start, steady-state traffic confined to the configured buckets performs
zero new compilations (the ``cache_misses`` counter stays at zero; warmup
builds are counted separately as ``warmup_builds``).

The build itself is what triggers JAX tracing/compilation for the real
imaging path: ``warmup`` runs the fresh program once on the factory's
representative section, so XLA compiles ahead of the first real request
(and lands in the persistent compilation cache when one is configured).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.serve.buckets import Bucket

log = logging.getLogger("das_diff_veh_tpu.serve")

# (padded_section, valid (n_ch, nt), state_in) -> (result, state_out)
ComputeFn = Callable[[DasSection, Tuple[int, int], Any], Tuple[Any, Any]]


class ComputeFactory:
    """Builds one compute function per bucket; subclass or wrap a closure.

    ``config_key`` is hashed into the cache key: two engines serving
    different numerical configs never share programs.  ``warmup_section``
    must return an input *representative of real traffic* for the bucket —
    for the imaging pipeline that means the deployment's actual fiber axis,
    because host-side geometry (``x`` values) selects static slice bounds
    and therefore the compiled program (see serve/imaging.py).
    """

    config_key: str = ""

    tuner_entry = None
    """Tuner-store entry applied to this factory's config at construction
    (``das_diff_veh_tpu.tune``), or None when running default knobs.
    Factories that consult the store (serve/imaging.py) set it *before*
    computing ``config_key``, so tuned and default deployments never share
    cache entries; ``warmup`` logs it as build provenance."""

    def build(self, bucket: Bucket) -> ComputeFn:
        raise NotImplementedError

    def build_placed(self, bucket: Bucket, placement) -> ComputeFn:
        """Placement-aware build for mesh serving (``serve.mesh``): called
        with the :class:`~das_diff_veh_tpu.serve.mesh.Placement` the cache
        entry is keyed under.  Default ignores the placement — every
        replica runs the single-device program (a fresh closure per
        placement, so each replica's jit cache is its own).  Factories
        with an SPMD variant override this and return the ``shard_map``
        program for ``placement.kind == "ring"`` (see
        serve/mesh/allpairs.py)."""
        return self.build(bucket)

    def validate(self, section: DasSection,
                 bucket: Bucket) -> Optional[str]:
        """Admission-time check, called by ``ServingEngine.submit`` after
        bucket selection: return a human-readable rejection reason for a
        request this factory could never serve (shed up front as
        ``InvalidRequestError`` instead of failing later on the dispatcher),
        or None to admit.  Default: everything is servable."""
        return None

    def warmup_section(self, bucket: Bucket) -> DasSection:
        import numpy as np
        n_ch, nt = bucket
        return DasSection(np.zeros(bucket, dtype=np.float32),
                          np.arange(n_ch, dtype=np.float64),
                          np.arange(nt, dtype=np.float64))


class FnComputeFactory(ComputeFactory):
    """Adapter: a plain ``bucket -> ComputeFn`` builder plus a key."""

    def __init__(self, build_fn: Callable[[Bucket], ComputeFn],
                 config_key: str = "",
                 warmup_section_fn: Optional[Callable[[Bucket], DasSection]] = None):
        self._build_fn = build_fn
        self.config_key = config_key
        self._warmup_section_fn = warmup_section_fn

    def build(self, bucket: Bucket) -> ComputeFn:
        return self._build_fn(bucket)

    def warmup_section(self, bucket: Bucket) -> DasSection:
        if self._warmup_section_fn is not None:
            return self._warmup_section_fn(bucket)
        return super().warmup_section(bucket)


class CompiledFunctionCache:
    """Maps ``(bucket, config_key, placement)`` to a built compute function.

    ``placement`` is None for the single-device engine (the historical
    two-part key, unchanged behavior) or a ``serve.mesh.Placement`` — each
    replica and the ring hold their OWN entry per bucket, so AOT warmup per
    placement guarantees the zero-steady-state-compile SLO holds on every
    worker, not just the first one to touch a bucket.
    """

    def __init__(self, factory: ComputeFactory, metrics):
        self._factory = factory
        self._metrics = metrics
        self._lock = threading.Lock()
        self._programs: Dict[Tuple[Bucket, str, Optional[str]], ComputeFn] = {}

    def _key(self, bucket: Bucket,
             placement=None) -> Tuple[Bucket, str, Optional[str]]:
        return (bucket, self._factory.config_key,
                None if placement is None else placement.key)

    def _build(self, bucket: Bucket, placement) -> ComputeFn:
        if placement is None:
            return self._factory.build(bucket)
        return self._factory.build_placed(bucket, placement)

    def warmup(self, bucket: Bucket, placement=None, device=None) -> None:
        """Build the ``(bucket, placement)`` program and execute it once on
        the factory's representative section, so tracing AND the XLA
        compile happen now.  ``device``: run the warmup execution under
        ``jax.default_device`` so a replica's compile lands on the device
        its worker will dispatch to."""
        key = self._key(bucket, placement)
        with self._lock:
            if key in self._programs:
                return
            program = self._build(bucket, placement)
            self._programs[key] = program
        self._metrics.inc("warmup_builds")
        tuned = getattr(self._factory, "tuner_entry", None)
        if tuned is not None:
            # tuned-knob provenance: this warmed program IS the tuned one
            # (the factory applied winners before computing config_key)
            self._metrics.inc("tuned_warmups")
            log.info("bucket %s warms with tuned knobs %s", bucket,
                     tuned.winners)
        section = self._factory.warmup_section(bucket)
        if device is not None:
            import jax
            with jax.default_device(device):
                program(section, bucket, None)
        else:
            program(section, bucket, None)
        log.info("warmed bucket %s placement %s", bucket,
                 None if placement is None else placement.key)

    def get(self, bucket: Bucket, placement=None) -> ComputeFn:
        """Program for ``(bucket, placement)``; builds on miss (counted —
        steady-state in-bucket traffic after warmup never misses)."""
        key = self._key(bucket, placement)
        with self._lock:
            program = self._programs.get(key)
            if program is not None:
                self._metrics.inc("cache_hits")
                return program
            program = self._build(bucket, placement)
            self._programs[key] = program
        self._metrics.inc("cache_misses")
        log.info("compiled-cache miss: built bucket %s placement %s "
                 "on demand", bucket,
                 None if placement is None else placement.key)
        return program

    @property
    def buckets(self):
        with self._lock:
            return sorted({b for b, _, _ in self._programs})
