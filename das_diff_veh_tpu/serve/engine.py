"""In-process online serving engine: continuously batched, bucketed,
deadline-aware.

Callers ``submit(section, deadline_ms=..., session=...)`` and get a
``concurrent.futures.Future`` back; a single dispatcher thread drains the
bounded admission queue, pads each request to its bucket, and executes it
through the compiled-function cache.  Overload is shed, not absorbed:

- **reject-on-full** — ``submit`` raises :class:`QueueFullError` once
  ``max_queue`` requests wait (counted as ``shed_rejected``);
- **expire-in-queue** — a request whose deadline passes before compute
  starts fails with :class:`DeadlineExceededError` (``shed_expired``)
  instead of wasting device time on an answer nobody is waiting for.

Batching is *continuous* (iteration-level, the Orca/vLLM discipline —
PAPERS.md): the batch slot stays open while members execute, and a
same-bucket request that arrives mid-batch is admitted into the open slot
at the next member boundary (counted as ``continuous_admitted``) instead
of waiting out a linger window.  An idle engine therefore executes a lone
request immediately — the old ``batch_window_ms`` linger is gone — while
a busy engine still coalesces up to ``max_batch`` members per
compiled-program visit.  Members execute *serially* through the bucket's
one compiled program (``process_chunk`` is not vmappable across requests —
host-side geometry staging picks static slice bounds per call): what
batching buys is one program lookup and bucket switch per batch,
back-to-back device dispatches, and coherent deadline checks — not
vectorized compute.

Every request is accounted in four spans — queue / pad / compute / unpad —
emitted through :mod:`das_diff_veh_tpu.runtime.tracing` (the queue span
starts in ``submit`` and closes on the dispatcher thread via
``tracer.complete``) and aggregated by :class:`ServeMetrics`
(p50/p95/p99 latency, queue depth, batch occupancy, shed + cache counters:
``engine.metrics()``).  Consecutive segments of one fiber may share a
``session``: the dispatcher threads the per-session state through the
compute function in execution order (see serve/session.py).
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Optional, Tuple

from das_diff_veh_tpu.config import ServeConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.obs import xla_events
from das_diff_veh_tpu.obs.flight import FlightRecorder
from das_diff_veh_tpu.obs.profiling import HBMSampler
from das_diff_veh_tpu.obs.registry import MetricsRegistry
from das_diff_veh_tpu.resilience import faults
from das_diff_veh_tpu.runtime.tracing import NullTracer
from das_diff_veh_tpu.serve.buckets import (Bucket, normalize_buckets,
                                            pad_section, pick_bucket)
from das_diff_veh_tpu.serve.compile_cache import (CompiledFunctionCache,
                                                  ComputeFactory)
from das_diff_veh_tpu.serve.metrics import ServeMetrics
from das_diff_veh_tpu.serve.session import SessionStore

log = logging.getLogger("das_diff_veh_tpu.serve")


class ShedError(RuntimeError):
    """Base class for load-shedding rejections."""


class QueueFullError(ShedError):
    """Admission queue at ``max_queue``: backpressure, try again later."""


class DeadlineExceededError(ShedError):
    """The request's deadline passed before compute started."""


class NoBucketError(ShedError):
    """No configured bucket fits the request's ``(n_ch, nt)``."""


class InvalidRequestError(ShedError):
    """The compute factory's admission check rejected the request (e.g.
    geometry that does not match the warmed programs)."""


class PoisonInputError(InvalidRequestError):
    """The admission-time health screen rejected the request: NaN/Inf
    content or dead/clipped channels beyond ``ServeConfig.health`` bounds.
    Shed *before* queueing so one poison request can never contaminate a
    microbatch cohort's shared dispatch window.  Carries the structured
    :class:`~das_diff_veh_tpu.resilience.health.ChannelHealth` report the
    HTTP front renders as a 422 body."""

    def __init__(self, reason: str, health):
        super().__init__(reason)
        self.health = health


class EngineClosedError(RuntimeError):
    """submit() after close()."""


class ShutdownError(EngineClosedError):
    """The engine was closed while this request was still pending and the
    dispatcher could not be joined (wedged in a long compute): the future
    is failed with this instead of hanging its caller forever."""


@dataclass
class _Request:
    section: DasSection
    valid: Tuple[int, int]
    bucket: Bucket
    deadline: float                    # absolute perf_counter seconds
    session: Optional[str]
    future: Future
    t_submit: float                    # perf_counter seconds
    t_submit_us: float                 # tracer clock (for the queue span)
    tenant: Optional[str] = None       # mesh engine: quota/fair-share owner
    session_key: Optional[str] = None  # SessionStore key (mesh engine
                                       # tenant-namespaces it; base = session)
    placement: Any = None              # mesh engine: serve.mesh Placement


class ServingEngine:
    """One engine = one numerical config + one bucket set + one dispatcher.

    ``factory`` builds the per-bucket compute functions (see
    serve/compile_cache.py for the contract; serve/imaging.py for the real
    ``process_chunk`` factory).  Call :meth:`start` before submitting;
    :meth:`close` drains in-flight requests and stops the dispatcher.
    """

    def __init__(self, factory: ComputeFactory,
                 cfg: Optional[ServeConfig] = None, tracer=None,
                 registry: Optional[MetricsRegistry] = None,
                 flight: Optional[FlightRecorder] = None):
        self.cfg = cfg if cfg is not None else ServeConfig()
        self.buckets = normalize_buckets(self.cfg.buckets)
        self.factory = factory
        self.tracer = tracer if tracer is not None else NullTracer()
        # each engine defaults to its own registry (isolation); pass
        # obs.default_registry() to join the process-wide scrape/sink —
        # the serve CLI does, so runtime/parallel metrics ride /metrics too
        self.registry = registry if registry is not None else MetricsRegistry()
        self._metrics = ServeMetrics(latency_window=self.cfg.latency_window,
                                     registry=self.registry)
        obs_cfg = self.cfg.obs
        self.flight = flight if flight is not None else FlightRecorder(
            capacity=obs_cfg.flight_capacity, out_dir=obs_cfg.flight_dir,
            name="serve_flight")
        self._compile_watch = None
        self._hbm: Optional[HBMSampler] = None
        self.sessions = SessionStore()
        self.cache = CompiledFunctionCache(factory, self._metrics)
        self._queue: queue.Queue = queue.Queue(maxsize=self.cfg.max_queue)
        self._stash: deque = deque()   # dequeued, deferred to a later batch
        # requests dequeued into the dispatcher's current batch but not yet
        # executing: a wedged close() must fail these too (they are in
        # neither the queue nor the stash).  Guarded by _backlog_lock so the
        # close-path snapshot never races the dispatcher's append/popleft.
        self._batch_backlog: deque = deque()
        self._backlog_lock = threading.Lock()
        self._dispatch_seq = itertools.count()   # serve.dispatch fault keys
        self._closed = threading.Event()
        self._started = False
        self._thread: Optional[threading.Thread] = None
        self._metrics.bind_queue_depth(
            lambda: self._queue.qsize() + len(self._stash))

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ServingEngine":
        if self._closed.is_set():
            raise EngineClosedError("engine was closed; build a new one")
        if self._started:
            return self
        self._started = True
        if self.cfg.compilation_cache_dir:
            from das_diff_veh_tpu.cache import enable_compilation_cache
            enable_compilation_cache(cache_dir=self.cfg.compilation_cache_dir)
        if self.cfg.obs.xla_events:
            self._compile_watch = xla_events.install(self.registry)
        if self.cfg.obs.hbm_sample_interval_s > 0:
            self._hbm = HBMSampler(
                self.registry, interval_s=self.cfg.obs.hbm_sample_interval_s)
        if self.cfg.warmup:
            with self.tracer.span("warmup", cat="serve",
                                  buckets=list(map(list, self.buckets))):
                self._warmup_all()
        if self._compile_watch is not None:
            # device-truth SLO gauge: jaxpr traces since warmup finished.
            # The compiled-function cache's own hit/miss counters cannot see
            # a compile that happens OUTSIDE the cache; jax.monitoring can.
            watch, base = self._compile_watch, self._compile_watch.traces
            self.registry.gauge(
                "das_serve_steady_state_compiles",
                "fresh jit traces since warmup (SLO: stays 0)",
            ).set_fn(lambda: watch.traces - base)
        self._start_workers()
        return self

    def _warmup_all(self) -> None:
        """AOT-compile every configured bucket (the mesh engine overrides
        this to warm per placement)."""
        for b in self.buckets:
            self.cache.warmup(b)

    def _start_workers(self) -> None:
        """Spawn the execution thread(s); the base engine runs ONE
        dispatcher, the mesh engine one worker per replica plus the ring."""
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="serve-dispatch", daemon=True)
        self._thread.start()

    def close(self, timeout: float = 30.0) -> None:
        """Stop admitting, drain queued requests, join the dispatcher.

        Requests already queued complete normally; anything that slips into
        the queue after the dispatcher exits (the submit/close race) is
        failed with :class:`EngineClosedError` rather than left hanging."""
        self._closed.set()
        if self._compile_watch is not None:
            xla_events.uninstall(self.registry)
            self._compile_watch = None
        if self._hbm is not None:
            self._hbm.close()
            self._hbm = None
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # wedged in a long compute: it owns the request it is
                # currently executing, but everything still queued, stashed,
                # or dequeued into its unexecuted batch tail would otherwise
                # hang its caller on .result() forever — fail those futures
                # NOW.  Futures are only ever resolved
                # through done()-guarded set_result/set_exception calls, so
                # if the dispatcher later unwedges it skips them cleanly.
                n = (self._queue.qsize() + len(self._stash)
                     + len(self._batch_backlog))
                log.warning("dispatcher did not exit within %.1fs (compute "
                            "still running); failing %d pending requests "
                            "with ShutdownError", timeout, n)
                self._fail_pending(ShutdownError(
                    f"engine closed while the dispatcher was wedged "
                    f"(did not exit within {timeout:.1f}s)"), drain=False)
                return
            self._thread = None
        self._fail_pending(EngineClosedError("engine closed"))

    def _fail_pending(self, exc: Exception, drain: bool = True) -> None:
        """Fail queued/stashed futures.  ``drain=True`` (dispatcher gone):
        pop everything via the normal path.  ``drain=False`` (dispatcher
        wedged but alive): fail the stash and the dispatcher's current
        batch backlog over *snapshots* without mutating their deques (the
        dispatcher owns them — it skips done() futures when it unwedges),
        and pop only from the thread-safe admission queue."""
        if not drain:
            with self._backlog_lock:
                backlog = list(self._batch_backlog)
            for req in backlog + list(self._stash):
                if not req.future.done():
                    req.future.set_exception(exc)
                    self._finish(req, "shutdown")
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    return
                if not req.future.done():
                    req.future.set_exception(exc)
                    self._finish(req, "shutdown")
        while True:
            req = self._next_request(timeout=0.0)
            if req is None:
                return
            if not req.future.done():
                req.future.set_exception(exc)
                self._finish(req, "shutdown")

    # -- submission ----------------------------------------------------------
    def _admit_checks(self, section: DasSection,
                      session: Optional[str]) -> Tuple[Tuple[int, int], Bucket]:
        """Shape/geometry/health admission gauntlet shared with the mesh
        engine: returns ``(valid, bucket)`` or raises the shed error."""
        valid = tuple(int(s) for s in section.data.shape)
        bucket = pick_bucket(valid, self.buckets)
        if bucket is None:
            self._metrics.inc("shed_no_bucket")
            self._record_shed("no_bucket", valid, None, session)
            raise NoBucketError(
                f"no bucket fits request shape {valid} "
                f"(buckets: {list(self.buckets)})")
        reason = self.factory.validate(section, bucket)
        if reason is not None:
            self._metrics.inc("shed_invalid")
            self._record_shed("invalid", valid, bucket, session, reason=reason)
            raise InvalidRequestError(reason)
        hcfg = self.cfg.health
        if hcfg is not None and hcfg.enabled:
            # zero-dispatch numpy screen on the request thread: a poison
            # request (NaN/Inf burst, dead-channel flood) is shed HERE so
            # it can never share a microbatch window with healthy cohort
            # members — the 422 path (docs/ROBUSTNESS.md)
            from das_diff_veh_tpu.resilience.health import (admission_verdict,
                                                            quick_screen)
            health = quick_screen(section.data, hcfg)
            verdict = admission_verdict(health, hcfg)
            if verdict is not None:
                self._metrics.inc("shed_poison")
                self._record_shed("poison", valid, bucket, session,
                                  **health.summary())
                raise PoisonInputError(verdict, health)
        return valid, bucket

    def submit(self, section: DasSection, deadline_ms: Optional[float] = None,
               session: Optional[str] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the compute
        result (or raising the shed/compute error).  Raises immediately on
        backpressure (:class:`QueueFullError`) and unservable shapes
        (:class:`NoBucketError`).  ``tenant`` is accepted for interface
        parity with the mesh engine and ignored here — the single-device
        engine has no quotas (``serve.mesh.MeshServingEngine`` enforces
        them)."""
        if self._closed.is_set():
            raise EngineClosedError("engine is closed")
        valid, bucket = self._admit_checks(section, session)
        if deadline_ms is None:
            deadline_ms = self.cfg.default_deadline_ms
        now = time.perf_counter()
        req = _Request(section=section, valid=valid, bucket=bucket,
                       deadline=now + deadline_ms / 1e3, session=session,
                       future=Future(), t_submit=now,
                       t_submit_us=self.tracer.now_us(), session_key=session)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            self._metrics.inc("shed_rejected")
            self.tracer.instant("shed", cat="serve", reason="queue_full")
            self._record_shed("queue_full", valid, bucket, session)
            raise QueueFullError(
                f"admission queue full ({self.cfg.max_queue})") from None
        self._metrics.inc("submitted")
        # submit/close race: if close() won and the dispatcher already
        # exited, nothing will ever drain this request — fail it now
        # instead of hanging the caller.  (A dispatcher that is merely
        # draining is still alive and will process it.)
        if self._closed.is_set() and (
                self._thread is None or not self._thread.is_alive()):
            if not req.future.done():
                req.future.set_exception(EngineClosedError("engine closed"))
            raise EngineClosedError("engine is closed")
        return req.future

    def process(self, section: DasSection,
                deadline_ms: Optional[float] = None,
                session: Optional[str] = None,
                timeout: Optional[float] = None,
                tenant: Optional[str] = None) -> Any:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(section, deadline_ms, session,
                           tenant=tenant).result(timeout)

    def _record_shed(self, cause: str, valid, bucket, session,
                     **fields) -> None:
        """Flight-record one shed request and (rate-limited) dump — the
        post-mortem artifact for 'why did production reject traffic'."""
        self.flight.record("shed", cause=cause, shape=list(valid),
                           bucket=list(bucket) if bucket else None,
                           session=session, **fields)
        self.flight.dump("shed", cause=cause)

    # -- introspection -------------------------------------------------------
    def metrics(self) -> dict:
        snap = self._metrics.snapshot()
        snap["buckets"] = [list(b) for b in self.buckets]
        snap["sessions"] = len(self.sessions)
        return snap

    def session_state(self, session: str) -> Any:
        return self.sessions.get(session)

    # -- dispatcher ----------------------------------------------------------
    def _finish(self, req: _Request, outcome: str) -> None:
        """Terminal-outcome hook, called exactly once per request from
        whichever path resolves its future (``completed`` / ``error`` /
        ``expired`` / ``shutdown``).  Base engine: nothing to release; the
        mesh engine returns the tenant's quota slot and records per-tenant
        outcome counters here."""

    def _expired(self, req: _Request) -> bool:
        if time.perf_counter() <= req.deadline:
            return False
        self._metrics.inc("shed_expired")
        self.tracer.instant("shed", cat="serve", reason="deadline",
                            bucket=list(req.bucket))
        self._record_shed("deadline", req.valid, req.bucket, req.session,
                          queued_ms=(time.perf_counter() - req.t_submit) * 1e3)
        if not req.future.done():
            req.future.set_exception(DeadlineExceededError(
                f"deadline passed after "
                f"{(time.perf_counter() - req.t_submit) * 1e3:.1f} ms in queue"))
        self._finish(req, "expired")
        return True

    def _next_request(self, timeout: float) -> Optional[_Request]:
        if self._stash:
            return self._stash.popleft()
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def _poll_same_bucket(self, bucket: Bucket) -> Optional[_Request]:
        """A same-bucket companion waiting NOW, or None — the continuous
        batching admission point, called between member executions with the
        batch slot still open.  No linger: whatever already sits in the
        stash or the admission queue is considered, nothing is waited for.
        Other-bucket requests are stashed (they head a later batch, in
        arrival order)."""
        for i, r in enumerate(self._stash):
            if r.bucket == bucket:
                del self._stash[i]
                return r
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                return None
            if self._expired(r):
                continue
            if r.bucket == bucket:
                return r
            self._stash.append(r)

    def _dispatch_loop(self) -> None:
        while True:
            head = self._next_request(timeout=0.05)
            if head is None:
                if self._closed.is_set() and not self._stash \
                        and self._queue.empty():
                    return
                continue
            if self._expired(head):
                continue
            self._run_batch(head)

    def _run_batch(self, head: _Request, placement: Any = None,
                   poll=None) -> int:
        """Continuous batch anchored at ``head``: execute it immediately,
        then keep admitting same-bucket companions into the open slot at
        each member boundary (``poll``, default :meth:`_poll_same_bucket`)
        until none is waiting or ``max_batch`` members ran.  Members after
        the head are exactly the continuous admissions
        (``continuous_admitted``).  Returns the batch occupancy."""
        bucket = head.bucket
        program = self.cache.get(bucket, placement)
        poll = poll if poll is not None else self._poll_same_bucket
        occupancy = 0
        req: Optional[_Request] = head
        while req is not None:
            with self._backlog_lock:
                self._batch_backlog.append(req)
            if occupancy > 0:
                self._metrics.inc("continuous_admitted")
            self._execute_one(req, bucket, program, placement)
            occupancy += 1
            if occupancy >= self.cfg.max_batch:
                break
            req = poll(bucket)
        self._metrics.observe_batch(occupancy)
        self.tracer.counter("serve_batch", occupancy=occupancy)
        return occupancy

    def _call_program(self, program, padded: DasSection, req: _Request,
                      placement: Any):
        """Run the compiled program for one member — the mesh engine wraps
        this in the placement's device context."""
        return program(padded, req.valid, self.sessions.get(req.session_key))

    def _execute_one(self, req: _Request, bucket: Bucket, program,
                     placement: Any = None) -> None:
        with self._backlog_lock:       # req is now in-flight, not backlog
            if self._batch_backlog and self._batch_backlog[0] is req:
                self._batch_backlog.popleft()
            else:
                # mesh engine: several workers interleave one shared
                # backlog, so this member may not be at the head
                try:
                    self._batch_backlog.remove(req)
                except ValueError:
                    pass
        if req.future.done():          # failed by a wedged-dispatcher close
            return
        if self._expired(req):         # deadline may pass while batching
            return
        t_dq = time.perf_counter()
        self.tracer.complete("queue", req.t_submit_us, cat="serve",
                             bucket=list(bucket))
        try:
            # chaos site: per-request dispatch failure INSIDE the try —
            # an injected fault fails this one future, not the cohort
            faults.fire("serve.dispatch", next(self._dispatch_seq))
            t0 = time.perf_counter()
            with self.tracer.span("pad", cat="serve",
                                  valid=list(req.valid),
                                  bucket=list(bucket)):
                padded = pad_section(req.section, bucket)
            t1 = time.perf_counter()
            with self.tracer.span("compute", cat="serve",
                                  bucket=list(bucket)):
                result, state = self._call_program(program, padded, req,
                                                   placement)
            t2 = time.perf_counter()
            with self.tracer.span("unpad", cat="serve"):
                self.sessions.put(req.session_key, state)
                if not req.future.done():
                    req.future.set_result(result)
            t3 = time.perf_counter()
        except Exception as e:
            self._metrics.inc("errors")
            log.exception("request failed in bucket %s", bucket)
            self.flight.record("error", shape=list(req.valid),
                               bucket=list(bucket), session=req.session,
                               error=f"{type(e).__name__}: {e}")
            self.flight.dump("error", bucket=list(bucket))
            if not req.future.done():
                req.future.set_exception(e)
            self._finish(req, "error")
            return
        stages = {"queue": (t_dq - req.t_submit) * 1e3,
                  "pad": (t1 - t0) * 1e3,
                  "compute": (t2 - t1) * 1e3,
                  "unpad": (t3 - t2) * 1e3}
        self._metrics.observe_request((t3 - req.t_submit) * 1e3, stages)
        self._finish(req, "completed")
        self.flight.record("request", shape=list(req.valid),
                           bucket=list(bucket), session=req.session,
                           total_ms=round((t3 - req.t_submit) * 1e3, 3),
                           stages_ms={k: round(v, 3)
                                      for k, v in stages.items()})
