"""Mesh-distributed multi-tenant serving engine.

The PR 3 dispatcher married to the device mesh (ROADMAP item 1): one
:class:`MeshServingEngine` fans admitted requests across

- **N data-parallel replica workers** — one thread per device, each
  draining its own :class:`~das_diff_veh_tpu.serve.mesh.tenancy.FairQueue`
  and executing the single-device program under ``jax.default_device``
  (independent requests scale with the device count);
- **one ring worker** — dispatching the channel-sharded ``shard_map``
  program across the whole mesh for large-geometry requests
  (``ring_min_channels``; see serve/mesh/allpairs.py for the factory
  contract and the bit-exactness pin vs the single-device program).

Placement happens at admission (:class:`PlacementPolicy`: ring route,
session stickiness, least-loaded) and the compile cache holds ONE entry
per ``(bucket, placement)`` — AOT warmup covers every placement, so the
zero-steady-state-compile SLO holds on every worker.  Each worker runs the
base engine's continuous batching against its own queue: companions are
admitted at member boundaries in fair-share order (heads only, preserving
per-tenant FIFO and therefore per-session execution order).

Multi-tenancy is enforced at submit (quota / quarantine / drain gates —
serve/mesh/tenancy.py) and unwound in the ``_finish`` hook, which every
terminal path of the base engine calls exactly once per request; per-tenant
outcome counters and latency histograms land in the same registry the
single-engine metrics do, so one Prometheus scrape covers the whole mesh.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, List, Optional

from das_diff_veh_tpu.config import MeshServeConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.obs.flight import FlightRecorder
from das_diff_veh_tpu.obs.registry import MetricsRegistry
from das_diff_veh_tpu.serve.compile_cache import ComputeFactory
from das_diff_veh_tpu.serve.engine import (EngineClosedError, PoisonInputError,
                                           QueueFullError, ServingEngine,
                                           ShedError, ShutdownError, _Request)
from das_diff_veh_tpu.serve.mesh.placement import (RING, Placement,
                                                   PlacementPolicy)
from das_diff_veh_tpu.serve.mesh.tenancy import FairQueue, TenantTable
from das_diff_veh_tpu.serve.session import SessionStore

log = logging.getLogger("das_diff_veh_tpu.serve.mesh")

DEFAULT_TENANT = "default"


class NoReplicaError(ShedError):
    """Every replica is draining and the request has no ring route."""

    http_status = 503                  # whole-engine unavailability


class _Replica:
    """One data-parallel worker: device + queue + drain flag + thread."""

    def __init__(self, index: int, device):
        self.index = index
        self.device = device
        self.placement = Placement("replica", index)
        self.queue = FairQueue()
        self.draining = threading.Event()
        self.thread: Optional[threading.Thread] = None


class MeshServingEngine(ServingEngine):
    """Continuous batching across a device mesh, multi-tenant.

    ``mesh``: the ring placements' :class:`jax.sharding.Mesh`; defaults to
    ``parallel.mesh.make_mesh(cfg.ring_devices)`` when the ring route is
    enabled.  Everything else (buckets, deadlines, health screen, obs)
    rides the wrapped ``cfg.serve``.
    """

    def __init__(self, factory: ComputeFactory,
                 cfg: Optional[MeshServeConfig] = None, mesh=None,
                 tracer=None, registry: Optional[MetricsRegistry] = None,
                 flight: Optional[FlightRecorder] = None):
        cfg = cfg if cfg is not None else MeshServeConfig()
        super().__init__(factory, cfg.serve, tracer=tracer,
                         registry=registry, flight=flight)
        self.mesh_cfg = cfg
        import jax
        devices = list(jax.devices())
        n_rep = cfg.replicas if cfg.replicas is not None else len(devices)
        n_rep = max(1, min(int(n_rep), len(devices)))
        self._replicas: List[_Replica] = [
            _Replica(i, devices[i]) for i in range(n_rep)]
        self.ring_mesh = None
        self._ring_queue: Optional[FairQueue] = None
        self._ring_thread: Optional[threading.Thread] = None
        if cfg.ring_min_channels is not None:
            from das_diff_veh_tpu.parallel.mesh import make_mesh
            self.ring_mesh = mesh if mesh is not None else make_mesh(
                cfg.ring_devices)
            self._ring_queue = FairQueue()
        self.policy = PlacementPolicy(n_rep, cfg.ring_min_channels)
        self.tenants = TenantTable(cfg.tenant_quota,
                                   cfg.tenant_poison_quarantine)
        self._queued_total = 0
        self._queued_lock = threading.Lock()
        self._metrics.enable_mesh(n_rep)
        for rep in self._replicas:
            self._metrics.bind_replica_depth(rep.index, rep.queue.qsize)
        self._metrics.bind_queue_depth(self._depth_total)

    # -- introspection -------------------------------------------------------
    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def _depth_total(self) -> int:
        with self._queued_lock:
            return self._queued_total

    def _depths(self) -> List[int]:
        return [rep.queue.qsize() for rep in self._replicas]

    def _draining_flags(self) -> List[bool]:
        return [rep.draining.is_set() for rep in self._replicas]

    def metrics(self) -> dict:
        snap = super().metrics()
        snap["tenant_table"] = self.tenants.snapshot()
        snap["mesh"] = {
            "replicas": self.n_replicas,
            "draining": [rep.index for rep in self._replicas
                         if rep.draining.is_set()],
            "ring": self.ring_mesh is not None,
            "ring_devices": (0 if self.ring_mesh is None
                             else self.ring_mesh.devices.size),
        }
        return snap

    # -- lifecycle -----------------------------------------------------------
    def _warmup_all(self) -> None:
        """AOT warmup PER PLACEMENT: every bucket on every replica (the
        compile lands on the replica's device), plus ring-eligible buckets
        on the mesh — steady-state traffic never compiles on any worker."""
        ring_min = self.mesh_cfg.ring_min_channels
        for b in self.buckets:
            for rep in self._replicas:
                self.cache.warmup(b, rep.placement, device=rep.device)
            if self._ring_queue is not None and b[0] >= ring_min:
                self.cache.warmup(b, RING)

    def _start_workers(self) -> None:
        for rep in self._replicas:
            rep.thread = threading.Thread(
                target=self._worker_loop,
                args=(rep.queue, rep.placement, rep.draining, rep.index),
                name=f"serve-replica-{rep.index}", daemon=True)
            rep.thread.start()
        if self._ring_queue is not None:
            self._ring_thread = threading.Thread(
                target=self._worker_loop,
                args=(self._ring_queue, RING, None, None),
                name="serve-ring", daemon=True)
            self._ring_thread.start()

    def close(self, timeout: float = 30.0) -> None:
        """Stop admitting, let every worker drain its queue, join them all;
        a worker wedged in a long compute fails the still-pending requests
        with :class:`ShutdownError` exactly like the base engine."""
        self._closed.set()
        from das_diff_veh_tpu.obs import xla_events
        if self._compile_watch is not None:
            xla_events.uninstall(self.registry)
            self._compile_watch = None
        if self._hbm is not None:
            self._hbm.close()
            self._hbm = None
        for rep in self._replicas:
            rep.queue.wake()
        if self._ring_queue is not None:
            self._ring_queue.wake()
        threads = [rep.thread for rep in self._replicas if rep.thread]
        if self._ring_thread is not None:
            threads.append(self._ring_thread)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        if any(t.is_alive() for t in threads):
            n = self._depth_total()
            log.warning("mesh workers did not exit within %.1fs; failing "
                        "%d pending requests with ShutdownError", timeout, n)
            self._fail_pending(ShutdownError(
                f"engine closed while a worker was wedged "
                f"(did not exit within {timeout:.1f}s)"), drain=False)
            return
        self._fail_pending(EngineClosedError("engine closed"))

    def _fail_pending(self, exc: Exception, drain: bool = True) -> None:
        reqs: List[_Request] = []
        for rep in self._replicas:
            reqs.extend(rep.queue.drain_all())
        if self._ring_queue is not None:
            reqs.extend(self._ring_queue.drain_all())
        with self._backlog_lock:
            backlog = list(self._batch_backlog)
        for req in reqs:
            self._dec_queued()
        for req in backlog + reqs:
            if not req.future.done():
                req.future.set_exception(exc)
                self._finish(req, "shutdown")

    # -- workers -------------------------------------------------------------
    def _dec_queued(self) -> None:
        with self._queued_lock:
            if self._queued_total > 0:
                self._queued_total -= 1

    def _on_dequeue(self, replica_index: Optional[int]) -> None:
        self._dec_queued()
        if replica_index is not None:
            self._metrics.observe_replica_request(replica_index)

    def _worker_loop(self, q: FairQueue, placement: Placement,
                     draining: Optional[threading.Event],
                     replica_index: Optional[int]) -> None:
        while True:
            head = q.get(timeout=0.05)
            if head is None:
                if q.qsize() == 0 and (
                        self._closed.is_set()
                        or (draining is not None and draining.is_set())):
                    return
                continue
            self._on_dequeue(replica_index)
            if self._expired(head):
                continue
            if replica_index is not None:
                self._metrics.set_replica_busy(replica_index, True)
            try:
                self._run_batch(head, placement=placement,
                                poll=lambda b: self._poll_queue(
                                    q, b, replica_index))
            finally:
                if replica_index is not None:
                    self._metrics.set_replica_busy(replica_index, False)

    def _poll_queue(self, q: FairQueue, bucket,
                    replica_index: Optional[int]):
        req = q.poll_bucket(bucket)
        if req is not None:
            self._on_dequeue(replica_index)
        return req

    def _call_program(self, program, padded: DasSection, req: _Request,
                      placement: Any):
        state = self.sessions.get(req.session_key)
        if placement is not None and placement.kind == "replica":
            import jax
            with jax.default_device(self._replicas[placement.index].device):
                return program(padded, req.valid, state)
        return program(padded, req.valid, state)

    # -- tenancy unwind ------------------------------------------------------
    def _finish(self, req: _Request, outcome: str) -> None:
        # every terminal path (complete/error/expire/shutdown) funnels here
        # exactly once per request: the quota slot returns and the tenant's
        # outcome counters advance.  First-wins flag: a wedged close may
        # race the unwedging worker over the same request.
        with self._backlog_lock:
            if getattr(req, "_mesh_done", False):
                return
            req._mesh_done = True
        if req.tenant is None:
            return
        self.tenants.release(req.tenant)
        self._metrics.observe_tenant(req.tenant, outcome)
        if outcome == "completed":
            self._metrics.observe_tenant_latency(
                req.tenant, (time.perf_counter() - req.t_submit) * 1e3)

    # -- submission ----------------------------------------------------------
    def submit(self, section: DasSection, deadline_ms: Optional[float] = None,
               session: Optional[str] = None,
               tenant: Optional[str] = None):
        """Tenant-aware submit: gate (quarantine/drain) -> validate/health
        -> quota -> placement -> fair-queue enqueue.  ``tenant`` defaults
        to one shared ``"default"`` tenant, so single-tenant callers use
        the engine exactly like the base one."""
        tenant = tenant if tenant is not None else DEFAULT_TENANT
        if self._closed.is_set():
            raise EngineClosedError("engine is closed")
        try:
            self.tenants.gate(tenant)
        except ShedError as e:
            cause = ("quarantined" if "quarantined" in type(e).__name__.lower()
                     else "draining")
            self._metrics.inc(f"shed_{cause}")
            self._metrics.observe_tenant(tenant, f"shed_{cause}")
            self._record_shed(cause, tuple(section.data.shape), None,
                              session, tenant=tenant)
            raise
        try:
            valid, bucket = self._admit_checks(section, session)
        except PoisonInputError:
            self._metrics.observe_tenant(tenant, "shed_poison")
            if self.tenants.note_poison(tenant):
                self._metrics.observe_tenant(tenant, "quarantined")
                self.flight.record("tenant_quarantine", tenant=tenant)
                self.flight.dump("tenant_quarantine", tenant=tenant)
            raise
        self.tenants.note_healthy(tenant)
        try:
            self.tenants.admit(tenant)
        except ShedError:
            self._metrics.inc("shed_quota")
            self._metrics.observe_tenant(tenant, "shed_quota")
            self._record_shed("quota", valid, bucket, session, tenant=tenant)
            raise
        session_key = SessionStore.scoped(tenant, session)
        try:
            placement = self.policy.place(valid[0], session_key,
                                          self._depths(),
                                          self._draining_flags())
            if placement is None:
                self._record_shed("no_replica", valid, bucket, session,
                                  tenant=tenant)
                raise NoReplicaError(
                    "all replicas draining and no ring route fits")
            with self._queued_lock:
                if self._queued_total >= self.cfg.max_queue:
                    raise QueueFullError(
                        f"admission queues full ({self.cfg.max_queue} "
                        "across replicas + ring)")
                self._queued_total += 1
        except QueueFullError:
            self.tenants.release(tenant)
            self._metrics.inc("shed_rejected")
            self.tracer.instant("shed", cat="serve", reason="queue_full")
            self._record_shed("queue_full", valid, bucket, session,
                              tenant=tenant)
            raise
        except ShedError:
            self.tenants.release(tenant)
            raise
        if deadline_ms is None:
            deadline_ms = self.cfg.default_deadline_ms
        now = time.perf_counter()
        from concurrent.futures import Future
        req = _Request(section=section, valid=valid, bucket=bucket,
                       deadline=now + deadline_ms / 1e3, session=session,
                       future=Future(), t_submit=now,
                       t_submit_us=self.tracer.now_us(), tenant=tenant,
                       session_key=session_key, placement=placement)
        if placement.kind == "ring":
            self._ring_queue.put(req)
        else:
            self._replicas[placement.index].queue.put(req)
        self._metrics.inc("submitted")
        self._metrics.observe_placement(placement.key)
        self._metrics.observe_tenant(tenant, "submitted")
        # submit/close race: close() may have drained the queues between
        # our put and here — fail the request instead of hanging its caller
        if self._closed.is_set() and not any(
                t and t.is_alive()
                for t in [rep.thread for rep in self._replicas]
                + [self._ring_thread]):
            if not req.future.done():
                req.future.set_exception(EngineClosedError("engine closed"))
                self._finish(req, "shutdown")
            raise EngineClosedError("engine is closed")
        return req.future

    # -- drain ---------------------------------------------------------------
    def _replace_requests(self, reqs: List[_Request]) -> None:
        """Re-place drained-replica requests onto survivors (or the ring);
        when nowhere survives they fail with ShutdownError."""
        for req in reqs:
            placement = self.policy.place(req.valid[0], req.session_key,
                                          self._depths(),
                                          self._draining_flags())
            if placement is None:
                self._dec_queued()
                if not req.future.done():
                    req.future.set_exception(ShutdownError(
                        "replica drained with no surviving replica"))
                self._finish(req, "shutdown")
                continue
            req.placement = placement
            self._metrics.observe_placement(placement.key)
            if placement.kind == "ring":
                self._ring_queue.put(req)
            else:
                self._replicas[placement.index].queue.put(req)

    def drain_replica(self, index: int,
                      timeout: Optional[float] = None) -> None:
        """Retire one replica under load: new placements avoid it, its
        queued requests re-place onto survivors (session stickiness re-pins
        there too), its worker finishes the in-flight batch and exits."""
        rep = self._replicas[index]
        rep.draining.set()
        evicted = self.policy.evict_replica(index)
        self._replace_requests(rep.queue.drain_all())
        rep.queue.wake()
        t = rep.thread
        if t is not None:
            t.join(timeout if timeout is not None
                   else self.mesh_cfg.drain_timeout_s)
        # a submit racing the drain flag may have slipped one in after the
        # first drain_all; the worker is gone now, so sweep again
        self._replace_requests(rep.queue.drain_all())
        self.flight.record("replica_drain", replica=index,
                           sticky_evicted=evicted)
        log.info("replica %d drained (%d sticky sessions evicted)",
                 index, evicted)

    def drain_tenant(self, tenant: str,
                     timeout: Optional[float] = None) -> dict:
        """PR 7 drain semantics per tenant: new submits shed
        (:class:`TenantDrainingError`), queued requests fail with
        :class:`ShutdownError`, in-flight ones complete (bounded wait),
        then the tenant's sessions and record drop — one misbehaving
        tenant leaves without wedging the cohort.  Returns a summary."""
        self.tenants.start_drain(tenant)
        doomed: List[_Request] = []
        for rep in self._replicas:
            doomed.extend(rep.queue.take_tenant(tenant))
        if self._ring_queue is not None:
            doomed.extend(self._ring_queue.take_tenant(tenant))
        exc = ShutdownError(f"tenant {tenant!r} drained")
        for req in doomed:
            self._dec_queued()
            if not req.future.done():
                req.future.set_exception(exc)
            self._finish(req, "shutdown")
        idle = self.tenants.wait_idle(
            tenant, timeout if timeout is not None
            else self.mesh_cfg.drain_timeout_s)
        dropped = self.sessions.drop_tenant(tenant)
        self.tenants.finish_drain(tenant)
        self._metrics.observe_tenant(tenant, "drained")
        summary = {"tenant": tenant, "queued_failed": len(doomed),
                   "sessions_dropped": dropped, "idle": idle}
        self.flight.record("tenant_drain", **summary)
        log.info("tenant %r drained: %s", tenant, summary)
        return summary

    def quarantine_tenant(self, tenant: str) -> None:
        """Operator action: shed all of the tenant's submits until
        :meth:`release_tenant`."""
        self.tenants.quarantine(tenant)
        self._metrics.observe_tenant(tenant, "quarantined")

    def release_tenant(self, tenant: str) -> None:
        self.tenants.release_tenant(tenant)
        self._metrics.observe_tenant(tenant, "released")
