"""All-pairs xcorr compute factory: the mesh engine's SPMD route.

The large-geometry request class the ring placement exists for: an
``(n_ch, n_ch)`` peak-lag cross-correlation matrix over every channel
pair, quadratic in the channel count.  The factory serves it two ways:

- :meth:`build` — the single-device program
  (:func:`~das_diff_veh_tpu.ops.pallas_xcorr.xcorr_all_pairs_peak`),
  what replica placements and the plain :class:`ServingEngine` run;
- :meth:`build_placed` with ``placement.kind == "ring"`` — the
  channel-sharded ``shard_map`` ring
  (:func:`~das_diff_veh_tpu.parallel.allpairs.sharded_all_pairs_peak`):
  each device keeps its channel block resident and source blocks rotate
  by ``lax.ppermute``, so the full matrix never materializes per device.

On the kernel path (``use_pallas=True``; ``interpret=True`` on CPU) the
two programs are **bit-exact** — the ring computes the same FP ops in the
same order per pair, only on different devices (pinned by PR 4's
tests/test_parallel.py and re-pinned THROUGH the two engines by
tests/test_serve_mesh.py) — so routing a request to the ring is purely a
placement decision, never a numerics decision.

Per-pair independence also makes bucket padding safe for the trim: padded
rows only add rows/cols ≥ ``valid[0]`` to the matrix, which the compute fn
slices off; the surviving entries are computed from the untouched real
channels.  Zero-padded *time* samples do perturb a pair's correlation, so
(as everywhere in serving) buckets should tile the real ``nt`` — the
result carries ``padded`` for callers to tell.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import numpy as np

from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.serve.buckets import Bucket
from das_diff_veh_tpu.serve.compile_cache import ComputeFactory, ComputeFn


@dataclass
class AllPairsResult:
    """One served all-pairs request: the peak matrix + provenance."""

    peaks: np.ndarray                  # (valid_nch, valid_nch)
    valid: Tuple[int, int]
    bucket: Bucket
    placement: str                     # "single" | "ring"
    padded: bool


class AllPairsComputeFactory(ComputeFactory):
    """Builds per-bucket all-pairs peak programs, ring-capable.

    ``mesh`` is only required once a ring placement is warmed; replicas
    and the single-device engine never touch it.  ``use_pallas=True,
    interpret=True`` is the CPU-testable kernel path — the configuration
    under which single-device and ring programs are bit-exact.
    """

    def __init__(self, wlen: int, mesh=None, overlap_ratio: float = 0.5,
                 src_chunk: int = 64, use_pallas: Optional[bool] = None,
                 interpret: bool = False, ring: Optional[bool] = None):
        self.wlen = int(wlen)
        self.mesh = mesh
        self.overlap_ratio = float(overlap_ratio)
        self.src_chunk = int(src_chunk)
        self.use_pallas = use_pallas
        self.interpret = interpret
        self.ring = ring
        self.config_key = (
            f"allpairs:w{self.wlen}:o{self.overlap_ratio}:"
            f"c{self.src_chunk}:p{self.use_pallas}:i{self.interpret}")

    def _result(self, peaks, valid: Tuple[int, int], bucket: Bucket,
                placement: str) -> AllPairsResult:
        n = int(valid[0])
        return AllPairsResult(peaks=np.asarray(peaks)[:n, :n],
                              valid=tuple(valid), bucket=bucket,
                              placement=placement,
                              padded=tuple(valid) != tuple(bucket))

    def build(self, bucket: Bucket) -> ComputeFn:
        from das_diff_veh_tpu.ops.pallas_xcorr import xcorr_all_pairs_peak

        def compute(section: DasSection, valid: Tuple[int, int],
                    state: Any) -> Tuple[AllPairsResult, Any]:
            peaks = xcorr_all_pairs_peak(
                section.data, self.wlen, overlap_ratio=self.overlap_ratio,
                src_chunk=self.src_chunk, use_pallas=self.use_pallas,
                interpret=self.interpret)
            return self._result(peaks, valid, bucket, "single"), state

        return compute

    def build_placed(self, bucket: Bucket, placement) -> ComputeFn:
        if placement.kind != "ring":
            return self.build(bucket)
        if self.mesh is None:
            raise ValueError(
                "AllPairsComputeFactory needs a mesh to serve ring "
                "placements; pass mesh=parallel.mesh.make_mesh(...)")
        from das_diff_veh_tpu.parallel.allpairs import sharded_all_pairs_peak

        mesh = self.mesh

        def compute(section: DasSection, valid: Tuple[int, int],
                    state: Any) -> Tuple[AllPairsResult, Any]:
            peaks = sharded_all_pairs_peak(
                section.data, self.wlen, mesh,
                overlap_ratio=self.overlap_ratio, src_chunk=self.src_chunk,
                use_pallas=self.use_pallas, interpret=self.interpret,
                ring=self.ring)
            return self._result(peaks, valid, bucket, "ring"), state

        return compute
