"""Mesh-distributed multi-tenant serving (docs/SERVING.md).

``MeshServingEngine`` fans the continuous-batching dispatcher across the
device mesh: data-parallel replica workers for independent requests, the
channel-sharded ring for large geometries, per-tenant quotas / fair-share
/ drain on top.  See serve/mesh/engine.py for the architecture overview.
"""

from das_diff_veh_tpu.serve.mesh.allpairs import (AllPairsComputeFactory,
                                                  AllPairsResult)
from das_diff_veh_tpu.serve.mesh.engine import (DEFAULT_TENANT,
                                                MeshServingEngine,
                                                NoReplicaError)
from das_diff_veh_tpu.serve.mesh.placement import (RING, Placement,
                                                   PlacementPolicy)
from das_diff_veh_tpu.serve.mesh.tenancy import (FairQueue, TenantDrainingError,
                                                 TenantQuarantinedError,
                                                 TenantQuotaError, TenantTable)

__all__ = [
    "MeshServingEngine", "NoReplicaError", "DEFAULT_TENANT",
    "Placement", "RING", "PlacementPolicy",
    "TenantTable", "FairQueue",
    "TenantQuotaError", "TenantQuarantinedError", "TenantDrainingError",
    "AllPairsComputeFactory", "AllPairsResult",
]
