"""Multi-tenancy: admission quotas, fair-share queues, quarantine, drain.

One mesh engine serves many tenants (fleet monitoring: each roadside fiber
operator is a tenant submitting its own sessions).  Tenancy is three
mechanisms, each shedding with its own error so the HTTP front can map
them to distinct status codes:

- **quota** (:class:`TenantTable.admit`) — a tenant may hold at most
  ``quota`` queued + in-flight requests; the next submit sheds with
  :class:`TenantQuotaError` (HTTP 429).  One tenant saturates at most its
  quota, never the engine;
- **quarantine** — ``poison_after`` consecutive poison sheds (the
  admission health screen, PR 7) auto-quarantines the tenant: further
  submits shed with :class:`TenantQuarantinedError` until
  ``release_tenant``.  A healthy admission resets the streak;
- **drain** — ``drain_tenant`` marks the tenant draining
  (:class:`TenantDrainingError` for new submits), fails its queued
  requests with ``ShutdownError`` (PR 7 semantics), waits out its
  in-flight ones, then drops its sessions and record.

:class:`FairQueue` is the per-worker scheduling structure: per-tenant FIFO
subqueues drained least-recently-served-tenant first (round-robin over
active tenants by a monotonic pick sequence).  Per-tenant order is never
reordered — the continuous-batch poll (:meth:`FairQueue.poll_bucket`)
considers only each tenant's HEAD request, so session state still updates
in submission order — but ACROSS tenants a flood from one tenant cannot
starve another's next request behind its backlog.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from das_diff_veh_tpu.serve.engine import ShedError


class TenantQuotaError(ShedError):
    """The tenant's queued + in-flight requests are at its quota."""

    http_status = 429                  # per-tenant backpressure


class TenantQuarantinedError(ShedError):
    """The tenant is quarantined (poison streak or operator action); all
    its submits shed until ``release_tenant``."""

    http_status = 429


class TenantDrainingError(ShedError):
    """The tenant is being drained; new submits shed until the drain
    completes."""

    http_status = 429


@dataclass
class TenantState:
    admitted: int = 0          # queued + in-flight right now (quota charge)
    submitted: int = 0         # lifetime admissions
    poison_streak: int = 0     # consecutive poison sheds
    draining: bool = False
    quarantined: bool = False


class TenantTable:
    """Thread-safe per-tenant admission state (quota / quarantine / drain).

    ``release`` must be called exactly once per admitted request on its
    terminal outcome — the engine's ``_finish`` hook does, from every path
    that resolves a future.
    """

    def __init__(self, quota: int, poison_after: Optional[int] = None):
        self.quota = int(quota)
        self.poison_after = poison_after
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tenants: Dict[str, TenantState] = {}

    def _state(self, tenant: str) -> TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            st = self._tenants[tenant] = TenantState()
        return st

    def gate(self, tenant: str) -> None:
        """The pre-validation shed gate: quarantined and draining tenants
        are rejected before the engine spends validation/health work on
        their payload."""
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                return
            if st.quarantined:
                raise TenantQuarantinedError(
                    f"tenant {tenant!r} is quarantined "
                    f"(poison streak {st.poison_streak}); "
                    "release_tenant() to readmit")
            if st.draining:
                raise TenantDrainingError(f"tenant {tenant!r} is draining")

    def admit(self, tenant: str) -> None:
        """Charge one quota slot or shed with :class:`TenantQuotaError`."""
        with self._lock:
            st = self._state(tenant)
            if st.admitted >= self.quota:
                raise TenantQuotaError(
                    f"tenant {tenant!r} at quota "
                    f"({st.admitted}/{self.quota} queued + in-flight)")
            st.admitted += 1
            st.submitted += 1

    def release(self, tenant: str) -> None:
        """Return one quota slot (terminal request outcome)."""
        with self._cond:
            st = self._tenants.get(tenant)
            if st is not None and st.admitted > 0:
                st.admitted -= 1
                if st.admitted == 0:
                    self._cond.notify_all()

    def note_poison(self, tenant: str) -> bool:
        """Record one poison shed; returns True when this crossed the
        quarantine threshold."""
        with self._lock:
            st = self._state(tenant)
            st.poison_streak += 1
            if (self.poison_after is not None and not st.quarantined
                    and st.poison_streak >= self.poison_after):
                st.quarantined = True
                return True
            return False

    def note_healthy(self, tenant: str) -> None:
        with self._lock:
            st = self._tenants.get(tenant)
            if st is not None:
                st.poison_streak = 0

    def quarantine(self, tenant: str) -> None:
        with self._lock:
            self._state(tenant).quarantined = True

    def release_tenant(self, tenant: str) -> None:
        """Operator override: lift quarantine and reset the streak."""
        with self._lock:
            st = self._tenants.get(tenant)
            if st is not None:
                st.quarantined = False
                st.poison_streak = 0

    def start_drain(self, tenant: str) -> None:
        with self._lock:
            self._state(tenant).draining = True

    def finish_drain(self, tenant: str) -> None:
        """Drop the tenant's record entirely: a later submit re-admits it
        as a fresh tenant."""
        with self._lock:
            self._tenants.pop(tenant, None)

    def wait_idle(self, tenant: str, timeout: float) -> bool:
        """Block until the tenant holds zero quota slots (queued requests
        were failed by the drain; this waits out the in-flight tail).
        Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                st = self._tenants.get(tenant)
                if st is None or st.admitted == 0:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)

    def inflight(self, tenant: str) -> int:
        with self._lock:
            st = self._tenants.get(tenant)
            return 0 if st is None else st.admitted

    def snapshot(self) -> dict:
        with self._lock:
            return {
                t: {"admitted": st.admitted, "submitted": st.submitted,
                    "poison_streak": st.poison_streak,
                    "draining": st.draining, "quarantined": st.quarantined}
                for t, st in sorted(self._tenants.items())}


@dataclass
class _SubQueue:
    q: deque = field(default_factory=deque)
    last_pick: int = -1                # monotonic round-robin position


class FairQueue:
    """Per-tenant FIFO subqueues, drained fair-share across tenants.

    The pick rule is round-robin by least-recently-served tenant: each
    pop stamps the tenant with a monotonically increasing sequence number
    and the next pop takes the non-empty tenant with the OLDEST stamp —
    so N active tenants each get every Nth slot regardless of backlog
    sizes, and a new tenant's first request waits at most one rotation.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._sub: Dict[str, _SubQueue] = {}
        self._seq = 0
        self._n = 0

    def put(self, req) -> None:
        tenant = req.tenant if req.tenant is not None else ""
        with self._cond:
            sub = self._sub.get(tenant)
            if sub is None:
                sub = self._sub[tenant] = _SubQueue()
            sub.q.append(req)
            self._n += 1
            self._cond.notify()

    def _pick_locked(self, eligible: List[str]):
        tenant = min(eligible, key=lambda t: (self._sub[t].last_pick, t))
        sub = self._sub[tenant]
        req = sub.q.popleft()
        self._seq += 1
        sub.last_pick = self._seq
        self._n -= 1
        return req

    def get(self, timeout: float):
        """Fair-order head pop, blocking up to ``timeout``; None when
        nothing arrived."""
        with self._cond:
            if self._n == 0:
                self._cond.wait(timeout)
            if self._n == 0:
                return None
            return self._pick_locked([t for t, s in self._sub.items()
                                      if s.q])

    def poll_bucket(self, bucket):
        """Continuous-batch companion poll: the fair-order next request
        among tenants whose HEAD request matches ``bucket`` (heads only —
        per-tenant FIFO and therefore per-session execution order is
        preserved), or None without waiting."""
        with self._cond:
            eligible = [t for t, s in self._sub.items()
                        if s.q and s.q[0].bucket == bucket]
            if not eligible:
                return None
            return self._pick_locked(eligible)

    def take_tenant(self, tenant: str) -> list:
        """Remove and return every queued request of ``tenant`` (drain)."""
        with self._cond:
            sub = self._sub.pop(tenant, None)
            if sub is None:
                return []
            self._n -= len(sub.q)
            return list(sub.q)

    def drain_all(self) -> list:
        """Remove and return everything, fair order not preserved."""
        with self._cond:
            out = []
            for sub in self._sub.values():
                out.extend(sub.q)
            self._sub.clear()
            self._n = 0
            return out

    def qsize(self) -> int:
        with self._lock:
            return self._n

    def wake(self) -> None:
        """Nudge a blocked ``get`` (drain/close paths)."""
        with self._cond:
            self._cond.notify_all()
