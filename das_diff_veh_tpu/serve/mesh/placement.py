"""Placement scheduling: which worker runs an admitted request.

The mesh engine owns two kinds of execution targets:

- **replica workers** — one per device, each draining its own fair queue
  through the single-device program under ``jax.default_device``; the
  scaling unit for independent requests (data parallelism across the
  request stream, not inside a program);
- **the ring worker** — one thread dispatching the channel-sharded
  ``shard_map`` program (``parallel.allpairs``) across the WHOLE mesh; the
  route for large-geometry requests whose per-device memory or latency a
  single replica cannot hold.

:class:`PlacementPolicy` decides at admission time, in strict priority
order:

1. **ring** — the request's valid channel count reaches
   ``ring_min_channels`` (None disables the route);
2. **sticky replica** — a session's requests pin to one replica so
   per-session state updates keep their execution order (session state is
   threaded through the compute chain; two replicas interleaving one
   session would race it).  Stickiness survives until the replica drains;
3. **least-loaded replica** — smallest queue depth among non-draining
   replicas (ties to the lowest index, keeping the decision
   deterministic for the counter assertions in tests).

Every decision is counted per target in
``das_serve_placements_total{placement=...}`` by the engine, so scheduler
behavior is asserted from counters, not log prose.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass(frozen=True)
class Placement:
    """One execution target: a replica index or the ring."""

    kind: str                          # "replica" | "ring"
    index: int = 0                     # replica index; the ring uses 0

    @property
    def key(self) -> str:
        """Stable string form — the compile-cache key part and the
        ``placement`` label value."""
        return f"{self.kind}:{self.index}"


RING = Placement("ring", 0)


class PlacementPolicy:
    """Admission-time placement with session stickiness (thread-safe:
    ``place`` runs on arbitrary submit threads)."""

    def __init__(self, n_replicas: int,
                 ring_min_channels: Optional[int] = None):
        self.n_replicas = int(n_replicas)
        self.ring_min_channels = ring_min_channels
        self._lock = threading.Lock()
        self._sticky: Dict[str, int] = {}

    def place(self, valid_nch: int, session_key: Optional[str],
              depths: List[int],
              draining: List[bool]) -> Optional[Placement]:
        """The target for a request with ``valid_nch`` true channels, or
        None when every replica is draining (the engine sheds).  ``depths``
        and ``draining`` are the engine's per-replica queue-depth and
        drain-flag snapshots."""
        if (self.ring_min_channels is not None
                and valid_nch >= self.ring_min_channels):
            return RING
        with self._lock:
            if session_key is not None:
                idx = self._sticky.get(session_key)
                if idx is not None and not draining[idx]:
                    return Placement("replica", idx)
            alive = [i for i in range(self.n_replicas) if not draining[i]]
            if not alive:
                return None
            idx = min(alive, key=lambda i: (depths[i], i))
            if session_key is not None:
                self._sticky[session_key] = idx
            return Placement("replica", idx)

    def evict_replica(self, index: int) -> int:
        """Forget stickiness onto a draining replica: its sessions re-pin
        to a surviving replica on their next request.  Returns how many
        sessions were evicted."""
        with self._lock:
            doomed = [k for k, v in self._sticky.items() if v == index]
            for k in doomed:
                del self._sticky[k]
            return len(doomed)

    def sticky_replica(self, session_key: str) -> Optional[int]:
        with self._lock:
            return self._sticky.get(session_key)
