"""Streaming session state: consecutive segments of one fiber share state.

A DAS interrogator produces an endless record; online callers submit it as
consecutive time segments.  ``SessionStore`` keeps an opaque per-session
value that the engine threads through the compute function — segment k's
compute receives the state segment k-1 returned (the imaging compute uses
it to carry the running dispersion-image accumulator and vehicle count, so
a session behaves like the batch workflow's per-date accumulator).

All state updates happen on the single dispatcher thread in execution
order, so no per-session locking is needed beyond the store's own map lock.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class SessionStore:
    """Thread-safe map of session id -> opaque compute state."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state: Dict[str, Any] = {}

    def get(self, session: Optional[str]) -> Any:
        if session is None:
            return None
        with self._lock:
            return self._state.get(session)

    def put(self, session: Optional[str], state: Any) -> None:
        if session is None:
            return
        with self._lock:
            if state is None:
                self._state.pop(session, None)
            else:
                self._state[session] = state

    def drop(self, session: str) -> None:
        with self._lock:
            self._state.pop(session, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._state)
