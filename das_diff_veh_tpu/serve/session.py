"""Streaming session state: consecutive segments of one fiber share state.

A DAS interrogator produces an endless record; online callers submit it as
consecutive time segments.  ``SessionStore`` keeps an opaque per-session
value that the engine threads through the compute function — segment k's
compute receives the state segment k-1 returned (the imaging compute uses
it to carry the running dispersion-image accumulator and vehicle count, so
a session behaves like the batch workflow's per-date accumulator).

All state updates for one session happen on one worker thread in execution
order (the single dispatcher, or — mesh engine — the session's sticky
replica), so no per-session locking is needed beyond the store's own map
lock.

Multi-tenant serving (``serve.mesh``) namespaces sessions per tenant: the
store key is :meth:`SessionStore.scoped`'s ``"tenant::session"`` string, so
two tenants naming a session ``"fiber-7"`` never share state, and a tenant
drain can drop exactly its own sessions (:meth:`drop_tenant`).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional


class SessionStore:
    """Thread-safe map of session id -> opaque compute state."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state: Dict[str, Any] = {}

    @staticmethod
    def scoped(tenant: Optional[str], session: Optional[str]) -> Optional[str]:
        """The store key for ``session`` under ``tenant`` (None tenant =
        the single-tenant engine's bare key)."""
        if session is None:
            return None
        if tenant is None:
            return session
        return f"{tenant}::{session}"

    def sessions_for(self, tenant: str) -> List[str]:
        """Store keys belonging to ``tenant`` (scoped-key prefix match)."""
        prefix = f"{tenant}::"
        with self._lock:
            return [k for k in self._state if k.startswith(prefix)]

    def drop_tenant(self, tenant: str) -> int:
        """Drop every session of ``tenant``; returns how many were held."""
        prefix = f"{tenant}::"
        with self._lock:
            doomed = [k for k in self._state if k.startswith(prefix)]
            for k in doomed:
                del self._state[k]
            return len(doomed)

    def get(self, session: Optional[str]) -> Any:
        if session is None:
            return None
        with self._lock:
            return self._state.get(session)

    def put(self, session: Optional[str], state: Any) -> None:
        if session is None:
            return
        with self._lock:
            if state is None:
                self._state.pop(session, None)
            else:
                self._state[session] = state

    def drop(self, session: str) -> None:
        with self._lock:
            self._state.pop(session, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._state)
