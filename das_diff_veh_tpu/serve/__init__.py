"""Online serving engine: microbatched, shape-bucketed, deadline-aware.

The batch side of this repo (``runtime``/``pipeline.workflow``) walks
directories of dates; this package is the other entry point the ROADMAP's
"serves heavy traffic" north star needs — hand the system ONE DAS segment,
get a dispersion image back with bounded latency.  Seven concerns, one
module each:

- :mod:`buckets` — pad ``(n_ch, nt)`` onto a small configurable shape set;
- :mod:`compile_cache` — compiled programs keyed ``(bucket, config_hash)``,
  AOT-warmable so steady-state requests never pay a trace;
- :mod:`engine` — bounded admission queue, deadline shedding, a dispatcher
  thread forming same-bucket microbatches, per-request span accounting;
- :mod:`metrics` — p50/p95/p99 latency, queue depth, occupancy, shed and
  cache counters as one snapshot dict;
- :mod:`session` — streaming per-fiber state across consecutive segments;
- :mod:`imaging` — the production ``process_chunk`` compute factory;
- :mod:`http` / :mod:`cli` — stdlib JSON endpoint + ``serve`` subcommand;
- :mod:`mesh` — the mesh-distributed multi-tenant engine (data-parallel
  replica workers + the channel-sharded ring + tenant quotas/fair-share/
  drain; docs/SERVING.md).
"""

from das_diff_veh_tpu.config import ServeConfig
from das_diff_veh_tpu.serve.buckets import (normalize_buckets, pad_section,
                                            pick_bucket, unpad)
from das_diff_veh_tpu.serve.compile_cache import (CompiledFunctionCache,
                                                  ComputeFactory,
                                                  FnComputeFactory)
from das_diff_veh_tpu.serve.engine import (DeadlineExceededError,
                                           EngineClosedError,
                                           InvalidRequestError, NoBucketError,
                                           PoisonInputError, QueueFullError,
                                           ServingEngine, ShedError,
                                           ShutdownError)
from das_diff_veh_tpu.serve.http import make_server, serve_in_thread
from das_diff_veh_tpu.serve.imaging import ImagingComputeFactory, ImagingResult
from das_diff_veh_tpu.serve.metrics import ServeMetrics
from das_diff_veh_tpu.serve.session import SessionStore

__all__ = [
    "ServeConfig", "ServingEngine", "ComputeFactory", "FnComputeFactory",
    "CompiledFunctionCache", "ImagingComputeFactory", "ImagingResult",
    "ServeMetrics", "SessionStore", "ShedError", "QueueFullError",
    "DeadlineExceededError", "NoBucketError", "InvalidRequestError",
    "PoisonInputError", "EngineClosedError", "ShutdownError",
    "normalize_buckets", "pick_bucket", "pad_section", "unpad",
    "make_server", "serve_in_thread",
    "mesh", "MeshServingEngine",
]

# imported LAST: serve.mesh pulls serve.engine/compile_cache back in, so it
# must only load once this package namespace is fully populated
from das_diff_veh_tpu.serve import mesh  # noqa: E402
from das_diff_veh_tpu.serve.mesh import MeshServingEngine  # noqa: E402
