"""Shape buckets: pad a request's ``(n_ch, nt)`` onto a small fixed set.

``process_chunk`` traces and compiles per input shape (~40 s/call on CPU
for a fresh shape), so an online engine that forwarded raw request shapes
would pay a compile on every novel ``(n_ch, nt)``.  Instead every admitted
request is zero-padded up to the smallest configured bucket that fits it;
the compiled-function cache is keyed on the bucket, and the set of programs
the engine can ever run is fixed (and warmable) at startup.

Padding is pure host-side NumPy: data gets trailing zeros, the ``x`` and
``t`` axes are extended by continuing their own spacing (so ``dx``/``dt``
derived by downstream code is unchanged).  ``valid`` — the request's true
extents — travels with the padded section; compute functions use it to
mask or slice so the engine round trip (pad -> compute -> unpad) is exactly
the unpadded computation (asserted in tests/test_serve.py).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from das_diff_veh_tpu.core.section import DasSection

Bucket = Tuple[int, int]            # (n_ch, nt), the padded shape


def normalize_buckets(buckets: Sequence[Sequence[int]]) -> Tuple[Bucket, ...]:
    """Validate + sort buckets smallest-area-first (the selection order)."""
    out = []
    for b in buckets:
        n_ch, nt = int(b[0]), int(b[1])
        if n_ch <= 0 or nt <= 0:
            raise ValueError(f"bucket shape must be positive, got {(n_ch, nt)}")
        out.append((n_ch, nt))
    out.sort(key=lambda b: (b[0] * b[1], b))
    return tuple(out)


def pick_bucket(shape: Tuple[int, int],
                buckets: Sequence[Bucket]) -> Optional[Bucket]:
    """Smallest-area bucket that fits ``shape`` in both dims; None if none
    does (the engine rejects such requests at submit).  One O(n) scan, no
    normalization — this sits on the submit hot path; validation happens
    once at engine construction via :func:`normalize_buckets`."""
    n_ch, nt = shape
    best = None
    for b in buckets:
        bc, bn = int(b[0]), int(b[1])
        if bc >= n_ch and bn >= nt:
            key = (bc * bn, (bc, bn))
            if best is None or key < best[0]:
                best = (key, (bc, bn))
    return best[1] if best is not None else None


def pad_section(section: DasSection, bucket: Bucket) -> DasSection:
    """Zero-pad ``section`` up to ``bucket``, extending axes by their own
    spacing.  A section already at the bucket shape is returned untouched
    (same arrays — the exact-shape fast path pads nothing)."""
    data = np.asarray(section.data)
    n_ch, nt = data.shape
    b_ch, b_nt = bucket
    if n_ch > b_ch or nt > b_nt:
        raise ValueError(f"section {data.shape} does not fit bucket {bucket}")
    if (n_ch, nt) == (b_ch, b_nt):
        return section
    x = np.asarray(section.x)
    t = np.asarray(section.t)
    padded = np.zeros((b_ch, b_nt), dtype=data.dtype)
    padded[:n_ch, :nt] = data
    return DasSection(padded, _extend_axis(x, b_ch), _extend_axis(t, b_nt))


def unpad(array: np.ndarray, valid: Tuple[int, int]) -> np.ndarray:
    """Slice a bucket-shaped per-sample array back to the request's true
    extents (identity for outputs whose shape does not follow the input,
    e.g. the fixed-grid dispersion image — callers only unpad arrays whose
    leading dims match the bucket)."""
    return np.asarray(array)[:valid[0], :valid[1]]


def _extend_axis(axis: np.ndarray, n: int) -> np.ndarray:
    if axis.size >= n:
        return axis
    step = float(axis[1] - axis[0]) if axis.size > 1 else 1.0
    extra = axis[-1] + step * np.arange(1, n - axis.size + 1, dtype=axis.dtype)
    return np.concatenate([axis, extra.astype(axis.dtype)])
