"""Serving metrics as a thin view over the shared obs registry.

Historically this module owned its own counter dict and latency deques; it
is now a facade over :class:`das_diff_veh_tpu.obs.MetricsRegistry` — the
same families the serve HTTP front exposes as Prometheus text on
``GET /metrics`` back the legacy JSON ``snapshot()`` served on
``/v1/metrics``, so the two surfaces can never disagree.  Registered
families (``das_serve_*``):

- ``das_serve_events_total{event=...}`` — the legacy counter set
  (submitted/completed/errors/shed_*/cache_*/warmup_builds);
- ``das_serve_latency_ms`` — total-latency ring (p50/p95/p99);
- ``das_serve_stage_ms{stage=...}`` — per-stage rings.  Stages now report
  the same percentile set as totals (they used to report only means; the
  mean is kept in the snapshot for continuity);
- ``das_serve_batches_total`` / ``das_serve_batched_requests_total`` /
  ``das_serve_batch_max_occupancy`` — microbatch accounting;
- ``das_serve_queue_depth`` — live depth via a collect-time callback.

Each engine defaults to its OWN registry (tests and embedded engines stay
isolated); the serve CLI passes ``obs.default_registry()`` so runtime and
parallel metrics ride the same scrape — the "one registry" contract.
"""

from __future__ import annotations

from typing import Dict, Optional

from das_diff_veh_tpu.obs.registry import MetricsRegistry, percentile

# bench.py and tests import the historical name
_percentile = percentile


class ServeMetrics:
    """Counters + bounded latency reservoirs for one serving engine."""

    _STAGES = ("queue", "pad", "compute", "unpad")
    _COUNTS = ("submitted", "completed", "errors",
               "shed_rejected", "shed_expired", "shed_no_bucket",
               "shed_invalid", "shed_poison",
               "cache_hits", "cache_misses", "warmup_builds")

    def __init__(self, latency_window: int = 1024,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self._window = latency_window
        self._events = self.registry.counter(
            "das_serve_events_total", "serving engine events by type",
            labels=("event",))
        for name in self._COUNTS:       # pre-touch: stable snapshot/scrape
            self._events.labels(event=name)
        self._latency = self.registry.histogram(
            "das_serve_latency_ms", "total request latency [ms]",
            window=latency_window)
        self._stage = self.registry.histogram(
            "das_serve_stage_ms", "per-stage request latency [ms]",
            labels=("stage",), window=latency_window)
        for s in self._STAGES:
            self._stage.labels(stage=s)
        self._batches = self.registry.counter(
            "das_serve_batches_total", "microbatches executed")
        self._batched = self.registry.counter(
            "das_serve_batched_requests_total", "requests executed in batches")
        self._max_occ = self.registry.gauge(
            "das_serve_batch_max_occupancy", "largest microbatch so far")
        self._depth = self.registry.gauge(
            "das_serve_queue_depth", "requests waiting (queue + stash)")

    # -- write side (engine threads) -----------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        self._events.labels(event=name).inc(by)

    def observe_batch(self, occupancy: int) -> None:
        self._batches.inc()
        self._batched.inc(occupancy)
        if occupancy > self._max_occ.value:
            self._max_occ.set(occupancy)

    def observe_request(self, total_ms: float,
                        stages_ms: Optional[Dict[str, float]] = None) -> None:
        self._events.labels(event="completed").inc()
        self._latency.observe(total_ms)
        for name, v in (stages_ms or {}).items():
            self._stage.labels(stage=name).observe(v)

    def bind_queue_depth(self, fn) -> None:
        """Register a zero-arg callable reporting the live queue depth."""
        self._depth.set_fn(fn)

    # -- read side -----------------------------------------------------------
    def count(self, name: str) -> int:
        return int(self._events.labels(event=name).value)

    def _stage_snapshot(self, child) -> dict:
        vals = child.values()
        return {
            "n": len(vals),
            "mean": round(sum(vals) / len(vals), 3) if vals else 0.0,
            "p50": round(percentile(vals, 0.50), 3),
            "p95": round(percentile(vals, 0.95), 3),
            "p99": round(percentile(vals, 0.99), 3),
        }

    def snapshot(self) -> dict:
        lat = self._latency.values()
        batches = int(self._batches.value)
        snap = {
            **{event: int(child.value)
               for (event,), child in self._events.children()},
            "queue_depth": int(self._depth.value),
            "latency_ms": {
                "n": len(lat),
                "p50": round(percentile(lat, 0.50), 3),
                "p95": round(percentile(lat, 0.95), 3),
                "p99": round(percentile(lat, 0.99), 3),
                "max": round(lat[-1], 3) if lat else 0.0,
            },
            "stages_ms": {
                stage: self._stage_snapshot(child)
                for (stage,), child in self._stage.children()
            },
            "batch": {
                "count": batches,
                "mean_occupancy": round(
                    self._batched.value / batches, 3) if batches else 0.0,
                "max_occupancy": int(self._max_occ.value),
            },
        }
        return snap
