"""Thread-safe serving metrics: counters, latency percentiles, occupancy.

One registry per engine.  Counters are plain monotonic ints; completed
request latencies (and their per-stage spans) go into bounded rings so the
snapshot's p50/p95/p99 reflect recent traffic without unbounded memory.
``snapshot()`` returns one JSON-ready dict — the engine's metrics API and
the HTTP ``/metrics`` endpoint both serve it verbatim.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile of an already-sorted sequence."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return float(sorted_vals[idx])


class ServeMetrics:
    """Counters + bounded latency reservoirs for one serving engine."""

    _STAGES = ("queue", "pad", "compute", "unpad")

    def __init__(self, latency_window: int = 1024):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {
            "submitted": 0, "completed": 0, "errors": 0,
            "shed_rejected": 0, "shed_expired": 0, "shed_no_bucket": 0,
            "shed_invalid": 0,
            "cache_hits": 0, "cache_misses": 0, "warmup_builds": 0,
        }
        self._latency = deque(maxlen=latency_window)       # total ms
        self._stage = {s: deque(maxlen=latency_window) for s in self._STAGES}
        self._batches = 0
        self._batched_requests = 0
        self._max_occupancy = 0
        self._queue_depth_fn = None

    # -- write side (engine threads) -----------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + by

    def observe_batch(self, occupancy: int) -> None:
        with self._lock:
            self._batches += 1
            self._batched_requests += occupancy
            self._max_occupancy = max(self._max_occupancy, occupancy)

    def observe_request(self, total_ms: float,
                        stages_ms: Optional[Dict[str, float]] = None) -> None:
        with self._lock:
            self._counts["completed"] += 1
            self._latency.append(total_ms)
            for name, v in (stages_ms or {}).items():
                self._stage.setdefault(
                    name, deque(maxlen=self._latency.maxlen)).append(v)

    def bind_queue_depth(self, fn) -> None:
        """Register a zero-arg callable reporting the live queue depth."""
        self._queue_depth_fn = fn

    # -- read side -----------------------------------------------------------
    def count(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._latency)
            snap = {
                **self._counts,
                "queue_depth": self._queue_depth_fn() if self._queue_depth_fn else 0,
                "latency_ms": {
                    "n": len(lat),
                    "p50": round(_percentile(lat, 0.50), 3),
                    "p95": round(_percentile(lat, 0.95), 3),
                    "p99": round(_percentile(lat, 0.99), 3),
                    "max": round(lat[-1], 3) if lat else 0.0,
                },
                "stages_ms": {
                    name: round(sum(ring) / len(ring), 3) if ring else 0.0
                    for name, ring in self._stage.items()
                },
                "batch": {
                    "count": self._batches,
                    "mean_occupancy": round(
                        self._batched_requests / self._batches, 3)
                        if self._batches else 0.0,
                    "max_occupancy": self._max_occupancy,
                },
            }
        return snap
