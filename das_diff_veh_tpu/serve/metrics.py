"""Serving metrics as a thin view over the shared obs registry.

Historically this module owned its own counter dict and latency deques; it
is now a facade over :class:`das_diff_veh_tpu.obs.MetricsRegistry` — the
same families the serve HTTP front exposes as Prometheus text on
``GET /metrics`` back the legacy JSON ``snapshot()`` served on
``/v1/metrics``, so the two surfaces can never disagree.  Registered
families (``das_serve_*``):

- ``das_serve_events_total{event=...}`` — the legacy counter set
  (submitted/completed/errors/shed_*/cache_*/warmup_builds);
- ``das_serve_latency_ms`` — total-latency ring (p50/p95/p99);
- ``das_serve_stage_ms{stage=...}`` — per-stage rings.  Stages now report
  the same percentile set as totals (they used to report only means; the
  mean is kept in the snapshot for continuity);
- ``das_serve_batches_total`` / ``das_serve_batched_requests_total`` /
  ``das_serve_batch_max_occupancy`` — continuous-batch accounting (the
  ``continuous_admitted`` event counts members admitted into an already
  executing batch slot);
- ``das_serve_queue_depth`` — live depth via a collect-time callback.

The mesh engine (``serve.mesh``) additionally registers — via
:meth:`ServeMetrics.enable_mesh` — the placement/tenancy families its
scheduler is counter-asserted on:

- ``das_serve_placements_total{placement=...}`` — placement decisions
  (``replica:N`` / ``ring:0``);
- ``das_serve_replica_requests_total{replica=...}`` /
  ``das_serve_replica_queue_depth{replica=...}`` /
  ``das_serve_replica_busy{replica=...}`` — per-replica occupancy;
- ``das_serve_tenant_events_total{tenant=..., event=...}`` /
  ``das_serve_tenant_latency_ms{tenant=...}`` — per-tenant outcomes and
  latency histograms.

All of them live in the engine's ONE registry, so the Prometheus scrape
(``GET /metrics``) and the JSON ``/v1/metrics`` snapshot expose the mesh
views without a second endpoint.

Each engine defaults to its OWN registry (tests and embedded engines stay
isolated); the serve CLI passes ``obs.default_registry()`` so runtime and
parallel metrics ride the same scrape — the "one registry" contract.
"""

from __future__ import annotations

from typing import Dict, Optional

from das_diff_veh_tpu.obs.registry import MetricsRegistry, percentile

# bench.py and tests import the historical name
_percentile = percentile


class ServeMetrics:
    """Counters + bounded latency reservoirs for one serving engine."""

    _STAGES = ("queue", "pad", "compute", "unpad")
    _COUNTS = ("submitted", "completed", "errors",
               "shed_rejected", "shed_expired", "shed_no_bucket",
               "shed_invalid", "shed_poison",
               "shed_quota", "shed_quarantined", "shed_draining",
               "continuous_admitted",
               "cache_hits", "cache_misses", "warmup_builds",
               "tuned_warmups")

    def __init__(self, latency_window: int = 1024,
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self._window = latency_window
        self._events = self.registry.counter(
            "das_serve_events_total", "serving engine events by type",
            labels=("event",))
        for name in self._COUNTS:       # pre-touch: stable snapshot/scrape
            self._events.labels(event=name)
        self._latency = self.registry.histogram(
            "das_serve_latency_ms", "total request latency [ms]",
            window=latency_window)
        self._stage = self.registry.histogram(
            "das_serve_stage_ms", "per-stage request latency [ms]",
            labels=("stage",), window=latency_window)
        for s in self._STAGES:
            self._stage.labels(stage=s)
        self._batches = self.registry.counter(
            "das_serve_batches_total", "microbatches executed")
        self._batched = self.registry.counter(
            "das_serve_batched_requests_total", "requests executed in batches")
        self._max_occ = self.registry.gauge(
            "das_serve_batch_max_occupancy", "largest microbatch so far")
        self._depth = self.registry.gauge(
            "das_serve_queue_depth", "requests waiting (queue + stash)")
        self._mesh = False
        self._placements = None
        self._replica_reqs = None
        self._replica_depth = None
        self._replica_busy = None
        self._tenant_events = None
        self._tenant_latency = None

    # -- mesh views (serve.mesh engine only) ---------------------------------
    def enable_mesh(self, n_replicas: int) -> None:
        """Register the placement/tenancy families the mesh engine is
        counter-asserted on; per-replica children are pre-touched so the
        scrape shape is stable from the first request."""
        self._mesh = True
        self._placements = self.registry.counter(
            "das_serve_placements_total", "placement decisions by target",
            labels=("placement",))
        self._replica_reqs = self.registry.counter(
            "das_serve_replica_requests_total",
            "requests executed per replica", labels=("replica",))
        self._replica_depth = self.registry.gauge(
            "das_serve_replica_queue_depth",
            "requests waiting per replica queue", labels=("replica",))
        self._replica_busy = self.registry.gauge(
            "das_serve_replica_busy",
            "1 while the replica's worker is executing a batch",
            labels=("replica",))
        self._tenant_events = self.registry.counter(
            "das_serve_tenant_events_total",
            "per-tenant serving outcomes", labels=("tenant", "event"))
        self._tenant_latency = self.registry.histogram(
            "das_serve_tenant_latency_ms",
            "per-tenant total request latency [ms]", labels=("tenant",),
            window=self._window)
        for i in range(n_replicas):
            self._replica_reqs.labels(replica=str(i))
            self._replica_busy.labels(replica=str(i))

    def observe_placement(self, placement_key: str) -> None:
        self._placements.labels(placement=placement_key).inc()

    def observe_replica_request(self, replica: int) -> None:
        self._replica_reqs.labels(replica=str(replica)).inc()

    def bind_replica_depth(self, replica: int, fn) -> None:
        self._replica_depth.labels(replica=str(replica)).set_fn(fn)

    def set_replica_busy(self, replica: int, busy: bool) -> None:
        self._replica_busy.labels(replica=str(replica)).set(1 if busy else 0)

    def observe_tenant(self, tenant: str, event: str) -> None:
        self._tenant_events.labels(tenant=tenant, event=event).inc()

    def observe_tenant_latency(self, tenant: str, total_ms: float) -> None:
        self._tenant_latency.labels(tenant=tenant).observe(total_ms)

    # -- write side (engine threads) -----------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        self._events.labels(event=name).inc(by)

    def observe_batch(self, occupancy: int) -> None:
        self._batches.inc()
        self._batched.inc(occupancy)
        if occupancy > self._max_occ.value:
            self._max_occ.set(occupancy)

    def observe_request(self, total_ms: float,
                        stages_ms: Optional[Dict[str, float]] = None) -> None:
        self._events.labels(event="completed").inc()
        self._latency.observe(total_ms)
        for name, v in (stages_ms or {}).items():
            self._stage.labels(stage=name).observe(v)

    def bind_queue_depth(self, fn) -> None:
        """Register a zero-arg callable reporting the live queue depth."""
        self._depth.set_fn(fn)

    # -- read side -----------------------------------------------------------
    def count(self, name: str) -> int:
        return int(self._events.labels(event=name).value)

    def _stage_snapshot(self, child) -> dict:
        vals = child.values()
        return {
            "n": len(vals),
            "mean": round(sum(vals) / len(vals), 3) if vals else 0.0,
            "p50": round(percentile(vals, 0.50), 3),
            "p95": round(percentile(vals, 0.95), 3),
            "p99": round(percentile(vals, 0.99), 3),
        }

    def snapshot(self) -> dict:
        lat = self._latency.values()
        batches = int(self._batches.value)
        snap = {
            **{event: int(child.value)
               for (event,), child in self._events.children()},
            "queue_depth": int(self._depth.value),
            "latency_ms": {
                "n": len(lat),
                "p50": round(percentile(lat, 0.50), 3),
                "p95": round(percentile(lat, 0.95), 3),
                "p99": round(percentile(lat, 0.99), 3),
                "max": round(lat[-1], 3) if lat else 0.0,
            },
            "stages_ms": {
                stage: self._stage_snapshot(child)
                for (stage,), child in self._stage.children()
            },
            "batch": {
                "count": batches,
                "mean_occupancy": round(
                    self._batched.value / batches, 3) if batches else 0.0,
                "max_occupancy": int(self._max_occ.value),
            },
        }
        if self._mesh:
            snap["placements"] = {
                key: int(child.value)
                for (key,), child in self._placements.children()}
            snap["replicas"] = {
                idx: {
                    "requests": int(child.value),
                    "queue_depth": int(self._replica_depth.labels(
                        replica=idx).value),
                    "busy": int(self._replica_busy.labels(replica=idx).value),
                }
                for (idx,), child in self._replica_reqs.children()}
            tenants: dict = {}
            for (tenant, event), child in self._tenant_events.children():
                tenants.setdefault(tenant, {})[event] = int(child.value)
            for (tenant,), child in self._tenant_latency.children():
                vals = child.values()
                tenants.setdefault(tenant, {})["latency_ms"] = {
                    "n": len(vals),
                    "p50": round(percentile(vals, 0.50), 3),
                    "p99": round(percentile(vals, 0.99), 3),
                }
            snap["tenants"] = tenants
        return snap
