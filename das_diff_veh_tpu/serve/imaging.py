"""The production compute factory: ``process_chunk`` behind the engine.

One deployment serves one fiber section: the channel axis is fixed (the
interrogator's geometry picks the static slice bounds inside the compiled
program — host code like ``np.argmax(x >= start_x)`` turns ``x`` *values*
into compile-time constants), while the record length ``nt`` varies with
segment truncation.  Buckets should therefore share the deployment's
``n_ch`` and tile the expected ``nt`` range; see docs/USAGE.md §serving.

Geometry is enforced at admission (:meth:`ImagingComputeFactory.validate`,
called by ``ServingEngine.submit``): channel-axis padding, a foreign x
axis, or a wrong sample rate are rejected up front — mismatched geometry
would otherwise re-trace the pipeline inline on the dispatcher thread
(~40 s/shape on CPU) while the bucket cache still reported a hit, silently
breaking the zero-compile guarantee.

The time axis is *canonicalized*: compute rebases ``t`` onto the warmed
``arange(nt) * (1/fs)`` grid (the result is time-origin invariant, and a
large absolute ``t0`` — epoch seconds, hours into a recording — would both
quantize the float time steps and change the compiled dt constant).  The
segment's absolute start lands in ``ImagingResult.t0`` for provenance.
A request whose shape equals its bucket and whose t axis already starts at
0 therefore runs the identical program a direct ``process_chunk`` call
would (bit-exact, asserted in tests/test_serve.py); a time-padded request
computes on trailing zeros — the right semantics for a truncated tail
segment, surfaced as ``ImagingResult.padded`` so callers can tell.

Session state carries the batch workflow's accumulator across consecutive
segments of one fiber: the running sum of per-segment average images and
the vehicle count (``run_directory``'s ``avg_image += images.avg_image``
semantics, online).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional, Tuple

import numpy as np

from das_diff_veh_tpu.config import PipelineConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.runtime.manifest import config_hash
from das_diff_veh_tpu.serve.buckets import Bucket
from das_diff_veh_tpu.serve.compile_cache import ComputeFactory, ComputeFn


@dataclass
class ImagingResult:
    """One served segment: dispersion image + provenance."""

    image: np.ndarray                  # (nvel, nfreq)
    n_windows: int                     # isolated vehicles in this segment
    valid: Tuple[int, int]             # the request's true (n_ch, nt)
    bucket: Bucket                     # shape it executed at
    padded: bool                       # valid != bucket (trailing zeros)
    t0: float = 0.0                    # absolute segment start [s] (the
                                       # compute itself runs origin-rebased)


def _fresh_state() -> dict:
    return {"avg_image": None, "n_windows": 0, "n_segments": 0}


class ImagingComputeFactory(ComputeFactory):
    """Builds per-bucket ``process_chunk`` programs for one fiber section.

    ``x_axis`` is the deployment's channel axis (channel numbers when
    ``x_is_channels``, meters otherwise), at least as long as the largest
    bucket's ``n_ch`` — warmup uses its prefix so the warmed program is the
    one real traffic hits.  ``fs`` fixes the canonical time grid.
    """

    def __init__(self, cfg: Optional[PipelineConfig] = None,
                 method: str = "xcorr", x_is_channels: bool = True,
                 x_axis: Optional[np.ndarray] = None, fs: float = 250.0,
                 tuner_store: Optional[str] = None,
                 tuner_geometry: str = "default"):
        self.cfg = cfg if cfg is not None else PipelineConfig()
        self.method = method
        self.x_is_channels = x_is_channels
        self.fs = float(fs)
        self._x_axis = None if x_axis is None else np.asarray(x_axis, np.float64)
        # tuner winners are applied BEFORE config_key is computed, so the
        # programs the engine warms (cache keyed on config_key) are exactly
        # the tuned programs steady-state traffic hits — cache_misses == 0
        # still holds with tuned values active (tests/test_tune.py).
        # load_tuned is soft: a corrupt/missing store means default knobs.
        self.tuner_entry = None
        if tuner_store is not None:
            from das_diff_veh_tpu.tune import load_tuned
            self.cfg, _, self.tuner_entry = load_tuned(
                self.cfg, tuner_store, tuner_geometry)
        self.config_key = config_hash(self.cfg, method, x_is_channels)

    def _x_for(self, n_ch: int) -> np.ndarray:
        if self._x_axis is not None:
            if self._x_axis.size < n_ch:
                raise ValueError(
                    f"x_axis has {self._x_axis.size} channels, bucket "
                    f"needs {n_ch}")
            return self._x_axis[:n_ch]
        it = self.cfg.interrogator
        if self.x_is_channels:
            return it.start_ch + np.arange(n_ch, dtype=np.float64)
        return np.arange(n_ch, dtype=np.float64) * it.dx

    def _canonical_t(self, nt: int) -> np.ndarray:
        # same construction as io/synthetic.py's scene axis, so a t-axis
        # that already starts at 0 rebases to itself bit-for-bit
        return np.arange(nt, dtype=np.float64) * (1.0 / self.fs)

    def validate(self, section: DasSection,
                 bucket: Bucket) -> Optional[str]:
        """Admission-time geometry check (engine calls this in ``submit``):
        returns a rejection reason, or None for a servable request."""
        n_ch, nt = section.data.shape
        if int(n_ch) != int(bucket[0]):
            return (f"channel-axis padding ({n_ch} -> {bucket[0]}) is not "
                    "supported by the imaging factory: cross-channel "
                    "filtering would see zero rows inside the aperture; "
                    "configure buckets with the deployment's exact n_ch")
        x = np.asarray(section.x)
        expected_x = self._x_for(int(bucket[0]))
        if x.shape != expected_x.shape or not np.array_equal(x, expected_x):
            return ("request x axis does not match the deployment axis this "
                    "engine was warmed for; serving is per-fiber — build a "
                    "factory with this request's x_axis instead")
        t = np.asarray(section.t)
        dt = float(t[1] - t[0])
        if not math.isclose(dt, 1.0 / self.fs, rel_tol=1e-6):
            return (f"request sample interval {dt!r} != 1/fs "
                    f"{1.0 / self.fs!r}: resample or build a factory with "
                    "the matching fs")
        return None

    def warmup_section(self, bucket: Bucket) -> DasSection:
        n_ch, nt = bucket
        return DasSection(np.zeros(bucket, dtype=np.float32),
                          self._x_for(n_ch), self._canonical_t(nt))

    def build(self, bucket: Bucket) -> ComputeFn:
        import jax

        from das_diff_veh_tpu.pipeline.timelapse import process_chunk

        canonical_t = self._canonical_t(bucket[1])

        def compute(section: DasSection, valid: Tuple[int, int],
                    state: Any) -> Tuple[ImagingResult, Any]:
            # defense in depth for direct (engine-less) factory use; the
            # engine already ran this at admission (the padded section
            # passes the same checks: n_ch/x untouched, dt preserved)
            err = self.validate(section, bucket)
            if err is not None:
                raise ValueError(err)
            t = np.asarray(section.t)
            t0 = float(t[0])
            if not np.array_equal(t, canonical_t):
                # rebase onto the warmed grid: origin-invariant result, and
                # the compiled dt constant stays the canonical 1/fs
                section = DasSection(section.data, section.x, canonical_t)
            chunk = process_chunk(section, self.cfg, method=self.method,
                                  x_is_channels=self.x_is_channels)
            # one coalesced pull of everything the result needs (blocks
            # like the old block_until_ready did); with
            # cfg.chunk_pipeline="fused" this is the fused program's single
            # device->host transfer per request
            n, img = jax.device_get((chunk.n_windows, chunk.disp_image))
            n = int(n)
            img = np.asarray(img)
            result = ImagingResult(image=img, n_windows=n,
                                   valid=tuple(valid), bucket=bucket,
                                   padded=tuple(valid) != tuple(bucket),
                                   t0=t0)
            state = dict(state) if state is not None else _fresh_state()
            if n > 0:
                state["avg_image"] = (img if state["avg_image"] is None
                                      else state["avg_image"] + img)
                state["n_windows"] += n
            state["n_segments"] += 1
            return result, state

        return compute
