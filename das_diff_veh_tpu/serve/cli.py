"""``serve`` subcommand: stand up the online engine behind the HTTP front.

    python -m das_diff_veh_tpu.pipeline.cli serve \
        --buckets 140x30000,140x15000 --x0 700 --method xcorr \
        --port 8080 --compilation_cache_dir /var/cache/das_jax

Warms every bucket at startup (AOT — steady-state requests never trace),
then serves until interrupted; the metrics snapshot prints on exit.
"""

from __future__ import annotations

import argparse
import json
import logging

from das_diff_veh_tpu.config import (ImagingConfig, ObsConfig, PipelineConfig,
                                     ServeConfig)
from das_diff_veh_tpu.obs import default_registry
from das_diff_veh_tpu.runtime.tracing import make_tracer
from das_diff_veh_tpu.serve.engine import ServingEngine
from das_diff_veh_tpu.serve.http import make_server
from das_diff_veh_tpu.serve.imaging import ImagingComputeFactory


def parse_buckets(spec: str):
    """``"140x30000,100x15000"`` -> ((140, 30000), (100, 15000))."""
    try:
        return tuple(tuple(int(v) for v in part.split("x"))
                     for part in spec.split(",") if part)
    except ValueError as e:
        raise argparse.ArgumentTypeError(
            f"buckets must look like 140x30000,100x15000 (got {spec!r})") from e


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="das_diff_veh_tpu serve",
        description="Online DAS-segment serving engine (HTTP JSON front)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="0 picks an ephemeral port (printed at startup)")
    p.add_argument("--buckets", type=parse_buckets, required=True,
                   metavar="CHxNT[,CHxNT...]",
                   help="padded request shapes, e.g. 140x30000,140x15000")
    p.add_argument("--x0", type=float, default=700.0, help="pivot along fiber [m]")
    p.add_argument("--method", default="xcorr",
                   choices=["xcorr", "surface_wave"])
    p.add_argument("--x_is_channels", action="store_true",
                   help="request x axes carry channel numbers, not meters")
    p.add_argument("--fs", type=float, default=250.0,
                   help="sampling rate the warmup time axis assumes [Hz]")
    p.add_argument("--max_batch", type=int, default=4)
    p.add_argument("--max_queue", type=int, default=64)
    p.add_argument("--batch_window_ms", type=float, default=2.0,
                   help="DEPRECATED, ignored: batching is continuous "
                        "(iteration-level); kept so existing invocations "
                        "keep parsing (a non-default value raises "
                        "DeprecationWarning at config construction)")
    p.add_argument("--tuner_store", default=None, metavar="PATH",
                   help="tuner-store JSON (das_diff_veh_tpu.tune): apply "
                        "persisted knob winners for this backend/geometry "
                        "before warmup (docs/TUNING.md)")
    p.add_argument("--tuner_geometry", default="default", metavar="LABEL",
                   help="deployment-geometry label the tuner store is keyed "
                        "under")
    mesh = p.add_argument_group(
        "mesh serving",
        "multi-tenant engine across the device mesh (docs/SERVING.md)")
    mesh.add_argument("--mesh", action="store_true",
                      help="serve with the mesh engine: one continuous-"
                           "batching worker per replica + tenant quotas")
    mesh.add_argument("--replicas", type=int, default=None, metavar="N",
                      help="data-parallel replica workers (default: one per "
                           "visible device)")
    mesh.add_argument("--ring_min_channels", type=int, default=None,
                      metavar="NCH",
                      help="route requests with >= NCH valid channels onto "
                           "the channel-sharded ring (default: ring off)")
    mesh.add_argument("--tenant_quota", type=int, default=32,
                      help="max queued + in-flight requests per tenant "
                           "(429 beyond)")
    p.add_argument("--deadline_ms", type=float, default=30000.0,
                   help="default per-request deadline")
    p.add_argument("--no_warmup", action="store_true",
                   help="skip AOT bucket warmup (first requests pay traces)")
    p.add_argument("--compilation_cache_dir", default=None, metavar="DIR",
                   help="persistent XLA compilation cache "
                        "(jax_compilation_cache_dir) — makes warmup near-free "
                        "across restarts")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write Chrome-trace JSONL request spans to PATH")
    obs = p.add_argument_group("observability",
                               "registry/flight knobs (docs/OBSERVABILITY.md;"
                               " Prometheus scrape is GET /metrics)")
    obs.add_argument("--flight_dir", default=None, metavar="DIR",
                     help="crash-flight-recorder dump directory (a JSON "
                          "artifact of recent requests on shed/error)")
    obs.add_argument("--trace_flush_interval", type=float, default=0.0,
                     metavar="S", help="batch trace writes, flushing every S "
                                       "seconds (0 = flush per span)")
    obs.add_argument("--no_xla_events", action="store_true",
                     help="skip the jax.monitoring compile counters")
    p.add_argument("--verbal", action="store_true", help="info-level logs")
    return p


def serve_main(argv=None) -> int:
    args = build_serve_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO if args.verbal else logging.WARNING,
                        format="%(asctime)s %(name)s %(message)s")
    cfg = PipelineConfig().replace(imaging=ImagingConfig(x0=args.x0))
    obs_cfg = ObsConfig(flight_dir=args.flight_dir,
                        trace_flush_interval_s=args.trace_flush_interval,
                        xla_events=not args.no_xla_events)
    serve_cfg = ServeConfig(
        buckets=args.buckets, max_batch=args.max_batch,
        max_queue=args.max_queue, batch_window_ms=args.batch_window_ms,
        default_deadline_ms=args.deadline_ms, warmup=not args.no_warmup,
        compilation_cache_dir=args.compilation_cache_dir, obs=obs_cfg)
    tracer = make_tracer(args.trace,
                         flush_interval_s=args.trace_flush_interval)
    factory = ImagingComputeFactory(cfg, method=args.method,
                                    x_is_channels=args.x_is_channels,
                                    fs=args.fs,
                                    tuner_store=args.tuner_store,
                                    tuner_geometry=args.tuner_geometry)
    # the process-default registry: ring/runtime metrics registered anywhere
    # in this process land in the same GET /metrics scrape as das_serve_*
    if args.mesh:
        from das_diff_veh_tpu.config import MeshServeConfig
        from das_diff_veh_tpu.serve.mesh import MeshServingEngine
        engine = MeshServingEngine(
            factory,
            MeshServeConfig(serve=serve_cfg, replicas=args.replicas,
                            ring_min_channels=args.ring_min_channels,
                            tenant_quota=args.tenant_quota),
            tracer=tracer, registry=default_registry())
    else:
        engine = ServingEngine(factory, serve_cfg, tracer=tracer,
                               registry=default_registry())
    engine.start()
    server = make_server(engine, args.host, args.port)
    print(f"serving on http://{server.server_address[0]}"
          f":{server.server_address[1]} buckets={list(args.buckets)}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        engine.close()
        tracer.close()
        print(json.dumps(engine.metrics(), indent=1))
    return 0
