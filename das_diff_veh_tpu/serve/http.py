"""Thin stdlib-HTTP JSON front end for smoke-driving a ServingEngine.

Deliberately minimal — a demo/debug surface, not a production gateway (no
auth, JSON-array payloads, one engine per server):

- ``POST /v1/process`` — body ``{"data": [[...]], "x": [...], "t": [...],
  "deadline_ms": opt, "session": opt, "tenant": opt}``; responds with the
  result summary (``?image=1`` to inline the full image values).
- ``GET /v1/metrics`` — the engine's legacy JSON metrics snapshot.  When
  the engine is a ``serve.mesh.MeshServingEngine`` the SAME payload grows
  the per-replica / placement / per-tenant views (no second endpoint).
- ``GET /metrics`` — Prometheus text exposition of the engine's registry
  (``das_serve_*`` families, plus whatever else registered into the same
  registry — the serve CLI passes the process default registry, so runtime
  and parallel metrics ride the same scrape).
- ``GET /healthz`` — liveness + configured buckets.

Shed responses map onto HTTP status codes: 429 for backpressure
(queue full) and for the mesh engine's per-tenant sheds (quota reached,
quarantined, draining — the structured body carries ``cause`` so one
status code stays diagnosable), 503 when every replica is draining,
504 for a deadline that expired in queue, 413 for a shape no
bucket fits, 400 for malformed payloads and for requests the compute
factory's admission check rejects (e.g. geometry that does not match the
warmed programs), and 422 for poison inputs the admission health screen
sheds (NaN/Inf bursts, dead-channel floods) — the 422 body is structured
(``{"error", "nan_fraction", "dead_channels"}``) so the producer side can
diagnose its interrogator instead of parsing prose.  Tenancy errors are
mapped via their ``http_status`` class attribute rather than imports, so
this module never depends on ``serve.mesh``.
"""

from __future__ import annotations

import json
import threading
from dataclasses import asdict, is_dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

import numpy as np

from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.serve.engine import (DeadlineExceededError,
                                           InvalidRequestError, NoBucketError,
                                           PoisonInputError, QueueFullError,
                                           ServingEngine, ShedError)


def _jsonable(obj, full_arrays: bool = False):
    """Best-effort JSON rendering of an arbitrary compute result: arrays
    become summaries (or value lists with ``full_arrays``), dataclasses and
    containers recurse."""
    if is_dataclass(obj) and not isinstance(obj, type):
        return _jsonable(asdict(obj), full_arrays)
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, full_arrays) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v, full_arrays) for v in obj]
    if isinstance(obj, np.ndarray):
        if full_arrays:
            return obj.tolist()
        return {"shape": list(obj.shape), "dtype": str(obj.dtype),
                "sum": float(obj.sum())}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    return repr(obj)


class ServeHandler(BaseHTTPRequestHandler):
    """One handler class per server, bound to its engine via the factory in
    :func:`make_server`."""

    engine: ServingEngine = None       # set by make_server
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet by default; tracer has spans
        pass

    def _reply(self, code: int, payload: dict) -> None:
        self._reply_text(code, json.dumps(payload), "application/json")

    def _reply_text(self, code: int, body: str, content_type: str) -> None:
        raw = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def do_GET(self):
        path = urlparse(self.path).path
        if path == "/healthz":
            self._reply(200, {"ok": True,
                              "buckets": [list(b) for b in
                                          self.engine.buckets]})
        elif path == "/v1/metrics":
            self._reply(200, self.engine.metrics())
        elif path == "/metrics":
            self._reply_text(200, self.engine.registry.prometheus_text(),
                             "text/plain; version=0.0.4; charset=utf-8")
        else:
            self._reply(404, {"error": f"unknown path {path}"})

    def do_POST(self):
        url = urlparse(self.path)
        if url.path != "/v1/process":
            self._reply(404, {"error": f"unknown path {url.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n))
            data = np.asarray(payload["data"], dtype=np.float32)
            if data.ndim != 2:
                raise ValueError(f"data must be 2-D, got shape {data.shape}")
            x = np.asarray(payload.get(
                "x", np.arange(data.shape[0])), dtype=np.float64)
            t = np.asarray(payload.get(
                "t", np.arange(data.shape[1])), dtype=np.float64)
            deadline_ms = payload.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
            session = payload.get("session")
            tenant = payload.get("tenant")
        except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e}"})
            return
        section = DasSection(data, x, t)
        try:
            future = self.engine.submit(section, deadline_ms=deadline_ms,
                                        session=session, tenant=tenant)
            result = future.result()
        except QueueFullError as e:
            self._reply(429, {"error": str(e)})
            return
        except NoBucketError as e:
            self._reply(413, {"error": str(e)})
            return
        except PoisonInputError as e:
            # 422: syntactically fine, semantically unprocessable — the
            # structured body tells the caller WHAT is poisoned so the
            # producer side can be fixed (422 before 400: Poison subclasses
            # InvalidRequestError)
            self._reply(422, {"error": str(e),
                              "nan_fraction": e.health.nan_fraction,
                              "dead_channels": e.health.n_masked})
            return
        except InvalidRequestError as e:
            self._reply(400, {"error": str(e)})
            return
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e)})
            return
        except ShedError as e:
            # mesh tenancy/placement sheds (TenantQuotaError & co. declare
            # their status via http_status); the cause field keeps the
            # shared 429 diagnosable without a per-class handler here
            cause = type(e).__name__.removeprefix("Tenant") \
                .removesuffix("Error").lower()
            self._reply(getattr(e, "http_status", 400),
                        {"error": str(e), "cause": cause, "tenant": tenant})
            return
        except Exception as e:
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        full = "image=1" in (url.query or "")
        self._reply(200, {"result": _jsonable(result, full_arrays=full)})


def make_server(engine: ServingEngine, host: str = "127.0.0.1",
                port: int = 0) -> ThreadingHTTPServer:
    """A ThreadingHTTPServer bound to ``engine`` (port 0 = ephemeral; the
    bound port is ``server.server_address[1]``).  Caller owns serve_forever
    / shutdown."""
    handler = type("BoundServeHandler", (ServeHandler,), {"engine": engine})
    return ThreadingHTTPServer((host, port), handler)


def serve_in_thread(engine: ServingEngine, host: str = "127.0.0.1",
                    port: int = 0):
    """Start the server on a daemon thread; returns ``(server, thread)``."""
    server = make_server(engine, host, port)
    thread = threading.Thread(target=server.serve_forever,
                              name="serve-http", daemon=True)
    thread.start()
    return server, thread
