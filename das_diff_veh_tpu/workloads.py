"""Benchmark / dry-run workload builders.

Produces reference-geometry window batches (BASELINE.md problem geometry:
dx = 8.16 m, fs = 250 Hz, ~8 s x 300 m windows, 700 m pivot, class stacks of
~60 windows) filled with synthetic dispersive wavefields and linear vehicle
trajectories — the shapes the reference's 700 m imaging path processes
(apis/imaging_classes.py save_disp_imgs / bootstrap_disp).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.config import GatherConfig, WindowConfig
from das_diff_veh_tpu.core.section import WindowBatch
from das_diff_veh_tpu.io.synthetic import (default_phase_velocity,
                                           surface_wave_field)
from das_diff_veh_tpu.models.vsg import VsgGeometry


def make_window_batch(n_windows: int = 60, x0: float = 700.0,
                      fs: float = 250.0, dx: float = 8.16,
                      wcfg: WindowConfig = WindowConfig(),
                      noise: float = 0.3, seed: int = 0,
                      dtype=np.float32):
    """(WindowBatch, x_axis) with reference geometry and dispersive content.

    Every window radiates its OWN dispersive wavefield from its vehicle's
    channel crossings (per-window random speed and pivot-crossing time, the
    same moving-source synthesis the e2e scene generator uses) plus noise —
    windows are genuinely distinct, so a 60-window stack is a real
    incoherent average, not one cached shot plus i.i.d. noise (VERDICT r3
    weak #3).  Trajectories are linear, crossing the pivot near mid-window.
    """
    rng = np.random.default_rng(seed)
    dt = 1.0 / fs
    nx = int(wcfg.length_sw / dx)
    nt = int(wcfg.wlen_sw / dt)
    start_x = x0 - wcfg.length_sw * wcfg.spatial_ratio
    x = start_x + np.arange(nx) * dx

    data = np.empty((n_windows, nx, nt), dtype=dtype)
    t = np.empty((n_windows, nt), dtype=dtype)
    n_traj = 64
    traj_x = np.empty((n_windows, n_traj), dtype=dtype)
    traj_t = np.empty((n_windows, n_traj), dtype=dtype)
    for w in range(n_windows):
        # all windows share t0 = 0: float32 time axes keep full dt precision
        # (absolute offsets like 100*w would quantize 4 ms steps at ~600 s)
        t0 = 0.0
        t[w] = t0 + np.arange(nt, dtype=np.float64) * dt
        speed = rng.uniform(10.0, 22.0)
        # pivot crossing jitters around mid-window (selection centers it
        # only up to the tracker's sample resolution)
        t_pivot = t0 + nt // 2 * dt + rng.uniform(-0.2, 0.2)
        crossings = t_pivot + (x - x0) / speed            # (nx,)
        # channels far behind the pivot cross BEFORE the window opens (down
        # to ~-18 s at 10 m/s); synthesize an extended record starting early
        # enough and keep only its tail, so pre-window sources cannot wrap
        # around the FFT period into the window with inverted moveout
        lead = int(np.ceil(max(0.0, 2.0 - float(crossings.min())) / dt))
        field = surface_wave_field(nx, nt + lead, dx, dt,
                                   (crossings + lead * dt)[None, :],
                                   np.asarray([1.0]),
                                   default_phase_velocity)[:, lead:]
        field /= np.abs(field).max()
        data[w] = field + noise * rng.standard_normal((nx, nt))
        tx = np.linspace(x[0] - 50.0, x[-1] + 50.0, n_traj)
        traj_x[w] = tx
        traj_t[w] = t_pivot + (tx - x0) / speed
    batch = WindowBatch(data=jnp.asarray(data), x=jnp.asarray(x.astype(dtype)),
                        t=jnp.asarray(t), traj_x=jnp.asarray(traj_x),
                        traj_t=jnp.asarray(traj_t),
                        valid=jnp.ones(n_windows, bool))
    return batch, x


def make_ambient_record(nch: int, nt: int, seed: int = 0,
                        dtype=np.float32) -> jnp.ndarray:
    """(nch, nt) synthetic ambient-noise record for the config-4 all-pairs
    benchmarks (BASELINE.md: 10k channels at 1 kHz, minutes-long records).

    White Gaussian noise: the all-pairs engine's cost is data-independent
    (fixed FFT + tile-product work per (pair, window)), so an uncorrelated
    record is throughput-representative while keeping the builder cheap
    enough to synthesize minutes-long 10k-channel inputs (nt ~ 60k) in the
    bench process.  A fixed ``seed`` keeps reruns byte-identical.
    """
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((nch, nt)).astype(dtype))


def make_gather_geometry(x: np.ndarray, x0: float = 700.0, fs: float = 250.0,
                         cfg: GatherConfig = GatherConfig()) -> VsgGeometry:
    """Reference gather geometry for a window batch: offsets start_x .. end_x
    around the pivot (the notebooks' 700 m setup, x0-150 .. x0+far_offset)."""
    return VsgGeometry.build(x, 1.0 / fs, x0, x0 - 150.0, x0 + cfg.far_offset, cfg)
