"""Executable numpy specification of the virtual-shot-gather construction.

Semantics from apis/virtual_shot_gather.py:111-192 (preprocessing_window /
construct_shot_gather / construct_shot_gather_other_side /
post_processing_XCF / the other-side merge in VirtualShotGather.__init__),
on raw arrays instead of window objects.  Parity oracle + NumPy baseline for
das_diff_veh_tpu.models.vsg.
"""

from __future__ import annotations

import numpy as np

from das_diff_veh_tpu.oracle.windows_ref import lin_interp_extrap
from das_diff_veh_tpu.oracle.xcorr_ref import ref_xcorr_traj_follow, ref_xcorr_vshot


def _traj_time_at(traj_x: np.ndarray, traj_t: np.ndarray, xq) -> np.ndarray:
    m = np.isfinite(traj_t) & np.isfinite(traj_x)
    return lin_interp_extrap(xq, traj_x[m], traj_t[m])


def _traj_rows(data, t_axis, pivot_idx, traj_x, traj_t, x_axis, ch_lo, ch_hi,
               nsamp, wlen, delta_t, reverse):
    """Per-channel trajectory-following rows (reference :14-43)."""
    ch = np.arange(ch_lo, ch_hi)
    t_at_ch = _traj_time_at(traj_x, traj_t, x_axis[ch])
    t_at_ch = t_at_ch - delta_t if reverse else t_at_ch + delta_t
    return ref_xcorr_traj_follow(data, t_axis, pivot_idx, ch, t_at_ch,
                                 nsamp, wlen, reverse=reverse)


def _post(xcf, pivot_idx, start_x_idx, norm, norm_amp, reverse):
    """post_processing_XCF (reference :129-142), with 0-row guard."""
    if norm:
        rn = np.linalg.norm(xcf, axis=-1, keepdims=True)
        xcf = xcf / np.where(rn > 0, rn, 1.0)
    if norm_amp:
        amp = np.max(xcf[pivot_idx - start_x_idx])
        if abs(amp) > 0:
            xcf = xcf / amp
    if not reverse:
        xcf = xcf[:, ::-1]
    return xcf


def ref_build_gather(data: np.ndarray, x_axis: np.ndarray, t_axis: np.ndarray,
                     traj_x: np.ndarray, traj_t: np.ndarray, pivot: float,
                     start_x: float, end_x: float, wlen_s: float = 2.0,
                     time_window: float = 4.0, delta_t: float = 1.0,
                     norm: bool = True, norm_amp: bool = True,
                     include_other_side: bool = True):
    """One window -> (XCF (nch_out, wlen), offsets, lags)."""
    dt = t_axis[1] - t_axis[0]
    pivot_idx = int(np.argmax(x_axis >= pivot))
    sxi = int(np.argmax(x_axis >= start_x))
    exi = int(np.abs(x_axis - end_x).argmin())
    nsamp = int(time_window // dt)
    wlen = int(wlen_s / dt)
    d = data / np.linalg.norm(data)

    # main side
    pt = _traj_time_at(traj_x, traj_t, pivot)[0] + delta_t
    pti = int(np.argmax(t_axis >= pt))
    near = ref_xcorr_vshot(d[sxi:pivot_idx + 1, pti:pti + nsamp],
                           pivot_idx - sxi, wlen)
    far = _traj_rows(d, t_axis, pivot_idx, traj_x, traj_t, x_axis,
                     pivot_idx + 1, exi, nsamp, wlen, delta_t, reverse=False)
    main = _post(np.concatenate([near, far], axis=0), pivot_idx, sxi,
                 norm, norm_amp, reverse=False)

    if include_other_side:
        pt2 = _traj_time_at(traj_x, traj_t, pivot)[0] - delta_t
        pti2 = int(np.argmax(t_axis >= pt2))
        if pti2 - nsamp < 0:
            right = np.zeros((exi - pivot_idx, wlen))
        else:
            right = ref_xcorr_vshot(d[pivot_idx:exi, pti2 - nsamp:pti2], 0,
                                    wlen, reverse=True)
        left = _traj_rows(d, t_axis, pivot_idx, traj_x, traj_t, x_axis,
                          sxi, pivot_idx, nsamp, wlen, delta_t, reverse=True)
        other = _post(np.concatenate([left, right], axis=0), pivot_idx, sxi,
                      norm, norm_amp, reverse=True)
        stack = np.linalg.norm(other, axis=-1) > 0
        main = main.copy()
        main[stack] = 0.5 * (main[stack] + other[stack])

    offsets = x_axis[sxi:exi] - x_axis[pivot_idx]
    lags = (np.arange(wlen) - wlen // 2) * dt
    return main, offsets, lags
