"""Executable numpy specification of ridge extraction
(reference modules/utils.py:621-678 extract_ridge_ref_idx)."""

from __future__ import annotations

import numpy as np
from scipy.signal import savgol_filter


def ref_extract_ridge(freq, vel, fv_map, ref_freq_idx=None, sigma=25,
                      vel_max=400, ref_vel=None):
    vel = np.asarray(vel)[::-1]
    fv_map = np.asarray(fv_map)[::-1, :]

    if ref_freq_idx is None and ref_vel is None:
        max_idx = int(np.abs(vel_max - vel).argmin())
        v = vel[max_idx:]
        return v[np.argmax(fv_map[max_idx:], axis=0)]

    nf = len(freq)
    out = np.zeros(nf)
    if ref_vel is None:
        out[ref_freq_idx] = vel[np.argmax(fv_map[:, ref_freq_idx])]
        for i in range(ref_freq_idx - 1, -1, -1):
            mask = (vel > out[i + 1] - sigma) & (vel < out[i + 1] + sigma)
            out[i] = vel[mask][np.argmax(fv_map[mask, i])]
        for i in range(ref_freq_idx + 1, nf):
            mask = (vel > out[i - 1] - sigma) & (vel < out[i - 1] + sigma)
            out[i] = vel[mask][np.argmax(fv_map[mask, i])]
    else:
        centers = ref_vel(np.asarray(freq))
        for i in range(nf):
            mask = (vel > centers[i] - sigma) & (vel < centers[i] + sigma)
            out[i] = vel[mask][np.argmax(fv_map[mask, i])]
    return savgol_filter(out, 25, 2)
