"""Executable numpy specification of the reference vehicle tracker.

Semantics from apis/tracking.py:21-168 (detection + KF march + association)
and modules/car_tracking_utils.py:21-66 (likelihood, QC, NaN interpolation),
using scipy.signal.find_peaks directly.  Parity oracle for
das_diff_veh_tpu.models.tracking.
"""

from __future__ import annotations

import numpy as np
from scipy.signal import find_peaks

from das_diff_veh_tpu.config import TrackingConfig, TrackQCConfig


def ref_likelihood(peak_loc: np.ndarray, t_axis: np.ndarray, sigma: float) -> np.ndarray:
    out = np.zeros(t_axis.size)
    for p in peak_loc:
        z = (t_axis - t_axis[p]) / sigma
        out += np.exp(-0.5 * z * z) / (sigma * np.sqrt(2 * np.pi))
    return out


def ref_detect_base(data: np.ndarray, t_axis: np.ndarray, start_x_idx: int,
                    cfg: TrackingConfig = TrackingConfig()) -> np.ndarray:
    det = cfg.detect
    acc = np.zeros(t_axis.size)
    for i in range(cfg.n_detect_channels):
        pk = find_peaks(data[start_x_idx + i], prominence=det.min_prominence,
                        wlen=det.prominence_wlen, distance=det.min_separation)[0]
        acc += ref_likelihood(pk, t_axis, cfg.likelihood_sigma)
    base, _ = find_peaks(acc, height=acc.max() * 0.0, distance=det.min_separation)
    return base


def ref_track(data: np.ndarray, x_axis: np.ndarray, start_x: float, end_x: float,
              veh_base: np.ndarray, cfg: TrackingConfig = TrackingConfig()) -> np.ndarray:
    """KF march (reference tracking_with_veh_base, apis/tracking.py:65-156).
    Returns the strided (nveh, n_steps) recorded-state array (NaN = missed)."""
    det = cfg.detect
    sxi = int(np.abs(start_x - x_axis).argmin())
    exi = int(np.abs(end_x - x_axis).argmin())
    stride = cfg.channel_stride
    steps = list(range(sxi, exi + 1, stride))
    nveh = len(veh_base)
    states = np.full((nveh, len(steps)), np.nan)

    Tkk = np.full((nveh, 2), np.nan)
    Pkk = np.full((nveh, 2, 2), np.nan)
    Xv = np.full(nveh, np.nan)
    obs1 = np.full(nveh, np.nan)
    obs1_x = np.full(nveh, np.nan)
    C = np.array([1.0, 0.0])

    for s, i in enumerate(steps):
        pred = np.empty(nveh)
        Tk1k = np.full((nveh, 2), np.nan)
        Pk1k = np.full((nveh, 2, 2), np.nan)
        for v in range(nveh):
            count = np.sum(np.isfinite(states[v]))
            if count == 1:
                Tkk[v] = [obs1[v], 0.0]
                Pkk[v] = 0.0
                Xv[v] = obs1_x[v]
                pred[v] = veh_base[v]
            elif count == 0:
                pred[v] = veh_base[v]
            else:
                dx = x_axis[i] - Xv[v]
                A = np.array([[1.0, dx], [0.0, 1.0]])
                Q = cfg.sigma_a * np.array([[0.25 * dx ** 4, 0.5 * dx ** 3],
                                            [0.5 * dx ** 3, dx ** 2]])
                Tk1k[v] = A @ Tkk[v]
                Pk1k[v] = A @ Pkk[v] @ A.T + Q
                pred[v] = Tk1k[v, 0]

        peak_loc = find_peaks(data[i], prominence=det.min_prominence,
                              wlen=det.prominence_wlen,
                              distance=det.min_separation)[0]
        for v in range(nveh):
            dist = peak_loc - pred[v]
            gate = np.where((dist > cfg.gate_lo) & (dist <= cfg.gate_hi))[0]
            gdist = dist[gate]
            pos = gdist[gdist > 0]
            if pos.size > 0:
                if cfg.assoc_bug_compat:
                    # the reference indexes the gate subset with the
                    # positive-subset argmin (apis/tracking.py:132-135) ->
                    # effectively the first gated peak
                    states[v, s] = peak_loc[gate[int(np.argmin(pos))]]
                else:
                    pos_gate = gate[gdist > 0]
                    states[v, s] = peak_loc[pos_gate[int(np.argmin(pos))]]
            elif gdist.size > 0:
                states[v, s] = peak_loc[gate[int(np.argmin(np.abs(gdist)))]]
            if np.isfinite(states[v, s]) and np.sum(np.isfinite(states[v, :s])) == 0:
                obs1[v] = states[v, s]
                obs1_x[v] = x_axis[i]

        for v in range(nveh):
            count = np.sum(np.isfinite(states[v]))
            if count > 2 and np.isfinite(states[v, s]):
                K = Pk1k[v] @ C / (cfg.meas_noise + C @ Pk1k[v] @ C)
                Tkk[v] = Tk1k[v] + K * (states[v, s] - C @ Tk1k[v])
                Pkk[v] = Pk1k[v] - (K.reshape(2, 1) @ C.reshape(1, 2)) @ Pk1k[v]
                Xv[v] = x_axis[i]
    return states


def ref_track_qc(states: np.ndarray, qc: TrackQCConfig = TrackQCConfig()):
    """remove_unrealistic_tracking (modules/car_tracking_utils.py:38-66) on the
    strided array; returns (jump-masked states, keep mask)."""
    out = states.copy()
    ns = states.shape[-1]
    keep = np.ones(states.shape[0], bool)
    w = int(qc.retrograde_window)
    for v in range(states.shape[0]):
        row = states[v]
        tmp = row[np.isfinite(row)]
        d = np.diff(tmp)
        retro = np.sum(np.convolve(d, np.ones(w), mode="valid") <= qc.retrograde_threshold) > 0 \
            if d.size > 0 else False
        nan_idx = np.where(np.isnan(row))[0]
        adjacency = np.sum(np.diff(nan_idx) == 1) if nan_idx.size else 0
        if (tmp.size < qc.min_valid_fraction * ns or retro or
                abs(np.sum(d)) < qc.min_travel_samples * (tmp.size / ns) or
                adjacency >= qc.max_adjacent_nan):
            keep[v] = False
        vidx = np.where(np.isfinite(row))[0]
        bad = np.where(np.abs(d) > qc.max_jump)[0]
        out[v, vidx[bad + 1]] = np.nan
    return out, keep


def ref_upsample(states: np.ndarray, factor: int) -> np.ndarray:
    """Stride-expand + np.interp NaN fill (reference tracking.py:162-166,
    car_tracking_utils.py:28-35)."""
    full = np.full((states.shape[0], states.shape[1] * factor), np.nan)
    full[:, ::factor] = states
    for row in full:
        good = np.where(np.isfinite(row))[0]
        row[np.isnan(row)] = np.interp(np.where(np.isnan(row))[0], good, row[good])
    return full
