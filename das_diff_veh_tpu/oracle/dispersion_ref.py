"""Executable specification of the reference's dispersion transform.

map_fv semantics (modules/utils.py:457-475): padded 2-D FFT magnitude,
linear-spline sampling along k = f/v (the removed scipy ``interp2d``;
``RectBivariateSpline(kx=1, ky=1)`` is scipy's documented bug-compatible
replacement), Savitzky-Golay (25,4) smoothing over frequency, transpose to
(nvel, nfreq).
"""

from __future__ import annotations

import math

import numpy as np
from scipy.interpolate import RectBivariateSpline
from scipy.signal import savgol_filter


def ref_fk(data: np.ndarray, dx: float, dt: float):
    nch, nt = data.shape
    nf = 2 ** (1 + math.ceil(math.log2(nt)))
    nk = 2 ** (1 + math.ceil(math.log2(nch)))
    fk_res = np.fft.fftshift(np.fft.fft2(data, s=[nk, nf]))
    f_axis = np.arange(-nf / 2, nf / 2) / nf / dt
    k_axis = np.arange(-nk / 2, nk / 2) / nk / dx
    return np.absolute(fk_res), f_axis, k_axis


def ref_map_fv(data: np.ndarray, dx: float, dt: float, freqs: np.ndarray,
               vels: np.ndarray, norm: bool = False,
               sg_window: int = 25, sg_order: int = 4) -> np.ndarray:
    if norm:
        data = data / np.linalg.norm(data, axis=-1, keepdims=True, ord=1)
    fk_mag, f_axis, k_axis = ref_fk(data, dx, dt)
    spline = RectBivariateSpline(k_axis, f_axis, fk_mag, kx=1, ky=1)
    fv = np.zeros((len(freqs), len(vels)))
    for i, fr in enumerate(freqs):
        fv[i] = spline(fr / vels, fr, grid=False)
    fv = savgol_filter(fv, sg_window, sg_order, axis=0)
    return fv.T
