"""Executable specification of the reference's windowed cross-correlation.

Semantics from modules/utils.py:250-314 (XCORR_two_traces / XCORR_vshot /
repeat1d) and apis/virtual_shot_gather.py:14-43
(xcorr_two_traces_based_on_traj): source window circularly doubled, scipy
``correlate(mode='valid', method='fft')`` per 50%-overlap window, stack,
roll by wlen//2.  Used as the test oracle and the NumPy baseline in bench.py.
"""

from __future__ import annotations

import numpy as np
from scipy import signal


def _doubled(win: np.ndarray) -> np.ndarray:
    return np.concatenate([win, win[:-1]])


def ref_xcorr_pair(tr_src: np.ndarray, tr_rcv: np.ndarray, wlen: int,
                   overlap_ratio: float = 0.5) -> np.ndarray:
    offset = int(wlen * (1.0 - overlap_ratio))
    nwin = (tr_src.size - wlen) // offset + 1
    acc = np.zeros(wlen)
    for w in range(nwin):
        s = slice(w * offset, w * offset + wlen)
        acc += signal.correlate(_doubled(tr_src[s]), tr_rcv[s], mode="valid", method="fft")
    acc = np.roll(acc, wlen // 2)
    return acc / nwin if nwin > 0 else acc


def ref_xcorr_vshot(data: np.ndarray, ivs: int, wlen: int,
                    overlap_ratio: float = 0.5, reverse: bool = False) -> np.ndarray:
    nch, nt = data.shape
    offset = int(wlen * (1.0 - overlap_ratio))
    nwin = (nt - wlen) // offset + 1
    out = np.zeros((nch, wlen))
    for w in range(nwin):
        s = slice(w * offset, w * offset + wlen)
        src = _doubled(data[ivs, s])
        for r in range(nch):
            if reverse:
                out[r] += signal.correlate(data[r, s], src, mode="valid", method="fft")
            else:
                out[r] += signal.correlate(src, data[r, s], mode="valid", method="fft")
    if nwin == 0:
        return out
    return np.roll(out, wlen // 2, axis=-1) / nwin


def ref_xcorr_traj_follow(data: np.ndarray, t_axis: np.ndarray, pivot_idx: int,
                          ch_indices: np.ndarray, t_at_ch: np.ndarray,
                          nsamp: int, wlen: int, overlap_ratio: float = 0.5,
                          reverse: bool = False) -> np.ndarray:
    """Numpy-slice parity: the forward window [ti, ti+nsamp) truncates at the
    record end (fewer correlation windows); the backward window
    [ti-nsamp, ti) is *empty* when ti < nsamp (numpy negative-start slice,
    reference apis/virtual_shot_gather.py:31) and the row stays zero."""
    out = np.zeros((len(ch_indices), wlen))
    for k, (ch, t_target) in enumerate(zip(ch_indices, t_at_ch)):
        ti = int(np.argmax(t_axis >= t_target))
        if reverse:
            if ti - nsamp < 0:
                continue
            sl = slice(ti - nsamp, ti)
        else:
            sl = slice(ti, ti + nsamp)
        tr_ch = data[ch, sl]
        tr_pv = data[pivot_idx, sl]
        if tr_ch.size < wlen:
            continue
        if reverse:
            out[k] = ref_xcorr_pair(tr_pv, tr_ch, wlen, overlap_ratio)
        else:
            out[k] = ref_xcorr_pair(tr_ch, tr_pv, wlen, overlap_ratio)
    return out
