"""Executable numpy specification of window selection + trajectory muting.

Semantics from apis/data_classes.py: the per-time-sample Tukey mute loop
(:49-104) and SurfaceWaveSelector.locate_windows (:170-223).  Used as the
parity oracle for das_diff_veh_tpu.models.windows.
"""

from __future__ import annotations

import numpy as np
from scipy.signal import windows as _windows


def lin_interp_extrap(xq: np.ndarray, xs: np.ndarray, ys: np.ndarray) -> np.ndarray:
    """Piecewise-linear interp with linear end-segment extrapolation —
    scipy interp1d(fill_value='extrapolate') / extrap1d behavior."""
    xq = np.atleast_1d(np.asarray(xq, dtype=float))
    i = np.clip(np.searchsorted(xs, xq, side="right") - 1, 0, len(xs) - 2)
    w = (xq - xs[i]) / (xs[i + 1] - xs[i])
    return ys[i] + w * (ys[i + 1] - ys[i])


def ref_traj_mute_mask(x_axis: np.ndarray, t_axis: np.ndarray,
                       traj_x: np.ndarray, traj_t: np.ndarray, dx: float,
                       offset: float = 200.0, alpha: float = 0.3,
                       delta_x: float = 20.0,
                       double_sided: bool = False) -> np.ndarray:
    """Per-time-sample Tukey mask loop (reference apis/data_classes.py:60-70,86-96)."""
    nx = x_axis.size
    n_samp = int(offset / dx)
    tuk = _windows.tukey(n_samp, alpha)
    car_positions = lin_interp_extrap(t_axis, traj_t, traj_x)
    mask = np.zeros((nx, t_axis.size))
    for k, car_loc in enumerate(car_positions):
        center_x = car_loc if double_sided else car_loc - offset / 2 + delta_x
        center_idx = int(np.argmax(x_axis > center_x))
        lo = max(0, center_idx - n_samp // 2)
        hi = min(nx, center_idx + n_samp // 2)
        tlo = lo + n_samp // 2 - center_idx
        mask[lo:hi, k] = tuk[tlo:tlo + hi - lo]
    return mask


def ref_select_windows(data: np.ndarray, x: np.ndarray, t: np.ndarray,
                       veh_t_idx: np.ndarray, x_track: np.ndarray,
                       t_track: np.ndarray, x0: float, wlen_sw: float = 8.0,
                       length_sw: float = 300.0, spatial_ratio: float = 0.75,
                       temporal_spacing: float | None = None):
    """locate_windows (reference apis/data_classes.py:170-223) on raw arrays.

    ``veh_t_idx``: (nveh, n_track_ch) float arrival sample indices sorted by
    arrival (detection order).  Returns (accepted_ids, window_data_list,
    start_t_indices, x_slice).
    """
    dt = t[1] - t[0]
    spacing = temporal_spacing if temporal_spacing else wlen_sw
    win_nsamp = int(wlen_sw / dt)
    x0_track_idx = int(np.abs(x_track - x0).argmin())

    start_x = x0 - length_sw * spatial_ratio
    end_x = start_x + length_sw
    sxi = int(np.abs(start_x - x).argmin())
    exi = int(np.abs(end_x - x).argmin())

    accepted, wins, starts = [], [], []
    nveh = veh_t_idx.shape[0]
    for k in range(nveh):
        raw = veh_t_idx[k, x0_track_idx]
        if not np.isfinite(raw):
            continue
        t0 = t_track[int(raw)]
        if k < nveh - 1 and np.isfinite(veh_t_idx[k + 1, x0_track_idx]):
            t0_next = t_track[int(veh_t_idx[k + 1, x0_track_idx])]
            if t0_next - t0 < spacing:
                continue
        if k > 0 and np.isfinite(veh_t_idx[k - 1, x0_track_idx]):
            t0_prev = t_track[int(veh_t_idx[k - 1, x0_track_idx])]
            if spacing > t0 - t0_prev >= 0:
                continue
        t0_sw_idx = int(np.abs(t0 - t).argmin())
        if t0_sw_idx < win_nsamp // 2 or t0_sw_idx + win_nsamp // 2 > t.size:
            continue
        st = t0_sw_idx - win_nsamp // 2
        accepted.append(k)
        starts.append(st)
        wins.append(data[sxi:exi, st:st + win_nsamp].copy())
    return accepted, wins, starts, slice(sxi, exi)
