"""NumPy/SciPy oracle backend.

Re-states the reference algorithms (NohPei/das_diff_veh) in plain NumPy so
that (a) every JAX kernel has an executable specification to test against and
(b) the benchmark harness can measure the TPU speedup against the same
baseline the reference would achieve.  Written fresh from the survey of the
reference's behavior — structured as pure functions, not a translation.
"""
