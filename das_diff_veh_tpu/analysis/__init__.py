"""Analysis layer: vehicle classification, ridge extraction, bootstrap
uncertainty, and class summaries — the library form of the reference's
imaging_diff_* / inversion_diff_* notebook logic."""

from das_diff_veh_tpu.analysis.bootstrap import (bootstrap_disp,
                                                 convergence_test,
                                                 sample_indices)
from das_diff_veh_tpu.analysis.class_profiles import (class_psd,
                                                      class_timeseries_stats,
                                                      quasi_static_signatures)
from das_diff_veh_tpu.analysis.classed import (ClassedAnalysis, class_stacks,
                                               classed_analysis)
from das_diff_veh_tpu.analysis.classify import (classify_by_speed,
                                                classify_by_weight,
                                                majority_speed_mask,
                                                majority_weight_mask,
                                                quasi_static_peaks,
                                                vehicle_speeds)
from das_diff_veh_tpu.analysis.ridge import extract_ridge, extract_ridge_batch
