"""Classed vehicle analysis: the imaging_diff_{speed,weight} notebook flow
as one driver.

Reference flow (imaging_diff_speed.ipynb cells 5-18 / imaging_diff_weight
cells 5-18): per-vehicle quasi-static peak signature -> majority filter on
the *other* attribute (weight mode +-0.3 sigma for the speed study, speed
mean +- sigma for the weight study) -> three classes (speed: mean +- sigma;
weight: 1.2 / histogram-mode thresholds) -> per-class quasi-static
time-series stats and averaged Welch PSD.  Window-batch rows map 1:1 to
tracked vehicles (models.windows.select_windows), so speed (from tracks) and
weight (from qs windows) signatures align by row index.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Tuple

import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.analysis import classify as C
from das_diff_veh_tpu.analysis.class_profiles import (class_psd,
                                                      class_timeseries_stats,
                                                      quasi_static_signatures)
from das_diff_veh_tpu.core.section import VehicleTracks, WindowBatch


@dataclass
class ClassedAnalysis:
    """Per-class masks + profiles for one chunk's vehicles."""

    masks: Dict[str, np.ndarray]          # class name -> (max_windows,) bool
    majority: np.ndarray                  # majority-filter mask (pre-split)
    speeds: np.ndarray                    # (max_windows,) m/s (NaN invalid)
    peaks: np.ndarray                     # (max_windows,) qs peak (NaN invalid)
    signatures: np.ndarray                # (max_windows, nt_win)
    ts_stats: Mapping[str, Tuple[np.ndarray, np.ndarray, np.ndarray]]
    psd_freqs: np.ndarray
    psd: Mapping[str, Tuple[np.ndarray, np.ndarray]]


def classed_analysis(qs_batch: WindowBatch, tracks: VehicleTracks, *,
                     by: str = "speed", fs: float = 250.0,
                     nperseg: int = 2048,
                     heavy_threshold: float = 1.2) -> ClassedAnalysis:
    """Run the classed-analysis flow on one chunk's raw-band windows + tracks.

    ``by="speed"``: majority-weight filter, then fast/mid/slow split
    (imaging_diff_speed.ipynb cells 5-8).  ``by="weight"``: majority-speed
    filter, then heavy/mid/light split (imaging_diff_weight.ipynb cells 5-8).
    Profiles (cells 11, 16-18) are computed for the resulting classes.
    """
    assert by in ("speed", "weight")
    sig = quasi_static_signatures(qs_batch)
    peaks = np.asarray(jnp.max(jnp.abs(sig), axis=-1))
    speeds = np.asarray(C.vehicle_speeds(tracks))
    speeds = np.where(np.asarray(qs_batch.valid), speeds, np.nan)

    if by == "speed":
        majority = C.majority_weight_mask(peaks)
        split = np.where(majority, speeds, np.nan)
        fast, mid, slow = C.classify_by_speed(split)
        masks = {"fast": fast, "mid": mid, "slow": slow}
    else:
        majority = C.majority_speed_mask(speeds)
        split = np.where(majority, peaks, np.nan)
        heavy, mid, light = C.classify_by_weight(
            split, heavy_threshold=heavy_threshold)
        masks = {"heavy": heavy, "mid": mid, "light": light}

    ts_stats = class_timeseries_stats(sig, masks)
    freqs, psd = class_psd(np.asarray(qs_batch.data), masks, fs,
                           nperseg=min(nperseg, qs_batch.data.shape[-1]))
    return ClassedAnalysis(masks=masks, majority=np.asarray(majority),
                           speeds=speeds, peaks=peaks,
                           signatures=np.asarray(sig), ts_stats=ts_stats,
                           psd_freqs=np.asarray(freqs), psd=psd)


def class_stacks(per_window: jnp.ndarray, valid,
                 masks: Mapping[str, np.ndarray]) -> Dict[str, jnp.ndarray]:
    """Masked-mean stack of any per-window tensor (gathers or dispersion
    images) per class — the aggregation inside the reference's
    ``save_disp_imgs`` (apis/imaging_classes.py:50-85)."""
    from das_diff_veh_tpu.models.vsg import stack_gathers

    valid = jnp.asarray(valid)
    return {name: stack_gathers(per_window, valid & jnp.asarray(mask))
            for name, mask in masks.items()}
