"""Vehicle speed/weight classification (library form of the notebook logic).

Sources: imaging_diff_speed.ipynb cells 5-8 (quasi-static peak signature,
majority filters, mean±sigma speed classes) and imaging_diff_weight.ipynb
cells 5-8 (1.2 / histogram-mode weight thresholds).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.core.section import VehicleTracks, WindowBatch


def vehicle_speeds(tracks: VehicleTracks) -> jnp.ndarray:
    """Per-vehicle speed [m/s] from the tracked trajectory: least-squares
    slope of arrival time vs position over the valid samples.

    (The reference ships precomputed ``veh_speed`` in its pickles —
    imaging_diff_speed.ipynb cell 2; the tracks are the only source of speed
    in this framework.)
    """
    x = jnp.asarray(tracks.x)
    dt_track = tracks.t[1] - tracks.t[0]

    def one(row):
        valid = jnp.isfinite(row)
        n = jnp.maximum(jnp.sum(valid), 2)
        t_s = jnp.where(valid, row, 0.0) * dt_track
        xm = jnp.sum(jnp.where(valid, x, 0.0)) / n
        tm = jnp.sum(t_s) / n
        cov = jnp.sum(jnp.where(valid, (x - xm) * (t_s - tm), 0.0))
        var = jnp.sum(jnp.where(valid, (x - xm) ** 2, 0.0))
        slowness = cov / jnp.where(var > 0, var, 1.0)         # s/m
        return jnp.where(jnp.abs(slowness) > 1e-9, 1.0 / jnp.abs(slowness), jnp.nan)

    return jax.vmap(one)(tracks.t_idx)


def quasi_static_peaks(qs_batch: WindowBatch, sg_window: int = 101,
                       sg_order: int = 3) -> jnp.ndarray:
    """Quasi-static load signature per window: channel-mean trace ->
    Savitzky-Golay(101,3) -> linear detrend -> re-zero at the first sample ->
    max |.| (imaging_diff_speed.ipynb cell 5).  NaN for invalid windows."""
    from das_diff_veh_tpu.analysis.class_profiles import quasi_static_signatures

    sig = quasi_static_signatures(qs_batch, sg_window=sg_window, sg_order=sg_order)
    return jnp.max(jnp.abs(sig), axis=-1)   # NaN rows (invalid windows) stay NaN


def _hist_mode(values: np.ndarray, bins: int = 100) -> float:
    hist, edges = np.histogram(values, bins=bins)
    return float(edges[int(np.argmax(hist))])


def majority_weight_mask(peaks: np.ndarray, frac_sigma: float = 0.3,
                         bins: int = 100) -> np.ndarray:
    """Keep the majority-weight population: peaks within ±frac_sigma·std of
    the histogram mode (imaging_diff_speed.ipynb cell 6).  Empty/all-NaN
    input yields an all-False mask (no vehicles -> no majority class)."""
    peaks = np.asarray(peaks)
    ok = np.isfinite(peaks)
    if not ok.any():
        return ok
    mode = _hist_mode(peaks[ok], bins)
    sigma = float(np.std(peaks[ok]))
    return ok & (peaks >= mode - frac_sigma * sigma) & (peaks <= mode + frac_sigma * sigma)


def majority_speed_mask(speeds: np.ndarray, n_sigma: float = 1.0) -> np.ndarray:
    """Keep speeds within mean ± n_sigma·std (imaging_diff_weight.ipynb
    cell 5).  Empty/all-NaN input yields an all-False mask."""
    speeds = np.asarray(speeds)
    ok = np.isfinite(speeds)
    if not ok.any():
        return ok
    mu, sd = float(np.mean(speeds[ok])), float(np.std(speeds[ok]))
    return ok & (speeds >= mu - n_sigma * sd) & (speeds <= mu + n_sigma * sd)


def classify_by_speed(speeds: np.ndarray):
    """fast / mid / slow at mean ± std (imaging_diff_speed.ipynb cell 8).
    Returns three boolean masks (all-False on empty/all-NaN input)."""
    speeds = np.asarray(speeds)
    ok = np.isfinite(speeds)
    if not ok.any():
        return ok, ok.copy(), ok.copy()
    hi = float(np.mean(speeds[ok]) + np.std(speeds[ok]))
    lo = float(np.mean(speeds[ok]) - np.std(speeds[ok]))
    fast = ok & (speeds > hi)
    mid = ok & (speeds <= hi) & (speeds > lo)
    slow = ok & (speeds <= lo)
    return fast, mid, slow


def classify_by_weight(peaks: np.ndarray, heavy_threshold: float = 1.2,
                       bins: int = 100):
    """heavy / mid / light: > 1.2, (mode, 1.2], <= histogram mode
    (imaging_diff_weight.ipynb cell 8).  Returns three boolean masks
    (all-False on empty/all-NaN input)."""
    peaks = np.asarray(peaks)
    ok = np.isfinite(peaks)
    if not ok.any():
        return ok, ok.copy(), ok.copy()
    mode = _hist_mode(peaks[ok], bins)
    heavy = ok & (peaks > heavy_threshold)
    mid = ok & (peaks <= heavy_threshold) & (peaks > mode)
    light = ok & (peaks <= mode)
    return heavy, mid, light
