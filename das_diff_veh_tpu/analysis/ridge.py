"""Dispersion-curve (ridge) extraction from f-v maps.

Parity re-design of ``extract_ridge_ref_idx`` (reference
modules/utils.py:621-678): velocity axis reversed to descending; three modes —

- no reference index: plain argmax per frequency below ``vel_max``;
- reference index: pick the global argmax at the reference frequency, then
  walk backward and forward extracting the argmax within ±sigma of the
  previous pick (mode tracking) — the sequential walks become two
  ``lax.scan``s;
- reference curve ``ref_vel(freq)``: masked argmax around the supplied curve
  per frequency (vectorized).

All masked argmaxes use a -inf fill, which matches the reference's
first-of-max tie behavior on the compacted subarray.  The picked curve is
Savitzky-Golay(25,2) smoothed, as in the reference (:676).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp

from das_diff_veh_tpu.ops.savgol import savgol_filter


def _masked_argmax_vel(col: jnp.ndarray, vel: jnp.ndarray, center, sigma: float):
    mask = (vel > center - sigma) & (vel < center + sigma)
    score = jnp.where(mask, col, -jnp.inf)
    return vel[jnp.argmax(score)]


def extract_ridge(freq: np.ndarray, vel: np.ndarray, fv_map: jnp.ndarray,
                  ref_freq_idx: Optional[int] = None, sigma: float = 25.0,
                  vel_max: float = 400.0,
                  ref_vel: Optional[Callable] = None,
                  sg_window: int = 25, sg_order: int = 2) -> jnp.ndarray:
    """Extract the ridge curve (len(freq),) from ``fv_map`` (nvel, nfreq)."""
    freq = np.asarray(freq)
    vel_rev = np.asarray(vel)[::-1]
    fv = fv_map[::-1, :]                                  # match reversed vel

    if ref_freq_idx is None and ref_vel is None:
        max_idx = int(np.abs(vel_max - vel_rev).argmin())
        sub_vel = jnp.asarray(vel_rev[max_idx:].copy())
        return sub_vel[jnp.argmax(fv[max_idx:], axis=0)]

    vel_j = jnp.asarray(vel_rev.copy())
    if ref_vel is not None:
        centers = jnp.asarray(ref_vel(freq))
        picked = jax.vmap(lambda col, c: _masked_argmax_vel(col, vel_j, c, sigma),
                          in_axes=(1, 0))(fv, centers)
    else:
        nf = freq.shape[0]
        v0 = vel_j[jnp.argmax(fv[:, ref_freq_idx])]

        def walk(cols):
            def step(prev, col):
                v = _masked_argmax_vel(col, vel_j, prev, sigma)
                return v, v
            _, picks = jax.lax.scan(step, v0, cols)
            return picks

        back = walk(jnp.flip(fv[:, :ref_freq_idx], axis=1).T)  # ref-1 ... 0
        fwd = walk(fv[:, ref_freq_idx + 1:].T)                 # ref+1 ... nf-1
        picked = jnp.concatenate([jnp.flip(back), jnp.asarray([v0]), fwd])
        assert picked.shape[0] == nf
    return savgol_filter(picked[None, :], sg_window, sg_order, axis=-1)[0]
