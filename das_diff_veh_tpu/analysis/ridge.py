"""Dispersion-curve (ridge) extraction from f-v maps.

Parity re-design of ``extract_ridge_ref_idx`` (reference
modules/utils.py:621-678): velocity axis reversed to descending; three modes —

- no reference index: plain argmax per frequency below ``vel_max``;
- reference index: pick the global argmax at the reference frequency, then
  walk backward and forward extracting the argmax within ±sigma of the
  previous pick (mode tracking) — the sequential walks become two
  ``lax.scan``s;
- reference curve ``ref_vel(freq)``: masked argmax around the supplied curve
  per frequency (vectorized).

All masked argmaxes use a -inf fill, which matches the reference's
first-of-max tie behavior on the compacted subarray.  The picked curve is
Savitzky-Golay(25,2) smoothed, as in the reference (:676).

Layout: host-side preparation (axis reversal, band geometry, reference-curve
evaluation) is split from the traced core so the bootstrap can run MANY maps
through one jitted batched program (:func:`extract_ridge_batch`) instead of
re-tracing per repetition — the reference's heaviest workload (SURVEY §3.3
convergence study) hits this path 1800 times per class.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.ops.savgol import savgol_filter


def _masked_argmax_vel(col: jnp.ndarray, vel: jnp.ndarray, center, sigma: float):
    mask = (vel > center - sigma) & (vel < center + sigma)
    score = jnp.where(mask, col, -jnp.inf)
    return vel[jnp.argmax(score)]


def _core(fv, vel_rev, centers, max_idx: Optional[int],
          ref_freq_idx: Optional[int], sigma: float,
          sg_window: int, sg_order: int):
    """Traced ridge core on ONE already-velocity-reversed map (nvel, nfreq).

    Exactly one of the three modes is active (static dispatch):
    ``max_idx`` (plain argmax), ``centers`` (masked argmax around a
    reference curve), or ``ref_freq_idx`` (two lax.scan walks).
    """
    if max_idx is not None:
        sub_vel = vel_rev[max_idx:]
        return sub_vel[jnp.argmax(fv[max_idx:], axis=0)]

    if centers is not None:
        picked = jax.vmap(
            lambda col, c: _masked_argmax_vel(col, vel_rev, c, sigma),
            in_axes=(1, 0))(fv, centers)
    else:
        v0 = vel_rev[jnp.argmax(fv[:, ref_freq_idx])]

        def walk(cols):
            def step(prev, col):
                v = _masked_argmax_vel(col, vel_rev, prev, sigma)
                return v, v
            _, picks = jax.lax.scan(step, v0, cols)
            return picks

        back = walk(jnp.flip(fv[:, :ref_freq_idx], axis=1).T)  # ref-1 ... 0
        fwd = walk(fv[:, ref_freq_idx + 1:].T)                 # ref+1 ...
        picked = jnp.concatenate([jnp.flip(back), v0[None], fwd])
    return savgol_filter(picked[None, :], sg_window, sg_order, axis=-1)[0]


def _prep(freq: np.ndarray, vel: np.ndarray, ref_freq_idx, vel_max: float,
          ref_vel):
    """Host-side geometry shared by the single and batched entry points."""
    freq = np.asarray(freq)
    vel_rev = np.asarray(vel)[::-1].copy()
    centers = max_idx = None
    if ref_freq_idx is None and ref_vel is None:
        max_idx = int(np.abs(vel_max - vel_rev).argmin())
        ref_freq_idx = None
    elif ref_vel is not None:
        # accept a callable c(f) (reference interp1d curves) or a
        # precomputed per-frequency center array
        centers = jnp.asarray(ref_vel(freq) if callable(ref_vel)
                              else np.asarray(ref_vel))
        ref_freq_idx = None
    return freq, jnp.asarray(vel_rev), centers, max_idx, ref_freq_idx


def extract_ridge(freq: np.ndarray, vel: np.ndarray, fv_map: jnp.ndarray,
                  ref_freq_idx: Optional[int] = None, sigma: float = 25.0,
                  vel_max: float = 400.0,
                  ref_vel: Optional[Callable] = None,
                  sg_window: int = 25, sg_order: int = 2) -> jnp.ndarray:
    """Extract the ridge curve (len(freq),) from ``fv_map`` (nvel, nfreq)."""
    freq, vel_rev, centers, max_idx, ref_freq_idx = _prep(
        freq, vel, ref_freq_idx, vel_max, ref_vel)
    out = _core(fv_map[::-1, :], vel_rev, centers, max_idx,
                None if ref_freq_idx is None else int(ref_freq_idx),
                float(sigma), sg_window, sg_order)
    if ref_freq_idx is not None:
        assert out.shape[0] == freq.shape[0]
    return out


@partial(jax.jit, static_argnames=("max_idx", "ref_freq_idx", "sigma",
                                   "sg_window", "sg_order", "serial"))
def _ridge_batch(fv_maps, vel_rev, centers, max_idx, ref_freq_idx,
                 sigma, sg_window, sg_order, serial):
    f = lambda fv: _core(fv[::-1, :], vel_rev, centers, max_idx,
                         ref_freq_idx, sigma, sg_window, sg_order)
    if serial:
        return jax.lax.map(f, fv_maps)
    return jax.vmap(f)(fv_maps)


def extract_ridge_batch(freq: np.ndarray, vel: np.ndarray,
                        fv_maps: jnp.ndarray,
                        ref_freq_idx: Optional[int] = None,
                        sigma: float = 25.0, vel_max: float = 400.0,
                        ref_vel: Optional[Callable] = None,
                        sg_window: int = 25, sg_order: int = 2,
                        serial: Optional[bool] = None) -> jnp.ndarray:
    """Ridges for a whole (n_maps, nvel, nfreq) batch through ONE compiled
    program (module-level jit: repeated calls with the same shapes and
    band settings re-use the executable — the convergence study makes 60
    such calls).  ``serial`` maps sequentially (``lax.map``) instead of
    vmapping; default: serial on CPU (the XLA CPU compiler struggles with
    wide gather-heavy batches), vectorized elsewhere."""
    if serial is None:
        serial = jax.default_backend() == "cpu"
    freq, vel_rev, centers, max_idx, ref_freq_idx = _prep(
        freq, vel, ref_freq_idx, vel_max, ref_vel)
    return _ridge_batch(fv_maps, vel_rev, centers, max_idx,
                        None if ref_freq_idx is None else int(ref_freq_idx),
                        float(sigma), sg_window, sg_order, bool(serial))
