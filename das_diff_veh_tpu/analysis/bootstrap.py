"""Bootstrap dispersion uncertainty + convergence study.

The reference recomputes every virtual shot gather for every bootstrap
repetition (apis/imaging_classes.py:31-36: bt_times × bt_size full gather
builds).  Stacking is linear in the per-window gathers, so this module
computes each window's gather ONCE and resamples *stacks* — algebraically
identical, ~bt_times× cheaper (SURVEY.md §7 step 9) — then images and
ridge-extracts per repetition.

Every device stage is a module-level jitted function, so repeated calls with
the same shapes re-use their executables.  The convergence study
(imaging_diff_speed.ipynb cells 30-33 — the reference's single heaviest
workload, SURVEY §3.3) exploits this by padding every repetition's index row
to ``max_sample_num`` with a per-row count mask: all 60 ``bt_size`` sweeps
share ONE compiled program instead of retracing per size.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.analysis.ridge import extract_ridge_batch
from das_diff_veh_tpu.config import BootstrapConfig, DispersionConfig
from das_diff_veh_tpu.models.vsg import gather_disp_image


def sample_indices(n_windows: int, bt_size: int, bt_times: int,
                   rng: np.random.Generator,
                   exclude_first: bool = True) -> np.ndarray:
    """(bt_times, bt_size) resampling matrix, without replacement per rep.

    ``exclude_first`` mirrors the reference's ``random.sample(range(1, n))``
    (apis/imaging_classes.py:32) which never samples window 0.
    """
    lo = 1 if exclude_first else 0
    if bt_size > n_windows - lo:
        raise ValueError(f"bt_size={bt_size} > available windows {n_windows - lo}")
    return np.stack([rng.choice(np.arange(lo, n_windows), size=bt_size,
                                replace=False) for _ in range(bt_times)])


@jax.jit
def _resample_stacks(gathers, idx):
    """(bt_times, ...) mean-stacks of ``gathers[idx[r]]`` per repetition."""
    return jax.vmap(lambda sel: jnp.mean(gathers[sel], axis=0))(idx)


@jax.jit
def _resample_stacks_counts(gathers, idx, counts):
    """Masked variant: row r averages ``gathers[idx[r, :counts[r]]]``.

    Index rows are padded to a common static width so every ``bt_size``
    shares one executable; padded slots point at a valid window and are
    masked out of the mean.
    """
    mask = jnp.arange(idx.shape[1])[None, :] < counts[:, None]

    def one(sel, m, c):
        g = gathers[sel]
        return jnp.sum(g * m[(...,) + (None,) * (g.ndim - 1)], axis=0) / c

    return jax.vmap(one)(idx, mask, counts)


@partial(jax.jit, static_argnames=("offsets", "dt", "dx", "disp_cfg",
                                   "start_x", "end_x"))
def _image_batch(stacks, offsets, dt, dx, disp_cfg, start_x, end_x):
    """Dispersion images of a stack batch; serial ``lax.map`` body — a
    traced fancy-index gather of a closed-over array combined with FFTs
    inside one map body segfaults the XLA CPU compiler, so the gather stage
    (:func:`_resample_stacks`) stays a separate program."""
    off = np.asarray(offsets)
    return jax.lax.map(
        lambda s: gather_disp_image(s, off, dt, dx, disp_cfg,
                                    start_x, end_x),
        stacks)


def bootstrap_disp(gathers: jnp.ndarray, offsets: np.ndarray, dt: float,
                   dx: float, idx_matrix: np.ndarray,
                   cfg: BootstrapConfig = BootstrapConfig(),
                   disp_cfg: DispersionConfig = DispersionConfig(),
                   ref_vel: Optional[Sequence] = None,
                   disp_start_x: float = -150.0, disp_end_x: float = 0.0,
                   counts: Optional[np.ndarray] = None):
    """Per-mode bootstrap ridge curves.

    ``gathers``: (n_windows, nch_out, wlen) precomputed per-window VSGs.
    ``idx_matrix``: (bt_times, bt_size) window indices per repetition.
    ``counts``: optional (bt_times,) — row r uses only its first
    ``counts[r]`` indices (rows padded to a common width; see
    :func:`convergence_test`).
    Returns ``(ridges, freqs)`` where ``ridges[mode]`` is (bt_times,
    n_freqs_in_band) and ``freqs`` is the full scan axis.
    """
    freqs = np.arange(disp_cfg.freq_min, disp_cfg.freq_max, disp_cfg.freq_step)
    vels = np.arange(disp_cfg.vel_min, disp_cfg.vel_max, disp_cfg.vel_step)
    idx = jnp.asarray(np.asarray(idx_matrix))
    n_modes = len(cfg.freq_lb)
    if ref_vel is None:
        ref_vel = [None] * n_modes

    if counts is None:
        stacks = _resample_stacks(gathers, idx)
    else:
        stacks = _resample_stacks_counts(gathers, idx,
                                         jnp.asarray(np.asarray(counts)))
    images = _image_batch(stacks, tuple(np.asarray(offsets).tolist()),
                          float(dt), float(dx), disp_cfg,
                          float(disp_start_x), float(disp_end_x))

    ridges: List[np.ndarray] = []
    for m in range(n_modes):
        band = (freqs >= cfg.freq_lb[m]) & (freqs < cfg.freq_ub[m])
        # reference: ref index shifted into the band frame
        # (apis/imaging_classes.py:45)
        ref_idx = int(cfg.ref_freq_idx[m] - np.sum(freqs < cfg.freq_lb[m]))
        rv = ref_vel[m]
        ridges.append(np.asarray(extract_ridge_batch(
            freqs[band], vels, images[:, :, band],
            ref_freq_idx=None if rv is not None else ref_idx,
            sigma=float(cfg.sigma[m]), vel_max=cfg.vel_max, ref_vel=rv)))
    return ridges, freqs


def convergence_test(gathers: jnp.ndarray, offsets: np.ndarray, dt: float,
                     dx: float, max_sample_num: int, bt_times: int,
                     rng: np.random.Generator,
                     cfg: BootstrapConfig = BootstrapConfig(),
                     disp_cfg: DispersionConfig = DispersionConfig(),
                     ref_vel: Optional[Sequence] = None) -> np.ndarray:
    """Bootstrap spread vs sample count (imaging_diff_speed.ipynb cell 30):
    for bt_size = 1..max, run the bootstrap and record the summed per-mode
    ridge standard deviation.  Returns (n_modes, max_sample_num).

    Every index matrix is padded to ``max_sample_num`` columns with a count
    mask, so all sweeps share the jitted stages' executables — one compile
    for the whole study instead of one per ``bt_size``.
    """
    n_modes = len(cfg.freq_lb)
    out = np.empty((n_modes, max_sample_num))
    for bt_size in range(1, max_sample_num + 1):
        idx = sample_indices(gathers.shape[0], bt_size, bt_times, rng)
        pad = np.broadcast_to(idx[:, :1], (bt_times, max_sample_num - bt_size))
        idx = np.concatenate([idx, pad], axis=1)
        ridges, _ = bootstrap_disp(gathers, offsets, dt, dx, idx, cfg,
                                   disp_cfg, ref_vel,
                                   counts=np.full(bt_times, bt_size))
        for m in range(n_modes):
            out[m, bt_size - 1] = float(np.sum(np.std(ridges[m], axis=0)))
    return out
