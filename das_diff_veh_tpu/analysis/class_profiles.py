"""Per-class quasi-static time-series and PSD profiles.

Library form of the notebook analysis cells the reference runs per vehicle
class: the mean quasi-static deformation trace with a spread band
(imaging_diff_speed.ipynb cell 11) and the per-class averaged Welch PSD with
a min/max envelope (cells 16-18).  The per-vehicle signature is the same
channel-mean -> Savitzky-Golay(101,3) -> detrend -> re-zero trace whose peak
drives the weight classifier (cell 5).
"""

from __future__ import annotations

from typing import Dict, Mapping, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.core.section import WindowBatch
from das_diff_veh_tpu.ops.psd import welch_psd
from das_diff_veh_tpu.ops.savgol import savgol_filter


def quasi_static_signatures(qs_batch: WindowBatch, sg_window: int = 101,
                            sg_order: int = 3) -> jnp.ndarray:
    """Per-window quasi-static signature trace (nwin, nt): channel mean ->
    SG(101,3) -> linear detrend -> re-zero at the first sample
    (imaging_diff_speed.ipynb cell 5 — whose ``max|.|`` is the weight peak)."""
    from das_diff_veh_tpu.ops.filters import detrend_linear

    def one(data):
        m = jnp.mean(data, axis=0)
        sm = savgol_filter(m[None, :], sg_window, sg_order, axis=-1)[0]
        d = detrend_linear(sm[None, :])[0]
        return d - d[0]

    sig = jax.vmap(one)(qs_batch.data)
    return jnp.where(qs_batch.valid[:, None], sig, jnp.nan)


def class_timeseries_stats(signatures, class_masks: Mapping[str, np.ndarray]
                           ) -> Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Per-class (mean, std, 95% CI) over the vehicle axis of the signature
    traces (imaging_diff_speed.ipynb cell 11).  Classes with no members map
    to NaN arrays rather than raising."""
    sig = np.asarray(signatures)
    out = {}
    for name, mask in class_masks.items():
        mask = np.asarray(mask, dtype=bool)
        rows = sig[mask]
        rows = rows[np.isfinite(rows).all(axis=-1)] if rows.size else rows
        if rows.shape[0] == 0:
            nanrow = np.full(sig.shape[-1], np.nan)
            out[name] = (nanrow, nanrow.copy(), nanrow.copy())
            continue
        mean = rows.mean(axis=0)
        std = rows.std(axis=0)
        # CI needs a sample-spread estimate: NaN for n=1 rather than a
        # zero-width band implying perfect certainty
        if rows.shape[0] > 1:
            ci = 1.96 * rows.std(axis=0, ddof=1) / np.sqrt(rows.shape[0])
        else:
            ci = np.full(sig.shape[-1], np.nan)
        out[name] = (mean, std, ci)
    return out


def class_psd(window_data, class_masks: Mapping[str, np.ndarray], fs: float,
              nperseg: int = 2048):
    """Per-class Welch PSD profile (imaging_diff_speed.ipynb cells 16-18).

    ``window_data``: (nwin, nch, nt).  For each class: PSD per channel per
    window (scipy-default Welch), mean over channels -> per-window PSDs, then
    the class average — the reference's ``win_avg_psd`` restricted to the
    class members.  Returns ``(freqs, {name: (avg, per_window)})``; empty
    classes yield NaN avg and an empty per-window array.  Windows whose PSD
    is non-finite (e.g. NaN-padded invalid batch slots caught by a too-wide
    mask) are dropped per class rather than poisoning the average.
    """
    data = jnp.asarray(window_data)
    freqs, p = welch_psd(data, fs, nperseg=nperseg)      # (nwin, nch, nf)
    per_window = np.asarray(jnp.mean(p, axis=1))         # (nwin, nf)
    freqs = np.asarray(freqs)
    finite = np.isfinite(per_window).all(axis=-1)
    out = {}
    for name, mask in class_masks.items():
        rows = per_window[np.asarray(mask, dtype=bool) & finite]
        if rows.shape[0] == 0:
            out[name] = (np.full(freqs.shape, np.nan), rows)
        else:
            out[name] = (rows.mean(axis=0), rows)
    return freqs, out
