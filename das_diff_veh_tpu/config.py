"""Typed configuration tree for the whole pipeline.

The reference passes nested dicts with ``.get(key, default)`` lookups and many
hardcoded constants (reference: apis/timeLapseImaging.py:14-19 interrogator
table, apis/imaging_workflow.py:14-20 tracking params, hardcoded dx=8.16 at
apis/virtual_shot_gather.py:257). Here every knob lives in one frozen
dataclass tree so jitted functions can treat configs as static arguments.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class InterrogatorConfig:
    """Channel geometry of one interrogator (reference: apis/timeLapseImaging.py:14-19)."""

    name: str = "odh3"
    start_ch: int = 400          # first physical channel of the fiber section
    dx: float = 8.16             # channel spacing [m]
    fs: float = 250.0            # sampling rate [Hz]


@dataclass(frozen=True)
class DetectConfig:
    """Peak detection knobs (reference: apis/imaging_workflow.py:14-20)."""

    min_prominence: float = 0.2
    min_separation: int = 50          # samples between peaks
    prominence_wlen: int = 600        # window for prominence evaluation
    height: Optional[float] = None
    max_peaks: int = 64               # static capacity for jit (padding)


@dataclass(frozen=True)
class TrackingConfig:
    """Kalman-filter vehicle tracking (reference: apis/tracking.py:21-168)."""

    detect: DetectConfig = field(default_factory=DetectConfig)
    n_detect_channels: int = 15       # channels stacked for initial detection
    likelihood_sigma: float = 0.08    # KDE sigma [s] for detection stacking
    sigma_a: float = 0.01             # process-noise scale
    channel_stride: int = 3           # march every `stride` channels
    gate_lo: float = -15.0            # association gate (samples), asymmetric
    gate_hi: float = 30.0
    meas_noise: float = 1.0           # R
    max_vehicles: int = 64            # static capacity for jit
    # The reference's "prefer smallest positive lag" pick indexes the gate
    # subset with a positive-subset index (apis/tracking.py:132-135), so with
    # mixed-sign lags in the gate it actually records the *first* gated peak.
    # True reproduces that behavior bit-for-bit; False implements the intent.
    assoc_bug_compat: bool = True


@dataclass(frozen=True)
class TrackQCConfig:
    """Track sanity rejection (reference: modules/car_tracking_utils.py:38-66)."""

    min_valid_fraction: float = 0.3
    retrograde_window: int = 20
    retrograde_threshold: float = -15.0
    min_travel_samples: float = 30.0
    max_adjacent_nan: int = 20
    max_jump: float = 20.0


@dataclass(frozen=True)
class TrackingPreprocessConfig:
    """Quasi-static band preprocessing for tracking (reference: apis/timeLapseImaging.py:74-102)."""

    flo: float = 0.08                 # temporal band [Hz]
    fhi: float = 1.0
    subsample: int = 5                # 250 Hz -> 50 Hz
    target_dx: float = 1.0            # spatial resample 8.16 m -> 1 m
    flo_space: float = 0.006          # spatial band [cycles/m]
    fhi_space: float = 0.04
    noise_level: float = 10.0         # channel kill threshold (median abs)
    empty_threshold: float = 30.0


@dataclass(frozen=True)
class SurfaceWavePreprocessConfig:
    """Surface-wave band preprocessing (reference: apis/timeLapseImaging.py:51-71)."""

    flo: float = 1.2                  # [Hz]
    fhi: float = 30.0
    noise_threshold: float = 5.0
    impute_noisy: bool = True
    impute_empty: bool = True
    normalize_traces: bool = True     # per-trace L2 norm (surface_wave method)


@dataclass(frozen=True)
class WindowConfig:
    """Per-vehicle surface-wave window geometry (reference: apis/data_classes.py:126-223)."""

    wlen_sw: float = 8.0              # window length [s]
    length_sw: float = 300.0          # window spatial extent [m]
    spatial_ratio: float = 0.75       # fraction of length_sw behind the pivot
    temporal_spacing: Optional[float] = None  # isolation spacing [s]; None -> wlen_sw


@dataclass(frozen=True)
class MuteConfig:
    """Trajectory-aware muting (reference: apis/data_classes.py:49-104).

    ``offset=300`` is the aggregation-path default (reference
    apis/imaging_classes.py:96 ``mute_offset=300``); the SurfaceWaveWindow
    method defaults are offset=200 with alpha=0.3 (single-sided, :49) /
    alpha=0.05 (double-sided, :74).
    """

    offset: float = 300.0             # taper width [m]
    alpha: float = 0.3                # tukey shape, single-sided mute
    alpha_double: float = 0.05        # tukey shape, double-sided mute
    delta_x: float = 20.0             # asymmetric center shift [m]
    time_alpha: float = 0.3


@dataclass(frozen=True)
class GatherConfig:
    """Virtual-shot-gather interferometry (reference: apis/virtual_shot_gather.py:145-192)."""

    wlen: float = 2.0                 # correlation window [s]
    time_window: float = 4.0          # data span fed to xcorr [s]
    delta_t: float = 1.0              # pivot-time offset [s]
    overlap_ratio: float = 0.5
    norm: bool = True                 # per-trace L2 norm of the gather
    norm_amp: bool = True             # normalize by pivot-trace max
    include_other_side: bool = True
    far_offset: float = 75.0          # gather far end beyond the pivot [m]
                                      # (reference end_x = x0 + 75, notebook
                                      # save_disp_imgs / bootstrap geometry)

    traj_gather: str = "auto"
    """Window-cut engine for the trajectory-following correlations
    (``ops.xcorr.xcorr_traj_follow``).  ``"serialized"``: the legacy vmapped
    ``dynamic_slice`` cut — an O(nch) serialized slice chain on TPU, the
    pipeline's measured hottest op (docs/PERF.md).  ``"fused"``: the Pallas
    scalar-prefetch gather kernel (``ops.pallas_gather``) — per-channel
    window starts ride a prefetched scalar operand so one kernel sweep cuts
    every channel's window at its own offset (interpret-mode fallback
    off-TPU).  ``"auto"``: fused on TPU backends when the shape is in the
    kernel's bounds (``ops.pallas_gather.fused_supported``: nwin within the
    per-step unroll cap, dot-finish VMEM budget), serialized elsewhere —
    an out-of-bounds shape on TPU silently takes the serialized path
    rather than erroring.  Execution knob, not physics: fused/serialized
    parity is pinned at the oracle bar (<= 1e-7) by
    tests/test_pallas_gather.py."""

    traj_gather_finish: str = "rfft"
    """Correlate finish of the fused gather path.  ``"rfft"`` (default):
    the kernel emits packed window tensors and the batched-rfft circular
    correlate finishes outside — numerically the serialized path with the
    cut swapped out.  ``"dot"``: the circular correlation finishes
    in-kernel as an MXU dot against the doubled source-window matrix
    (small windows only: ``wlen <= dot_max_wlen`` and
    ``nwin*wlen^2 <= dot_max_matrix_elems``, the joint VMEM budget of the
    in-kernel matrix; time-domain float rounding applies, see tests for
    the pinned tolerance)."""

    fused_max_nwin: int = 64
    """Per-kernel-step unroll cap of the fused gather (the former
    ``ops.pallas_gather.FUSED_MAX_NWIN`` module constant, hoisted so the
    tuner can sweep it per backend/geometry — docs/TUNING.md).  Shapes with
    more windows than this take the serialized path under
    ``traj_gather="auto"``.  Execution knob: participates in the runtime
    config hash via the dataclass repr."""

    dot_max_wlen: int = 256
    """VMEM budget cap on the window length admitted to the in-kernel
    ``"dot"`` finish (former ``DOT_MAX_WLEN`` constant; tunable knob)."""

    dot_max_matrix_elems: int = 1 << 20
    """Joint VMEM budget cap ``nwin * wlen^2`` of the in-kernel doubled
    source matrix for the ``"dot"`` finish (former ``DOT_MAX_MATRIX_ELEMS``
    constant; tunable knob)."""

    precision: str = "f32"
    """MXU precision tier of the fused gather's ``"dot"`` finish.
    ``"f32"`` (default): full float32 operands, HIGHEST precision — the
    parity tier, bit-identical to the pre-tier behavior.  ``"bf16"``:
    bfloat16 operands with float32 accumulation
    (``preferred_element_type``) — trades last-digit parity for MXU
    throughput under the per-stage error budget committed in
    tests/test_precision.py and disclosed in docs/TUNING.md.  NOT swept by
    the tuner (accuracy is an operator decision, not a timing winner)."""


@dataclass(frozen=True)
class DispersionConfig:
    """f-v transform scan grid (reference: apis/dispersion_classes.py:11, virtual_shot_gather.py:247)."""

    freq_min: float = 0.8
    freq_max: float = 25.0
    freq_step: float = 0.1
    vel_min: float = 200.0
    vel_max: float = 1200.0
    vel_step: float = 1.0
    sg_window: int = 25               # savgol smoothing along frequency
    sg_order: int = 4
    # The reference's production imaging paths call map_fv with norm=False
    # (apis/dispersion_classes.py:29-31, virtual_shot_gather.py:253-256 pass
    # no norm argument; modules/utils.py:457 defaults norm=False).
    norm: bool = False                # L1 trace norm before transform
    # "fk": reference-parity map_fv (2-D FFT + bilinear k=f/v sampling);
    # "phase_shift": frequency-domain slant stack (Park et al.), no padded
    # 2-D FFT and no gather (see ops/dispersion.py).  Measured on v5e at the
    # reference problem size, "fk" is the faster of the two (bench.py
    # stage_disp_image_* keys) as well as the parity path.
    method: str = "fk"

    precision: str = "f32"
    """Precision tier of the slant-stack contractions (``ops.dispersion``).
    ``"f32"`` (default): HIGHEST-precision float32 — the parity tier,
    bit-identical to the pre-tier behavior.  ``"bf16"``: bfloat16 operands
    into the f-k bilinear-sampling matmuls / phase-shift steering einsum
    with float32 accumulation; error budget committed in
    tests/test_precision.py and disclosed in docs/TUNING.md.  Unlike
    ``method`` this is an execution tier — but it DOES move last digits,
    so it is an explicit operator opt-in and the tuner never sweeps it."""

    @property
    def n_freqs(self) -> int:
        import numpy as np
        return int(np.arange(self.freq_min, self.freq_max, self.freq_step).size)

    @property
    def n_vels(self) -> int:
        import numpy as np
        return int(np.arange(self.vel_min, self.vel_max, self.vel_step).size)


@dataclass(frozen=True)
class ImagingConfig:
    """One pivot's imaging geometry (reference: imaging_diff_speed.ipynb cell 2)."""

    x0: float = 700.0                 # pivot along fiber [m]
    tracking_offset: float = 200.0    # start_x = x0 - offset, end_x = x0 + offset
    disp_start_x: float = -150.0      # offsets fed to the dispersion transform
    disp_end_x: float = 0.0

    @property
    def start_x(self) -> float:
        return self.x0 - self.tracking_offset

    @property
    def end_x(self) -> float:
        return self.x0 + self.tracking_offset


@dataclass(frozen=True)
class BootstrapConfig:
    """Bootstrap uncertainty (reference: apis/imaging_classes.py:8-48, notebook cell 25)."""

    bt_times: int = 30
    bt_size: int = 60
    sigma: Tuple[float, ...] = (25.0, 50.0, 50.0, 50.0)
    ref_freq_idx: Tuple[int, ...] = (80, 130, 170, 170)
    freq_lb: Tuple[float, ...] = (2.5, 10.0, 14.0, 16.0)
    freq_ub: Tuple[float, ...] = (14.0, 15.0, 19.0, 20.0)
    vel_max: float = 800.0


@dataclass(frozen=True)
class RingConfig:
    """Multi-chip all-pairs ring pipeline knobs (``parallel.allpairs``).

    Execution knobs, not physics: on the kernel path every mode/buffering
    choice produces bit-identical peaks (pinned by tests/test_parallel.py
    on the 8-device CPU mesh); the einsum fallback agrees across choices
    to dot_general reduction-order tolerance (~1e-7 relative, held to 2e-5
    in tests).  They trade per-device memory against collective traffic.
    """

    mode: str = "ring"
    """``"ring"``: each device keeps only its own nch/D receiver-spectra
    shard and the shards rotate around the mesh via ``lax.ppermute`` —
    per-device receiver memory is O(nch/D).  ``"replicated"``: the pre-ring
    layout (full receiver set on every device, no collectives in the loop) —
    per-device memory O(nch), kept for A/B benchmarking and single-chip
    deployments where the broadcast is free."""

    double_buffer: bool = True
    """Issue step k+1's receiver-shard ``ppermute`` before step k's
    correlation so XLA's latency-hiding scheduler overlaps the ICI transfer
    with the Pallas compute (the ring-attention decomposition).  False
    gates each rotation on the finished correlation through a
    ``lax.optimization_barrier`` so transfer and compute truly serialize —
    only useful for isolating ICI time in a profile (without the barrier
    both orderings trace to the same dependency graph)."""

    lagmax_block: Optional[int] = None
    """Receiver rows per fused irfft + Pallas lag-max pass inside the peak
    finish (``ops.pallas_xcorr.peak_from_spectra``).  None = fuse on the
    kernel path with the default block; 0 = unfused XLA finish; >0 = that
    block size."""

    win_block: Optional[int] = None
    """Windows per correlation-kernel grid step
    (``ops.pallas_xcorr`` spectra-tile kernel; also batches the einsum
    fallback).  None = the auto heuristic (stream long records in blocks,
    single pass otherwise); >0 pins that block size.  Hoisted into config
    so the tuner can sweep it per backend/geometry (docs/TUNING.md);
    participates in the runtime config hash via the dataclass repr."""

    lag_tile_max: int = 512
    """Upper bound of the lag-axis tile auto-sizing in the fused Pallas
    lag-max finish (former ``ops.pallas_xcorr._PEAK_TILE_L`` constant;
    tunable knob).  The tile grows by doubling from the 128-lane floor
    while it divides the padded lag span, capped here."""

    precision: str = "f32"
    """Precision tier of the ring correlation.  ``"f32"`` (default): full
    float32 spectra planes, HIGHEST-precision einsum fallback — the parity
    tier, bit-identical to the pre-tier behavior.  ``"bf16"``: bfloat16
    planar spectra with float32 accumulation — halves the HBM/VMEM
    footprint of the receiver planes the ring rotates; error budget
    committed in tests/test_precision.py.  Not swept by the tuner."""


@dataclass(frozen=True)
class HealthConfig:
    """Input-health sentinel knobs (``das_diff_veh_tpu.resilience.health``).

    Unlike :class:`ObsConfig` these are NOT pure execution knobs: masking an
    unhealthy channel changes output values (that is the point — a NaN
    channel would otherwise poison every FFT it touches), so ``health``
    lives in :class:`PipelineConfig` and participates in the resume
    manifest's config hash.  Disabled by default: the sentinel then costs
    one attribute check and zero extra device dispatches
    (counter-asserted in tests/test_resilience.py).
    """

    enabled: bool = False
    """Master switch.  When True, every chunk/request is screened by ONE
    fused jitted program (NaN/Inf counts, flatline variance, clipping
    fraction per channel) and unhealthy channels are masked before the
    gather/VSG/stack path sees them."""

    flatline_var: float = 0.0
    """A channel whose peak-to-peak span is <= this is flagged
    dead/flatline (0.0 catches exactly-constant channels — a dead
    interrogator output — bit-robustly, which a variance threshold would
    miss to mean-subtraction roundoff)."""

    clip_limit: float = 0.0
    """Absolute amplitude at which a sample counts as clipped/saturated.
    0.0 disables clip detection (npz units vary per deployment; set it to
    the interrogator's full-scale value)."""

    clip_fraction_max: float = 0.05
    """A channel with more than this fraction of clipped samples is flagged
    saturated (only with ``clip_limit`` > 0)."""

    impute: bool = True
    """Replace masked channels by the sum of their immediate neighbors
    (the ``ops.qc.impute_traces`` rule, mirroring the reference — note:
    sum, not average, so an interior imputed channel carries roughly the
    combined neighbor amplitude) instead of leaving them zero.  Either
    way the mask-aware normalization downstream never divides by a garbage
    norm; imputation just keeps the aperture gap-free."""

    max_masked_fraction: float = 0.5
    """Chunk-level poison verdict: when more than this fraction of channels
    is masked the chunk is beyond degrading — the batch path quarantines it
    (``PoisonedChunkError``) and the serve path sheds the request pre-batch
    (HTTP 422) instead of imaging noise."""

    nan_fraction_max: float = 0.0
    """Request-level admission bound for serving: a request whose global
    non-finite sample fraction exceeds this is shed as poison before it
    can join (and corrupt) a microbatch cohort."""


@dataclass(frozen=True)
class ObsConfig:
    """Observability knobs (``das_diff_veh_tpu.obs``), shared by the batch
    runtime (``RuntimeConfig.obs``) and the serving engine
    (``ServeConfig.obs``).  Pure execution knobs: none of them changes an
    output bit, and the batch resume manifest's config hash excludes them.
    The full model (registry, Prometheus scrape, flight-recorder workflow,
    profiler window) is documented in docs/OBSERVABILITY.md.
    """

    enabled: bool = True
    """Master switch for the batch runtime's observability instrumentation
    (registry families, flight ring, sink/profiler/monitoring hooks).
    False turns ALL of it off — the bench ``obs_overhead`` A/B's bare
    side, so the committed <2% number measures the whole stack, not just
    the optional artifact writers.  The serve engine's metrics are its
    product surface (``/v1/metrics`` is built from them) and ignore this
    switch."""

    metrics_jsonl: Optional[str] = None
    """Append periodic registry snapshots (one JSON line each) here during
    batch runs — the scrapeless counterpart of the serve front's
    ``GET /metrics``.  None disables the sink."""

    metrics_interval_s: float = 10.0
    """Seconds between JSONL sink snapshots (a final line is always written
    when the run ends)."""

    flight_dir: Optional[str] = None
    """Directory for crash-flight-recorder dumps.  When set, the last
    ``flight_capacity`` per-chunk / per-request records are written as a
    JSON artifact on quarantine, shed, unhandled error, and SIGTERM
    (``scripts/obs_report.py`` renders them).  None keeps the in-memory
    ring but never writes."""

    flight_capacity: int = 256
    """Records retained in the flight-recorder ring."""

    profile_dir: Optional[str] = None
    """Write a programmatic ``jax.profiler`` capture of
    ``profile_n_chunks`` steady-state chunks here (batch runs; the window
    opens after ``profile_start_chunk`` chunks so compile/warmup noise
    stays out).  This is the device-truth view docs/PERF.md's "stage_* is
    a budget statement" caveat points at.  None disables."""

    profile_start_chunk: int = 3
    """Chunks to skip before the profiler window opens (warmup exclusion)."""

    profile_n_chunks: int = 2
    """Chunks captured inside the profiler window."""

    hbm_sample_interval_s: float = 0.0
    """Background per-device ``memory_stats()`` sampling period [s] (the
    bench.py peak-bytes pattern made continuous).  0 registers the lazy
    scrape-time gauges only — no thread."""

    trace_flush_interval_s: float = 0.0
    """Chrome-trace writer flush cadence.  0 (default) flushes every event
    line — crash-durable, one syscall per span.  > 0 batches writes and
    flushes at most every this many seconds (tight per-chunk loops stop
    paying a syscall per span; an unclean kill can lose up to one
    interval's events)."""

    xla_events: bool = True
    """Subscribe the run's registry to ``jax.monitoring`` compile/trace
    events (``das_jax_traces_total`` etc. — the device-truth counters the
    zero-steady-state-compiles gauge is built on)."""


@dataclass(frozen=True)
class ServeConfig:
    """Online serving engine knobs (``das_diff_veh_tpu.serve``).

    Like :class:`RuntimeConfig` (runtime/config.py) these are execution
    knobs, not physics: none of them changes a single output bit for a
    request that is admitted.  ``buckets`` is the one exception in spirit —
    it decides how much zero-padding a request's ``(n_ch, nt)`` receives
    before hitting the compiled program, so bucket choice belongs next to
    the numerical config it serves (see docs/USAGE.md §serving for bucket
    selection guidance).
    """

    buckets: Tuple[Tuple[int, int], ...] = ()
    """Allowed padded request shapes, ``(n_ch, nt)`` each.  A request is
    padded up to the smallest bucket that fits it (area-wise smallest
    first); a request no bucket fits is rejected at submit.  Empty means
    the engine cannot admit anything — always configure this."""

    max_batch: int = 4
    """Microbatch size cap: the dispatcher executes at most this many
    same-bucket requests per compiled-program visit."""

    max_queue: int = 64
    """Admission-queue bound (backpressure): ``submit`` raises
    ``QueueFullError`` once this many requests are waiting."""

    batch_window_ms: float = 2.0
    """Deprecated, ignored.  The dispatcher no longer lingers for
    companions: batching is *continuous* (iteration-level) — a request
    arriving while a same-bucket batch is executing is admitted into the
    open batch slot at the next member boundary, so an idle engine pays
    zero added latency and a busy engine still coalesces.  The field is
    kept so existing configs/CLI invocations keep parsing; setting it to
    a non-default value emits a ``DeprecationWarning``."""

    default_deadline_ms: float = 30000.0
    """Deadline applied to requests that do not pass one.  A request still
    queued past its deadline is shed (``DeadlineExceededError``), counted
    separately from backpressure rejections."""

    warmup: bool = True
    """Ahead-of-time compile every configured bucket at ``start()`` so
    steady-state requests never pay a trace (the compiled-cache miss
    counter stays at zero for in-bucket traffic)."""

    latency_window: int = 1024
    """Completed-request latencies kept for the p50/p95/p99 snapshot."""

    compilation_cache_dir: Optional[str] = None
    """Persistent XLA compilation cache directory
    (``jax_compilation_cache_dir``) applied at engine start, so warmups are
    near-free across process restarts.  None leaves the process setting
    untouched."""

    obs: ObsConfig = field(default_factory=ObsConfig)
    """Observability knobs: flight-recorder dumps on shed/error paths and
    the ``jax.monitoring`` compile counters behind the
    ``das_serve_steady_state_compiles`` gauge (see :class:`ObsConfig`)."""

    health: Optional[HealthConfig] = None
    """Admission-time input-health screen (:class:`HealthConfig`).  When
    set and enabled, ``submit`` runs a host-side (numpy, zero-dispatch)
    screen and sheds poison requests — NaN/Inf bursts, dead-channel
    floods — as :class:`~das_diff_veh_tpu.serve.engine.PoisonInputError`
    (HTTP 422) before they can join a microbatch, so one corrupt request
    never contaminates a cohort.  None disables the screen entirely."""

    def __post_init__(self) -> None:
        if self.batch_window_ms != 2.0:
            import warnings
            warnings.warn(
                "ServeConfig.batch_window_ms is deprecated and ignored: "
                "the dispatcher batches continuously (iteration-level) "
                "instead of lingering for companions.  Drop the argument.",
                DeprecationWarning, stacklevel=3)


@dataclass(frozen=True)
class MeshServeConfig:
    """Mesh-distributed multi-tenant serving knobs (``serve.mesh``).

    Wraps a :class:`ServeConfig` (buckets, deadlines, warmup — unchanged
    semantics) with the placement and tenancy policy of
    :class:`~das_diff_veh_tpu.serve.mesh.MeshServingEngine`: data-parallel
    replica workers for independent requests, the channel-sharded ring
    (``parallel.allpairs``) for large-geometry ones, per-tenant admission
    quotas and fair-share scheduling.  Execution knobs, not physics — a
    request computes the same bits wherever it is placed (ring placement
    bit-exactness is pinned by tests/test_serve_mesh.py).
    """

    serve: ServeConfig = field(default_factory=ServeConfig)
    """The wrapped single-engine config; ``serve.max_queue`` bounds the
    TOTAL queued requests across all replica/ring queues and
    ``serve.max_batch`` caps each worker's continuous-batch occupancy."""

    replicas: Optional[int] = None
    """Data-parallel replica workers, one per device.  None = one replica
    per visible JAX device (capped at the device count); on a single
    device this degrades to the plain engine plus tenancy."""

    ring_min_channels: Optional[int] = None
    """Requests with at least this many valid channels route to the
    channel-sharded ring placement instead of a replica.  None disables
    the ring route entirely (every request is replica-placed)."""

    ring_devices: Optional[int] = None
    """Mesh size for ring placements (``parallel.mesh.make_mesh``).
    None = all visible devices."""

    tenant_quota: int = 32
    """Per-tenant admission bound: queued + in-flight requests a single
    tenant may hold.  The next submit over quota sheds with
    ``TenantQuotaError`` (HTTP 429) — one tenant can saturate at most its
    quota, never the whole engine."""

    tenant_poison_quarantine: Optional[int] = 3
    """Consecutive poison sheds (admission health screen) after which a
    tenant is quarantined: further submits shed with
    ``TenantQuarantinedError`` until ``release_tenant``.  None disables
    auto-quarantine (poison requests are still shed individually)."""

    drain_timeout_s: float = 30.0
    """``drain_tenant``/``drain_replica`` wait at most this long for the
    target's in-flight requests before returning."""


@dataclass(frozen=True)
class FleetInversionConfig:
    """Fleet-inversion batch-size knobs (``fleet.*``).

    Host-chunking for :func:`das_diff_veh_tpu.inversion.fleet.invert_fleet`:
    how the (targets x runs x pop) working set is cut so big fleets stay
    inside HBM.  Execution knobs, not physics — every chunking produces the
    same inverted profiles to restart-fusion tolerance (pinned by
    tests/test_fleet_inversion.py), so all three are tuner-sweepable
    (``tune.TUNABLE_KNOBS``).
    """

    target_chunk: int = 0
    """Targets inverted per device dispatch (0 = the whole fleet at once).
    Every chunk is padded to this size so each hits the same compiled
    program; with a mesh it is rounded up to a device-count multiple."""

    eval_chunk: int = 0
    """Per-target swarm-evaluation chunk handed to the inner
    ``lax.map``-chunked population eval (0 = whole population at once)."""

    refine_chunk: int = 0
    """Multi-start refinement starts per dispatch inside the fleet's
    Adam-polish stage (0 = all starts at once)."""


@dataclass(frozen=True)
class PipelineConfig:
    """Everything, bundled. Static under jit."""

    interrogator: InterrogatorConfig = field(default_factory=InterrogatorConfig)
    tracking: TrackingConfig = field(default_factory=TrackingConfig)
    track_qc: TrackQCConfig = field(default_factory=TrackQCConfig)
    tracking_preprocess: TrackingPreprocessConfig = field(default_factory=TrackingPreprocessConfig)
    sw_preprocess: SurfaceWavePreprocessConfig = field(default_factory=SurfaceWavePreprocessConfig)
    window: WindowConfig = field(default_factory=WindowConfig)
    mute: MuteConfig = field(default_factory=MuteConfig)
    gather: GatherConfig = field(default_factory=GatherConfig)
    dispersion: DispersionConfig = field(default_factory=DispersionConfig)
    imaging: ImagingConfig = field(default_factory=ImagingConfig)
    bootstrap: BootstrapConfig = field(default_factory=BootstrapConfig)
    health: HealthConfig = field(default_factory=HealthConfig)
    fleet: FleetInversionConfig = field(default_factory=FleetInversionConfig)
    max_windows: int = 64             # static per-chunk window capacity

    chunk_pipeline: str = "staged"
    """Execution mode of the per-chunk pipeline (``pipeline.timelapse``).
    ``"staged"`` (default): every stage is an explicit eager call with host
    geometry resolved between stages — the parity oracle, and the only mode
    whose intermediate pytrees are individually inspectable.  ``"fused"``:
    the whole post-screen pipeline (preprocess -> track -> window select ->
    gather/stack -> dispersion image) runs as ONE jitted, buffer-donated XLA
    program per chunk (``pipeline.fused.fused_process_chunk``): all slice
    geometry is hoisted to trace time from the host ``(x, t, cfg)``
    metadata, ``n_windows`` stays a device scalar, and the result pytree is
    pulled in a single ``jax.device_get`` by the consumer.  One dispatch per
    chunk instead of one per stage — on the tunneled test rig each avoided
    dispatch is a ~100-200 ms round trip (docs/PERF.md).  Execution knob,
    not physics: fused/staged parity is pinned bit-exact on the default
    config by tests/test_fused_pipeline.py.  The knob participates in the
    runtime config hash, so resumed runs and serve bucket caches never mix
    modes silently."""

    def replace(self, **kw) -> "PipelineConfig":
        return dataclasses.replace(self, **kw)


def default_config() -> PipelineConfig:
    return PipelineConfig()
