"""Pipelined chunk executor: prefetch + retry/backoff + quarantine + spans.

One generic loop used by every batch workflow: a sequence of ``ChunkTask``s
(host-side ``load`` thunks) is streamed through a ``PrefetchLoader`` while
the main thread runs ``compute`` (device work) and ``accumulate`` (ordered
reduction) per chunk.  Failures are isolated per chunk: the failing stage is
retried with linear backoff up to ``RuntimeConfig.max_retries`` times, and a
chunk that still fails lands on the quarantine list — costing one chunk, not
the run.

Accumulation happens on the main thread in task-submission order, so results
are bit-identical to the serial loop regardless of prefetch depth.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

from das_diff_veh_tpu.runtime.config import RuntimeConfig
from das_diff_veh_tpu.runtime.prefetch import PrefetchLoader
from das_diff_veh_tpu.runtime.tracing import NullTracer

log = logging.getLogger("das_diff_veh_tpu.runtime")


@dataclass
class ChunkTask:
    """One unit of work: a manifest key plus a host-side load thunk."""

    index: int
    key: str
    load: Callable[[], Any]


@dataclass
class QuarantineRecord:
    key: str
    stage: str          # "load" or "compute"
    error: str
    retries: int


@dataclass
class ExecStats:
    n_done: int = 0
    n_retries: int = 0
    quarantined: List[QuarantineRecord] = field(default_factory=list)
    wall_s: float = 0.0

    @property
    def chunks_per_s(self) -> float:
        return self.n_done / self.wall_s if self.wall_s > 0 else 0.0


def _retrying(fn: Callable[[], Any], stage: str, key: str, cfg: RuntimeConfig,
              tracer, stats: ExecStats, prior_error: Optional[Exception] = None):
    """Run ``fn`` with up to max_retries extra attempts; returns
    (value, error, n_retries_used).  ``prior_error`` marks an attempt that
    already failed elsewhere (the prefetch thread), so every call here is a
    counted, backed-off retry."""
    err: Optional[Exception] = prior_error
    first = 1 if prior_error is not None else 0
    for attempt in range(first, cfg.max_retries + 1):
        if attempt:
            stats.n_retries += 1
            tracer.instant("retry", stage=stage, key=key, attempt=attempt)
            time.sleep(cfg.retry_backoff_s * attempt)
            log.warning("%s: retrying %s (attempt %d/%d): %s", key, stage,
                        attempt, cfg.max_retries, err)
        try:
            return fn(), None, attempt
        except Exception as e:
            err = e
    return None, err, cfg.max_retries


def run_pipelined(tasks: Sequence[ChunkTask],
                  compute: Callable[[Any], Any],
                  accumulate: Callable[[ChunkTask, Any], None],
                  cfg: Optional[RuntimeConfig] = None,
                  tracer=None,
                  on_quarantine: Optional[Callable[[QuarantineRecord], None]] = None,
                  ) -> ExecStats:
    """Execute every task; never raises for a per-chunk failure.

    ``compute`` runs device work for one loaded value; ``accumulate`` folds
    its result into caller state (called in task order).  ``on_quarantine``
    fires once per permanently-failed chunk (manifest bookkeeping).
    """
    cfg = cfg or RuntimeConfig()
    tracer = tracer or NullTracer()
    stats = ExecStats()
    loader = PrefetchLoader([t.load for t in tasks], depth=cfg.prefetch_depth)
    t_start = time.perf_counter()
    try:
        pending = iter(loader)
        while True:
            with tracer.span("input_wait"):
                nxt = next(pending, None)
            if nxt is None:
                break
            idx, value, err = nxt
            task = tasks[idx]
            retries = 0
            if err is not None:
                # the prefetched attempt was attempt 0; retry inline from 1
                log.warning("%s: load failed: %s", task.key, err)
                value, err, retries = _retrying(task.load, "load", task.key,
                                                cfg, tracer, stats,
                                                prior_error=err)
            if err is not None:
                rec = QuarantineRecord(task.key, "load", f"{type(err).__name__}: {err}",
                                       retries)
                stats.quarantined.append(rec)
                log.error("%s: quarantined after load failure: %s", task.key, rec.error)
                if on_quarantine:
                    on_quarantine(rec)
                continue

            def _compute(v=value):
                with tracer.span("compute", key=task.key):
                    return compute(v)

            result, err, retries = _retrying(_compute, "compute", task.key,
                                             cfg, tracer, stats)
            if err is not None:
                rec = QuarantineRecord(task.key, "compute",
                                       f"{type(err).__name__}: {err}", retries)
                stats.quarantined.append(rec)
                log.error("%s: quarantined after compute failure: %s",
                          task.key, rec.error)
                if on_quarantine:
                    on_quarantine(rec)
                continue

            with tracer.span("accumulate", key=task.key):
                accumulate(task, result)
            stats.n_done += 1
            tracer.counter("chunks", done=stats.n_done,
                           quarantined=len(stats.quarantined))
    finally:
        loader.close()
    stats.wall_s = time.perf_counter() - t_start
    return stats
