"""Pipelined chunk executor: prefetch + retry/backoff + quarantine + spans.

One generic loop used by every batch workflow: a sequence of ``ChunkTask``s
(host-side ``load`` thunks) is streamed through a ``PrefetchLoader`` while
the main thread runs ``compute`` (device work) and ``accumulate`` (ordered
reduction) per chunk.  Failures are isolated per chunk: the failing stage is
retried with linear backoff up to ``RuntimeConfig.max_retries`` times, and a
chunk that still fails lands on the quarantine list — costing one chunk, not
the run.

Accumulation happens on the main thread in task-submission order, so results
are bit-identical to the serial loop regardless of prefetch depth.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

from das_diff_veh_tpu.obs.flight import FlightRecorder
from das_diff_veh_tpu.obs.registry import MetricsRegistry, default_registry
from das_diff_veh_tpu.resilience import faults
from das_diff_veh_tpu.runtime.config import RuntimeConfig
from das_diff_veh_tpu.runtime.prefetch import PrefetchLoader
from das_diff_veh_tpu.runtime.tracing import NullTracer

log = logging.getLogger("das_diff_veh_tpu.runtime")


class _NullObs:
    """No-op stand-in for the metric families and the flight recorder when
    ``ObsConfig.enabled`` is False (the bench ``obs_overhead`` A/B's bare
    side): the hot loop stays branch-free while paying literally nothing."""

    def labels(self, **kv):
        return self

    def inc(self, by: float = 1.0) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def record(self, kind: str, **fields) -> None:
        pass

    def dump(self, reason: str, **context) -> None:
        return None


_NULL_OBS = _NullObs()


@dataclass
class ChunkTask:
    """One unit of work: a manifest key plus a host-side load thunk."""

    index: int
    key: str
    load: Callable[[], Any]


def consult_tuner(cfg, runtime_cfg: RuntimeConfig,
                  registry: Optional[MetricsRegistry] = None):
    """Apply persisted tuner winners to ``cfg`` per the runtime's policy.

    Returns ``(cfg, entry)``: the (possibly) tuned PipelineConfig plus the
    store entry that was applied, or ``(cfg, None)`` untouched when
    ``RuntimeConfig.tuner_store`` is unset or the store has nothing for
    this (backend, geometry, config).  Soft by contract — a corrupt store,
    a hash mismatch, any failure at all resolves to default knobs
    (``das_tuner_consults_total{status=...}`` counts hit/miss/disabled for
    the obs stack), so batch start can never crash on tuning state.
    """
    if runtime_cfg.tuner_store is None:
        return cfg, None
    from das_diff_veh_tpu.tune import load_tuned
    cfg, _, entry = load_tuned(cfg, runtime_cfg.tuner_store,
                               runtime_cfg.tuner_geometry)
    if registry is not None:
        registry.counter(
            "das_tuner_consults_total",
            "tuner-store consultations by outcome", labels=("status",),
        ).labels(status="hit" if entry is not None else "miss").inc()
    if entry is not None:
        log.info("tuner store %s: applied winners %s",
                 runtime_cfg.tuner_store, entry.winners)
    return cfg, entry


@dataclass
class QuarantineRecord:
    key: str
    stage: str          # "load" or "compute"
    error: str
    retries: int


@dataclass
class ExecStats:
    n_done: int = 0
    n_retries: int = 0
    quarantined: List[QuarantineRecord] = field(default_factory=list)
    wall_s: float = 0.0

    @property
    def chunks_per_s(self) -> float:
        return self.n_done / self.wall_s if self.wall_s > 0 else 0.0


def _retrying(fn: Callable[[], Any], stage: str, key: str, cfg: RuntimeConfig,
              tracer, stats: ExecStats, prior_error: Optional[Exception] = None,
              on_failure: Optional[Callable] = None):
    """Run ``fn`` with up to max_retries extra attempts; returns
    (value, error, n_retries_used).  ``prior_error`` marks an attempt that
    already failed elsewhere (the prefetch thread), so every call here is a
    counted, backed-off retry.  ``on_failure(stage, key, error, attempt)``
    fires once per failed attempt *before* the next retry — the hook the
    degradation ladder rides (demote the fancy path so the retry runs the
    fallback)."""
    err: Optional[Exception] = prior_error
    if err is not None and on_failure is not None:
        on_failure(stage, key, err, 0)
    first = 1 if prior_error is not None else 0
    for attempt in range(first, cfg.max_retries + 1):
        if attempt:
            stats.n_retries += 1
            tracer.instant("retry", stage=stage, key=key, attempt=attempt)
            time.sleep(cfg.retry_backoff_s * attempt)
            log.warning("%s: retrying %s (attempt %d/%d): %s", key, stage,
                        attempt, cfg.max_retries, err)
        try:
            return fn(), None, attempt
        except Exception as e:
            err = e
            if on_failure is not None:
                on_failure(stage, key, e, attempt)
    return None, err, cfg.max_retries


def run_pipelined(tasks: Sequence[ChunkTask],
                  compute: Callable[[Any], Any],
                  accumulate: Callable[[ChunkTask, Any], None],
                  cfg: Optional[RuntimeConfig] = None,
                  tracer=None,
                  on_quarantine: Optional[Callable[[QuarantineRecord], None]] = None,
                  registry: Optional[MetricsRegistry] = None,
                  flight: Optional[FlightRecorder] = None,
                  on_stage_failure: Optional[Callable] = None,
                  ) -> ExecStats:
    """Execute every task; never raises for a per-chunk failure.

    ``compute`` runs device work for one loaded value; ``accumulate`` folds
    its result into caller state (called in task order).  ``on_quarantine``
    fires once per permanently-failed chunk (manifest bookkeeping);
    ``on_stage_failure(stage, key, error, attempt)`` once per failed
    attempt before its retry (the degradation ladder's hook — demote a
    flaky code path so the retry takes the fallback).

    Chunk progress, retries, quarantines, per-chunk wall time, and the live
    prefetch queue depth register as ``das_runtime_*`` families into
    ``registry`` (default: the process registry, so a serve front in the
    same process scrapes them); per-chunk records land in ``flight`` and a
    quarantine dumps the ring (the post-mortem artifact).
    """
    cfg = cfg or RuntimeConfig()
    tracer = tracer or NullTracer()
    # an explicit registry/flight is intent enough to instrument; otherwise
    # ObsConfig.enabled=False (the bench A/B's bare side) skips everything
    obs_on = cfg.obs.enabled or registry is not None or flight is not None
    depth_gauge = None
    if obs_on:
        reg = registry if registry is not None else default_registry()
        flight = flight if flight is not None else FlightRecorder(
            capacity=cfg.obs.flight_capacity, out_dir=cfg.obs.flight_dir,
            name="runtime_flight")
        c_chunks = reg.counter("das_runtime_chunks_total",
                               "chunks by terminal status", labels=("status",))
        c_retries = reg.counter("das_runtime_retries_total",
                                "per-stage retry attempts", labels=("stage",))
        h_chunk = reg.histogram("das_runtime_chunk_seconds",
                                "wall seconds per completed chunk")
    else:
        flight = _NULL_OBS
        c_chunks = c_retries = h_chunk = _NULL_OBS
    stats = ExecStats()
    loader = PrefetchLoader([t.load for t in tasks], depth=cfg.prefetch_depth)
    if obs_on:
        depth_gauge = reg.gauge("das_runtime_prefetch_depth",
                                "chunks staged ahead by the loader")
        depth_gauge.set_fn(loader.qsize)
    t_start = time.perf_counter()
    try:
        pending = iter(loader)
        while True:
            with tracer.span("input_wait"):
                nxt = next(pending, None)
            if nxt is None:
                break
            idx, value, err = nxt
            task = tasks[idx]
            t_chunk0 = time.perf_counter()
            retries = 0
            if err is not None:
                # the prefetched attempt was attempt 0; retry inline from 1
                log.warning("%s: load failed: %s", task.key, err)
                value, err, retries = _retrying(task.load, "load", task.key,
                                                cfg, tracer, stats,
                                                prior_error=err,
                                                on_failure=on_stage_failure)
                if retries:
                    c_retries.labels(stage="load").inc(retries)
            if err is not None:
                rec = QuarantineRecord(task.key, "load", f"{type(err).__name__}: {err}",
                                       retries)
                stats.quarantined.append(rec)
                log.error("%s: quarantined after load failure: %s", task.key, rec.error)
                c_chunks.labels(status="quarantined").inc()
                flight.record("chunk", key=task.key, stage="load",
                              error=rec.error, retries=retries)
                flight.dump("quarantine", key=task.key, stage="load")
                if on_quarantine:
                    on_quarantine(rec)
                continue

            def _compute(v=value):
                # chaos sites: slow-chunk latency + compute dispatch failure
                # (no-ops unless a fault injector is installed)
                faults.fire("runtime.slow", task.key)
                faults.fire("runtime.compute", task.key)
                with tracer.span("compute", key=task.key):
                    return compute(v)

            result, err, retries = _retrying(_compute, "compute", task.key,
                                             cfg, tracer, stats,
                                             on_failure=on_stage_failure)
            if retries:
                c_retries.labels(stage="compute").inc(retries)
            if err is not None:
                rec = QuarantineRecord(task.key, "compute",
                                       f"{type(err).__name__}: {err}", retries)
                stats.quarantined.append(rec)
                log.error("%s: quarantined after compute failure: %s",
                          task.key, rec.error)
                c_chunks.labels(status="quarantined").inc()
                flight.record("chunk", key=task.key, stage="compute",
                              error=rec.error, retries=retries)
                flight.dump("quarantine", key=task.key, stage="compute")
                if on_quarantine:
                    on_quarantine(rec)
                continue

            with tracer.span("accumulate", key=task.key):
                accumulate(task, result)
            stats.n_done += 1
            dt_chunk = time.perf_counter() - t_chunk0
            c_chunks.labels(status="done").inc()
            h_chunk.observe(dt_chunk)
            flight.record("chunk", key=task.key, retries=retries,
                          wall_s=round(dt_chunk, 4))
            tracer.counter("chunks", done=stats.n_done,
                           quarantined=len(stats.quarantined))
    finally:
        loader.close()
        if depth_gauge is not None:
            # replace the loader-bound callback with a plain 0 so the gauge
            # (process-lifetime) stops pinning the loader and any staged
            # sections its queue still holds after an aborted run
            depth_gauge.set(0.0)
    stats.wall_s = time.perf_counter() - t_start
    return stats
