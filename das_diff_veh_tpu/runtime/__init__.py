"""Pipelined batch-execution runtime.

Four concerns, one module each:

- :mod:`prefetch` — bounded-queue background loader overlapping host npz
  read + preprocess + ``device_put`` with device compute;
- :mod:`executor` — per-chunk retry/backoff and quarantine (a corrupt file
  costs one chunk, not the date), ordered bit-exact accumulation;
- :mod:`manifest` — config-hash-keyed resume manifest + partial-state
  checkpoints for exact mid-date restart;
- :mod:`tracing` — Chrome-trace-format JSONL span events and throughput
  counters.

The batch workflows (``pipeline.workflow``) and the CLI are thin callers of
this package; it has no knowledge of DAS specifics beyond "a chunk loads,
computes, accumulates".
"""

from das_diff_veh_tpu.runtime.config import RuntimeConfig
from das_diff_veh_tpu.runtime.executor import (ChunkTask, ExecStats,
                                               QuarantineRecord,
                                               consult_tuner, run_pipelined)
from das_diff_veh_tpu.runtime.manifest import RunManifest, config_hash
from das_diff_veh_tpu.runtime.prefetch import PrefetchLoader
from das_diff_veh_tpu.runtime.tracing import (NullTracer, TraceWriter,
                                              load_trace, make_tracer)

__all__ = [
    "RuntimeConfig", "ChunkTask", "ExecStats", "QuarantineRecord",
    "consult_tuner", "run_pipelined", "RunManifest", "config_hash",
    "PrefetchLoader", "NullTracer", "TraceWriter", "load_trace",
    "make_tracer",
]
