"""Structured span tracing in Chrome trace event format, one event per line.

The runtime emits complete ("ph": "X") duration events for every pipeline
stage (read / preprocess / compute / accumulate) plus counter ("ph": "C")
events for throughput, from both the main thread and the prefetch loader
thread.  The file is line-delimited JSON so a killed run still leaves every
completed event on disk; ``load_trace`` re-wraps the lines into the JSON
array form that ``chrome://tracing`` and Perfetto ingest (both viewers also
accept the raw line-delimited file directly — the Chrome trace parser
tolerates missing array brackets).

Timestamps are microseconds since the writer was opened (``perf_counter``
based, so spans from different threads are mutually ordered).
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from typing import Iterator, List, Optional

_REQUIRED_KEYS = {"name", "ph", "ts", "pid", "tid"}


class NullTracer:
    """No-op tracer with the TraceWriter API; used when tracing is off."""

    path: Optional[str] = None

    @contextmanager
    def span(self, name: str, cat: str = "runtime", **args) -> Iterator[None]:
        yield

    def now_us(self) -> float:
        return 0.0

    def complete(self, name: str, start_us: float, cat: str = "runtime",
                 **args) -> None:
        pass

    def counter(self, name: str, **values) -> None:
        pass

    def instant(self, name: str, **args) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class TraceWriter(NullTracer):
    """Thread-safe Chrome-trace JSONL writer.

    ``flush_interval_s`` controls crash durability vs syscall cost: 0 (the
    default) flushes after every event line, so a killed run keeps every
    completed span; > 0 batches writes in the stdio buffer and flushes at
    most once per interval (``ObsConfig.trace_flush_interval_s`` — tight
    per-chunk loops stop paying one ``write`` syscall per span, an unclean
    kill can lose up to one interval's events).  ``close`` always flushes.
    """

    def __init__(self, path: str, process_name: str = "das_diff_veh_tpu",
                 flush_interval_s: float = 0.0):
        self.path = path
        self.flush_interval_s = float(flush_interval_s)
        self._f = open(path, "w")
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._last_flush = time.perf_counter()
        self._named_tids: set = set()
        self._emit({"name": "process_name", "ph": "M", "ts": 0, "pid": 1,
                    "tid": 0, "args": {"name": process_name}})

    # -- internals -----------------------------------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _tid(self) -> int:
        t = threading.current_thread()
        tid = t.ident or 0
        if tid not in self._named_tids:
            self._named_tids.add(tid)
            self._emit({"name": "thread_name", "ph": "M", "ts": 0, "pid": 1,
                        "tid": tid, "args": {"name": t.name}})
        return tid

    def _emit(self, event: dict) -> None:
        line = json.dumps(event)
        with self._lock:
            if not self._f.closed:
                self._f.write(line + "\n")
                if self.flush_interval_s <= 0.0:
                    self._f.flush()
                else:
                    now = time.perf_counter()
                    if now - self._last_flush >= self.flush_interval_s:
                        self._f.flush()
                        self._last_flush = now

    def flush(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._last_flush = time.perf_counter()

    # -- public API ----------------------------------------------------------
    def now_us(self) -> float:
        """Current trace-clock timestamp; pair with :meth:`complete` for
        spans whose start and end happen on different threads (the serving
        engine's queue-wait span starts in ``submit`` and ends in the
        dispatcher)."""
        return self._now_us()

    def complete(self, name: str, start_us: float, cat: str = "runtime",
                 **args) -> None:
        """Emit one complete ("X") event from an explicit start timestamp
        (a value previously returned by :meth:`now_us`) to now."""
        self._emit({"name": name, "cat": cat, "ph": "X",
                    "ts": round(start_us, 1),
                    "dur": round(max(self._now_us() - start_us, 0.0), 1),
                    "pid": 1, "tid": self._tid(), "args": args})

    @contextmanager
    def span(self, name: str, cat: str = "runtime", **args) -> Iterator[None]:
        """Emit one complete ("X") event covering the with-block."""
        tid = self._tid()
        t0 = self._now_us()
        try:
            yield
        finally:
            self._emit({"name": name, "cat": cat, "ph": "X", "ts": round(t0, 1),
                        "dur": round(self._now_us() - t0, 1), "pid": 1,
                        "tid": tid, "args": args})

    def counter(self, name: str, **values) -> None:
        self._emit({"name": name, "ph": "C", "ts": round(self._now_us(), 1),
                    "pid": 1, "tid": self._tid(), "args": values})

    def instant(self, name: str, **args) -> None:
        self._emit({"name": name, "ph": "i", "s": "g",
                    "ts": round(self._now_us(), 1), "pid": 1,
                    "tid": self._tid(), "args": args})

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def make_tracer(path: Optional[str],
                flush_interval_s: float = 0.0) -> NullTracer:
    return (TraceWriter(path, flush_interval_s=flush_interval_s)
            if path else NullTracer())


def load_trace(path: str) -> List[dict]:
    """Parse + validate a trace file; returns the event list.

    Raises ValueError on any line that is not a Chrome trace event (valid
    JSON object, required keys, dur on complete events), so tests can assert
    format validity with one call.
    """
    events = []
    with open(path) as f:
        for n, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{n}: not valid JSON: {e}") from e
            if not isinstance(ev, dict) or not _REQUIRED_KEYS <= set(ev):
                raise ValueError(f"{path}:{n}: missing Chrome trace keys "
                                 f"{_REQUIRED_KEYS - set(ev)}")
            if ev["ph"] == "X" and "dur" not in ev:
                raise ValueError(f"{path}:{n}: complete event without dur")
            events.append(ev)
    return events
