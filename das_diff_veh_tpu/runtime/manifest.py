"""Per-run resume manifest: config hash + per-chunk done/quarantined status.

Replaces skip-if-output-exists resume (reference imaging_workflow.py:189-191)
with exact mid-date resume: the manifest records every chunk file's status
and the partial accumulator is checkpointed alongside it, so an interrupted
run restarts at the first unprocessed chunk and reproduces the uninterrupted
result bit-for-bit (chunks accumulate in sorted file order, and a resumed
run continues the same order from the saved prefix sum).

The manifest is keyed on a hash of everything that determines output values
(PipelineConfig, method, dataset preprocessing knobs) so stale outputs from
an older configuration are invalidated instead of silently skipped.
RuntimeConfig is excluded on purpose — prefetch depth or retry policy never
changes a bit of output.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Dict, Optional

MANIFEST_VERSION = 1

STATUS_DONE = "done"
STATUS_QUARANTINED = "quarantined"


def config_hash(*parts) -> str:
    """Deterministic hash of config-ish objects via their repr.

    Frozen dataclass reprs are stable field-ordered renderings, which makes
    repr a faithful value fingerprint for the config tree (callables inside,
    if any, would not be — none of the hashed configs carry them).
    """
    h = hashlib.sha256()
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]


def _atomic_write_json(path: str, payload: dict) -> None:
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


@dataclass
class RunManifest:
    """Status of one date-directory run, persisted as JSON."""

    path: str
    config_hash: str
    date: str = ""
    complete: bool = False
    files: Dict[str, dict] = field(default_factory=dict)
    """basename -> {"status": done|quarantined, "n_windows": int,
    "error": str, "stage": str, "retries": int, "health": dict}
    (keys per status; "health" only on chunks the input-health sentinel
    degraded — masked channels, NaN fraction — so a resumed run still
    knows which of its accumulated chunks ran in degraded mode)."""

    # -- persistence ---------------------------------------------------------
    @classmethod
    def load(cls, path: str) -> Optional["RunManifest"]:
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                d = json.load(f)
        except (json.JSONDecodeError, OSError):
            return None           # unreadable manifest == no manifest
        if d.get("version") != MANIFEST_VERSION:
            return None
        return cls(path=path, config_hash=d.get("config_hash", ""),
                   date=d.get("date", ""), complete=bool(d.get("complete")),
                   files=d.get("files", {}))

    def save(self) -> None:
        _atomic_write_json(self.path, {
            "version": MANIFEST_VERSION, "config_hash": self.config_hash,
            "date": self.date, "complete": self.complete, "files": self.files})

    # -- status accounting ---------------------------------------------------
    def status(self, key: str) -> Optional[str]:
        entry = self.files.get(key)
        return entry["status"] if entry else None

    def is_settled(self, key: str) -> bool:
        """Done or quarantined — nothing left to do for this chunk."""
        return self.status(key) in (STATUS_DONE, STATUS_QUARANTINED)

    def mark_done(self, key: str, n_windows: int, retries: int = 0,
                  health: Optional[dict] = None) -> None:
        entry = {"status": STATUS_DONE, "n_windows": int(n_windows),
                 "retries": int(retries)}
        if health:     # degraded-mode provenance (masked channels etc.)
            entry["health"] = health
        self.files[key] = entry

    def mark_quarantined(self, key: str, stage: str, error: str,
                         retries: int = 0) -> None:
        self.files[key] = {"status": STATUS_QUARANTINED, "stage": stage,
                           "error": error[:500], "retries": int(retries)}

    def clear_quarantined(self) -> int:
        """Drop every quarantine record so those chunks re-enter the work
        list (``RuntimeConfig.retry_quarantined``); returns how many."""
        keys = [k for k, e in self.files.items()
                if e["status"] == STATUS_QUARANTINED]
        for k in keys:
            del self.files[k]
        return len(keys)

    @property
    def n_vehicles(self) -> int:
        return sum(e.get("n_windows", 0) for e in self.files.values()
                   if e["status"] == STATUS_DONE)

    @property
    def n_chunks(self) -> int:
        """Chunks that contributed to the accumulator (done, >=1 window)."""
        return sum(1 for e in self.files.values()
                   if e["status"] == STATUS_DONE and e.get("n_windows", 0) > 0)

    @property
    def quarantined(self) -> Dict[str, dict]:
        return {k: e for k, e in self.files.items()
                if e["status"] == STATUS_QUARANTINED}

    @property
    def degraded(self) -> Dict[str, dict]:
        """Done chunks that ran with health-masked channels."""
        return {k: e for k, e in self.files.items()
                if e["status"] == STATUS_DONE and e.get("health")}
