"""Bounded background prefetch: load chunk k+1..k+depth while k computes.

The loader thread runs each task's ``load`` callable (host npz read +
savgol preprocess + ``jax.device_put`` staging) and feeds a bounded queue;
the main thread drains it in submission order.  Load exceptions are
delivered in-band as ``(index, None, exc)`` so the executor owns the
retry/quarantine policy — the loader never dies on a bad file.

NumPy I/O, zlib decompression, scipy filtering, and device transfer all
release the GIL, so the loader overlaps the main thread's device waits;
``depth`` bounds the host-memory footprint to ``depth + 1`` staged chunks.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Optional, Sequence, Tuple

LoadResult = Tuple[int, Any, Optional[BaseException]]

_SENTINEL = object()


class PrefetchLoader:
    """Iterate ``(index, value, error)`` over tasks, loaded ahead by a thread.

    ``depth <= 0`` runs every load inline on the calling thread (serial
    mode — the bench baseline and a debugging escape hatch).
    """

    def __init__(self, loads: Sequence[Callable[[], Any]], depth: int = 2,
                 thread_name: str = "chunk-prefetch"):
        self._loads = list(loads)
        self._depth = int(depth)
        self._stop = threading.Event()
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        if self._depth > 0 and self._loads:
            self._queue = queue.Queue(maxsize=self._depth)
            self._thread = threading.Thread(target=self._worker,
                                            name=thread_name, daemon=True)
            self._thread.start()

    def _worker(self) -> None:
        try:
            for i, load in enumerate(self._loads):
                if self._stop.is_set():
                    return
                try:
                    item: LoadResult = (i, load(), None)
                except BaseException as e:  # in-band; retry/quarantine policy
                    item = (i, None, e)     # lives upstream in the executor
                self._put(item)
        finally:
            self._put(_SENTINEL)            # never lose end-of-stream (deadlock)

    def _put(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self) -> Iterator[LoadResult]:
        if self._queue is None:             # inline (serial) mode
            for i, load in enumerate(self._loads):
                if self._stop.is_set():
                    return
                try:
                    yield i, load(), None
                except Exception as e:
                    yield i, None, e
            return
        while True:
            item = self._queue.get()
            if item is _SENTINEL:
                return
            yield item

    def qsize(self) -> int:
        """Chunks currently staged ahead (0 in inline mode) — the live
        queue-depth gauge the obs registry scrapes."""
        return self._queue.qsize() if self._queue is not None else 0

    def close(self) -> None:
        """Stop the loader early (executor abort); idempotent."""
        self._stop.set()
        if self._thread is not None:
            # drain so a blocked put observes the stop event promptly
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=5.0)
