"""Execution-runtime knobs, separate from the numerical PipelineConfig.

PipelineConfig is static-under-jit physics; RuntimeConfig is how the batch
loop *executes* — prefetch depth, retry policy, manifest cadence, tracing.
Changing it never changes a single output bit, so it is deliberately
excluded from the resume manifest's config hash.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from das_diff_veh_tpu.config import ObsConfig


@dataclass(frozen=True)
class RuntimeConfig:
    """How the pipelined batch executor runs one directory of chunks."""

    prefetch_depth: int = 2
    """Chunks the background loader may stage ahead of the TPU (bounded
    queue).  0 disables the loader thread entirely: loads run inline on the
    main thread (the serial reference behavior, and the bench baseline)."""

    max_retries: int = 1
    """Extra attempts per chunk per stage (load and compute retry
    independently) before the chunk is quarantined."""

    retry_backoff_s: float = 0.05
    """Sleep before retry attempt k is ``k * retry_backoff_s`` (linear
    backoff; transient NFS/device hiccups clear in well under a second)."""

    retry_quarantined: bool = False
    """Resume policy for chunks the manifest already recorded as
    quarantined.  False (default): a restart *skips* known-bad chunks —
    they settled once through the full retry ladder and re-failing them on
    every restart would turn one bad file into a per-restart tax.  True:
    their quarantine records are cleared and they re-enter the work list
    (use after fixing the underlying fault — a restored NFS mount, a
    repaired file)."""

    device_put: bool = True
    """Stage the loaded waterfall onto the default device from the loader
    thread (`jax.device_put`), overlapping H2D transfer with compute."""

    state_every: int = 1
    """Write the resume manifest + partial-accumulator state every N
    completed chunks.  1 (default) gives exact single-chunk-granularity
    resume; raise it if manifest I/O ever shows up in traces."""

    trace_path: Optional[str] = None
    """Write Chrome-trace-format JSONL span events here (read / preprocess /
    compute / accumulate, plus throughput counters).  None disables."""

    obs: ObsConfig = field(default_factory=ObsConfig)
    """Observability knobs for the batch run: metrics JSONL sink,
    flight-recorder dumps on quarantine/SIGTERM, the steady-state profiler
    window, trace flush batching (see :class:`~das_diff_veh_tpu.config.ObsConfig`
    and docs/OBSERVABILITY.md)."""

    tuner_store: Optional[str] = None
    """Path to a tuner-store JSON (``das_diff_veh_tpu.tune``).  When set,
    the batch workflow consults it at start-of-run
    (:func:`~das_diff_veh_tpu.runtime.executor.consult_tuner`) and applies
    any persisted knob winners for this backend/geometry/config before
    compiling.  None (default): defaults run untouched.  Living here is
    consistent with the PipelineConfig/RuntimeConfig split: which *store*
    to read is execution policy, while the applied knobs land in
    PipelineConfig and therefore in the manifest hash (a tuned run and a
    default run never share resume state)."""

    tuner_geometry: str = "default"
    """Deployment-geometry label the tuner keys winners under (channel
    count / spacing / record length change the optimum, and none of them
    are visible in PipelineConfig).  Operators name their fiber sections;
    the default label is for single-deployment installs."""
