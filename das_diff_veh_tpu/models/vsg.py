"""Virtual-shot-gather interferometry — the centerpiece of the framework.

Trajectory-aware seismic interferometry turning each per-vehicle window into
a virtual shot gather at a pivot channel (reference
apis/virtual_shot_gather.py:111-270):

- channels *behind* the vehicle correlate against the pivot over one fixed
  time window anchored ``delta_t`` after the vehicle's pivot arrival
  (reference :172 XCORR_vshot);
- channels *between pivot and vehicle* use per-channel windows that follow
  the trajectory (reference :14-43,174);
- the mirrored "other side" runs time-reversed windows *ahead* of the
  vehicle (reference :145-161) and is averaged in where nonzero (:189-192).

TPU-first design: all channel geometry is static (resolved host-side into a
:class:`VsgGeometry`), all data-dependent time offsets become masked windowed
FFT correlations (ops.xcorr), and the whole gather is one jit-able pure
function, vmapped over the window batch.  Stacking replaces the reference's
``__add__/__truediv__`` object algebra (:195-210) with a masked mean.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.config import DispersionConfig, GatherConfig
from das_diff_veh_tpu.core.section import WindowBatch
from das_diff_veh_tpu.ops import xcorr as xc
from das_diff_veh_tpu.ops.dispersion import fv_map_fk, fv_map_phase_shift
from das_diff_veh_tpu.ops.interp import masked_interp


@dataclass(frozen=True)
class VsgGeometry:
    """Static channel/time geometry of one gather configuration.

    Mirrors preprocessing_window's index math (reference
    apis/virtual_shot_gather.py:111-126) but resolved once on the host: the
    window batch shares its x/t axes, so these are compile-time constants.
    """

    start_x_idx: int       # argmax(x >= start_x)            (reference :120)
    end_x_idx: int         # argmin(|x - end_x|)             (reference :121)
    pivot_idx: int         # argmax(x >= pivot)              (reference :116)
    pivot_x: float         # the *requested* pivot coordinate — the reference
                           # interpolates the pivot arrival at this value, not
                           # at the snapped channel position (reference :117)
    nsamp: int             # int(time_window_to_xcorr // dt) (reference :123)
    wlen: int              # int(wlen / dt)  correlation window [samples]
    dt: float

    @property
    def nch_out(self) -> int:
        return self.end_x_idx - self.start_x_idx

    @classmethod
    def build(cls, x_axis: np.ndarray, dt: float, pivot: float,
              start_x: float, end_x: float, cfg: GatherConfig) -> "VsgGeometry":
        x = np.asarray(x_axis)
        return cls(
            start_x_idx=int(np.argmax(x >= start_x)),
            end_x_idx=int(np.abs(x - end_x).argmin()),
            pivot_idx=int(np.argmax(x >= pivot)),
            pivot_x=float(pivot),
            nsamp=int(cfg.time_window // dt),
            wlen=int(cfg.wlen / dt),
            dt=float(dt),
        )

    def offsets(self, x_axis: np.ndarray) -> np.ndarray:
        """Output x axis: offsets re-zeroed at the pivot (reference :130)."""
        x = np.asarray(x_axis)
        return x[self.start_x_idx:self.end_x_idx] - x[self.pivot_idx]

    def lags(self) -> np.ndarray:
        """Output lag-time axis, zero lag centered (reference :131-132)."""
        return (np.arange(self.wlen) - self.wlen // 2) * self.dt


def _postprocess(xcf: jnp.ndarray, g: VsgGeometry, norm: bool, norm_amp: bool,
                 reverse: bool) -> jnp.ndarray:
    """post_processing_XCF (reference apis/virtual_shot_gather.py:129-142):
    per-trace L2 norm, amplitude norm by the pivot trace's max, and a lag-axis
    flip on the main side.  Zero rows divide by 1 instead of 0/0 (the
    reference would emit NaN rows; masked stacking makes that unnecessary)."""
    if norm:
        rn = jnp.linalg.norm(xcf, axis=-1, keepdims=True)
        xcf = xcf / jnp.where(rn > 0, rn, 1.0)
    if norm_amp:
        amp = jnp.max(xcf[g.pivot_idx - g.start_x_idx])
        xcf = xcf / jnp.where(jnp.abs(amp) > 0, amp, 1.0)
    if not reverse:
        xcf = xcf[:, ::-1]
    return xcf


def build_gather(data: jnp.ndarray, t_axis: jnp.ndarray, x_axis: jnp.ndarray,
                 traj_x: jnp.ndarray, traj_t: jnp.ndarray,
                 traj_valid: jnp.ndarray, g: VsgGeometry,
                 cfg: GatherConfig = GatherConfig()) -> jnp.ndarray:
    """One window -> one virtual shot gather (nch_out, wlen).

    Mirrors construct_shot_gather (+ the other-side merge when
    ``cfg.include_other_side``) — reference apis/virtual_shot_gather.py:165-192.
    Pure function of arrays + static geometry: jit/vmap/shard freely.
    """
    arrival = lambda xq: masked_interp(xq, traj_x, traj_t, traj_valid)
    gn = jnp.linalg.norm(data)                           # global L2 (reference :125)
    d = data / jnp.where(gn > 0, gn, 1.0)                # all-zero (padded) windows stay 0
    x = jnp.asarray(x_axis)

    # ---- main side (behind the vehicle) --------------------------------------
    pivot_t = arrival(jnp.asarray(g.pivot_x)) + cfg.delta_t
    pivot_t_idx = jnp.argmax(t_axis >= pivot_t)
    near = xc.xcorr_vshot_at(d[g.start_x_idx:g.pivot_idx + 1],
                             g.pivot_idx - g.start_x_idx, pivot_t_idx,
                             g.nsamp, g.wlen, cfg.overlap_ratio)
    far_ch = jnp.arange(g.pivot_idx + 1, g.end_x_idx)
    far_t = arrival(x[far_ch]) + cfg.delta_t
    far = xc.xcorr_traj_follow(d, t_axis, g.pivot_idx, far_ch, far_t,
                               g.nsamp, g.wlen, cfg.overlap_ratio,
                               mode=cfg.traj_gather,
                               finish=cfg.traj_gather_finish,
                               max_nwin=cfg.fused_max_nwin,
                               dot_max_wlen=cfg.dot_max_wlen,
                               dot_max_elems=cfg.dot_max_matrix_elems,
                               precision=cfg.precision)
    main = _postprocess(jnp.concatenate([near, far], axis=0), g,
                        cfg.norm, cfg.norm_amp, reverse=False)
    if not cfg.include_other_side:
        return main

    # ---- other side (ahead of the vehicle, time-reversed windows) ------------
    pivot_t2 = arrival(jnp.asarray(g.pivot_x)) - cfg.delta_t
    pivot_t2_idx = jnp.argmax(t_axis >= pivot_t2)
    right = xc.xcorr_vshot_at(d[g.pivot_idx:g.end_x_idx], 0, pivot_t2_idx,
                              g.nsamp, g.wlen, cfg.overlap_ratio,
                              reverse=True, backward=True)
    left_ch = jnp.arange(g.start_x_idx, g.pivot_idx)
    left_t = arrival(x[left_ch]) - cfg.delta_t
    left = xc.xcorr_traj_follow(d, t_axis, g.pivot_idx, left_ch, left_t,
                                g.nsamp, g.wlen, cfg.overlap_ratio,
                                reverse=True, mode=cfg.traj_gather,
                                finish=cfg.traj_gather_finish,
                                max_nwin=cfg.fused_max_nwin,
                                dot_max_wlen=cfg.dot_max_wlen,
                                dot_max_elems=cfg.dot_max_matrix_elems,
                                precision=cfg.precision)
    other = _postprocess(jnp.concatenate([left, right], axis=0), g,
                         cfg.norm, cfg.norm_amp, reverse=True)

    # average in other-side rows where they are nonzero (reference :189-192)
    has_other = jnp.linalg.norm(other, axis=-1, keepdims=True) > 0
    return jnp.where(has_other, 0.5 * (main + other), main)


def build_gather_batch(batch: WindowBatch, g: VsgGeometry,
                       cfg: GatherConfig = GatherConfig()) -> jnp.ndarray:
    """vmap of :func:`build_gather` over a window batch: (max_windows, nch_out, wlen)."""
    traj_valid = jnp.isfinite(batch.traj_t)
    fn = lambda d, t, tx, tt, tv: build_gather(d, t, batch.x, tx, tt, tv, g, cfg)
    return jax.vmap(fn)(batch.data, batch.t, batch.traj_x, batch.traj_t, traj_valid)


def stack_gathers(gathers: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Masked mean over the window axis — replaces the reference's
    sum(images)/len (apis/imaging_classes.py:106-107).  ``where``-masked so a
    NaN in an invalid slot cannot leak through (NaN*0 == NaN)."""
    mask = valid.reshape(valid.shape + (1,) * (gathers.ndim - 1))
    num = jnp.sum(jnp.where(mask, gathers, 0.0), axis=0)
    return num / jnp.maximum(jnp.sum(valid.astype(gathers.dtype)), 1.0)


def gather_disp_image(xcf: jnp.ndarray, offsets: np.ndarray, dt: float,
                      dx: float, cfg: DispersionConfig = DispersionConfig(),
                      start_x: float | None = None,
                      end_x: float | None = None,
                      enhance: bool = False) -> jnp.ndarray:
    """Dispersion image of (a stack of) gathers over an offset sub-range
    (reference VirtualShotGather.compute_disp_image,
    apis/virtual_shot_gather.py:247-258 — which hardcodes dx=8.16; here the
    interrogator's dx is a parameter).  Returns (nvel, nfreq).

    ``cfg.method`` selects the transform: ``"fk"`` is the reference-parity
    2-D-FFT path; ``"phase_shift"`` is the frequency-domain slant stack
    (direction -1: the gather's offsets ascend toward the virtual source at
    0, so lag grows with decreasing x — see ops/dispersion.py).
    ``enhance=True`` applies the reference's CLAHE + blur post-processing
    (fv_map_enhance, modules/utils.py:613-619) and returns int32 0..255."""
    offsets = np.asarray(offsets)
    sxi = int(np.abs(offsets - (start_x if start_x is not None else offsets[0])).argmin())
    exi = int(np.abs(offsets - (end_x if end_x is not None else offsets[-1])).argmin())
    freqs = jnp.arange(cfg.freq_min, cfg.freq_max, cfg.freq_step)
    vels = jnp.arange(cfg.vel_min, cfg.vel_max, cfg.vel_step)
    sliced = xcf[..., sxi:exi + 1, :]
    if cfg.method == "phase_shift":
        img = fv_map_phase_shift(sliced, dx, dt, freqs, vels,
                                 direction=-1.0, whiten=False,
                                 precision=cfg.precision)
    else:
        img = fv_map_fk(sliced, dx, dt, freqs, vels, norm=cfg.norm,
                        sg_window=cfg.sg_window, sg_order=cfg.sg_order,
                        precision=cfg.precision)
    if enhance:
        from das_diff_veh_tpu.ops.enhance import fv_map_enhance
        img = fv_map_enhance(img)
    return img
