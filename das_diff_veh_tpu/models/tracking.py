"""Kalman-filter vehicle tracking on the quasi-static band.

TPU re-design of the reference tracker (apis/tracking.py:21-168,
modules/car_tracking_utils.py:21-66): the per-channel per-vehicle Python
double loop becomes one ``lax.scan`` over strided channels carrying all
vehicle states at once; peak detection is precomputed for every strided
channel as a vmapped batch (ops.peaks); track QC and NaN handling are
vectorized masks over fixed-capacity state tensors.

State model per vehicle (reference :84-155): 2-state [arrival-time sample
index, slowness] KF marched along channels; predict with A=[[1,dx],[0,1]] and
process noise Q = sigma_a*[[dx^4/4, dx^3/2],[dx^3/2, dx^2]]; asymmetric data
association gate (-15, +30] samples preferring the nearest *positive* lag;
update with C=[1,0], R=1 once a track has >2 recorded samples.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.config import TrackingConfig, TrackQCConfig
from das_diff_veh_tpu.core.section import VehicleTracks
from das_diff_veh_tpu.ops.interp import masked_interp_clamped
from das_diff_veh_tpu.ops.peaks import find_peaks, gaussian_likelihood


def detect_vehicle_base(data: jnp.ndarray, t_axis: jnp.ndarray,
                        start_x_idx: int, cfg: TrackingConfig = TrackingConfig(),
                        return_details: bool = False):
    """Stacked-likelihood vehicle arrival detection over ``n_detect_channels``
    consecutive channels at the section start (reference
    detect_in_one_section, apis/tracking.py:21-63).

    Returns (base_idx (max_vehicles,) int32, valid (max_vehicles,)); with
    ``return_details`` also the intermediates the reference's detection
    example figure shows (detection rows, per-row peaks, stacked likelihood
    — apis/tracking.py:47-60,197-237), consumed by ``viz.plot_detection``.
    """
    det = cfg.detect
    rows = jax.lax.dynamic_slice_in_dim(data, start_x_idx, cfg.n_detect_channels, 0)
    pk_pos, pk_valid = jax.vmap(
        lambda tr: find_peaks(tr, det.min_prominence, det.min_separation,
                              det.prominence_wlen, det.max_peaks))(rows)
    like = jax.vmap(lambda p, v: gaussian_likelihood(p, v, t_axis,
                                                     cfg.likelihood_sigma))(pk_pos, pk_valid)
    stacked = jnp.sum(like, axis=0)
    # reference :44: find_peaks(height=0, distance=minseparation) — local
    # maxima + distance pruning only
    base, valid = find_peaks(stacked, min_distance=det.min_separation,
                             max_peaks=cfg.max_vehicles, use_prominence=False)
    if return_details:
        return base, valid, (rows, pk_pos, pk_valid, stacked)
    return base, valid


class _KFCarry(NamedTuple):
    Tkk: jnp.ndarray       # (nveh, 2)
    Pkk: jnp.ndarray       # (nveh, 2, 2)
    Xv: jnp.ndarray        # (nveh,) x of last update (or first obs)
    count: jnp.ndarray     # (nveh,) recorded (non-NaN) samples so far
    obs1: jnp.ndarray      # (nveh,) first recorded sample index
    obs1_x: jnp.ndarray    # (nveh,) x where it was recorded


def _associate(pk_pos, pk_valid, pred, gate_lo, gate_hi, bug_compat=True):
    """Reference data association (apis/tracking.py:124-141): inside the
    asymmetric gate prefer a positive lag, else the smallest absolute lag;
    NaN when the gate is empty.

    ``bug_compat=True`` reproduces the reference's subset-indexing slip
    (:132-135): when a positive lag exists the *first* gated peak is recorded
    (which is the smallest positive only when no negative lags are gated).
    ``False`` records the smallest positive lag — the evident intent.
    """
    dist = pk_pos.astype(jnp.float32) - pred
    in_gate = pk_valid & (dist > gate_lo) & (dist <= gate_hi)
    pos = in_gate & (dist > 0)
    big = jnp.inf
    i_pos = (jnp.argmax(in_gate) if bug_compat
             else jnp.argmin(jnp.where(pos, dist, big)))
    i_abs = jnp.argmin(jnp.where(in_gate, jnp.abs(dist), big))
    any_pos = jnp.any(pos)
    any_gate = jnp.any(in_gate)
    choice = jnp.where(any_pos, i_pos, i_abs)
    return jnp.where(any_gate, pk_pos[choice].astype(pred.dtype), jnp.nan)


def track_vehicles(data: jnp.ndarray, x_axis, start_x: float,
                   end_x: float, base: jnp.ndarray, base_valid: jnp.ndarray,
                   cfg: TrackingConfig = TrackingConfig()):
    """March the per-vehicle KF along strided channels (reference
    tracking_with_veh_base, apis/tracking.py:65-156).

    ``x_axis``/``t_axis`` must be concrete (host) arrays.  Returns
    ``(veh_states (max_vehicles, n_steps) float — recorded arrival sample
    index per strided channel, NaN where unassociated; step_x (n_steps,))``.
    """
    x_axis = np.asarray(x_axis)
    start_x_idx = int(np.abs(start_x - x_axis).argmin())
    end_x_idx = int(np.abs(end_x - x_axis).argmin())
    step_idx = np.arange(start_x_idx, end_x_idx + 1, cfg.channel_stride)
    step_x = x_axis[step_idx]
    det = cfg.detect
    nveh = base.shape[0]

    rows = data[step_idx]
    pk_pos, pk_valid = jax.vmap(
        lambda tr: find_peaks(tr, det.min_prominence, det.min_separation,
                              det.prominence_wlen, det.max_peaks))(rows)

    base_f = jnp.where(base_valid, base, 0).astype(jnp.float32)
    init = _KFCarry(
        Tkk=jnp.zeros((nveh, 2), jnp.float32),
        Pkk=jnp.zeros((nveh, 2, 2), jnp.float32),
        Xv=jnp.zeros((nveh,), jnp.float32),
        count=jnp.zeros((nveh,), jnp.int32),
        obs1=jnp.zeros((nveh,), jnp.float32),
        obs1_x=jnp.zeros((nveh,), jnp.float32),
    )

    def step(carry: _KFCarry, inp):
        x_i, pos_i, valid_i = inp
        c0 = carry.count == 0
        c1 = carry.count == 1
        # the count==1 branch (reference :104-109) persistently re-seeds the
        # state from the single recorded sample
        Tkk = jnp.where(c1[:, None],
                        jnp.stack([carry.obs1, jnp.zeros_like(carry.obs1)], -1),
                        carry.Tkk)
        Pkk = jnp.where(c1[:, None, None], 0.0, carry.Pkk)
        Xv = jnp.where(c1, carry.obs1_x, carry.Xv)

        dx = x_i - Xv                                             # (nveh,)
        A = jnp.stack([jnp.stack([jnp.ones_like(dx), dx], -1),
                       jnp.stack([jnp.zeros_like(dx), jnp.ones_like(dx)], -1)], -2)
        Q = cfg.sigma_a * jnp.stack(
            [jnp.stack([0.25 * dx ** 4, 0.5 * dx ** 3], -1),
             jnp.stack([0.5 * dx ** 3, dx ** 2], -1)], -2)
        Tk1k = jnp.einsum("vij,vj->vi", A, Tkk)
        Pk1k = jnp.einsum("vij,vjk,vlk->vil", A, Pkk, A) + Q
        pred = jnp.where(c0 | c1, base_f, Tk1k[:, 0])

        obs = jax.vmap(lambda p: _associate(pos_i, valid_i, p,
                                            cfg.gate_lo, cfg.gate_hi,
                                            cfg.assoc_bug_compat))(pred)
        obs = jnp.where(base_valid, obs, jnp.nan)                 # padded slots stay empty
        rec = jnp.isfinite(obs)
        count = carry.count + rec.astype(jnp.int32)

        newly_first = rec & c0
        obs1 = jnp.where(newly_first, obs, carry.obs1)
        obs1_x = jnp.where(newly_first, x_i, carry.obs1_x)

        do_upd = (count > 2) & rec
        K = Pk1k[:, :, 0] / (cfg.meas_noise + Pk1k[:, 0, 0])[:, None]   # (nveh, 2)
        innov = jnp.where(rec, obs - Tk1k[:, 0], 0.0)
        Tkk_new = Tk1k + K * innov[:, None]
        Pkk_new = Pk1k - K[:, :, None] * Pk1k[:, 0:1, :]
        Tkk = jnp.where(do_upd[:, None], Tkk_new, Tkk)
        Pkk = jnp.where(do_upd[:, None, None], Pkk_new, Pkk)
        Xv = jnp.where(do_upd, x_i, Xv)

        return _KFCarry(Tkk, Pkk, Xv, count, obs1, obs1_x), obs

    xs = (jnp.asarray(step_x, jnp.float32), pk_pos, pk_valid)
    _, states = jax.lax.scan(step, init, xs)
    return states.T, step_x                                       # (nveh, n_steps)


def _compact(vals: jnp.ndarray, valid: jnp.ndarray):
    """Stable compaction: valid entries first, original order preserved."""
    n = vals.shape[-1]
    key = jnp.where(valid, jnp.arange(n), n + jnp.arange(n))
    order = jnp.argsort(key)
    return vals[order], valid[order]


def track_qc(veh_states: jnp.ndarray, qc: TrackQCConfig = TrackQCConfig()):
    """Vectorized remove_unrealistic_tracking
    (modules/car_tracking_utils.py:38-66) on the strided state array.

    Returns ``(veh_states with >max_jump jumps NaN'd, keep (nveh,) mask)``.
    Rejection tests use the pre-jump-masked values, like the reference.
    """
    ns = veh_states.shape[-1]
    w = int(qc.retrograde_window)

    def one(row):
        valid = jnp.isfinite(row)
        nv = jnp.sum(valid)
        vals, _ = _compact(jnp.where(valid, row, 0.0), valid)
        d = vals[1:] - vals[:-1]                     # diffs of consecutive valid samples
        nd = nv - 1
        d_ok = jnp.arange(d.shape[0]) < nd
        # retrograde: any 20-diff sliding sum <= threshold (conv 'valid');
        # with fewer than 20 diffs numpy's 'valid' convolve emits partial
        # sums all equal to sum(d), so total drift is tested instead
        cs = jnp.concatenate([jnp.zeros(1), jnp.cumsum(jnp.where(d_ok, d, 0.0))])
        win_sum = cs[w:] - cs[:-w]
        win_ok = jnp.arange(win_sum.shape[0]) + w <= nd
        retro_full = jnp.any(win_ok & (win_sum <= qc.retrograde_threshold))
        total = cs[jnp.clip(nd, 0, d.shape[0])]
        retro_partial = (nd > 0) & (nd < w) & (total <= qc.retrograde_threshold)
        retrograde = retro_full | retro_partial
        # total travel |last - first| scaled by coverage
        first = vals[0]
        last = vals[jnp.maximum(nv - 1, 0)]
        short = jnp.abs(last - first) < qc.min_travel_samples * (nv / ns)
        # adjacent-NaN pairs
        nanrow = ~valid
        adjacency = jnp.sum(nanrow[1:] & nanrow[:-1])
        reject = ((nv < qc.min_valid_fraction * ns) | retrograde | short |
                  (adjacency >= qc.max_adjacent_nan))
        # jump masking: the later sample of any |diff| > max_jump pair -> NaN
        jump = d_ok & (jnp.abs(d) > qc.max_jump)
        valid_pos = jnp.cumsum(valid) - 1                 # rank of each valid sample
        # sample with rank r+1 is NaN'd when diff r jumps
        jump_padded = jnp.concatenate([jnp.zeros(1, bool), jump])
        masked = jnp.where(valid & jump_padded[jnp.clip(valid_pos, 0, ns - 1)],
                           jnp.nan, row)
        return masked, ~reject

    masked, keep = jax.vmap(one)(veh_states)
    return masked, keep


def upsample_tracks(veh_states: jnp.ndarray, factor: int, n_out: int) -> jnp.ndarray:
    """Spread strided states onto the full channel grid and fill NaNs with
    np.interp semantics — linear inside the valid span, clamped to the edge
    values outside (reference tracking.py:162-166 + interp_nan_value)."""
    ns = veh_states.shape[-1]
    pos = jnp.arange(ns, dtype=veh_states.dtype) * factor
    q = jnp.arange(n_out, dtype=veh_states.dtype)

    def one(row):
        valid = jnp.isfinite(row)
        return masked_interp_clamped(q, pos, jnp.where(valid, row, 0.0), valid)

    return jax.vmap(one)(veh_states)


def track_grid(x_axis, start_x: float, end_x: float) -> np.ndarray:
    """Host copy of the [start_x, end_x]-restricted tracking x grid —
    exactly the axis :func:`track_section` returns as ``VehicleTracks.x``.
    Split out so callers that already hold the host metadata (the fused
    single-dispatch chunk program) can resolve downstream slice geometry
    without pulling ``tracks.x`` back off the device."""
    x_axis = np.asarray(x_axis)
    start_x_idx = int(np.abs(start_x - x_axis).argmin())
    end_x_idx = int(np.abs(end_x - x_axis).argmin())
    return x_axis[start_x_idx:end_x_idx + 1]


def track_section(data: jnp.ndarray, x_axis, t_axis, start_x: float,
                  end_x: float, cfg: TrackingConfig = TrackingConfig(),
                  qc: TrackQCConfig = TrackQCConfig()) -> VehicleTracks:
    """detect -> KF -> QC -> upsample: the full tracking stage
    (reference track_cars, apis/timeLapseImaging.py:104-119 +
    tracking.py:160-168).  Returns a VehicleTracks pytree on the tracking
    grid restricted to [start_x, end_x]."""
    x_axis = np.asarray(x_axis)
    t_axis = np.asarray(t_axis)
    start_x_idx = int(np.abs(start_x - x_axis).argmin())
    base, base_valid = detect_vehicle_base(data, jnp.asarray(t_axis),
                                           start_x_idx, cfg)
    states, _ = track_vehicles(data, x_axis, start_x, end_x,
                               base, base_valid, cfg)
    states, keep = track_qc(states, qc)
    grid = track_grid(x_axis, start_x, end_x)
    full = upsample_tracks(states, cfg.channel_stride, grid.shape[0])
    return VehicleTracks(t_idx=full, valid=base_valid & keep,
                         x=jnp.asarray(grid), t=jnp.asarray(t_axis))
