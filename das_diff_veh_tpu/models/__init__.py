"""Domain models: window selection/muting, virtual-shot gathers, tracking,
dispersion imaging, and the differentiable Rayleigh forward model."""
