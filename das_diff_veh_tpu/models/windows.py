"""Surface-wave window selection and trajectory-aware muting.

TPU-first re-design of the reference's SurfaceWaveSelector/SurfaceWaveWindow
(apis/data_classes.py:12-256): instead of a Python list of deep-copied window
objects, selection produces one static-shape :class:`WindowBatch` tensor with
a validity mask — every vehicle slot yields a (nx, nt_win) slice via
``dynamic_slice`` whether accepted or not, and rejected slots are masked.
Muting builds multiplicative (nx, nt) Tukey masks in one vectorized gather
instead of the reference's per-time-sample Python loop
(apis/data_classes.py:60-70).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.config import MuteConfig, WindowConfig
from das_diff_veh_tpu.core.section import VehicleTracks, WindowBatch
from das_diff_veh_tpu.ops.filters import tukey_window
from das_diff_veh_tpu.ops.interp import masked_interp


def traj_mute_mask(x_axis: jnp.ndarray, t_axis: jnp.ndarray,
                   traj_x: jnp.ndarray, traj_t: jnp.ndarray,
                   traj_valid: jnp.ndarray, dx: float,
                   offset: float = 200.0, alpha: float = 0.3,
                   delta_x: float = 20.0,
                   double_sided: bool = False) -> jnp.ndarray:
    """(nx, nt) multiplicative mute mask following the vehicle trajectory.

    Per time sample the mask is an ``int(offset/dx)``-sample Tukey window
    whose center tracks the interpolated car position — off-center by
    ``-offset/2 + delta_x`` in the single-sided variant (reference
    apis/data_classes.py:62) or centered in the double-sided one (:88); zero
    outside the taper.  The reference's ``argmax(x_axis > center)`` center
    pick (:63) is kept bit-for-bit, including its all-False -> 0 behavior.
    """
    n_samp = int(offset / dx)
    w = tukey_window(n_samp, alpha)
    car_x = masked_interp(t_axis, traj_t, traj_x, traj_valid)     # (nt,)
    center = car_x if double_sided else car_x - offset / 2.0 + delta_x
    center_idx = jnp.argmax(x_axis[:, None] > center[None, :], axis=0)   # (nt,)
    j = jnp.arange(x_axis.shape[0])[:, None] - (center_idx[None, :] - n_samp // 2)
    inside = (j >= 0) & (j < n_samp)
    return jnp.where(inside, w[jnp.clip(j, 0, n_samp - 1)], 0.0)


def mute_along_traj(data: jnp.ndarray, x_axis: jnp.ndarray, t_axis: jnp.ndarray,
                    traj_x: jnp.ndarray, traj_t: jnp.ndarray,
                    traj_valid: jnp.ndarray, dx: float,
                    cfg: MuteConfig = MuteConfig(),
                    double_sided: bool = False) -> jnp.ndarray:
    """Apply the trajectory mute (reference apis/data_classes.py:49-98)."""
    alpha = cfg.alpha_double if double_sided else cfg.alpha
    mask = traj_mute_mask(x_axis, t_axis, traj_x, traj_t, traj_valid, dx,
                          offset=cfg.offset, alpha=alpha,
                          delta_x=cfg.delta_x, double_sided=double_sided)
    return data * mask


def mute_along_time(data: jnp.ndarray, alpha: float = 0.3) -> jnp.ndarray:
    """Temporal Tukey mute (reference apis/data_classes.py:100-104)."""
    return data * tukey_window(data.shape[-1], alpha)[None, :]


def window_x_bounds(x: np.ndarray, x0: float,
                    cfg: WindowConfig = WindowConfig()) -> tuple:
    """Host ``(start_x_idx, end_x_idx)`` of the window aperture around pivot
    ``x0`` — the slice geometry :func:`select_windows` cuts with (end
    exclusive, reference apis/data_classes.py:212).  Split out so the fused
    chunk program (and the VSG geometry builder feeding on ``batch.x``) can
    resolve the aperture from host metadata without touching the device."""
    x = np.asarray(x)
    start_x = x0 - cfg.length_sw * cfg.spatial_ratio
    end_x = start_x + cfg.length_sw
    return (int(np.abs(start_x - x).argmin()),
            int(np.abs(end_x - x).argmin()))


def window_x_slice(x: np.ndarray, x0: float,
                   cfg: WindowConfig = WindowConfig()) -> np.ndarray:
    """Host copy of the ``WindowBatch.x`` axis :func:`select_windows`
    produces for this geometry."""
    start_x_idx, end_x_idx = window_x_bounds(x, x0, cfg)
    return np.asarray(x)[start_x_idx:end_x_idx]


def select_windows(data: jnp.ndarray, x: np.ndarray, t: np.ndarray,
                   tracks: VehicleTracks, x0: float,
                   cfg: WindowConfig = WindowConfig(), *,
                   track_x: np.ndarray = None,
                   track_t: np.ndarray = None) -> WindowBatch:
    """Cut one static-shape window batch around each tracked vehicle's arrival
    at pivot ``x0`` (reference SurfaceWaveSelector.locate_windows,
    apis/data_classes.py:170-223).

    Accept/reject logic (as validity masks instead of ``continue``):

    - the vehicle state at ``x0`` must be finite;
    - *isolation*: the arrival-time gap at ``x0`` to the list-adjacent
      vehicles (detection order = arrival order) must be >=
      ``temporal_spacing`` (reference :180-193); neighbors without a finite
      arrival at ``x0`` (padding slots / undetected-at-pivot) are skipped;
    - *boundary*: the +-wlen/2 cut must fit inside the record (:199-200).

    ``x``/``t`` must be concrete (host) arrays — static slice geometry is
    resolved in numpy; the per-vehicle time cuts are vmapped dynamic slices.
    ``data`` may be a tracer (the fused chunk program calls this inside
    jit); pass ``track_x``/``track_t`` (host copies of ``tracks.x``/
    ``tracks.t``, e.g. from ``models.tracking.track_grid``) in that case so
    the tracking-grid geometry below never reads the device."""
    if not isinstance(data, jax.core.Tracer):
        # sync in-flight device work first: the axon TPU tunnel cannot
        # service a device->host read (the np.asarray geometry below) while
        # compute is in flight, and the failure poisons the stream
        jax.block_until_ready(data)
    x = np.asarray(x)
    t = np.asarray(t)
    dt = float(t[1] - t[0])
    win_nsamp = int(cfg.wlen_sw / dt)
    spacing = cfg.temporal_spacing if cfg.temporal_spacing else cfg.wlen_sw

    start_x_idx, end_x_idx = window_x_bounds(x, x0, cfg)
    nx = end_x_idx - start_x_idx

    x_track = np.asarray(tracks.x if track_x is None else track_x)
    t_track = np.asarray(tracks.t if track_t is None else track_t)
    x0_track_idx = int(np.abs(x_track - x0).argmin())
    dt_track = float(t_track[1] - t_track[0])
    t_track0 = float(t_track[0])
    nt = t.shape[0]

    t_idx = tracks.t_idx                                  # (nveh, n_track_ch)
    raw = t_idx[:, x0_track_idx]                          # float sample index at x0
    finite = jnp.isfinite(raw)
    # reference: int(v[x0_idx]) truncation, then t_axis_tracking lookup (:177,195)
    t0_i = jnp.clip(jnp.floor(jnp.where(finite, raw, 0.0)), 0, t_track.shape[0] - 1)
    t0 = t_track0 + t0_i * dt_track

    valid = tracks.valid & finite

    # isolation against the list-adjacent vehicles (reference :180-193),
    # skipping neighbors without a finite arrival at x0
    t0_next = jnp.concatenate([t0[1:], jnp.asarray([0.0])])
    next_finite = jnp.concatenate([finite[1:], jnp.asarray([False])])
    t0_prev = jnp.concatenate([jnp.asarray([0.0]), t0[:-1]])
    prev_finite = jnp.concatenate([jnp.asarray([False]), finite[:-1]])
    reject_next = next_finite & ((t0_next - t0) < spacing)
    gap_prev = t0 - t0_prev
    reject_prev = prev_finite & (gap_prev >= 0) & (gap_prev < spacing)
    valid = valid & ~reject_next & ~reject_prev

    # boundary test on the surface-wave grid (reference :196-200)
    t0_sw_idx = jnp.clip(jnp.round((t0 - t[0]) / dt).astype(jnp.int32), 0, nt - 1)
    valid = valid & (t0_sw_idx >= win_nsamp // 2) & (t0_sw_idx + win_nsamp // 2 <= nt)

    start_t_idx = jnp.clip(t0_sw_idx - win_nsamp // 2, 0, nt - win_nsamp)
    sub = data[start_x_idx:end_x_idx]

    def cut(st):
        return jax.lax.dynamic_slice(sub, (jnp.zeros((), st.dtype), st),
                                     (nx, win_nsamp))

    win_data = jax.vmap(cut)(start_t_idx)                 # (nveh, nx, win_nsamp)
    win_t = t[0] + (start_t_idx[:, None] + jnp.arange(win_nsamp)[None, :]) * dt

    # trajectory in physical coordinates, floor-quantized to the tracking grid
    # exactly like _preprocess_veh_state (reference apis/data_classes.py:34-39)
    traj_t = t_track0 + jnp.floor(t_idx) * dt_track       # NaN-preserving
    traj_x = jnp.broadcast_to(jnp.asarray(x_track), t_idx.shape)

    return WindowBatch(data=win_data, x=jnp.asarray(x[start_x_idx:end_x_idx]),
                       t=win_t, traj_x=traj_x, traj_t=traj_t, valid=valid)
