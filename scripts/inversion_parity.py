"""Run the full reference-parity Vs inversions (BASELINE config 5).

Reproduces inversion_diff_speed.ipynb / inversion_diff_weight.ipynb cells
5-9 on the reference's shipped bootstrap-ridge archives: per vehicle class,
build modal curves (bands 0/2/3 -> modes 0/3/4), invert with the TPU-batched
swarm + optax refinement, and report the evodcinv-style weighted RMSE
(reference best: 0.2210 speed classes / 0.1164 weight classes).

Search runs on the default JAX device (TPU f32 under axon); the final best
model is re-scored on CPU float64 against the *full-resolution* curves so
the reported misfit is not a decimated or reduced-precision estimate.

Usage: python scripts/inversion_parity.py [--quick] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from das_diff_veh_tpu.inversion import (curves_from_ridges,
                                        load_reference_ridge_npz,
                                        make_misfit_fn, invert,
                                        speed_model_spec, weight_model_spec)
from das_diff_veh_tpu.inversion.curves import Curve

REF_DATA = os.environ.get("DAS_REF_DATA", "/root/reference/data")

# (archive, class key, ModelSpec, band->(mode, weight) rows used)  - from
# inversion_diff_speed.ipynb cell 5 and inversion_diff_weight.ipynb cell 5.
CASES = [
    ("700_speeds.npz", "vels_fast", "speed", [(0, 0, 1.0), (3, 4, 1.0)]),
    ("700_speeds.npz", "vels_mid", "speed",
     [(0, 0, 2.0), (2, 3, 1.0), (3, 4, 1.0)]),
    ("700_speeds.npz", "vels_slow", "speed",
     [(0, 0, 1.0), (2, 3, 1.0), (3, 4, 1.0)]),
    ("700_weights.npz", "vels_heavy", "weight",
     [(0, 0, 2.0), (2, 3, 1.0), (3, 4, 1.0)]),
    ("700_weights.npz", "vels_mid", "weight",
     [(0, 0, 2.0), (2, 3, 1.0), (3, 4, 1.0)]),
    ("700_weights.npz", "vels_light", "weight", [(0, 0, 2.0), (3, 4, 1.0)]),
]


def build_curves(archive: str, key: str, rows, decimate: int = 1):
    d = load_reference_ridge_npz(os.path.join(REF_DATA, archive))
    bands = [np.stack([np.asarray(v, dtype=np.float64) for v in d[key][i]])
             for i in range(len(d[key]))]
    use = [r[0] for r in rows]
    curves = curves_from_ridges(
        d["freqs"], d["freq_lb"], d["freq_ub"], bands,
        band_modes=[dict((b, m) for b, m, _ in rows).get(i, 0)
                    for i in range(len(bands))],
        weights=[dict((b, w) for b, _, w in rows).get(i, 1.0)
                 for i in range(len(bands))],
        skip_bands=[i for i in range(len(bands)) if i not in use])
    if decimate > 1:
        curves = [Curve(c.period[::decimate], c.velocity[::decimate], c.mode,
                        c.weight, c.uncertainty[::decimate]) for c in curves]
    return curves


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default="INVERSION_PARITY.json")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    popsize, maxiter, ref_steps = (24, 40, 40) if args.quick else (50, 300, 150)
    results = {}
    for archive, key, spec_name, rows in CASES:
        spec = speed_model_spec() if spec_name == "speed" else weight_model_spec()
        dec = build_curves(archive, key, rows, decimate=3)
        t0 = time.time()
        res = invert(spec, dec, popsize=popsize, maxiter=maxiter,
                     n_refine_starts=8, n_refine_steps=ref_steps,
                     n_grid=300, seed=args.seed)
        search_t = time.time() - t0
        # final f64 full-resolution scoring on CPU
        full = build_curves(archive, key, rows, decimate=1)
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            mf64 = make_misfit_fn(spec, full, n_grid=600)
            x = jax.device_put(np.asarray(res.x_best, dtype=np.float64), cpu)
            final = float(mf64(x))
        name = f"{archive.split('_')[0]}_{key.removeprefix('vels_')}_{spec_name}"
        results[name] = {
            "misfit_f64_full": final,
            "misfit_search": float(res.misfit),
            "search_seconds": round(search_t, 1),
            "vs_km_s": np.asarray(res.model.vs).round(4).tolist(),
            "thickness_m": (np.asarray(res.model.thickness)[:-1]
                            * 1000).round(1).tolist(),
        }
        print(name, json.dumps(results[name]), flush=True)

    results["reference_best"] = {"speed": 0.2210, "weight": 0.1164}
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote", args.out)


if __name__ == "__main__":
    main()
