"""Run the full reference-parity Vs inversions (BASELINE config 5).

Reproduces inversion_diff_speed.ipynb / inversion_diff_weight.ipynb cells
5-9 on the reference's shipped bootstrap-ridge archives: per vehicle class,
build modal curves (bands 0/2/3 -> modes 0/3/4), invert with the TPU-batched
swarm + optax refinement, and report the evodcinv-style weighted RMSE
(reference best: 0.2210 speed classes / 0.1164 weight classes).  Also covers
the second-pivot ``680_weights.npz`` archive (which no reference notebook
ever inverts — band map established empirically, see CASES) and the joint
two-pivot inversion of BASELINE config 5 (both pivots' curves in one
misfit).

Precision policy: the process enables x64 so float64 stays float64 (the
round-2 version silently downcast the final rescore to f32); the *search*
runs in explicit float32 on the default JAX device (TPU under axon), and
the final best model is re-scored in float64 on CPU against the
full-resolution curves at tightened root-solve settings, so the reported
misfit is neither decimated nor reduced-precision.

Two final numbers per class:
- ``misfit_f64_full``  — our objective (below-cutoff overtone samples carry
  the fixed INVALID_RESIDUAL=5 penalty);
- ``misfit_truncated`` — evodcinv's semantics (below-cutoff samples are
  *dropped*, rmse over the surviving prefix), directly comparable to the
  reference's 0.2210/0.1164, plus ``n_below_cutoff``.

Usage: python scripts/inversion_parity.py [--quick] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

from das_diff_veh_tpu.cache import enable_compilation_cache  # noqa: E402

enable_compilation_cache(_REPO)

from das_diff_veh_tpu.inversion import (curves_from_ridges,  # noqa: E402
                                        invert, invert_multirun,
                                        load_reference_ridge_npz,
                                        make_misfit_fn,
                                        phase_velocity,
                                        scan_mode_diagnostics,
                                        speed_model_spec, weight_model_spec)
from das_diff_veh_tpu.inversion.curves import Curve  # noqa: E402

REF_DATA = os.environ.get("DAS_REF_DATA", "/root/reference/data")

# Band -> (mode, weight) rows follow inversion_diff_speed.ipynb cell 5 /
# inversion_diff_weight.ipynb cell 5 (700 m archives: bands 0/2/3 are
# modes 0/3/4, band 1 unused by the reference inversions).
_700_SPEED_FAST = [("700_speeds.npz", "vels_fast", [(0, 0, 1.0), (3, 4, 1.0)])]
_700_WEIGHT_MID = [("700_weights.npz", "vels_mid",
                    [(0, 0, 2.0), (2, 3, 1.0), (3, 4, 1.0)])]
# 680 m archive (data/680_weights.npz, 20 bootstrap reps, 2 bands): no
# reference notebook consumes it, so the band->mode map is established
# empirically — predicting overtones 1-4 from the already-inverted 700 m
# mid-speed model puts the archive's 9-15 Hz band on MODE 1 (4.2% median
# error vs >=16% for modes 2-4; same site, so the identification carries).
_680 = lambda key: [("680_weights.npz", key, [(0, 0, 2.0), (1, 1, 1.0)])]

# (name, ModelSpec, [(archive, class key, band rows), ...]) — multi-source
# entries concatenate both archives' curves into ONE misfit (the joint
# 600m+700m inversion of BASELINE config 5).
CASES = [
    ("700_fast_speed", "speed", _700_SPEED_FAST),
    ("700_mid_speed", "speed",
     [("700_speeds.npz", "vels_mid", [(0, 0, 2.0), (2, 3, 1.0), (3, 4, 1.0)])]),
    ("700_slow_speed", "speed",
     [("700_speeds.npz", "vels_slow", [(0, 0, 1.0), (2, 3, 1.0), (3, 4, 1.0)])]),
    ("700_heavy_weight", "weight",
     [("700_weights.npz", "vels_heavy",
       [(0, 0, 2.0), (2, 3, 1.0), (3, 4, 1.0)])]),
    ("700_mid_weight", "weight", _700_WEIGHT_MID),
    ("700_light_weight", "weight",
     [("700_weights.npz", "vels_light", [(0, 0, 2.0), (3, 4, 1.0)])]),
    ("680_heavy_weight", "weight", _680("vels_heavy")),
    ("680_mid_weight", "weight", _680("vels_mid")),
    ("680_light_weight", "weight", _680("vels_light")),
    # joint two-pivot inversion: one model must explain both pivots' curve
    # sets simultaneously (5 curves, modes 0/1/3/4)
    ("joint_mid_weight", "weight", _700_WEIGHT_MID + _680("vels_mid")),
]


def build_curves(sources, decimate: int = 1):
    """Concatenated Curve list over one or more (archive, key, rows)."""
    curves = []
    for archive, key, rows in sources:
        d = load_reference_ridge_npz(os.path.join(REF_DATA, archive))
        bands = [np.stack([np.asarray(v, dtype=np.float64) for v in d[key][i]])
                 for i in range(len(d[key]))]
        use = [r[0] for r in rows]
        curves += curves_from_ridges(
            d["freqs"], d["freq_lb"], d["freq_ub"], bands,
            band_modes=[dict((b, m) for b, m, _ in rows).get(i, 0)
                        for i in range(len(bands))],
            weights=[dict((b, w) for b, _, w in rows).get(i, 1.0)
                     for i in range(len(bands))],
            skip_bands=[i for i in range(len(bands)) if i not in use])
    if decimate > 1:
        curves = [Curve(c.period[::decimate], c.velocity[::decimate], c.mode,
                        c.weight, c.uncertainty[::decimate]) for c in curves]
    return curves


def warm_points(spec, entry, rng, n_pts: int = 8):
    """Unit-cube warm-start points from a prior result entry.

    Entries carrying ``x_best`` reproduce it exactly; older entries are
    reconstructed from ``vs_km_s``/``thickness_m`` (free-Poisson specs get
    ``n_pts`` random nu draws since nu was not recorded; the ignored
    halfspace-thickness coordinate stays random too)."""
    if "x_best" in entry:
        return np.asarray(entry["x_best"], np.float64)[None, :]
    lo, hi = (np.asarray(a, np.float64) for a in spec.bounds_arrays())
    n = spec.n_layers
    pts = rng.uniform(0.05, 0.95, size=(n_pts, spec.n_params))
    unit = lambda v, i: np.clip((v - lo[i]) / (hi[i] - lo[i]), 0.0, 1.0)
    for i, v in enumerate(np.asarray(entry["thickness_m"], float) / 1000.0):
        pts[:, i] = unit(v, i)
    for i, v in enumerate(np.asarray(entry["vs_km_s"], float)):
        pts[:, n + i] = unit(v, n + i)
    return pts


def rescore_f64(spec, curves, x_best, n_grid: int = 600):
    """Float64 CPU rescoring of one model against full-resolution curves.

    Returns (penalty_rmse, truncated_rmse, n_below_cutoff): the first uses
    our INVALID_RESIDUAL=5 convention, the second drops below-cutoff points
    like evodcinv truncates predicted curves — apples-to-apples with the
    reference's recorded 0.2210 / 0.1164 misfits.  Both reuse
    ``make_misfit_fn``'s two ``invalid`` modes so the reported score can
    never drift from the search objective's semantics.
    """
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        x = jnp.asarray(np.asarray(x_best, np.float64))
        pen = float(make_misfit_fn(spec, curves, n_grid=n_grid, n_subdiv=3,
                                   invalid="penalty")(x))
        trunc = float(make_misfit_fn(spec, curves, n_grid=n_grid, n_subdiv=3,
                                     invalid="truncate")(x))
        # fleet-engine cross-check: the packed masked misfit (segment
        # reduction) must reproduce the closure oracle (static slicing) at
        # the scored model in BOTH invalid modes, or the committed numbers
        # would not transfer to invert_fleet
        from das_diff_veh_tpu.inversion import (make_packed_misfit_fn,
                                                pack_curve_sets)
        data = jax.tree.map(lambda a: a[0], pack_curve_sets([curves]))
        for mode, ref in (("penalty", pen), ("truncate", trunc)):
            packed = float(make_packed_misfit_fn(
                spec, n_grid=n_grid, n_subdiv=3, invalid=mode)(x, data))
            if abs(packed - ref) > 1e-8 * max(1.0, abs(ref)):
                raise AssertionError(
                    f"packed {mode} misfit {packed!r} != closure {ref!r}")
        # below-cutoff count from ONE concatenated forward call (same shape
        # as the misfit's internal call -> shares its compiled executable)
        model = spec.to_model(x)
        period_all = jnp.asarray(np.concatenate([c.period for c in curves]))
        mode_all = jnp.asarray(np.concatenate(
            [np.full(len(c.period), c.mode) for c in curves]))
        pred = phase_velocity(period_all, model, mode=mode_all,
                              n_grid=n_grid, n_subdiv=3)
        n_cut = int((~np.isfinite(np.asarray(pred))).sum())
        # mode-miss guard at the SEARCH resolution (n_grid=300): any missed
        # root pair or osculation dip at a scored period means the search
        # objective may have indexed an overtone one branch low there
        diag = scan_mode_diagnostics(period_all, model, n_grid=300)
        n_missed = int(np.asarray(diag["missed"]).sum())
        n_dip = int(np.asarray(diag["dip"]).sum())
        return pen, trunc, n_cut, {"periods_missed_roots_at_n300": n_missed,
                                   "periods_osculation_dip_at_n300": n_dip}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default="INVERSION_PARITY.json")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--maxrun", type=int, default=3,
                    help="independent seeds per class, best kept — the "
                         "reference's EarthModel.invert(maxrun=5) semantics")
    ap.add_argument("--cases", default=None,
                    help="comma-separated substrings; only matching class "
                         "names run (e.g. 'light,heavy')")
    ap.add_argument("--popsize", type=int, default=None)
    ap.add_argument("--maxiter", type=int, default=None)
    ap.add_argument("--refine-steps", type=int, default=None)
    ap.add_argument("--batched", action="store_true",
                    help="advance all maxrun restarts as one vmapped "
                         "computation (invert_multirun). Fastest when the "
                         "device has headroom; this environment's tunneled "
                         "TPU worker has crashed mid-refinement under the "
                         "full batched budget, so serial restarts are the "
                         "default here")
    ap.add_argument("--merge", action="store_true",
                    help="start from the existing --out file and only "
                         "replace a class when the new truncated misfit is "
                         "lower (budget-escalation reruns of weak classes)")
    ap.add_argument("--warm-start", action="store_true",
                    help="seed each rerun class's swarm with the prior "
                         "result (x_best if recorded, else reconstructed "
                         "from vs/thickness); implies --merge so a weaker "
                         "rerun can never overwrite the prior it started "
                         "from")
    ap.add_argument("--invalid", choices=("truncate", "penalty"),
                    default="truncate",
                    help="below-cutoff handling in the SEARCH objective: "
                         "'truncate' is evodcinv's semantics (reference "
                         "parity), but it rewards models that push hard "
                         "overtone samples below cutoff; 'penalty' forces "
                         "full curve coverage (each missing sample costs "
                         "INVALID_RESIDUAL) — use for full-coverage reruns "
                         "of classes the truncate search gamed")
    args = ap.parse_args()
    if args.warm_start:
        args.merge = True

    popsize, maxiter, ref_steps = (24, 60, 40) if args.quick else (50, 300, 150)
    popsize = args.popsize or popsize
    maxiter = args.maxiter or maxiter
    ref_steps = args.refine_steps or ref_steps
    run_cfg = {"popsize": popsize, "maxiter": maxiter,
               "refine_steps": ref_steps, "seed": args.seed,
               "maxrun": args.maxrun, "warm_start": bool(args.warm_start),
               "invalid": args.invalid}
    # resume: a crashed TPU worker kills the whole jax backend for this
    # process, so recovery = rerun the script; completed cases of the SAME
    # run config are skipped (a config change invalidates the partial file)
    results = {}
    if os.path.exists(args.out + ".partial"):
        with open(args.out + ".partial") as f:
            prior = json.load(f)
        if prior.get("config", {}) == run_cfg:
            results = {k: v for k, v in prior.items()
                       if isinstance(v, dict) and "misfit_f64_full" in v}
            print(f"resuming; {len(results)} case(s) already done", flush=True)
        else:
            print("partial file is from a different config; starting fresh",
                  flush=True)
    # existing per-class results always carry over for classes excluded by
    # --cases (so a filtered run can never silently drop the other classes
    # from the canonical output); --merge additionally keeps the better of
    # old/new for the classes that DO rerun
    merged = {}
    if os.path.exists(args.out):
        with open(args.out) as f:
            prior_all = json.load(f)
        merged = {k: v for k, v in prior_all.items()
                  if isinstance(v, dict) and "misfit_f64_full" in v}
        # provenance: entries predating per-class search_config inherit the
        # file's global config block, so carried-over classes keep an
        # accurate record of the settings that actually produced them
        prior_cfg = {k: v for k, v in prior_all.get("config", {}).items()
                     if k in ("popsize", "maxiter", "refine_steps", "seed",
                              "maxrun")}
        # the global block only reliably describes the LAST invocation, so a
        # backfilled per-class config is a best guess, marked as such
        for v in merged.values():
            v.setdefault("search_config", {**prior_cfg, "assumed": True})
    # announce scope up front: the substring filter now matches across
    # pivots (e.g. 'mid_weight' hits 700_/680_/joint_), so print exactly
    # which classes this invocation will run before spending search budget
    selected = [n for n, _, _ in CASES
                if n not in results            # resumed classes won't rerun
                and (not args.cases
                     or any(s in n for s in args.cases.split(",")))]
    print("cases to run:", ", ".join(selected) or "(none)", flush=True)
    t_all = time.time()
    for name, spec_name, sources in CASES:
        spec = speed_model_spec() if spec_name == "speed" else weight_model_spec()
        if name in results:
            continue
        if args.cases and not any(s in name for s in args.cases.split(",")):
            if name in merged:
                results[name] = merged[name]
            continue
        dec = build_curves(sources, decimate=3)
        x0 = None
        if args.warm_start and name in merged:
            x0 = warm_points(spec, merged[name],
                             np.random.default_rng(args.seed + 1000))
            print(f"  {name}: warm-starting from {x0.shape[0]} prior "
                  f"point(s)", flush=True)
        t0 = time.time()
        if args.batched:
            # all maxrun restarts advance as ONE vmapped computation;
            # eval/refine chunking bounds the device working set
            res = invert_multirun(spec, dec, n_runs=args.maxrun,
                                  popsize=popsize, maxiter=maxiter,
                                  n_refine_starts=8, n_refine_steps=ref_steps,
                                  n_grid=300, dtype=jnp.float32,
                                  invalid=args.invalid, seed=args.seed,
                                  eval_chunk=max(8, 64 // args.maxrun),
                                  refine_chunk=8, x0=x0)
            print(f"  {name}: best-of-{args.maxrun} search misfit "
                  f"{float(res.misfit):.4f}", flush=True)
        else:
            # one misfit closure per class: the jitted swarm/refine
            # executables key on its identity, so restarts re-trace nothing
            mf = make_misfit_fn(spec, dec, n_grid=300, dtype=jnp.float32,
                                invalid=args.invalid)
            res = None
            for run in range(args.maxrun):
                r = invert(spec, dec, popsize=popsize, maxiter=maxiter,
                           n_refine_starts=8, n_refine_steps=ref_steps,
                           n_grid=300, dtype=jnp.float32, invalid=args.invalid,
                           seed=args.seed + run, misfit_fn=mf, x0=x0)
                print(f"  {name} run {run}: misfit {float(r.misfit):.4f}",
                      flush=True)
                if res is None or float(r.misfit) < float(res.misfit):
                    res = r
        x_best = np.asarray(res.x_best, dtype=np.float64)
        search_t = time.time() - t0
        full = build_curves(sources, decimate=1)
        pen, trunc, n_cut, scan_diag = rescore_f64(spec, full, x_best)
        if (args.merge and name in merged
                and merged[name]["misfit_truncated"] <= round(trunc, 4)):
            print(f"  {name}: new {trunc:.4f} not better than kept "
                  f"{merged[name]['misfit_truncated']:.4f}", flush=True)
            results[name] = dict(merged[name])
            # symmetric alternate-keeping: a challenger that loses on the
            # (gameable) truncated metric but covers MORE of the curves with
            # a better honest penalty misfit is preserved inside the kept
            # entry — e.g. a --invalid penalty rerun of a class whose
            # truncate search pushed overtone samples below cutoff
            kept = results[name]
            old_alt = kept.get("full_coverage_alternate", {})
            if (n_cut < kept.get("n_below_cutoff", 0)
                    and n_cut <= old_alt.get("n_below_cutoff", 10**9)
                    and round(pen, 4) < kept.get("misfit_f64_full", 1e9)
                    and round(pen, 4) < old_alt.get("misfit_f64_full", 1e9)):
                kept["full_coverage_alternate"] = {
                    "misfit_f64_full": round(pen, 4),
                    "misfit_truncated": round(trunc, 4),
                    "n_below_cutoff": n_cut,
                    "vs_km_s": np.asarray(res.model.vs).round(4).tolist(),
                    "thickness_m": (np.asarray(res.model.thickness)[:-1]
                                    * 1000).round(1).tolist(),
                    "x_best": x_best.round(6).tolist(),
                    "search_config": run_cfg,
                }
                print(f"  {name}: kept challenger as full-coverage "
                      f"alternate (pen {pen:.4f}, n_cut {n_cut})", flush=True)
            with open(args.out + ".partial", "w") as f:
                json.dump({**results, "config": run_cfg}, f, indent=1)
            continue
        # keep-best keys on evodcinv's truncated RMSE (the reference's own
        # scoring, which drops below-cutoff overtone samples).  That metric
        # rewards models whose overtones vanish at scored periods, so when a
        # challenger wins, any fuller-coverage model already known — the
        # incumbent itself, or the incumbent's stored alternate (e.g. from a
        # --invalid penalty rerun) — survives inside the new entry as the
        # full-coverage alternate instead of being silently discarded.
        alternate = None
        if args.merge and name in merged:
            cands = []
            if n_cut > merged[name].get("n_below_cutoff", 0):
                cands.append({k: merged[name][k] for k in
                              ("misfit_f64_full", "misfit_truncated",
                               "n_below_cutoff", "vs_km_s", "thickness_m",
                               "x_best") if k in merged[name]})
            old_alt = merged[name].get("full_coverage_alternate")
            if old_alt and old_alt.get("n_below_cutoff", 0) < n_cut:
                cands.append(old_alt)
            if cands:
                # fullest coverage wins first; honest misfit breaks ties —
                # never trade away the only zero-cutoff model for a lower
                # misfit with more dropped samples
                alternate = min(cands, key=lambda c: (
                    c.get("n_below_cutoff", 10**9),
                    c.get("misfit_f64_full", 1e9)))
        results[name] = {
            "misfit_f64_full": round(pen, 4),
            "misfit_truncated": round(trunc, 4),
            "n_below_cutoff": n_cut,
            "misfit_search_f32": round(float(res.misfit), 4),
            "search_seconds": round(search_t, 1),
            "vs_km_s": np.asarray(res.model.vs).round(4).tolist(),
            "thickness_m": (np.asarray(res.model.thickness)[:-1]
                            * 1000).round(1).tolist(),
            "x_best": x_best.round(6).tolist(),   # unit-cube params: lets a
            # later run warm-start/re-polish without re-searching
            "scan_diag": scan_diag,     # mode-miss guard verdict (forward.py
            # scan_mode_diagnostics): nonzero counts => overtone indexing at
            # the search resolution is suspect for this model
            "search_config": run_cfg,   # per-class: merge reruns may escalate
        }
        if alternate is not None:
            results[name]["full_coverage_alternate"] = alternate
        print(name, json.dumps(results[name]), flush=True)
        with open(args.out + ".partial", "w") as f:
            json.dump({**results, "config": run_cfg}, f, indent=1)

    results["reference_best"] = {
        "speed": 0.2210, "weight": 0.1164,
        "minutes_per_class": "17-20 (evodcinv CPSO)",
        "note": "headline metric is FULL-coverage RMSE (every sample "
                "scored; full_coverage_alternate where it differs from the "
                "truncated-search result, and listed FIRST in the entry). "
                "misfit_truncated is the evodcinv-comparable secondary "
                "(below-cutoff overtone samples dropped): an entry with "
                "n_below_cutoff>0 scores on fewer samples than one with 0. "
                "680_*/joint_* have no reference counterpart (the 680 "
                "archive is shipped but never inverted by the reference).",
    }
    # per-class provenance lives in each entry's search_config; this block
    # records only THIS invocation (merge reruns leave other classes as-is)
    results["config"] = {**run_cfg, "device": str(jax.devices()[0]),
                         "this_invocation_seconds": round(time.time() - t_all, 1),
                         "note": "settings of the last invocation only; "
                                 "per-class settings in search_config"}
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    if os.path.exists(args.out + ".partial"):
        os.remove(args.out + ".partial")
    print("wrote", args.out)


if __name__ == "__main__":
    main()
