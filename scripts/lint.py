#!/usr/bin/env python
"""Repo lint entry point: ruff when available, ast fallback otherwise.

Every recent PR re-improvised the offline fallback the verify recipe
describes; this commits it.  With ruff importable (the `[dev]` extra) the
script delegates to ``python -m ruff check .`` — the committed rule set in
pyproject.toml (E4/E7/E9/F + I import sorting).  Without it (this
container bakes no ruff and installing is off-limits) the fallback walks
the tree with ``ast`` and enforces the two classes of finding the fallback
has always covered:

- **syntax**: every ``.py`` file must parse (ruff's E9);
- **import order** (I001's defaults): within each contiguous top-level
  import block, sections run future/stdlib -> third-party -> first-party
  (``das_diff_veh_tpu``) -> relative; within a section straight
  ``import x`` statements come before ``from x import y``, each kind
  sorted case-insensitively by module path; ``from``-import name lists
  follow isort's ``order_by_type`` default (CONSTANTS, Classes, then
  functions, case-insensitive within each kind).

Exit 0 = clean, 1 = findings (printed one per line), like ruff.
"""

from __future__ import annotations

import ast
import os
import subprocess
import sys

SKIP_DIRS = {".git", "__pycache__", ".jax_cache", "bench_profile",
             ".claude", "node_modules", ".venv"}


def _ruff_available() -> bool:
    try:
        import ruff  # noqa: F401
        return True
    except ImportError:
        pass
    try:
        return subprocess.run([sys.executable, "-m", "ruff", "--version"],
                              capture_output=True).returncode == 0
    except OSError:
        return False


def _py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _import_blocks(tree: ast.Module):
    """Contiguous top-level import runs, split on blank lines (section
    breaks) or any interleaved statement."""
    blocks, cur, prev_end = [], [], None
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if cur and prev_end is not None and node.lineno > prev_end + 1:
                blocks.append(cur)
                cur = []
            cur.append(node)
            prev_end = node.end_lineno
        else:
            if cur:
                blocks.append(cur)
                cur = []
            prev_end = None
    if cur:
        blocks.append(cur)
    return blocks


FIRST_PARTY = {"das_diff_veh_tpu"}


def _module_key(node) -> str:
    if isinstance(node, ast.Import):
        return node.names[0].name.lower()
    return ("." * node.level + (node.module or "")).lower()


def _section(node) -> int:
    """isort's default section order: future/stdlib, third-party,
    first-party, relative.  Anything unresolvable (scripts-dir siblings,
    test helpers) classifies third-party, matching ruff's behaviour with
    src = ["."]."""
    if isinstance(node, ast.ImportFrom) and node.level:
        return 3
    top = _module_key(node).split(".")[0]
    if top in FIRST_PARTY:
        return 2
    if top == "__future__" or top in sys.stdlib_module_names:
        return 0
    return 1


def _name_rank(name: str) -> int:
    """order_by_type default: CONSTANT_CASE, then Classes, then the rest."""
    if not any(c.islower() for c in name):
        return 0
    return 1 if name[0].isupper() else 2


def _check_imports(path: str, tree: ast.Module, findings: list) -> None:
    for block in _import_blocks(tree):
        sections = [_section(n) for n in block]
        if sections != sorted(sections):
            findings.append(
                f"{path}:{block[0].lineno}: I001 import sections out of "
                f"order (future/stdlib, third-party, first-party, relative)")
        for sec in sorted(set(sections)):
            group = [n for n in block if _section(n) == sec]
            straights = [n for n in group if isinstance(n, ast.Import)]
            froms = [n for n in group if isinstance(n, ast.ImportFrom)]
            if straights and froms and (max(n.lineno for n in straights)
                                        > min(n.lineno for n in froms)):
                findings.append(
                    f"{path}:{froms[0].lineno}: I001 straight imports must "
                    f"precede from-imports within a section")
            for kind in (straights, froms):
                keys = [_module_key(n) for n in kind]
                if keys != sorted(keys):
                    findings.append(
                        f"{path}:{kind[0].lineno}: I001 imports not sorted "
                        f"({', '.join(keys)})")
        for n in block:
            if not isinstance(n, ast.ImportFrom):
                continue
            names = [a.name for a in n.names]
            want = sorted(names, key=lambda s: (_name_rank(s), s.lower()))
            if names != want:
                findings.append(
                    f"{path}:{n.lineno}: I001 from-import names not sorted "
                    f"({', '.join(names)})")


def fallback_lint(root: str) -> int:
    findings: list = []
    for path in _py_files(root):
        rel = os.path.relpath(path, root)
        try:
            with open(path, "rb") as f:
                tree = ast.parse(f.read(), filename=rel)
        except SyntaxError as e:
            findings.append(f"{rel}:{e.lineno}: E999 syntax error: {e.msg}")
            continue
        _check_imports(rel, tree, findings)
    for line in findings:
        print(line)
    n = len(findings)
    print(f"fallback lint (no ruff): {n} finding(s)"
          if n else "fallback lint (no ruff): clean")
    return 1 if n else 0


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _ruff_available():
        return subprocess.run(
            [sys.executable, "-m", "ruff", "check", "."], cwd=root).returncode
    return fallback_lint(root)


if __name__ == "__main__":
    sys.exit(main())
