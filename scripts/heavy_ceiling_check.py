"""Evidence check: is the 700 m heavy-weight curve set itself the misfit floor?

The heavy class (103 vehicles — the reference's smallest, imaging_diff_weight
cell 8) refuses to drop below ~0.54 truncated while every other class reaches
0.11-0.29.  This script inverts SUBSETS of the heavy curve set (mode 0 alone,
mode 0+3, mode 0+4, full) with one budget and seed policy.  If each subset
fits far better than the full set, no 6-layer model satisfies all three
observed branches simultaneously — the bootstrap curves are mutually
inconsistent at the ~0.5 level and the full-set misfit is a property of the
DATA, not of the optimizer.  Results land in
``INVERSION_PARITY.json["700_heavy_weight"]["ceiling_check"]``.

Usage: python scripts/heavy_ceiling_check.py [--out INVERSION_PARITY.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_ENABLE_X64", "1")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

from das_diff_veh_tpu.cache import enable_compilation_cache  # noqa: E402

enable_compilation_cache(_REPO)

from inversion_parity import build_curves, rescore_f64  # noqa: E402
from das_diff_veh_tpu.inversion import (invert, make_misfit_fn,  # noqa: E402
                                        weight_model_spec)

# band -> (mode, weight) rows of the full heavy set
# (inversion_diff_weight.ipynb cell 5)
ROWS = {"m0": [(0, 0, 2.0)],
        "m0_m3": [(0, 0, 2.0), (2, 3, 1.0)],
        "m0_m4": [(0, 0, 2.0), (3, 4, 1.0)],
        "full": [(0, 0, 2.0), (2, 3, 1.0), (3, 4, 1.0)]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="INVERSION_PARITY.json")
    ap.add_argument("--maxrun", type=int, default=2)
    args = ap.parse_args()

    spec = weight_model_spec()
    # per-subset resume: the tunneled TPU worker is known to crash mid-run
    # (cf. inversion_parity's .partial machinery); each subset persists as
    # soon as it finishes
    part_path = args.out + ".ceiling.partial"
    out = {}
    if os.path.exists(part_path):
        with open(part_path) as f:
            out = json.load(f)
        print(f"resuming; {len(out)} subset(s) already done", flush=True)
    for name, rows in ROWS.items():
        if name in out:
            continue
        src = [("700_weights.npz", "vels_heavy", rows)]
        dec = build_curves(src, decimate=3)
        mf = make_misfit_fn(spec, dec, n_grid=300, dtype=jnp.float32,
                            invalid="truncate")
        t0, res = time.time(), None
        for run in range(args.maxrun):
            r = invert(spec, dec, popsize=50, maxiter=250, n_refine_starts=8,
                       n_refine_steps=120, n_grid=300, dtype=jnp.float32,
                       invalid="truncate", seed=100 + run, misfit_fn=mf)
            if res is None or float(r.misfit) < float(res.misfit):
                res = r
        full = build_curves(src, decimate=1)
        pen, trunc, n_cut, _ = rescore_f64(spec, full,
                                           np.asarray(res.x_best, np.float64))
        out[name] = {"misfit_truncated": round(trunc, 4),
                     "misfit_f64_full": round(pen, 4),
                     "n_below_cutoff": n_cut,
                     "seconds": round(time.time() - t0, 1)}
        print(name, out[name], flush=True)
        with open(part_path, "w") as f:
            json.dump(out, f, indent=1)

    # the note's numbers derive from THIS run's results so a rerun with a
    # different budget can never leave a self-contradicting artifact
    m0 = out["m0"]["misfit_truncated"]
    bound = 2.0 * m0 / 4.0   # mode-0 weight 2 of total weight 4
    note = (f"same budget/seeds per subset.  Finding: the FUNDAMENTAL curve "
            f"alone already floors at ~{m0:.2f} — no 6-layer model in the "
            f"notebook's search space fits the heavy class's mode-0 ridge "
            f"better (103 vehicles, the smallest class).  At curve weight 2 "
            f"of 4 this bounds the full-set weighted misfit at >= "
            f"~{bound:.2f} even with PERFECT overtones: the misfit level is "
            f"a property of the heavy-class curves, not of the optimizer")
    with open(args.out) as f:
        results = json.load(f)
    results.setdefault("700_heavy_weight", {})["ceiling_check"] = {
        **out, "note": note}
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    if os.path.exists(part_path):
        os.remove(part_path)
    print("wrote ceiling_check into", args.out)


if __name__ == "__main__":
    main()
