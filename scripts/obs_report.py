#!/usr/bin/env python
"""Join observability artifacts into one human-readable post-mortem report.

    python scripts/obs_report.py \
        --flight results/flight_20230301_quarantine_1234_0.json \
        --trace results/run_trace.jsonl \
        --metrics results/metrics.jsonl

Any subset of the three artifact kinds may be given (``--flight`` accepts
several paths); the report renders what it gets:

- **flight** — dump reason/context, then the ring of recent records with
  error/shed/quarantine records flagged;
- **trace** — Chrome-trace spans aggregated by name (count, total/mean/max
  ms) so the hot stage is visible without opening Perfetto;
- **metrics** — the LAST registry snapshot line (counters, gauges,
  histogram percentiles), plus how many snapshots the run wrote.

Where a flight record carries a chunk ``key``, the trace section's
per-name aggregation is joined by a per-key roll-up for the keys that
appear in failed flight records, so "what was the runtime doing to this
chunk" reads in one place.  Exit code is 0 when every given artifact
parsed, 2 otherwise (the verify recipe runs this against a smoke run's
artifacts).
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import defaultdict

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from das_diff_veh_tpu.obs.flight import load_flight_dump  # noqa: E402
from das_diff_veh_tpu.obs.sink import load_metrics_jsonl  # noqa: E402
from das_diff_veh_tpu.runtime.tracing import load_trace  # noqa: E402

_FAIL_KINDS = ("error", "shed", "quarantine")


def _fmt_ms(us: float) -> str:
    return f"{us / 1e3:.2f}"


def render_flight(payload: dict, lines: list) -> list:
    """Render one flight dump; returns the chunk/request keys of failed
    records (for the trace join)."""
    lines.append(f"reason: {payload['reason']}")
    if payload.get("context"):
        ctx = ", ".join(f"{k}={v}" for k, v in payload["context"].items())
        lines.append(f"context: {ctx}")
    records = payload["records"]
    lines.append(f"records: {len(records)} retained "
                 f"(of {payload.get('n_recorded', len(records))} recorded, "
                 f"capacity {payload.get('capacity', '?')})")
    failed_keys = []
    for rec in records:
        kind = rec.get("kind", "?")
        flag = " <<<" if (kind in _FAIL_KINDS or "error" in rec) else ""
        body = ", ".join(f"{k}={v}" for k, v in rec.items()
                         if k not in ("ts", "kind"))
        lines.append(f"  [{kind}] {body}{flag}")
        if flag and rec.get("key"):
            failed_keys.append(rec["key"])
    return failed_keys


def render_trace(events: list, lines: list, join_keys=()) -> None:
    spans = [e for e in events if e.get("ph") == "X"]
    agg = defaultdict(lambda: [0, 0.0, 0.0])        # name -> n, total, max
    per_key = defaultdict(lambda: defaultdict(float))
    for e in spans:
        a = agg[e["name"]]
        a[0] += 1
        a[1] += e.get("dur", 0.0)
        a[2] = max(a[2], e.get("dur", 0.0))
        key = (e.get("args") or {}).get("key") or (e.get("args") or {}).get("file")
        if key in join_keys:
            per_key[key][e["name"]] += e.get("dur", 0.0)
    lines.append(f"{len(spans)} spans, {len(agg)} span names "
                 f"({len(events)} events total)")
    lines.append(f"  {'span':<16}{'n':>6}{'total_ms':>12}"
                 f"{'mean_ms':>10}{'max_ms':>10}")
    for name, (n, total, mx) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
        lines.append(f"  {name:<16}{n:>6}{_fmt_ms(total):>12}"
                     f"{_fmt_ms(total / n):>10}{_fmt_ms(mx):>10}")
    for key, stages in per_key.items():
        stage_s = ", ".join(f"{k}={_fmt_ms(v)}ms"
                            for k, v in sorted(stages.items()))
        lines.append(f"  failed-record join {key}: {stage_s}")


def render_metrics(snaps: list, lines: list) -> None:
    last = snaps[-1]
    lines.append(f"{len(snaps)} snapshot lines; last at ts={last['ts']:.3f}")
    for name, fam in sorted(last["metrics"].items()):
        for lbl, val in sorted(fam.get("values", {}).items()):
            where = "" if lbl == "()" else lbl
            if isinstance(val, dict):               # histogram
                lines.append(
                    f"  {name}{where}: n={val.get('n')} p50={val.get('p50'):g}"
                    f" p95={val.get('p95'):g} p99={val.get('p99'):g}"
                    f" max={val.get('max'):g} count={val.get('count')}")
            else:
                lines.append(f"  {name}{where}: {val:g}")


def build_report(flight_paths, trace_path, metrics_path) -> str:
    lines: list = ["# das_diff_veh_tpu observability report"]
    join_keys: list = []
    for path in flight_paths or ():
        lines.append("")
        lines.append(f"## flight dump: {path}")
        join_keys += render_flight(load_flight_dump(path), lines)
    if trace_path:
        lines.append("")
        lines.append(f"## trace: {trace_path}")
        render_trace(load_trace(trace_path), lines, join_keys=set(join_keys))
    if metrics_path:
        lines.append("")
        lines.append(f"## metrics: {metrics_path}")
        snaps = load_metrics_jsonl(metrics_path)
        if snaps:
            render_metrics(snaps, lines)
        else:
            lines.append("(empty metrics file)")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--flight", nargs="*", default=[], metavar="JSON",
                   help="flight-recorder dump artifact(s)")
    p.add_argument("--trace", default=None, metavar="JSONL",
                   help="Chrome-trace span file (runtime/serve tracer)")
    p.add_argument("--metrics", default=None, metavar="JSONL",
                   help="metrics-sink snapshot file")
    p.add_argument("--out", default=None,
                   help="write the report here instead of stdout")
    args = p.parse_args(argv)
    if not (args.flight or args.trace or args.metrics):
        p.error("give at least one of --flight/--trace/--metrics")
    try:
        report = build_report(args.flight, args.trace, args.metrics)
    except (OSError, ValueError, KeyError) as e:
        print(f"obs_report: failed to parse artifacts: {e}", file=sys.stderr)
        return 2
    if args.out:
        with open(args.out, "w") as f:
            f.write(report)
    else:
        sys.stdout.write(report)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
