#!/usr/bin/env python
"""End-to-end benchmark: 700 m virtual-shot-gather stack + dispersion image.

Reproduces the reference's headline imaging workload (BASELINE.md: a
~60-window class stack at the 700 m pivot -> one dispersion image, the
save_disp_imgs / bootstrap inner loop, apis/imaging_classes.py:50-85) on the
accelerator via the batched jit pipeline, against the NumPy oracle (the
reference semantics, measured fresh on this machine per BASELINE.md §"must
measure").

The NumPy baseline times the FULL 60-window stack by default (no
extrapolation; set BENCH_BASELINE_WINDOWS to reduce it — the value is then
scaled and disclosed in the output) and runs BENCH_BASELINE_REPS times
(default 5), recording min/median/max.  A jax.profiler trace of the timed
section is written to ``bench_profile/`` for the perf narrative.  The other
BASELINE configs are timed into ``extra``: 3-class vmapped dispersion images
(config 2), amortized per-chunk cost + 24 h projection (config 3), and on
TPU backends the Pallas all-pairs engine (config 4): unsharded 4096- and
10000-channel runs, the ring-pipelined shard_map path on the device mesh
(``ring_*`` keys: receiver-spectra shards rotating via ppermute, parity vs
the unsharded kernel, and a replicated-vs-ring per-device peak-bytes A/B
from ``device.memory_stats()``), and a minutes-long (nt = 61440) record
through the win_block-streamed kernel with its record-length-invariance
ratio.  An end-to-end batch-runtime entry measures chunks/s of the serial loop vs
the prefetching executor on a synthetic compressed-npz directory
(``e2e_*`` keys; BENCH_E2E_FILES/REPS/DEPTH tune it), plus an
instrumentation-cost A/B (``obs_*`` keys: the full observability stack —
registry + monitoring listener + JSONL sink + flight ring + trace spans —
on vs off in interleaved pairs on the same prefetch workload, best-of-K
compared, BENCH_OBS_REPS pairs; the contract is < 2% overhead).  An online-serving
entry (``serve_*`` keys) drives an open-loop variable-shape request load
through naive per-request execution vs the microbatched shape-bucketed
serving engine (``das_diff_veh_tpu.serve``), reporting p50/p99 latency and
req/s for both plus the engine's steady-state compile count (asserted 0);
BENCH_SERVE_REQS/SHAPES/INTERARRIVAL_MS/NCH/NT tune the load.  A
mesh-serving entry (``serve_mesh_*`` keys) sweeps the multi-tenant mesh
engine's open-loop req/s and p99 over 1/2/4/8 data-parallel replicas
against the single-dispatcher engine on the same load — per-request device
time is SIMULATED with time.sleep on this one-core host (disclosed as
``serve_mesh_simulated_device_ms``/``serve_mesh_host_cores``); the sweep
asserts zero steady-state compiles per run and >= 3x req/s at 8 replicas,
fault-isolated to ``serve_mesh_error``
(BENCH_SERVE_MESH_REQS/INTERARRIVAL_MS/DEVICE_MS tune it).  A chaos
entry (``chaos_*`` keys) A/Bs fault-free vs 5%-dead-channel degraded-mode
chunks/s on the e2e directory — the health sentinel masks the injected
dead channels and the run completes degraded; failures are fault-isolated
to ``chaos_error`` like the gather entry.  A
trajectory-gather stage entry (``stage_gather_traj_*`` keys) times the
fused Pallas scalar-prefetch window cut against the legacy serialized
vmap(dynamic_slice) formulation at the pipeline's far-side shape
(BENCH_GATHER_K sets the in-dispatch K, floor 5; off-TPU the fused side
runs in interpret mode — its timing key is retagged
``stage_gather_traj_fused_interpret_only_s`` and no speedup key is
emitted, so smoke JSONs carry parity evidence only).  A fused-chunk-
pipeline entry (``stage_pipeline_fused_*`` keys) times the full per-chunk
pipeline staged vs fused (``cfg.chunk_pipeline="fused"``: one donated XLA
program per chunk, pipeline/fused.py) and commits the dispatch
accounting — staged programs-per-chunk N vs fused 1 dispatch/chunk with
zero steady-state traces; BENCH_FUSED_DURATION/REPS tune it.  A tuner entry
(``tune_*`` keys) runs a default-vs-tuned knob-sweep A/B through the real
``das_diff_veh_tpu.tune`` API (store round-trip + hit proven), and a
precision entry (``precision_*`` keys) A/Bs the dispersion transform at
f32 vs bf16 (the rel-err is the portable evidence; the throughput delta is
TPU-only).  A fleet-inversion entry (``invert_fleet_*`` keys) A/Bs the
serial per-target ``invert_multirun`` loop against the packed
``invert_fleet`` one-program path with trace counts on the clock.  All
three are *selectable*: ``bench.py --json-only tune precision
invert_fleet`` runs just those entries and prints one ``bench_subset``
JSON line — the tuner and CI path that skips the full smoke sweep.  Opt-outs:
BENCH_SKIP_E2E / BENCH_SKIP_OBS / BENCH_SKIP_CHAOS / BENCH_SKIP_SERVE / BENCH_SKIP_SERVE_MESH / BENCH_SKIP_PALLAS / BENCH_SKIP_SHARDED /
BENCH_SKIP_LONG / BENCH_SKIP_10K / BENCH_SKIP_FUSED / BENCH_SKIP_TUNE /
BENCH_SKIP_PRECISION / BENCH_SKIP_INVERT_FLEET; BENCH_10K_SRC_CHUNK tunes the 10k
source-chunk size (default 32 — see docs/PERF.md on the working-set effect).
The full env-knob table lives in docs/PERF.md §"Bench env knobs".

Prints ONE JSON line with the primary metric plus an ``extra`` dict:
  {"metric": "vsg_disp_700m_build", "value": <s>, "unit": "s",
   "vs_baseline": <numpy/jax>, "extra": {...}}

Two timings are measured and both reported: the per-dispatch wall latency
(``extra.single_dispatch_s`` — on this host it includes a ~100-200 ms axon
tunnel round trip per dispatch, an artifact of the tunneled single-chip test
rig), and the per-build device time amortized over K=32 builds executed
inside one dispatch (the primary ``value`` — what a non-tunneled deployment
sees per image, and the honest basis for the >=20x NumPy comparison).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_WINDOWS = 60


# --- selectable entries (bench.py --json-only <keys>) ------------------------
# Each entry is a standalone callable(extra) that fills its own keys and may
# raise — the caller fault-isolates to <name>_error like every other group.
# Legacy groups embedded in main() are *skipped* (not selected) via the
# BENCH_SKIP_* env knobs documented in docs/PERF.md; new modular entries
# register here so the tuner and CI can run one entry without paying the
# full smoke sweep.

def _bench_tune(extra: dict) -> None:
    """Default-vs-tuned A/B through the real tuner API (``tune_*`` keys).

    Sweeps ``ring.win_block`` for the einsum all-pairs peak on a small
    record with ``das_diff_veh_tpu.tune.tune`` (greedy sweep + store
    round-trip), then proves the persisted entry is a store *hit* on the
    second call.  Runs on any backend; on this CPU smoke rig the timings
    are CPU evidence only — the sweep mechanics and persistence are the
    committed result, the speedup is rig-specific.
    """
    import tempfile

    import jax

    from das_diff_veh_tpu.config import PipelineConfig, RingConfig
    from das_diff_veh_tpu.ops.pallas_xcorr import xcorr_all_pairs_peak
    from das_diff_veh_tpu.tune import KnobSpec, TunerStore, tune

    nch, nt, wlen = 48, 2048, 128
    rng = np.random.default_rng(7)
    import jax.numpy as jnp
    data = jnp.asarray(rng.standard_normal((nch, nt)).astype(np.float32))
    iters = max(2, int(os.environ.get("BENCH_TUNE_ITERS", 4)))

    def time_fn(cfg, ring):
        wb = ring.win_block

        def run():
            return xcorr_all_pairs_peak(data, wlen, use_pallas=False,
                                        win_block=wb).block_until_ready()

        run()                              # compile + warm outside the clock
        t0 = time.perf_counter()
        for _ in range(iters):
            run()
        return (time.perf_counter() - t0) / iters

    backend = jax.default_backend()
    knobs = [KnobSpec("ring.win_block", (8, 16, 32, 64))]
    with tempfile.TemporaryDirectory() as d:
        store = TunerStore(os.path.join(d, "tuner.json"))
        _, ring, entry = tune(store, backend, "bench_smoke",
                              PipelineConfig(), knobs, time_fn,
                              reps=2, ring=RingConfig())
        # second consult must hit the persisted entry (no re-sweep)
        _, _, entry2 = tune(store, backend, "bench_smoke",
                            PipelineConfig(), [], time_fn,
                            reps=1, ring=RingConfig())
    extra["tune_backend"] = backend
    extra["tune_default_s"] = round(entry.meta["baseline_s"], 5)
    extra["tune_tuned_s"] = round(entry.meta["tuned_s"], 5)
    extra["tune_speedup"] = round(entry.meta["speedup"], 3)
    extra["tune_winners"] = {k: repr(v) for k, v in entry.winners.items()}
    extra["tune_store_hit"] = entry2.winners == entry.winners


def _bench_precision(extra: dict) -> None:
    """f32-vs-bf16 A/B on the dispersion transform (``precision_*`` keys).

    Times ``fv_map_fk`` at both tiers on one jitted program each and
    records the relative error.  On CPU the bf16 tier only pays its
    rounding casts (no bf16 MXU exists to win on), so the committed
    evidence here is the error bound; the throughput delta is meaningful
    on TPU hardware only and is disclosed as such.
    """
    import jax
    import jax.numpy as jnp

    from das_diff_veh_tpu.config import DispersionConfig
    from das_diff_veh_tpu.ops.dispersion import fv_map_fk

    dcfg = DispersionConfig()
    rng = np.random.default_rng(11)
    data = jnp.asarray(rng.standard_normal((64, 2048)).astype(np.float32))
    freqs = jnp.arange(dcfg.freq_min, dcfg.freq_max, dcfg.freq_step)
    vels = jnp.arange(dcfg.vel_min, dcfg.vel_max, dcfg.vel_step)
    iters = max(2, int(os.environ.get("BENCH_PRECISION_ITERS", 4)))

    def timed(precision):
        f = jax.jit(lambda d: fv_map_fk(d, 8.16, 0.004, freqs, vels,
                                        precision=precision))
        out = f(data).block_until_ready()       # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            f(data).block_until_ready()
        return (time.perf_counter() - t0) / iters, out

    t32, img32 = timed("f32")
    t16, img16 = timed("bf16")
    rel = float(jnp.max(jnp.abs(img32 - img16)) / jnp.max(jnp.abs(img32)))
    extra["precision_f32_s"] = round(t32, 5)
    extra["precision_bf16_s"] = round(t16, 5)
    extra["precision_speedup"] = round(t32 / t16, 3)
    extra["precision_rel_err"] = round(rel, 6)
    extra["precision_note"] = (
        "bf16 throughput delta is TPU-MXU-only; on CPU the tier pays its "
        "rounding casts for free accuracy evidence (rel_err is the "
        "portable number, bound committed in tests/test_precision.py)")


def _bench_invert_fleet(extra: dict) -> None:
    """Serial-loop vs fleet-batched inversion A/B (``invert_fleet_*`` keys).

    The legacy path bakes each curve set into a Python closure, so a
    T-target loop over ``invert_multirun`` re-traces and re-compiles the
    swarm/refine programs per target; ``invert_fleet`` packs the fleet and
    runs ONE data-parameterized program regardless of T.  Both sides run
    cold (compiles on the clock — compile amortization IS the product),
    seeded to produce identical per-target searches, and their jaxpr trace
    counts are recorded via the ``obs/xla_events`` listener.  CPU-smoke
    budgets; the speedup is compile-dominated by design, matching the
    fleet use case (thousands of bootstrap/time-lapse targets).
    """
    import jax
    import jax.numpy as jnp

    from das_diff_veh_tpu.inversion import (Curve, LayerBounds, ModelSpec,
                                            LayeredModel,
                                            density_gardner_linear,
                                            invert_fleet, invert_multirun,
                                            make_misfit_fn, phase_velocity,
                                            vp_from_poisson)
    from das_diff_veh_tpu.obs import xla_events
    from das_diff_veh_tpu.obs.registry import MetricsRegistry

    T = max(2, int(os.environ.get("BENCH_FLEET_TARGETS", 10)))
    n_runs = 2
    budget = dict(n_runs=n_runs, popsize=8, maxiter=8, n_refine_starts=2,
                  n_refine_steps=6, n_grid=150)

    vs = jnp.asarray([0.20, 0.40, 0.70], dtype=jnp.float64)
    vp = vp_from_poisson(vs, 0.4375)
    truth = LayeredModel(jnp.asarray([0.006, 0.02, 0.0]), vp, vs,
                         density_gardner_linear(vp))
    periods = jnp.linspace(0.05, 0.4, 12)
    c0 = np.asarray(phase_velocity(periods, truth, mode=0, n_grid=400))
    rng = np.random.default_rng(20)
    curve_sets = [
        [Curve(np.asarray(periods), c0 + rng.normal(0.0, 0.005, c0.shape),
               mode=0, weight=1.0, uncertainty=0.01 * np.ones_like(c0))]
        for _ in range(T)]
    spec = ModelSpec(layers=(LayerBounds((0.002, 0.012), (0.1, 0.3)),
                             LayerBounds((0.01, 0.04), (0.25, 0.55)),
                             LayerBounds((0.02, 0.08), (0.5, 1.0))))

    def watched(fn):
        reg = MetricsRegistry()
        watch = xla_events.install(reg)
        t0 = time.perf_counter()
        try:
            out = fn()
        finally:
            xla_events.uninstall(reg)
        return time.perf_counter() - t0, watch.traces, out

    # serial legacy loop: fresh closure per target -> per-target retrace
    def serial():
        return [invert_multirun(spec, curve_sets[t], seed=t * n_runs,
                                **budget) for t in range(T)]

    # Both sides pay true compile costs: the persistent compilation cache
    # would otherwise absorb the serial loop's per-target compiles on any
    # rerun (the curve data is seeded, so the HLO repeats) and the A/B
    # would measure cache history instead of compile amortization.
    cache_was = bool(jax.config.jax_enable_compilation_cache)
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        t_serial, traces_serial, res_serial = watched(serial)

        # fleet: one packed data-parameterized program for all T targets
        t_fleet, traces_fleet, res_fleet = watched(
            lambda: invert_fleet(spec, curve_sets, seed=0, **budget))
        # steady state: a second fleet of the same shape must not retrace
        t_fleet2, traces_steady, _ = watched(
            lambda: invert_fleet(spec, curve_sets, seed=0, **budget))
    finally:
        jax.config.update("jax_enable_compilation_cache", cache_was)

    # parity: the legacy closure re-scores every fleet best — the packed
    # misfit must agree pointwise (deterministic; the end-to-end serial
    # and fleet searches are equal-seeded but f32 swarm trajectories are
    # chaotic, so only the pointwise number is a contract)
    parity = max(
        abs(float(make_misfit_fn(spec, curve_sets[t],
                                 n_grid=150)(jnp.asarray(res_fleet.x_best[t])))
            - float(res_fleet.misfit[t]))
        for t in range(T))
    quality = float(np.median(res_fleet.misfit
                              - np.asarray([r.misfit for r in res_serial])))

    extra["invert_fleet_targets"] = T
    extra["invert_fleet_serial_s"] = round(t_serial, 3)
    extra["invert_fleet_serial_s_per_target"] = round(t_serial / T, 3)
    extra["invert_fleet_serial_traces"] = traces_serial
    extra["invert_fleet_s"] = round(t_fleet, 3)
    extra["invert_fleet_s_per_target"] = round(t_fleet / T, 3)
    extra["invert_fleet_traces"] = traces_fleet
    extra["invert_fleet_steady_s_per_target"] = round(t_fleet2 / T, 3)
    extra["invert_fleet_steady_traces"] = traces_steady
    extra["invert_fleet_speedup"] = round(t_serial / t_fleet, 3)
    extra["invert_fleet_packed_vs_closure_absdiff"] = parity
    extra["invert_fleet_quality_delta_vs_serial"] = round(quality, 4)


ENTRIES = {
    "tune": _bench_tune,
    "precision": _bench_precision,
    "invert_fleet": _bench_invert_fleet,
}


def run_json_only(keys) -> int:
    """Run only the named registry entries; print ONE JSON line."""
    from das_diff_veh_tpu.cache import enable_compilation_cache

    enable_compilation_cache(os.path.dirname(os.path.abspath(__file__)))
    extra: dict = {}
    n_ok = 0
    for k in keys:
        fn = ENTRIES.get(k)
        if fn is None:
            extra[f"{k}_error"] = (f"KeyError: unknown bench entry {k!r}; "
                                   f"selectable: {sorted(ENTRIES)}")
            continue
        try:
            fn(extra)
            n_ok += 1
        except Exception as e:
            extra[f"{k}_error"] = f"{type(e).__name__}: {e}"[:300]
    print(json.dumps({"metric": "bench_subset", "value": n_ok,
                      "unit": "entries", "extra": extra}))
    return 0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from das_diff_veh_tpu.cache import enable_compilation_cache

    enable_compilation_cache(os.path.dirname(os.path.abspath(__file__)))

    from das_diff_veh_tpu.config import DispersionConfig, GatherConfig
    from das_diff_veh_tpu.models import vsg as V
    from das_diff_veh_tpu.oracle.vsg_ref import ref_build_gather
    from das_diff_veh_tpu.oracle.dispersion_ref import ref_map_fv
    from das_diff_veh_tpu.workloads import make_gather_geometry, make_window_batch

    x0, fs = 700.0, 250.0
    gcfg = GatherConfig()
    dcfg = DispersionConfig()
    batch, x = make_window_batch(N_WINDOWS, x0=x0, fs=fs)
    g = make_gather_geometry(x, x0=x0, fs=fs, cfg=gcfg)
    offs = g.offsets(x)
    freqs = np.arange(dcfg.freq_min, dcfg.freq_max, dcfg.freq_step)
    vels = np.arange(dcfg.vel_min, dcfg.vel_max, dcfg.vel_step)

    # --- NumPy oracle baseline (reference semantics), full stack by default ---
    # Measured BENCH_BASELINE_REPS times (default 5): the BENCH JSON carries
    # min/median/max so README/PERF quote a committed spread instead of an
    # asserted one, and vs_baseline compares against the median.
    n_base = int(os.environ.get("BENCH_BASELINE_WINDOWS", N_WINDOWS))
    n_base = max(1, min(n_base, N_WINDOWS))
    reps_base = max(1, int(os.environ.get("BENCH_BASELINE_REPS", 5)))
    d_np = np.asarray(batch.data, dtype=np.float64)
    t_np = np.asarray(batch.t, dtype=np.float64)
    tx_np = np.asarray(batch.traj_x, dtype=np.float64)
    tt_np = np.asarray(batch.traj_t, dtype=np.float64)
    sxi = int(np.abs(offs - (-150.0)).argmin())
    exi = int(np.abs(offs - 0.0).argmin())

    def run_baseline() -> float:
        t0 = time.perf_counter()
        acc = None
        for w in range(n_base):
            xcf, _, _ = ref_build_gather(d_np[w], x, t_np[w], tx_np[w],
                                         tt_np[w], x0, x0 - 150.0,
                                         x0 + gcfg.far_offset,
                                         wlen_s=gcfg.wlen,
                                         time_window=gcfg.time_window,
                                         delta_t=gcfg.delta_t)
            acc = xcf if acc is None else acc + xcf
        acc /= n_base
        gather_time = (time.perf_counter() - t0) * (N_WINDOWS / n_base)
        t0 = time.perf_counter()
        ref_map_fv(acc[sxi:exi + 1], 8.16, 1.0 / fs, freqs, vels,
                   norm=dcfg.norm)
        return gather_time + (time.perf_counter() - t0)  # image once per stack

    base_times = sorted(run_baseline() for _ in range(reps_base))
    np_time = float(np.median(base_times))

    # --- JAX pipeline (TPU when available) ------------------------------------
    def gather_stage(b):
        return V.stack_gathers(V.build_gather_batch(b, g, gcfg), b.valid)

    def image_stage(s):
        return V.gather_disp_image(s, offs, g.dt, 8.16, dcfg, -150.0, 0.0)

    def pipeline_body(b):
        return image_stage(gather_stage(b))

    pipeline = jax.jit(pipeline_body)

    img = jax.block_until_ready(pipeline(batch))        # compile
    reps = 5
    profile_dir = os.environ.get("BENCH_PROFILE_DIR", "bench_profile")
    with jax.profiler.trace(profile_dir):
        jax.block_until_ready(pipeline(batch))
    # single-dispatch latency: includes the axon tunnel's ~100 ms round trip
    # (np.asarray forces real synchronization; block_until_ready does not
    # reliably block through the tunnel for device-resident input chains)
    t0 = time.perf_counter()
    for _ in range(reps):
        img = np.asarray(pipeline(batch))
    jax_time = (time.perf_counter() - t0) / reps

    # device-only throughput: K executions inside ONE dispatch (inputs
    # perturbed per iteration so XLA cannot hoist), amortizing the tunnel
    # latency away — this is the number a non-tunneled deployment sees, and
    # what the >=20x north star meaningfully measures.  One protocol serves
    # every amortized metric below.
    import dataclasses

    from jax import lax

    K = 32

    def roll_batch(axis):
        return lambda b, i: dataclasses.replace(
            b, data=jnp.roll(b.data, i, axis=axis))

    def amortized_time(body, perturb, operand, acc_shape, k=K, reps=1):
        """Per-execution device time of ``body`` amortized over ``k``
        in-dispatch executions; median of ``reps`` timed dispatches."""
        @jax.jit
        def k_loop(op, j0):
            return lax.fori_loop(
                0, k, lambda i, acc: acc + body(perturb(op, i + j0)),
                jnp.zeros(acc_shape, jnp.float32))

        np.asarray(k_loop(operand, 0))                  # compile
        ts = []
        for j in range(reps):
            t0 = time.perf_counter()
            np.asarray(k_loop(operand, j + 1))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)) / k

    img_shape = (dcfg.n_vels, dcfg.n_freqs)
    device_time = amortized_time(pipeline_body, roll_batch(0), batch,
                                 img_shape, reps=3)

    # stage budget of one build, measured the same amortized way (VERDICT r3
    # weak #2: state the device-time split instead of inferring it from the
    # profile trace alone): gather-stack stage vs dispersion-image stage
    stack0 = jax.jit(gather_stage)(batch)   # jit: the axon rig cannot run
    # FFT chains op-by-op (see axon environment notes)
    stage_gather = amortized_time(gather_stage, roll_batch(0), batch,
                                  (g.nch_out, g.wlen))
    stage_image = amortized_time(image_stage,
                                 lambda s, i: jnp.roll(s, i, axis=0),
                                 stack0, img_shape)
    # the alternative phase-shift transform (no padded 2-D FFT, no gather;
    # ops/dispersion.py) on the same stack — measured, the fk/einsum path is
    # FASTER at the reference problem size on v5e (the bilinear-sampling
    # einsum rides the MXU; the phase-shift steering einsum is larger), so
    # fk stays the primary path and both numbers are reported
    dcfg_ps = dataclasses.replace(dcfg, method="phase_shift")
    stage_image_ps = amortized_time(
        lambda s: V.gather_disp_image(s, offs, g.dt, 8.16, dcfg_ps,
                                      -150.0, 0.0),
        lambda s, i: jnp.roll(s, i, axis=0), stack0, img_shape)

    # trajectory-following gather: fused Pallas scalar-prefetch kernel vs
    # the legacy serialized vmap(dynamic_slice) window cut, measured on the
    # pipeline's far-side shape (one window's worth of per-channel
    # data-dependent cuts, K >= 5 in-dispatch executions).  On CPU smoke
    # runs the fused kernel executes in INTERPRET mode (a compiled grid
    # emulation), so its time there is a correctness artifact, not hardware
    # evidence — the committed smoke carries the keys + the parity number
    # and is labeled as such in docs/PERF.md; TPU numbers land with the
    # next driver run under the same keys.  The fused timing is
    # fault-isolated so a kernel lowering issue surfaces as an *_error key
    # instead of killing the sweep.
    from das_diff_veh_tpu.ops import xcorr as XC

    gather_k = max(5, int(os.environ.get("BENCH_GATHER_K", 8)))
    d_one, t_one = batch.data[0], batch.t[0]
    # the full gather span's channels against the pivot — one window's
    # worth of per-channel data-dependent cuts at the pipeline geometry
    traj_ch = jnp.arange(g.start_x_idx, g.end_x_idx)
    traj_t = jnp.linspace(float(t_one[0]) + 1.0, float(t_one[-1]) - 1.0,
                          int(traj_ch.size))

    def traj_stage(mode):
        return lambda d: XC.xcorr_traj_follow(d, t_one, g.pivot_idx, traj_ch,
                                              traj_t, g.nsamp, g.wlen,
                                              mode=mode)

    perturb_rec = lambda d, i: jnp.roll(d, i, axis=0)
    traj_acc = (int(traj_ch.size), g.wlen)
    t_traj_serial = amortized_time(traj_stage("serialized"), perturb_rec,
                                   d_one, traj_acc, k=gather_k)
    extra_gather = {
        "stage_gather_traj_rows": int(traj_ch.size),
        "stage_gather_traj_k": gather_k,
        "stage_gather_traj_serialized_s": round(t_traj_serial, 5),
    }
    on_chip = jax.default_backend() in ("tpu", "axon")
    try:
        t_traj_fused = amortized_time(traj_stage("fused"), perturb_rec,
                                      d_one, traj_acc, k=gather_k)
        parity_traj = float(jnp.max(jnp.abs(
            traj_stage("fused")(d_one) - traj_stage("serialized")(d_one))))
        if on_chip:
            extra_gather["stage_gather_traj_fused_s"] = round(t_traj_fused, 5)
            extra_gather["stage_gather_traj_speedup"] = round(
                t_traj_serial / t_traj_fused, 3)
        else:
            # off-TPU the fused kernel runs in interpret mode: the timing is
            # a correctness artifact, so the keys carry the retag and the
            # hardware-claim keys (fused_s / speedup) are withheld — a smoke
            # JSON can no longer be misread as a chip speedup
            extra_gather["stage_gather_traj_fused_interpret_only_s"] = \
                round(t_traj_fused, 5)
        extra_gather["stage_gather_traj_parity_max_abs_diff"] = parity_traj
    except Exception as e:  # noqa: BLE001 — disclosed, never fatal
        extra_gather["stage_gather_traj_fused_error"] = \
            f"{type(e).__name__}: {e}"[:300]
    if not on_chip:
        extra_gather["stage_gather_traj_note"] = (
            "fused timed in interpret mode on this backend — parity "
            "evidence only, not a hardware speedup")

    # --- BASELINE config 2: multi-class stacked dispersion images -------------
    # (vmap over vehicle class: 3 class batches image in ONE device program,
    # the save_disp_imgs per-class loop of imaging_diff_*.ipynb cell 21)
    from das_diff_veh_tpu.core.section import WindowBatch

    n_cls = 3
    per_cls = N_WINDOWS // n_cls
    cls_batch = WindowBatch(
        data=batch.data[:n_cls * per_cls].reshape(n_cls, per_cls,
                                                  *batch.data.shape[1:]),
        x=batch.x,
        t=batch.t[:n_cls * per_cls].reshape(n_cls, per_cls, -1),
        traj_x=batch.traj_x[:n_cls * per_cls].reshape(n_cls, per_cls, -1),
        traj_t=batch.traj_t[:n_cls * per_cls].reshape(n_cls, per_cls, -1),
        valid=batch.valid[:n_cls * per_cls].reshape(n_cls, per_cls))
    cls_axes = WindowBatch(data=0, x=None, t=0, traj_x=0, traj_t=0, valid=0)
    vpipe = jax.vmap(pipeline_body, in_axes=(cls_axes,))
    t_cls = amortized_time(vpipe, roll_batch(1), cls_batch,
                           (n_cls,) + img_shape, k=8) / n_cls  # per class
    # (a shared-flat-gather + masked-class-stacks formulation was measured
    # SLOWER than this straight vmap — the nested-vmap window cuts are fine
    # since the one-slice-stream-per-pair change)

    # --- BASELINE config 3: 24 h sliding-window time-lapse stack --------------
    # single chip here: amortized per-chunk build cost on a typical ~4-vehicle
    # chunk, projected to a day of 2-minute chunks.  The window axis of this
    # same pipeline shards over a device mesh (parallel/stack.py,
    # bit-parity-tested on the CI 8-device CPU mesh + driver dryrun), so the
    # multi-chip number scales by the mesh size.
    chunk_n = 4
    chunk_batch = dataclasses.replace(
        batch, data=batch.data[:chunk_n], t=batch.t[:chunk_n],
        traj_x=batch.traj_x[:chunk_n], traj_t=batch.traj_t[:chunk_n],
        valid=batch.valid[:chunk_n])
    t_chunk = amortized_time(pipeline_body, roll_batch(0), chunk_batch,
                             img_shape)
    chunks_per_day = 24 * 60 // 2

    # primary metric per BASELINE.json: channel-pair xcorrs/sec.  Every output
    # gather row is one windowed pair correlation; both sides run when
    # include_other_side (reference virtual_shot_gather.py:189-192).
    sides = 2 if gcfg.include_other_side else 1
    n_pairs = N_WINDOWS * g.nch_out * sides
    pairs_per_sec = n_pairs / jax_time

    extra = {
        "np_baseline_s": round(np_time, 3),
        "np_baseline_min_s": round(base_times[0], 3),
        "np_baseline_median_s": round(np_time, 3),
        "np_baseline_max_s": round(base_times[-1], 3),
        "np_baseline_reps": reps_base,
        "baseline_windows_timed": n_base,
        "vs_baseline_note": "device-only amortized time vs NumPy wall; the "
                            "NumPy oracle has no dispatch/transfer component "
                            "(its wall IS its compute), the device side "
                            "excludes the tunnel round-trip disclosed below",
        "single_dispatch_s": round(jax_time, 5),
        "vs_baseline_single_dispatch": round(np_time / jax_time, 2),
        "single_dispatch_note": "includes ~100-200 ms axon tunnel round-trip "
                                "per dispatch (test-harness artifact, not "
                                "framework time; see module docstring)",
        "xcorr_pairs_per_sec": round(n_pairs / device_time, 1),
        "xcorr_pairs_per_sec_single_dispatch": round(pairs_per_sec, 1),
        "n_pair_xcorrs": n_pairs,
        "stage_gather_stack_s": round(stage_gather, 5),   # device-time budget
        "stage_disp_image_s": round(stage_image, 5),      # of one build
        "stage_disp_image_phase_shift_s": round(stage_image_ps, 5),
        **extra_gather,
        "multiclass_image_amortized_s": round(t_cls, 5),      # config 2
        "timelapse_chunk_amortized_s": round(t_chunk, 5),     # config 3
        "timelapse_24h_equiv_s": round(t_chunk * chunks_per_day, 2),
        "profile_dir": profile_dir,
        "backend": jax.default_backend(),
    }

    # --- fused single-dispatch chunk pipeline vs staged (PR 16) ---------------
    # The SAME full per-chunk pipeline (tracking -> windows -> VSG stack ->
    # dispersion image) run both ways on one synthetic chunk: the staged
    # path dispatches one tiny XLA program per eager op, the fused path
    # (cfg.chunk_pipeline="fused") launches ONE jitted donated program and
    # pulls the whole result in one device_get.  Timing is the consumer's
    # wall per chunk (process_chunk + the coalesced (n_windows, image)
    # pull), median over BENCH_FUSED_REPS warm/steady chunks.  Dispatch
    # accounting is device truth, not a narrative: staged N = distinct XLA
    # programs traced by its cold chunk (each re-dispatches every warm
    # chunk; counted via the obs jax.monitoring listener), fused = the
    # module's own dispatch counter (1/chunk) with a ZERO steady-state
    # trace delta.  Fault-isolated like the gather entry.
    if not os.environ.get("BENCH_SKIP_FUSED"):
        try:
            from das_diff_veh_tpu.config import (ImagingConfig as _IC,
                                                 PipelineConfig as _FPC)
            from das_diff_veh_tpu.core.section import DasSection as _DS
            from das_diff_veh_tpu.io.synthetic import (SceneConfig as _SC,
                                                       synthesize_section
                                                       as _synth)
            from das_diff_veh_tpu.obs import xla_events as _xev
            from das_diff_veh_tpu.obs.registry import (MetricsRegistry
                                                       as _MReg)
            from das_diff_veh_tpu.pipeline import fused as _fused
            from das_diff_veh_tpu.pipeline.timelapse import (process_chunk
                                                             as _pchunk)

            f_dur = float(os.environ.get("BENCH_FUSED_DURATION", 120.0))
            f_reps = max(1, int(os.environ.get("BENCH_FUSED_REPS", 2)))
            fsec, _ = _synth(_SC(nch=100, duration=f_dur, n_vehicles=4,
                                 seed=11, speed_range=(12.0, 18.0)))
            cfg_staged = _FPC().replace(imaging=_IC(x0=400.0))
            cfg_fused = cfg_staged.replace(chunk_pipeline="fused")
            fdata = np.asarray(fsec.data)
            fx, ft = np.asarray(fsec.x), np.asarray(fsec.t)

            def time_chunks(cfg, reps, j0=0):
                ts = []
                for i in range(reps):
                    # perturb data per rep (same geometry -> same programs)
                    sec_i = _DS(fdata * (1.0 + 0.01 * (j0 + i)), fx, ft)
                    t0 = time.perf_counter()
                    res = _pchunk(sec_i, cfg, method="xcorr")
                    n_w, img_f = jax.device_get((res.n_windows,
                                                 res.disp_image))
                    ts.append(time.perf_counter() - t0)
                    assert int(n_w) >= 1 and np.isfinite(img_f).all()
                return ts

            freg = _MReg()
            fwatch = _xev.install(freg)
            try:
                tr0 = fwatch.traces
                time_chunks(cfg_staged, 1)               # cold staged
                staged_programs = fwatch.traces - tr0
                staged_ts = time_chunks(cfg_staged, f_reps, j0=1)  # warm
                tr1 = fwatch.traces
                d0 = _fused.n_dispatches("process_chunk")
                time_chunks(cfg_fused, 1)                # cold fused
                fused_cold_traces = fwatch.traces - tr1
                tr2 = fwatch.traces
                fused_ts = time_chunks(cfg_fused, f_reps, j0=1)  # steady
                fused_steady_traces = fwatch.traces - tr2
                fused_disp = _fused.n_dispatches("process_chunk") - d0
            finally:
                _xev.uninstall(freg)

            t_staged = float(np.median(staged_ts))
            t_fused = float(np.median(fused_ts))
            extra["stage_pipeline_staged_chunk_s"] = round(t_staged, 4)
            extra["stage_pipeline_fused_chunk_s"] = round(t_fused, 4)
            extra["stage_pipeline_fused_speedup"] = round(
                t_staged / t_fused, 3)
            extra["stage_pipeline_staged_programs_per_chunk"] = \
                int(staged_programs)
            extra["stage_pipeline_fused_cold_traces"] = int(fused_cold_traces)
            extra["stage_pipeline_fused_dispatches_per_chunk"] = round(
                fused_disp / (f_reps + 1), 2)
            extra["stage_pipeline_fused_steady_state_traces"] = \
                int(fused_steady_traces)
            extra["stage_pipeline_fused_reps"] = f_reps
            extra["stage_pipeline_fused_duration_s"] = f_dur
            extra["stage_pipeline_note"] = (
                "staged N = XLA programs traced by one cold chunk, each "
                "dispatched >=1x per warm chunk; fused = module dispatch "
                "counter (1/chunk) + zero steady-state jaxpr traces")
        except Exception as e:  # noqa: BLE001 — disclosed, never fatal
            extra["stage_pipeline_fused_error"] = \
                f"{type(e).__name__}: {e}"[:300]

    # --- end-to-end batch runtime: serial vs prefetching chunks/s -------------
    # The pipelined execution runtime (das_diff_veh_tpu.runtime) overlaps
    # host npz read + savgol preprocess + H2D staging with device compute.
    # Measured on a synthetic per-date directory written fresh each run
    # (compressed npz — decompression is the realistic host I/O cost), serial
    # (prefetch_depth=0) vs prefetching, median of BENCH_E2E_REPS runs each.
    if not os.environ.get("BENCH_SKIP_E2E"):
        import shutil
        import tempfile

        from das_diff_veh_tpu.config import ImagingConfig, PipelineConfig
        from das_diff_veh_tpu.io.readers import DirectoryDataset
        from das_diff_veh_tpu.io.synthetic import SceneConfig, synthesize_section
        from das_diff_veh_tpu.pipeline.workflow import run_directory
        from das_diff_veh_tpu.runtime import RuntimeConfig

        n_files = int(os.environ.get("BENCH_E2E_FILES", 8))
        e2e_reps = max(1, int(os.environ.get("BENCH_E2E_REPS", 3)))
        e2e_depth = int(os.environ.get("BENCH_E2E_DEPTH", 3))
        e2e_dur = float(os.environ.get("BENCH_E2E_DURATION", 240.0))
        scene, _ = synthesize_section(SceneConfig(
            nch=100, duration=e2e_dur, n_vehicles=6, seed=7,
            speed_range=(12.0, 18.0)))
        pcfg = PipelineConfig().replace(imaging=ImagingConfig(x0=400.0))
        tdir = tempfile.mkdtemp(prefix="e2e_bench_")
        try:
            day = os.path.join(tdir, "20230301")
            os.makedirs(day)
            sdata = np.asarray(scene.data)
            for i in range(n_files):
                np.savez_compressed(
                    os.path.join(day, f"20230301_{i:02d}0000.npz"),
                    data=sdata * (1.0 + 0.01 * i), x_axis=np.asarray(scene.x),
                    t_axis=np.asarray(scene.t))

            def e2e_run(depth: int, runtime=None) -> float:
                ds = DirectoryDataset("20230301", root=tdir, ch1=None,
                                      ch2=None, smoothing=True,
                                      rescale_after=None)
                t0 = time.perf_counter()
                res = run_directory(ds, pcfg, method="xcorr",
                                    x_is_channels=False,
                                    runtime=runtime if runtime is not None
                                    else RuntimeConfig(prefetch_depth=depth,
                                                       max_retries=0))
                dt = time.perf_counter() - t0
                assert res.n_chunks > 0 and not res.quarantined
                return n_files / dt

            e2e_run(0)                                   # compile warm-up
            serial = float(np.median([e2e_run(0) for _ in range(e2e_reps)]))
            prefetch = float(np.median([e2e_run(e2e_depth)
                                        for _ in range(e2e_reps)]))
            extra["e2e_files"] = n_files
            extra["e2e_reps"] = e2e_reps
            extra["e2e_prefetch_depth"] = e2e_depth
            extra["e2e_serial_chunks_per_s"] = round(serial, 4)
            extra["e2e_prefetch_chunks_per_s"] = round(prefetch, 4)
            extra["e2e_prefetch_speedup"] = round(prefetch / serial, 3)

            # instrumentation-cost A/B on the SAME workload: the full obs
            # stack ON (metrics registry + jax.monitoring listener + JSONL
            # sink + flight-recorder ring + Chrome-trace spans, batched
            # flush) vs a bare prefetch run.  The contract
            # (docs/OBSERVABILITY.md) is < 2% on the e2e chunks/s key —
            # per-chunk obs work is a handful of dict/deque ops against
            # seconds of chunk compute.  Measurement shape matters more
            # than the instrumentation here: two back-to-back SERIES drift
            # apart by several % on this host (page cache, thermal — the
            # committed r06 vs r09 e2e keys differ ~7% at identical knobs),
            # so the A/B runs bare/obs in interleaved PAIRS and compares
            # best-of-K (the noise-floor estimator the NumPy-baseline
            # entries already use via their committed min): medians are
            # also committed so the spread is an artifact, not a footnote.
            if not os.environ.get("BENCH_SKIP_OBS"):
                from das_diff_veh_tpu.config import ObsConfig

                obs_dir = os.path.join(tdir, "obs")
                os.makedirs(obs_dir, exist_ok=True)

                def obs_runtime():
                    return RuntimeConfig(
                        prefetch_depth=e2e_depth, max_retries=0,
                        trace_path=os.path.join(obs_dir, "trace.jsonl"),
                        obs=ObsConfig(
                            metrics_jsonl=os.path.join(obs_dir,
                                                       "metrics.jsonl"),
                            metrics_interval_s=0.5,
                            flight_dir=obs_dir,
                            trace_flush_interval_s=0.2))

                def bare_runtime():
                    # ObsConfig.enabled=False strips the registry families,
                    # flight ring, and monitoring listener too — the off
                    # side is genuinely uninstrumented, not just sink-less
                    return RuntimeConfig(prefetch_depth=e2e_depth,
                                         max_retries=0,
                                         obs=ObsConfig(enabled=False))

                obs_reps = max(int(os.environ.get("BENCH_OBS_REPS", 3)), 2)
                bare, instrumented = [], []
                for _ in range(obs_reps):
                    bare.append(e2e_run(e2e_depth, runtime=bare_runtime()))
                    instrumented.append(
                        e2e_run(e2e_depth, runtime=obs_runtime()))
                off_best, on_best = max(bare), max(instrumented)
                extra["obs_reps"] = obs_reps
                extra["obs_off_chunks_per_s"] = round(off_best, 4)
                extra["obs_on_chunks_per_s"] = round(on_best, 4)
                extra["obs_off_median_chunks_per_s"] = round(
                    float(np.median(bare)), 4)
                extra["obs_on_median_chunks_per_s"] = round(
                    float(np.median(instrumented)), 4)
                extra["obs_overhead_pct"] = round(
                    (off_best - on_best) / off_best * 100.0, 2)

            # chaos/degraded-mode A/B on the SAME directory: fault-free vs
            # a 5%-dead-channel fleet (injected via the resilience fault
            # registry, masked+imputed by the health sentinel) — the
            # throughput cost of running degraded, as a measured ratio.
            # Fault-isolated like the gather entry: an injection/sentinel
            # failure surfaces as chaos_error instead of killing the sweep.
            if not os.environ.get("BENCH_SKIP_CHAOS"):
                try:
                    from das_diff_veh_tpu.config import HealthConfig
                    from das_diff_veh_tpu.resilience import (FaultPlan,
                                                             FaultSpec,
                                                             faults)

                    dead_frac = 0.05
                    pcfg_h = pcfg.replace(health=HealthConfig(enabled=True))

                    def chaos_run() -> tuple:
                        ds = DirectoryDataset("20230301", root=tdir,
                                              ch1=None, ch2=None,
                                              smoothing=True,
                                              rescale_after=None)
                        t0 = time.perf_counter()
                        res = run_directory(
                            ds, pcfg_h, method="xcorr", x_is_channels=False,
                            runtime=RuntimeConfig(prefetch_depth=e2e_depth,
                                                  max_retries=0))
                        dt = time.perf_counter() - t0
                        assert res.complete and not res.quarantined
                        return n_files / dt, res.n_degraded

                    # warm ONLY the sentinel's fused _screen program (the
                    # single cold piece — process_chunk is already warm from
                    # the e2e runs above) on one actually-loaded chunk so it
                    # compiles at the exact post-read shape/dtype; a full
                    # directory sweep here would re-pay n_files chunks for a
                    # millisecond compile
                    from das_diff_veh_tpu.resilience.health import \
                        screen_section
                    ds_w = DirectoryDataset("20230301", root=tdir,
                                            ch1=None, ch2=None,
                                            smoothing=True,
                                            rescale_after=None)
                    screen_section(ds_w[0], pcfg_h.health, tag="bench_warmup")
                    clean_cps, n_deg0 = chaos_run()
                    assert n_deg0 == 0
                    plan = FaultPlan(specs=(FaultSpec(
                        "io.corrupt", "dead", param=dead_frac),), seed=13)
                    with faults.injected(plan):
                        deg_cps, n_deg = chaos_run()
                    assert n_deg == n_files, \
                        f"expected every chunk degraded, got {n_deg}"
                    extra["chaos_dead_channel_fraction"] = dead_frac
                    extra["chaos_clean_chunks_per_s"] = round(clean_cps, 4)
                    extra["chaos_degraded_chunks_per_s"] = round(deg_cps, 4)
                    extra["chaos_degraded_over_clean"] = round(
                        deg_cps / clean_cps, 3)
                except Exception as e:  # noqa: BLE001 — disclosed, never fatal
                    extra["chaos_error"] = f"{type(e).__name__}: {e}"[:300]
        finally:
            shutil.rmtree(tdir, ignore_errors=True)
    elif not os.environ.get("BENCH_SKIP_CHAOS"):
        # the chaos A/B rides the e2e directory: skipping e2e skips it too,
        # but the verify contract wants chaos_* keys OR a disclosure, never
        # a silent hole in the JSON
        extra["chaos_error"] = "skipped: BENCH_SKIP_E2E set (chaos A/B runs on the e2e directory)"

    # --- online serving: naive per-request vs microbatched+bucketed engine ----
    # Open-loop load (fixed arrival schedule, latency includes queueing) of
    # requests whose nt varies across BENCH_SERVE_SHAPES variants.  The naive
    # server calls the jitted program directly on each request's exact shape
    # (one warmup on the first shape — a deployment that warmed its nominal
    # shape but receives variable-length segments), so every novel shape
    # pays a trace+compile inline and the requests queued behind it eat the
    # delay.  The engine pads everything to ONE bucket warmed ahead of time:
    # zero steady-state compiles (asserted via its cache-miss counter).  The
    # compute is a mid-weight real slice of the pipeline (surface-wave band
    # conditioning + f-v transform) so the bench stays minutes-scale on CPU
    # smoke runs; the compile-per-shape cost it amortizes is the same
    # phenomenon that costs ~40 s/shape for full process_chunk.
    if not os.environ.get("BENCH_SKIP_SERVE"):
        from das_diff_veh_tpu.config import (DispersionConfig as _DC,
                                             PipelineConfig as _PC,
                                             ServeConfig)
        from das_diff_veh_tpu.core.section import DasSection
        from das_diff_veh_tpu.ops.dispersion import fv_map_fk
        from das_diff_veh_tpu.pipeline.preprocess import preprocess_for_surface_waves
        from das_diff_veh_tpu.serve import FnComputeFactory, ServingEngine
        from das_diff_veh_tpu.serve.metrics import _percentile

        n_reqs = int(os.environ.get("BENCH_SERVE_REQS", 24))
        n_shapes = max(1, int(os.environ.get("BENCH_SERVE_SHAPES", 4)))
        inter_ms = float(os.environ.get("BENCH_SERVE_INTERARRIVAL_MS", 100.0))
        s_nch = int(os.environ.get("BENCH_SERVE_NCH", 96))
        s_nt = int(os.environ.get("BENCH_SERVE_NT", 4096))
        s_fs = 250.0
        s_pcfg = _PC()
        s_dcfg = _DC()
        s_freqs = jnp.asarray(freqs)
        s_vels = jnp.asarray(vels)
        nx_img = min(64, s_nch)

        def serve_body(data):
            d = preprocess_for_surface_waves(data, 1.0 / s_fs,
                                             s_pcfg.sw_preprocess,
                                             normalize=True)
            return fv_map_fk(d[:nx_img], s_pcfg.interrogator.dx, 1.0 / s_fs,
                             s_freqs, s_vels, norm=s_dcfg.norm,
                             sg_window=s_dcfg.sg_window,
                             sg_order=s_dcfg.sg_order)

        serve_jit = jax.jit(serve_body)

        def serve_build(bucket):
            def fn(section, valid, state):
                img = serve_jit(jnp.asarray(section.data))
                return np.asarray(jax.block_until_ready(img)), state
            return fn

        rng_s = np.random.default_rng(42)
        shapes = [(s_nch, s_nt - 128 * k) for k in range(n_shapes)]
        reqs = [DasSection(
                    rng_s.standard_normal(shapes[i % n_shapes],
                                          ).astype(np.float32),
                    np.arange(s_nch) * s_pcfg.interrogator.dx,
                    np.arange(shapes[i % n_shapes][1]) / s_fs)
                for i in range(n_reqs)]
        arrivals = np.arange(n_reqs) * inter_ms / 1e3

        def run_naive():
            lat = []
            t_start = time.perf_counter()
            for i, sec in enumerate(reqs):
                wait = arrivals[i] - (time.perf_counter() - t_start)
                if wait > 0:
                    time.sleep(wait)
                np.asarray(jax.block_until_ready(
                    serve_jit(jnp.asarray(sec.data))))
                lat.append((time.perf_counter() - t_start - arrivals[i]) * 1e3)
            wall = time.perf_counter() - t_start
            return lat, n_reqs / wall

        def run_engine():
            eng = ServingEngine(
                FnComputeFactory(serve_build, "bench_serve"),
                ServeConfig(buckets=((s_nch, s_nt),), max_batch=4,
                            max_queue=max(n_reqs, 8),
                            default_deadline_ms=600000.0)).start()
            futures = []
            t_start = time.perf_counter()
            for i, sec in enumerate(reqs):
                wait = arrivals[i] - (time.perf_counter() - t_start)
                if wait > 0:
                    time.sleep(wait)
                futures.append(eng.submit(sec))
            for f in futures:
                f.result()
            wall = time.perf_counter() - t_start
            snap = eng.metrics()            # ring has per-request latencies
            eng.close()
            return snap, n_reqs / wall

        # naive first (its first-shape warmup = the nominal-shape deployment)
        np.asarray(jax.block_until_ready(
            serve_jit(jnp.asarray(reqs[0].data))))
        naive_lat, naive_rps = run_naive()
        snap, engine_rps = run_engine()
        assert snap["cache_misses"] == 0, \
            "engine recompiled in steady state (bucketed warmup broken)"
        naive_sorted = sorted(naive_lat)
        pct = _percentile          # same nearest-rank as the engine metrics

        extra["serve_requests"] = n_reqs
        extra["serve_shape_variants"] = n_shapes
        extra["serve_interarrival_ms"] = inter_ms
        extra["serve_naive_p50_ms"] = round(pct(naive_sorted, 0.50), 2)
        extra["serve_naive_p99_ms"] = round(pct(naive_sorted, 0.99), 2)
        extra["serve_naive_req_per_s"] = round(naive_rps, 3)
        extra["serve_engine_p50_ms"] = snap["latency_ms"]["p50"]
        extra["serve_engine_p99_ms"] = snap["latency_ms"]["p99"]
        extra["serve_engine_req_per_s"] = round(engine_rps, 3)
        extra["serve_engine_cache_misses"] = snap["cache_misses"]
        extra["serve_engine_mean_batch_occupancy"] = \
            snap["batch"]["mean_occupancy"]
        extra["serve_p99_speedup"] = round(
            pct(naive_sorted, 0.99) / max(snap["latency_ms"]["p99"], 1e-9), 2)

    # --- mesh serving: open-loop req/s vs replica count -----------------------
    # Scaling of the mesh engine's data-parallel replica workers under an
    # open-loop arrival schedule faster than one device absorbs.  This host
    # exposes 8 XLA devices but owns ONE physical core
    # (serve_mesh_host_cores), so real compute cannot scale here; the
    # per-request device time is SIMULATED with time.sleep (which releases
    # the GIL, so N replica threads overlap exactly as N independent devices
    # would) — disclosed as serve_mesh_simulated_device_ms.  What the sweep
    # measures honestly is the ENGINE: placement, fair-share queueing,
    # continuous batching per worker, and the zero-steady-state-compile SLO
    # across every (bucket, replica) program.  Fault-isolated to
    # serve_mesh_error so a scheduler regression never zeroes the rest of
    # the bench JSON.
    if not os.environ.get("BENCH_SKIP_SERVE_MESH"):
        try:
            from das_diff_veh_tpu.config import (MeshServeConfig,
                                                 ServeConfig as _SC)
            from das_diff_veh_tpu.core.section import DasSection as _DS
            from das_diff_veh_tpu.serve import (FnComputeFactory as _FCF,
                                                ServingEngine as _SE)
            from das_diff_veh_tpu.serve.mesh import MeshServingEngine
            from das_diff_veh_tpu.serve.metrics import _percentile as _pctm

            m_reqs = int(os.environ.get("BENCH_SERVE_MESH_REQS", 48))
            m_inter_ms = float(os.environ.get(
                "BENCH_SERVE_MESH_INTERARRIVAL_MS", 5.0))
            m_dev_ms = float(os.environ.get("BENCH_SERVE_MESH_DEVICE_MS",
                                            40.0))
            m_bucket = (16, 64)

            def mesh_build(bucket):
                def fn(section, valid, state):
                    time.sleep(m_dev_ms / 1e3)     # simulated device time
                    return float(np.asarray(
                        section.data)[:valid[0], :valid[1]].sum()), state
                return fn

            rng_m = np.random.default_rng(7)
            m_secs = [_DS(rng_m.standard_normal(m_bucket).astype(np.float32),
                          np.arange(m_bucket[0], dtype=np.float64),
                          np.arange(m_bucket[1], dtype=np.float64))
                      for _ in range(m_reqs)]
            m_arrivals = np.arange(m_reqs) * m_inter_ms / 1e3
            m_serve_cfg = _SC(buckets=(m_bucket,), max_batch=8,
                              max_queue=max(m_reqs, 8),
                              default_deadline_ms=600000.0)

            def mesh_drive(eng, tenants=False):
                futures = []
                t_start = time.perf_counter()
                for i, sec in enumerate(m_secs):
                    wait = m_arrivals[i] - (time.perf_counter() - t_start)
                    if wait > 0:
                        time.sleep(wait)
                    futures.append(eng.submit(
                        sec, tenant=f"t{i % 2}" if tenants else None))
                for f in futures:
                    f.result()
                wall = time.perf_counter() - t_start
                snap = eng.metrics()
                eng.close()
                assert snap["cache_misses"] == 0, \
                    "mesh engine recompiled in steady state"
                return snap, m_reqs / wall

            # baseline: the single-dispatcher engine on the same load
            base_snap, base_rps = mesh_drive(
                _SE(_FCF(mesh_build, "bench_serve_mesh"),
                    m_serve_cfg).start())
            extra["serve_mesh_requests"] = m_reqs
            extra["serve_mesh_interarrival_ms"] = m_inter_ms
            extra["serve_mesh_simulated_device_ms"] = m_dev_ms
            extra["serve_mesh_host_cores"] = os.cpu_count()
            extra["serve_mesh_baseline_req_per_s"] = round(base_rps, 3)
            extra["serve_mesh_baseline_p99_ms"] = \
                base_snap["latency_ms"]["p99"]
            for n_rep in (1, 2, 4, 8):
                snap_m, rps_m = mesh_drive(
                    MeshServingEngine(
                        _FCF(mesh_build, "bench_serve_mesh"),
                        MeshServeConfig(serve=m_serve_cfg, replicas=n_rep,
                                        tenant_quota=m_reqs)).start(),
                    tenants=True)
                extra[f"serve_mesh_req_per_s_{n_rep}"] = round(rps_m, 3)
                extra[f"serve_mesh_p99_ms_{n_rep}"] = \
                    snap_m["latency_ms"]["p99"]
            extra["serve_mesh_speedup_8x"] = round(
                extra["serve_mesh_req_per_s_8"] / max(base_rps, 1e-9), 2)
            assert extra["serve_mesh_speedup_8x"] >= 3.0, \
                (f"8-replica mesh req/s only "
                 f"{extra['serve_mesh_speedup_8x']}x the single-device "
                 "engine (SLO: >= 3x)")
        except Exception as e:           # noqa: BLE001 — fault isolation
            extra["serve_mesh_error"] = f"{type(e).__name__}: {e}"[:300]

    # --- Pallas all-pairs kernel (BASELINE config 4) --------------------------
    # TPU backends only (the kernel uses pltpu memory spaces); "axon" is the
    # tunneled single-TPU platform of this environment.  Each sub-config has a
    # BENCH_SKIP_* opt-out so the full sweep stays one command while CI-style
    # runs can trim the long ones.
    if jax.default_backend() in ("tpu", "axon") and not os.environ.get("BENCH_SKIP_PALLAS"):
        from das_diff_veh_tpu.ops.pallas_xcorr import xcorr_all_pairs_peak
        from das_diff_veh_tpu.workloads import make_ambient_record

        wlen4 = 1024

        def nwin_of(nt):
            return (nt - wlen4) // (wlen4 // 2) + 1

        def bench_peak(data, src_chunk):
            fp = jax.jit(lambda d: xcorr_all_pairs_peak(
                d, wlen4, src_chunk=src_chunk, use_pallas=True))
            out = jax.block_until_ready(fp(data))        # compile
            t0 = time.perf_counter()
            out = jax.block_until_ready(fp(data))
            return time.perf_counter() - t0, out

        nch, nt = 4096, 4096
        big = make_ambient_record(nch, nt)
        dt_pallas, peak4k = bench_peak(big, 64)
        rate_4k = nch * nch / dt_pallas
        extra["pallas_allpairs_4k_s"] = round(dt_pallas, 3)
        extra["pallas_allpairs_4k_pairs_per_sec"] = round(rate_4k, 1)
        extra["pallas_allpairs_4k_pair_windows_per_sec"] = round(
            rate_4k * nwin_of(nt), 1)

        # sharded tier ON CHIP: parallel.allpairs runs the same Pallas kernel
        # under shard_map as a RING pipeline (receiver spectra shards rotate
        # via ppermute; one device on this rig makes the ring degenerate but
        # exercises the code path), with parity against the unsharded result
        # above.  The replicated-vs-ring memory A/B happens in the 10k
        # section below — ring first, replicated last, because peak-bytes
        # counters are cumulative.
        if not os.environ.get("BENCH_SKIP_SHARDED"):
            from das_diff_veh_tpu.config import RingConfig
            from das_diff_veh_tpu.parallel import (make_mesh,
                                                   sharded_all_pairs_peak)

            mesh = make_mesh()
            n_dev = int(mesh.devices.size)

            def peak_bytes():
                # min over mesh devices: every ring participant does the
                # same work, but device 0 additionally carries the earlier
                # unsharded benches in its cumulative peak counter — the
                # cleanest device is the honest per-device working set
                try:
                    stats = [d.memory_stats() for d in mesh.devices.flat]
                    return min(s["peak_bytes_in_use"] for s in stats)
                except Exception:
                    return None                 # platform has no allocator stats

            def bench_ring(data, n, src_chunk, cfg, key):
                f = jax.jit(lambda d: sharded_all_pairs_peak(
                    d, wlen4, mesh, src_chunk=src_chunk, use_pallas=True,
                    ring=cfg))
                out = jax.block_until_ready(f(data))     # compile
                t0 = time.perf_counter()
                out = jax.block_until_ready(f(data))
                dt = time.perf_counter() - t0
                extra[f"{key}_s"] = round(dt, 3)
                extra[f"{key}_pairs_per_sec"] = round(n * n / dt, 1)
                return out

            sh = bench_ring(big, nch, 64, RingConfig(), "ring_4k")
            extra["ring_n_devices"] = n_dev
            extra["ring_4k_parity_max_abs_diff"] = float(
                jnp.max(jnp.abs(sh - peak4k)))
            # legacy keys (pre-ring name) so BENCH history stays comparable
            extra["pallas_sharded_4k_s"] = extra["ring_4k_s"]
            extra["pallas_sharded_4k_pairs_per_sec"] = \
                extra["ring_4k_pairs_per_sec"]
            extra["pallas_sharded_n_devices"] = n_dev
            extra["pallas_sharded_parity_max_abs_diff"] = \
                extra["ring_4k_parity_max_abs_diff"]

        # minutes-long record (nt ~ 60k = 1 min at 1 kHz) through the
        # win_block kernel-grid streaming (auto-engaged: 119 windows), with a
        # short record at the SAME channel count anchoring the record-length-
        # invariance ratio in per-(pair, window) throughput
        if not os.environ.get("BENCH_SKIP_LONG"):
            nch_l, nt_l = 2048, 61440
            dt_s, _ = bench_peak(make_ambient_record(nch_l, 4096, seed=1), 64)
            dt_l, _ = bench_peak(make_ambient_record(nch_l, nt_l, seed=2), 64)
            pw_short = nch_l * nch_l * nwin_of(4096) / dt_s
            pw_long = nch_l * nch_l * nwin_of(nt_l) / dt_l
            extra["pallas_long_record_nt"] = nt_l
            extra["pallas_long_record_nwin"] = nwin_of(nt_l)
            extra["pallas_long_record_s"] = round(dt_l, 3)
            extra["pallas_long_record_pairs_per_sec"] = round(
                nch_l * nch_l / dt_l, 1)
            extra["pallas_long_record_pair_windows_per_sec"] = round(pw_long, 1)
            extra["pallas_short_record_2k_pair_windows_per_sec"] = round(
                pw_short, 1)
            extra["pallas_record_length_invariance_ratio"] = round(
                pw_long / pw_short, 3)

        # config 4 at its ACTUAL channel spec: 10k channels / 1 kHz
        # (BASELINE.md).  src_chunk drops to 32 here (env-tunable) so the
        # per-chunk HBM transients stay at the 4k config's footprint — the
        # working-set effect docs/PERF.md attributes the historical 4k->10k
        # pairs/s gap to.
        if not os.environ.get("BENCH_SKIP_10K"):
            nch10, nt10 = 10000, 4096                    # 1 kHz x ~4 s
            sc10 = int(os.environ.get("BENCH_10K_SRC_CHUNK", 32))
            big10 = make_ambient_record(nch10, nt10, seed=3)
            dt10, _ = bench_peak(big10, sc10)
            rate_10k = nch10 * nch10 / dt10
            extra["pallas_allpairs_10k_s"] = round(dt10, 3)
            extra["pallas_allpairs_10k_pairs_per_sec"] = round(rate_10k, 1)
            extra["pallas_allpairs_10k_src_chunk"] = sc10
            extra["pallas_allpairs_10k_vs_4k_rate"] = round(
                rate_10k / rate_4k, 3)

            # ring at the 10k spec + the per-device peak-memory A/B.  Ring
            # runs FIRST so its peak-bytes reading (min over mesh devices)
            # is not polluted by the replicated layout's O(nch) footprint;
            # the replicated/ring ratio should approach the device count D
            # (>= ~0.8*D on a multi-chip mesh — on this 1-chip rig both
            # layouts hold the full set and the ratio sits near 1,
            # disclosed via ring_n_devices).  The ratio is a LOWER bound
            # on the true layout ratio: mode-independent allocations (the
            # replicated (nch, nt) input record, earlier bench footprints)
            # appear in both peaks, diluting it — the structural
            # no-broadcast jaxpr pin in tests/test_parallel.py is the
            # primary O(nch/D) guarantee, this number is supporting
            # evidence.
            if not os.environ.get("BENCH_SKIP_SHARDED"):
                bench_ring(big10, nch10, sc10, RingConfig(), "ring_10k")
                ring_peak = peak_bytes()
                extra["ring_10k_vs_4k_rate"] = round(
                    extra["ring_10k_pairs_per_sec"]
                    / extra["ring_4k_pairs_per_sec"], 3)
                if ring_peak is not None:
                    extra["ring_10k_peak_bytes_per_device"] = ring_peak
                bench_ring(big10, nch10, sc10,
                           RingConfig(mode="replicated"), "replicated_10k")
                repl_peak = peak_bytes()
                if ring_peak is not None and repl_peak is not None:
                    extra["replicated_10k_peak_bytes_per_device"] = repl_peak
                    extra["replicated_vs_ring_peak_bytes_ratio"] = round(
                        repl_peak / max(ring_peak, 1), 3)

    # --- modular entries (also selectable via --json-only) -------------------
    for name, entry_fn in ENTRIES.items():
        if os.environ.get(f"BENCH_SKIP_{name.upper()}"):
            continue
        try:
            entry_fn(extra)
        except Exception as e:
            extra[f"{name}_error"] = f"{type(e).__name__}: {e}"[:300]

    assert bool(jnp.isfinite(img).all()), "benchmark produced non-finite image"
    # primary = per-build device time amortized over K in-dispatch builds:
    # the number a non-tunneled deployment sees.  The per-dispatch latency on
    # this host (single_dispatch_s) is dominated by the axon tunnel round
    # trip and is disclosed in extra.  The metric is RENAMED (was
    # vsg_disp_700m_build = single-dispatch wall in rounds 1-2) so history
    # is not silently compared across different definitions.
    print(json.dumps({
        "metric": "vsg_disp_700m_build_amortized",
        "value": round(device_time, 5),
        "unit": "s",
        "vs_baseline": round(np_time / device_time, 2),
        "extra": extra,
    }))


if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv and argv[0] == "--json-only":
        if not argv[1:]:
            print(f"usage: bench.py --json-only <key> [...]; "
                  f"selectable: {sorted(ENTRIES)}", file=sys.stderr)
            sys.exit(2)
        sys.exit(run_json_only(argv[1:]))
    sys.exit(main())
