#!/usr/bin/env python
"""End-to-end benchmark: 700 m virtual-shot-gather stack + dispersion image.

Reproduces the reference's headline imaging workload (BASELINE.md: a
~60-window class stack at the 700 m pivot -> one dispersion image, the
save_disp_imgs / bootstrap inner loop, apis/imaging_classes.py:50-85) on the
accelerator via the batched jit pipeline, against the NumPy oracle (the
reference semantics, measured fresh on this machine per BASELINE.md §"must
measure").

Prints ONE JSON line:
  {"metric": "vsg_disp_700m_build", "value": <seconds>, "unit": "s",
   "vs_baseline": <numpy_time / jax_time>}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N_WINDOWS = 60
N_BASELINE_WINDOWS = 6          # numpy oracle timed on a subset, scaled up


def main() -> None:
    import jax
    import jax.numpy as jnp

    from das_diff_veh_tpu.config import DispersionConfig, GatherConfig
    from das_diff_veh_tpu.models import vsg as V
    from das_diff_veh_tpu.oracle.vsg_ref import ref_build_gather
    from das_diff_veh_tpu.oracle.dispersion_ref import ref_map_fv
    from das_diff_veh_tpu.workloads import make_gather_geometry, make_window_batch

    x0, fs = 700.0, 250.0
    gcfg = GatherConfig()
    dcfg = DispersionConfig()
    batch, x = make_window_batch(N_WINDOWS, x0=x0, fs=fs)
    g = make_gather_geometry(x, x0=x0, fs=fs, cfg=gcfg)
    offs = g.offsets(x)
    freqs = np.arange(dcfg.freq_min, dcfg.freq_max, dcfg.freq_step)
    vels = np.arange(dcfg.vel_min, dcfg.vel_max, dcfg.vel_step)

    # --- NumPy oracle baseline (reference semantics) --------------------------
    d_np = np.asarray(batch.data, dtype=np.float64)
    t_np = np.asarray(batch.t, dtype=np.float64)
    tx_np = np.asarray(batch.traj_x, dtype=np.float64)
    tt_np = np.asarray(batch.traj_t, dtype=np.float64)
    t0 = time.perf_counter()
    acc = None
    for w in range(N_BASELINE_WINDOWS):
        xcf, _, _ = ref_build_gather(d_np[w], x, t_np[w], tx_np[w], tt_np[w],
                                     x0, x0 - 150.0, x0 + 75.0,
                                     wlen_s=gcfg.wlen, time_window=gcfg.time_window,
                                     delta_t=gcfg.delta_t)
        acc = xcf if acc is None else acc + xcf
    acc /= N_BASELINE_WINDOWS
    gather_time = (time.perf_counter() - t0) * (N_WINDOWS / N_BASELINE_WINDOWS)
    sxi = int(np.abs(offs - (-150.0)).argmin())
    exi = int(np.abs(offs - 0.0).argmin())
    t0 = time.perf_counter()
    ref_map_fv(acc[sxi:exi + 1], 8.16, 1.0 / fs, freqs, vels, norm=dcfg.norm)
    np_time = gather_time + (time.perf_counter() - t0)   # image runs once per stack

    # --- JAX pipeline (TPU when available) ------------------------------------
    @jax.jit
    def pipeline(b):
        stack = V.stack_gathers(V.build_gather_batch(b, g, gcfg), b.valid)
        return V.gather_disp_image(stack, offs, g.dt, 8.16, dcfg, -150.0, 0.0)

    img = jax.block_until_ready(pipeline(batch))        # compile
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        img = jax.block_until_ready(pipeline(batch))
    jax_time = (time.perf_counter() - t0) / reps

    assert bool(jnp.isfinite(img).all()), "benchmark produced non-finite image"
    print(json.dumps({
        "metric": "vsg_disp_700m_build",
        "value": round(jax_time, 5),
        "unit": "s",
        "vs_baseline": round(np_time / jax_time, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
