"""Fused trajectory-following gather kernel (ops/pallas_gather.py): parity
against the serialized path on forward/backward/reverse/edge-truncated
cases, the structural no-serialized-slice-chain jaxpr pin, and the
GatherConfig knob plumbing.  The kernel runs in interpret mode here (CPU
CI, ``mode="fused"`` forces it past the auto backend gate); the real-TPU
lowering is exercised by bench.py's ``stage_gather_traj_*`` entries.

Budget note: every case below is a small direct ``xcorr_traj_follow`` /
``build_gather`` call — no ``process_chunk`` compiles (those cost ~40 s
each on this host; the session-scoped conftest fixtures own them).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from das_diff_veh_tpu.config import GatherConfig
from das_diff_veh_tpu.ops import xcorr as jx
from das_diff_veh_tpu.ops.pallas_gather import DOT_MAX_WLEN, FUSED_MAX_NWIN

RNG = np.random.default_rng(31)

NCH, NT, WLEN, NSAMP = 10, 2000, 250, 800
PIVOT = 6


def _scene():
    data = jnp.asarray(RNG.standard_normal((NCH, NT)))
    t_axis = jnp.arange(NT) * 0.004                     # 8 s record
    ch = jnp.asarray([2, 3, 5, 7])
    return data, t_axis, ch


def _both(data, t_axis, ch, t_at_ch, reverse, finish="rfft", **kw):
    ser = np.asarray(jx.xcorr_traj_follow(data, t_axis, PIVOT, ch, t_at_ch,
                                          NSAMP, WLEN, reverse=reverse,
                                          mode="serialized", **kw))
    fus = np.asarray(jx.xcorr_traj_follow(data, t_axis, PIVOT, ch, t_at_ch,
                                          NSAMP, WLEN, reverse=reverse,
                                          mode="fused", finish=finish, **kw))
    return ser, fus


@pytest.mark.parametrize("reverse", [False, True])
def test_fused_parity_in_range(reverse):
    """Acceptance bar: fused vs serialized <= 1e-7 (measured bitwise on the
    rfft finish — the windows are identical copies and the correlate is the
    same batched-rfft program)."""
    data, t_axis, ch = _scene()
    t_at_ch = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    ser, fus = _both(data, t_axis, ch, t_at_ch, reverse)
    np.testing.assert_allclose(fus, ser, rtol=0, atol=1e-7)
    np.testing.assert_array_equal(fus, ser)             # and in fact exact


@pytest.mark.parametrize("reverse", [False, True])
def test_fused_parity_record_edge_truncated(reverse):
    """Starts at/past the record end: forward windows truncate like a numpy
    slice, backward starts past nt truncate from the far side — the
    kernel's avail masks must reproduce the serialized path exactly."""
    data, t_axis, ch = _scene()
    # dt_idx lands near nt, at nt-1, and past every sample (argmax -> 0 for
    # the never-true comparison is exercised by t > t_axis.max())
    t_at_ch = jnp.asarray([6.9, 7.5, 7.996, 4.0])
    ser, fus = _both(data, t_axis, ch, t_at_ch, reverse)
    np.testing.assert_array_equal(fus, ser)


def test_fused_parity_backward_empty_slice():
    """Backward windows with start < nsamp are numpy's empty slice: every
    window invalid, output rows exactly zero on both paths."""
    data, t_axis, ch = _scene()
    t_at_ch = jnp.asarray([0.1, 0.5, 3.5, 5.0])        # first two < nsamp*dt
    ser, fus = _both(data, t_axis, ch, t_at_ch, reverse=True)
    np.testing.assert_array_equal(fus, ser)
    assert np.abs(ser[:2]).max() == 0.0                 # the empty-slice rows
    assert np.abs(ser[2:]).max() > 0.0                  # the live rows


def test_fused_parity_float32():
    """The pipeline feeds float32 records; parity must not depend on the
    x64 default the test session enables."""
    data, t_axis, ch = _scene()
    t_at_ch = jnp.asarray([1.0, 2.5, 3.0, 6.5])
    ser, fus = _both(data.astype(jnp.float32), t_axis, ch, t_at_ch, False)
    assert fus.dtype == np.float32
    np.testing.assert_array_equal(fus, ser)


@pytest.mark.parametrize("reverse", [False, True])
def test_dot_finish_matches_rfft(reverse):
    """The in-kernel MXU dot finish is the same circular correlation
    evaluated in the time domain: equal to the rfft finish to float
    rounding (x64 session: ~1e-13; far inside the 1e-7 oracle bar)."""
    data, t_axis, ch = _scene()
    t_at_ch = jnp.asarray([1.0, 2.0, 3.0, 7.9])        # incl. a truncated row
    ser, dot = _both(data, t_axis, ch, t_at_ch, reverse, finish="dot")
    np.testing.assert_allclose(dot, ser, rtol=0, atol=1e-7)


def test_fused_under_jit_vmap():
    """The vsg pipeline calls the gather inside jit(vmap(...)): the
    scalar-prefetch pallas_call must batch (window-batch axis) and match
    the per-window results."""
    data, t_axis, ch = _scene()
    t_at_ch = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    f = jax.jit(lambda d, t: jx.xcorr_traj_follow(
        d, t_axis, PIVOT, ch, t, NSAMP, WLEN, mode="fused"))
    db = jnp.stack([data, data * 0.5 + 1.0])
    tb = jnp.stack([t_at_ch, t_at_ch + 0.5])
    got = np.asarray(jax.vmap(f)(db, tb))
    for i in range(2):
        np.testing.assert_array_equal(got[i], np.asarray(f(db[i], tb[i])))


def test_fused_traced_pivot():
    """The pivot row index rides the prefetched scalar operand, so a
    *traced* pivot (legal on the serialized path — cf. xcorr_vshot's
    traced ``ivs``) is equally legal on the fused path."""
    data, t_axis, ch = _scene()
    t_at_ch = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    f = jax.jit(lambda d, pv: jx.xcorr_traj_follow(
        d, t_axis, pv, ch, t_at_ch, NSAMP, WLEN, mode="fused"))
    got = np.asarray(f(data, jnp.int32(PIVOT)))
    want = np.asarray(jx.xcorr_traj_follow(data, t_axis, PIVOT, ch, t_at_ch,
                                           NSAMP, WLEN, mode="serialized"))
    np.testing.assert_array_equal(got, want)


def test_no_serialized_slice_chain_jaxpr():
    """Structural acceptance pin: the fused program contains NO record-
    cutting gather/dynamic-slice outside the kernel (the serialized chain
    XLA would sequence on TPU) and DOES contain the pallas_call; the
    serialized program trips the same detector — which validates it."""
    from jaxpr_checks import has_primitive, record_cut_slices

    data, t_axis, ch = _scene()
    t_at_ch = jnp.asarray([1.0, 2.0, 3.0, 4.0])

    def traced(mode):
        return jax.make_jaxpr(
            lambda d, t: jx.xcorr_traj_follow(d, t_axis, PIVOT, ch, t,
                                              NSAMP, WLEN, mode=mode))(
            data, t_at_ch)

    fused = traced("fused")
    assert not record_cut_slices(fused, NT), \
        f"record cut outside the kernel: {record_cut_slices(fused, NT)}"
    assert has_primitive(fused, "pallas_call")
    serialized = traced("serialized")
    assert record_cut_slices(serialized, NT), \
        "detector failed to flag the legacy serialized slice chain"
    assert not has_primitive(serialized, "pallas_call")


def test_vsg_gather_config_knob():
    """build_gather honors GatherConfig.traj_gather: fused and serialized
    configurations agree at the oracle bar through the full gather (both
    xcorr_traj_follow sides AND the xcorr_vshot_at near/right slabs, i.e.
    parity of the composed program against the existing engine)."""
    from test_vsg import _window_scene

    from das_diff_veh_tpu.models import vsg as V

    data, x, t, traj_x, traj_t, x0 = _window_scene()
    args = (jnp.asarray(data), jnp.asarray(t), jnp.asarray(x),
            jnp.asarray(traj_x), jnp.asarray(traj_t),
            jnp.ones(traj_t.size, bool))
    outs = {}
    for mode in ("serialized", "fused"):
        cfg = GatherConfig(traj_gather=mode)
        g = V.VsgGeometry.build(x, t[1] - t[0], x0, x0 - 150.0, x0 + 75.0,
                                cfg)
        outs[mode] = np.asarray(V.build_gather(*args, g, cfg))
    np.testing.assert_allclose(outs["fused"], outs["serialized"],
                               rtol=0, atol=1e-7)


def test_auto_mode_serialized_on_cpu():
    """``"auto"`` (the config default) resolves to the serialized path off
    TPU — same backend gate as pallas_xcorr._decide_pallas — so the CPU
    pipeline programs (and their tier-1 compile times) are unchanged."""
    from das_diff_veh_tpu.ops.xcorr import _decide_traj_gather

    assert jax.default_backend() == "cpu"
    assert _decide_traj_gather("auto", 5, WLEN, "rfft") is False
    assert _decide_traj_gather(None, 5, WLEN, "rfft") is False
    assert _decide_traj_gather("fused", 5, WLEN, "rfft") is True
    assert _decide_traj_gather("serialized", 5, WLEN, "rfft") is False


def test_invalid_knobs_rejected():
    data, t_axis, ch = _scene()
    t_at_ch = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    with pytest.raises(ValueError, match="traj_gather"):
        jx.xcorr_traj_follow(data, t_axis, PIVOT, ch, t_at_ch, NSAMP, WLEN,
                             mode="warp")
    with pytest.raises(ValueError, match="traj_gather_finish"):
        jx.xcorr_traj_follow(data, t_axis, PIVOT, ch, t_at_ch, NSAMP, WLEN,
                             mode="fused", finish="fft2")
    # dot finish past the VMEM cap: explicit request raises with guidance
    # (error names the GatherConfig knob the cap now lives on)
    big_wlen = DOT_MAX_WLEN + 2
    with pytest.raises(ValueError, match="dot_max_wlen"):
        jx.xcorr_traj_follow(data, t_axis, PIVOT, ch, t_at_ch,
                             4 * big_wlen, big_wlen, mode="fused",
                             finish="dot")
    # ... and the bound is JOINT in (nwin, wlen): an in-cap wlen with a
    # window count that blows the (nwin, wlen, wlen) VMEM matrix also
    # raises (and auto falls back rather than lowering it)
    from das_diff_veh_tpu.ops.pallas_gather import fused_supported
    nwin_many = 17                                      # 17*256^2 > 2^20
    nsamp_many = (nwin_many - 1) * (DOT_MAX_WLEN // 2) + DOT_MAX_WLEN
    assert not fused_supported(nwin_many, DOT_MAX_WLEN, "dot")
    with pytest.raises(ValueError, match="dot_max_matrix_elems"):
        jx.xcorr_traj_follow(data, t_axis, PIVOT, ch, t_at_ch,
                             nsamp_many, DOT_MAX_WLEN, mode="fused",
                             finish="dot")
    # past the per-step unroll bound the fused path refuses (auto falls
    # back to serialized instead — fused_supported gates it)
    small_wlen = 16
    nsamp_big = (FUSED_MAX_NWIN + 2) * (small_wlen // 2) + small_wlen
    assert not fused_supported(FUSED_MAX_NWIN + 2, small_wlen, "rfft")
    with pytest.raises(ValueError, match="fused_max_nwin"):
        jx.xcorr_traj_follow(data, t_axis, PIVOT, ch, t_at_ch,
                             nsamp_big, small_wlen, mode="fused")


def test_empty_channel_set():
    """nk = 0 (pivot adjacent to the gather end) short-circuits to an
    empty result on the fused path like the vmapped legacy path."""
    data, t_axis, _ = _scene()
    empty = jnp.asarray([], dtype=jnp.int32)
    tt = jnp.asarray([])
    ser, fus = _both(data, t_axis, empty, tt, reverse=False)
    assert ser.shape == fus.shape == (0, WLEN)
