import numpy as np
import jax.numpy as jnp
import pytest

from das_diff_veh_tpu.ops import xcorr as jx
from das_diff_veh_tpu.oracle import xcorr_ref as ox

RNG = np.random.default_rng(7)


def test_pair_matches_reference_scheme():
    nt, wlen = 1000, 250
    a = RNG.standard_normal(nt)
    b = RNG.standard_normal(nt)
    ref = ox.ref_xcorr_pair(a, b, wlen)
    ours = np.asarray(jx.xcorr_pair(jnp.asarray(a), jnp.asarray(b), wlen))
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-9)


@pytest.mark.parametrize("reverse", [False, True])
def test_vshot_matches_reference_scheme(reverse):
    nch, nt, wlen = 12, 1000, 250
    data = RNG.standard_normal((nch, nt))
    ref = ox.ref_xcorr_vshot(data, ivs=4, wlen=wlen, reverse=reverse)
    ours = np.asarray(jx.xcorr_vshot(jnp.asarray(data), 4, wlen, reverse=reverse))
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-9)


def test_vshot_batch_consistent_with_single():
    nch, nt, wlen = 6, 600, 128
    data = RNG.standard_normal((nch, nt))
    batch = np.asarray(jx.xcorr_vshot_batch(jnp.asarray(data), wlen))
    for ivs in range(nch):
        single = np.asarray(jx.xcorr_vshot(jnp.asarray(data), ivs, wlen))
        np.testing.assert_allclose(batch[ivs], single, rtol=1e-8, atol=1e-10)


def test_lag_recovery():
    """xcorr of a lag-shifted copy peaks at the known lag."""
    nt, wlen, lag = 4000, 500, 30
    base = RNG.standard_normal(nt + lag)
    src = base[:nt]
    rcv = base[lag:lag + nt]          # rcv(t) = src(t + lag): rcv leads
    out = np.asarray(jx.xcorr_pair(jnp.asarray(src), jnp.asarray(rcv), wlen))
    # c[k] = sum src[(n+k)%W] rcv[n] with rcv[n]=src[n+lag] peaks at k=lag;
    # zero lag sits at wlen//2 after the centering roll
    assert int(np.argmax(out)) == wlen // 2 + lag


@pytest.mark.parametrize("reverse", [False, True])
def test_traj_follow_matches_reference_scheme(reverse):
    nch, nt, wlen, nsamp = 10, 2000, 250, 800
    data = RNG.standard_normal((nch, nt))
    t_axis = np.arange(nt) * 0.004
    ch_indices = np.array([2, 3, 5, 7])
    t_at_ch = np.array([1.0, 2.0, 3.0, 4.0])
    ref = ox.ref_xcorr_traj_follow(data, t_axis, 6, ch_indices, t_at_ch,
                                   nsamp, wlen, reverse=reverse)
    ours = np.asarray(jx.xcorr_traj_follow(jnp.asarray(data), jnp.asarray(t_axis), 6,
                                           jnp.asarray(ch_indices), jnp.asarray(t_at_ch),
                                           nsamp, wlen, reverse=reverse))
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-9)


def test_traj_follow_clips_at_boundaries():
    """Windows that would run off the record are clipped, not wrapped."""
    nch, nt, wlen, nsamp = 4, 1000, 100, 400
    data = RNG.standard_normal((nch, nt))
    t_axis = np.arange(nt) * 0.004
    # target time near record end -> forward window must clip
    out = np.asarray(jx.xcorr_traj_follow(jnp.asarray(data), jnp.asarray(t_axis), 0,
                                          jnp.asarray([1]), jnp.asarray([3.99]),
                                          nsamp, wlen))
    assert np.isfinite(out).all()
