import jax.numpy as jnp
import numpy as np
import pytest

from das_diff_veh_tpu.ops import xcorr as jx
from das_diff_veh_tpu.oracle import xcorr_ref as ox

RNG = np.random.default_rng(7)


def test_pair_matches_reference_scheme():
    nt, wlen = 1000, 250
    a = RNG.standard_normal(nt)
    b = RNG.standard_normal(nt)
    ref = ox.ref_xcorr_pair(a, b, wlen)
    ours = np.asarray(jx.xcorr_pair(jnp.asarray(a), jnp.asarray(b), wlen))
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-9)


@pytest.mark.parametrize("reverse", [False, True])
def test_vshot_matches_reference_scheme(reverse):
    nch, nt, wlen = 12, 1000, 250
    data = RNG.standard_normal((nch, nt))
    ref = ox.ref_xcorr_vshot(data, ivs=4, wlen=wlen, reverse=reverse)
    ours = np.asarray(jx.xcorr_vshot(jnp.asarray(data), 4, wlen, reverse=reverse))
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-9)


def test_vshot_batch_consistent_with_single():
    nch, nt, wlen = 6, 600, 128
    data = RNG.standard_normal((nch, nt))
    batch = np.asarray(jx.xcorr_vshot_batch(jnp.asarray(data), wlen))
    for ivs in range(nch):
        single = np.asarray(jx.xcorr_vshot(jnp.asarray(data), ivs, wlen))
        np.testing.assert_allclose(batch[ivs], single, rtol=1e-8, atol=1e-10)


def test_lag_recovery():
    """xcorr of a lag-shifted copy peaks at the known lag."""
    nt, wlen, lag = 4000, 500, 30
    base = RNG.standard_normal(nt + lag)
    src = base[:nt]
    rcv = base[lag:lag + nt]          # rcv(t) = src(t + lag): rcv leads
    out = np.asarray(jx.xcorr_pair(jnp.asarray(src), jnp.asarray(rcv), wlen))
    # c[k] = sum src[(n+k)%W] rcv[n] with rcv[n]=src[n+lag] peaks at k=lag;
    # zero lag sits at wlen//2 after the centering roll
    assert int(np.argmax(out)) == wlen // 2 + lag


@pytest.mark.parametrize("reverse", [False, True])
def test_traj_follow_matches_reference_scheme(reverse):
    nch, nt, wlen, nsamp = 10, 2000, 250, 800
    data = RNG.standard_normal((nch, nt))
    t_axis = np.arange(nt) * 0.004
    ch_indices = np.array([2, 3, 5, 7])
    t_at_ch = np.array([1.0, 2.0, 3.0, 4.0])
    ref = ox.ref_xcorr_traj_follow(data, t_axis, 6, ch_indices, t_at_ch,
                                   nsamp, wlen, reverse=reverse)
    ours = np.asarray(jx.xcorr_traj_follow(jnp.asarray(data), jnp.asarray(t_axis), 6,
                                           jnp.asarray(ch_indices), jnp.asarray(t_at_ch),
                                           nsamp, wlen, reverse=reverse))
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-9)


def test_traj_follow_clips_at_boundaries():
    """Windows that would run off the record are clipped, not wrapped."""
    nch, nt, wlen, nsamp = 4, 1000, 100, 400
    data = RNG.standard_normal((nch, nt))
    t_axis = np.arange(nt) * 0.004
    # target time near record end -> forward window must clip
    out = np.asarray(jx.xcorr_traj_follow(jnp.asarray(data), jnp.asarray(t_axis), 0,
                                          jnp.asarray([1]), jnp.asarray([3.99]),
                                          nsamp, wlen))
    assert np.isfinite(out).all()


def test_masked_window_specs_numpy_slice_semantics_all_starts():
    """The block-cut fast path must reproduce numpy slice semantics for
    EVERY start — in-range, at the record end, and out of range (backward
    start > nt truncates like data[s0:start]; s0 < 0 is the empty slice):
    validity masks match, and every valid window's samples are exact."""
    from das_diff_veh_tpu.ops.xcorr import _masked_window_specs

    nt, nsamp, wlen, offset = 500, 300, 100, 50

    def ref(data, start, backward):
        sl = (data[max(start - nsamp, 0):start] if backward
              else data[start:start + nsamp])
        if backward and start - nsamp < 0:
            sl = sl[:0]
        nwin = (nsamp - wlen) // offset + 1
        wins, valid = [], []
        for w in range(nwin):
            seg = sl[w * offset:w * offset + wlen]
            valid.append(seg.shape[-1] == wlen)
            wins.append(seg if valid[-1] else np.zeros(wlen))
        return np.stack(wins), np.asarray(valid)

    d = np.random.default_rng(0).standard_normal(nt)
    for backward in (False, True):
        for start in (0, 100, 350, 450, 499, 501, 700):
            wf, valid, n_eff = _masked_window_specs(
                jnp.asarray(d), jnp.asarray(start), nsamp, wlen, offset,
                backward)
            rw, rv = ref(d, start, backward)
            assert np.array_equal(np.asarray(valid), rv), (backward, start)
            assert int(n_eff) == int(rv.sum())
            got = np.asarray(jnp.fft.irfft(wf, n=wlen, axis=-1))
            for w in np.flatnonzero(rv):
                np.testing.assert_allclose(got[w], rw[w], atol=1e-12)
