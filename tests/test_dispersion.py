import jax.numpy as jnp
import numpy as np

from das_diff_veh_tpu.io.synthetic import dispersive_shot
from das_diff_veh_tpu.ops import dispersion as jd
from das_diff_veh_tpu.oracle import dispersion_ref as od

RNG = np.random.default_rng(11)


def test_fk_matches_reference():
    data = RNG.standard_normal((37, 500))
    ref_mag, ref_f, ref_k = od.ref_fk(data, 8.16, 0.004)
    mag, f, k = jd.fk_transform(jnp.asarray(data), 8.16, 0.004)
    np.testing.assert_allclose(np.asarray(f), ref_f, atol=1e-9)
    np.testing.assert_allclose(np.asarray(k), ref_k, atol=1e-12)
    np.testing.assert_allclose(np.asarray(mag), ref_mag, rtol=1e-9, atol=1e-9)


def test_fv_map_fk_matches_reference():
    data = RNG.standard_normal((19, 400))
    freqs = np.arange(0.8, 25, 0.1)
    vels = np.arange(200.0, 1200.0)
    ref = od.ref_map_fv(data, 8.16, 0.004, freqs, vels)
    ours = np.asarray(jd.fv_map_fk(jnp.asarray(data), 8.16, 0.004,
                                   jnp.asarray(freqs), jnp.asarray(vels)))
    assert ours.shape == (len(vels), len(freqs))
    np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-8 * np.abs(ref).max())


def test_fv_map_fk_norm_matches_reference():
    data = RNG.standard_normal((19, 400)) + 2.0
    freqs = np.arange(1.0, 20, 0.2)
    vels = np.arange(200.0, 900.0, 2.0)
    ref = od.ref_map_fv(data, 8.16, 0.004, freqs, vels, norm=True)
    ours = np.asarray(jd.fv_map_fk(jnp.asarray(data), 8.16, 0.004,
                                   jnp.asarray(freqs), jnp.asarray(vels), norm=True))
    np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-8 * np.abs(ref).max())


def _recovered_curve(fv, freqs, vels):
    return np.asarray(vels)[np.argmax(np.asarray(fv), axis=0)]


def test_phase_shift_recovers_known_dispersion():
    """Slant stack of a synthetic dispersive wavefield recovers c(f)."""
    c_true = lambda f: 300.0 + 500.0 * np.exp(-np.asarray(f, dtype=float) / 8.0)
    nx, nt, dx, dt = 37, 2000, 8.16, 0.004
    data = dispersive_shot(nx, nt, dx, dt, phase_velocity=c_true)
    freqs = np.arange(3.0, 20.0, 0.25)
    vels = np.arange(200.0, 1000.0, 2.0)
    fv = jd.fv_map_phase_shift(jnp.asarray(data), dx, dt,
                               jnp.asarray(freqs), jnp.asarray(vels))
    rec = _recovered_curve(fv, freqs, vels)
    err = np.abs(rec - c_true(freqs)) / c_true(freqs)
    assert np.median(err) < 0.03, np.median(err)
    assert err.max() < 0.12, err.max()


def test_fk_map_recovers_known_dispersion():
    """The reference-parity fk path also recovers c(f) (coarser).

    The (k>0, f>0) quadrant it samples holds waves propagating toward
    *decreasing* x — the reference gathers' orientation (offsets -150..0 m,
    virtual source at 0; apis/imaging_classes.py:37) — so the synthetic
    source sits at the far end of the line here.
    """
    c_true = lambda f: 300.0 + 500.0 * np.exp(-np.asarray(f, dtype=float) / 8.0)
    nx, nt, dx, dt = 37, 2000, 8.16, 0.004
    data = dispersive_shot(nx, nt, dx, dt, phase_velocity=c_true, src_idx=nx - 1)
    freqs = np.arange(4.0, 18.0, 0.25)
    vels = np.arange(200.0, 1000.0, 2.0)
    fv = jd.fv_map_fk(jnp.asarray(data), dx, dt, jnp.asarray(freqs), jnp.asarray(vels))
    rec = _recovered_curve(fv, freqs, vels)
    err = np.abs(rec - c_true(freqs)) / c_true(freqs)
    assert np.median(err) < 0.08, np.median(err)


def test_phase_shift_direction_flag():
    """direction=-1 on a leftward-propagating field == direction=+1 on the
    mirrored field."""
    c_true = lambda f: 300.0 + 500.0 * np.exp(-np.asarray(f, dtype=float) / 8.0)
    nx, nt, dx, dt = 24, 1500, 8.16, 0.004
    data = dispersive_shot(nx, nt, dx, dt, phase_velocity=c_true, src_idx=nx - 1)
    freqs = np.arange(4.0, 16.0, 0.5)
    vels = np.arange(250.0, 900.0, 5.0)
    a = np.asarray(jd.fv_map_phase_shift(jnp.asarray(data), dx, dt,
                                         jnp.asarray(freqs), jnp.asarray(vels),
                                         direction=-1.0))
    b = np.asarray(jd.fv_map_phase_shift(jnp.asarray(data[::-1].copy()), dx, dt,
                                         jnp.asarray(freqs), jnp.asarray(vels),
                                         direction=1.0))
    rec_a = _recovered_curve(a, freqs, vels)
    rec_b = _recovered_curve(b, freqs, vels)
    np.testing.assert_allclose(rec_a, rec_b, atol=10.0)


def test_stacking_is_mean():
    maps = jnp.asarray(RNG.standard_normal((5, 10, 12)))
    np.testing.assert_allclose(np.asarray(jd.stack_fv_maps(maps)),
                               np.asarray(maps).mean(0), atol=1e-12)
