"""Resilience tests: fault injection, health sentinel, degradation ladder,
and the seeded chaos campaign (ISSUE 7).

Everything here is stub-compute cheap — no ``process_chunk`` traces, no new
compile shapes; the one test that touches the real pipeline consumes the
session-scoped ``chunk_result_xcorr`` fixture (conftest.py) read-only to
counter-assert the sentinel's zero-dispatch-when-disabled contract.  The
``chaos``-marked campaign drives the REAL ``run_directory`` workflow (real
npz I/O, real prefetch threads, real manifest/flight artifacts) under a
seeded :class:`FaultPlan` and asserts plan-exact outcomes.
"""

import os
import time

import numpy as np
import pytest

from das_diff_veh_tpu.config import HealthConfig, PipelineConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.io.readers import (DirectoryDataset, read_npz_section,
                                         save_section_npz)
from das_diff_veh_tpu.obs.flight import FlightRecorder, load_flight_dump
from das_diff_veh_tpu.obs.registry import MetricsRegistry, default_registry
from das_diff_veh_tpu.pipeline.workflow import run_directory
from das_diff_veh_tpu.resilience import degrade, faults, health
from das_diff_veh_tpu.resilience.faults import (FaultPlan, FaultSpec,
                                                InjectedFault)
from das_diff_veh_tpu.resilience.health import (PoisonedChunkError,
                                                admission_verdict,
                                                quick_screen, screen_arrays,
                                                screen_section)
from das_diff_veh_tpu.runtime import ChunkTask, RuntimeConfig, run_pipelined

DATE = "20230301"


@pytest.fixture(autouse=True)
def _clean_globals():
    """No injector and no ladder leaks across tests — both are process-wide
    and sticky by design."""
    faults.uninstall()
    degrade.set_ladder(None)
    yield
    faults.uninstall()
    degrade.set_ladder(None)


def _counter_value(reg, name, **labels):
    fam = reg.get(name)
    if fam is None:
        return 0.0
    child = fam.labels(**labels) if labels else fam
    return child.value


# --------------------------------------------------------------------------
# fault injector
# --------------------------------------------------------------------------

def test_fire_and_corrupt_are_noops_when_disabled():
    data = np.ones((4, 8))
    faults.fire("io.read", "a.npz")              # no injector: returns
    assert faults.corrupt("io.corrupt", "a.npz", data) is data  # same object


def test_error_spec_fires_only_on_matching_keys():
    plan = FaultPlan(specs=(FaultSpec("io.read", "error", keys=("b.npz",)),))
    with faults.injected(plan, registry=MetricsRegistry()) as inj:
        faults.fire("io.read", "a.npz")          # wrong key: silent
        faults.fire("runtime.compute", "b.npz")  # wrong site: silent
        with pytest.raises(InjectedFault) as exc:
            faults.fire("io.read", "b.npz")
        assert exc.value.site == "io.read"
        assert inj.n_injected == 1
    # context manager cleaned up
    faults.fire("io.read", "b.npz")


def test_corruption_is_deterministic_per_key_and_counted():
    reg = MetricsRegistry()
    plan = FaultPlan(specs=(FaultSpec("io.corrupt", "nan", keys=("k",),
                                      param=0.25),), seed=11)
    rng = np.random.default_rng(3)
    data = rng.standard_normal((8, 64))
    with faults.injected(plan, registry=reg) as inj:
        out1 = inj.corrupt("io.corrupt", "k", data)
        out2 = inj.corrupt("io.corrupt", "k", data)
    assert out1 is not data and not np.isnan(data).any()   # copy, not mutation
    assert np.isnan(out1).any()
    # a retry of the same chunk refires the identical corruption
    assert np.array_equal(np.isnan(out1), np.isnan(out2))
    assert _counter_value(reg, "das_faults_injected_total",
                          site="io.corrupt", kind="nan") == 2


def test_dead_and_clip_kinds():
    plan = FaultPlan(specs=(
        FaultSpec("io.corrupt", "dead", keys=("k",), channels=(1, 3)),
        FaultSpec("io.corrupt", "clip", keys=("k",), channels=(5,),
                  param=2.0)))
    data = np.random.default_rng(0).standard_normal((8, 32))
    with faults.injected(plan, registry=MetricsRegistry()) as inj:
        out = inj.corrupt("io.corrupt", "k", data)
    assert not out[1].any() and not out[3].any()
    assert np.all(np.abs(out[5]) == 2.0)
    assert np.array_equal(out[0], data[0])       # untargeted rows untouched


def test_slow_spec_sleeps_then_error_spec_raises():
    plan = FaultPlan(specs=(FaultSpec("io.read", "slow", param=0.05),
                            FaultSpec("io.read", "error")))
    with faults.injected(plan, registry=MetricsRegistry()):
        t0 = time.perf_counter()
        with pytest.raises(InjectedFault):
            faults.fire("io.read", "anything")
        assert time.perf_counter() - t0 >= 0.05


def test_plan_sample_is_seeded_and_disjoint():
    keys = [f"{i:02d}.npz" for i in range(10)]
    a = FaultPlan.sample(7, keys, n_loader_faults=3, n_corrupt=2)
    b = FaultPlan.sample(7, keys, n_loader_faults=3, n_corrupt=2)
    assert a == b                                 # deterministic
    read = next(s for s in a.specs if s.site == "io.read")
    corrupt = next(s for s in a.specs if s.site == "io.corrupt")
    assert len(read.keys) == 3 and len(corrupt.keys) == 2
    assert not set(read.keys) & set(corrupt.keys)
    assert a.n_keys("io.read") == 3 and a.n_keys("io.corrupt") == 2
    with pytest.raises(ValueError):
        FaultPlan.sample(0, keys[:3], n_loader_faults=2, n_corrupt=2)


def test_reader_sites_end_to_end(tmp_path):
    sec = DasSection(np.random.default_rng(1).standard_normal((6, 128)),
                     np.arange(6.0), np.arange(128) / 250.0)
    path = str(tmp_path / "chunk.npz")
    save_section_npz(path, sec)
    clean = read_npz_section(path, cut_taper=False)
    plan = FaultPlan(specs=(
        FaultSpec("io.read", "error", keys=("other.npz",)),
        FaultSpec("io.corrupt", "dead", keys=("chunk.npz",),
                  channels=(2,))))
    with faults.injected(plan, registry=MetricsRegistry()):
        got = read_npz_section(path, cut_taper=False)   # io.read key mismatch
        assert not np.asarray(got.data)[2].any()
        assert np.array_equal(np.asarray(got.data)[0],
                              np.asarray(clean.data)[0])
    plan2 = FaultPlan(specs=(FaultSpec("io.read", "error",
                                       keys=("chunk.npz",)),))
    with faults.injected(plan2, registry=MetricsRegistry()):
        with pytest.raises(InjectedFault):
            read_npz_section(path, cut_taper=False)


# --------------------------------------------------------------------------
# health sentinel
# --------------------------------------------------------------------------

def _waterfall(nch=12, nt=200, seed=0):
    return np.random.default_rng(seed).standard_normal((nch, nt))


def test_sentinel_masks_nan_flatline_and_clipped_channels():
    cfg = HealthConfig(enabled=True, clip_limit=5.0, clip_fraction_max=0.1)
    data = _waterfall()
    data[2, 50:80] = np.nan
    data[5, 10] = np.inf
    data[7] = 0.123                               # flatlined
    data[9] = 6.0 * np.sign(data[9] + 0.01)       # saturated rail
    san, h = screen_arrays(data, cfg, tag="unit")
    assert not h.healthy[2] and not h.healthy[5]
    assert not h.healthy[7] and not h.healthy[9]
    assert h.healthy[[0, 1, 3, 4, 6, 8, 10, 11]].all()
    assert h.n_masked == 4 and h.degraded
    assert h.n_nonfinite_channels == 2 and h.n_dead == 1 and h.n_clipped == 1
    assert h.nan_fraction == pytest.approx(31 / data.size)
    san = np.asarray(san)
    assert np.isfinite(san).all()
    # healthy channels pass through bit-identically
    for c in (0, 1, 3, 4, 6, 8, 10, 11):
        assert np.array_equal(san[c], data[c])
    # masked channels are neighbor-imputed (qc.impute_traces rule)
    assert np.array_equal(san[7], san[6] + san[8])


def test_sentinel_clean_data_is_bit_identical_and_not_degraded():
    cfg = HealthConfig(enabled=True)
    data = _waterfall(seed=4)
    san, h = screen_arrays(data, cfg, tag="unit")
    assert h.healthy.all() and not h.degraded and h.ok(cfg)
    assert np.array_equal(np.asarray(san), data)


def test_quick_screen_matches_fused_sentinel():
    cfg = HealthConfig(enabled=True, clip_limit=4.0)
    data = _waterfall(seed=5)
    data[1, :20] = np.nan
    data[3] = 0.0
    _, fused = screen_arrays(data, cfg, tag="unit")
    quick = quick_screen(data, cfg)
    assert np.array_equal(quick.healthy, np.asarray(fused.healthy))
    assert quick.summary() == fused.summary()


def test_poison_verdicts():
    cfg = HealthConfig(enabled=True, max_masked_fraction=0.25)
    data = _waterfall(nch=8)
    data[:4] = np.nan                             # half the fiber gone
    _, h = screen_arrays(data, cfg, tag="unit")
    assert not h.ok(cfg)
    assert admission_verdict(h, cfg) is not None
    with pytest.raises(PoisonedChunkError):
        raise PoisonedChunkError(h)
    ok = quick_screen(_waterfall(seed=6), cfg)
    assert admission_verdict(ok, cfg) is None


def test_screen_section_preserves_axes():
    cfg = HealthConfig(enabled=True)
    sec = DasSection(_waterfall(), np.arange(12.0), np.arange(200) / 250.0)
    out, _ = screen_section(sec, cfg, tag="unit")
    assert out.x is sec.x and out.t is sec.t


def test_sentinel_zero_dispatches_in_default_process_chunk(chunk_result_xcorr):
    """The acceptance bar, counter-asserted: the session's canonical
    ``process_chunk`` run (default config — health disabled) performed ZERO
    health screens, and its result carries no health verdict.  Every screen
    increments ``SCREENS_BY_TAG[tag]``; nothing in tier-1 screens under the
    "process_chunk" tag, so this holds regardless of test order."""
    assert chunk_result_xcorr.health is None
    assert health.n_screens("process_chunk") == 0


# --------------------------------------------------------------------------
# degradation ladder
# --------------------------------------------------------------------------

def test_ladder_thresholds_counters_and_flight():
    reg = MetricsRegistry()
    flight = FlightRecorder(capacity=16)
    lad = degrade.DegradationLadder(registry=reg, flight=flight, threshold=2)
    assert not lad.note_failure("gather.fused", ValueError("once"))
    assert not lad.demoted("gather.fused")
    assert lad.note_failure("gather.fused", ValueError("twice"))
    assert lad.demoted("gather.fused")
    lad.note_failure("gather.fused")              # idempotent past threshold
    assert _counter_value(reg, "das_degrade_transitions_total",
                          component="gather.fused") == 1
    assert _counter_value(reg, "das_degrade_active",
                          component="gather.fused") == 1
    recs = [r for r in flight.records() if r["kind"] == "degrade"]
    assert len(recs) == 1 and recs[0]["component"] == "gather.fused"
    lad.reset("gather.fused")
    assert not lad.demoted("gather.fused")
    assert _counter_value(reg, "das_degrade_active",
                          component="gather.fused") == 0


def test_auto_gather_mode_honors_demotion(monkeypatch):
    """Rung 2: once ``gather.fused`` is demoted, ``traj_gather="auto"`` on a
    TPU backend resolves to the serialized cut; the explicit "fused"
    override still forces the kernel."""
    import jax

    from das_diff_veh_tpu.ops.xcorr import _decide_traj_gather

    degrade.set_ladder(degrade.DegradationLadder(registry=MetricsRegistry()))
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert _decide_traj_gather("auto", 8, 128, "rfft") is True
    degrade.note_failure(degrade.GATHER_FUSED, RuntimeError("kernel died"))
    assert _decide_traj_gather("auto", 8, 128, "rfft") is False
    assert _decide_traj_gather("fused", 8, 128, "rfft") is True
    assert _decide_traj_gather("serialized", 8, 128, "rfft") is False


def test_ring_fault_falls_back_to_replicated_bit_identical():
    """Rung 3: an injected ring failure degrades to the replicated layout
    (same result — it is the same einsum program), demotes the component,
    and the NEXT call skips the ring without re-failing."""
    from das_diff_veh_tpu.config import RingConfig
    from das_diff_veh_tpu.parallel import make_mesh, sharded_all_pairs_peak

    reg = MetricsRegistry()
    degrade.set_ladder(degrade.DegradationLadder(registry=reg))
    mesh = make_mesh(8)
    data = np.random.default_rng(2).standard_normal((16, 512)).astype(
        np.float32)
    ref = sharded_all_pairs_peak(data, 64, mesh, use_pallas=False,
                                 ring=RingConfig(mode="replicated"),
                                 registry=reg)
    plan = FaultPlan(specs=(FaultSpec("parallel.ring", "error"),))
    with faults.injected(plan, registry=reg) as inj:
        out = degrade.resilient_all_pairs_peak(data, 64, mesh,
                                               use_pallas=False, registry=reg)
        assert inj.n_injected == 1
        assert degrade.demoted(degrade.PARALLEL_RING)
        # demoted: goes straight to replicated, the ring site never fires
        out2 = degrade.resilient_all_pairs_peak(data, 64, mesh,
                                                use_pallas=False,
                                                registry=reg)
        assert inj.n_injected == 1
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))
    assert _counter_value(reg, "das_degrade_transitions_total",
                          component="parallel.ring") == 1


def test_validation_error_reraises_without_demotion():
    """A pre-dispatch input-validation error (caller bug) re-raises from
    resilient_all_pairs_peak untouched: every rung would fail identically,
    so it must not burn a demotion or run the fallback ladder."""
    from das_diff_veh_tpu.parallel import make_mesh

    reg = MetricsRegistry()
    degrade.set_ladder(degrade.DegradationLadder(registry=reg))
    mesh = make_mesh(8)
    data = np.random.default_rng(3).standard_normal((16, 512)).astype(
        np.float32)
    with pytest.raises(ValueError):
        degrade.resilient_all_pairs_peak(data, 64, mesh, win_block=-4,
                                         registry=reg)
    assert not degrade.demoted(degrade.PARALLEL_RING)
    assert _counter_value(reg, "das_degrade_transitions_total",
                          component="parallel.ring") == 0


# --------------------------------------------------------------------------
# executor integration: sites + on_stage_failure hook
# --------------------------------------------------------------------------

def test_executor_compute_site_quarantines_and_reports_failures():
    plan = FaultPlan(specs=(FaultSpec("runtime.compute", "error",
                                      keys=("bad",)),))
    seen = []
    acc = []
    tasks = [ChunkTask(i, k, lambda k=k: k) for i, k in
             enumerate(["a", "bad", "c"])]
    with faults.injected(plan, registry=MetricsRegistry()):
        stats = run_pipelined(
            tasks, compute=lambda v: v, accumulate=lambda t, r: acc.append(r),
            cfg=RuntimeConfig(max_retries=1, retry_backoff_s=0.0),
            on_stage_failure=lambda st, k, e, at: seen.append((st, k, at)))
    assert acc == ["a", "c"]
    assert [q.key for q in stats.quarantined] == ["bad"]
    assert "InjectedFault" in stats.quarantined[0].error
    # one initial failure + one failed retry, both reported to the hook
    assert seen == [("compute", "bad", 0), ("compute", "bad", 1)]


def test_executor_slow_site_delays_but_completes():
    plan = FaultPlan(specs=(FaultSpec("runtime.slow", "slow", keys=("a",),
                                      param=0.05),))
    acc = []
    with faults.injected(plan, registry=MetricsRegistry()):
        t0 = time.perf_counter()
        stats = run_pipelined([ChunkTask(0, "a", lambda: "v")],
                              compute=lambda v: v,
                              accumulate=lambda t, r: acc.append(r),
                              cfg=RuntimeConfig(max_retries=0))
        dt = time.perf_counter() - t0
    assert acc == ["v"] and stats.n_done == 1 and not stats.quarantined
    assert dt >= 0.05


# --------------------------------------------------------------------------
# the seeded chaos campaign (the acceptance-criteria test)
# --------------------------------------------------------------------------

N_FILES = 8
N_LOADER = 2
N_CORRUPT = 2


def _write_dir(root):
    day = os.path.join(str(root), DATE)
    os.makedirs(day, exist_ok=True)
    keys = []
    for i in range(N_FILES):
        rng = np.random.default_rng(100 + i)
        sec = DasSection(rng.standard_normal((10, 256)) * (1.0 + 0.1 * i),
                         np.arange(10.0), np.arange(256) / 250.0)
        name = f"{DATE}_{i:02d}0000.npz"
        save_section_npz(os.path.join(day, name), sec)
        keys.append(name)
    return str(root), keys


def _capturing_compute(store):
    """Deterministic stand-in for process_chunk that is sensitive to every
    channel (so masked channels change the image)."""
    def compute(section):
        d = np.asarray(section.data)
        img = np.outer(d.mean(axis=1), d.std(axis=1) + 1.0)
        store.append(img)
        return 1, img
    return compute

def _run(root, store, out=None, runtime=None, health_on=True):
    cfg = PipelineConfig()
    if health_on:
        cfg = cfg.replace(health=HealthConfig(enabled=True))
    ds = DirectoryDataset(DATE, root=root, ch1=None, ch2=None,
                          smoothing=False, rescale_after=None)
    return run_directory(ds, cfg, out_dir=out,
                         compute_fn=_capturing_compute(store),
                         runtime=runtime or RuntimeConfig(
                             max_retries=1, retry_backoff_s=0.0))


@pytest.mark.chaos
def test_chaos_campaign_plan_exact_counts_and_bit_identity(tmp_path):
    """The ISSUE 7 acceptance test: a seeded fault plan injecting
    ``N_LOADER`` loader faults + ``N_CORRUPT`` corrupt-channel chunks; the
    run completes, ``quarantined + degraded`` counts equal the plan, obs
    counters and flight events record every transition, and every
    unaffected chunk's contribution is bit-identical to a fault-free run."""
    root, keys = _write_dir(tmp_path / "data")
    plan = FaultPlan.sample(5, keys, n_loader_faults=N_LOADER,
                            n_corrupt=N_CORRUPT, corrupt_fraction=0.2)
    loader_keys = sorted(next(s.keys for s in plan.specs
                              if s.site == "io.read"))
    corrupt_keys = sorted(next(s.keys for s in plan.specs
                               if s.site == "io.corrupt"))

    # fault-free baseline, health sentinel ON (same config as the campaign)
    base_imgs = []
    base = _run(root, base_imgs)
    assert base.n_chunks == N_FILES and not base.quarantined
    assert base.n_degraded == 0                   # clean data: no masking
    by_key_base = dict(zip(keys, base_imgs))

    # --- the campaign ------------------------------------------------------
    reg = default_registry()
    before = {
        "quar": _counter_value(reg, "das_runtime_chunks_total",
                               status="quarantined"),
        "deg": _counter_value(reg, "das_health_degraded_chunks_total"),
        "f_read": _counter_value(reg, "das_faults_injected_total",
                                 site="io.read", kind="error"),
        "f_nan": _counter_value(reg, "das_faults_injected_total",
                                site="io.corrupt", kind="nan"),
    }
    out = str(tmp_path / "res")
    flight_dir = str(tmp_path / "flight")
    inj_flight = FlightRecorder(capacity=64)
    camp_imgs = []
    from das_diff_veh_tpu.config import ObsConfig
    runtime = RuntimeConfig(max_retries=1, retry_backoff_s=0.0,
                            obs=ObsConfig(flight_dir=flight_dir))
    with faults.injected(plan, flight=inj_flight) as inj:
        res = _run(root, camp_imgs, out=out, runtime=runtime)

    # the run completes; quarantined + degraded == the plan, exactly
    assert res.complete
    assert sorted(q.key for q in res.quarantined) == loader_keys
    assert res.n_degraded == N_CORRUPT
    assert res.n_chunks == N_FILES - N_LOADER

    # obs counters recorded every transition (deltas over the campaign)
    assert _counter_value(reg, "das_runtime_chunks_total",
                          status="quarantined") - before["quar"] == N_LOADER
    assert _counter_value(reg, "das_health_degraded_chunks_total") \
        - before["deg"] == N_CORRUPT
    # io.read refires on the retry (1 + max_retries per key, deterministic)
    assert _counter_value(reg, "das_faults_injected_total", site="io.read",
                          kind="error") - before["f_read"] == 2 * N_LOADER
    assert _counter_value(reg, "das_faults_injected_total", site="io.corrupt",
                          kind="nan") - before["f_nan"] == N_CORRUPT
    assert inj.n_injected == 2 * N_LOADER + N_CORRUPT
    fault_recs = [r for r in inj_flight.records() if r["kind"] == "fault"]
    assert len(fault_recs) == inj.n_injected

    # flight-recorder artifacts: the quarantine dump names the bad chunk and
    # the ring carries the degraded-chunk health events
    dumps = [os.path.join(flight_dir, f) for f in os.listdir(flight_dir)
             if "quarantine" in f]
    assert dumps
    payload = load_flight_dump(dumps[0])
    kinds = {r["kind"] for r in payload["records"]}
    assert "chunk" in kinds and "run" in kinds
    health_recs = [r for r in payload["records"] if r["kind"] == "health"]
    assert {r["key"] for r in health_recs} <= set(corrupt_keys)

    # bit-identity: every unaffected chunk's image equals the baseline's;
    # every corrupt chunk's image differs (its channels were masked)
    computed_keys = [k for k in keys if k not in loader_keys]
    assert len(camp_imgs) == len(computed_keys)
    by_key_camp = dict(zip(computed_keys, camp_imgs))
    for k in computed_keys:
        if k in corrupt_keys:
            assert not np.array_equal(by_key_camp[k], by_key_base[k])
        else:
            np.testing.assert_array_equal(by_key_camp[k], by_key_base[k])

    # manifest persisted both kinds of badness
    from das_diff_veh_tpu.runtime import RunManifest
    man = RunManifest.load(os.path.join(out, f"{DATE}_manifest.json"))
    assert sorted(man.quarantined) == loader_keys
    assert sorted(man.degraded) == corrupt_keys
    assert man.degraded[corrupt_keys[0]]["health"]["n_masked"] >= 1


@pytest.mark.chaos
def test_chaos_restart_skips_known_bad_then_requeues_on_demand(tmp_path):
    """Satellite: a restart skips manifest-quarantined chunks without
    re-failing them through the retry ladder; ``retry_quarantined=True``
    requeues them, and once the fault is gone they complete and fold into
    the accumulator in deterministic order."""
    root, keys = _write_dir(tmp_path / "data")
    plan = FaultPlan.sample(5, keys, n_loader_faults=N_LOADER,
                            n_corrupt=N_CORRUPT, corrupt_fraction=0.2)
    loader_keys = sorted(next(s.keys for s in plan.specs
                              if s.site == "io.read"))
    out = str(tmp_path / "res")
    camp_imgs = []
    runtime = RuntimeConfig(max_retries=1, retry_backoff_s=0.0)
    with faults.injected(plan, registry=MetricsRegistry()):
        res1 = _run(root, camp_imgs, out=out, runtime=runtime)
        assert sorted(q.key for q in res1.quarantined) == loader_keys

        # restart with the fault STILL present: nothing is re-attempted —
        # known-bad chunks are settled, the retry ladder never runs
        imgs2 = []
        res2 = _run(root, imgs2, out=out, runtime=runtime)
        assert imgs2 == [] and res2.n_resumed == N_FILES
        assert res2.resumed_quarantined == loader_keys
        assert not res2.quarantined and res2.complete

    # fault fixed + retry_quarantined: ONLY the known-bad chunks rerun
    imgs3 = []
    res3 = _run(root, imgs3, out=out,
                runtime=RuntimeConfig(max_retries=1, retry_backoff_s=0.0,
                                      retry_quarantined=True))
    assert res3.n_requeued == N_LOADER and len(imgs3) == N_LOADER
    assert not res3.quarantined and res3.complete
    assert res3.n_chunks == N_FILES
    # accumulator extends the interrupted sum in sorted-key order
    expected = res1.avg_image.copy()
    fresh = dict(zip(loader_keys, imgs3))
    for k in loader_keys:
        expected = expected + fresh[k]
    np.testing.assert_array_equal(res3.avg_image, expected)


@pytest.mark.chaos
def test_chaos_poisoned_chunk_quarantined_not_averaged(tmp_path):
    """A chunk corrupted beyond max_masked_fraction is quarantined by the
    poison verdict (stage 'compute'), not silently averaged."""
    root, keys = _write_dir(tmp_path / "data")
    plan = FaultPlan(specs=(FaultSpec("io.corrupt", "nan", keys=(keys[2],),
                                      param=0.9),), seed=1)
    imgs = []
    with faults.injected(plan, registry=MetricsRegistry()):
        res = _run(root, imgs, runtime=RuntimeConfig(max_retries=0))
    assert [q.key for q in res.quarantined] == [keys[2]]
    assert "Poisoned" in res.quarantined[0].error
    assert res.n_degraded == 0 and res.n_chunks == N_FILES - 1
    assert len(imgs) == N_FILES - 1


def test_serve_dispatch_fault_fails_one_request_not_the_cohort():
    """An injected dispatch failure on the serve dispatcher thread fails
    exactly the targeted request; the rest of the microbatch completes."""
    from das_diff_veh_tpu.config import ServeConfig
    from das_diff_veh_tpu.serve import FnComputeFactory, ServingEngine

    def build(bucket):
        def fn(section, valid, state):
            return float(np.asarray(section.data).sum()), state
        return fn

    plan = FaultPlan(specs=(FaultSpec("serve.dispatch", "error",
                                      keys=("1",)),))   # second dispatch
    eng = ServingEngine(FnComputeFactory(build, "t"),
                        ServeConfig(buckets=((4, 16),), warmup=False,
                                    default_deadline_ms=600000.0)).start()
    sec = DasSection(np.ones((4, 16), np.float32), np.arange(4.0),
                     np.arange(16.0) / 250.0)
    try:
        with faults.injected(plan, registry=MetricsRegistry()):
            futures = [eng.submit(sec) for _ in range(3)]
            results = []
            for f in futures:
                try:
                    results.append(f.result(timeout=30))
                except InjectedFault:
                    results.append("failed")
        assert results.count("failed") == 1
        assert results.count(64.0) == 2
        assert eng.metrics()["errors"] == 1
    finally:
        eng.close()
