"""Serving-engine tests: bucketing, shedding, metrics, sessions, HTTP, CLI.

Dispatcher behavior is driven with injected stub compute factories
(``FnComputeFactory``) so tier-1 never traces ``process_chunk`` on a new
shape; the one real-compute case pins bit-exactness of the pad -> compute
-> unpad round trip on the production path against the session-scoped
``chunk_result_xcorr`` fixture (conftest.py), adding ONE jit-cache-hit
execution and zero compiles of its own.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from das_diff_veh_tpu.config import HealthConfig, PipelineConfig, ServeConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.runtime import load_trace, make_tracer
from das_diff_veh_tpu.serve import (DeadlineExceededError, EngineClosedError,
                                    FnComputeFactory, ImagingComputeFactory,
                                    InvalidRequestError, NoBucketError,
                                    PoisonInputError, QueueFullError,
                                    ServingEngine, ShutdownError,
                                    normalize_buckets, pad_section,
                                    pick_bucket, serve_in_thread)


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

def _section(nch, nt, value=1.0, dtype=np.float32):
    return DasSection(np.full((nch, nt), value, dtype),
                      np.arange(nch, dtype=np.float64) * 8.16,
                      np.arange(nt, dtype=np.float64) / 250.0)


def _sum_build(bucket):
    """Stub compute: padding-invariant sum over the valid region, with the
    running session total as state."""
    def fn(section, valid, state):
        assert tuple(section.data.shape) == tuple(bucket)  # engine padded
        d = np.asarray(section.data)[:valid[0], :valid[1]]
        total = float(d.sum())
        return {"sum": total, "valid": tuple(valid)}, (state or 0.0) + total
    return fn


def _engine(buckets=((8, 32), (16, 64)), compute=_sum_build, **kw):
    cfg = ServeConfig(buckets=buckets, **kw)
    return ServingEngine(FnComputeFactory(compute, "test"), cfg).start()


class _Gate:
    """Blocks the dispatcher inside compute until released."""

    def __init__(self):
        self.started = threading.Event()
        self.release = threading.Event()

    def build(self, bucket):
        def fn(section, valid, state):
            self.started.set()
            assert self.release.wait(timeout=30.0)
            return float(np.asarray(section.data)[:valid[0], :valid[1]].sum()), state
        return fn


# --------------------------------------------------------------------------
# buckets
# --------------------------------------------------------------------------

def test_pick_bucket_smallest_fit():
    buckets = ((16, 64), (8, 32), (8, 128))
    assert normalize_buckets(buckets)[0] == (8, 32)
    assert pick_bucket((8, 32), buckets) == (8, 32)
    assert pick_bucket((5, 20), buckets) == (8, 32)
    assert pick_bucket((8, 40), buckets) == (8, 128)   # area-smallest fit
    assert pick_bucket((9, 32), buckets) == (16, 64)
    assert pick_bucket((17, 10), buckets) is None
    assert pick_bucket((8, 200), buckets) is None


def test_pad_section_round_trip():
    sec = _section(5, 20, 3.0)
    padded = pad_section(sec, (8, 32))
    assert padded.data.shape == (8, 32)
    d = np.asarray(padded.data)
    assert np.array_equal(d[:5, :20], np.asarray(sec.data))
    assert not d[5:].any() and not d[:, 20:].any()
    # axes continue their own spacing (dx/dt derived downstream unchanged)
    assert np.allclose(np.diff(np.asarray(padded.x)), 8.16)
    assert np.allclose(np.diff(np.asarray(padded.t)), 1.0 / 250.0)
    # exact-shape fast path: nothing copied
    same = pad_section(sec, (5, 20))
    assert same.data is sec.data


def test_pad_section_too_big_raises():
    with pytest.raises(ValueError, match="does not fit"):
        pad_section(_section(9, 10), (8, 32))


# --------------------------------------------------------------------------
# engine: pad -> compute -> unpad round trip + compile-cache counters
# --------------------------------------------------------------------------

def test_engine_round_trip_equals_direct_and_zero_misses():
    """Engine output over assorted in-bucket shapes equals the stub applied
    directly to each unpadded section, and after AOT warmup the request
    stream performs zero new compilations."""
    eng = _engine()
    try:
        shapes = [(8, 32), (5, 20), (3, 32), (8, 1), (16, 64), (9, 33)]
        for nch, nt in shapes:
            sec = _section(nch, nt, value=0.5 + nch)
            got = eng.process(sec, timeout=30)
            direct, _ = _sum_build((nch, nt))(sec, (nch, nt), None)
            assert got["sum"] == direct["sum"]
            assert got["valid"] == (nch, nt)
        m = eng.metrics()
        assert m["warmup_builds"] == 2          # one AOT build per bucket
        assert m["cache_misses"] == 0           # steady state never compiles
        assert m["cache_hits"] == len(shapes)
        assert m["completed"] == len(shapes)
    finally:
        eng.close()


def test_no_warmup_first_request_is_a_counted_miss():
    eng = _engine(warmup=False)
    try:
        eng.process(_section(4, 16), timeout=30)
        m = eng.metrics()
        assert m["warmup_builds"] == 0 and m["cache_misses"] == 1
    finally:
        eng.close()


def test_no_bucket_rejection():
    eng = _engine()
    try:
        with pytest.raises(NoBucketError):
            eng.submit(_section(17, 10))
        assert eng.metrics()["shed_no_bucket"] == 1
    finally:
        eng.close()


# --------------------------------------------------------------------------
# engine: backpressure + deadline shedding
# --------------------------------------------------------------------------

def test_backpressure_rejects_on_full():
    gate = _Gate()
    eng = ServingEngine(FnComputeFactory(gate.build, "gated"),
                        ServeConfig(buckets=((8, 32),), max_batch=1,
                                    max_queue=2, warmup=False)).start()
    try:
        blocked = eng.submit(_section(8, 32))
        assert gate.started.wait(timeout=10)    # dispatcher is inside compute
        ok = [eng.submit(_section(8, 32)) for _ in range(2)]  # fills queue
        with pytest.raises(QueueFullError):
            eng.submit(_section(8, 32))
        assert eng.metrics()["shed_rejected"] == 1
        gate.release.set()
        for f in [blocked, *ok]:
            assert isinstance(f.result(timeout=30), float)
        m = eng.metrics()
        assert m["completed"] == 3 and m["queue_depth"] == 0
    finally:
        gate.release.set()
        eng.close()


def test_deadline_expires_in_queue():
    gate = _Gate()
    eng = ServingEngine(FnComputeFactory(gate.build, "gated"),
                        ServeConfig(buckets=((8, 32),), max_batch=4,
                                    max_queue=8, warmup=False)).start()
    try:
        blocked = eng.submit(_section(8, 32), deadline_ms=60000.0)
        assert gate.started.wait(timeout=10)
        doomed = eng.submit(_section(8, 32), deadline_ms=1.0)
        time.sleep(0.05)                        # let the 1 ms deadline pass
        gate.release.set()
        assert isinstance(blocked.result(timeout=30), float)
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=30)
        m = eng.metrics()
        assert m["shed_expired"] == 1 and m["completed"] == 1
    finally:
        gate.release.set()
        eng.close()


def test_compute_error_fails_one_request_not_the_engine():
    def build(bucket):
        def fn(section, valid, state):
            if float(np.asarray(section.data).flat[0]) < 0:
                raise ValueError("poisoned request")
            return "ok", state
        return fn

    eng = _engine(compute=build)
    try:
        bad = eng.submit(_section(4, 16, value=-1.0))
        assert isinstance(bad.exception(timeout=30), ValueError)
        assert eng.process(_section(4, 16, value=1.0), timeout=30) == "ok"
        m = eng.metrics()
        assert m["errors"] == 1 and m["completed"] == 1
    finally:
        eng.close()


def test_closed_engine_rejects_submits_and_restarts():
    eng = _engine()
    eng.close()
    with pytest.raises(EngineClosedError):
        eng.submit(_section(4, 16))
    # a closed engine cannot be resurrected into a dispatcherless zombie
    with pytest.raises(EngineClosedError):
        eng.start()


def test_imaging_factory_rejects_mismatched_geometry():
    """The zero-compile guard: channel padding, a foreign x axis, or a
    wrong sample rate are shed AT SUBMIT (never queued, never traced —
    this test pays no compile), while an absolute-time axis at the right
    rate is admitted (compute rebases the origin)."""
    x_axis = np.arange(16, dtype=np.float64) * 8.16
    factory = ImagingComputeFactory(PipelineConfig(), x_is_channels=False,
                                    x_axis=x_axis, fs=250.0)
    eng = ServingEngine(factory, ServeConfig(buckets=((16, 64),),
                                             warmup=False)).start()
    try:
        def sec(nch, x=None, dt=1.0 / 250.0, t0=0.0):
            xs = x_axis[:nch] if x is None else x
            return DasSection(np.zeros((nch, 64), np.float32), xs,
                              t0 + np.arange(64, dtype=np.float64) * dt)

        with pytest.raises(InvalidRequestError, match="channel-axis padding"):
            eng.submit(sec(12))
        with pytest.raises(InvalidRequestError, match="x axis does not match"):
            eng.submit(sec(16, x=np.arange(16.0)))
        with pytest.raises(InvalidRequestError, match="sample interval"):
            eng.submit(sec(16, dt=1.0 / 500.0))
        m = eng.metrics()
        assert m["shed_invalid"] == 3 and m["errors"] == 0
        # streaming sessions carry absolute time: admitted, not rejected
        assert factory.validate(sec(16, t0=7200.0), (16, 64)) is None
    finally:
        eng.close()


# --------------------------------------------------------------------------
# engine: microbatching + sessions + metrics + traces
# --------------------------------------------------------------------------

def test_microbatch_groups_same_bucket_requests():
    gate = _Gate()
    eng = ServingEngine(FnComputeFactory(gate.build, "gated"),
                        ServeConfig(buckets=((8, 32),), max_batch=8,
                                    max_queue=16, warmup=False)).start()
    try:
        first = eng.submit(_section(8, 32))
        assert gate.started.wait(timeout=10)
        rest = [eng.submit(_section(6, 20)) for _ in range(3)]
        gate.release.set()
        for f in [first, *rest]:
            f.result(timeout=30)
        b = eng.metrics()["batch"]
        assert b["max_occupancy"] >= 3          # the 3 queued ones grouped
        assert b["count"] < 4
    finally:
        gate.release.set()
        eng.close()


def test_session_state_carries_across_requests():
    eng = _engine()
    try:
        for i in range(3):
            eng.process(_section(8, 32, value=1.0), session="fiber-a",
                        timeout=30)
        eng.process(_section(8, 32, value=2.0), session="fiber-b", timeout=30)
        eng.process(_section(8, 32, value=1.0), timeout=30)  # sessionless
        assert eng.session_state("fiber-a") == 3 * 8 * 32
        assert eng.session_state("fiber-b") == 2 * 8 * 32
        assert eng.session_state("missing") is None
        assert eng.metrics()["sessions"] == 2
        eng.sessions.drop("fiber-a")
        assert eng.session_state("fiber-a") is None
    finally:
        eng.close()


def test_metrics_snapshot_counters_and_percentiles():
    eng = _engine()
    try:
        for _ in range(5):
            eng.process(_section(4, 16), timeout=30)
        m = eng.metrics()
        assert m["submitted"] == m["completed"] == 5
        assert m["queue_depth"] == 0
        lat = m["latency_ms"]
        assert lat["n"] == 5
        assert 0 <= lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
        assert set(m["stages_ms"]) >= {"queue", "pad", "compute", "unpad"}
        # stages report the same percentile set as totals (they used to
        # report only means), from the same shared-histogram rings
        for stage in ("queue", "pad", "compute", "unpad"):
            s = m["stages_ms"][stage]
            assert s["n"] == 5
            assert 0 <= s["p50"] <= s["p95"] <= s["p99"]
            assert s["mean"] >= 0
        assert m["buckets"] == [[8, 32], [16, 64]]
    finally:
        eng.close()


def test_request_spans_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "serve_trace.jsonl")
    tracer = make_tracer(path)
    cfg = ServeConfig(buckets=((8, 32),))
    eng = ServingEngine(FnComputeFactory(_sum_build, "t"), cfg,
                        tracer=tracer).start()
    try:
        for _ in range(2):
            eng.process(_section(5, 20), timeout=30)
    finally:
        eng.close()
        tracer.close()
    events = load_trace(path)                   # validates every line
    spans = [e for e in events if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert {"warmup", "queue", "pad", "compute", "unpad"} <= names
    # the cross-thread queue span (submit -> dispatcher) has a sane duration
    queue_spans = [e for e in spans if e["name"] == "queue"]
    assert len(queue_spans) == 2
    assert all(e["dur"] >= 0 for e in queue_spans)
    assert {"serve_batch"} <= {e["name"] for e in events if e["ph"] == "C"}


def test_compilation_cache_dir_knob(tmp_path):
    import jax
    before = jax.config.jax_compilation_cache_dir
    try:
        eng = _engine(buckets=((4, 8),),
                      compilation_cache_dir=str(tmp_path / "xla"))
        eng.close()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "xla")
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


# --------------------------------------------------------------------------
# HTTP front
# --------------------------------------------------------------------------

def _post(base, path, payload):
    req = urllib.request.Request(base + path, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_smoke():
    eng = _engine(buckets=((8, 32),))
    server, _ = serve_in_thread(eng)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=15) as r:
            health = json.loads(r.read())
        assert health == {"ok": True, "buckets": [[8, 32]]}

        code, body = _post(base, "/v1/process",
                           {"data": np.ones((4, 16)).tolist(),
                            "session": "s"})
        assert code == 200 and body["result"]["sum"] == 64.0

        code, _ = _post(base, "/v1/process", {"data": np.ones((9, 40)).tolist()})
        assert code == 413                      # no bucket fits
        code, _ = _post(base, "/v1/process", {"wrong": "keys"})
        assert code == 400
        code, _ = _post(base, "/v1/nope", {})
        assert code == 404

        with urllib.request.urlopen(base + "/v1/metrics", timeout=15) as r:
            m = json.loads(r.read())
        assert m["completed"] == 1 and m["shed_no_bucket"] == 1
    finally:
        server.shutdown()
        server.server_close()
        eng.close()


def test_http_prometheus_scrape_one_registry_zero_compiles():
    """The serve HTTP metrics surface (ISSUE 6 satellite): ``GET /metrics``
    is well-formed Prometheus text exposition, ``/v1/metrics`` keeps its
    legacy JSON shape, the ``jax.monitoring`` compile counter stays 0
    across a warmed steady-state stub run, and — the one-registry
    contract — serve, runtime, and parallel families all land in one
    scrape when the subsystems share a registry (as the serve CLI wires
    via ``obs.default_registry()``).  All stub-driven: zero fresh
    ``process_chunk`` compiles."""
    import jax
    from jax.sharding import Mesh

    from das_diff_veh_tpu.config import RingConfig
    from das_diff_veh_tpu.obs import MetricsRegistry
    from das_diff_veh_tpu.parallel.allpairs import _observe_ring_build
    from das_diff_veh_tpu.runtime import ChunkTask, RuntimeConfig, run_pipelined
    from test_obs import assert_prometheus_wellformed

    reg = MetricsRegistry()
    eng = ServingEngine(FnComputeFactory(_sum_build, "test"),
                        ServeConfig(buckets=((8, 32),)), registry=reg).start()
    server, _ = serve_in_thread(eng)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        for _ in range(3):                       # warmed steady-state traffic
            eng.process(_section(5, 20), timeout=30)
        deadline = time.perf_counter() + 10.0
        while eng.metrics()["completed"] < 3:    # set_result precedes the
            assert time.perf_counter() < deadline
            time.sleep(0.005)                    # counter increment
        # runtime + parallel register into the SAME registry
        run_pipelined([ChunkTask(0, "k0", lambda: 1.0)], lambda v: v,
                      lambda t, r: None, cfg=RuntimeConfig(max_retries=0),
                      registry=reg)
        _observe_ring_build(Mesh(np.array(jax.devices()[:1]), ("ch",)),
                            RingConfig(), reg)

        with urllib.request.urlopen(base + "/metrics", timeout=15) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        types = assert_prometheus_wellformed(text)
        assert types["das_serve_events_total"] == "counter"
        assert types["das_serve_latency_ms"] == "summary"
        assert types["das_serve_stage_ms"] == "summary"
        assert types["das_serve_queue_depth"] == "gauge"
        assert types["das_runtime_chunks_total"] == "counter"  # one scrape
        assert types["das_ring_builds_total"] == "counter"     # carries all
        assert types["das_device_bytes_in_use"] == "gauge"     # three layers
        assert 'das_serve_events_total{event="completed"} 3' in text
        assert 'das_runtime_chunks_total{status="done"} 1' in text
        assert 'das_ring_builds_total{mode="ring"} 1' in text
        # device-truth SLO: zero fresh jit traces since warmup, measured by
        # the jax.monitoring listener, not the cache's own counters
        assert "das_jax_traces_total" in types
        assert "das_serve_steady_state_compiles 0" in text

        # legacy JSON surface unchanged: same keys, same counter values
        with urllib.request.urlopen(base + "/v1/metrics", timeout=15) as r:
            m = json.loads(r.read())
        assert m["completed"] == 3 and m["cache_misses"] == 0
        assert set(m) >= {"submitted", "completed", "errors", "shed_rejected",
                          "shed_expired", "shed_no_bucket", "shed_invalid",
                          "cache_hits", "cache_misses", "warmup_builds",
                          "queue_depth", "latency_ms", "stages_ms", "batch",
                          "buckets"}
        assert set(m["latency_ms"]) == {"n", "p50", "p95", "p99", "max"}
    finally:
        server.shutdown()
        server.server_close()
        eng.close()


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def test_serve_cli_parser():
    from das_diff_veh_tpu.serve.cli import build_serve_parser, parse_buckets
    assert parse_buckets("140x30000,100x15000") == ((140, 30000), (100, 15000))
    args = build_serve_parser().parse_args(
        ["--buckets", "100x15000", "--port", "0", "--x0", "400",
         "--max_batch", "2", "--deadline_ms", "5000",
         "--compilation_cache_dir", "/tmp/xla"])
    assert args.buckets == ((100, 15000),)
    assert args.max_batch == 2 and args.deadline_ms == 5000.0
    assert args.compilation_cache_dir == "/tmp/xla"


def test_cli_serve_subcommand_dispatch(monkeypatch):
    import das_diff_veh_tpu.serve.cli as serve_cli
    from das_diff_veh_tpu.pipeline.cli import main
    seen = {}
    monkeypatch.setattr(serve_cli, "serve_main",
                        lambda argv: seen.setdefault("argv", argv) and 0 or 0)
    assert main(["serve", "--buckets", "8x32"]) == 0
    assert seen["argv"] == ["--buckets", "8x32"]


def test_cli_batch_compilation_cache_flag():
    from das_diff_veh_tpu.pipeline.cli import build_parser
    args = build_parser().parse_args(
        ["--data_root", "/d", "--start_date", "20230301",
         "--end_date", "20230301", "--compilation_cache_dir", "/tmp/xla"])
    assert args.compilation_cache_dir == "/tmp/xla"


# --------------------------------------------------------------------------
# the one real-compute case: production path bit-exactness
# --------------------------------------------------------------------------

def test_real_imaging_engine_bit_exact(pipeline_scene, pipeline_cfg,
                                       chunk_result_xcorr):
    """Engine round trip on the production ``process_chunk`` path equals the
    direct call bit-for-bit, and the session accumulator matches the batch
    workflow's semantics.

    Every piece is the session-scoped canonical fixture set (conftest.py):
    the direct reference is ``chunk_result_xcorr`` — already compiled and
    executed for the pipeline tests — and the engine runs the SAME config
    and bucket shape, so its one execution is a jit-cache hit.  This test
    traces nothing of its own (a private scene/config here used to pay its
    own ~40 s process_chunk compile on top of the shared one)."""
    section, _ = pipeline_scene
    shape = tuple(int(s) for s in section.data.shape)
    factory = ImagingComputeFactory(pipeline_cfg, method="xcorr",
                                    x_is_channels=False,
                                    x_axis=np.asarray(section.x), fs=250.0)
    eng = ServingEngine(factory, ServeConfig(
        buckets=(shape,), warmup=False, default_deadline_ms=600000.0)).start()
    try:
        res = eng.process(DasSection(np.asarray(section.data),
                                     np.asarray(section.x),
                                     np.asarray(section.t)),
                          session="fiber", timeout=600)
    finally:
        eng.close()
    direct = chunk_result_xcorr
    assert res.n_windows == int(direct.n_windows) >= 1
    assert np.array_equal(res.image, np.asarray(direct.disp_image))
    assert res.valid == res.bucket == shape and not res.padded
    state = eng.session_state("fiber")
    assert state["n_segments"] == 1
    assert state["n_windows"] == res.n_windows
    assert np.array_equal(state["avg_image"], res.image)


# --------------------------------------------------------------------------
# robustness (ISSUE 7): wedged-close ShutdownError, poison admission, 422
# --------------------------------------------------------------------------

def test_close_with_wedged_dispatcher_fails_pending_futures():
    """close() on an engine whose dispatcher is stuck in a long compute must
    not leave queued requests hanging forever on .result(): they fail with
    ShutdownError; the in-flight request stays with the dispatcher."""
    gate = _Gate()
    eng = ServingEngine(FnComputeFactory(gate.build, "gated"),
                        ServeConfig(buckets=((8, 32),), max_batch=1,
                                    warmup=False,
                                    default_deadline_ms=600000.0)).start()
    f_wedged = eng.submit(_section(8, 32))
    assert gate.started.wait(timeout=10.0)     # dispatcher is now inside compute
    f_queued = eng.submit(_section(8, 32, value=2.0))
    eng.close(timeout=0.2)                     # dispatcher cannot exit in time
    with pytest.raises(ShutdownError):
        f_queued.result(timeout=5.0)
    assert isinstance(ShutdownError("x"), EngineClosedError)  # catchable as before
    gate.release.set()                         # unwedge: in-flight one completes
    assert f_wedged.result(timeout=10.0) == float(
        np.asarray(_section(8, 32).data).sum())


def test_close_with_wedged_dispatcher_fails_batch_tail():
    """max_batch > 1: with continuous batching the batch slot is still open
    while the head wedges in compute, so a same-bucket tail submitted
    meanwhile sits in the admission queue as a WOULD-BE continuous
    admission.  A wedged close must fail it with ShutdownError — and the
    unwedged dispatcher must not admit its dead future into the batch."""
    gate = _Gate()
    eng = ServingEngine(FnComputeFactory(gate.build, "gated"),
                        ServeConfig(buckets=((8, 32),), max_batch=4,
                                    warmup=False,
                                    default_deadline_ms=600000.0)).start()
    f_wedged = eng.submit(_section(8, 32))
    assert gate.started.wait(timeout=10.0)     # head is inside compute
    f_tail = eng.submit(_section(8, 32, value=3.0))
    eng.close(timeout=0.2)
    with pytest.raises(ShutdownError):
        f_tail.result(timeout=5.0)
    gate.release.set()
    assert f_wedged.result(timeout=10.0) == float(
        np.asarray(_section(8, 32).data).sum())
    # the tail request was failed before the member boundary, not computed:
    # exactly one compute ran and nothing was continuously admitted
    snap = eng.metrics()
    assert snap["completed"] == 1
    assert snap["continuous_admitted"] == 0


def test_continuous_admission_into_inflight_batch():
    """The tentpole semantics change (ISSUE 18): requests arriving while a
    same-bucket batch is EXECUTING join its open slot at the next member
    boundary — one batch, late members counted as ``continuous_admitted`` —
    instead of waiting out a linger window or heading a second batch."""
    gate = _Gate()
    eng = ServingEngine(FnComputeFactory(gate.build, "gated"),
                        ServeConfig(buckets=((8, 32),), max_batch=4,
                                    warmup=False,
                                    default_deadline_ms=600000.0)).start()
    try:
        f_head = eng.submit(_section(8, 32))
        assert gate.started.wait(timeout=10.0)   # head is mid-compute: the
        gate.started.clear()                     # batch slot is open
        f_late1 = eng.submit(_section(8, 32, value=2.0))
        f_late2 = eng.submit(_section(8, 32, value=3.0))
        gate.release.set()                       # member boundary reached
        assert f_head.result(timeout=10.0) == float(
            np.asarray(_section(8, 32).data).sum())
        assert f_late1.result(timeout=10.0) == float(
            np.asarray(_section(8, 32, value=2.0).data).sum())
        assert f_late2.result(timeout=10.0) == float(
            np.asarray(_section(8, 32, value=3.0).data).sum())
        snap = eng.metrics()
        # all three rode ONE batch: the two late arrivals were admitted into
        # the in-flight slot, no second batch was formed
        assert snap["batch"]["count"] == 1
        assert snap["batch"]["max_occupancy"] == 3
        assert snap["continuous_admitted"] == 2
    finally:
        gate.release.set()
        eng.close()


def _poison_engine(**hkw):
    cfg = ServeConfig(buckets=((8, 32),),
                      health=HealthConfig(enabled=True, **hkw))
    return ServingEngine(FnComputeFactory(_sum_build, "test"), cfg).start()


def _noisy_section(nch=8, nt=32, seed=0):
    """Non-constant data: the flatline rule (rightly) flags a constant
    channel as dead, so health tests need live-looking traces."""
    sec = _section(nch, nt)
    sec.data[:] = np.random.default_rng(seed).standard_normal(
        (nch, nt)).astype(np.float32)
    return sec


def test_poison_request_shed_at_admission_protects_cohort():
    """A NaN-laden request is shed pre-queue (PoisonInputError with the
    structured report) and never reaches the dispatcher; healthy requests
    around it complete normally — the microbatch cohort is protected."""
    eng = _poison_engine()
    try:
        good1 = _noisy_section(seed=1)
        ok1 = eng.submit(good1)
        bad = _noisy_section(seed=2)
        bad.data[3, 5:20] = np.nan
        with pytest.raises(PoisonInputError) as exc:
            eng.submit(bad)
        assert exc.value.health.nan_fraction > 0
        assert exc.value.health.n_masked >= 1
        good2 = _noisy_section(seed=3)
        ok2 = eng.submit(good2)
        assert ok1.result(timeout=10)["sum"] == float(good1.data.sum())
        assert ok2.result(timeout=10)["sum"] == float(good2.data.sum())
        snap = eng.metrics()
        assert snap["shed_poison"] == 1 and snap["completed"] == 2
        assert snap["errors"] == 0
    finally:
        eng.close()


def test_poison_screen_disabled_by_default_admits_nan():
    """Without ServeConfig.health the engine behaves exactly as before:
    admission does not inspect sample values (zero-overhead default)."""
    eng = _engine(buckets=((8, 32),))
    try:
        bad = _section(8, 32)
        bad.data[0, 0] = np.nan
        res = eng.submit(bad).result(timeout=10)   # stub compute tolerates it
        assert np.isnan(res["sum"])
    finally:
        eng.close()


def test_http_poison_maps_to_422_with_structured_body():
    eng = _poison_engine()
    server, _ = serve_in_thread(eng)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        data = np.asarray(_noisy_section(seed=4).data, dtype=np.float64)
        data[2, :8] = np.nan                       # JSON null -> NaN
        code, body = _post(base, "/v1/process",
                           {"data": [[None if not np.isfinite(v) else v
                                      for v in row] for row in data.tolist()]})
        assert code == 422
        assert set(body) == {"error", "nan_fraction", "dead_channels"}
        assert body["nan_fraction"] > 0 and body["dead_channels"] >= 1
        # healthy request on the same engine still serves
        good = _noisy_section(seed=5)
        code, body = _post(base, "/v1/process", {"data": good.data.tolist()})
        assert code == 200
        assert body["result"]["sum"] == pytest.approx(float(good.data.sum()))
    finally:
        server.shutdown()
        server.server_close()
        eng.close()
