"""bf16 precision-tier error budgets + f32 default-tier bit-identity.

Each MXU-bound stage ships a *committed* bf16-vs-f32 relative-error bound
(ISSUE 19), the same disclosure pattern as the einsum-fallback 2e-5
concession in tests/test_parallel.py: the bound is measured on the CPU
smoke rig (~5-10x headroom over the observed error so hardware-accumulator
differences on a real MXU stay inside it) and documented in docs/TUNING.md.
The f32 tier must remain the untouched default: same bits as a call that
never mentions precision.

Measured on this rig (2026-08): white noise (the fixture below) — ring
all-pairs ~2.4e-4, gather dot ~2.3e-3, fv_map_fk ~3.5e-3, phase-shift
slant stack ~1.9e-3; realistic synthetic scenes (synthesize_section,
3 seeds, the verify drive) run hotter on the coherent-signal stages —
ring up to ~2.6e-3, fv_map_fk ~3.8e-3, phase shift ~1.3e-3, gather dot
~2.6e-4 — which is what sizes the ring budget at 1e-2 rather than the
white-noise-only 2e-3.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from das_diff_veh_tpu.ops import xcorr as xc
from das_diff_veh_tpu.ops.dispersion import fv_map_fk, fv_map_phase_shift
from das_diff_veh_tpu.ops.pallas_xcorr import xcorr_all_pairs_peak

# committed per-stage bf16 error budgets (max |f32 - bf16| / max |f32|)
RING_BF16_BUDGET = 1e-2
GATHER_DOT_BF16_BUDGET = 2e-2
DISP_FK_BF16_BUDGET = 3e-2
DISP_PS_BF16_BUDGET = 2e-2


def _rel(a, b):
    return float(jnp.max(jnp.abs(a - b)) / jnp.max(jnp.abs(a)))


@pytest.fixture(scope="module")
def record():
    rng = np.random.default_rng(20)
    return jnp.asarray(rng.standard_normal((24, 1024)).astype(np.float32))


# --------------------------------------------------------------------------
# ring correlate (ops/pallas_xcorr): planar spectra + accumulator tier
# --------------------------------------------------------------------------

def test_ring_bf16_budget_einsum(record):
    f32 = xcorr_all_pairs_peak(record, 128, use_pallas=False)
    b16 = xcorr_all_pairs_peak(record, 128, use_pallas=False,
                               precision="bf16")
    assert not jnp.array_equal(f32, b16), "bf16 tier must change bits"
    assert _rel(f32, b16) < RING_BF16_BUDGET


def test_ring_bf16_budget_pallas_interpret(record):
    f32 = xcorr_all_pairs_peak(record, 128, use_pallas=True, interpret=True)
    b16 = xcorr_all_pairs_peak(record, 128, use_pallas=True, interpret=True,
                               precision="bf16")
    assert _rel(f32, b16) < RING_BF16_BUDGET


def test_ring_f32_default_bit_identical(record):
    bare = xcorr_all_pairs_peak(record, 128, use_pallas=False)
    explicit = xcorr_all_pairs_peak(record, 128, use_pallas=False,
                                    precision="f32")
    assert jnp.array_equal(bare, explicit)


def test_ring_precision_validated(record):
    with pytest.raises(ValueError, match="precision"):
        xcorr_all_pairs_peak(record, 128, precision="f16")


# --------------------------------------------------------------------------
# gather "dot" finish (ops/pallas_gather via xcorr_traj_follow)
# --------------------------------------------------------------------------

def _traj_args(record):
    nch, nt = record.shape
    t_axis = jnp.arange(nt) * 0.004
    ch = jnp.arange(4, 12)
    t_at = jnp.asarray(0.5 + 0.02 * np.arange(8))
    return (record, t_axis, 2, ch, t_at), dict(nsamp=512, wlen=128,
                                               overlap_ratio=0.5)


def test_gather_dot_bf16_budget(record):
    args, kw = _traj_args(record)
    f32 = xc.xcorr_traj_follow(*args, mode="fused", finish="dot",
                               interpret=True, **kw)
    b16 = xc.xcorr_traj_follow(*args, mode="fused", finish="dot",
                               interpret=True, precision="bf16", **kw)
    assert not jnp.array_equal(f32, b16), "bf16 tier must change bits"
    assert _rel(f32, b16) < GATHER_DOT_BF16_BUDGET


def test_gather_dot_f32_default_bit_identical(record):
    args, kw = _traj_args(record)
    bare = xc.xcorr_traj_follow(*args, mode="fused", finish="dot",
                                interpret=True, **kw)
    explicit = xc.xcorr_traj_follow(*args, mode="fused", finish="dot",
                                    interpret=True, precision="f32", **kw)
    assert jnp.array_equal(bare, explicit)


def test_gather_rfft_finish_ignores_precision(record):
    """The rfft finish never touches the MXU: both tiers are the same
    program, bit-for-bit."""
    args, kw = _traj_args(record)
    f32 = xc.xcorr_traj_follow(*args, mode="fused", finish="rfft",
                               interpret=True, **kw)
    b16 = xc.xcorr_traj_follow(*args, mode="fused", finish="rfft",
                               interpret=True, precision="bf16", **kw)
    assert jnp.array_equal(f32, b16)


# --------------------------------------------------------------------------
# dispersion transforms (ops/dispersion)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def disp_axes():
    return (jnp.arange(1.0, 20.0, 0.5), jnp.arange(200.0, 800.0, 20.0))


def test_fv_map_fk_bf16_budget(record, disp_axes):
    freqs, vels = disp_axes
    f32 = fv_map_fk(record, 8.16, 0.004, freqs, vels)
    b16 = fv_map_fk(record, 8.16, 0.004, freqs, vels, precision="bf16")
    assert not jnp.array_equal(f32, b16), "bf16 tier must change bits"
    assert _rel(f32, b16) < DISP_FK_BF16_BUDGET


def test_fv_map_fk_f32_default_bit_identical(record, disp_axes):
    freqs, vels = disp_axes
    bare = fv_map_fk(record, 8.16, 0.004, freqs, vels)
    explicit = fv_map_fk(record, 8.16, 0.004, freqs, vels, precision="f32")
    assert jnp.array_equal(bare, explicit)


def test_fv_map_phase_shift_bf16_budget(record, disp_axes):
    freqs, vels = disp_axes
    f32 = fv_map_phase_shift(record, 8.16, 0.004, freqs, vels)
    b16 = fv_map_phase_shift(record, 8.16, 0.004, freqs, vels,
                             precision="bf16")
    assert not jnp.array_equal(f32, b16), "bf16 tier must change bits"
    assert _rel(f32, b16) < DISP_PS_BF16_BUDGET


def test_fv_map_phase_shift_f32_default_bit_identical(record, disp_axes):
    freqs, vels = disp_axes
    bare = fv_map_phase_shift(record, 8.16, 0.004, freqs, vels)
    explicit = fv_map_phase_shift(record, 8.16, 0.004, freqs, vels,
                                  precision="f32")
    assert jnp.array_equal(bare, explicit)


@pytest.mark.parametrize("fn", [fv_map_fk, fv_map_phase_shift])
def test_dispersion_precision_validated(record, disp_axes, fn):
    freqs, vels = disp_axes
    with pytest.raises(ValueError, match="precision"):
        fn(record, 8.16, 0.004, freqs, vels, precision="f64")


# --------------------------------------------------------------------------
# config plumbing: the tier rides GatherConfig/DispersionConfig/RingConfig
# --------------------------------------------------------------------------

def test_precision_fields_default_f32_and_hash():
    from das_diff_veh_tpu.config import (DispersionConfig, GatherConfig,
                                         RingConfig)
    from das_diff_veh_tpu.runtime import config_hash
    assert GatherConfig().precision == "f32"
    assert DispersionConfig().precision == "f32"
    assert RingConfig().precision == "f32"
    # the tier participates in the config hash (repr-based): a bf16 run
    # never shares resume state or serve cache entries with an f32 run
    assert (config_hash(GatherConfig(precision="bf16"))
            != config_hash(GatherConfig()))
    assert (config_hash(DispersionConfig(precision="bf16"))
            != config_hash(DispersionConfig()))
