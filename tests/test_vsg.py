import jax
import jax.numpy as jnp
import numpy as np
import pytest

from das_diff_veh_tpu.config import GatherConfig
from das_diff_veh_tpu.models import vsg as V
from das_diff_veh_tpu.ops import xcorr as jx
from das_diff_veh_tpu.oracle import vsg_ref as OV
from das_diff_veh_tpu.oracle import xcorr_ref as ox

RNG = np.random.default_rng(23)


def _window_scene(nt=2000, nx=37, fs=250.0, dx=8.16, x0=500.0, speed=15.0,
                  pivot_frac=0.5):
    """One per-vehicle window: data + axes + trajectory through the pivot.

    ``pivot_frac`` places the vehicle's pivot arrival inside the window:
    ~0.5 keeps the forward (main-side) correlation windows live; ~0.75 makes
    the time-reversed other-side windows live instead.
    """
    t = 100.0 + np.arange(nt) / fs
    x = x0 - 225.0 + np.arange(nx) * dx
    t_pivot = t[int(nt * pivot_frac)]
    traj_t = np.linspace(t_pivot - 40.0, t_pivot + 40.0, 80)
    traj_x = x0 + (traj_t - t_pivot) * speed
    data = RNG.standard_normal((nx, nt))
    return data, x, t, traj_x, traj_t, x0


@pytest.mark.parametrize("other_side,pivot_frac",
                         [(False, 0.5), (True, 0.5), (True, 0.75)])
def test_build_gather_matches_reference(other_side, pivot_frac):
    data, x, t, traj_x, traj_t, x0 = _window_scene(pivot_frac=pivot_frac)
    cfg = GatherConfig(include_other_side=other_side)
    start_x, end_x = x0 - 150.0, x0 + 75.0
    ref, roff, rlags = OV.ref_build_gather(
        data, x, t, traj_x, traj_t, x0, start_x, end_x,
        wlen_s=cfg.wlen, time_window=cfg.time_window, delta_t=cfg.delta_t,
        include_other_side=other_side)
    g = V.VsgGeometry.build(x, t[1] - t[0], x0, start_x, end_x, cfg)
    ours = np.asarray(V.build_gather(
        jnp.asarray(data), jnp.asarray(t), jnp.asarray(x),
        jnp.asarray(traj_x), jnp.asarray(traj_t),
        jnp.ones(traj_t.size, bool), g, cfg))
    assert ours.shape == ref.shape == (g.nch_out, g.wlen)
    np.testing.assert_allclose(ours, ref, rtol=1e-7, atol=1e-9)
    np.testing.assert_allclose(g.offsets(x), roff, rtol=1e-12)
    np.testing.assert_allclose(g.lags(), rlags, rtol=1e-12)


def test_build_gather_jits_and_vmaps():
    data, x, t, traj_x, traj_t, x0 = _window_scene()
    cfg = GatherConfig()
    g = V.VsgGeometry.build(x, t[1] - t[0], x0, x0 - 150.0, x0 + 75.0, cfg)
    fn = jax.jit(lambda d, tt, tx, tj: V.build_gather(
        d, tt, jnp.asarray(x), tx, tj, jnp.isfinite(tj), g, cfg))
    batch_d = jnp.asarray(np.stack([data, data * 0.5]))
    batch_t = jnp.asarray(np.stack([t, t]))
    batch_tx = jnp.asarray(np.stack([traj_x, traj_x]))
    batch_tt = jnp.asarray(np.stack([traj_t, traj_t]))
    out = jax.vmap(fn)(batch_d, batch_t, batch_tx, batch_tt)
    assert out.shape == (2, g.nch_out, g.wlen)
    # gather is invariant to a global amplitude scale (global-L2 preprocess)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out[1]),
                               rtol=1e-6, atol=1e-9)


def test_stack_gathers_masks_invalid():
    a = jnp.asarray(RNG.standard_normal((3, 4, 8)))
    valid = jnp.asarray([True, True, False])
    out = np.asarray(V.stack_gathers(a, valid))
    np.testing.assert_allclose(out, np.asarray((a[0] + a[1]) / 2.0), rtol=1e-12)


@pytest.mark.parametrize("backward", [False, True])
def test_xcorr_pair_at_truncation_parity(backward):
    """Masked static-shape windows reproduce numpy truncation/empty slices."""
    nt, wlen, nsamp = 1200, 200, 700
    a = RNG.standard_normal(nt)
    b = RNG.standard_normal(nt)
    for start in [0, 300, 650, 900, 1150]:
        if backward:
            if start - nsamp < 0:
                ref = np.zeros(wlen)
            else:
                sl = slice(start - nsamp, start)
                ref = ox.ref_xcorr_pair(a[sl], b[sl], wlen)
        else:
            sl = slice(start, start + nsamp)
            if a[sl].size < wlen:
                ref = np.zeros(wlen)
            else:
                ref = ox.ref_xcorr_pair(a[sl], b[sl], wlen)
        ours = np.asarray(jx.xcorr_pair_at(jnp.asarray(a), jnp.asarray(b),
                                           start, nsamp, wlen, backward=backward))
        np.testing.assert_allclose(ours, np.atleast_1d(np.squeeze(ref)),
                                   rtol=1e-8, atol=1e-10, err_msg=f"start={start}")


def test_disp_method_ab_parity():
    """DispersionConfig.method A/B: the fk (reference-parity) and
    phase_shift (TPU slant-stack) paths both recover a known c(f) from the
    same gather-oriented wavefield (offsets ascending to the source at 0,
    like the real VSG stack after postprocessing)."""
    from das_diff_veh_tpu.config import DispersionConfig
    from das_diff_veh_tpu.io.synthetic import dispersive_shot

    c_true = lambda f: 300.0 + 500.0 * np.exp(-np.asarray(f, dtype=float) / 8.0)
    nx, nt, dx, dt = 28, 500, 8.16, 0.004
    data = dispersive_shot(nx, nt, dx, dt, phase_velocity=c_true,
                           src_idx=nx - 1)
    offs = (np.arange(nx) - (nx - 1)) * dx
    freqs = np.arange(0.8, 25, 0.1)
    vels = np.arange(200.0, 1200.0, 1.0)
    band = (freqs >= 4) & (freqs <= 16)
    for method, tol in [("fk", 0.02), ("phase_shift", 0.04)]:
        cfg = DispersionConfig(method=method)
        img = np.asarray(V.gather_disp_image(jnp.asarray(data), offs, dt, dx,
                                             cfg, -150.0, 0.0))
        rec = vels[img[:, band].argmax(axis=0)]
        err = np.abs(rec - c_true(freqs[band])) / c_true(freqs[band])
        assert np.median(err) < tol, (method, np.median(err))


def test_gather_physics_moveout():
    """VSG of a non-dispersive propagating field peaks at lag = offset/c."""
    nt, fs, dx, c = 4000, 250.0, 8.16, 500.0
    nx = 37
    x = np.arange(nx) * dx
    t = np.arange(nt) / fs
    # plane wave sweeping from the far end toward channel 0 repeatedly
    rng = np.random.default_rng(3)
    src = rng.standard_normal(nt * 2)
    data = np.stack([np.interp(t - xi / c, np.arange(-nt, nt) / fs, src)
                     for xi in x])
    pivot = x[-1]
    traj_t = np.array([t[0] - 20.0, t[-1] + 20.0])
    traj_x = np.array([x[-1] + 300.0, x[-1] + 301.0])  # car far away: fixed window
    cfg = GatherConfig(delta_t=-50.0, time_window=10.0, norm_amp=False,
                       include_other_side=False)
    g = V.VsgGeometry.build(x, 1.0 / fs, pivot, 0.0, pivot, cfg)
    out = np.asarray(V.build_gather(jnp.asarray(data), jnp.asarray(t),
                                    jnp.asarray(x), jnp.asarray(traj_x),
                                    jnp.asarray(traj_t),
                                    jnp.ones(2, bool), g, cfg))
    lags = g.lags()
    offsets = g.offsets(x)
    for row in [5, 15, 25]:
        lag_peak = lags[np.argmax(out[row])]
        expect = abs(offsets[row]) / c
        assert abs(abs(lag_peak) - expect) < 0.05, (row, lag_peak, expect)
