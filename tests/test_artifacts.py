"""Artifact persistence: npz round-trips, reference schema compatibility,
and the precomputed-gathers -> bootstrap cross-session path."""

import numpy as np

from das_diff_veh_tpu.io import artifacts as A

RNG = np.random.default_rng(9)


def test_gather_roundtrip_reference_schema(tmp_path):
    xcf = RNG.standard_normal((28, 100)).astype(np.float32)
    offs = np.linspace(-150.0, 70.0, 28)
    lags = (np.arange(100) - 50) * 0.004
    p = str(tmp_path / "gather.npz")
    A.save_gather_npz(p, xcf, offs, lags)

    # keys must match the reference loader (virtual_shot_gather.py:231-232)
    f = np.load(p)
    assert set(f.files) >= {"XCF_out", "x_axis", "t_axis"}

    g = A.load_gather_npz(p)
    np.testing.assert_array_equal(g.xcf, xcf)
    np.testing.assert_array_equal(g.offsets, offs)
    np.testing.assert_array_equal(g.lags, lags)


def test_dispersion_roundtrip_reference_schema(tmp_path):
    fv = RNG.standard_normal((50, 40))
    freqs = np.arange(0.8, 4.8, 0.1)
    vels = np.arange(200.0, 250.0)
    p = str(tmp_path / "disp.npz")
    A.save_dispersion_npz(p, fv, freqs, vels)
    f = np.load(p)
    assert set(f.files) == {"freqs", "vels", "fv_map"}
    d = A.load_dispersion_npz(p)
    np.testing.assert_array_equal(d.fv_map, fv)
    np.testing.assert_array_equal(d.freqs, freqs)
    np.testing.assert_array_equal(d.vels, vels)


def test_window_gathers_roundtrip_and_bootstrap(tmp_path):
    import jax.numpy as jnp

    from das_diff_veh_tpu.analysis.bootstrap import bootstrap_disp, sample_indices
    from das_diff_veh_tpu.config import BootstrapConfig, DispersionConfig

    n_win, nch, wlen = 10, 19, 64
    gathers = RNG.standard_normal((n_win, nch, wlen)).astype(np.float32)
    valid = np.ones(n_win, bool)
    offs = np.linspace(-150.0, 0.0, nch)
    lags = (np.arange(wlen) - wlen // 2) * 0.004
    p = str(tmp_path / "wg.npz")
    A.save_window_gathers(p, gathers, valid, offs, lags)
    art = A.load_window_gathers(p)
    np.testing.assert_array_equal(art.gathers, gathers)
    np.testing.assert_array_equal(art.valid, valid)

    # the reloaded batch drives a bootstrap directly (cross-session path)
    cfg = BootstrapConfig(bt_times=3, bt_size=4, freq_lb=(3.0,),
                          freq_ub=(8.0,), sigma=(50.0,), ref_freq_idx=(30,))
    dcfg = DispersionConfig(freq_step=0.2, vel_step=20.0)
    idx = sample_indices(n_win, 4, 3, RNG)
    ridges, freqs = bootstrap_disp(jnp.asarray(art.gathers), art.offsets,
                                   0.004, 8.16, idx, cfg, dcfg)
    assert ridges[0].shape[0] == 3
    assert np.isfinite(ridges[0]).all()
