import numpy as np
import pytest

from das_diff_veh_tpu.config import ImagingConfig, PipelineConfig
from das_diff_veh_tpu.core.section import DasSection
from das_diff_veh_tpu.io.readers import save_section_npz
from das_diff_veh_tpu.pipeline.timelapse import process_chunk
from das_diff_veh_tpu.pipeline.workflow import date_range, run_date_range


@pytest.fixture(scope="module")
def scene(pipeline_scene):
    """Alias of the session-scoped canonical scene (conftest.py): every
    process_chunk trace in this module reuses the shared geometry, so the
    ~40 s compile happens once per session, not once per module."""
    return pipeline_scene


def _cfg(x0=400.0):
    return PipelineConfig().replace(imaging=ImagingConfig(x0=x0))


def test_process_chunk_xcorr(chunk_result_xcorr):
    res = chunk_result_xcorr
    assert res.n_windows >= 1
    img = np.asarray(res.disp_image)
    assert img.shape == (1000, 242)
    assert np.isfinite(img).all()
    assert np.asarray(res.vsg_stack).ndim == 2
    # raw-band windows are opt-in (nothing downstream consumes them here)
    assert res.qs_batch is None


def test_process_chunk_surface_wave(chunk_result_sw):
    res = chunk_result_sw
    assert res.n_windows >= 1
    assert res.vsg_stack is None
    assert np.isfinite(np.asarray(res.disp_image)).all()


def test_date_range_helper():
    assert date_range("20230227", "20230302") == \
        ["20230227", "20230228", "20230301", "20230302"]


def test_run_date_range_with_resume(tmp_path, scene, caplog):
    section, _ = scene
    day = tmp_path / "20230301"
    day.mkdir()
    # two chunk files, 2 min apart, plus one corrupt file the runtime must
    # quarantine without aborting the date
    sec = DasSection(np.asarray(section.data), np.asarray(section.x),
                     np.asarray(section.t))
    save_section_npz(str(day / "20230301_000000.npz"), sec)
    save_section_npz(str(day / "20230301_000200.npz"), sec)
    (day / "20230301_000400.npz").write_bytes(b"corrupt bytes, not an npz")

    out = tmp_path / "results"
    kwargs = dict(ch1=None, ch2=None, smoothing=False, rescale_after=None,
                  x_is_channels=False)
    summary = run_date_range(str(tmp_path), "20230301", "20230302",
                             cfg=_cfg(), method="xcorr", out_dir=str(out),
                             **kwargs)
    assert summary["20230301"]["n_chunks"] == 2
    assert summary["20230301"]["n_quarantined"] == 1
    assert summary["20230301"]["complete"] is True
    final = out / "20230301_final.npz"
    assert final.exists()
    with np.load(final) as f:
        n_vehicles = int(f["n_vehicles"])
        assert np.isfinite(f["avg_image"]).all()
        assert n_vehicles > 0
    # resume: second run skips, but still reports the date's n_vehicles so
    # resumed and fresh runs are comparable
    summary2 = run_date_range(str(tmp_path), "20230301", "20230302",
                              cfg=_cfg(), method="xcorr", out_dir=str(out),
                              **kwargs)
    assert summary2["20230301"] == {"skipped": True, "n_vehicles": n_vehicles}


def test_run_date_range_missing_folder(tmp_path):
    summary = run_date_range(str(tmp_path), "20230301", "20230301",
                             out_dir=str(tmp_path / "r"))
    assert summary == {}


def test_cli_parser():
    from das_diff_veh_tpu.pipeline.cli import build_parser
    args = build_parser().parse_args(
        ["--data_root", "/d", "--start_date", "20230301",
         "--end_date", "20230302", "--x0", "600"])
    assert args.x0 == 600.0 and args.method == "xcorr"


def test_end_to_end_truth_recovery(scene):
    """SURVEY §4 item 3: synthetic scene -> full pipeline -> physics.

    (a) tracked vehicle speeds match the injected truth speeds;
    (b) the stacked xcorr dispersion image's ridge matches the injected
        phase-velocity curve c(f) over the usable band (interferometric
        stacking needs multiple isolated vehicles, so a longer scene is
        synthesized here; single-source gathers are biased).
    """
    from das_diff_veh_tpu.analysis.classify import vehicle_speeds
    from das_diff_veh_tpu.io.synthetic import SceneConfig, synthesize_section

    # --- (a) tracked speeds on the shared small scene ------------------------
    section, truth = scene
    res = process_chunk(section, _cfg(), method="xcorr", with_qs=True)
    assert bool((res.qs_batch.valid == res.batch.valid).all())
    speeds = np.asarray(vehicle_speeds(res.tracks))
    got = speeds[np.asarray(res.tracks.valid) & np.isfinite(speeds)]
    assert got.size >= 1
    for s in got:
        assert np.min(np.abs(truth.speed - s) / truth.speed) < 0.08, s

    # pipeline -> classed-analysis integration (notebook cells 5-18 flow on
    # real pipeline outputs): masks partition the majority set, profiles
    # are finite for non-empty classes
    from das_diff_veh_tpu.analysis import classed_analysis

    ca = classed_analysis(res.qs_batch, res.tracks, by="weight", fs=250.0,
                          nperseg=512)
    union = np.zeros_like(ca.majority)
    for name, mask in ca.masks.items():
        assert not (union & mask).any()          # classes are disjoint
        union |= mask
        if mask.any():
            assert np.isfinite(ca.ts_stats[name][0]).all()
            assert np.isfinite(ca.psd[name][0]).all()
    valid = np.asarray(res.qs_batch.valid)
    assert (union <= (ca.majority & valid)).all()

    # --- (b) dispersion ridge vs injected c(f), many stacked windows ---------
    # smallest scene that keeps >=5 isolated windows and a ~4x margin on the
    # ridge assertion (probed: med_err 0.026 vs the 0.12 threshold)
    cfg0 = SceneConfig(nch=100, duration=300.0, n_vehicles=8, seed=3,
                       speed_range=(10.0, 20.0), noise_std=0.005)
    big, big_truth = synthesize_section(cfg0)
    res2 = process_chunk(big, _cfg(), method="xcorr")
    assert res2.n_windows >= 5
    img = np.asarray(res2.disp_image)
    freqs = np.arange(0.8, 25, 0.1)
    vels = np.arange(200.0, 1200.0, 1.0)
    band = (freqs >= 3.0) & (freqs <= 10.0)
    rec = vels[img[:, band].argmax(axis=0)]
    c_true = big_truth.phase_velocity(freqs[band])
    med_err = np.median(np.abs(rec - c_true) / c_true)
    assert med_err < 0.12, med_err  # measured 0.026 on this scene
