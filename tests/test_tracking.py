import jax.numpy as jnp
import numpy as np
import pytest
from scipy.signal import find_peaks as scipy_find_peaks

from das_diff_veh_tpu.config import TrackingConfig
from das_diff_veh_tpu.models import tracking as T
from das_diff_veh_tpu.ops import peaks as P
from das_diff_veh_tpu.oracle import tracking_ref as OT

RNG = np.random.default_rng(5)


def _smooth_noise(n, nt=3000, fs=50.0, seed=1):
    """Band-limited noise resembling the quasi-static tracking band."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nt))
    spec = np.fft.rfft(x, axis=-1)
    f = np.fft.rfftfreq(nt, d=1.0 / fs)
    spec *= np.exp(-((f - 0.4) / 0.5) ** 2)
    return np.fft.irfft(spec, n=nt, axis=-1) * 30.0


@pytest.mark.parametrize("prominence,distance,wlen", [
    (0.2, 50, 600), (0.5, 30, 300), (0.05, 80, 601),
])
def test_find_peaks_matches_scipy(prominence, distance, wlen):
    data = _smooth_noise(6, seed=int(distance))
    for tr in data:
        ref = scipy_find_peaks(tr, prominence=prominence, wlen=wlen,
                               distance=distance)[0]
        pos, valid = P.find_peaks(jnp.asarray(tr), prominence, distance, wlen,
                                  max_peaks=128)
        got = np.asarray(pos)[np.asarray(valid)]
        np.testing.assert_array_equal(got, ref)


def test_find_peaks_no_prominence_matches_scipy():
    tr = np.abs(_smooth_noise(1, seed=9)[0])
    ref = scipy_find_peaks(tr, height=0.0, distance=50)[0]
    pos, valid = P.find_peaks(jnp.asarray(tr), min_distance=50, max_peaks=128,
                              use_prominence=False)
    np.testing.assert_array_equal(np.asarray(pos)[np.asarray(valid)], ref)


def test_gaussian_likelihood_matches_reference():
    t_axis = np.arange(2000) * 0.02
    pk = np.array([100, 900, 1500])
    ref = OT.ref_likelihood(pk, t_axis, 0.08)
    full = np.zeros(8, dtype=int); full[:3] = pk
    valid = np.zeros(8, bool); valid[:3] = True
    ours = np.asarray(P.gaussian_likelihood(jnp.asarray(full), jnp.asarray(valid),
                                            jnp.asarray(t_axis), 0.08))
    np.testing.assert_allclose(ours, ref, rtol=1e-9, atol=1e-12)


def _tracking_scene(n_veh=4, nx=420, nt=3900, fs=50.0, tau=0.9, seed=3):
    """Quasi-static-band scene on the 1 m / 50 Hz tracking grid."""
    rng = np.random.default_rng(seed)
    x = np.arange(nx, dtype=float)
    t = np.arange(nt) / fs
    speeds = rng.uniform(10.0, 20.0, n_veh)
    enters = 5.0 + np.arange(n_veh) * 15.0 + rng.uniform(0, 3, n_veh)
    t_arr = enters[:, None] + x[None, :] / speeds[:, None]     # (nveh, nx)
    data = np.zeros((nx, nt))
    for v in range(n_veh):
        data += np.exp(-0.5 * ((t[None, :] - t_arr[v][:, None]) / tau) ** 2)
    data += 0.02 * rng.standard_normal(data.shape)
    return data, x, t, t_arr, speeds


def test_detect_base_matches_oracle():
    data, x, t, t_arr, _ = _tracking_scene()
    cfg = TrackingConfig()
    ref = OT.ref_detect_base(data, t, start_x_idx=10, cfg=cfg)
    base, valid = T.detect_vehicle_base(jnp.asarray(data), jnp.asarray(t), 10, cfg)
    got = np.asarray(base)[np.asarray(valid)]
    np.testing.assert_array_equal(got, ref)
    assert len(ref) >= 4          # all vehicles seen (maybe + noise peaks)


@pytest.mark.parametrize("bug_compat", [True, False])
def test_track_vehicles_matches_oracle(bug_compat):
    data, x, t, t_arr, _ = _tracking_scene()
    cfg = TrackingConfig(assoc_bug_compat=bug_compat, max_vehicles=8)
    base_ref = OT.ref_detect_base(data, t, 10, cfg)
    ref_states = OT.ref_track(data, x, 10.0, 400.0, base_ref, cfg)

    nb = len(base_ref)
    base = np.zeros(8, dtype=np.int32); base[:nb] = base_ref
    bvalid = np.zeros(8, bool); bvalid[:nb] = True
    states, step_x = T.track_vehicles(jnp.asarray(data), x, 10.0, 400.0,
                                      jnp.asarray(base), jnp.asarray(bvalid), cfg)
    states = np.asarray(states)[:nb]
    assert states.shape == ref_states.shape
    both_nan = np.isnan(states) & np.isnan(ref_states)
    agree = np.isclose(states, ref_states, rtol=0, atol=1e-4) | both_nan
    assert agree.all(), np.argwhere(~agree)[:10]
    assert np.isfinite(ref_states).sum() > 0.5 * ref_states.size


def test_track_qc_matches_oracle():
    data, x, t, t_arr, _ = _tracking_scene()
    cfg = TrackingConfig(max_vehicles=8)
    base_ref = OT.ref_detect_base(data, t, 10, cfg)
    states = OT.ref_track(data, x, 10.0, 400.0, base_ref, cfg)
    # corrupt one row into retrograde motion and another into sparsity
    states = np.vstack([states,
                        states[0][::-1] if states.shape[1] else states[0]])
    sparse = np.full(states.shape[1], np.nan); sparse[::11] = 100.0
    states = np.vstack([states, sparse])
    ref_masked, ref_keep = OT.ref_track_qc(states)
    ours_masked, ours_keep = T.track_qc(jnp.asarray(states))
    np.testing.assert_array_equal(np.asarray(ours_keep), ref_keep)
    a, b = np.asarray(ours_masked), ref_masked
    assert ((np.isnan(a) & np.isnan(b)) | np.isclose(a, b, atol=1e-6)).all()


def test_track_qc_partial_window_retrograde():
    """With fewer diffs than the retrograde window, numpy's 'valid' convolve
    yields partial sums equal to the total drift — a short backwards track
    must still be rejected."""
    ns = 50
    row = np.full(ns, np.nan)
    row[0:31:2] = 100.0 - np.arange(16)      # 16 samples drifting -15 total
    ref_m, ref_keep = OT.ref_track_qc(row[None].copy())
    _, keep = T.track_qc(jnp.asarray(row[None]))
    assert not ref_keep[0] and not bool(np.asarray(keep)[0])
    fwd = np.full(ns, np.nan)
    fwd[0:31:2] = 100.0 + np.arange(16)      # same shape, forward drift
    ref_m, ref_keep = OT.ref_track_qc(fwd[None].copy())
    _, keep = T.track_qc(jnp.asarray(fwd[None]))
    assert ref_keep[0] and bool(np.asarray(keep)[0])


def test_upsample_matches_oracle():
    rows = np.array([[10.0, np.nan, 16.0, 19.0, np.nan, 25.0],
                     [np.nan, 5.0, 8.0, np.nan, 14.0, np.nan]])
    ref = OT.ref_upsample(rows.copy(), factor=3)
    ours = np.asarray(T.upsample_tracks(jnp.asarray(rows), 3, rows.shape[1] * 3))
    np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-9)


def test_track_section_recovers_trajectories():
    # keep every transit fully inside the record so QC has no reason to reject
    data, x, t, t_arr, speeds = _tracking_scene(seed=7)
    end_x = 300.0
    tracks = T.track_section(jnp.asarray(data), x, t, 10.0, end_x,
                             TrackingConfig(max_vehicles=8))
    got = np.asarray(tracks.t_idx)[np.asarray(tracks.valid)]
    assert got.shape[0] >= 3, "most vehicles should survive QC"
    # each kept track should match one true trajectory to within ~1 s
    fs = 50.0
    t_arr_idx = (t_arr[:, 10:301]) * fs                  # truth in sample units
    for row in got:
        err = np.nanmedian(np.abs(t_arr_idx - row[None, :]), axis=1)
        assert err.min() < 50.0, err
