"""Tests: Morlet CWT + travel-time picker, per-class QS/PSD profiles, CSV reader."""

import os

import jax.numpy as jnp
import numpy as np
import pytest
from scipy import signal as ssig

from das_diff_veh_tpu.analysis.class_profiles import (class_psd,
                                                      class_timeseries_stats,
                                                      quasi_static_signatures)
from das_diff_veh_tpu.analysis.classify import quasi_static_peaks
from das_diff_veh_tpu.core.section import WindowBatch
from das_diff_veh_tpu.io.readers import read_csv_section
from das_diff_veh_tpu.ops.cwt import cwt_morlet, log_freqs, pick_travel_times


def _tone_burst(nt, dt, f0, t_center, width):
    t = np.arange(nt) * dt
    return np.cos(2 * np.pi * f0 * (t - t_center)) * np.exp(
        -0.5 * ((t - t_center) / width) ** 2)


class TestCWT:
    def test_peak_frequency_row_matches_tone(self):
        dt, nt, f0 = 1 / 250.0, 2048, 8.0
        x = _tone_burst(nt, dt, f0, nt * dt / 2, 0.5)
        freqs = log_freqs(2.0, 20.0, 64)
        mag = np.abs(np.asarray(cwt_morlet(jnp.asarray(x), 1 / dt, freqs)))
        # frequency of the globally strongest coefficient ~ f0
        fi, _ = np.unravel_index(np.argmax(mag), mag.shape)
        assert abs(freqs[fi] - f0) / f0 < 0.1

    def test_time_localization(self):
        dt, nt, f0, tc = 1 / 250.0, 2048, 10.0, 3.1
        x = _tone_burst(nt, dt, f0, tc, 0.3)
        freqs = np.array([f0])
        mag = np.abs(np.asarray(cwt_morlet(jnp.asarray(x), 1 / dt, freqs)))[0]
        assert abs(np.argmax(mag) * dt - tc) < 0.1

    def test_batch_matches_single(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((3, 512))
        freqs = log_freqs(2, 12, 16)
        batch = np.asarray(cwt_morlet(jnp.asarray(x), 250.0, freqs))
        single = np.asarray(cwt_morlet(jnp.asarray(x[1]), 250.0, freqs))
        np.testing.assert_allclose(batch[1], single, rtol=1e-5, atol=1e-8)

    def test_picker_recovers_known_travel_times(self):
        # gather layout: zero lag at nt//2; arrivals at +tau per trace
        dt, nt, f0 = 1 / 250.0, 2000, 12.0
        taus = np.array([0.4, 0.8, 1.6])
        gather = np.stack([
            _tone_burst(nt, dt, f0, nt // 2 * dt + tau, 0.15) for tau in taus])
        times, f_used = pick_travel_times(jnp.asarray(gather), dt, pick_freq=f0)
        assert abs(f_used - f0) < 0.5
        np.testing.assert_allclose(np.asarray(times), taus, atol=0.05)


def _qs_batch(rng, nwin=4, nch=6, nt=512):
    data = rng.standard_normal((nwin, nch, nt)) * 0.01
    # deterministic slow bump per window with distinct amplitude
    t = np.linspace(0, 1, nt)
    for w in range(nwin):
        data[w] += (w + 1) * np.exp(-0.5 * ((t - 0.5) / 0.1) ** 2)[None, :]
    valid = np.array([True] * (nwin - 1) + [False])
    return WindowBatch(
        data=jnp.asarray(data), x=jnp.arange(nch, dtype=jnp.float64),
        t=jnp.asarray(np.broadcast_to(t, (nwin, nt)).copy()),
        traj_x=jnp.zeros((nwin, 8)), traj_t=jnp.zeros((nwin, 8)),
        valid=jnp.asarray(valid))


class TestClassProfiles:
    def test_signatures_shape_and_invalid_nan(self):
        batch = _qs_batch(np.random.default_rng(1))
        sig = np.asarray(quasi_static_signatures(batch))
        assert sig.shape == (4, 512)
        assert np.isnan(sig[-1]).all() and np.isfinite(sig[:-1]).all()
        # amplitude ordering of the injected bumps survives the processing
        peaks = np.asarray(quasi_static_peaks(batch))
        assert peaks[0] < peaks[1] < peaks[2] and np.isnan(peaks[3])

    def test_timeseries_stats(self):
        batch = _qs_batch(np.random.default_rng(2))
        sig = quasi_static_signatures(batch)
        masks = {"light": np.array([1, 0, 0, 0], bool),
                 "heavy": np.array([0, 1, 1, 1], bool),
                 "none": np.zeros(4, bool)}
        stats = class_timeseries_stats(sig, masks)
        m, s, ci = stats["light"]
        np.testing.assert_allclose(m, np.asarray(sig)[0], atol=1e-12)
        assert np.allclose(s, 0)
        assert np.isnan(ci).all()   # n=1: no honest CI, not a zero-width band
        # invalid window 3 is NaN and must be dropped from "heavy", not poison it
        assert np.isfinite(stats["heavy"][0]).all()
        assert np.isnan(stats["none"][0]).all()

    def test_class_psd_matches_scipy_welch(self):
        rng = np.random.default_rng(3)
        data = rng.standard_normal((3, 4, 1024))
        masks = {"a": np.array([1, 1, 0], bool), "empty": np.zeros(3, bool)}
        freqs, out = class_psd(data, masks, fs=250.0, nperseg=256)
        f_ref, p_ref = ssig.welch(data[:2], 250.0, nperseg=256)
        np.testing.assert_allclose(freqs, f_ref, atol=1e-12)
        np.testing.assert_allclose(out["a"][0], p_ref.mean(axis=1).mean(axis=0),
                                   rtol=1e-5)
        assert np.isnan(out["empty"][0]).all()
        assert out["empty"][1].shape[0] == 0

    def test_class_plots_smoke(self, tmp_path):
        batch = _qs_batch(np.random.default_rng(4))
        from das_diff_veh_tpu.viz import plot_class_psd, plot_class_timeseries
        sig = quasi_static_signatures(batch)
        masks = {"light": np.array([1, 0, 0, 0], bool),
                 "heavy": np.array([0, 1, 1, 0], bool)}
        stats = class_timeseries_stats(sig, masks)
        p1 = os.path.join(tmp_path, "ts.png")
        plot_class_timeseries(np.asarray(batch.t)[0], stats, fig_path=p1)
        freqs, psds = class_psd(np.asarray(batch.data), masks, fs=250.0,
                                nperseg=256)
        p2 = os.path.join(tmp_path, "psd.png")
        plot_class_psd(freqs, psds, fig_path=p2)
        assert os.path.getsize(p1) > 0 and os.path.getsize(p2) > 0


class TestCSVReader:
    def test_roundtrip(self, tmp_path):
        rng = np.random.default_rng(5)
        data = rng.standard_normal((3, 7))
        x = np.arange(3.0) * 8.16
        t = np.arange(7.0) / 250.0
        base = os.path.join(tmp_path, "drive")
        np.savetxt(base + ".csv", data, delimiter=" ")
        np.savetxt(base + "_x_axis.csv", x)
        np.savetxt(base + "_t_axis.csv", t)
        sec = read_csv_section(str(tmp_path), "drive")
        np.testing.assert_allclose(np.asarray(sec.data), data, rtol=1e-12)
        np.testing.assert_allclose(np.asarray(sec.x), x)
        np.testing.assert_allclose(np.asarray(sec.t), t)

    def test_aligned_columns_and_single_sample(self, tmp_path):
        # aligned/padded columns (multiple spaces) must not create phantom
        # NaN columns; an (N, 1) triplet must reshape, not fail
        base = os.path.join(tmp_path, "aligned")
        with open(base + ".csv", "w") as f:
            f.write("  1.0   -2.0\n 3.50   4.25\n")
        np.savetxt(base + "_x_axis.csv", [0.0, 8.16])
        np.savetxt(base + "_t_axis.csv", [0.0, 0.004])
        sec = read_csv_section(str(tmp_path), "aligned")
        np.testing.assert_allclose(np.asarray(sec.data),
                                   [[1.0, -2.0], [3.5, 4.25]])
        base = os.path.join(tmp_path, "col")
        np.savetxt(base + ".csv", np.arange(3.0))
        np.savetxt(base + "_x_axis.csv", np.arange(3.0))
        np.savetxt(base + "_t_axis.csv", [0.0])
        assert np.asarray(read_csv_section(str(tmp_path), "col").data).shape == (3, 1)

    def test_class_psd_drops_nan_window(self):
        data = np.random.default_rng(6).standard_normal((3, 2, 512))
        data[2] = np.nan
        freqs, out = class_psd(data, {"a": np.ones(3, bool)}, fs=250.0,
                               nperseg=128)
        assert np.isfinite(out["a"][0]).all()
        assert out["a"][1].shape[0] == 2

    def test_shape_mismatch_raises(self, tmp_path):
        base = os.path.join(tmp_path, "bad")
        np.savetxt(base + ".csv", np.zeros((3, 7)), delimiter=" ")
        np.savetxt(base + "_x_axis.csv", np.zeros(2))
        np.savetxt(base + "_t_axis.csv", np.zeros(7))
        with pytest.raises(ValueError, match="does not match"):
            read_csv_section(str(tmp_path), "bad")


class TestClassedAnalysis:
    def _scene(self, bumps, speeds_mps):
        from das_diff_veh_tpu.core.section import VehicleTracks
        nveh, nch, nt = len(bumps), 6, 1024
        fs, dt_track = 250.0, 0.02
        t = np.arange(nt) / fs
        rng = np.random.default_rng(8)
        data = rng.standard_normal((nveh, nch, nt)) * 0.01
        for w, b in enumerate(bumps):
            data[w] += b * np.exp(-0.5 * ((t - 2.0) / 0.3) ** 2)[None, :]
        batch = WindowBatch(
            data=jnp.asarray(data), x=jnp.arange(nch, dtype=jnp.float64),
            t=jnp.asarray(np.broadcast_to(t, (nveh, nt)).copy()),
            traj_x=jnp.zeros((nveh, 4)), traj_t=jnp.zeros((nveh, 4)),
            valid=jnp.ones(nveh, bool))
        x_track = np.arange(50.0)
        t_idx = np.stack([x_track / (v * dt_track) for v in speeds_mps])
        tracks = VehicleTracks(t_idx=jnp.asarray(t_idx),
                               valid=jnp.ones(nveh, bool),
                               x=jnp.asarray(x_track),
                               t=jnp.arange(2000.0) * dt_track)
        return batch, tracks

    def test_by_speed_with_weight_outlier(self):
        from das_diff_veh_tpu.analysis import classed_analysis
        bumps = [1.0, 1.05, 0.95, 3.0, 1.0, 1.02, 0.98, 1.0]
        speeds = [20.0, 20.0, 15.0, 15.0, 15.0, 15.0, 10.0, 10.0]
        batch, tracks = self._scene(bumps, speeds)
        res = classed_analysis(batch, tracks, by="speed", fs=250.0,
                               nperseg=256)
        assert not res.majority[3]          # weight outlier filtered out
        assert res.masks["fast"].sum() == 2 and res.masks["slow"].sum() == 2
        assert res.masks["mid"].sum() == 3  # vehicle 3 excluded from mid
        np.testing.assert_allclose(res.speeds[:2], 20.0, rtol=0.02)
        for name in res.masks:
            assert np.isfinite(res.ts_stats[name][0]).all()
            assert np.isfinite(res.psd[name][0]).all()

    def test_by_weight(self):
        from das_diff_veh_tpu.analysis import classed_analysis
        bumps = [1.5, 1.6, 0.5, 0.52, 0.48, 0.5, 0.9, 0.92]
        speeds = [15.0] * 8
        batch, tracks = self._scene(bumps, speeds)
        res = classed_analysis(batch, tracks, by="weight", fs=250.0,
                               nperseg=256)
        assert res.masks["heavy"].sum() == 2
        assert (res.masks["heavy"] & np.array([1, 1, 0, 0, 0, 0, 0, 0],
                                              bool)).sum() == 2

    def test_class_stacks_masked_mean(self):
        from das_diff_veh_tpu.analysis import class_stacks
        per_win = jnp.asarray(np.arange(8, dtype=np.float64)[:, None, None]
                              * np.ones((8, 3, 4)))
        valid = np.array([1, 1, 1, 1, 1, 1, 1, 0], bool)
        masks = {"a": np.array([1, 1, 0, 0, 0, 0, 0, 1], bool)}
        out = class_stacks(per_win, valid, masks)
        # window 7 is invalid: mean over {0, 1} only
        np.testing.assert_allclose(np.asarray(out["a"]), 0.5)
