"""Test configuration: force a virtual 8-device CPU platform.

Multi-chip hardware is unavailable in CI; sharding paths are exercised on a
fake 8-device CPU mesh exactly as the driver's dryrun does.  The session may
export JAX_PLATFORMS=axon (single tunneled TPU chip) — tests override it.

A persistent compilation cache is enabled: this host has a single slow CPU
core and XLA backend compiles dominate the suite's first run (minutes per
large graph); cached reruns skip them entirely.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "1"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax  # noqa: E402
import pytest  # noqa: E402

from das_diff_veh_tpu.cache import enable_compilation_cache  # noqa: E402

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", "cpu")
enable_compilation_cache(_REPO)


# --------------------------------------------------------------------------
# the canonical real-compute scene, shared session-wide
#
# A full ``process_chunk`` trace costs ~40 s on this host's single CPU core
# and the tier-1 budget is 870 s, so every test that needs a REAL pipeline
# run must reuse one scene geometry + one PipelineConfig: the jit cache
# then compiles the program once per session and every later caller
# (including the serving engine, whose config hash feeds its bucket cache)
# is a cache hit.  Tests that need different physics knobs should stub the
# compute instead (tests/test_serve.py's FnComputeFactory pattern).
# --------------------------------------------------------------------------

@pytest.fixture(scope="session")
def pipeline_scene():
    """(section, truth) of the canonical small synthetic scene."""
    from das_diff_veh_tpu.io.synthetic import SceneConfig, synthesize_section

    return synthesize_section(SceneConfig(nch=100, duration=120.0,
                                          n_vehicles=4, seed=11,
                                          speed_range=(12.0, 18.0)))


@pytest.fixture(scope="session")
def pipeline_cfg():
    """The PipelineConfig every real process_chunk test runs under."""
    from das_diff_veh_tpu.config import ImagingConfig, PipelineConfig

    return PipelineConfig().replace(imaging=ImagingConfig(x0=400.0))


@pytest.fixture(scope="session")
def chunk_result_xcorr(pipeline_scene, pipeline_cfg):
    """``process_chunk`` compiled and executed ONCE per session on the
    canonical scene; consumers assert against this shared result instead
    of tracing their own variant."""
    from das_diff_veh_tpu.pipeline.timelapse import process_chunk

    section, _ = pipeline_scene
    return process_chunk(section, pipeline_cfg, method="xcorr")


@pytest.fixture(scope="session")
def chunk_result_sw(pipeline_scene, pipeline_cfg):
    """Staged surface_wave sibling of ``chunk_result_xcorr`` — the parity
    oracle for the fused path's non-xcorr branch, shared for the same
    compile-budget reason."""
    from das_diff_veh_tpu.pipeline.timelapse import process_chunk

    section, _ = pipeline_scene
    return process_chunk(section, pipeline_cfg, method="surface_wave")


# --------------------------------------------------------------------------
# fused-pipeline siblings (PR 16): each fixture compiles ONE fused program
# per session; later fused runs at this geometry (the edge-case tests, the
# steady-state counter assertions) hit pipeline.fused's program cache and
# never retrace.
# --------------------------------------------------------------------------

@pytest.fixture(scope="session")
def fused_cfg(pipeline_cfg):
    """``pipeline_cfg`` with the single-dispatch fused chunk path enabled."""
    return pipeline_cfg.replace(chunk_pipeline="fused")


@pytest.fixture(scope="session")
def fused_chunk_xcorr(pipeline_scene, fused_cfg):
    from das_diff_veh_tpu.pipeline.timelapse import process_chunk

    section, _ = pipeline_scene
    return process_chunk(section, fused_cfg, method="xcorr")


@pytest.fixture(scope="session")
def fused_chunk_sw(pipeline_scene, fused_cfg):
    from das_diff_veh_tpu.pipeline.timelapse import process_chunk

    section, _ = pipeline_scene
    return process_chunk(section, fused_cfg, method="surface_wave")


@pytest.fixture(scope="session")
def small_scene():
    """(section, truth) of a 40 s early-vehicle scene, ~3x cheaper per chunk
    than ``pipeline_scene``.  Seed 5 is the first probed seed whose
    echo-doubled variant still tracks vehicles while isolating zero
    windows — the property the fused all-invalid edge test depends on.
    (Time-slicing ``pipeline_scene`` instead loses its vehicles entirely:
    the one it isolates enters late in the 120 s record.)"""
    from das_diff_veh_tpu.io.synthetic import SceneConfig, synthesize_section

    return synthesize_section(SceneConfig(nch=100, duration=40.0,
                                          n_vehicles=2, seed=5,
                                          speed_range=(12.0, 18.0)))


@pytest.fixture(scope="session")
def small_scene_sw():
    """Surface-wave sibling of ``small_scene``: window selection is
    method-dependent and no probed seed satisfies both methods at 40 s —
    seed 6 is the first (x64) whose surface_wave run isolates a window."""
    from das_diff_veh_tpu.io.synthetic import SceneConfig, synthesize_section

    return synthesize_section(SceneConfig(nch=100, duration=40.0,
                                          n_vehicles=2, seed=6,
                                          speed_range=(12.0, 18.0)))


@pytest.fixture(scope="session")
def small_chunk_sw(small_scene_sw, pipeline_cfg):
    """Staged surface_wave oracle on the small surface-wave scene."""
    from das_diff_veh_tpu.pipeline.timelapse import process_chunk

    section, _ = small_scene_sw
    return process_chunk(section, pipeline_cfg, method="surface_wave")


@pytest.fixture(scope="session")
def fused_small_sw(small_scene_sw, fused_cfg):
    from das_diff_veh_tpu.pipeline.timelapse import process_chunk

    section, _ = small_scene_sw
    return process_chunk(section, fused_cfg, method="surface_wave")


@pytest.fixture(scope="session")
def fused_small_echo(small_scene, fused_cfg):
    """Fused xcorr run on the echo-doubled small scene (every vehicle glued
    to a twin 3 s behind it): vehicles still track, but no isolation window
    survives.  First fused xcorr run at the small geometry, so it also
    primes pipeline.fused's program cache for the zero-vehicle test."""
    import numpy as np

    from das_diff_veh_tpu.core.section import DasSection
    from das_diff_veh_tpu.pipeline.timelapse import process_chunk

    section, _ = small_scene
    d = np.asarray(section.data)
    d = d + np.roll(d, int(3.0 * 250.0), axis=1)  # 3 s at the 250 Hz rate
    sec = DasSection(d, np.asarray(section.x), np.asarray(section.t))
    return process_chunk(sec, fused_cfg, method="xcorr")
