"""Test configuration: force a virtual 8-device CPU platform.

Multi-chip hardware is unavailable in CI; sharding paths are exercised on a
fake 8-device CPU mesh exactly as the driver's dryrun does.  The session may
export JAX_PLATFORMS=axon (single tunneled TPU chip) — tests override it.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "1"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", "cpu")
