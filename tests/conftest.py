"""Test configuration: force a virtual 8-device CPU platform.

Multi-chip hardware is unavailable in CI; sharding paths are exercised on a
fake 8-device CPU mesh exactly as the driver's dryrun does.  The session may
export JAX_PLATFORMS=axon (single tunneled TPU chip) — tests override it.

A persistent compilation cache is enabled: this host has a single slow CPU
core and XLA backend compiles dominate the suite's first run (minutes per
large graph); cached reruns skip them entirely.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "1"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax  # noqa: E402

from das_diff_veh_tpu.cache import enable_compilation_cache  # noqa: E402

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", "cpu")
enable_compilation_cache(_REPO)
