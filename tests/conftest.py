"""Test configuration: force a virtual 8-device CPU platform.

Multi-chip hardware is unavailable in CI; sharding paths are exercised on a
fake 8-device CPU mesh exactly as the driver's dryrun does.  The session may
export JAX_PLATFORMS=axon (single tunneled TPU chip) — tests override it.

A persistent compilation cache is enabled: this host has a single slow CPU
core and XLA backend compiles dominate the suite's first run (minutes per
large graph); cached reruns skip them entirely.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "1"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax  # noqa: E402
import pytest  # noqa: E402

from das_diff_veh_tpu.cache import enable_compilation_cache  # noqa: E402

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", "cpu")
enable_compilation_cache(_REPO)


# --------------------------------------------------------------------------
# the canonical real-compute scene, shared session-wide
#
# A full ``process_chunk`` trace costs ~40 s on this host's single CPU core
# and the tier-1 budget is 870 s, so every test that needs a REAL pipeline
# run must reuse one scene geometry + one PipelineConfig: the jit cache
# then compiles the program once per session and every later caller
# (including the serving engine, whose config hash feeds its bucket cache)
# is a cache hit.  Tests that need different physics knobs should stub the
# compute instead (tests/test_serve.py's FnComputeFactory pattern).
# --------------------------------------------------------------------------

@pytest.fixture(scope="session")
def pipeline_scene():
    """(section, truth) of the canonical small synthetic scene."""
    from das_diff_veh_tpu.io.synthetic import SceneConfig, synthesize_section

    return synthesize_section(SceneConfig(nch=100, duration=120.0,
                                          n_vehicles=4, seed=11,
                                          speed_range=(12.0, 18.0)))


@pytest.fixture(scope="session")
def pipeline_cfg():
    """The PipelineConfig every real process_chunk test runs under."""
    from das_diff_veh_tpu.config import ImagingConfig, PipelineConfig

    return PipelineConfig().replace(imaging=ImagingConfig(x0=400.0))


@pytest.fixture(scope="session")
def chunk_result_xcorr(pipeline_scene, pipeline_cfg):
    """``process_chunk`` compiled and executed ONCE per session on the
    canonical scene; consumers assert against this shared result instead
    of tracing their own variant."""
    from das_diff_veh_tpu.pipeline.timelapse import process_chunk

    section, _ = pipeline_scene
    return process_chunk(section, pipeline_cfg, method="xcorr")
